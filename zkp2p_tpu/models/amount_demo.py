"""The amount-extraction demo circuit: a small REAL member of the model
family (the Venmo amount block of `circuit/circuit.circom:225-272`) —
byte range checks, the VenmoAmountRegex DFA scan with exact match count,
masked reveal, one-hot shift window, 7-byte packing — over a 32-byte
subject slice (~3.4k constraints).

Shared by the driver's `dryrun_multichip` (sharded prove path on virtual
devices) and `bench.py`'s CPU-fallback path: small enough for a 1-core
host, real enough to exercise the whole gadget stack.
"""

from __future__ import annotations

AMOUNT_LEN = 21
SUBJ_LEN = 32


def amount_circuit():
    """-> (ConstraintSystem, public signal values, witness seed)."""
    subj_len, amount_len, subj = SUBJ_LEN, AMOUNT_LEN, b"subject:$42.00\r\n"
    from ..gadgets import core
    from ..gadgets.regex import CharClassCache, dfa_scan, match_count, reveal_bytes
    from ..inputs.email import pack_bytes_le
    from ..models import common
    from ..models.venmo import _amount_reveal_states
    from ..regexc import compiler as regexc
    from ..snark.r1cs import LC, ConstraintSystem

    n_words = (amount_len + 6) // 7
    cs = ConstraintSystem("graft_amount")
    amount_words = [cs.new_public(f"amount[{i}]") for i in range(n_words)]
    subject = cs.new_wires(subj_len, "subject")
    amount_idx = cs.new_wire("amount_idx")
    cs.mark_input(subject + [amount_idx])  # the witness seed keys below
    bits = core.assert_bytes(cs, subject, "subj")
    cache = CharClassCache(cs)
    for w, b in zip(subject, bits):
        cache.register_bits(w, b)
    dfa = regexc.search_dfa(regexc.VENMO_AMOUNT)
    states = dfa_scan(cs, list(subject), dfa, cache, "amt")
    cnt = match_count(cs, states, dfa.accept, "amt.cnt")
    cs.enforce_eq(LC.of(cnt), LC.const(1), "amt/count")
    reveal = reveal_bytes(cs, subject, states, _amount_reveal_states(dfa), "amt.rev")
    onehot = core.one_hot(cs, amount_idx, subj_len - amount_len, "amt.idx")
    chars = common.shift_window(cs, reveal, onehot, amount_len, "amt.shift")
    words = core.pack_bytes(cs, chars, 7, "amt.pack")
    for w, pub in zip(words, amount_words):
        cs.enforce_eq(LC.of(w), LC.of(pub), "amt/out")

    # $ must sit inside the one-hot window (subj_len - amount_len lanes)
    subj = subj + b"\x00" * (subj_len - len(subj))
    amt_start = subj.find(b"$") + 1
    amt = subj[amt_start:subj.index(b".", amt_start) + 1]
    amt = amt + b"\x00" * (amount_len - len(amt))
    pubs = pack_bytes_le(amt, 7)
    seed = {w: b for w, b in zip(subject, subj)}
    seed[amount_idx] = amt_start
    return cs, pubs, seed


def dryrun_circuit():
    """Tiny-shape member of the flagship's gadget stack for the driver's
    `dryrun_multichip`: the venmo-id packing + Poseidon block
    (models/venmo.py vid.pack / vid.pos, `circuit/circuit.circom:189-218`)
    over an 8-byte id — 319 constraints, domain 512.

    The driver validates that the FULL sharded prove step compiles and
    executes on a virtual CPU mesh of a 1-core host, on "tiny shapes" by
    its own spec; MSM runtime there scales with wire count (the
    3.4k-constraint amount default needed ~130 s PER MSM on that host,
    the MULTICHIP_r03 rc=124 budget kill), so the dryrun runs the
    identical prove dataflow at the smallest faithful shape instead.
    -> (ConstraintSystem, public values, witness seed)"""
    from ..gadgets import core
    from ..gadgets.poseidon import poseidon
    from ..gadgets.poseidon_params import poseidon_hash
    from ..inputs.email import pack_bytes_le
    from ..snark.r1cs import LC, ConstraintSystem

    raw = b"44993321"
    cs = ConstraintSystem("graft_dryrun_vid")
    out = cs.new_public("hashed_id")
    wires = cs.new_wires(len(raw), "id")
    cs.mark_input(wires)  # the witness seed keys below
    core.assert_bytes(cs, wires, "id")
    words = core.pack_bytes(cs, wires, 7, "id.pack")
    h = poseidon(cs, words, "id.pos")
    cs.enforce_eq(LC.of(h), LC.of(out), "id/out")
    pubs = [poseidon_hash(pack_bytes_le(raw, 7))]
    seed = {w: b for w, b in zip(wires, raw)}
    return cs, pubs, seed
