"""The amount-extraction demo circuit: a small REAL member of the model
family (the Venmo amount block of `circuit/circuit.circom:225-272`) —
byte range checks, the VenmoAmountRegex DFA scan with exact match count,
masked reveal, one-hot shift window, 7-byte packing — over a 32-byte
subject slice (~3.4k constraints).

Shared by the driver's `dryrun_multichip` (sharded prove path on virtual
devices) and `bench.py`'s CPU-fallback path: small enough for a 1-core
host, real enough to exercise the whole gadget stack.
"""

from __future__ import annotations

AMOUNT_LEN = 21
SUBJ_LEN = 32


def amount_circuit():
    """-> (ConstraintSystem, public signal values, witness seed)."""
    from ..gadgets import core
    from ..gadgets.regex import CharClassCache, dfa_scan, match_count, reveal_bytes
    from ..inputs.email import pack_bytes_le
    from ..models import common
    from ..models.venmo import _amount_reveal_states
    from ..regexc import compiler as regexc
    from ..snark.r1cs import LC, ConstraintSystem

    cs = ConstraintSystem("graft_amount")
    amount_words = [cs.new_public(f"amount[{i}]") for i in range(3)]
    subject = cs.new_wires(SUBJ_LEN, "subject")
    amount_idx = cs.new_wire("amount_idx")
    bits = core.assert_bytes(cs, subject, "subj")
    cache = CharClassCache(cs)
    for w, b in zip(subject, bits):
        cache.register_bits(w, b)
    dfa = regexc.search_dfa(regexc.VENMO_AMOUNT)
    states = dfa_scan(cs, list(subject), dfa, cache, "amt")
    cnt = match_count(cs, states, dfa.accept, "amt.cnt")
    cs.enforce_eq(LC.of(cnt), LC.const(1), "amt/count")
    reveal = reveal_bytes(cs, subject, states, _amount_reveal_states(dfa), "amt.rev")
    onehot = core.one_hot(cs, amount_idx, SUBJ_LEN - AMOUNT_LEN, "amt.idx")
    chars = common.shift_window(cs, reveal, onehot, AMOUNT_LEN, "amt.shift")
    words = core.pack_bytes(cs, chars, 7, "amt.pack")
    for w, pub in zip(words, amount_words):
        cs.enforce_eq(LC.of(w), LC.of(pub), "amt/out")

    # $ must sit inside the one-hot window (SUBJ_LEN - AMOUNT_LEN lanes)
    subj = b"subject:$42.00\r\n"
    subj = subj + b"\x00" * (SUBJ_LEN - len(subj))
    amt = b"42." + b"\x00" * (AMOUNT_LEN - 3)
    pubs = pack_bytes_le(amt, 7)
    seed = {w: b for w, b in zip(subject, subj)}
    seed[amount_idx] = subj.find(b"$") + 1
    return cs, pubs, seed
