"""The flagship model: P2POnrampVerify — Venmo DKIM payment-receipt circuit.

Rebuild of `circuit/circuit.circom:17-310` (`P2POnrampVerify(max_header,
max_body, n, k)`), block for block:

  header SHA-256            (:67-82)   -> gadgets.sha256 (variable length)
  RSA-2048 e=65537          (:86-98)   -> gadgets.rsa
  DKIM to/from regex ==2    (:102-110) -> gadgets.regex + regexc DFA
  body-hash regex ==1       (:115-119) -> gadgets.regex
  bh= extraction + shift    (:115-132) -> one-hot shift matrix
  partial body SHA          (:137-156) -> gadgets.sha256 midstate resume
  base64(bh) == body hash   (:137-156) -> gadgets.base64
  offramper-ID regex+reveal (:162-218) -> gadgets.regex reveal + shift
  7-byte packing + Poseidon (:189-218) -> gadgets.core.pack_bytes + poseidon
  amount regex + packing    (:225-272) -> same machinery on the subject
  nullifier = sig[0:3]      (:291-294)
  order/claim binding        (:297-304)

Public signal layout (the uint[26] `contracts/Verifier.sol:360` /
`Ramp.sol:253-293` contract expects):
  [0]     Poseidon(packed venmo id)
  [1:4]   packed amount (3 x 7-byte words)
  [4:7]   nullifier (first 3 signature limbs)
  [7:24]  RSA modulus (17 x 121-bit limbs)
  [24]    order id     [25] claim id

Parameterised so CI can build a miniature instance (small max lengths)
while bench builds the production 1024/6400 shape — the reference bakes
one instantiation (`main = P2POnrampVerify(1024, 6400, 121, 17)`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from ..field.bn254 import R

from ..gadgets import core, rsa, sha256
from ..gadgets.poseidon import poseidon
from ..gadgets.regex import CharClassCache, dfa_scan, match_count, reveal_bytes
from ..regexc import compiler as regexc
from ..snark.r1cs import LC, ConstraintSystem
from . import common


@dataclass
class VenmoParams:
    max_header_bytes: int = 1024
    max_body_bytes: int = 6400
    n: int = 121
    k: int = 17
    bh_b64_len: int = 44  # base64(SHA-256) incl padding
    id_len: int = 28  # venmo id + soft wrap, zero padded (venmoHash.ts:3-44)
    amount_len: int = 21  # 3 packed words (Ramp.sol signals [1:4])
    dkim_match_count: int = 2  # to: and from: (circuit.circom:106)
    id_match_count: int = 1


@dataclass
class VenmoLayout:
    """Wire indices for input seeding (the circuit's `input.json` shape:
    SURVEY.md §2.3 sample input)."""

    hashed_id: int = 0
    amount_words: List[int] = field(default_factory=list)
    nullifier: List[int] = field(default_factory=list)
    modulus: List[int] = field(default_factory=list)
    order_id: int = 0
    claim_id: int = 0
    header: List[int] = field(default_factory=list)
    header_blocks: int = 0
    signature: List[int] = field(default_factory=list)
    body: List[int] = field(default_factory=list)
    body_blocks: int = 0
    midstate_bits: List[int] = field(default_factory=list)
    body_hash_idx: int = 0
    amount_idx: int = 0
    id_idx: int = 0
    order_sq: int = 0
    claim_sq: int = 0


# Shared with models.email_verify — hoisted to models.common so soundness
# fixes land in one place (see the round-2 bh= divergence).
_shift_window = common.shift_window


def build_venmo_circuit(p: VenmoParams) -> tuple[ConstraintSystem, VenmoLayout]:
    assert p.max_header_bytes % 64 == 0 and p.max_body_bytes % 64 == 0
    cs = ConstraintSystem("p2p_onramp_verify")
    lay = VenmoLayout()

    # ---- public signals, contract order (Ramp.sol:253-293)
    lay.hashed_id = cs.new_public("hashed_venmo_id")
    lay.amount_words = [cs.new_public(f"amount[{i}]") for i in range(3)]
    lay.nullifier = [cs.new_public(f"nullifier[{i}]") for i in range(3)]
    lay.modulus = [cs.new_public(f"modulus[{i}]") for i in range(p.k)]
    lay.order_id = cs.new_public("order_id")
    lay.claim_id = cs.new_public("claim_id")

    # ---- private inputs
    lay.header = cs.new_wires(p.max_header_bytes, "in_padded")
    header_blocks = cs.new_wire("in_len_blocks")
    lay.header_blocks = header_blocks
    lay.signature = cs.new_wires(p.k, "signature")
    lay.body = cs.new_wires(p.max_body_bytes, "in_body_padded")
    body_blocks = cs.new_wire("in_body_len_blocks")
    lay.body_blocks = body_blocks
    lay.midstate_bits = cs.new_wires(256, "precomputed_sha")
    lay.body_hash_idx = cs.new_wire("body_hash_idx")
    lay.amount_idx = cs.new_wire("venmo_amount_idx")
    lay.id_idx = cs.new_wire("venmo_offramper_id_idx")

    # prover-seeded inputs (the witness() private_inputs keys built by
    # inputs.email) — the audit's determinism sources and hook-coverage
    # exemptions (snark.analysis)
    cs.mark_input(
        lay.header + [header_blocks] + lay.signature + lay.body
        + [body_blocks] + lay.midstate_bits
        + [lay.body_hash_idx, lay.amount_idx, lay.id_idx]
    )

    header_bits = core.assert_bytes(cs, lay.header, "hdr")
    body_bits = core.assert_bytes(cs, lay.body, "body")
    for w in lay.midstate_bits:
        cs.enforce_bool(w, "midstate")

    # ---- header hash + RSA (circuit.circom:67-98)
    digest_bits = sha256.sha256_blocks(cs, header_bits, header_blocks, tag="sha_hdr")
    rsa.rsa_verify_65537(cs, lay.signature, lay.modulus, digest_bits, p.n, p.k, "rsa")

    # ---- header regexes (circuit.circom:102-132)
    cache = CharClassCache(cs)
    for w, bits in zip(lay.header, header_bits):
        cache.register_bits(w, bits)
    for w, bits in zip(lay.body, body_bits):
        cache.register_bits(w, bits)
    common.dkim_header_match(cs, lay.header, cache, p.dkim_match_count)

    # ---- bh= extraction + body hash equality (circuit.circom:115-156)
    # Shared soundness-critical block: see models.common.constrain_body_hash.
    common.constrain_body_hash(
        cs,
        lay.header,
        body_bits,
        body_blocks,
        lay.midstate_bits,
        lay.body_hash_idx,
        cache,
        p.max_header_bytes,
        p.bh_b64_len,
    )

    # ---- offramper id regex + reveal + hash (circuit.circom:162-218)
    # The `+`-terminated pattern re-accepts on every id char, so the match
    # count is data-length-dependent; like the reference (which only logs
    # it, circuit.circom:168-173) we rely on the reveal mask + the claim's
    # on-chain hash equality for soundness, not on an exact count.
    id_dfa = regexc.search_dfa(regexc.VENMO_OFFRAMPER_ID)
    id_states = dfa_scan(cs, list(lay.body), id_dfa, cache, "vid")
    id_reveal = reveal_bytes(cs, lay.body, id_states, sorted(id_dfa.accept), "vid.rev")

    id_onehot = core.one_hot(cs, lay.id_idx, p.max_body_bytes - p.id_len, "vid.idx")
    id_chars = _shift_window(cs, id_reveal, id_onehot, p.id_len, "vid.shift")
    # The window must anchor on a real revealed char: with an all-zero
    # reveal mask (no DFA match anywhere in the body) every shift window
    # is zero and a forged email could claim Poseidon(0..0).  x·x⁻¹ = 1
    # forces id_chars[0] != 0 — strictly stronger than the reference,
    # which only console-logs the match count (circuit.circom:168-173).
    id_inv = cs.new_wire("venmo_id_first_inv")
    cs.compute(id_inv, lambda v: pow(v, R - 2, R) if v else 0, [id_chars[0]])
    cs.enforce(LC.of(id_chars[0]), LC.of(id_inv), LC.const(1), "vid/nonzero")
    id_words = core.pack_bytes(cs, id_chars, 7, "vid.pack")
    hashed = poseidon(cs, id_words, "vid.pos")
    cs.enforce_eq(LC.of(hashed), LC.of(lay.hashed_id), "vid/out")

    # ---- amount regex on the subject line (circuit.circom:225-272)
    amt_dfa = regexc.search_dfa(regexc.VENMO_AMOUNT)
    amt_states = dfa_scan(cs, list(lay.header), amt_dfa, cache, "amt")
    amt_cnt = match_count(cs, amt_states, amt_dfa.accept, "amt.cnt")
    cs.enforce_eq(LC.of(amt_cnt), LC.const(1), "amt/count")
    amt_reveal = reveal_bytes(cs, lay.header, amt_states, _amount_reveal_states(amt_dfa), "amt.rev")
    amt_onehot = core.one_hot(cs, lay.amount_idx, p.max_header_bytes - p.amount_len, "amt.idx")
    amt_chars = _shift_window(cs, amt_reveal, amt_onehot, p.amount_len, "amt.shift")
    amt_words = core.pack_bytes(cs, amt_chars, 7, "amt.pack")
    for w, pub in zip(amt_words, lay.amount_words):
        cs.enforce_eq(LC.of(w), LC.of(pub), "amt/out")

    # ---- nullifier + order/claim binding (circuit.circom:291-304)
    for i in range(3):
        cs.enforce_eq(LC.of(lay.signature[i]), LC.of(lay.nullifier[i]), "null/eq")
    lay.order_sq = cs.new_wire("order_sq")
    cs.enforce(LC.of(lay.order_id), LC.of(lay.order_id), LC.of(lay.order_sq), "order/sq")
    cs.compute(lay.order_sq, lambda v: v * v % R, [lay.order_id])
    lay.claim_sq = cs.new_wire("claim_sq")
    cs.enforce(LC.of(lay.claim_id), LC.of(lay.claim_id), LC.of(lay.claim_sq), "claim/sq")
    cs.compute(lay.claim_sq, lambda v: v * v % R, [lay.claim_id])

    return cs, lay


def _amount_reveal_states(dfa) -> List[int]:
    """States reached after the '$' — everything except the roaming start
    component (state 0 and states only reachable without consuming '$')."""
    searching = {0}
    frontier = [0]
    while frontier:
        s = frontier.pop()
        for c in range(256):
            if c == ord("$"):
                continue
            d = int(dfa.next[s, c])
            if d != -1 and d not in searching:
                searching.add(d)
                frontier.append(d)
    return [s for s in range(dfa.n_states) if s not in searching]
