"""Shared DKIM-circuit building blocks used by both model families.

The venmo (`circuit/circuit.circom:17-310`) and EmailVerify
(`zk-email-verify-circuits/email.circom:15-222`) circuits share the whole
header-to-body-hash spine: reveal-shift extraction windows and the
bh= base64 / partial-SHA body-hash equality block
(`circuit.circom:115-156`).  Hoisted here so a soundness fix lands in one
place for every model (the round-2 bh= bug existed precisely because this
block was duplicated).
"""

from __future__ import annotations

from typing import List, Sequence

from ..gadgets import base64 as b64
from ..gadgets import core, sha256
from ..gadgets.regex import CharClassCache, dfa_scan, match_count, reveal_bytes
from ..regexc import compiler as regexc
from ..snark.r1cs import LC, ConstraintSystem


def shift_window(
    cs: ConstraintSystem,
    data: Sequence[int],
    idx_onehot: Sequence[int],
    width: int,
    tag: str,
) -> List[int]:
    """out[j] = Σ_i onehot[i] · data[i+j] — the reveal-shift matrix
    (`circuit.circom:115-132,189-194`): O(len·width) products, which in the
    JAX witness tracer becomes a windowed gather (SURVEY.md §3.5).  All
    products and sums witnessed by ONE BlockHook (r1cs.witness_batch)."""
    import numpy as np

    out = []
    L = len(data)
    n_one = len(idx_onehot)
    for ind in idx_onehot:  # window soundness: exactly-one-hot 0/1 lanes
        cs.require_width(ind, 1, f"{tag}/shift.lane")
    block_outs: List[int] = []
    rows: List[tuple] = []  # (j, i) per product, in creation order
    for j in range(width):
        prods = []
        for i, ind in enumerate(idx_onehot):
            if i + j >= L:
                continue
            p = cs.new_wire(f"{tag}.p{j}.{i}.out")
            cs.enforce(LC.of(ind), LC.of(data[i + j]), LC.of(p), f"{tag}.p{j}.{i}")
            # one-hot lane x data byte: bounded by the data wire's width
            cs.set_width(p, cs.wire_width.get(data[i + j], 254))
            prods.append(p)
            block_outs.append(p)
            rows.append((j, i))
        w = cs.new_wire(f"{tag}.out{j}")
        cs.enforce_eq(core.lc_sum(prods), LC.of(w), f"{tag}/sum{j}")
        cs.set_width(w, max((cs.wire_width.get(q, 254) for q in prods), default=254))
        block_outs.append(w)
        out.append(w)

    j_arr = np.asarray([j for j, _ in rows])
    i_arr = np.asarray([i for _, i in rows])
    # output-row mapping: products in order, then the sum wire after each
    # j's run — rebuild positions once here.
    order: List[int] = []
    prod_pos: List[int] = []
    k = 0
    for j in range(width):
        n_p = int((i_arr[j_arr == j]).shape[0])
        prod_pos.extend(range(k, k + n_p))
        order.append(k + n_p)
        k += n_p + 1

    def vfn(m, j_arr=j_arr, i_arr=i_arr, n_one=n_one, prod_pos=prod_pos, sum_pos=order, width=width, k_total=k):
        ind = m[0:n_one]
        dat = m[n_one:]
        pv = ind[i_arr] * dat[i_arr + j_arr]  # (n_prods, K)
        res = np.empty((k_total, m.shape[1]), dtype=m.dtype)
        res[prod_pos] = pv
        sums = np.zeros((width, m.shape[1]), dtype=m.dtype)
        np.add.at(sums, j_arr, pv)
        res[sum_pos] = sums
        return res

    cs.compute_block(block_outs, vfn, list(idx_onehot) + list(data))
    return out


def bh_value_states(dfa) -> List[int]:
    """States inside the bh= base64 value of the BODY_HASH DFA: exactly
    those from which ';' then ' ' completes the match.  Only the value
    component of `...bh=[0-9A-Za-z+/=]+; ` can end the match this way (the
    inner `[a-z]+=[^;]+; ` tag-value loop continues to more tags, never to
    accept), so the reveal mask is 1 precisely on the matched b64 chars —
    verified against a canonical relaxed-canonicalized header in tests."""
    out = []
    for s in range(dfa.n_states):
        z = int(dfa.next[s, ord(";")])
        if z != -1 and int(dfa.next[z, ord(" ")]) in dfa.accept:
            out.append(s)
    assert out, "BODY_HASH DFA has no value states"
    return out


def constrain_body_hash(
    cs: ConstraintSystem,
    header: Sequence[int],
    body_bits: Sequence[Sequence[int]],
    body_blocks: int,
    midstate_bits: Sequence[int],
    body_hash_idx: int,
    cache: CharClassCache,
    max_header_bytes: int,
    bh_b64_len: int,
) -> None:
    """The bh= extraction + body-hash equality block
    (`circuit.circom:115-156`): scan the signed header for the DKIM bh=
    tag (exactly one match), reveal ONLY the regex-masked value chars
    (soundness: a prover must not be able to point body_hash_idx at
    arbitrary base64-alphabet header bytes — the shift consumes the reveal
    mask, zero outside the match), shift them to a fixed window,
    base64-decode, and constrain equal to the midstate-resumed partial
    SHA-256 of the body."""
    bh_dfa = regexc.search_dfa(regexc.BODY_HASH)
    bh_states = dfa_scan(cs, list(header), bh_dfa, cache, "bh")
    bh_cnt = match_count(cs, bh_states, bh_dfa.accept, "bh.cnt")
    cs.enforce_eq(LC.of(bh_cnt), LC.const(1), "bh/count")

    bh_reveal = reveal_bytes(cs, header, bh_states, bh_value_states(bh_dfa), "bh.rev")
    bh_onehot = core.one_hot(cs, body_hash_idx, max_header_bytes - bh_b64_len, "bh.idx")
    bh_chars = shift_window(cs, bh_reveal, bh_onehot, bh_b64_len, "bh.shift")
    decoded = b64.base64_decode_bits(cs, bh_chars, cache, "bh.dec")

    mid_words = [list(midstate_bits[32 * i : 32 * i + 32]) for i in range(8)]
    body_digest = sha256.sha256_blocks(cs, body_bits, body_blocks, init_state=mid_words, tag="sha_body")
    # body digest: 8 words x 32 LE bits; decoded: per-byte LE bits.
    # digest byte 4w+b (big-endian in word) = word bits [8*(3-b) .. +8)
    for byte_i in range(32):
        wrd, b_in_w = divmod(byte_i, 4)
        for bit in range(8):
            cs.enforce_eq(
                LC.of(decoded[byte_i][bit]),
                LC.of(body_digest[32 * wrd + 8 * (3 - b_in_w) + bit]),
                "bh/eq",
            )


def dkim_header_match(
    cs: ConstraintSystem,
    header: Sequence[int],
    cache: CharClassCache,
    match_count_required: int,
) -> None:
    """DKIM to/from regex over [\\x80] + header with the required exact
    match count (`circuit.circom:102-110`; sentinel
    `dkim_header_regex.circom:11-14`)."""
    sentinel = cs.new_wire("sentinel80")
    cs.enforce_eq(LC.of(sentinel), LC.const(0x80), "sentinel")
    cs.compute(sentinel, lambda: 0x80, [])
    dkim_dfa = regexc.search_dfa(regexc.DKIM_HEADER)
    dkim_states = dfa_scan(cs, [sentinel] + list(header), dkim_dfa, cache, "dkim")
    dkim_cnt = match_count(cs, dkim_states, dkim_dfa.accept, "dkim.cnt")
    cs.enforce_eq(LC.of(dkim_cnt), LC.const(match_count_required), "dkim/count")
