"""Circuit registry: named builders + declared public layouts, with the
static soundness audit (snark.analysis) as the ADMISSION PRECONDITION.

ROADMAP item 1 wants the service to serve many circuits; ISSUE 15's
point is that every circuit must pass an automated soundness gate
before it is served — a hand review per minted regex circuit does not
scale.  `audited()` is that gate: build -> audit (cached by structural
digest under .bench_cache) -> REFUSE on any unwaived finding.  The CLI
`setup` path and `zkp2p-tpu lint --circuits` / `make circuit-audit`
both route through here, and each in-process audit lands in
run_manifest (utils.metrics) beside the knob/gate arms.

Each spec declares its on-chain public-signal count (`n_public`) — the
audit's public-layout rule closes the docs/EVM_PARITY.md loop per
circuit: the venmo layout is the contract's uint[26]
(`Verifier.sol:360` / `Ramp.sol:253-293`), and a circuit whose built
n_public drifts from its declaration is refused before any key is cut.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..snark.analysis import audit_circuit, require_clean


@dataclass(frozen=True)
class CircuitSpec:
    name: str
    build: Callable[[], object]  # -> ConstraintSystem, inputs marked
    n_public: int  # declared on-chain signal layout (public-layout rule)
    description: str
    flagship: bool = False  # multi-minute build: slow tier only


def _build_venmo_mini():
    from .venmo import VenmoParams, build_venmo_circuit

    cs, _ = build_venmo_circuit(VenmoParams(max_header_bytes=256, max_body_bytes=192))
    return cs


def _build_venmo_full():
    from .venmo import VenmoParams, build_venmo_circuit

    cs, _ = build_venmo_circuit(VenmoParams())  # 1024/6400: the 4.9M flagship
    return cs


def _build_email_mini():
    from .email_verify import EmailVerifyParams, build_email_verify

    cs, _ = build_email_verify(
        EmailVerifyParams(max_header_bytes=256, max_body_bytes=128)
    )
    return cs


def _build_amount_demo():
    from .amount_demo import amount_circuit

    cs, _, _ = amount_circuit()
    return cs


def _build_dryrun_vid():
    from .amount_demo import dryrun_circuit

    cs, _, _ = dryrun_circuit()
    return cs


def build_sha2b() -> Tuple[object, List[int]]:
    """Two-block fixed SHA-256 over 128 padded private bytes — the
    tools/sharded_scale.py shape (the flagship's dominant gadget family
    at a 2^16 domain).  Returns (cs, digest bit wires); no publics (the
    scale harness compares the witness digest against hashlib)."""
    from ..gadgets import core, sha256
    from ..snark.r1cs import ConstraintSystem

    cs = ConstraintSystem("sharded-scale-sha2b")
    msg = cs.new_wires(128, "msg")
    cs.mark_input(msg)
    bits = core.assert_bytes(cs, msg, "msg")
    out = sha256.sha256_blocks(cs, bits, None)
    return cs, out


def _build_regex_actor():
    """Minted from regexc (the reference's regex_to_circom L0 layer):
    see regexc.compiler.reveal_circuit."""
    from ..regexc.compiler import VENMO_ACTOR_ID, reveal_circuit

    cs, _ = reveal_circuit(
        VENMO_ACTOR_ID, n_bytes=48, reveal_len=14, name="regex_actor"
    )
    return cs


SPECS: Dict[str, CircuitSpec] = {
    s.name: s
    for s in (
        CircuitSpec(
            "venmo", _build_venmo_mini, 26,
            "P2POnrampVerify at the CI shape (256/192 header/body)",
        ),
        CircuitSpec(
            "venmo-full", _build_venmo_full, 26,
            "the 4.94M-constraint production flagship (1024/6400)",
            flagship=True,
        ),
        CircuitSpec(
            "email_verify", _build_email_mini, 20,
            "generic DKIM EmailVerify at the CI shape (256/128)",
        ),
        CircuitSpec(
            "amount_demo", _build_amount_demo, 3,
            "Venmo amount block over a 32-byte subject slice",
        ),
        CircuitSpec(
            "dryrun_vid", _build_dryrun_vid, 1,
            "venmo-id packing + Poseidon (the multichip dryrun shape)",
        ),
        CircuitSpec(
            "sha2b", lambda: build_sha2b()[0], 0,
            "two-block SHA-256, the tools/sharded_scale.py scale shape",
        ),
        CircuitSpec(
            "regex_actor", _build_regex_actor, 2,
            "regexc-minted actor_id reveal circuit (the L0 minting path)",
        ),
    )
}


def circuit_ids(include_flagship: bool = False) -> List[str]:
    return [
        n for n, s in SPECS.items() if include_flagship or not s.flagship
    ]


def build(name: str):
    spec = SPECS.get(name)
    if spec is None:
        raise KeyError(
            f"unknown circuit {name!r}; registered: {', '.join(sorted(SPECS))}"
        )
    return spec.build()


def audited(name: str, use_cache: bool = True, cache_dir: Optional[str] = None):
    """The admission gate: build the named circuit, audit it (report
    cached by circuit digest), and REFUSE — CircuitAuditError — on any
    unwaived soundness finding.  Returns (cs, report)."""
    spec = SPECS.get(name)
    if spec is None:
        raise KeyError(
            f"unknown circuit {name!r}; registered: {', '.join(sorted(SPECS))}"
        )
    cs = spec.build()
    report = audit_circuit(
        cs,
        name=name,
        declared_n_public=spec.n_public,
        use_cache=use_cache,
        cache_dir=cache_dir,
    )
    require_clean(report)
    return cs, report
