"""EmailVerify — the generic DKIM email circuit family.

Rebuild of `zk-email-verify-circuits/email.circom:15-222`
(`EmailVerify(max_header_bytes, max_body_bytes, n, k)`) — the
architectural ancestor of the Venmo circuit: header SHA-256 + RSA-2048 +
DKIM to/from regex + bh= extraction + partial body SHA + base64 check,
WITHOUT the Venmo-specific extraction; plus an optional body regex with
packed reveal output (instantiated here with `TwitterResetRegex`
semantics, `twitter_reset_regex.circom:5`, to complete the family).

Public signal layout: [modulus (k) | reveal words (n_reveal_words)] —
matching EmailVerify's `public [modulus]` + packed reveal outputs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..field.bn254 import R
from ..gadgets import base64 as b64
from ..gadgets import core, rsa, sha256
from ..gadgets.regex import CharClassCache, dfa_scan, match_count, reveal_bytes
from ..regexc import compiler as regexc
from ..snark.r1cs import LC, ConstraintSystem


@dataclass
class EmailVerifyParams:
    max_header_bytes: int = 1024
    max_body_bytes: int = 1536
    n: int = 121
    k: int = 17
    bh_b64_len: int = 44
    # optional body extraction (None = header/bh/body-hash checks only)
    body_regex: Optional[str] = regexc.TWITTER_RESET
    reveal_len: int = 21  # bytes -> 3 packed words
    dkim_match_count: int = 2


@dataclass
class EmailVerifyLayout:
    modulus: List[int] = field(default_factory=list)
    reveal_words: List[int] = field(default_factory=list)
    header: List[int] = field(default_factory=list)
    header_blocks: int = 0
    signature: List[int] = field(default_factory=list)
    body: List[int] = field(default_factory=list)
    body_blocks: int = 0
    midstate_bits: List[int] = field(default_factory=list)
    body_hash_idx: int = 0
    reveal_idx: int = 0


def build_email_verify(p: EmailVerifyParams):
    assert p.max_header_bytes % 64 == 0 and p.max_body_bytes % 64 == 0
    cs = ConstraintSystem("email_verify")
    lay = EmailVerifyLayout()

    lay.modulus = [cs.new_public(f"modulus[{i}]") for i in range(p.k)]
    n_words = (p.reveal_len + 6) // 7 if p.body_regex else 0
    lay.reveal_words = [cs.new_public(f"reveal[{i}]") for i in range(n_words)]

    lay.header = cs.new_wires(p.max_header_bytes, "in_padded")
    lay.header_blocks = cs.new_wire("in_len_blocks")
    lay.signature = cs.new_wires(p.k, "signature")
    lay.body = cs.new_wires(p.max_body_bytes, "in_body_padded")
    lay.body_blocks = cs.new_wire("in_body_len_blocks")
    lay.midstate_bits = cs.new_wires(256, "precomputed_sha")
    lay.body_hash_idx = cs.new_wire("body_hash_idx")
    if p.body_regex:
        lay.reveal_idx = cs.new_wire("reveal_idx")

    header_bits = core.assert_bytes(cs, lay.header, "hdr")
    body_bits = core.assert_bytes(cs, lay.body, "body")
    for w in lay.midstate_bits:
        cs.enforce_bool(w, "midstate")

    digest_bits = sha256.sha256_blocks(cs, header_bits, lay.header_blocks, tag="sha_hdr")
    rsa.rsa_verify_65537(cs, lay.signature, lay.modulus, digest_bits, p.n, p.k, "rsa")

    cache = CharClassCache(cs)
    for w, bits in zip(lay.header, header_bits):
        cache.register_bits(w, bits)
    for w, bits in zip(lay.body, body_bits):
        cache.register_bits(w, bits)

    sentinel = cs.new_wire("sentinel80")
    cs.enforce_eq(LC.of(sentinel), LC.const(0x80), "sentinel")
    cs.compute(sentinel, lambda: 0x80, [])
    dkim_dfa = regexc.search_dfa(regexc.DKIM_HEADER)
    dkim_states = dfa_scan(cs, [sentinel] + list(lay.header), dkim_dfa, cache, "dkim")
    dkim_cnt = match_count(cs, dkim_states, dkim_dfa.accept, "dkim.cnt")
    cs.enforce_eq(LC.of(dkim_cnt), LC.const(p.dkim_match_count), "dkim/count")

    bh_dfa = regexc.search_dfa(regexc.BODY_HASH)
    bh_states = dfa_scan(cs, list(lay.header), bh_dfa, cache, "bh")
    bh_cnt = match_count(cs, bh_states, bh_dfa.accept, "bh.cnt")
    cs.enforce_eq(LC.of(bh_cnt), LC.const(1), "bh/count")

    bh_onehot = core.one_hot(cs, lay.body_hash_idx, p.max_header_bytes - p.bh_b64_len, "bh.idx")
    from .venmo import _shift_window

    bh_chars = _shift_window(cs, lay.header, bh_onehot, p.bh_b64_len, "bh.shift")
    decoded = b64.base64_decode_bits(cs, bh_chars, cache, "bh.dec")

    mid_words = [lay.midstate_bits[32 * i : 32 * i + 32] for i in range(8)]
    body_digest = sha256.sha256_blocks(cs, body_bits, lay.body_blocks, init_state=mid_words, tag="sha_body")
    for byte_i in range(32):
        wrd, b_in_w = divmod(byte_i, 4)
        for bit in range(8):
            cs.enforce_eq(
                LC.of(decoded[byte_i][bit]),
                LC.of(body_digest[32 * wrd + 8 * (3 - b_in_w) + bit]),
                "bh/eq",
            )

    if p.body_regex:
        dfa = regexc.search_dfa(p.body_regex)
        states = dfa_scan(cs, list(lay.body), dfa, cache, "brx")
        reveal = reveal_bytes(cs, lay.body, states, sorted(dfa.accept), "brx.rev")
        onehot = core.one_hot(cs, lay.reveal_idx, p.max_body_bytes - p.reveal_len, "brx.idx")
        chars = _shift_window(cs, reveal, onehot, p.reveal_len, "brx.shift")
        words = core.pack_bytes(cs, chars, 7, "brx.pack")
        for w, pub in zip(words, lay.reveal_words):
            cs.enforce_eq(LC.of(w), LC.of(pub), "brx/out")

    return cs, lay
