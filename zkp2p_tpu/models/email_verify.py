"""EmailVerify — the generic DKIM email circuit family.

Rebuild of `zk-email-verify-circuits/email.circom:15-222`
(`EmailVerify(max_header_bytes, max_body_bytes, n, k)`) — the
architectural ancestor of the Venmo circuit: header SHA-256 + RSA-2048 +
DKIM to/from regex + bh= extraction + partial body SHA + base64 check,
WITHOUT the Venmo-specific extraction; plus an optional body regex with
packed reveal output (instantiated here with `TwitterResetRegex`
semantics, `twitter_reset_regex.circom:5`, to complete the family).

Public signal layout: [modulus (k) | reveal words (n_reveal_words)] —
matching EmailVerify's `public [modulus]` + packed reveal outputs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..gadgets import core, rsa, sha256
from ..gadgets.regex import CharClassCache, dfa_scan, reveal_bytes
from ..regexc import compiler as regexc
from ..snark.r1cs import LC, ConstraintSystem
from . import common


@dataclass
class EmailVerifyParams:
    max_header_bytes: int = 1024
    max_body_bytes: int = 1536
    n: int = 121
    k: int = 17
    bh_b64_len: int = 44
    # optional body extraction (None = header/bh/body-hash checks only)
    body_regex: Optional[str] = regexc.TWITTER_RESET
    reveal_len: int = 21  # bytes -> 3 packed words
    dkim_match_count: int = 2


@dataclass
class EmailVerifyLayout:
    modulus: List[int] = field(default_factory=list)
    reveal_words: List[int] = field(default_factory=list)
    header: List[int] = field(default_factory=list)
    header_blocks: int = 0
    signature: List[int] = field(default_factory=list)
    body: List[int] = field(default_factory=list)
    body_blocks: int = 0
    midstate_bits: List[int] = field(default_factory=list)
    body_hash_idx: int = 0
    reveal_idx: int = 0


def build_email_verify(p: EmailVerifyParams):
    assert p.max_header_bytes % 64 == 0 and p.max_body_bytes % 64 == 0
    cs = ConstraintSystem("email_verify")
    lay = EmailVerifyLayout()

    lay.modulus = [cs.new_public(f"modulus[{i}]") for i in range(p.k)]
    n_words = (p.reveal_len + 6) // 7 if p.body_regex else 0
    lay.reveal_words = [cs.new_public(f"reveal[{i}]") for i in range(n_words)]

    lay.header = cs.new_wires(p.max_header_bytes, "in_padded")
    lay.header_blocks = cs.new_wire("in_len_blocks")
    lay.signature = cs.new_wires(p.k, "signature")
    lay.body = cs.new_wires(p.max_body_bytes, "in_body_padded")
    lay.body_blocks = cs.new_wire("in_body_len_blocks")
    lay.midstate_bits = cs.new_wires(256, "precomputed_sha")
    lay.body_hash_idx = cs.new_wire("body_hash_idx")
    if p.body_regex:
        lay.reveal_idx = cs.new_wire("reveal_idx")

    # prover-seeded inputs (inputs.email email_verify seed keys): the
    # audit's determinism sources + hook-coverage exemptions
    cs.mark_input(
        lay.header + [lay.header_blocks] + lay.signature + lay.body
        + [lay.body_blocks] + lay.midstate_bits + [lay.body_hash_idx]
        + ([lay.reveal_idx] if p.body_regex else [])
    )

    header_bits = core.assert_bytes(cs, lay.header, "hdr")
    body_bits = core.assert_bytes(cs, lay.body, "body")
    for w in lay.midstate_bits:
        cs.enforce_bool(w, "midstate")

    digest_bits = sha256.sha256_blocks(cs, header_bits, lay.header_blocks, tag="sha_hdr")
    rsa.rsa_verify_65537(cs, lay.signature, lay.modulus, digest_bits, p.n, p.k, "rsa")

    cache = CharClassCache(cs)
    for w, bits in zip(lay.header, header_bits):
        cache.register_bits(w, bits)
    for w, bits in zip(lay.body, body_bits):
        cache.register_bits(w, bits)

    common.dkim_header_match(cs, lay.header, cache, p.dkim_match_count)

    # bh= extraction + body hash equality — shared soundness-critical block
    # (shifts the regex-masked reveal, NOT the raw header; the round-2 bug
    # here was shifting lay.header directly, letting a prover point
    # body_hash_idx at arbitrary base64-alphabet bytes of the signed
    # header).  See models.common.constrain_body_hash.
    common.constrain_body_hash(
        cs,
        lay.header,
        body_bits,
        lay.body_blocks,
        lay.midstate_bits,
        lay.body_hash_idx,
        cache,
        p.max_header_bytes,
        p.bh_b64_len,
    )

    if p.body_regex:
        dfa = regexc.search_dfa(p.body_regex)
        states = dfa_scan(cs, list(lay.body), dfa, cache, "brx")
        reveal = reveal_bytes(cs, lay.body, states, sorted(dfa.accept), "brx.rev")
        onehot = core.one_hot(cs, lay.reveal_idx, p.max_body_bytes - p.reveal_len, "brx.idx")
        chars = common.shift_window(cs, reveal, onehot, p.reveal_len, "brx.shift")
        words = core.pack_bytes(cs, chars, 7, "brx.pack")
        for w, pub in zip(words, lay.reveal_words):
            cs.enforce_eq(LC.of(w), LC.of(pub), "brx/out")

    return cs, lay
