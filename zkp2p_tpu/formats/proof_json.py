"""snarkjs-compatible proof / public-signal / vkey JSON (wire formats).

The pipeline contract (SURVEY.md §3.2): same `proof.json` / `public.json`
shapes snarkjs and rapidsnark emit (`dizkus-scripts/5_gen_proof.sh`,
`6_gen_proof_rapidsnark.sh`), so our `prover=tpu` output drops into
`snarkjs groth16 verify` and the existing upload/chain tooling.

G2 coordinate order: snarkjs JSON stores [[x.c0,x.c1],[y.c0,y.c1]]; the
EVM precompile wants c1 before c0, so the app flips pi_b before calling
`Ramp.onRamp` (`SubmitOrderOnRampForm.tsx:36-46`).  `proof_to_calldata`
reproduces that flip — byte-for-byte the uint layout `Verifier.sol:360`
expects.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Tuple

from ..curve.host import G1Point, G2Point
from ..field.tower import Fq2
from ..snark.groth16 import Proof, VerifyingKey


def _g1(pt: G1Point) -> List[str]:
    assert pt is not None
    return [str(pt[0]), str(pt[1]), "1"]


def _g2(pt: G2Point) -> List[List[str]]:
    assert pt is not None
    x, y = pt
    return [[str(x.c0), str(x.c1)], [str(y.c0), str(y.c1)], ["1", "0"]]


def _parse_g1(v: Sequence) -> G1Point:
    x, y = int(v[0]), int(v[1])
    if x == 0 and y == 0:
        return None
    return (x, y)


def _parse_g2(v: Sequence) -> G2Point:
    return (Fq2(int(v[0][0]), int(v[0][1])), Fq2(int(v[1][0]), int(v[1][1])))


def proof_to_json(proof: Proof) -> Dict:
    return {
        "pi_a": _g1(proof.a),
        "pi_b": _g2(proof.b),
        "pi_c": _g1(proof.c),
        "protocol": "groth16",
        "curve": "bn128",
    }


def proof_from_json(d: Dict) -> Proof:
    return Proof(a=_parse_g1(d["pi_a"]), b=_parse_g2(d["pi_b"]), c=_parse_g1(d["pi_c"]))


def public_to_json(signals: Sequence[int]) -> List[str]:
    return [str(s) for s in signals]


def proof_to_calldata(proof: Proof, signals: Sequence[int]) -> Tuple:
    """(a, b, c, signals) uint tuples with the pi_b c1/c0 flip — the
    reformatProofForChain transform (SubmitOrderOnRampForm.tsx:36-46)."""
    a = (proof.a[0], proof.a[1])
    bx, by = proof.b
    b = ((bx.c1, bx.c0), (by.c1, by.c0))
    c = (proof.c[0], proof.c[1])
    return a, b, c, tuple(int(s) for s in signals)


def proof_from_calldata(a: Sequence, b: Sequence, c: Sequence) -> Proof:
    """Inverse of `proof_to_calldata`: reassemble a Proof from the EVM
    calldata layout (`Ramp.onRamp(uint[2] a, uint[2][2] b, uint[2] c, ...)`,
    `Verifier.sol:360`), undoing the pi_b c1/c0 flip.  This is how the
    chain-side pinned vectors (`test/ramp.test.js:193-196`) map back to
    curve points."""
    return Proof(
        a=_parse_g1(a),
        b=(Fq2(int(b[0][1]), int(b[0][0])), Fq2(int(b[1][1]), int(b[1][0]))),
        c=_parse_g1(c),
    )


def vkey_to_json(vk: VerifyingKey) -> Dict:
    """snarkjs verification_key.json (the embedded `app/src/helpers/vkey.ts`
    shape; `vk_alphabeta_12` is omitted — snarkjs recomputes pairings from
    the points during verify)."""
    return {
        "protocol": "groth16",
        "curve": "bn128",
        "nPublic": vk.n_public,
        "vk_alpha_1": _g1(vk.alpha_1),
        "vk_beta_2": _g2(vk.beta_2),
        "vk_gamma_2": _g2(vk.gamma_2),
        "vk_delta_2": _g2(vk.delta_2),
        "IC": [_g1(pt) for pt in vk.ic],
    }


def vkey_from_json(d: Dict) -> VerifyingKey:
    return VerifyingKey(
        n_public=int(d["nPublic"]),
        alpha_1=_parse_g1(d["vk_alpha_1"]),
        beta_2=_parse_g2(d["vk_beta_2"]),
        gamma_2=_parse_g2(d["vk_gamma_2"]),
        delta_2=_parse_g2(d["vk_delta_2"]),
        ic=[_parse_g1(p) for p in d["IC"]],
    )


def dump(obj, path: str) -> None:
    """Atomic write (temp + rename): concurrent service workers racing a
    stale-claim takeover must never leave a torn half-written JSON — a
    reader sees either the old complete file or the new complete file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)


def load(path: str):
    with open(path) as f:
        return json.load(f)
