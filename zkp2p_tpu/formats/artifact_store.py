"""Chunked artifact distribution: the zkey-chunk / S3 / IndexedDB layer.

Rebuild of the reference's key-delivery pipeline (SURVEY.md §2.7 artifact
sharding): the 3.5 GB proving key ships as gzip chunks `circuit.zkeyb..k`
(fork pinned at `dizkus-scripts/3_gen_both_zkeys.sh:22`), uploaded by
`upload_chunked_keys_to_s3.sh:13-23`, fetched concurrently and cached in
IndexedDB by `app/src/helpers/zkp.ts:24-68`.

Here: a content-addressed chunk store over any directory-like backend
(local fs now; an S3/GCS client drops into `Backend`), with gzip chunks,
a manifest, resumable fetch into a local cache, and integrity hashes —
the checkpoint/resume behavior the browser got from IndexedDB.
"""

from __future__ import annotations

import gzip
import hashlib
import json
import os
from dataclasses import dataclass
from typing import List, Optional, Protocol

CHUNK_SUFFIXES = "bcdefghijk"  # 10 chunks, zkp.ts:13


class Backend(Protocol):
    def put(self, name: str, data: bytes) -> None: ...
    def get(self, name: str) -> bytes: ...
    def exists(self, name: str) -> bool: ...


class DirBackend:
    """Local directory backend (S3 stand-in; msw-mock analog in tests)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def put(self, name: str, data: bytes) -> None:
        with open(os.path.join(self.root, name), "wb") as f:
            f.write(data)

    def get(self, name: str) -> bytes:
        with open(os.path.join(self.root, name), "rb") as f:
            return f.read()

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self.root, name))


@dataclass
class Manifest:
    name: str
    chunks: List[str]
    sha256: str
    raw_size: int


def upload_chunked(backend: Backend, name: str, blob: bytes, n_chunks: int = len(CHUNK_SUFFIXES)) -> Manifest:
    """Split + gzip + upload (upload_chunked_keys_to_s3.sh semantics:
    ~45% size cut from gzip, 10-way parallel download)."""
    n_chunks = min(n_chunks, max(1, len(blob)))
    size = (len(blob) + n_chunks - 1) // n_chunks
    chunk_names = []
    for i in range(n_chunks):
        part = blob[i * size : (i + 1) * size]
        cname = f"{name}{CHUNK_SUFFIXES[i] if i < len(CHUNK_SUFFIXES) else i}.gz"
        backend.put(cname, gzip.compress(part))
        chunk_names.append(cname)
    manifest = Manifest(
        name=name,
        chunks=chunk_names,
        sha256=hashlib.sha256(blob).hexdigest(),
        raw_size=len(blob),
    )
    backend.put(f"{name}.manifest.json", json.dumps(manifest.__dict__).encode())
    return manifest


def download_chunked(backend: Backend, name: str, cache_dir: Optional[str] = None, progress=None) -> bytes:
    """Fetch + uncompress + reassemble, with a local chunk cache so
    re-fetches are free (the IndexedDB localforage cache, zkp.ts:51-68)."""
    manifest = Manifest(**json.loads(backend.get(f"{name}.manifest.json")))
    parts: List[bytes] = []
    for i, cname in enumerate(manifest.chunks):
        cached = os.path.join(cache_dir, cname) if cache_dir else None
        if cached and os.path.exists(cached):
            with open(cached, "rb") as f:
                comp = f.read()
        else:
            comp = backend.get(cname)
            if cached:
                os.makedirs(cache_dir, exist_ok=True)
                with open(cached, "wb") as f:
                    f.write(comp)
        parts.append(gzip.decompress(comp))
        if progress:
            progress(i + 1, len(manifest.chunks))
    blob = b"".join(parts)
    if hashlib.sha256(blob).hexdigest() != manifest.sha256:
        raise IOError(f"chunk integrity failure for {name}")
    if len(blob) != manifest.raw_size:
        raise IOError(f"size mismatch for {name}")
    return blob
