"""circom binary formats: .r1cs and .wtns, read AND write.

Interop with the reference's toolchain (SURVEY.md §2.2): `circom --r1cs`
emits .r1cs consumed by snarkjs setup; witness generators emit .wtns
consumed by `snarkjs groth16 prove` / rapidsnark
(`dizkus-scripts/2_gen_wtns.sh`, `6_gen_proof_rapidsnark.sh:24-31`).
Supporting both directions means:
  - our ConstraintSystem can be exported for snarkjs to set up / prove
    (differential verification of circuits), and
  - real circom artifacts can be imported and proven by the TPU prover
    (drop-in `prover=tpu`).

Format (iden3 binfile): magic(4) version(u32) n_sections(u32) then
sections of [type u32][size u64][payload].  Field elements are 32-byte
little-endian.  r1cs header section: fieldSize u32, prime, nWires,
nPubOut, nPubIn, nPrvIn, nLabels u64, nConstraints.  Wire order:
[1, pubOuts, pubIns, prvIns] — our publics map to pubOuts.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Tuple

from ..field.bn254 import R
from ..snark.r1cs import ConstraintSystem

R1CS_MAGIC = b"r1cs"
WTNS_MAGIC = b"wtns"


def _fe_bytes(x: int) -> bytes:
    return (x % R).to_bytes(32, "little")


def _write_binfile(path: str, magic: bytes, version: int, sections: List[Tuple[int, bytes]]) -> None:
    with open(path, "wb") as f:
        f.write(magic)
        f.write(struct.pack("<II", version, len(sections)))
        for stype, payload in sections:
            f.write(struct.pack("<IQ", stype, len(payload)))
            f.write(payload)


def _read_binfile(path: str, magic: bytes) -> Dict[int, bytes]:
    with open(path, "rb") as f:
        data = f.read()
    assert data[:4] == magic, f"bad magic {data[:4]!r}"
    _version, n_sections = struct.unpack_from("<II", data, 4)
    off = 12
    sections: Dict[int, bytes] = {}
    for _ in range(n_sections):
        stype, size = struct.unpack_from("<IQ", data, off)
        off += 12
        sections[stype] = data[off : off + size]
        off += size
    return sections


# -------------------------------------------------------------- r1cs


@dataclass
class R1csFile:
    n_wires: int
    n_pub_out: int
    n_pub_in: int
    n_prv_in: int
    constraints: List[Tuple[Dict[int, int], Dict[int, int], Dict[int, int]]]

    @property
    def n_public(self) -> int:
        return self.n_pub_out + self.n_pub_in


def write_r1cs(cs: ConstraintSystem, path: str) -> None:
    header = struct.pack("<I", 32) + R.to_bytes(32, "little")
    n_prv = cs.num_wires - 1 - cs.num_public
    header += struct.pack("<IIIIQI", cs.num_wires, cs.num_public, 0, n_prv, cs.num_wires, cs.num_constraints)

    body = bytearray()
    for con in cs.constraints:
        for terms in (con.a, con.b, con.c):
            body += struct.pack("<I", len(terms))
            for wire, coeff in sorted(terms.items()):
                body += struct.pack("<I", wire) + _fe_bytes(coeff)

    labels = b"".join(struct.pack("<Q", i) for i in range(cs.num_wires))
    _write_binfile(path, R1CS_MAGIC, 1, [(1, header), (2, bytes(body)), (3, labels)])


def read_r1cs(path: str) -> R1csFile:
    sections = _read_binfile(path, R1CS_MAGIC)
    hdr = sections[1]
    fs = struct.unpack_from("<I", hdr, 0)[0]
    prime = int.from_bytes(hdr[4 : 4 + fs], "little")
    assert prime == R, "not a BN254-scalar r1cs"
    n_wires, n_pub_out, n_pub_in, n_prv, _n_labels, n_constraints = struct.unpack_from(
        "<IIIIQI", hdr, 4 + fs
    )
    body = sections[2]
    off = 0
    constraints = []
    for _ in range(n_constraints):
        lcs = []
        for _k in range(3):
            (n_terms,) = struct.unpack_from("<I", body, off)
            off += 4
            terms: Dict[int, int] = {}
            for _t in range(n_terms):
                (wire,) = struct.unpack_from("<I", body, off)
                off += 4
                terms[wire] = int.from_bytes(body[off : off + fs], "little")
                off += fs
            lcs.append(terms)
        constraints.append((lcs[0], lcs[1], lcs[2]))
    return R1csFile(
        n_wires=n_wires,
        n_pub_out=n_pub_out,
        n_pub_in=n_pub_in,
        n_prv_in=n_prv,
        constraints=constraints,
    )


def r1cs_to_constraint_system(r: R1csFile, name: str = "imported") -> ConstraintSystem:
    """Imported circuits carry no witness program — witnesses arrive via
    .wtns (the circom witness generator's job)."""
    cs = ConstraintSystem(name)
    for i in range(r.n_public):
        cs.new_public(f"pub{i}")
    for i in range(r.n_wires - 1 - r.n_public):
        cs.new_wire(f"w{i}")
    for a, b, c in r.constraints:
        from ..snark.r1cs import LC

        cs.enforce(LC(a), LC(b), LC(c), "imported")
    return cs


# -------------------------------------------------------------- wtns


def write_wtns(witness: List[int], path: str) -> None:
    header = struct.pack("<I", 32) + R.to_bytes(32, "little") + struct.pack("<I", len(witness))
    body = b"".join(_fe_bytes(w) for w in witness)
    _write_binfile(path, WTNS_MAGIC, 2, [(1, header), (2, body)])


def read_wtns(path: str) -> List[int]:
    sections = _read_binfile(path, WTNS_MAGIC)
    hdr = sections[1]
    fs = struct.unpack_from("<I", hdr, 0)[0]
    prime = int.from_bytes(hdr[4 : 4 + fs], "little")
    assert prime == R
    (n,) = struct.unpack_from("<I", hdr, 4 + fs)
    body = sections[2]
    return [int.from_bytes(body[i * fs : (i + 1) * fs], "little") for i in range(n)]
