"""snarkjs `.zkey` read/write — monolithic AND b..k-chunked.

The reference's entire key pipeline is zkey-shaped: setup emits
`circuit_final.zkey` (`dizkus-scripts/3_gen_both_zkeys.sh:18-65`), the
browser downloads it as ten chunks `circuit.zkeyb..k`
(`app/src/helpers/zkp.ts:13`, `upload_chunked_keys_to_s3.sh:13-23`), and
every prover consumes it.  Supporting the format both ways means

  - a ceremony key produced by the actual reference toolchain can be
    imported and proven with `prover=tpu` (drop-in compatibility), and
  - our development setup can be exported for stock snarkjs to prove /
    verify against (differential verification), and
  - the CLI's key persistence is a documented public format instead of
    pickle (round-1 advisor finding).

Format (iden3 binfile, magic "zkey", version 1; snarkjs
src/zkey_utils.js): sections [type u32][size u64][payload]:

  1 header        : protocol id u32 (1 = groth16)
  2 groth16 header: n8q u32, q, n8r u32, r, nVars u32, nPublic u32,
                    domainSize u32, alpha1 G1, beta1 G1, beta2 G2,
                    gamma2 G2, delta1 G1, delta2 G2
  3 IC            : (nPublic+1) G1
  4 coeffs        : nCoeffs u32, then [matrix u32, row u32, wire u32,
                    value Fr] — matrices A(0)/B(1) only, INCLUDING the
                    public-input binding rows appended after the R1CS
                    rows (row = nConstraints + i, wire = i, value = 1),
                    exactly our `snark.groth16.qap_rows` convention
  5..8 A/B1/B2/C  : per-wire query points (C omits wires 0..nPublic)
  9 H             : domainSize G1 points — the coset-Lagrange basis
                    (our setup adopts the identical odd-coset convention,
                    `snark.groth16.coset_gen`)
  10 contributions: ceremony transcript (opaque here)

All field elements are little-endian **Montgomery** form (R = 2^256),
per snarkjs `toRprLEM`/`fromRprLEM`; infinity is all-zero bytes.

Chunked form: the forks split the byte stream into equal slices with
suffixes b..k; `read_zkey` accepts either one path or the chunk list and
`split_zkey` produces the chunks (`zkp.ts:13` suffix convention).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..curve.host import G1Point, G2Point
from ..field.bn254 import MONT_R, P, R
from ..field.tower import Fq2
from ..snark.groth16 import ProvingKey, VerifyingKey

ZKEY_MAGIC = b"zkey"
_Q_INV = pow(MONT_R, -1, P)
_R_INV = pow(MONT_R, -1, R)
N8 = 32

CHUNK_SUFFIXES = "bcdefghijk"  # app/src/helpers/zkp.ts:13


# ------------------------------------------------------------ primitives


def _fq_to_m(x: int) -> bytes:
    return (x * MONT_R % P).to_bytes(N8, "little")


def _fq_from_m(b: bytes) -> int:
    return int.from_bytes(b, "little") * _Q_INV % P


def _fr_to_m(x: int) -> bytes:
    return (x * MONT_R % R).to_bytes(N8, "little")


def _fr_from_m(b: bytes) -> int:
    return int.from_bytes(b, "little") * _R_INV % R


def _g1_bytes(pt: G1Point) -> bytes:
    if pt is None:
        return b"\x00" * (2 * N8)
    return _fq_to_m(pt[0]) + _fq_to_m(pt[1])


def _g1_parse(b: bytes) -> G1Point:
    if b == b"\x00" * (2 * N8):
        return None
    return (_fq_from_m(b[:N8]), _fq_from_m(b[N8:]))


def _g2_bytes(pt: G2Point) -> bytes:
    if pt is None:
        return b"\x00" * (4 * N8)
    x, y = pt
    return _fq_to_m(x.c0) + _fq_to_m(x.c1) + _fq_to_m(y.c0) + _fq_to_m(y.c1)


def _g2_parse(b: bytes) -> G2Point:
    if b == b"\x00" * (4 * N8):
        return None
    vals = [_fq_from_m(b[i * N8 : (i + 1) * N8]) for i in range(4)]
    return (Fq2(vals[0], vals[1]), Fq2(vals[2], vals[3]))


# ------------------------------------------------------------ data model


@dataclass
class Contribution:
    """One phase-2 MPC contribution record (section 10), the shape
    snarkjs `zkey contribute`/`beacon` appends
    (`dizkus-scripts/3_gen_both_zkeys.sh:18-65`): the post-contribution
    delta, a BGM17 proof-of-knowledge of the applied delta', and the
    running transcript hash.  kind 0 = interactive, 1 = beacon (beacon
    params stored so verifiers can re-derive delta' deterministically)."""

    delta_after: G1Point
    pok_g1_s: G1Point
    pok_g1_sx: G1Point
    pok_g2_spx: G2Point
    transcript: bytes  # 64
    kind: int = 0
    name: str = ""
    beacon_hash: bytes = b""
    beacon_iter_exp: int = 0


@dataclass
class MpcParams:
    cs_hash: bytes  # 64-byte circuit digest
    contributions: List[Contribution]


def _mpc_to_bytes(mpc: MpcParams) -> bytes:
    out = bytearray()
    out += mpc.cs_hash.ljust(64, b"\x00")[:64]
    out += struct.pack("<I", len(mpc.contributions))
    for c in mpc.contributions:
        out += _g1_bytes(c.delta_after) + _g1_bytes(c.pok_g1_s) + _g1_bytes(c.pok_g1_sx)
        out += _g2_bytes(c.pok_g2_spx)
        out += c.transcript.ljust(64, b"\x00")[:64]
        name_b = c.name.encode()
        out += struct.pack("<II", c.kind, len(name_b)) + name_b
        if c.kind == 1:
            out += c.beacon_hash.ljust(64, b"\x00")[:64] + struct.pack("<I", c.beacon_iter_exp)
    return bytes(out)


def _mpc_from_bytes(buf: bytes) -> Optional[MpcParams]:
    """Parse OUR section-10 layout.  snarkjs's own record encoding
    differs (TLV-parameterized); a zkey produced by stock snarkjs with
    contributions will not match — in that case return None so the key
    still imports (contribution records become opaque, exactly the
    pre-ceremony behavior), rather than desyncing into garbage."""
    try:
        if len(buf) < 68:
            return MpcParams(cs_hash=buf.ljust(64, b"\x00")[:64], contributions=[])
        cs_hash = buf[:64]
        (n,) = struct.unpack_from("<I", buf, 64)
        if n > 10_000:  # sanity: no real ceremony has this many rounds
            return None
        o = 68
        contribs = []
        for _ in range(n):
            if o + 384 + 8 > len(buf):
                return None
            delta_after = _g1_parse(buf[o : o + 64]); o += 64
            g1_s = _g1_parse(buf[o : o + 64]); o += 64
            g1_sx = _g1_parse(buf[o : o + 64]); o += 64
            g2_spx = _g2_parse(buf[o : o + 128]); o += 128
            transcript = buf[o : o + 64]; o += 64
            kind, name_len = struct.unpack_from("<II", buf, o); o += 8
            if kind not in (0, 1) or o + name_len > len(buf):
                return None
            name = buf[o : o + name_len].decode(); o += name_len
            beacon_hash, beacon_iter = b"", 0
            if kind == 1:
                if o + 68 > len(buf):
                    return None
                beacon_hash = buf[o : o + 64]; o += 64
                (beacon_iter,) = struct.unpack_from("<I", buf, o); o += 4
            contribs.append(
                Contribution(delta_after, g1_s, g1_sx, g2_spx, transcript, kind, name, beacon_hash, beacon_iter)
            )
        if o != len(buf):
            return None  # trailing bytes: not our layout
        return MpcParams(cs_hash=cs_hash, contributions=contribs)
    except Exception:  # noqa: BLE001 — foreign/corrupt section -> opaque
        return None


@dataclass
class ZkeyData:
    n_vars: int
    n_public: int
    domain_size: int
    alpha_1: G1Point
    beta_1: G1Point
    beta_2: G2Point
    gamma_2: G2Point
    delta_1: G1Point
    delta_2: G2Point
    ic: List[G1Point]
    # (matrix 0/1, row, wire, value) — includes public binding rows
    coeffs: List[Tuple[int, int, int, int]]
    a_query: List[G1Point]
    b1_query: List[G1Point]
    b2_query: List[G2Point]
    c_query: List[Optional[G1Point]]  # None for wires 0..n_public
    h_query: List[G1Point]
    mpc: Optional[MpcParams] = None

    def to_proving_key(self) -> ProvingKey:
        return ProvingKey(
            n_public=self.n_public,
            domain_size=self.domain_size,
            alpha_1=self.alpha_1,
            beta_1=self.beta_1,
            beta_2=self.beta_2,
            delta_1=self.delta_1,
            delta_2=self.delta_2,
            a_query=self.a_query,
            b1_query=self.b1_query,
            b2_query=self.b2_query,
            c_query=self.c_query,
            h_query=self.h_query,
        )

    def to_verifying_key(self) -> VerifyingKey:
        return VerifyingKey(
            n_public=self.n_public,
            alpha_1=self.alpha_1,
            beta_2=self.beta_2,
            gamma_2=self.gamma_2,
            delta_2=self.delta_2,
            ic=list(self.ic),
        )

    def qap_row_arrays(self) -> Tuple[List[Dict[int, int]], List[Dict[int, int]]]:
        """Coeff section -> per-row A and B wire->value dicts (the shape
        `prover.groth16_tpu.device_pk_from_rows` consumes)."""
        n_rows = max((row for _m, row, _w, _v in self.coeffs), default=-1) + 1
        a: List[Dict[int, int]] = [dict() for _ in range(n_rows)]
        b: List[Dict[int, int]] = [dict() for _ in range(n_rows)]
        for m, row, wire, value in self.coeffs:
            tgt = a if m == 0 else b
            tgt[row][wire] = (tgt[row].get(wire, 0) + value) % R
        return a, b


# ------------------------------------------------------------------ write


def write_zkey_data(path: str, z: ZkeyData) -> None:
    """Serialize a ZkeyData verbatim (coeff order preserved) — the path
    the ceremony ops use so a contributed key round-trips exactly."""
    sections: List[Tuple[int, bytes]] = []
    sections.append((1, struct.pack("<I", 1)))
    hdr = struct.pack("<I", N8) + P.to_bytes(N8, "little")
    hdr += struct.pack("<I", N8) + R.to_bytes(N8, "little")
    hdr += struct.pack("<III", z.n_vars, z.n_public, z.domain_size)
    hdr += _g1_bytes(z.alpha_1) + _g1_bytes(z.beta_1) + _g2_bytes(z.beta_2)
    hdr += _g2_bytes(z.gamma_2) + _g1_bytes(z.delta_1) + _g2_bytes(z.delta_2)
    sections.append((2, hdr))
    sections.append((3, b"".join(_g1_bytes(p) for p in z.ic)))
    coeffs = bytearray()
    for m, row, wire, value in z.coeffs:
        coeffs += struct.pack("<III", m, row, wire) + _fr_to_m(value)
    sections.append((4, struct.pack("<I", len(z.coeffs)) + bytes(coeffs)))
    sections.append((5, b"".join(_g1_bytes(p) for p in z.a_query)))
    sections.append((6, b"".join(_g1_bytes(p) for p in z.b1_query)))
    sections.append((7, b"".join(_g2_bytes(p) for p in z.b2_query)))
    sections.append((8, b"".join(_g1_bytes(p) for p in z.c_query[z.n_public + 1 :])))
    sections.append((9, b"".join(_g1_bytes(p) for p in z.h_query)))
    mpc = z.mpc or MpcParams(cs_hash=b"\x00" * 64, contributions=[])
    sections.append((10, _mpc_to_bytes(mpc)))
    with open(path, "wb") as f:
        f.write(ZKEY_MAGIC)
        f.write(struct.pack("<II", 1, len(sections)))
        for stype, payload in sections:
            f.write(struct.pack("<IQ", stype, len(payload)))
            f.write(payload)


def write_zkey(path: str, pk: ProvingKey, vk: VerifyingKey, qap_rows) -> None:
    """Serialize our key material as a snarkjs-readable zkey.

    `qap_rows` is `snark.groth16.qap_rows(cs)` — R1CS rows + the appended
    public binding rows, written to the coeff section the same way
    snarkjs's setup does."""
    sections: List[Tuple[int, bytes]] = []
    sections.append((1, struct.pack("<I", 1)))  # groth16

    hdr = struct.pack("<I", N8) + P.to_bytes(N8, "little")
    hdr += struct.pack("<I", N8) + R.to_bytes(N8, "little")
    n_vars = len(pk.a_query)
    hdr += struct.pack("<III", n_vars, pk.n_public, pk.domain_size)
    hdr += _g1_bytes(pk.alpha_1) + _g1_bytes(pk.beta_1) + _g2_bytes(pk.beta_2)
    hdr += _g2_bytes(vk.gamma_2) + _g1_bytes(pk.delta_1) + _g2_bytes(pk.delta_2)
    sections.append((2, hdr))

    sections.append((3, b"".join(_g1_bytes(p) for p in vk.ic)))

    coeffs = bytearray()
    n_coeffs = 0
    for m in (0, 1):
        for row, triple in enumerate(qap_rows):
            for wire, value in triple[m].items():
                coeffs += struct.pack("<III", m, row, wire) + _fr_to_m(value)
                n_coeffs += 1
    sections.append((4, struct.pack("<I", n_coeffs) + bytes(coeffs)))

    sections.append((5, b"".join(_g1_bytes(p) for p in pk.a_query)))
    sections.append((6, b"".join(_g1_bytes(p) for p in pk.b1_query)))
    sections.append((7, b"".join(_g2_bytes(p) for p in pk.b2_query)))
    sections.append(
        (8, b"".join(_g1_bytes(p) for p in pk.c_query[pk.n_public + 1 :]))
    )
    sections.append((9, b"".join(_g1_bytes(p) for p in pk.h_query)))
    # Section 10 (MPC params): snarkjs readMPCParams expects a 64-byte
    # circuit hash BEFORE the u32 contribution count — a bare count makes
    # `zkey verify`/`contribute` misparse the export (groth16 prove and
    # vkey export never read this section).  Dev setup: zero hash, zero
    # contributions.
    sections.append((10, b"\x00" * 64 + struct.pack("<I", 0)))

    with open(path, "wb") as f:
        f.write(ZKEY_MAGIC)
        f.write(struct.pack("<II", 1, len(sections)))
        for stype, payload in sections:
            f.write(struct.pack("<IQ", stype, len(payload)))
            f.write(payload)


def split_zkey(path: str, n_chunks: int = 10) -> List[str]:
    """Monolithic zkey -> `path` + suffix chunks b..k (`zkp.ts:13`)."""
    if not 1 <= n_chunks <= len(CHUNK_SUFFIXES):
        raise ValueError(f"n_chunks must be 1..{len(CHUNK_SUFFIXES)} (suffixes {CHUNK_SUFFIXES})")
    with open(path, "rb") as f:
        data = f.read()
    per = (len(data) + n_chunks - 1) // n_chunks
    out = []
    for i in range(n_chunks):
        p = path + CHUNK_SUFFIXES[i]
        with open(p, "wb") as f:
            f.write(data[i * per : (i + 1) * per])
        out.append(p)
    return out


# ------------------------------------------------------------------- read


def read_zkey(path_or_chunks) -> ZkeyData:
    """Parse a zkey from one path, an ordered chunk-path list, or raw
    bytes (e.g. reassembled from the artifact store)."""
    if isinstance(path_or_chunks, (bytes, bytearray)):
        data = bytes(path_or_chunks)
    elif isinstance(path_or_chunks, (list, tuple)):
        data = b""
        for p in path_or_chunks:
            with open(p, "rb") as f:
                data += f.read()
    else:
        with open(path_or_chunks, "rb") as f:
            data = f.read()
    assert data[:4] == ZKEY_MAGIC, f"bad magic {data[:4]!r}"
    _version, n_sections = struct.unpack_from("<II", data, 4)
    off = 12
    sections: Dict[int, bytes] = {}
    for _ in range(n_sections):
        stype, size = struct.unpack_from("<IQ", data, off)
        off += 12
        sections[stype] = data[off : off + size]
        off += size

    (protocol,) = struct.unpack_from("<I", sections[1], 0)
    assert protocol == 1, f"not a groth16 zkey (protocol {protocol})"

    hdr = sections[2]
    o = 0
    (n8q,) = struct.unpack_from("<I", hdr, o)
    o += 4
    q = int.from_bytes(hdr[o : o + n8q], "little")
    o += n8q
    assert n8q == N8 and q == P, "not a BN254 zkey"
    (n8r,) = struct.unpack_from("<I", hdr, o)
    o += 4
    r = int.from_bytes(hdr[o : o + n8r], "little")
    o += n8r
    assert n8r == N8 and r == R
    n_vars, n_public, domain_size = struct.unpack_from("<III", hdr, o)
    o += 12
    alpha_1 = _g1_parse(hdr[o : o + 64]); o += 64
    beta_1 = _g1_parse(hdr[o : o + 64]); o += 64
    beta_2 = _g2_parse(hdr[o : o + 128]); o += 128
    gamma_2 = _g2_parse(hdr[o : o + 128]); o += 128
    delta_1 = _g1_parse(hdr[o : o + 64]); o += 64
    delta_2 = _g2_parse(hdr[o : o + 128]); o += 128

    ic = [_g1_parse(sections[3][i * 64 : (i + 1) * 64]) for i in range(n_public + 1)]

    cbuf = sections[4]
    (n_coeffs,) = struct.unpack_from("<I", cbuf, 0)
    coeffs = []
    o = 4
    for _ in range(n_coeffs):
        m, row, wire = struct.unpack_from("<III", cbuf, o)
        o += 12
        coeffs.append((m, row, wire, _fr_from_m(cbuf[o : o + N8])))
        o += N8

    a_query = [_g1_parse(sections[5][i * 64 : (i + 1) * 64]) for i in range(n_vars)]
    b1_query = [_g1_parse(sections[6][i * 64 : (i + 1) * 64]) for i in range(n_vars)]
    b2_query = [_g2_parse(sections[7][i * 128 : (i + 1) * 128]) for i in range(n_vars)]
    n_priv = n_vars - n_public - 1
    c_priv = [_g1_parse(sections[8][i * 64 : (i + 1) * 64]) for i in range(n_priv)]
    c_query: List[Optional[G1Point]] = [None] * (n_public + 1) + c_priv
    h_query = [_g1_parse(sections[9][i * 64 : (i + 1) * 64]) for i in range(domain_size)]
    mpc = _mpc_from_bytes(sections[10]) if 10 in sections else None

    return ZkeyData(
        n_vars=n_vars,
        n_public=n_public,
        domain_size=domain_size,
        alpha_1=alpha_1,
        beta_1=beta_1,
        beta_2=beta_2,
        gamma_2=gamma_2,
        delta_1=delta_1,
        delta_2=delta_2,
        ic=ic,
        coeffs=coeffs,
        a_query=a_query,
        b1_query=b1_query,
        b2_query=b2_query,
        c_query=c_query,
        h_query=h_query,
        mpc=mpc,
    )
