"""Host-side BN254 group arithmetic: G1 over Fq, G2 over the Fq2 twist.

Used by the trusted setup, the pairing-based verifier, serializers, and as
the oracle the vectorised TPU point kernels (zkp2p_tpu.ops) are tested
against.  The reference delegates all of this to snarkjs/rapidsnark
internals and to the EVM precompiles (contracts/Verifier.sol:42-100
ecAdd/ecMul via precompiles 6 and 7).

Points are affine tuples of ints / Fq2 (None = point at infinity); scalar
multiplication runs in Jacobian coordinates internally.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..field.bn254 import CURVE_B, G1_GEN, G2_GEN, P
from ..field.tower import Fq2, XI

G1Point = Optional[Tuple[int, int]]
G2Point = Optional[Tuple[Fq2, Fq2]]

# b coefficient of the D-type twist curve  y^2 = x^3 + 3/xi  over Fq2.
TWIST_B = Fq2(3, 0) * XI.inv()

G2_GENERATOR: G2Point = (Fq2(*G2_GEN[0]), Fq2(*G2_GEN[1]))
G1_GENERATOR: G1Point = G1_GEN


# ---------------------------------------------------------------- G1 (Fq)


def g1_is_on_curve(pt: G1Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - CURVE_B) % P == 0


def g1_neg(pt: G1Point) -> G1Point:
    if pt is None:
        return None
    return (pt[0], (-pt[1]) % P)


def g1_add(a: G1Point, b: G1Point) -> G1Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        # doubling
        lam = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def g1_double(a: G1Point) -> G1Point:
    return g1_add(a, a)


def g1_mul(pt: G1Point, k: int) -> G1Point:
    """Scalar multiplication via Jacobian double-and-add."""
    if pt is None or k == 0:
        return None
    if k < 0:
        return g1_mul(g1_neg(pt), -k)
    # Jacobian (X, Y, Z); affine = (X/Z^2, Y/Z^3)
    X, Y, Z = pt[0], pt[1], 1
    RX, RY, RZ = 0, 1, 0  # infinity
    bits = bin(k)[2:]
    for bit in bits:
        if RZ != 0:
            RX, RY, RZ = _jac_double(RX, RY, RZ)
        if bit == "1":
            if RZ == 0:
                RX, RY, RZ = X, Y, Z
            else:
                RX, RY, RZ = _jac_add(RX, RY, RZ, X, Y, Z)
    if RZ == 0:
        return None
    zinv = pow(RZ, P - 2, P)
    z2 = zinv * zinv % P
    return (RX * z2 % P, RY * z2 % P * zinv % P)


def _jac_double(X1, Y1, Z1):
    A = X1 * X1 % P
    B = Y1 * Y1 % P
    C = B * B % P
    D = 2 * ((X1 + B) * (X1 + B) - A - C) % P
    E = 3 * A % P
    F = E * E % P
    X3 = (F - 2 * D) % P
    Y3 = (E * (D - X3) - 8 * C) % P
    Z3 = 2 * Y1 * Z1 % P
    return X3, Y3, Z3


def _jac_add(X1, Y1, Z1, X2, Y2, Z2):
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if U1 == U2:
        if S1 != S2:
            return 0, 1, 0
        return _jac_double(X1, Y1, Z1)
    H = (U2 - U1) % P
    I = (2 * H) * (2 * H) % P
    J = H * I % P
    rr = 2 * (S2 - S1) % P
    V = U1 * I % P
    X3 = (rr * rr - J - 2 * V) % P
    Y3 = (rr * (V - X3) - 2 * S1 * J) % P
    Z3 = ((Z1 + Z2) * (Z1 + Z2) - Z1Z1 - Z2Z2) * H % P
    return X3, Y3, Z3


def g1_msm(points, scalars) -> G1Point:
    """Reference MSM (naive); the TPU Pippenger kernel is tested against this."""
    acc: G1Point = None
    for pt, s in zip(points, scalars, strict=True):
        acc = g1_add(acc, g1_mul(pt, s))
    return acc


# ---------------------------------------------------------------- G2 (Fq2)


def g2_is_on_curve(pt: G2Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y.square() - x.square() * x - TWIST_B).is_zero()


def g2_neg(pt: G2Point) -> G2Point:
    if pt is None:
        return None
    return (pt[0], -pt[1])


def g2_add(a: G2Point, b: G2Point) -> G2Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if (y1 + y2).is_zero():
            return None
        lam = (x1.square() * 3) * (y1 * 2).inv()
    else:
        lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam.square() - x1 - x2
    y3 = lam * (x1 - x3) - y1
    return (x3, y3)


def g2_double(a: G2Point) -> G2Point:
    return g2_add(a, a)


def g2_mul(pt: G2Point, k: int) -> G2Point:
    if pt is None or k == 0:
        return None
    if k < 0:
        return g2_mul(g2_neg(pt), -k)
    result: G2Point = None
    addend = pt
    while k:
        if k & 1:
            result = g2_add(result, addend)
        addend = g2_double(addend)
        k >>= 1
    return result


def g2_msm(points, scalars) -> G2Point:
    acc: G2Point = None
    for pt, s in zip(points, scalars, strict=True):
        acc = g2_add(acc, g2_mul(pt, s))
    return acc


# ------------------------------------------------- fixed-base scalar mul

from ..field.bn254 import R as _R_SCALAR  # noqa: E402


def _g2_jac_add(X1, Y1, Z1, X2, Y2, Z2):
    """Jacobian add over Fq2 (mirrors _jac_add; Fq2 operators auto-reduce)."""
    Z1Z1 = Z1 * Z1
    Z2Z2 = Z2 * Z2
    U1 = X1 * Z2Z2
    U2 = X2 * Z1Z1
    S1 = Y1 * Z2 * Z2Z2
    S2 = Y2 * Z1 * Z1Z1
    if U1 == U2:
        if S1 != S2:
            return Fq2.zero(), Fq2.one(), Fq2.zero()
        return _g2_jac_double(X1, Y1, Z1)
    H = U2 - U1
    I = (H + H) * (H + H)
    J = H * I
    rr = (S2 - S1) + (S2 - S1)
    V = U1 * I
    X3 = rr * rr - J - V - V
    Y3 = rr * (V - X3) - (S1 * J + S1 * J)
    Z3 = ((Z1 + Z2) * (Z1 + Z2) - Z1Z1 - Z2Z2) * H
    return X3, Y3, Z3


def _g2_jac_double(X1, Y1, Z1):
    A = X1 * X1
    B = Y1 * Y1
    C = B * B
    t = (X1 + B) * (X1 + B) - A - C
    D = t + t
    E = A + A + A
    F = E * E
    X3 = F - D - D
    C8 = C + C
    C8 = C8 + C8
    C8 = C8 + C8
    Y3 = E * (D - X3) - C8
    YZ = Y1 * Z1
    Z3 = YZ + YZ
    return X3, Y3, Z3


class FixedBaseMul:
    """Windowed fixed-base scalar multiplication (host).

    Setup evaluates hundreds of thousands of scalar muls of the SAME base
    (the generators) — `[A_i(tau)]1` etc. for every wire.  A one-time
    8-bit-window affine table (32 windows x 255 entries) turns each mul
    into <= 31 Jacobian mixed additions with a single final inversion:
    ~15x over per-mul double-and-add."""

    WINDOW = 8

    def __init__(self, base, add, jac_add, to_affine):
        self._jac_add = jac_add
        self._to_affine = to_affine
        self.tables = []
        w_base = base
        for _ in range(256 // self.WINDOW):
            row = [None]
            cur = None
            for _d in range(1, 1 << self.WINDOW):
                cur = add(cur, w_base)
                row.append(cur)
            self.tables.append(row)
            for _ in range(self.WINDOW):
                w_base = add(w_base, w_base)

    def mul(self, k: int):
        k %= _R_SCALAR
        acc = None  # (X, Y, Z) jacobian
        w = 0
        while k:
            d = k & ((1 << self.WINDOW) - 1)
            k >>= self.WINDOW
            if d:
                x, y = self.tables[w][d]
                if acc is None:
                    acc = (x, y, self._one())
                else:
                    acc = self._jac_add(*acc, x, y, self._one())
            w += 1
        return None if acc is None else self._to_affine(acc)

    def _one(self):
        raise NotImplementedError


class _G1Fixed(FixedBaseMul):
    def __init__(self):
        super().__init__(G1_GENERATOR, g1_add, _jac_add, self._affine)

    def _one(self):
        return 1

    @staticmethod
    def _affine(acc):
        X, Y, Z = acc
        if Z == 0:
            return None
        zi = pow(Z, P - 2, P)
        z2 = zi * zi % P
        return (X * z2 % P, Y * z2 % P * zi % P)


class _G2Fixed(FixedBaseMul):
    def __init__(self):
        super().__init__(G2_GENERATOR, g2_add, _g2_jac_add, self._affine)

    def _one(self):
        return Fq2.one()

    @staticmethod
    def _affine(acc):
        X, Y, Z = acc
        if Z.is_zero():
            return None
        zi = Z.inv()
        z2 = zi * zi
        return (X * z2, Y * z2 * zi)


_g1_fixed: Optional[_G1Fixed] = None
_g2_fixed: Optional[_G2Fixed] = None


def g1_gen_mul(k: int) -> G1Point:
    """k*G1 via the shared fixed-base table (setup's hot path)."""
    global _g1_fixed
    if _g1_fixed is None:
        _g1_fixed = _G1Fixed()
    return _g1_fixed.mul(k)


def g2_gen_mul(k: int) -> G2Point:
    global _g2_fixed
    if _g2_fixed is None:
        _g2_fixed = _G2Fixed()
    return _g2_fixed.mul(k)


def g1_gen_mul_batch(scalars) -> "list[G1Point]":
    """Batch k*G1: native C++ fixed-base when available (~135us/mul),
    Python windowed tables otherwise."""
    try:
        from ..native.lib import g1_fixed_base_batch

        res = g1_fixed_base_batch(G1_GENERATOR, list(scalars))
        if res is not None:
            return res
    except Exception:
        pass
    return [g1_gen_mul(k) for k in scalars]
