"""Vectorised BN254 group arithmetic on TPU (JAX): G1 over Fq, G2 over Fq2.

TPU mirror of the EVM ecAdd/ecMul precompiles the reference leans on
(``contracts/Verifier.sol:42-100``) and of rapidsnark's Jacobian point
kernels.  Points are Jacobian triples of Montgomery limb tensors — G1:
three ``(..., 16)`` uint32 arrays, G2: three ``(..., 2, 16)`` — so every
op is elementwise over leading batch dims and `vmap`/`shard_map`-ready.

All case handling (infinity, P+P, P+(-P)) is branchless via `select`, so
one traced program serves every lane of a batch: exactly what `jit` +
SPMD sharding need (no data-dependent control flow, SURVEY.md §7).

Formulas: standard a=0 Jacobian dbl (3 sq + 4 mul) and add (4 sq + 12 mul),
shared verbatim between G1 and G2 by parameterising over the field ops
object (`JPrimeField` / `JFq2Ops` expose the same interface).

Infinity encoding: Jacobian Z == 0; affine sentinel (0, 0) (not on either
curve: 0^3 + b != 0 for b = 3 and b = 3/xi).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax

from ..utils.jaxcfg import on_tpu as _on_tpu
import jax.numpy as jnp
import numpy as np

from ..field.bn254 import P
from ..field.jfield import FQ, FQ2, NUM_LIMBS, int_to_limbs
from ..field.tower import Fq2
from .host import G1Point, G2Point

# A Jacobian point is a (X, Y, Z) tuple of limb tensors (a JAX pytree).
JacPoint = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]
# An affine point is an (X, Y) tuple; (0, 0) means infinity.
AffPoint = Tuple[jnp.ndarray, jnp.ndarray]



# Curve-op implementation selector: "auto" (default — pallas on a real
# TPU backend, xla elsewhere), "xla" (force the packed-mul formulas
# below), or "pallas" (force ops.pallas_curve where the backend allows).
# The pallas kernels collapse the ~8 kernel launches + HBM round-trips
# per point add into one VMEM-resident kernel; measured on a v5e chip
# (r4): 17.7 M G1 add_mixed/s vs 0.65 M for the XLA path (27x), MSM
# 0.150 M pts/s vs 0.009 (16.7x) — see docs/ROOFLINE.md.
from ..utils.config import load_config as _load_config

CURVE_IMPL = _load_config().curve_kernel


class JCurve:
    """Short-Weierstrass a=0 curve ops over a vectorised field."""

    def __init__(self, field):
        self.F = field

    def _pallas(self) -> bool:
        """Route through ops.pallas_curve?  Decided at trace time (static
        under jit).  TPU only: on other backends the kernels would run in
        interpret mode, which is orders of magnitude slower than the XLA
        path (the differential tests call the kernels directly with
        interpret=True instead).  Reports its arm to the execution audit
        (trace-time record: the arm is baked into the executable)."""
        from ..utils.audit import record_arm

        v = CURVE_IMPL in ("pallas", "auto") and _on_tpu()
        record_arm("curve_kernel", "pallas" if v else "xla")
        return v

    # ------------------------------------------------------------ helpers

    def infinity(self, batch_shape: Tuple[int, ...] = ()) -> JacPoint:
        z = jnp.broadcast_to(self.F.zero_limbs, batch_shape + self.F.zero_limbs.shape)
        return (z, z, z)

    def is_inf(self, p: JacPoint) -> jnp.ndarray:
        return self.F.is_zero(p[2])

    def is_inf_affine(self, a: AffPoint) -> jnp.ndarray:
        return self.F.is_zero(a[0]) & self.F.is_zero(a[1])

    def from_affine(self, a: AffPoint) -> JacPoint:
        """Affine -> Jacobian; the (0,0) sentinel maps to Z=0."""
        inf = self.is_inf_affine(a)
        one = jnp.broadcast_to(self.F.one_mont, a[0].shape)
        z = self.F.select(inf, jnp.zeros_like(one), one)
        return (a[0], a[1], z)

    def neg(self, p: JacPoint) -> JacPoint:
        return (p[0], self.F.neg(p[1]), p[2])

    def select(self, cond: jnp.ndarray, p: JacPoint, q: JacPoint) -> JacPoint:
        F = self.F
        return (F.select(cond, p[0], q[0]), F.select(cond, p[1], q[1]), F.select(cond, p[2], q[2]))

    # --------------------------------------------------------------- core
    #
    # Field muls are PACKED: independent products are stacked on a fresh
    # leading axis and issued as ONE batched mul per dependency layer.  A
    # Jacobian add is 16 field muls but only ~6 dependency layers; packing
    # cuts both the traced graph (XLA compile time scales with op count)
    # and runtime (wider elementwise kernels vectorise better on the VPU).

    def _pack(self, *xs):
        shape = jnp.broadcast_shapes(*(x.shape for x in xs))
        return jnp.stack([jnp.broadcast_to(x, shape) for x in xs])

    def double(self, p: JacPoint) -> JacPoint:
        """dbl-2009-l in 3 packed mul layers; infinity -> infinity for free
        (Z3 = 2YZ = 0)."""
        F = self.F
        if self._pallas():
            from ..ops.pallas_curve import g1_double, g2_double

            interp = not _on_tpu()
            if F.zero_limbs.ndim == 1:
                return g1_double(F, p, interp)
            return g2_double(F, p, interp)
        X1, Y1, Z1 = p
        sq = F.square(self._pack(X1, Y1))  # L1
        A, B = sq[0], sq[1]
        m2 = F.mul(self._pack(B, F.add(X1, B), Y1), self._pack(B, F.add(X1, B), Z1))  # L2
        C, XB2, YZ = m2[0], m2[1], m2[2]
        t = F.sub(F.sub(XB2, A), C)
        D = F.add(t, t)
        E = F.add(F.add(A, A), A)
        Fv = F.square(E)  # L3a
        X3 = F.sub(Fv, F.add(D, D))
        C8 = F.add(C, C)
        C8 = F.add(C8, C8)
        C8 = F.add(C8, C8)
        Y3 = F.sub(F.mul(E, F.sub(D, X3)), C8)  # L3b (depends on X3)
        Z3 = F.add(YZ, YZ)
        return (X3, Y3, Z3)

    def add(self, p: JacPoint, q: JacPoint) -> JacPoint:
        """Complete Jacobian add: handles inf / equal / negated lanes."""
        F = self.F
        if self._pallas():
            from ..ops.pallas_curve import g1_add, g2_add

            interp = not _on_tpu()
            if F.zero_limbs.ndim == 1:
                return g1_add(F, p, q, interp)
            return g2_add(F, p, q, interp)
        X1, Y1, Z1 = p
        X2, Y2, Z2 = q
        sq = F.square(self._pack(Z1, Z2))  # L1
        Z1Z1, Z2Z2 = sq[0], sq[1]
        m2 = F.mul(self._pack(X1, X2, Y1, Y2, Z1), self._pack(Z2Z2, Z1Z1, Z2, Z1, Z2))  # L2
        U1, U2, t1, t2, Z1Z2 = m2[0], m2[1], m2[2], m2[3], m2[4]
        m3 = F.mul(self._pack(t1, t2), self._pack(Z2Z2, Z1Z1))  # L3
        S1, S2 = m3[0], m3[1]
        return self._add_core(p, q, U1, U2, S1, S2, Z1Z2)

    def add_mixed(self, p: JacPoint, a: AffPoint) -> JacPoint:
        """p (Jacobian) + a (affine, Z2=1): saves 4 muls + 1 sq vs `add`.

        The workhorse of MSM bucket accumulation, where all bases are the
        affine zkey points (SURVEY.md §7 step 3)."""
        F = self.F
        if self._pallas():
            from ..ops.pallas_curve import g1_add_mixed, g2_add_mixed

            interp = not _on_tpu()
            if F.zero_limbs.ndim == 1:
                return g1_add_mixed(F, p, a, interp)
            return g2_add_mixed(F, p, a, interp)
        X1, Y1, Z1 = p
        X2, Y2 = a
        Z1Z1 = F.square(Z1)  # L1
        m2 = F.mul(self._pack(X2, Y2), self._pack(Z1Z1, F.mul(Z1, Z1Z1)))  # L2 (+Z1^3)
        U2, S2 = m2[0], m2[1]
        # _add_core's q-select handles p==inf via from_affine(a).
        return self._add_core(p, self.from_affine(a), X1, U2, Y1, S2, Z1)

    def _add_core(
        self,
        p: JacPoint,
        q: JacPoint,
        U1: jnp.ndarray,
        U2: jnp.ndarray,
        S1: jnp.ndarray,
        S2: jnp.ndarray,
        Z1Z2: jnp.ndarray,
    ) -> JacPoint:
        F = self.F
        H = F.sub(U2, U1)
        Rr = F.sub(S2, S1)
        sq = F.square(self._pack(H, Rr))  # L4
        HH, R2 = sq[0], sq[1]
        m5 = F.mul(self._pack(H, U1), self._pack(HH, HH))  # L5
        HHH, V = m5[0], m5[1]
        X3 = F.sub(F.sub(R2, HHH), F.add(V, V))
        m6 = F.mul(self._pack(Rr, S1, Z1Z2), self._pack(F.sub(V, X3), HHH, H))  # L6
        Y3 = F.sub(m6[0], m6[1])
        Z3 = m6[2]
        res: JacPoint = (X3, Y3, Z3)

        same_x = F.is_zero(H)
        same_y = F.is_zero(Rr)
        res = self.select(same_x & same_y, self.double(p), res)
        res = self.select(same_x & ~same_y, self.infinity(same_x.shape), res)
        res = self.select(self.is_inf(p), q, res)
        res = self.select(self.is_inf(q), p, res)
        return res

    # -------------------------------------------------------- scalar mul

    def scalar_mul(self, p: JacPoint, bits: jnp.ndarray) -> JacPoint:
        """Branchless MSB-first double-and-add.

        `bits`: (256, *batch) uint32 bit planes (see `scalar_bit_planes`),
        batch broadcastable against p's batch shape.  One `lax.scan` of 256
        steps — static trip count, jit-stable."""
        acc0 = self.infinity(jnp.broadcast_shapes(bits.shape[1:], p[2].shape[:-self._elem_ndim()]))

        def step(acc, bit):
            acc = self.double(acc)
            return self.select(bit.astype(bool), self.add(acc, p), acc), None

        acc, _ = jax.lax.scan(step, acc0, bits)
        return acc

    def _elem_ndim(self) -> int:
        return self.F.zero_limbs.ndim


G1J = JCurve(FQ)
G2J = JCurve(FQ2)


# ------------------------------------------------- host <-> device bridges


def scalar_bit_planes(scalars: Sequence[int]) -> jnp.ndarray:
    """Host ints -> (256, n) uint32 bit planes, MSB first (plane 0 = bit 255)."""
    limbs = np.stack([int_to_limbs(s % (1 << 256)) for s in scalars])  # (n, 16)
    planes = np.zeros((256, len(limbs)), dtype=np.uint32)
    for j in range(256):
        planes[255 - j] = (limbs[:, j // 16] >> (j % 16)) & 1
    return jnp.asarray(planes)


def g1_to_affine_arrays(points: Sequence[G1Point]) -> AffPoint:
    """Host affine G1 -> Montgomery limb arrays; None -> (0, 0) sentinel."""
    n = len(points)
    xs = np.zeros((n, NUM_LIMBS), dtype=np.uint32)
    ys = np.zeros((n, NUM_LIMBS), dtype=np.uint32)
    for i, pt in enumerate(points):
        if pt is None:
            continue
        xs[i] = FQ.to_mont_host(pt[0])
        ys[i] = FQ.to_mont_host(pt[1])
    return jnp.asarray(xs), jnp.asarray(ys)


def g2_to_affine_arrays(points: Sequence[G2Point]) -> AffPoint:
    """Host affine G2 -> (n, 2, 16) Montgomery limb arrays."""
    n = len(points)
    xs = np.zeros((n, 2, NUM_LIMBS), dtype=np.uint32)
    ys = np.zeros((n, 2, NUM_LIMBS), dtype=np.uint32)
    for i, pt in enumerate(points):
        if pt is None:
            continue
        x, y = pt
        xs[i, 0] = FQ.to_mont_host(x.c0)
        xs[i, 1] = FQ.to_mont_host(x.c1)
        ys[i, 0] = FQ.to_mont_host(y.c0)
        ys[i, 1] = FQ.to_mont_host(y.c1)
    return jnp.asarray(xs), jnp.asarray(ys)


def _fq_from_limbs(limbs: np.ndarray) -> int:
    return FQ.from_mont_host(limbs)


def g1_jac_to_host(p: JacPoint) -> List[G1Point]:
    """Device Jacobian batch -> host affine points (slow; results only)."""
    X, Y, Z = (np.asarray(c) for c in p)
    X, Y, Z = X.reshape(-1, NUM_LIMBS), Y.reshape(-1, NUM_LIMBS), Z.reshape(-1, NUM_LIMBS)
    out: List[G1Point] = []
    for i in range(X.shape[0]):
        z = _fq_from_limbs(Z[i])
        if z == 0:
            out.append(None)
            continue
        zinv = pow(z, P - 2, P)
        zi2 = zinv * zinv % P
        out.append((_fq_from_limbs(X[i]) * zi2 % P, _fq_from_limbs(Y[i]) * zi2 % P * zinv % P))
    return out


def g2_jac_to_host(p: JacPoint) -> List[G2Point]:
    X, Y, Z = (np.asarray(c) for c in p)
    X, Y, Z = (a.reshape(-1, 2, NUM_LIMBS) for a in (X, Y, Z))
    out: List[G2Point] = []
    for i in range(X.shape[0]):
        z = Fq2(_fq_from_limbs(Z[i, 0]), _fq_from_limbs(Z[i, 1]))
        if z.is_zero():
            out.append(None)
            continue
        zinv = z.inv()
        zi2 = zinv * zinv
        x = Fq2(_fq_from_limbs(X[i, 0]), _fq_from_limbs(X[i, 1])) * zi2
        y = Fq2(_fq_from_limbs(Y[i, 0]), _fq_from_limbs(Y[i, 1])) * zi2 * zinv
        out.append((x, y))
    return out
