"""Host SHA-256 with exposed internal state (midstate checkpointing).

Twin of the reference's `app/src/helpers/fast-sha256.ts` (a SHA-256 whose
`cacheState()` exports the chaining value) and `shaHash.ts:7-36`
(`partialSha`, `sha256Pad`).  The exported midstate feeds the in-circuit
`Sha256Partial` resume (gadgets/sha256.sha256_blocks init_state) so the
parallelisable body prefix is hashed outside the circuit.
"""

from __future__ import annotations

from typing import Tuple

from ..gadgets.sha256 import H0, K

MASK32 = 0xFFFFFFFF


def _rotr(x: int, r: int) -> int:
    return ((x >> r) | (x << (32 - r))) & MASK32


def compress(state: Tuple[int, ...], block: bytes) -> Tuple[int, ...]:
    assert len(block) == 64
    w = [int.from_bytes(block[4 * i : 4 * i + 4], "big") for i in range(16)]
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> 3)
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> 10)
        w.append((s1 + w[t - 7] + s0 + w[t - 16]) & MASK32)
    a, b, c, d, e, f, g, h = state
    for t in range(64):
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + S1 + ch + K[t] + w[t]) & MASK32
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        mj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (S0 + mj) & MASK32
        a, b, c, d, e, f, g, h = (t1 + t2) & MASK32, a, b, c, (d + t1) & MASK32, e, f, g
    return tuple((s + v) & MASK32 for s, v in zip(state, (a, b, c, d, e, f, g, h)))


def midstate(prefix: bytes, init: Tuple[int, ...] = tuple(H0)) -> Tuple[int, ...]:
    """Chaining value after hashing `prefix` (length must be 64-aligned) —
    `partialSha` (shaHash.ts:11)."""
    assert len(prefix) % 64 == 0
    state = tuple(init)
    for off in range(0, len(prefix), 64):
        state = compress(state, prefix[off : off + 64])
    return state


def sha256_pad(msg: bytes, max_len: int) -> Tuple[bytes, int]:
    """MD-pad to a fixed max length; returns (padded, used_bytes) where
    used = message + padding (a 64 multiple) — `sha256Pad` (shaHash.ts:17-36).
    The region [used:max_len] is zero filler the circuit never selects."""
    assert max_len % 64 == 0
    length_bits = len(msg) * 8
    padded = bytearray(msg) + b"\x80"
    while (len(padded) + 8) % 64:
        padded.append(0)
    padded += length_bits.to_bytes(8, "big")
    used = len(padded)
    if used > max_len:
        raise ValueError(f"message needs {used} bytes > max {max_len}")
    padded += b"\x00" * (max_len - used)
    return bytes(padded), used


def digest_from_state(state: Tuple[int, ...]) -> bytes:
    return b"".join(s.to_bytes(4, "big") for s in state)


def sha256_full(msg: bytes) -> bytes:
    padded, used = sha256_pad(msg, ((len(msg) + 9 + 63) // 64) * 64)
    return digest_from_state(midstate(padded[:used]))
