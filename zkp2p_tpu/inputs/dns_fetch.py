"""DKIM key fetch over DNS/DoH, with registry fallback.

The reference resolves `selector._domainkey.domain TXT` at run time —
DNS-over-HTTPS in the browser, node `dns.resolve` locally
(`app/src/helpers/dkim/tools.js:261-283`) — and keeps hardcoded values
for offline use (`tools.js:284-286`).  This is that seam made explicit:

  fetch_dkim_modulus(domain, selector, resolver=..., registry=...)

`resolver` is any callable `qname -> list of TXT strings` — the
injectable boundary (tests use a mock; production can plug a DoH
client).  The default `doh_resolver` speaks RFC 8484-adjacent JSON
(Google/Cloudflare `?name=...&type=TXT` shape) through urllib; in the
zero-egress build environment it simply raises and the registry answers,
which is exactly the reference's offline path.

TXT parsing follows RFC 6376 §3.6.1: semicolon-separated tags, `p=` the
base64 SPKI (whitespace/quote tolerant, the `tools.js` normalization),
`k=rsa` (default) the only supported key type here.
"""

from __future__ import annotations

import json
import re
import urllib.parse
import urllib.request
from typing import Callable, List, Optional

from .dkim import KeyRegistry
from .known_keys import _modulus_from_spki_b64, default_registry

Resolver = Callable[[str], List[str]]

DOH_ENDPOINT = "https://dns.google/resolve"  # ?name=<qname>&type=TXT


def doh_resolver(qname: str, endpoint: str = DOH_ENDPOINT, timeout: float = 5.0) -> List[str]:
    """TXT lookup over DNS-over-HTTPS (JSON API shape).  Raises on any
    network/parse failure — callers fall back to the registry."""
    url = f"{endpoint}?name={urllib.parse.quote(qname)}&type=TXT"
    req = urllib.request.Request(url, headers={"accept": "application/dns-json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = json.loads(resp.read().decode())
    answers = body.get("Answer") or []
    return [a.get("data", "") for a in answers if a.get("type") == 16]


def parse_dkim_txt(txt: str) -> Optional[int]:
    """One TXT record -> RSA modulus, or None if it is not a usable
    DKIM1 rsa key record.  Mirrors the tools.js normalization: strip
    whitespace and quote characters (TXT strings arrive chunked and
    quoted), then tag-parse."""
    cleaned = re.sub(r"\s+", "", txt).replace('"', "")
    tags = {}
    for part in cleaned.split(";"):
        if "=" in part:
            k, v = part.split("=", 1)
            tags[k.strip().lower()] = v.strip()
    if tags.get("v", "DKIM1") != "DKIM1":
        return None
    if tags.get("k", "rsa") != "rsa":
        return None
    p = tags.get("p")
    if not p:  # empty p= means a revoked key (RFC 6376 §3.6.1)
        return None
    try:
        return _modulus_from_spki_b64(p)
    except Exception:  # noqa: BLE001 — malformed SPKI == unusable record
        return None


def fetch_dkim_modulus(
    domain: str,
    selector: str,
    resolver: Optional[Resolver] = None,
    registry: Optional[KeyRegistry] = None,
    min_bits: int = 1024,
) -> Optional[int]:
    """The DNS-with-registry-fallback key lookup (`getPublicKey`,
    tools.js:261-283): try the resolver; on failure or no usable record,
    answer from the registry.  A resolved key shorter than `min_bits`
    is rejected (the reference's minBitLength gate)."""
    qname = f"{selector}._domainkey.{domain}"
    res = resolver if resolver is not None else doh_resolver
    try:
        for txt in res(qname):
            mod = parse_dkim_txt(txt)
            if mod is not None:
                if mod.bit_length() < min_bits:
                    continue  # too-short key: keep looking / fall back
                return mod
    except Exception:  # noqa: BLE001 — resolver failure -> offline path
        pass
    reg = registry if registry is not None else default_registry()
    return reg.get(domain, selector)
