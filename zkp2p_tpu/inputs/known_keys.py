"""Known DKIM RSA public keys (production constants).

The reference pins these same keys: the browser fetches them over
DNS/DoH at run time (`app/src/helpers/dkim/tools.js:261-283`) with the
values also hardcoded for offline use (`tools.js:284-286`), and the Ramp
contract stores the Venmo modulus limbs on-chain
(`scripts/deploy.js:23-47`).  Zero-egress environments (CI, air-gapped
provers) resolve from this registry instead of DNS.
"""

from __future__ import annotations

import base64

from .dkim import KeyRegistry


def _der_read(der: bytes, off: int):
    """One TLV at `off` -> (tag, value_start, value_len)."""
    tag = der[off]
    ln = der[off + 1]
    if ln < 0x80:
        return tag, off + 2, ln
    n = ln & 0x7F
    return tag, off + 2 + n, int.from_bytes(der[off + 2 : off + 2 + n], "big")


def _modulus_from_spki_b64(b64: str) -> int:
    """RSA modulus from a base64 SubjectPublicKeyInfo (the DNS TXT `p=`
    payload shape, RFC 6376 §3.6.1).  A proper structural DER walk —
    byte-pattern scanning can lock onto modulus bytes that happen to look
    like an INTEGER header."""
    der = base64.b64decode(b64)
    tag, off, _ = _der_read(der, 0)  # SPKI SEQUENCE
    assert tag == 0x30, "not a SEQUENCE"
    tag, alg_start, alg_len = _der_read(der, off)  # AlgorithmIdentifier
    assert tag == 0x30
    tag, bits_start, _ = _der_read(der, alg_start + alg_len)  # BIT STRING
    assert tag == 0x03
    bits_start += 1  # skip unused-bits octet
    tag, rsa_off, _ = _der_read(der, bits_start)  # RSAPublicKey SEQUENCE
    assert tag == 0x30
    tag, mod_start, mod_len = _der_read(der, rsa_off)  # modulus INTEGER
    assert tag == 0x02
    return int.from_bytes(der[mod_start : mod_start + mod_len].lstrip(b"\x00"), "big")


# venmo.com yzlavq3ml4jl4lt6dltbgmnoftxftkly — `tools.js:284`; the same
# 1024-bit modulus whose 121-bit limbs Ramp stores (`scripts/deploy.js:24-42`).
VENMO_SPKI = (
    "MIGfMA0GCSqGSIb3DQEBAQUAA4GNADCBiQKBgQCoecgrbF4KMhqGMZK02Dv2vZgGnSAo9CDpYEZCpNDRBLXkfp/0Yzp3"
    "rgngm4nuiQWbhHO457vQ37nvc88I9ANuJKa3LIodD+QtOLCjwlzH+li2A81duY4fKLHcHYO3XKw+uYXKWd+bABQqps3A"
    "QP5KxoOgQ/P1EssOnvtQYBHjWQIDAQAB"
)

# twitter.com dkim-201406 — `tools.js:285`; signs the reference fixture
# email `app/src/__fixtures__/email/zktestemail.test-eml`.
TWITTER_SPKI = (
    "MIIBIjANBgkqhkiG9w0BAQEFAAOCAQ8AMIIBCgKCAQEAwe34ubzrMzM9sT0XVkcc3UXd7W+EHCyHoqn70l2AxXox52lA"
    "ZzH/UnKwAoO+5qsuP7T9QOifIJ9ddNH9lEQ95Y/GdHBsPLGdgSJIs95mXNxscD6MSyejpenMGL9TPQAcxfqY5xPViZ+1"
    "wA1qcryjdZKRqf1f4fpMY+x3b8k7H5Qyf/Smz0sv4xFsx1r+THNIz0rzk2LO3GvE0f1ybp6P+5eAelYU4mGeZQqsKw/e"
    "B20I3jHWEyGrXuvzB67nt6ddI+N2eD5K38wg/aSytOsb5O+bUSEe7P0zx9ebRRVknCD6uuqG3gSmQmttlD5OrMWSXzrP"
    "IXe8eTBaaPd+e/jfxwIDAQAB"
)


def default_registry() -> KeyRegistry:
    reg = KeyRegistry()
    reg.add("venmo.com", "yzlavq3ml4jl4lt6dltbgmnoftxftkly", _modulus_from_spki_b64(VENMO_SPKI))
    reg.add("twitter.com", "dkim-201406", _modulus_from_spki_b64(TWITTER_SPKI))
    return reg
