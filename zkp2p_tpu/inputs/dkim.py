"""DKIM email parsing + canonicalization + signature extraction.

Rebuild of the reference's vendored mailauth subset
(`app/src/helpers/dkim/*`, ~1.8 kLoC): MIME header/body split
(`message-parser.js:13`), simple/relaxed canonicalization
(`body/relaxed.js:16`, `body/simple.js`, `header/*`), DKIM-Signature tag
parsing (`parse-dkim-headers.js`) and signed-data assembly
(`dkim-verifier.js:147-320`).

The DNS key fetch (`tools.ts:261-283`, DNS TXT / DoH) becomes an explicit
KeyRegistry — zero-egress environments supply keys directly, mirroring
the reference's own hardcoded-Venmo-key fallback comment.

Output: the exact byte string whose SHA-256 the mailserver signed (header
signed-data) plus the canonicalized body — the two inputs the circuit
hashes (`generate_input.ts:191-231`).
"""

from __future__ import annotations

import hashlib
import re
from base64 import b64decode
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class ParsedEmail:
    headers: List[Tuple[str, bytes]]  # (lowercase name, full raw header line incl name, no trailing CRLF)
    body: bytes


def parse_eml(raw: bytes) -> ParsedEmail:
    raw = raw.replace(b"\n", b"\r\n").replace(b"\r\r\n", b"\r\n")  # insert13Before10 fixup
    if b"\r\n\r\n" in raw:
        head, body = raw.split(b"\r\n\r\n", 1)
    else:
        head, body = raw, b""
    lines = head.split(b"\r\n")
    headers: List[Tuple[str, bytes]] = []
    cur: Optional[bytes] = None
    for line in lines:
        if line[:1] in (b" ", b"\t") and cur is not None:
            cur = cur + b"\r\n" + line  # folded continuation
            headers[-1] = (headers[-1][0], cur)
            continue
        if b":" in line:
            name = line.split(b":", 1)[0].strip().lower().decode()
            cur = line
            headers.append((name, cur))
    return ParsedEmail(headers=headers, body=body)


# ----------------------------------------------------- canonicalization


def canon_body_simple(body: bytes) -> bytes:
    while body.endswith(b"\r\n\r\n"):
        body = body[:-2]
    if body and not body.endswith(b"\r\n"):
        body += b"\r\n"
    return body or b"\r\n"


def canon_body_relaxed(body: bytes) -> bytes:
    lines = body.split(b"\r\n")
    out = []
    for line in lines:
        line = re.sub(rb"[ \t]+", b" ", line).rstrip()
        out.append(line)
    while out and out[-1] == b"":
        out.pop()
    return b"".join(l + b"\r\n" for l in out)


def canon_header_relaxed(raw: bytes) -> bytes:
    name, value = raw.split(b":", 1)
    value = re.sub(rb"\r\n[ \t]+", b" ", value)
    value = re.sub(rb"[ \t]+", b" ", value).strip()
    return name.strip().lower() + b":" + value


def canon_header_simple(raw: bytes) -> bytes:
    return raw


# ------------------------------------------------------- DKIM signature


@dataclass
class DkimSignature:
    domain: str
    selector: str
    algo: str
    header_canon: str
    body_canon: str
    bh: bytes  # decoded body hash
    b: int  # RSA signature as int
    signed_headers: List[str]
    raw_header: bytes  # the full dkim-signature header line


def _strip_b_tag(dkim_raw: bytes) -> bytes:
    """Empty the b= tag's value in a raw DKIM-Signature header (RFC 6376
    §3.7), locating it positionally at tag level — ';' delimits tags, and a
    tag name is the bytes before the first '=' modulo folding whitespace.
    A regex over the folded raw value can misfire on a 'b=' byte sequence
    inside another tag's value (e.g. a bh= base64 value whose final chars
    fold to '\\r\\n b='), blanking the wrong tag."""
    header, _, value = dkim_raw.partition(b":")
    segs = value.split(b";")
    for i, seg in enumerate(segs):
        name = re.sub(rb"[\s\r\n]+", b"", seg.split(b"=", 1)[0])
        if name == b"b" and b"=" in seg:
            prefix = seg[: seg.index(b"=") + 1]
            segs[i] = prefix
            break
    return header + b":" + b";".join(segs)


def parse_dkim_signature(raw: bytes) -> DkimSignature:
    value = raw.split(b":", 1)[1]
    unfolded = re.sub(rb"\r\n[ \t]+", b" ", value).decode()
    tags: Dict[str, str] = {}
    for part in unfolded.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        k, v = part.split("=", 1)
        tags[k.strip()] = v.strip()
    canon = tags.get("c", "simple/simple")
    hc, _, bc = canon.partition("/")
    bc = bc or "simple"
    return DkimSignature(
        domain=tags.get("d", ""),
        selector=tags.get("s", ""),
        algo=tags.get("a", "rsa-sha256"),
        header_canon=hc,
        body_canon=bc,
        bh=b64decode(re.sub(r"\s", "", tags.get("bh", ""))),
        b=int.from_bytes(b64decode(re.sub(r"\s", "", tags.get("b", ""))), "big") if tags.get("b") else 0,
        signed_headers=[h.strip().lower() for h in tags.get("h", "").split(":") if h.strip()],
        raw_header=raw,
    )


class KeyRegistry:
    """selector._domainkey.domain -> RSA modulus (the DNS TXT / DoH layer
    of dkim/tools.ts:261-283, made explicit for zero-egress operation)."""

    def __init__(self):
        self._keys: Dict[Tuple[str, str], int] = {}

    def add(self, domain: str, selector: str, modulus: int) -> None:
        self._keys[(domain.lower(), selector.lower())] = modulus

    def get(self, domain: str, selector: str) -> Optional[int]:
        return self._keys.get((domain.lower(), selector.lower()))


@dataclass
class DkimVerification:
    signed_data: bytes  # what the mailserver's RSA signature covers
    body_canon: bytes
    signature: int
    modulus: Optional[int]
    body_hash_ok: bool
    signature_ok: Optional[bool]  # None when no key available
    sig: DkimSignature


def extract_and_verify(raw_eml: bytes, keys: Optional[KeyRegistry] = None) -> DkimVerification:
    """Parse, canonicalize and (when a key is known) verify the DKIM
    signature; returns the circuit-facing byte strings either way."""
    email = parse_eml(raw_eml)
    dkim_raw = next((h for n, h in email.headers if n == "dkim-signature"), None)
    if dkim_raw is None:
        raise ValueError("no dkim-signature header")
    sig = parse_dkim_signature(dkim_raw)

    body = canon_body_relaxed(email.body) if sig.body_canon == "relaxed" else canon_body_simple(email.body)
    body_hash_ok = hashlib.sha256(body).digest() == sig.bh

    hc = canon_header_relaxed if sig.header_canon == "relaxed" else canon_header_simple
    # select signed headers bottom-up per name occurrence (RFC 6376 §5.4.2)
    pools: Dict[str, List[bytes]] = {}
    for name, raw in email.headers:
        pools.setdefault(name, []).append(raw)
    picked: List[bytes] = []
    for name in sig.signed_headers:
        pool = pools.get(name, [])
        if pool:
            picked.append(pool.pop())
    stripped = _strip_b_tag(dkim_raw)
    parts = [hc(h) + b"\r\n" for h in picked]
    parts.append(hc(stripped))
    signed_data = b"".join(parts)

    modulus = keys.get(sig.domain, sig.selector) if keys else None
    signature_ok: Optional[bool] = None
    if modulus is not None and sig.b:
        from ..gadgets.rsa import DIGEST_INFO

        em_len = (modulus.bit_length() + 7) // 8
        digest = hashlib.sha256(signed_data).digest()
        pad_len = em_len - 3 - 19 - 32
        em = b"\x00\x01" + b"\xff" * pad_len + b"\x00" + DIGEST_INFO.to_bytes(19, "big") + digest
        signature_ok = pow(sig.b, 65537, modulus) == int.from_bytes(em, "big")

    return DkimVerification(
        signed_data=signed_data,
        body_canon=body,
        signature=sig.b,
        modulus=modulus,
        body_hash_ok=body_hash_ok,
        signature_ok=signature_ok,
        sig=sig,
    )
