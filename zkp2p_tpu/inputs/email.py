"""Email -> circuit-input generation (the L4 crypto-helper layer).

Rebuild of `app/src/scripts/generate_input.ts:70-231` +
`app/src/helpers/{binaryFormat,shaHash,venmoHash}.ts`: takes a DKIM-signed
email, produces the witness seed for models.venmo plus the public signal
values (the `circuit/input.json` shape).

Includes a synthetic Venmo-style email signer so the whole pipeline is
testable hermetically (the reference's fixture email depends on a DNS key
fetch, `dkim/tools.ts:261-283`; zero-egress CI can't do that, so tests
sign with their own key — same trick as the hardcoded-Venmo-key comment).
"""

from __future__ import annotations

import hashlib
from base64 import b64encode
from dataclasses import dataclass
from typing import Dict, List

from ..gadgets.bigint import int_to_limbs_host
from ..gadgets.poseidon_params import poseidon_hash
from ..gadgets.rsa import DIGEST_INFO
from ..models.venmo import VenmoLayout, VenmoParams
from .sha_host import midstate, sha256_pad

SOFT_WRAP_AT = 14  # venmoHash.ts:19 inserts `=\r\n` after the 14th char


# ------------------------------------------------------------ packing


def pack_bytes_le(data: bytes, n_per: int = 7) -> List[int]:
    """Little-endian n_per-byte words (binaryFormat.ts packBytesIntoNBytes
    :177-199 / utils.circom Bytes2Packed)."""
    out = []
    for i in range(0, len(data), n_per):
        chunk = data[i : i + n_per]
        out.append(sum(b << (8 * j) for j, b in enumerate(chunk)))
    return out


def venmo_id_circuit_bytes(raw_id: str) -> bytes:
    """Insert the quoted-printable soft wrap and zero-pad to 28 — must equal
    the bytes the circuit reveals (venmoHash.ts initializeRawVenmoId)."""
    bs = bytearray(raw_id.encode())
    bs[SOFT_WRAP_AT:SOFT_WRAP_AT] = b"=\r\n"
    bs.extend(b"\x00" * (28 - len(bs)))
    return bytes(bs[:28])


def venmo_id_hash(raw_id: str) -> int:
    """generateVenmoIdHash (venmoHash.ts:3-44): pack + Poseidon."""
    return poseidon_hash(pack_bytes_le(venmo_id_circuit_bytes(raw_id)))


# ------------------------------------------------------- synthetic signer


@dataclass
class TestRsaKey:
    n: int
    d: int
    e: int = 65537

    def sign(self, message: bytes) -> int:
        digest = hashlib.sha256(message).digest()
        em = b"\x00\x01" + b"\xff" * 202 + b"\x00" + DIGEST_INFO.to_bytes(19, "big") + digest
        return pow(int.from_bytes(em, "big"), self.d, self.n)


def make_test_key(seed: int = 1) -> TestRsaKey:
    """Deterministic 2048-bit RSA key (Fermat-filtered pseudoprimes; fixed
    seed -> reproducible fixtures)."""
    import random

    rng = random.Random(seed)

    def rand_prime(bits):
        while True:
            c = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
            if all(pow(a, c - 1, c) == 1 for a in (2, 3, 5, 7)):
                return c

    p, q = rand_prime(1024), rand_prime(1024)
    n = p * q
    return TestRsaKey(n=n, d=pow(65537, -1, (p - 1) * (q - 1)))


@dataclass
class SyntheticEmail:
    """A circuit-facing email: canonicalized signed header + canonical
    body + RSA signature (synthetic OR parsed from a real .eml)."""

    header: bytes  # canonicalized, incl. dkim-signature header with bh=
    body: bytes
    signature: int
    raw_id: str
    amount: str
    modulus: int | None = None  # DKIM RSA modulus when resolved from a registry


def make_venmo_email(
    key: TestRsaKey,
    raw_id: str = "1234567891234567891",
    amount: str = "30",
    body_filler: int = 0,
    to_addr: str = "onramper@example.com",
) -> SyntheticEmail:
    body = (
        b"<html>receipt " + b"x" * body_filler + b"\r\n"
        b"<!-- recipient name -->\r\n"
        b'href=3D"https://venmo.com/code?user_id=3D'
        + raw_id[:SOFT_WRAP_AT].encode()
        + b"=\r\n"
        + raw_id[SOFT_WRAP_AT:].encode()
        + b'"\r\n</html>\r\n'
    )
    bh = b64encode(hashlib.sha256(body).digest())
    header_wo_sig = (
        b"to:" + to_addr.encode() + b"\r\n"
        b"from:venmo@venmo.com\r\n"
        b"subject:You paid Alice $" + amount.encode() + b".00\r\n"
    )
    dkim = b"dkim-signature:v=1; a=rsa-sha256; d=venmo.com; s=yzlavq3ml4jl4lt6dltbgmnoftxftkly; bh=" + bh + b"; b="
    header = header_wo_sig + dkim + b"\r\n"
    sig = key.sign(header)
    return SyntheticEmail(header=header, body=body, signature=sig, raw_id=raw_id, amount=amount)


# ------------------------------------------------- EmailVerify inputs


def make_twitter_email(key: TestRsaKey, handle: str = "zk_pranker", filler: int = 0) -> SyntheticEmail:
    """Synthetic twitter password-reset email (the TwitterResetRegex
    family, twitter_reset_regex.circom:5)."""
    body = (
        b"<html>" + b"y" * filler + b"\r\n"
        b"This email was meant for @" + handle.encode() + b" only.\r\n</html>\r\n"
    )
    from base64 import b64encode as _b64e

    bh = _b64e(hashlib.sha256(body).digest())
    header = (
        b"to:user@example.com\r\n"
        b"from:info@twitter.com\r\n"
        b"subject:Password reset request\r\n"
        b"dkim-signature:v=1; a=rsa-sha256; d=twitter.com; s=dkim; bh=" + bh + b"; b="
        b"\r\n"
    )
    return SyntheticEmail(header=header, body=body, signature=key.sign(header), raw_id=handle, amount="0")


def generate_email_verify_inputs(email: SyntheticEmail, modulus: int, params, layout):
    """Witness seed + public signals for models.email_verify."""
    header_padded, header_used = sha256_pad(email.header, params.max_header_bytes)
    body_padded_full, body_used = sha256_pad(email.body, ((len(email.body) + 9 + 63) // 64) * 64)
    marker = b"This email was meant for @"
    presel = email.body.find(marker)
    cut = (presel // 64) * 64 if presel >= 0 else 0
    prefix, suffix = body_padded_full[:cut], body_padded_full[cut:body_used]
    mid = midstate(prefix)
    body_suffix_padded = suffix + b"\x00" * (params.max_body_bytes - len(suffix))

    reveal_idx = body_suffix_padded.find(marker) + len(marker)
    handle = email.raw_id.encode()
    reveal_bytes_ = handle + b"\x00" * (params.reveal_len - len(handle))
    reveal_words = pack_bytes_le(reveal_bytes_, 7)

    mod_limbs = int_to_limbs_host(modulus, params.n, params.k)
    sig_limbs = int_to_limbs_host(email.signature, params.n, params.k)
    public_signals = mod_limbs + (reveal_words if params.body_regex else [])

    seed: Dict[int, int] = {}
    for w, b in zip(layout.header, header_padded):
        seed[w] = b
    seed[layout.header_blocks] = header_used // 64
    for w, v in zip(layout.signature, sig_limbs):
        seed[w] = v
    for w, b in zip(layout.body, body_suffix_padded):
        seed[w] = b
    seed[layout.body_blocks] = len(suffix) // 64
    for i, word in enumerate(mid):
        for b in range(32):
            seed[layout.midstate_bits[32 * i + b]] = (word >> b) & 1
    seed[layout.body_hash_idx] = email.header.find(b"bh=") + 3
    if params.body_regex:
        seed[layout.reveal_idx] = reveal_idx
    return VenmoInputs(public_signals=public_signals, seed=seed)


# ------------------------------------------------------------ real emails


def _verified_eml(raw_eml: bytes, keys, allow_unverified: bool = False):
    """Shared .eml preamble: registry default, canonicalize, check body
    hash + the RSA signature.  An unknown signing key is an ERROR by
    default — silently returning unverified email objects from the
    documented parse entry points would let a forged email flow into
    input generation with only a None modulus as the tell.  Pass
    allow_unverified=True for body-hash-only parsing (tests, tooling)."""
    from .dkim import extract_and_verify

    if keys is None:
        from .known_keys import default_registry

        keys = default_registry()
    v = extract_and_verify(raw_eml, keys)
    if not v.body_hash_ok:
        raise ValueError("DKIM body hash mismatch")
    if v.signature_ok is False:
        raise ValueError("DKIM signature invalid")
    if v.signature_ok is None and not allow_unverified:
        raise ValueError(
            f"unknown DKIM key {v.sig.domain}/{v.sig.selector}; add it to "
            "inputs.known_keys or pass allow_unverified=True"
        )
    return v


def email_from_eml(raw_eml: bytes, keys=None, allow_unverified: bool = False) -> SyntheticEmail:
    """Real .eml -> the circuit-facing email object: DKIM-canonicalized
    signed header data + canonical body + signature, with the Venmo id and
    amount located in the content (generate_input.ts:191-231 semantics).
    DKIM keys resolve from known_keys.default_registry when none given."""
    import re as _re

    v = _verified_eml(raw_eml, keys, allow_unverified)
    m = _re.search(rb"user_id=3D([0-9=\r\n]+)", v.body_canon)
    raw_id = m.group(1).replace(b"=\r\n", b"").decode() if m else ""
    # the subject may not be in the signed set (h=); fall back to the raw
    # header block for field location
    am = _re.search(rb"\$([0-9]+)\.", v.signed_data) or _re.search(rb"\$([0-9]+)\.", raw_eml)
    amount = am.group(1).decode() if am else "0"
    return SyntheticEmail(
        header=v.signed_data,
        body=v.body_canon,
        signature=v.signature,
        raw_id=raw_id,
        amount=amount,
        modulus=v.modulus,
    )


def email_verify_from_eml(raw_eml: bytes, keys=None, allow_unverified: bool = False):
    """Real .eml -> (email object, modulus) for the EmailVerify family:
    DKIM verify against the key registry (known_keys.default_registry
    when none given), extract the @handle the TwitterResetRegex reveals
    (`twitter_reset_regex.circom:5`).  Validated against the reference
    fixture `app/src/__fixtures__/email/zktestemail.test-eml`."""
    import re as _re

    v = _verified_eml(raw_eml, keys, allow_unverified)
    m = _re.search(rb"meant for @([A-Za-z0-9_]+)", v.body_canon)
    handle = m.group(1).decode() if m else ""
    email = SyntheticEmail(
        header=v.signed_data,
        body=v.body_canon,
        signature=v.signature,
        raw_id=handle,
        amount="0",
        modulus=v.modulus,
    )
    return email, v.modulus


# --------------------------------------------------------- input generation


@dataclass
class VenmoInputs:
    public_signals: List[int]
    seed: Dict[int, int]


def _bits_le_byte(b: int) -> List[int]:
    return [(b >> i) & 1 for i in range(8)]


def generate_inputs(
    email: SyntheticEmail,
    modulus: int,
    order_id: int,
    claim_id: int,
    params: VenmoParams,
    layout: VenmoLayout,
) -> VenmoInputs:
    """getCircuitInputs (generate_input.ts:70-189) for our layout: pad the
    header, cut the body at the preselector's 64-byte boundary, compute the
    SHA midstate checkpoint, locate the three indices, pack the outputs."""
    header_padded, header_used = sha256_pad(email.header, params.max_header_bytes)
    n_header_blocks = header_used // 64

    # Body cut: largest 64-boundary at or before the preselector
    # (generate_input.ts:110-124, STRING_PRESELECTOR constants.ts:22).
    presel = email.body.find(b"<!-- recipient name -->")
    # No preselector -> no midstate cut, whole body hashed in-circuit
    # (the preselector is a Venmo-email artifact, constants.ts:22).
    cut = (presel // 64) * 64 if presel >= 0 else 0
    body_padded_full, body_used = sha256_pad(email.body, ((len(email.body) + 9 + 63) // 64) * 64)
    prefix, suffix = body_padded_full[:cut], body_padded_full[cut:body_used]
    mid = midstate(prefix)
    body_suffix_padded = suffix + b"\x00" * (params.max_body_bytes - len(suffix))
    assert len(suffix) <= params.max_body_bytes
    n_body_blocks = len(suffix) // 64

    # Indices.
    bh_pos = email.header.find(b"bh=") + 3
    body_hash_idx = bh_pos
    amount_idx = email.header.find(b"$") + 1
    id_marker = b"user_id=3D"
    id_pos = body_suffix_padded.find(id_marker) + len(id_marker)
    id_idx = id_pos

    # Public outputs.
    hashed_id = venmo_id_hash(email.raw_id)
    amt_revealed = (email.amount + ".").encode()
    amt_bytes = amt_revealed + b"\x00" * (params.amount_len - len(amt_revealed))
    amount_words = pack_bytes_le(amt_bytes, 7)
    sig_limbs = int_to_limbs_host(email.signature, params.n, params.k)
    mod_limbs = int_to_limbs_host(modulus, params.n, params.k)
    nullifier = sig_limbs[:3]
    public_signals = [hashed_id] + amount_words + nullifier + mod_limbs + [order_id, claim_id]

    # Witness seed.
    seed: Dict[int, int] = {}
    for w, b in zip(layout.header, header_padded):
        seed[w] = b
    seed[layout.header_blocks] = n_header_blocks
    for w, v in zip(layout.signature, sig_limbs):
        seed[w] = v
    for w, b in zip(layout.body, body_suffix_padded):
        seed[w] = b
    seed[layout.body_blocks] = n_body_blocks
    for i, word in enumerate(mid):
        for b in range(32):
            seed[layout.midstate_bits[32 * i + b]] = (word >> b) & 1
    seed[layout.body_hash_idx] = body_hash_idx
    seed[layout.amount_idx] = amount_idx
    seed[layout.id_idx] = id_idx
    return VenmoInputs(public_signals=public_signals, seed=seed)
