from .pipeline.cli import main

main()
