"""Core R1CS gadgets: bits, comparators, selectors, packing.

Our equivalents of the circom stdlib the reference circuits lean on —
circomlib `bitify`/`comparators`/`gates`, `zk-email-verify-circuits/
utils.circom` (`QuinSelector:20-47`, `CalculateTotal:49`, `Bytes2Packed:
120-172`) and `regex_helpers.circom` (`MultiOR:34-47`).  Each gadget
emits constraints AND registers witness hooks, so `cs.witness` stays a
complete host oracle for the vectorised JAX witness tracers.

Convention: functions take the ConstraintSystem first, wires as ints /
lists of ints, and return output wire(s).
"""

from __future__ import annotations

from typing import List, Sequence

from ..field.bn254 import R
from ..snark.r1cs import LC, ConstraintSystem


def const_mul(wire: int, k: int) -> LC:
    return LC.of(wire, k % R)


def lc_sum(wires: Sequence[int], coeffs: Sequence[int] | None = None) -> LC:
    acc = LC()
    for i, w in enumerate(wires):
        acc = acc + LC.of(w, 1 if coeffs is None else coeffs[i] % R)
    return acc


# ------------------------------------------------------------------- bits


def num2bits(cs: ConstraintSystem, x: int, n: int, tag: str = "num2bits", hook: bool = True) -> List[int]:
    """x -> n little-endian bit wires; enforces booleanity + recomposition.
    (circomlib Num2Bits; the decomposition must be unique, so n must be
    small enough that 2^n - 1 < R.)  hook=False: the caller witnesses the
    bits inside its own BlockHook (constraints are emitted regardless)."""
    assert n < 254, "ambiguous decomposition"
    bits = cs.new_wires(n, f"{tag}.b")
    for b in bits:
        cs.enforce_bool(b, f"{tag}/bool")
    cs.enforce_eq(lc_sum(bits, [1 << i for i in range(n)]), LC.of(x), f"{tag}/recompose")
    cs.set_width(x, n)  # recomposition from n bool bits bounds x < 2^n
    if not hook:
        return bits
    import numpy as np

    if n <= 62:  # int64-safe: one vectorized shift for all n bits
        cs.compute_block(bits, lambda m, n=n: (m[0] >> np.arange(n)[:, None]) & 1, [x])
    else:
        # Wide decompositions (bigint limbs): bytes + unpackbits — one
        # to_bytes per element then a C-speed bit explode (the object-int
        # shift matrix was ~0.1 ms per call, the top residual cost of the
        # batch witness tier).
        nb = (n + 7) // 8

        def vfn(m, n=n, nb=nb):
            buf = b"".join(int(v).to_bytes(nb, "little") for v in m[0])
            by = np.frombuffer(buf, dtype=np.uint8).reshape(m.shape[1], nb)
            # object result: the consumers (bigint limb hooks) live on the
            # object matrix — an int64 result would migrate back per hook
            return np.unpackbits(by, axis=1, bitorder="little")[:, :n].T.astype(object)

        cs.compute_block(bits, vfn, [x], int64=False)
    return bits


def bits2num(cs: ConstraintSystem, bits: Sequence[int], tag: str = "bits2num") -> int:
    """Little-endian bit wires -> one wire (no booleanity re-check)."""
    out = cs.new_wire(f"{tag}.out")
    cs.enforce_eq(lc_sum(bits, [1 << i for i in range(len(bits))]), LC.of(out), tag)
    if all(cs.wire_width.get(b, 254) == 1 for b in bits):
        cs.set_width(out, len(bits))
    import numpy as np

    if len(bits) <= 62:
        w = np.asarray([1 << i for i in range(len(bits))], dtype=np.int64)
        cs.compute_block([out], lambda m, w=w: (w @ m)[None, :], list(bits))
    else:
        w = np.asarray([1 << i for i in range(len(bits))], dtype=object)[:, None]
        cs.compute_block(
            [out], lambda m, w=w: ((w * m).sum(axis=0) % R)[None, :], list(bits), int64=False
        )
    return out


def range_check(cs: ConstraintSystem, x: int, n: int, tag: str = "range") -> None:
    """x < 2^n via throwaway bit decomposition."""
    num2bits(cs, x, n, tag)


# ------------------------------------------------------------- comparators


def is_zero(cs: ConstraintSystem, x: int, tag: str = "iszero") -> int:
    """out = 1 iff x == 0 (circomlib IsZero: out = -x*inv + 1, x*out = 0)."""
    inv = cs.new_wire(f"{tag}.inv")
    out = cs.new_wire(f"{tag}.out")
    cs.enforce(LC.of(x), LC.of(inv), LC.const(1) - LC.of(out), f"{tag}/inv")
    cs.enforce(LC.of(x), LC.of(out), LC(), f"{tag}/zero")
    # out is bool for EVERY satisfying witness by case analysis (x=0
    # forces out=1 via the inv row; x!=0 forces out=0 via the zero row)
    cs.set_width(out, 1)
    cs.waive(
        "determinism", f"{tag}.inv",
        "IsZero inverse: unconstrained exactly when x == 0 (then "
        "x*inv = 0 = 1-out holds for every inv); out is still forced "
        "by the case pair, and inv occurs in no other constraint, so "
        "its freedom reaches no other wire",
    )
    cs.compute(inv, lambda v: pow(v, R - 2, R) if v else 0, [x])
    cs.compute(out, lambda v: 0 if v else 1, [x])
    return out


def is_equal(cs: ConstraintSystem, x: int, y: int, tag: str = "iseq") -> int:
    diff = cs.new_wire(f"{tag}.diff")
    cs.enforce_eq(LC.of(x) - LC.of(y), LC.of(diff), f"{tag}/diff")
    cs.compute(diff, lambda a, b: (a - b) % R, [x, y])
    return is_zero(cs, diff, tag)


def is_equal_const(cs: ConstraintSystem, x: int, k: int, tag: str = "iseqc") -> int:
    """x == constant k, without a diff wire."""
    inv = cs.new_wire(f"{tag}.inv")
    out = cs.new_wire(f"{tag}.out")
    cs.enforce(LC.of(x) - k, LC.of(inv), LC.const(1) - LC.of(out), f"{tag}/inv")
    cs.enforce(LC.of(x) - k, LC.of(out), LC(), f"{tag}/zero")
    cs.set_width(out, 1)  # bool by the IsZero case pair
    cs.waive(
        "determinism", f"{tag}.inv",
        "IsZero inverse (x==k case): free only when the difference is "
        "zero, where out is already forced; occurs in no other constraint",
    )
    cs.compute(inv, lambda v: pow((v - k) % R, R - 2, R) if (v - k) % R else 0, [x])
    cs.compute(out, lambda v: 1 if v == k % R else 0, [x])
    return out


def less_than(cs: ConstraintSystem, n: int, a: int, b: int, tag: str = "lt") -> int:
    """a < b for a, b < 2^n (circomlib LessThan: top bit of a - b + 2^n)."""
    assert n < 252
    # soundness REQUIRES a, b < 2^n: an unbounded operand wraps the
    # shifted difference and flips the verdict — the classic circom
    # comparator forgery.  The static auditor checks the demand.
    cs.require_width(a, n, f"{tag}/less_than.a")
    cs.require_width(b, n, f"{tag}/less_than.b")
    shifted = cs.new_wire(f"{tag}.shift")
    cs.enforce_eq(LC.of(a) - LC.of(b) + (1 << n), LC.of(shifted), f"{tag}/shift")
    cs.compute(shifted, lambda x, y: (x - y + (1 << n)) % R, [a, b])
    bits = num2bits(cs, shifted, n + 1, f"{tag}.bits")
    out = cs.new_wire(f"{tag}.out")
    cs.enforce_eq(LC.const(1) - LC.of(bits[n]), LC.of(out), f"{tag}/out")
    cs.set_width(out, 1)  # 1 - (bool bit)
    cs.compute(out, lambda top: 1 - top, [bits[n]])
    return out


# ---------------------------------------------------------------- boolean


def and_gate(cs: ConstraintSystem, a: int, b: int, tag: str = "and") -> int:
    cs.require_width(a, 1, f"{tag}/and.a")  # product == AND only for bools
    cs.require_width(b, 1, f"{tag}/and.b")
    out = cs.new_wire(f"{tag}.out")
    cs.enforce(LC.of(a), LC.of(b), LC.of(out), tag)
    cs.set_width(out, 1)
    cs.compute(out, lambda x, y: x * y % R, [a, b])
    return out


def multi_or(cs: ConstraintSystem, bits: Sequence[int], tag: str = "or") -> int:
    """OR of boolean wires as NOT(sum == 0) (regex_helpers MultiOR:34-47)."""
    for i, w in enumerate(bits):
        cs.require_width(w, 1, f"{tag}/or.in{i}")  # field sum of bools
    total = cs.new_wire(f"{tag}.sum")
    cs.enforce_eq(lc_sum(bits), LC.of(total), f"{tag}/sum")
    cs.set_width(total, max(1, len(list(bits)).bit_length()))
    cs.compute(total, lambda *bs: sum(bs) % R, list(bits))
    z = is_zero(cs, total, f"{tag}.z")
    out = cs.new_wire(f"{tag}.out")
    cs.enforce_eq(LC.const(1) - LC.of(z), LC.of(out), f"{tag}/not")
    cs.set_width(out, 1)
    cs.compute(out, lambda v: 1 - v, [z])
    return out


def mux2(cs: ConstraintSystem, sel: int, a: int, b: int, tag: str = "mux") -> int:
    """sel ? b : a  (sel boolean)."""
    cs.require_width(sel, 1, f"{tag}/mux.sel")  # sel=2 would leak a-2b+2out
    out = cs.new_wire(f"{tag}.out")
    cs.enforce(LC.of(sel), LC.of(b) - LC.of(a), LC.of(out) - LC.of(a), tag)
    # branch-free (x + s*(y-x)): columnar-safe for the batch witness tier
    cs.compute(out, lambda s, x, y: x + s * (y - x), [sel, a, b])
    return out


# ---------------------------------------------------------------- selectors


def one_hot(cs: ConstraintSystem, idx: int, n: int, tag: str = "onehot") -> List[int]:
    """Indicator wires ind[i] = (idx == i) with Σ ind = 1 and Σ i·ind = idx.

    The two closing sums make the decomposition sound without per-lane
    IsEqual inverses being trusted blindly.  All lane inverses come from
    ONE BlockHook via Montgomery batch inversion — one exponentiation per
    call instead of n per witness (the per-lane pow hooks were the
    dominant fallback cost of the batch witness tier)."""
    import numpy as np

    invs: List[int] = []
    inds: List[int] = []
    for i in range(n):
        inv = cs.new_wire(f"{tag}.{i}.inv")
        out = cs.new_wire(f"{tag}.{i}.out")
        cs.enforce(LC.of(idx) - i, LC.of(inv), LC.const(1) - LC.of(out), f"{tag}.{i}/inv")
        cs.enforce(LC.of(idx) - i, LC.of(out), LC(), f"{tag}.{i}/zero")
        invs.append(inv)
        inds.append(out)
        # ind*(idx-i)=0 with sum(ind)=1 and sum(i*ind)=idx makes each
        # lane 0/1 for satisfying witnesses (invs stay full-width)
        cs.set_width(out, 1)
    cs.waive(
        "determinism", f"{tag}.*.inv",
        "one-hot lane inverse: unconstrained exactly on the selected "
        "lane (idx == i), where the lane output is forced by the case "
        "pair and the two closing sums; each inv occurs in no other "
        "constraint, so its freedom reaches no other wire",
    )
    cs.enforce_eq(lc_sum(inds), LC.const(1), f"{tag}/onehot")
    cs.enforce_eq(lc_sum(inds, list(range(n))), LC.of(idx), f"{tag}/index")
    cs.set_width(idx, max(1, (n - 1).bit_length()))

    def vfn(m, n=n):
        v = m[0]  # (K,) object
        diffs = (v[None, :] - np.arange(n, dtype=object)[:, None]) % R  # (n, K)
        flat = diffs.reshape(-1)
        nz = np.flatnonzero(flat)
        xs = [int(flat[j]) for j in nz]
        # Montgomery trick: len(xs) inverses for 3 muls each + one pow.
        prefix = [1] * (len(xs) + 1)
        for j, x in enumerate(xs):
            prefix[j + 1] = prefix[j] * x % R
        inv_run = pow(prefix[-1], R - 2, R)
        inv_flat = np.zeros_like(flat)
        for j in range(len(xs) - 1, -1, -1):
            inv_flat[nz[j]] = prefix[j] * inv_run % R
            inv_run = inv_run * xs[j] % R
        invs_m = inv_flat.reshape(n, -1)
        outs_m = np.asarray(flat == 0, dtype=object).reshape(n, -1) * 1
        # creation order: inv, out, inv, out, ...
        return np.stack([invs_m, outs_m], axis=1).reshape(2 * n, -1)

    wires = [w for pair in zip(invs, inds) for w in pair]
    cs.compute_block(wires, vfn, [idx], int64=False)
    return inds


def quin_selector(cs: ConstraintSystem, idx: int, options: Sequence[int], tag: str = "quin") -> int:
    """out = options[idx] (utils.circom QuinSelector:20-47): one-hot dot.

    The select products are emitted directly rather than through
    and_gate: options are arbitrary field values, and and_gate's bool
    demand on both operands (correct for AND) was the first bool-width
    finding of the circuit auditor — a select is a mul, not an AND."""
    inds = one_hot(cs, idx, len(options), tag)
    out = cs.new_wire(f"{tag}.out")
    prods = []
    for i, (ind, opt) in enumerate(zip(inds, options)):
        p = cs.new_wire(f"{tag}.p{i}.out")
        cs.enforce(LC.of(ind), LC.of(opt), LC.of(p), f"{tag}.p{i}")
        cs.set_width(p, cs.wire_width.get(opt, 254))  # bool lane x option
        cs.compute(p, lambda s, v: s * v % R, [ind, opt])
        prods.append(p)
    cs.enforce_eq(lc_sum(prods), LC.of(out), f"{tag}/sum")
    cs.set_width(out, max((cs.wire_width.get(p, 254) for p in prods), default=254))
    cs.compute(out, lambda *ps: sum(ps) % R, prods)
    return out


# ----------------------------------------------------------------- packing


def pack_bytes(cs: ConstraintSystem, byte_wires: Sequence[int], n_per: int = 7, tag: str = "pack") -> List[int]:
    """Pack byte wires into little-endian n_per-byte field words
    (utils.circom Bytes2Packed:120-172; 7 bytes/signal keeps values < 2^56).
    Bytes must already be range-checked to 8 bits by the producer (the
    static auditor enforces the demand: an unbounded byte forges the
    packed word)."""
    for i, w in enumerate(byte_wires):
        cs.require_width(w, 8, f"{tag}/pack.byte{i}")
    out = []
    for chunk_i in range(0, len(byte_wires), n_per):
        chunk = byte_wires[chunk_i : chunk_i + n_per]
        w = cs.new_wire(f"{tag}.word{chunk_i // n_per}")
        cs.enforce_eq(lc_sum(chunk, [1 << (8 * j) for j in range(len(chunk))]), LC.of(w), f"{tag}/word")
        cs.compute(w, lambda *bs: sum(b << (8 * j) for j, b in enumerate(bs)) % R, list(chunk))
        out.append(w)
    return out


def assert_bytes(cs: ConstraintSystem, wires: Sequence[int], tag: str = "byte") -> List[List[int]]:
    """Range-check wires to 8 bits; returns the bit decompositions."""
    return [num2bits(cs, w, 8, f"{tag}.{i}") for i, w in enumerate(wires)]
