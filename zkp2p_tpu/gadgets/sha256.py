"""SHA-256 as R1CS gadgets: compression, variable-length, midstate resume.

Our rebuild of the reference's SHA stack (`zk-email-verify-circuits/
sha.circom:7,30`, `sha256general.circom:9`, `sha256partial.circom:9`,
circomlib `sha256compression`): byte wires in, 256 output bit wires out,
with the two tricks the reference's scaling depends on (SURVEY.md §5
long-context):

  - variable length via output selection at block index `len/64`
    (`sha256general.circom:110-118` QuinSelector semantics), keeping the
    actual message length a private input;
  - midstate resume (`Sha256Partial`): the compression chain can start
    from 256 caller-provided state bits, so the parallelisable prefix of
    the body hash lives OUTSIDE the circuit (`generate_input.ts:110-124`).

Bit convention: every 32-bit word is a little-endian list of 32 boolean
wires (index 0 = LSB), so modular addition is one LC sum + one
decomposition; rotations and shifts are pure rewiring (zero constraints).
Costs per block ≈ 30k constraints, matching the reference's annotated
506,670 for 16 header blocks (`circuit/circuit.circom:62`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..field.bn254 import R
from ..snark.r1cs import LC, ConstraintSystem
from .core import lc_sum, num2bits, one_hot

# FIPS 180-4 constants.
K = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
    0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3, 0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
    0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
    0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13, 0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
    0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
    0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208, 0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
]
H0 = [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A, 0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19]

# A "word" is 32 bit entries; an entry is a wire int or None (constant 0,
# produced by logical right shifts).
Word = List[Optional[int]]


def _rotr(w: Word, r: int) -> Word:
    return [w[(i + r) % 32] for i in range(32)]


def _shr(w: Word, r: int) -> Word:
    return [w[i + r] if i + r < 32 else None for i in range(32)]


def _xor2_bit(cs: ConstraintSystem, x: int, y: int, tag: str) -> int:
    out = cs.new_wire(tag)
    # out = x + y - 2xy  <=>  (2x) * y = x + y - out
    cs.enforce(LC.of(x, 2), LC.of(y), LC.of(x) + LC.of(y) - LC.of(out), tag)
    cs.compute(out, lambda a, b: a ^ b, [x, y])
    cs.set_width(out, 1)  # xor of bool wires is bool
    return out


def _xor_bits(cs: ConstraintSystem, bits: Sequence[Optional[int]], tag: str) -> Optional[int]:
    live = [b for b in bits if b is not None]
    if not live:
        return None
    acc = live[0]
    for j, b in enumerate(live[1:]):
        acc = _xor2_bit(cs, acc, b, f"{tag}.x{j}")
    return acc


def _xor_words(cs: ConstraintSystem, words: Sequence[Word], tag: str) -> Word:
    """Bitwise XOR of up to 3 words: per position a chain of 2-input xor
    constraints; ALL chain wires witnessed by ONE BlockHook (a padded
    bitwise_xor.accumulate over (positions, chain) — the per-bit hook
    tier was ~half the SHA witness cost, r1cs.witness_batch)."""
    import numpy as np

    out: Word = []
    ins: List[int] = []
    idx_rows: List[List[int]] = []  # per multi-live position: indices into ins (padded later)
    chain_wires: List[int] = []
    sel_rows: List[int] = []
    sel_cols: List[int] = []
    for i in range(32):
        live = [w[i] for w in words if w[i] is not None]
        if not live:
            out.append(None)
            continue
        if len(live) == 1:
            out.append(live[0])
            continue
        row = len(idx_rows)
        base = len(ins)
        ins.extend(live)
        idx_rows.append(list(range(base, base + len(live))))
        acc = live[0]
        for j, b in enumerate(live[1:]):
            o = cs.new_wire(f"{tag}.{i}.x{j}")
            cs.enforce(LC.of(acc, 2), LC.of(b), LC.of(acc) + LC.of(b) - LC.of(o), f"{tag}.{i}")
            cs.set_width(o, 1)  # xor chain over bool wires
            chain_wires.append(o)
            sel_rows.append(row)
            sel_cols.append(j + 1)
            acc = o
        out.append(acc)
    if chain_wires:
        max_l = max(len(r) for r in idx_rows)
        pad = len(ins)  # index of the zero row appended by the vfn
        idx = np.asarray([r + [pad] * (max_l - len(r)) for r in idx_rows])
        rows = np.asarray(sel_rows)
        cols = np.asarray(sel_cols)

        def vfn(m, idx=idx, rows=rows, cols=cols):
            ext = np.vstack([m, np.zeros((1, m.shape[1]), dtype=m.dtype)])
            acc = np.bitwise_xor.accumulate(ext[idx], axis=1)
            return acc[rows, cols]

        cs.compute_block(chain_wires, vfn, ins)
    return out


def _add_mod32(cs: ConstraintSystem, words: Sequence[Word], const_extra: int, n_terms: int, tag: str) -> Word:
    """word-wise sum of `words` (+ a constant) mod 2^32: one LC-sum wire,
    one 32+log2(n_terms)-bit decomposition, low 32 bits returned."""
    extra = max(1, (n_terms - 1).bit_length())
    terms: dict = {}
    ins: List[int] = []
    weights: List[int] = []
    for w in words:
        for i, b in enumerate(w):
            if b is None:
                continue
            terms[b] = (terms.get(b, 0) + (1 << i)) % R
            ins.append(b)
            weights.append(1 << i)
    total = cs.new_wire(f"{tag}.sum")
    cs.enforce_eq(LC(terms) + const_extra, LC.of(total), f"{tag}/sum")
    import numpy as np

    bits = num2bits(cs, total, 32 + extra, f"{tag}.bits", hook=False)
    w_arr = np.asarray(weights, dtype=np.int64)  # sum < n_terms * 2^32: int64-safe
    nb = 32 + extra

    def vfn(m, w=w_arr, ce=const_extra, nb=nb):
        tot = (w @ m + ce)[None, :]
        return np.concatenate([tot, (tot >> np.arange(nb)[:, None]) & 1], axis=0)

    cs.compute_block([total] + bits, vfn, ins)
    return bits[:32]


def _ch(cs: ConstraintSystem, e: Word, f: Word, g: Word, tag: str) -> Word:
    """ch = g + e*(f - g), bitwise (1 constraint/bit); one BlockHook for
    all 32 bits."""
    out: Word = []
    for i in range(32):
        o = cs.new_wire(f"{tag}.{i}")
        cs.enforce(LC.of(e[i]), LC.of(f[i]) - LC.of(g[i]), LC.of(o) - LC.of(g[i]), f"{tag}/ch")
        cs.set_width(o, 1)  # mux of bool wires is bool
        out.append(o)

    def vfn(m):
        ev, fv, gv = m[0:32], m[32:64], m[64:96]
        return gv + ev * (fv - gv)

    cs.compute_block(out, vfn, list(e) + list(f) + list(g))
    return out


def _maj(cs: ConstraintSystem, a: Word, b: Word, c: Word, tag: str) -> Word:
    """maj = t + c*(a + b - 2t), t = a*b (2 constraints/bit); one
    BlockHook for all 64 wires."""
    import numpy as np

    ts: Word = []
    out: Word = []
    for i in range(32):
        t = cs.new_wire(f"{tag}.t{i}")
        cs.enforce(LC.of(a[i]), LC.of(b[i]), LC.of(t), f"{tag}/t")
        o = cs.new_wire(f"{tag}.{i}")
        cs.enforce(LC.of(c[i]), LC.of(a[i]) + LC.of(b[i]) - LC.of(t, 2), LC.of(o) - LC.of(t), f"{tag}/maj")
        cs.set_width(t, 1)  # and / majority of bool wires are bool
        cs.set_width(o, 1)
        ts.append(t)
        out.append(o)

    def vfn(m):
        av, bv, cv = m[0:32], m[32:64], m[64:96]
        tv = av * bv
        return np.vstack([tv, tv + cv * (av + bv - 2 * tv)])

    cs.compute_block(ts + out, vfn, list(a) + list(b) + list(c))
    return out


def sha256_compression(cs: ConstraintSystem, state: List[Word], block: List[Word], tag: str = "sha") -> List[Word]:
    """One compression round chain: state (8 words) x block (16 words) ->
    new state (8 words).  The R1CS twin of circomlib sha256compression."""
    w: List[Word] = list(block)
    for t in range(16, 64):
        s0 = _xor_words(cs, [_rotr(w[t - 15], 7), _rotr(w[t - 15], 18), _shr(w[t - 15], 3)], f"{tag}.s0.{t}")
        s1 = _xor_words(cs, [_rotr(w[t - 2], 17), _rotr(w[t - 2], 19), _shr(w[t - 2], 10)], f"{tag}.s1.{t}")
        w.append(_add_mod32(cs, [s1, w[t - 7], s0, w[t - 16]], 0, 4, f"{tag}.w{t}"))

    a, b, c, d, e, f, g, h = state
    for t in range(64):
        S1 = _xor_words(cs, [_rotr(e, 6), _rotr(e, 11), _rotr(e, 25)], f"{tag}.S1.{t}")
        ch = _ch(cs, e, f, g, f"{tag}.ch.{t}")
        S0 = _xor_words(cs, [_rotr(a, 2), _rotr(a, 13), _rotr(a, 22)], f"{tag}.S0.{t}")
        mj = _maj(cs, a, b, c, f"{tag}.mj.{t}")
        # t1 = h + S1 + ch + K[t] + w[t];  t2 = S0 + maj
        t1_words = [h, S1, ch, w[t]]
        new_e = _add_mod32(cs, t1_words + [d], K[t], 6, f"{tag}.e.{t}")
        new_a = _add_mod32(cs, t1_words + [S0, mj], K[t], 7, f"{tag}.a.{t}")
        a, b, c, d, e, f, g, h = new_a, a, b, c, new_e, e, f, g

    return [
        _add_mod32(cs, [sw, rw], 0, 2, f"{tag}.fin{i}")
        for i, (sw, rw) in enumerate(zip(state, [a, b, c, d, e, f, g, h]))
    ]


def bytes_to_words(cs: ConstraintSystem, byte_bits: List[List[int]]) -> List[Word]:
    """Byte bit-decompositions (little-endian per byte) -> big-endian words.
    word = b0<<24 | b1<<16 | b2<<8 | b3; word bit i = byte[3 - i//8], bit i%8."""
    words: List[Word] = []
    for w0 in range(0, len(byte_bits), 4):
        group = byte_bits[w0 : w0 + 4]
        words.append([group[3 - i // 8][i % 8] for i in range(32)])
    return words


def state_words_from_const(cs: ConstraintSystem, values: Sequence[int], tag: str = "h0") -> List[Word]:
    """Allocate wires pinned to constant 32-bit values (initial SHA state)."""
    import numpy as np

    words: List[Word] = []
    flat: List[int] = []
    bits: List[int] = []
    for wi, v in enumerate(values):
        word: Word = []
        for i in range(32):
            bit = (v >> i) & 1
            wire = cs.new_wire(f"{tag}.{wi}.{i}")
            cs.enforce_eq(LC.of(wire), LC.const(bit), f"{tag}/const")
            word.append(wire)
            flat.append(wire)
            bits.append(bit)
        words.append(word)
    consts = np.asarray(bits, dtype=np.int64)
    cs.compute_block(flat, lambda m, c=consts: np.broadcast_to(c[:, None], (c.shape[0], m.shape[1])), [])
    return words


def sha256_blocks(
    cs: ConstraintSystem,
    padded_byte_bits: List[List[int]],
    n_blocks_wire: Optional[int],
    init_state: Optional[List[Word]] = None,
    tag: str = "sha256",
) -> List[int]:
    """Variable-length SHA over pre-padded bytes (mirror of Sha256General /
    Sha256Partial).

    padded_byte_bits: per-byte bit wires, len = 64 * max_blocks (padding is
    done outside the circuit, `shaHash.ts:17-36` semantics).
    n_blocks_wire: wire holding the actual block count (1..max_blocks); the
    output is the chained state AFTER block n_blocks-1, selected by one-hot.
    None = always use all blocks (fixed length).
    init_state: 8 words to resume from (midstate checkpoint); None = H0.

    Returns 256 output bit wires (little-endian within each of 8 words,
    words in h0..h7 order)."""
    assert len(padded_byte_bits) % 64 == 0
    max_blocks = len(padded_byte_bits) // 64
    # the whole compression pipeline (xor chains, ch/maj muxes, mod-2^32
    # sums) assumes boolean message bits; a wide "bit" forges the digest
    for bb in padded_byte_bits:
        for w in bb:
            cs.require_width(w, 1, f"{tag}/sha.msg_bit")
    if init_state is not None:
        for word in init_state:
            for w in word:
                if w is not None:
                    cs.require_width(w, 1, f"{tag}/sha.midstate_bit")
    state = init_state if init_state is not None else state_words_from_const(cs, H0, f"{tag}.h0")
    per_block_out: List[List[Word]] = []
    for blk in range(max_blocks):
        words = bytes_to_words(cs, padded_byte_bits[blk * 64 : (blk + 1) * 64])
        state = sha256_compression(cs, state, words, f"{tag}.b{blk}")
        per_block_out.append(state)

    if n_blocks_wire is None:
        return [b for word in state for b in word]

    # One-hot select the state after block (n_blocks - 1).  All select
    # products + sums witnessed by ONE BlockHook over (blocks, 256, K).
    import numpy as np

    inds = one_hot(cs, n_blocks_wire, max_blocks + 1, f"{tag}.sel")  # ind[k] = (n==k)
    out_bits: List[int] = []
    block_outs: List[int] = []
    for wi in range(8):
        for bi in range(32):
            o = cs.new_wire(f"{tag}.out.{wi}.{bi}")
            cs.set_width(o, 1)  # one-hot select over bool state bits
            prods = []
            for blk in range(max_blocks):
                p = cs.new_wire(f"{tag}.outp.{wi}.{bi}.{blk}")
                cs.enforce(LC.of(inds[blk + 1]), LC.of(per_block_out[blk][wi][bi]), LC.of(p), f"{tag}/selmul")
                cs.set_width(p, 1)
                prods.append(p)
            cs.enforce_eq(lc_sum(prods), LC.of(o), f"{tag}/selsum")
            block_outs.extend(prods)
            block_outs.append(o)
            out_bits.append(o)

    def vfn(m, nb=max_blocks):
        sel = m[0:nb]  # (blocks, K)
        vals = m[nb:].reshape(256, nb, -1)  # (256, blocks, K)
        p = sel[None, :, :] * vals
        o = p.sum(axis=1, keepdims=True)
        return np.concatenate([p, o], axis=1).reshape(-1, m.shape[1])

    sel_ins = [inds[blk + 1] for blk in range(max_blocks)]
    val_ins = [
        per_block_out[blk][wi][bi]
        for wi in range(8)
        for bi in range(32)
        for blk in range(max_blocks)
    ]
    cs.compute_block(block_outs, vfn, sel_ins + val_ins)
    return out_bits
