"""Base64 decoding in R1CS (the `bh=` body-hash check).

Rebuild of `zk-email-verify-circuits/base64.circom`: `Base64Lookup`
(:6-57, range-arithmetic char -> 6-bit value) and `Base64Decode`
(:59-108, 4 chars -> 3 bytes).  The main circuit uses it to compare the
44-char base64 `bh=` value from the DKIM header against the partial-SHA
body hash (`circuit.circom:137-156`).

Outputs are little-endian bit wires per decoded byte so they compare
directly against the SHA gadget's output bits (no repacking constraints).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from ..snark.r1cs import LC, ConstraintSystem
from .core import lc_sum, num2bits
from .regex import CharClassCache


def base64_lookup(cs: ConstraintSystem, c: int, cache: CharClassCache, tag: str = "b64") -> Tuple[int, List[int]]:
    """char wire -> (6-bit value wire, its bits).  Valid alphabet enforced
    (A-Z a-z 0-9 + / and '=' padding -> 0)."""
    cs.require_width(c, 8, f"{tag}/b64.char")  # raw c feeds the value LC
    ind_AZ = cache.in_range(c, 65, 90)
    ind_az = cache.in_range(c, 97, 122)
    ind_09 = cache.in_range(c, 48, 57)
    ind_pl = cache.eq_const(c, 43)
    ind_sl = cache.eq_const(c, 47)
    ind_eq = cache.eq_const(c, 61)
    inds = [ind_AZ, ind_az, ind_09, ind_pl, ind_sl, ind_eq]
    cs.enforce_eq(lc_sum(inds), LC.const(1), f"{tag}/valid")

    # v = AZ*(c-65) + az*(c-71) + 09*(c+4) + 62*pl + 63*sl + 0*eq
    v = cs.new_wire(f"{tag}.v")
    t1 = cs.new_wire(f"{tag}.t1")
    cs.enforce(LC.of(ind_AZ), LC.of(c) - 65, LC.of(t1), f"{tag}/az")
    cs.compute(t1, lambda i, cc: i * (cc - 65), [ind_AZ, c])
    t2 = cs.new_wire(f"{tag}.t2")
    cs.enforce(LC.of(ind_az), LC.of(c) - 71, LC.of(t2), f"{tag}/lz")
    cs.compute(t2, lambda i, cc: i * (cc - 71), [ind_az, c])
    t3 = cs.new_wire(f"{tag}.t3")
    cs.enforce(LC.of(ind_09), LC.of(c) + 4, LC.of(t3), f"{tag}/dg")
    cs.compute(t3, lambda i, cc: i * (cc + 4), [ind_09, c])
    cs.enforce_eq(
        LC.of(t1) + LC.of(t2) + LC.of(t3) + LC.of(ind_pl, 62) + LC.of(ind_sl, 63),
        LC.of(v),
        f"{tag}/v",
    )
    cs.compute(v, lambda a, b, d, p, s: a + b + d + 62 * p + 63 * s, [t1, t2, t3, ind_pl, ind_sl])
    bits = num2bits(cs, v, 6, f"{tag}.bits")
    return v, bits


def base64_decode_bits(
    cs: ConstraintSystem, char_wires: Sequence[int], cache: CharClassCache | None = None, tag: str = "b64d"
) -> List[List[int]]:
    """Base64 chars -> decoded bytes as per-byte little-endian bit lists.
    len(char_wires) must be a multiple of 4; output has 3 bytes per group
    (padding '=' decodes to zero bits, matching Base64Decode)."""
    assert len(char_wires) % 4 == 0
    cache = cache or CharClassCache(cs)
    out: List[List[int]] = []
    for g in range(0, len(char_wires), 4):
        vals = [base64_lookup(cs, c, cache, f"{tag}.{g + i}")[1] for i, c in enumerate(char_wires[g : g + 4])]
        # 4x6 bits (little-endian per value) -> 24-bit group, MSB-first chars:
        # group = v0<<18 | v1<<12 | v2<<6 | v3; bytes big-endian within group.
        group_bits = []  # little-endian bit index 0..23
        for vi, shift in ((3, 0), (2, 6), (1, 12), (0, 18)):
            group_bits.extend(vals[vi])
        byte0 = group_bits[16:24]  # bits 23..16 -> first byte
        byte1 = group_bits[8:16]
        byte2 = group_bits[0:8]
        out.extend([byte0, byte1, byte2])
    return out
