"""RSA-2048 signature verification in R1CS (e = 65537, PKCS#1 v1.5).

Rebuild of `zk-email-verify-circuits/rsa.circom`: `FpPow65537Mod` (:8-43,
16 squarings + 1 multiply), `RSAPad` (:45-122, the 0x01 FF..FF 00 ||
DigestInfo || SHA-256 padding with the DigestInfo constant
0x3031300d060960864801650304020105000420 at :85), and `RSAVerify65537`
(:124-156, sig < modulus + padded-message equality).

Limb parameterisation follows the reference: n x k with n=121, k=17
(`main` instantiation `circuit.circom:310`), which is what makes the
17-limb public modulus signals line up with `Ramp.sol`'s
`venmoMailserverKeys[17]` check (`Ramp.sol:253-293` signals [7:23]).
"""

from __future__ import annotations

from typing import List, Sequence

from ..snark.r1cs import LC, ConstraintSystem
from .bigint import big_less_than, big_mult_mod, range_check_limbs

# SHA-256 DigestInfo prefix (rsa.circom:85).
DIGEST_INFO = 0x3031300D060960864801650304020105000420


def pkcs1v15_pad_limbs_lc(digest_bits: Sequence[int], n: int, k: int, key_bits: int = 2048) -> List[LC]:
    """The padded message EM = 0x00 01 FF..FF 00 || DigestInfo || H as k
    n-bit limb LCs over the 256 digest bit wires (everything else constant).

    digest_bits: 256 wires, bit i of SHA word j at index 32j + i (our
    sha256 gadget's output order: words big-endian in the message, bits
    little-endian per word).  The integer value of H is
    Σ_j word_j · 2^(32·(7-j))."""
    # Constant part of EM as an integer.
    pad_len = key_bits // 8 - 3 - 19 - 32  # 0x00,0x01,0x00 + DigestInfo(19) + H(32)
    em = bytearray(key_bits // 8)
    em[0] = 0x00
    em[1] = 0x01
    for i in range(2, 2 + pad_len):
        em[i] = 0xFF
    em[2 + pad_len] = 0x00
    di = DIGEST_INFO.to_bytes(19, "big")
    em[3 + pad_len : 3 + pad_len + 19] = di
    em_int = int.from_bytes(bytes(em), "big")  # digest area (last 32 bytes) zero

    # Bit weight of digest bit (word j, bit i) inside EM: the digest's
    # byte 4j+b (big-endian) sits at EM byte offset key_bits/8 - 32 + 4j+b.
    lcs: List[LC] = []
    for limb in range(k):
        terms: dict = {}
        lo = n * limb
        hi = n * (limb + 1)
        const_part = (em_int >> lo) & ((1 << n) - 1)
        if const_part:
            terms[0] = const_part
        for j in range(8):
            word_weight = 32 * (7 - j)  # bit position of word j's LSB in H
            for i in range(32):
                pos = word_weight + i  # bit position within H
                if lo <= pos < hi:
                    w = digest_bits[32 * j + i]
                    terms[w] = terms.get(w, 0) + (1 << (pos - lo))
        lcs.append(LC(terms))
    return lcs


def rsa_verify_65537(
    cs: ConstraintSystem,
    signature: Sequence[int],
    modulus: Sequence[int],
    digest_bits: Sequence[int],
    n: int = 121,
    k: int = 17,
    tag: str = "rsa",
) -> None:
    """Enforce signature^65537 mod modulus == PKCS1v15-pad(digest).

    signature/modulus: k n-bit limb wires (range-checked here, matching
    RSAVerify65537's own checks); digest_bits: 256 bit wires from the
    header SHA gadget."""
    range_check_limbs(cs, signature, n, f"{tag}.sig")
    range_check_limbs(cs, modulus, n, f"{tag}.mod")
    lt = big_less_than(cs, signature, modulus, n, f"{tag}.ltmod")
    cs.enforce_eq(LC.of(lt), LC.const(1), f"{tag}/sig_lt_mod")

    acc = list(signature)
    for s in range(16):
        acc = big_mult_mod(cs, acc, acc, modulus, n, f"{tag}.sq{s}")
    acc = big_mult_mod(cs, acc, signature, modulus, n, f"{tag}.fin")

    padded = pkcs1v15_pad_limbs_lc(digest_bits, n, k)
    for i in range(k):
        cs.enforce_eq(LC.of(acc[i]), padded[i], f"{tag}/pad{i}")
