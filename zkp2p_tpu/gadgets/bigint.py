"""Limbed bignum arithmetic in R1CS: the in-circuit side of RSA-2048.

Our rebuild of `zk-email-verify-circuits/bigint.circom` + `fp.circom`:
values are k limbs x n bits (the reference instantiates n=121, k=17 for
RSA-2048, `circuit.circom:310`, `constants.ts:17-18`).  Multiplication
correctness uses the polynomial-identity trick (`BigMultNoCarry`
`bigint.circom:179-218`): interpret limb vectors as polynomial
coefficients and enforce A(t)·B(t) = C(t) at 2k-1 constant points — each
point costs ONE constraint because A(t), B(t) are linear combinations.
Carry correctness of  a·b - (q·p + r) = 0  follows CheckCarryToZero
(`bigint.circom:536-561`): witness carry wires, range-checked, rippled
limb by limb.

The witness side (host hooks) uses Python bigints (`long_div` twin of
`bigint_func.circom:29+`).
"""

from __future__ import annotations

from typing import List, Sequence

from ..field.bn254 import R
from ..snark.r1cs import LC, ConstraintSystem
from .core import num2bits


def limbs_to_int_host(limbs: Sequence[int], n: int) -> int:
    return sum(v << (n * i) for i, v in enumerate(limbs))


def int_to_limbs_host(x: int, n: int, k: int) -> List[int]:
    return [(x >> (n * i)) & ((1 << n) - 1) for i in range(k)]


def alloc_limbs(cs: ConstraintSystem, k: int, label: str) -> List[int]:
    return cs.new_wires(k, label)


def range_check_limbs(cs: ConstraintSystem, limbs: Sequence[int], n: int, tag: str) -> None:
    for i, w in enumerate(limbs):
        num2bits(cs, w, n, f"{tag}.{i}")


def _poly_eval_lc(limbs: Sequence[int], t: int) -> LC:
    """LC for Σ limbs_i · t^i (constant point t)."""
    acc: dict = {}
    power = 1
    for w in limbs:
        acc[w] = (acc.get(w, 0) + power) % R
        power = power * t % R
    return LC(acc)


def big_mult_no_carry(
    cs: ConstraintSystem, a: Sequence[int], b: Sequence[int], tag: str = "bigmul"
) -> List[int]:
    """Unreduced limb product: c_i = Σ_j a_j·b_{i-j} (2k-1 limbs, each up
    to k·2^2n — NOT range checked).  Soundness via 2k-1 point evaluations."""
    k = len(a)
    assert len(b) == k
    c = cs.new_wires(2 * k - 1, f"{tag}.c")

    def conv(*vals):
        av, bv = vals[:k], vals[k:]
        out = [0] * (2 * k - 1)
        for i, x in enumerate(av):
            for j, y in enumerate(bv):
                out[i + j] = (out[i + j] + x * y) % R
        return out

    cs.compute(c, conv, list(a) + list(b))
    for t in range(2 * k - 1):
        cs.enforce(_poly_eval_lc(a, t), _poly_eval_lc(b, t), _poly_eval_lc(c, t), f"{tag}/pt{t}")
    return c


def check_carry_to_zero(
    cs: ConstraintSystem, x_lc: List[LC], n: int, m_bits: int, hook_ins: List[int], hook_fn, tag: str = "ccz"
) -> None:
    """Enforce that the limb vector x (given as LCs, limbs signed with
    |x_i| < 2^m_bits) represents the integer 0: ripple witness carries,
    x_i + carry_{i-1} = carry_i · 2^n, last carry 0
    (CheckCarryToZero, bigint.circom:536-561).

    hook computes the concrete limb values (signed, centered by +2^m_bits
    offset is handled here)."""
    L = len(x_lc)
    carry_bits = m_bits - n + 2
    carries = cs.new_wires(L - 1, f"{tag}.carry")

    def compute_carries(*vals):
        xs = hook_fn(*vals)  # signed ints
        out = []
        c = 0
        for i in range(L - 1):
            total = xs[i] + c
            assert total % (1 << n) == 0, "carry check failed in witness"
            c = total >> n
            out.append(c % R)
        assert xs[L - 1] + c == 0, "nonzero bignum in check_carry_to_zero"
        return out

    cs.compute(carries, compute_carries, hook_ins)
    for i in range(L - 1):
        prev = LC.of(carries[i - 1]) if i > 0 else LC()
        cs.enforce_eq(x_lc[i] + prev, LC.of(carries[i], 1 << n), f"{tag}/limb{i}")
        # range: carry + 2^carry_bits in [0, 2^(carry_bits+1))
        shifted = cs.new_wire(f"{tag}.cs{i}")
        cs.enforce_eq(LC.of(carries[i]) + (1 << carry_bits), LC.of(shifted), f"{tag}/shift{i}")
        cs.compute(shifted, lambda v: (v + (1 << carry_bits)) % R, [carries[i]])
        num2bits(cs, shifted, carry_bits + 1, f"{tag}.cb{i}")
    cs.enforce_eq(x_lc[L - 1] + LC.of(carries[L - 2]), LC(), f"{tag}/last")


def big_mult_mod(
    cs: ConstraintSystem,
    a: Sequence[int],
    b: Sequence[int],
    p: Sequence[int],
    n: int,
    tag: str = "mulmod",
) -> List[int]:
    """r = a·b mod p over k n-bit limbs (FpMul, fp.circom:26-85): witness
    (q, r) by long division, then  a·b - q·p - r = 0  by carry check.
    a, b, p limbs must already be range-checked to n bits by the caller;
    q and r are range-checked here."""
    k = len(a)
    q = cs.new_wires(k, f"{tag}.q")
    r = cs.new_wires(k, f"{tag}.r")
    # The modmul interior is deliberately NOT witness-unique (the
    # bigint.circom / zk-email FpMul design): r is range-checked to k·n
    # bits, not r < p, so (q, r) admits shifted solutions (q-j, r+j·p)
    # — and through them the conv limbs, carries and range-check bits.
    # Soundness is a congruence argument instead: the integer identity
    # a·b = q·p + r (enforced by CheckCarryToZero over range-checked
    # limbs) preserves a·b ≡ r (mod p) for EVERY admissible (q, r), and
    # the chain's final residue is equated limb-wise against a value
    # < p (rsa_verify's PKCS#1 padded digest), which pins the class to
    # its unique representative.  Callers that do not pin the final
    # residue must not rely on intermediate uniqueness.
    _why = (
        "FpMul residue-class freedom: (q, r) -> (q-j, r+j*p) all satisfy; "
        "a*b === r (mod p) is preserved and the final residue is pinned "
        "< p by the consumer (see the comment at big_mult_mod)"
    )
    for g in (f"{tag}.q[*", f"{tag}.r[*", f"{tag}.qb.*", f"{tag}.rb.*",
              f"{tag}.ab.c[*", f"{tag}.qp.c[*", f"{tag}.ccz.*"):
        cs.waive("determinism", g, _why)

    def divide(*vals):
        av = limbs_to_int_host(vals[:k], n)
        bv = limbs_to_int_host(vals[k : 2 * k], n)
        pv = limbs_to_int_host(vals[2 * k :], n)
        qq, rr = divmod(av * bv, pv)
        return int_to_limbs_host(qq, n, k) + int_to_limbs_host(rr, n, k)

    cs.compute(list(q) + list(r), divide, list(a) + list(b) + list(p))
    range_check_limbs(cs, q, n, f"{tag}.qb")
    range_check_limbs(cs, r, n, f"{tag}.rb")

    ab = big_mult_no_carry(cs, a, b, f"{tag}.ab")
    qp = big_mult_no_carry(cs, q, p, f"{tag}.qp")

    # x = ab - qp - r, limbwise (2k-1 limbs; r only spans the first k)
    x_lc = []
    for i in range(2 * k - 1):
        lc = LC.of(ab[i]) - LC.of(qp[i])
        if i < k:
            lc = lc - LC.of(r[i])
        x_lc.append(lc)

    def signed_limbs(*vals):
        abv = vals[: 2 * k - 1]
        qpv = vals[2 * k - 1 : 2 * (2 * k - 1)]
        rv = vals[2 * (2 * k - 1) :]
        out = []
        for i in range(2 * k - 1):
            v = _signed(abv[i]) - _signed(qpv[i]) - (_signed(rv[i]) if i < k else 0)
            out.append(v)
        return out

    m_bits = 2 * n + (k - 1).bit_length() + 1
    check_carry_to_zero(
        cs, x_lc, n, m_bits, list(ab) + list(qp) + list(r), signed_limbs, f"{tag}.ccz"
    )
    return list(r)


def _signed(v: int) -> int:
    """Interpret an Fr element as a (small) signed integer."""
    return v - R if v > R // 2 else v


def big_less_than(cs: ConstraintSystem, a: Sequence[int], b: Sequence[int], n: int, tag: str = "biglt") -> int:
    """a < b over k n-bit limbs (BigLessThan, bigint.circom:298): lexicographic
    fold from the most significant limb."""
    from .core import is_equal, less_than, mux2

    k = len(a)
    # Fold least -> most significant: at limb i, equality defers to the
    # lower-limb verdict, difference decides via lt_i; the outermost
    # (most significant) application dominates, as it must.
    result = less_than(cs, n, a[0], b[0], f"{tag}.lt0")
    for i in range(1, k):
        lt = less_than(cs, n, a[i], b[i], f"{tag}.lt{i}")
        eq = is_equal(cs, a[i], b[i], f"{tag}.eq{i}")
        result = mux2(cs, eq, lt, result, f"{tag}.mux{i}")
    return result


def limbs_equal(cs: ConstraintSystem, a: Sequence[int], b: Sequence[int], tag: str = "bigeq") -> None:
    for i, (x, y) in enumerate(zip(a, b)):
        cs.enforce_eq(LC.of(x), LC.of(y), f"{tag}/{i}")
