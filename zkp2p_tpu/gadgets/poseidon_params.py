"""Poseidon parameter generation (Grain LFSR), circomlib-compatible.

The reference hashes the payee Venmo ID with circomlib Poseidon
(`app/src/helpers/poseidonHash.ts:5-24`, in-circuit `circuit.circom:210`
via circomlib poseidon.circom).  circomlib's constants come from the
official `generate_params_poseidon.sage 1 0 254 t R_F R_P` procedure
(Grain LFSR stream, x^5 S-box, BN254 prime); this module reproduces that
stream in pure Python so no constants are copied from anywhere — they are
re-derived from the public algorithm and validated against the canonical
circomlib test vector (poseidon([1,2]), see tests).

R_P table per t follows circomlib's POSEIDON_NROUNDSP (security-level 128
choices for alpha=5, n=254).
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Tuple

from ..field.bn254 import R as P  # BN254 scalar field prime (circomlib's "p")

R_F = 8
# circomlib poseidon.circom N_ROUNDS_P for t = 2..17.
N_ROUNDS_P = [56, 57, 56, 60, 60, 63, 64, 63, 60, 66, 60, 65, 70, 60, 64, 68]


class _Grain:
    def __init__(self, t: int, r_f: int, r_p: int, n: int = 254, field: int = 1, sbox: int = 0):
        bits: List[int] = []
        for value, width in ((field, 2), (sbox, 4), (n, 12), (t, 12), (r_f, 10), (r_p, 10)):
            bits.extend(int(b) for b in bin(value)[2:].zfill(width))
        bits.extend([1] * 30)
        assert len(bits) == 80
        self.state = bits
        for _ in range(160):
            self._update()

    def _update(self) -> int:
        s = self.state
        new = s[62] ^ s[51] ^ s[38] ^ s[23] ^ s[13] ^ s[0]
        self.state = s[1:] + [new]
        return new

    def _next_filtered_bit(self) -> int:
        # shrinking generator: a 1 bit passes the next bit through
        while True:
            b1 = self._update()
            b2 = self._update()
            if b1:
                return b2

    def next_field_element(self, n_bits: int = 254) -> int:
        while True:
            v = 0
            for _ in range(n_bits):
                v = (v << 1) | self._next_filtered_bit()
            if v < P:
                return v


@lru_cache(maxsize=None)
def poseidon_params(t: int) -> Tuple[Tuple[int, ...], Tuple[Tuple[int, ...], ...], int, int]:
    """(round_constants, mds, R_F, R_P) for state width t (t-1 inputs)."""
    r_p = N_ROUNDS_P[t - 2]
    g = _Grain(t, R_F, r_p)
    n_consts = t * (R_F + r_p)
    consts = tuple(g.next_field_element() for _ in range(n_consts))
    # MDS: Cauchy matrix from fresh x/y vectors of the same stream.
    xs = [g.next_field_element() for _ in range(t)]
    ys = [g.next_field_element() for _ in range(t)]
    mds = tuple(
        tuple(pow((xs[i] + ys[j]) % P, P - 2, P) for j in range(t)) for i in range(t)
    )
    return consts, mds, R_F, r_p


def poseidon_hash(inputs: List[int]) -> int:
    """Host Poseidon (the circomlibjs `buildPoseidon` twin)."""
    t = len(inputs) + 1
    consts, mds, r_f, r_p = poseidon_params(t)
    state = [0] + [x % P for x in inputs]
    ci = 0
    total = r_f + r_p
    for rnd in range(total):
        state = [(s + consts[ci + i]) % P for i, s in enumerate(state)]
        ci += t
        if rnd < r_f // 2 or rnd >= total - r_f // 2:
            state = [pow(s, 5, P) for s in state]
        else:
            state[0] = pow(state[0], 5, P)
        state = [sum(mds[i][j] * state[j] for j in range(t)) % P for i in range(t)]
    return state[0]


def poseidon_k(inputs: List[int], chunk: int = 16) -> int:
    """poseidonK (poseidonHash.ts:13-24): fold wide inputs in chunks."""
    out = 0
    for i in range(0, len(inputs), chunk):
        seg = inputs[i : i + chunk]
        out = poseidon_hash(([out] if i else []) + seg) if i else poseidon_hash(seg)
    return out
