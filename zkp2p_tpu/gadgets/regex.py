"""DFA regex matching as R1CS: one-hot state recurrence over bytes.

Our rebuild of the generated regex circuits (`venmo_offramper_id_regex
.circom:29-217`, `dkim_header_regex.circom`, `body_hash_regex.circom`,
`gen.py:64-163` codegen): instead of emitting circom source per regex, ONE
generic gadget consumes the compiled DFA table (regexc.compiler.DFA).

Per byte t:   s_{t+1}[d] = Σ_{(s,d,cls)} s_t[s] · ind_cls(byte_t)
where ind_cls is a char-class membership indicator built from range /
equality tests against constants (the lt/eq component pattern of
`gen.py:64-163`), memoised per (byte, class) so overlapping regexes and
shared classes pay once.

Outputs mirror the reference templates: per-step one-hot state wires, a
match count (`out === 2` style checks, `circuit.circom:106,119`), and
reveal masks `reveal[i] = in[i] * states[i+1][j]` (`gen.py:214-217`).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Sequence, Tuple

from ..regexc.compiler import DFA
from ..snark.r1cs import LC, ConstraintSystem
from .core import lc_sum, num2bits


def _ranges(chars: FrozenSet[int]) -> List[Tuple[int, int]]:
    xs = sorted(chars)
    out = []
    lo = prev = xs[0]
    for c in xs[1:]:
        if c == prev + 1:
            prev = c
            continue
        out.append((lo, prev))
        lo = prev = c
    out.append((lo, prev))
    return out


class CharClassCache:
    """Shared per-byte machinery for char-class membership tests.

    Each byte gets lazily-built NIBBLE one-hots (24 constraints per nibble,
    48 per byte, shared by every class of every regex on that byte).  A
    class indicator then groups its chars by high nibble: the low-nibble
    part is a free LC over the low one-hot, so the indicator costs ONE
    multiplication per populated high nibble (<= 4 for the email classes)
    plus one closing sum.  This is what keeps multi-regex scans at
    reference-level constraint counts (the naive lt/eq-per-range form was
    ~25 constraints per class per byte — 60% of the whole circuit)."""

    def __init__(self, cs: ConstraintSystem):
        self.cs = cs
        self._bits: Dict[int, List[int]] = {}
        self._nib: Dict[int, Tuple[List[int], List[int]]] = {}  # byte -> (lo16, hi16)
        self._cls: Dict[Tuple[int, FrozenSet[int]], int] = {}

    def register_bits(self, byte: int, bits: List[int]) -> None:
        """Reuse an existing 8-bit decomposition (e.g. from assert_bytes)."""
        self._bits.setdefault(byte, bits)

    def _nibble_onehot(self, bits4: List[int], tag: str) -> List[int]:
        """One-hot of a 4-bit value via two 2-bit one-hots; all 24 wires
        witnessed by ONE BlockHook (equality against arange)."""
        import numpy as np

        cs = self.cs
        pair0: List[int] = []  # one-hot of bits4[0:2]
        for v in range(4):
            w = cs.new_wire(f"{tag}.p{v}")
            a = LC.of(bits4[0]) if v & 1 else LC.const(1) - LC.of(bits4[0])
            b = LC.of(bits4[1]) if v & 2 else LC.const(1) - LC.of(bits4[1])
            cs.enforce(a, b, LC.of(w), f"{tag}/p")
            cs.set_width(w, 1)
            pair0.append(w)
        pair1: List[int] = []  # one-hot of bits4[2:4]
        for v in range(4):
            w = cs.new_wire(f"{tag}.q{v}")
            a = LC.of(bits4[2]) if v & 1 else LC.const(1) - LC.of(bits4[2])
            b = LC.of(bits4[3]) if v & 2 else LC.const(1) - LC.of(bits4[3])
            cs.enforce(a, b, LC.of(w), f"{tag}/q")
            cs.set_width(w, 1)
            pair1.append(w)
        out: List[int] = []
        for v in range(16):
            w = cs.new_wire(f"{tag}.n{v}")
            cs.enforce(LC.of(pair0[v & 3]), LC.of(pair1[v >> 2]), LC.of(w), f"{tag}/n")
            cs.set_width(w, 1)
            out.append(w)

        def vfn(m):
            lo = m[0] + 2 * m[1]  # (K,)
            hi = m[2] + 2 * m[3]
            p0 = (lo[None, :] == np.arange(4)[:, None]).astype(np.int64)
            p1 = (hi[None, :] == np.arange(4)[:, None]).astype(np.int64)
            n = (p1[:, None, :] * p0[None, :, :]).reshape(16, -1)  # n[v] = p0[v&3]*p1[v>>2]
            return np.concatenate([p0, p1, n], axis=0)

        cs.compute_block(pair0 + pair1 + out, vfn, list(bits4))
        return out

    def _nibbles(self, byte: int) -> Tuple[List[int], List[int]]:
        if byte not in self._nib:
            bits = self._bits.get(byte)
            if bits is None:
                bits = num2bits(self.cs, byte, 8, "re.bits")
                self._bits[byte] = bits
            lo = self._nibble_onehot(bits[0:4], "re.lo")
            hi = self._nibble_onehot(bits[4:8], "re.hi")
            self._nib[byte] = (lo, hi)
        return self._nib[byte]

    def eq_const(self, byte: int, c: int) -> int:
        return self.indicator(byte, frozenset([c]))

    def in_range(self, byte: int, lo: int, hi: int) -> int:
        return self.indicator(byte, frozenset(range(lo, hi + 1)))

    def indicator(self, byte: int, chars: FrozenSet[int]) -> int:
        key = (byte, chars)
        if key in self._cls:
            return self._cls[key]
        import numpy as np

        cs = self.cs
        lo16, hi16 = self._nibbles(byte)
        by_hi: Dict[int, List[int]] = {}
        for c in chars:
            by_hi.setdefault(c >> 4, []).append(c & 0xF)
        parts: List[int] = []
        full_his: List[int] = []
        ins: List[int] = []
        group_rows: List[List[int]] = []  # per part: [hi idx, lo idxs...] into ins
        for h, los in sorted(by_hi.items()):
            if len(los) == 16:
                full_his.append(hi16[h])  # whole row: no product needed
                continue
            p = cs.new_wire("re.cls.p")
            cs.set_width(p, 1)  # hi-lane x (disjoint lo-lane sum) is bool
            mask = lc_sum([lo16[l] for l in los])
            cs.enforce(LC.of(hi16[h]), mask, LC.of(p), "re.cls/p")
            row = [len(ins)]
            ins.append(hi16[h])
            for l in los:
                row.append(len(ins))
                ins.append(lo16[l])
            group_rows.append(row)
            parts.append(p)
        if not parts and len(full_his) == 1:
            out = full_his[0]
        elif len(parts) == 1 and not full_his:
            out = parts[0]
            self._register_indicator_block(parts, None, ins, group_rows, full_his)
            self._cls[key] = out
            return out
        else:
            out = cs.new_wire("re.cls")
            cs.set_width(out, 1)  # disjoint bool parts sum to 0/1
            cs.enforce_eq(lc_sum(parts + full_his), LC.of(out), "re.cls/sum")
        if parts:
            self._register_indicator_block(
                parts, out if out not in parts and out not in full_his else None,
                ins, group_rows, full_his,
            )
        elif out not in full_his:
            # sum-of-full-rows only: one block for the closing sum
            fh = list(full_his)
            self.cs.compute_block(
                [out], lambda m: m.sum(axis=0, keepdims=True), fh
            )
        self._cls[key] = out
        return out

    def indicator_bulk(self, byte_wires: Sequence[int], chars: FrozenSet[int]) -> List[int]:
        """indicator(byte, chars) for MANY byte wires with ONE BlockHook
        covering every cache miss — the per-(byte, class) block tier left
        ~20k small blocks on the mini circuit; a scan calls this once per
        distinct class instead (same wires, same constraints, same cache
        entries — later scans still hit the per-byte cache)."""
        import numpy as np

        cs = self.cs
        missing = [b for b in byte_wires if (b, chars) not in self._cls]
        # Group structure is identical for every byte (it depends only on
        # `chars`), so the miss block vectorizes over bytes.
        by_hi: Dict[int, List[int]] = {}
        for c in chars:
            by_hi.setdefault(c >> 4, []).append(c & 0xF)
        groups = sorted((h, los) for h, los in by_hi.items() if len(los) < 16)
        fulls = sorted(h for h, los in by_hi.items() if len(los) == 16)
        if missing and groups:
            outs: List[int] = []
            ins: List[int] = []
            g_sizes = [1 + len(los) for _, los in groups]
            stride = sum(g_sizes) + len(fulls)
            n_parts = len(groups)
            needs_sum = n_parts + len(fulls) > 1
            for b in missing:
                lo16, hi16 = self._nibbles(b)
                parts = []
                for h, los in groups:
                    p = cs.new_wire("re.cls.p")
                    cs.set_width(p, 1)  # hi-lane x (disjoint lo-lane sum)
                    cs.enforce(LC.of(hi16[h]), lc_sum([lo16[l] for l in los]), LC.of(p), "re.cls/p")
                    ins.append(hi16[h])
                    ins.extend(lo16[l] for l in los)
                    parts.append(p)
                ins.extend(hi16[h] for h in fulls)
                if needs_sum:
                    o = cs.new_wire("re.cls")
                    cs.set_width(o, 1)  # disjoint bool parts sum to 0/1
                    cs.enforce_eq(lc_sum(parts + [hi16[h] for h in fulls]), LC.of(o), "re.cls/sum")
                else:
                    o = parts[0]
                outs.extend(parts)
                if needs_sum:
                    outs.append(o)
                self._cls[(b, chars)] = o

            starts = np.cumsum([0] + g_sizes[:-1])

            def vfn(m, starts=starts, g_sizes=g_sizes, stride=stride,
                    n_parts=n_parts, n_full=len(fulls), needs_sum=needs_sum):
                nb = m.shape[0] // stride
                mm = m.reshape(nb, stride, -1)
                parts = [
                    mm[:, s] * mm[:, s + 1 : s + g].sum(axis=1)
                    for s, g in zip(starts, g_sizes)
                ]
                pv = np.stack(parts, axis=1)  # (nb, n_parts, K)
                if not needs_sum:
                    return pv.reshape(-1, m.shape[1])
                tot = pv.sum(axis=1) + mm[:, stride - n_full :].sum(axis=1)
                return np.concatenate([pv, tot[:, None, :]], axis=1).reshape(-1, m.shape[1])

            cs.compute_block(outs, vfn, ins)
        elif missing:  # pure full-row classes: indicator is an existing wire or a sum
            for b in missing:
                self.indicator(b, chars)
        return [self._cls[(b, chars)] for b in byte_wires]

    def _register_indicator_block(self, parts, out, ins, group_rows, full_his):
        """ONE BlockHook for an indicator's part products (+ closing sum):
        parts[i] = hi * sum(los); out = sum(parts) + sum(full_his)."""
        import numpy as np

        cs = self.cs
        n_ins = len(ins)
        all_ins = ins + list(full_his)
        outs = list(parts) + ([out] if out is not None else [])
        rows = group_rows
        n_fh = len(full_his)

        def vfn(m, rows=rows, n_ins=n_ins, n_fh=n_fh, has_out=out is not None):
            res = [m[r[0]] * m[r[1:]].sum(axis=0) for r in rows]
            if has_out:
                total = res[0] * 0
                for p in res:
                    total = total + p
                if n_fh:
                    total = total + m[n_ins:].sum(axis=0)
                res.append(total)
            return np.stack(res)

        cs.compute_block(outs, vfn, all_ins)


def dfa_scan(
    cs: ConstraintSystem,
    byte_wires: Sequence[int],
    dfa: DFA,
    cache: CharClassCache | None = None,
    tag: str = "re",
) -> List[List[int]]:
    """Run the DFA over byte wires; returns states[t][s] one-hot wires for
    t in 0..T (states[0] pinned to start).  Dead state is implicit: when no
    transition fires, all lanes go 0 (Σ state can drop to 0 and stays 0)."""
    cache = cache or CharClassCache(cs)
    S = dfa.n_states
    trans = dfa.transitions()

    import numpy as np

    s0 = []
    for j in range(S):
        w = cs.new_wire(f"{tag}.s0.{j}")
        cs.enforce_eq(LC.of(w), LC.const(1 if j == 0 else 0), f"{tag}/init")
        cs.set_width(w, 1)
        s0.append(w)
    init = np.asarray([1] + [0] * (S - 1), dtype=np.int64)
    cs.compute_block(s0, lambda m, c=init: np.broadcast_to(c[:, None], (S, m.shape[1])), [])
    states = [s0]

    # All class indicators for the whole scan up front: one BlockHook per
    # distinct class covering every byte position (vs one per (byte,
    # class) — ~20k tiny blocks on the mini circuit).
    class_cols = {
        chars: cache.indicator_bulk(byte_wires, chars)
        for chars in {c for _, _, c in trans}
    }

    for t, byte in enumerate(byte_wires):
        prev = states[-1]
        # Per-step BlockHook: every transition product AND every next-state
        # sum from one numpy program (ins: S prev states + the step's
        # indicator wires) — the per-wire hook tier here was ~20% of the
        # whole witness (r1cs.witness_batch).
        prods: List[int] = []
        srcs: List[int] = []
        ind_ins: List[int] = []
        dst_mat_rows: List[Tuple[int, int]] = []  # (dst, prod_idx)
        for src, dst, chars in trans:
            ind = class_cols[chars][t]
            p = cs.new_wire(f"{tag}.t{t}.{src}.{dst}.out")
            cs.enforce(LC.of(prev[src]), LC.of(ind), LC.of(p), f"{tag}.t{t}")
            cs.set_width(p, 1)  # one-hot state x class indicator
            prods.append(p)
            srcs.append(src)
            ind_ins.append(ind)
            dst_mat_rows.append((dst, len(prods) - 1))
        nxt = []
        terms_by_dst: Dict[int, List[int]] = {}
        for dst, pi in dst_mat_rows:
            terms_by_dst.setdefault(dst, []).append(prods[pi])
        for j in range(S):
            w = cs.new_wire(f"{tag}.s{t + 1}.{j}")
            cs.enforce_eq(lc_sum(terms_by_dst.get(j, [])), LC.of(w), f"{tag}/step")
            cs.set_width(w, 1)  # deterministic DFA: at most one product fires
            nxt.append(w)
        src_idx = np.asarray(srcs)
        dst_onehot = np.zeros((S, len(prods)), dtype=np.int64)
        for dst, pi in dst_mat_rows:
            dst_onehot[dst, pi] = 1

        def vfn(m, src_idx=src_idx, dst=dst_onehot, S=S):
            pv = m[src_idx] * m[S:]  # (n_trans, K)
            return np.concatenate([pv, dst @ pv], axis=0)

        cs.compute_block(prods + nxt, vfn, list(prev) + ind_ins)
        states.append(nxt)
    return states


def match_count(cs: ConstraintSystem, states: List[List[int]], accept: FrozenSet[int], tag: str = "re.cnt") -> int:
    """Number of steps landing in an accept state (the template's `out`
    signal; main circuit asserts exact counts, `circuit.circom:106,119`)."""
    import numpy as np

    out = cs.new_wire(tag)
    acc_wires = [states[t][a] for t in range(1, len(states)) for a in accept]
    for w in acc_wires:  # count-by-sum assumes 0/1 lanes
        cs.require_width(w, 1, f"{tag}/match_count.lane")
    cs.enforce_eq(lc_sum(acc_wires), LC.of(out), tag)
    cs.set_width(out, max(1, len(acc_wires).bit_length()))
    cs.compute_block([out], lambda m: m.sum(axis=0, keepdims=True), acc_wires)
    return out


def reveal_bytes(
    cs: ConstraintSystem,
    byte_wires: Sequence[int],
    states: List[List[int]],
    reveal_states: Sequence[int],
    tag: str = "re.rev",
) -> List[int]:
    """reveal[i] = byte[i] * (state_{i+1} in reveal_states)
    (`gen.py:214-217`: the extraction mask for payee ID / amount).
    All mask sums + products witnessed by ONE BlockHook."""
    import numpy as np

    T = len(byte_wires)
    nr = len(reveal_states)
    out = []
    block_outs: List[int] = []
    for i, byte in enumerate(byte_wires):
        mask_wires = [states[i + 1][s] for s in reveal_states]
        for w in mask_wires:  # mask-by-sum assumes disjoint 0/1 lanes
            cs.require_width(w, 1, f"{tag}/reveal.lane")
        if len(mask_wires) == 1:
            mask = mask_wires[0]
        else:
            mask = cs.new_wire(f"{tag}.m{i}")
            cs.enforce_eq(lc_sum(mask_wires), LC.of(mask), f"{tag}/mask")
            cs.set_width(mask, 1)  # disjoint one-hot state lanes
            block_outs.append(mask)
        p = cs.new_wire(f"{tag}.{i}.out")
        cs.enforce(LC.of(byte), LC.of(mask), LC.of(p), f"{tag}.{i}")
        cs.set_width(p, max(cs.wire_width.get(byte, 254), 1))  # byte x bool mask
        block_outs.append(p)
        out.append(p)

    # ins: bytes (T) then the reveal-state wires per position (T, nr)
    state_ins = [states[i + 1][s] for i in range(T) for s in reveal_states]

    def vfn(m, T=T, nr=nr):
        bytes_v = m[0:T]
        masks = m[T:].reshape(T, nr, -1).sum(axis=1)  # (T, K)
        pv = bytes_v * masks
        if nr == 1:
            return pv  # no mask wires were created
        return np.stack([masks, pv], axis=1).reshape(2 * T, -1)

    cs.compute_block(block_outs, vfn, list(byte_wires) + state_ins)
    return out
