"""DFA regex matching as R1CS: one-hot state recurrence over bytes.

Our rebuild of the generated regex circuits (`venmo_offramper_id_regex
.circom:29-217`, `dkim_header_regex.circom`, `body_hash_regex.circom`,
`gen.py:64-163` codegen): instead of emitting circom source per regex, ONE
generic gadget consumes the compiled DFA table (regexc.compiler.DFA).

Per byte t:   s_{t+1}[d] = Σ_{(s,d,cls)} s_t[s] · ind_cls(byte_t)
where ind_cls is a char-class membership indicator built from range /
equality tests against constants (the lt/eq component pattern of
`gen.py:64-163`), memoised per (byte, class) so overlapping regexes and
shared classes pay once.

Outputs mirror the reference templates: per-step one-hot state wires, a
match count (`out === 2` style checks, `circuit.circom:106,119`), and
reveal masks `reveal[i] = in[i] * states[i+1][j]` (`gen.py:214-217`).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Sequence, Tuple

from ..field.bn254 import R
from ..regexc.compiler import DEAD, DFA
from ..snark.r1cs import LC, ConstraintSystem
from .core import and_gate, lc_sum, num2bits


def _ranges(chars: FrozenSet[int]) -> List[Tuple[int, int]]:
    xs = sorted(chars)
    out = []
    lo = prev = xs[0]
    for c in xs[1:]:
        if c == prev + 1:
            prev = c
            continue
        out.append((lo, prev))
        lo = prev = c
    out.append((lo, prev))
    return out


class CharClassCache:
    """Shared per-byte machinery for char-class membership tests.

    Each byte gets lazily-built NIBBLE one-hots (24 constraints per nibble,
    48 per byte, shared by every class of every regex on that byte).  A
    class indicator then groups its chars by high nibble: the low-nibble
    part is a free LC over the low one-hot, so the indicator costs ONE
    multiplication per populated high nibble (<= 4 for the email classes)
    plus one closing sum.  This is what keeps multi-regex scans at
    reference-level constraint counts (the naive lt/eq-per-range form was
    ~25 constraints per class per byte — 60% of the whole circuit)."""

    def __init__(self, cs: ConstraintSystem):
        self.cs = cs
        self._bits: Dict[int, List[int]] = {}
        self._nib: Dict[int, Tuple[List[int], List[int]]] = {}  # byte -> (lo16, hi16)
        self._cls: Dict[Tuple[int, FrozenSet[int]], int] = {}

    def register_bits(self, byte: int, bits: List[int]) -> None:
        """Reuse an existing 8-bit decomposition (e.g. from assert_bytes)."""
        self._bits.setdefault(byte, bits)

    def _nibble_onehot(self, bits4: List[int], tag: str) -> List[int]:
        cs = self.cs
        pair0: List[int] = []  # one-hot of bits4[0:2]
        for v in range(4):
            w = cs.new_wire(f"{tag}.p{v}")
            a = LC.of(bits4[0]) if v & 1 else LC.const(1) - LC.of(bits4[0])
            b = LC.of(bits4[1]) if v & 2 else LC.const(1) - LC.of(bits4[1])
            cs.enforce(a, b, LC.of(w), f"{tag}/p")
            # branch-free equality on bits ((1-(b^x))*(1-(b^y))) so the
            # batch witness tier runs it columnar (r1cs.witness_batch)
            cs.compute(
                w,
                lambda b0, b1, vv=v: (1 - (b0 ^ (vv & 1))) * (1 - (b1 ^ ((vv >> 1) & 1))),
                [bits4[0], bits4[1]],
            )
            pair0.append(w)
        pair1: List[int] = []  # one-hot of bits4[2:4]
        for v in range(4):
            w = cs.new_wire(f"{tag}.q{v}")
            a = LC.of(bits4[2]) if v & 1 else LC.const(1) - LC.of(bits4[2])
            b = LC.of(bits4[3]) if v & 2 else LC.const(1) - LC.of(bits4[3])
            cs.enforce(a, b, LC.of(w), f"{tag}/q")
            cs.compute(
                w,
                lambda b2, b3, vv=v: (1 - (b2 ^ (vv & 1))) * (1 - (b3 ^ ((vv >> 1) & 1))),
                [bits4[2], bits4[3]],
            )
            pair1.append(w)
        out: List[int] = []
        for v in range(16):
            w = cs.new_wire(f"{tag}.n{v}")
            cs.enforce(LC.of(pair0[v & 3]), LC.of(pair1[v >> 2]), LC.of(w), f"{tag}/n")
            cs.compute(w, lambda x, y: x * y, [pair0[v & 3], pair1[v >> 2]])
            out.append(w)
        return out

    def _nibbles(self, byte: int) -> Tuple[List[int], List[int]]:
        if byte not in self._nib:
            bits = self._bits.get(byte)
            if bits is None:
                bits = num2bits(self.cs, byte, 8, "re.bits")
                self._bits[byte] = bits
            lo = self._nibble_onehot(bits[0:4], "re.lo")
            hi = self._nibble_onehot(bits[4:8], "re.hi")
            self._nib[byte] = (lo, hi)
        return self._nib[byte]

    def eq_const(self, byte: int, c: int) -> int:
        return self.indicator(byte, frozenset([c]))

    def in_range(self, byte: int, lo: int, hi: int) -> int:
        return self.indicator(byte, frozenset(range(lo, hi + 1)))

    def indicator(self, byte: int, chars: FrozenSet[int]) -> int:
        key = (byte, chars)
        if key in self._cls:
            return self._cls[key]
        cs = self.cs
        lo16, hi16 = self._nibbles(byte)
        by_hi: Dict[int, List[int]] = {}
        for c in chars:
            by_hi.setdefault(c >> 4, []).append(c & 0xF)
        parts: List[int] = []
        full_his: List[int] = []
        for h, los in sorted(by_hi.items()):
            if len(los) == 16:
                full_his.append(hi16[h])  # whole row: no product needed
                continue
            p = cs.new_wire("re.cls.p")
            mask = lc_sum([lo16[l] for l in los])
            cs.enforce(LC.of(hi16[h]), mask, LC.of(p), "re.cls/p")
            cs.compute(
                p,
                lambda hv, *lvs: hv * (sum(lvs) % R),
                [hi16[h]] + [lo16[l] for l in los],
            )
            parts.append(p)
        if not parts and len(full_his) == 1:
            out = full_his[0]
        elif len(parts) == 1 and not full_his:
            out = parts[0]
        else:
            out = cs.new_wire("re.cls")
            cs.enforce_eq(lc_sum(parts + full_his), LC.of(out), "re.cls/sum")
            cs.compute(out, lambda *ps: sum(ps), parts + full_his)
        self._cls[key] = out
        return out


def dfa_scan(
    cs: ConstraintSystem,
    byte_wires: Sequence[int],
    dfa: DFA,
    cache: CharClassCache | None = None,
    tag: str = "re",
) -> List[List[int]]:
    """Run the DFA over byte wires; returns states[t][s] one-hot wires for
    t in 0..T (states[0] pinned to start).  Dead state is implicit: when no
    transition fires, all lanes go 0 (Σ state can drop to 0 and stays 0)."""
    cache = cache or CharClassCache(cs)
    S = dfa.n_states
    trans = dfa.transitions()

    s0 = []
    for j in range(S):
        w = cs.new_wire(f"{tag}.s0.{j}")
        cs.enforce_eq(LC.of(w), LC.const(1 if j == 0 else 0), f"{tag}/init")
        cs.compute(w, lambda v=1 if j == 0 else 0: v, [])
        s0.append(w)
    states = [s0]

    for t, byte in enumerate(byte_wires):
        prev = states[-1]
        terms_by_dst: Dict[int, List[int]] = {}
        for src, dst, chars in trans:
            ind = cache.indicator(byte, chars)
            p = and_gate(cs, prev[src], ind, f"{tag}.t{t}.{src}.{dst}")
            terms_by_dst.setdefault(dst, []).append(p)
        nxt = []
        for j in range(S):
            w = cs.new_wire(f"{tag}.s{t + 1}.{j}")
            ts = terms_by_dst.get(j, [])
            cs.enforce_eq(lc_sum(ts), LC.of(w), f"{tag}/step")
            cs.compute(w, lambda *ps: sum(ps), ts)
            nxt.append(w)
        states.append(nxt)
    return states


def match_count(cs: ConstraintSystem, states: List[List[int]], accept: FrozenSet[int], tag: str = "re.cnt") -> int:
    """Number of steps landing in an accept state (the template's `out`
    signal; main circuit asserts exact counts, `circuit.circom:106,119`)."""
    out = cs.new_wire(tag)
    acc_wires = [states[t][a] for t in range(1, len(states)) for a in accept]
    cs.enforce_eq(lc_sum(acc_wires), LC.of(out), tag)
    cs.compute(out, lambda *vs: sum(vs), acc_wires)
    return out


def reveal_bytes(
    cs: ConstraintSystem,
    byte_wires: Sequence[int],
    states: List[List[int]],
    reveal_states: Sequence[int],
    tag: str = "re.rev",
) -> List[int]:
    """reveal[i] = byte[i] * (state_{i+1} in reveal_states)
    (`gen.py:214-217`: the extraction mask for payee ID / amount)."""
    out = []
    for i, byte in enumerate(byte_wires):
        mask_wires = [states[i + 1][s] for s in reveal_states]
        if len(mask_wires) == 1:
            mask = mask_wires[0]
        else:
            mask = cs.new_wire(f"{tag}.m{i}")
            cs.enforce_eq(lc_sum(mask_wires), LC.of(mask), f"{tag}/mask")
            cs.compute(mask, lambda *vs: sum(vs), mask_wires)
        out.append(and_gate(cs, byte, mask, f"{tag}.{i}"))
    return out
