"""Poseidon hash as R1CS (x^5 S-box, BN254, circomlib parameterisation).

Rebuild of circomlib poseidon.circom as used at `circuit.circom:210-218`
(payee Venmo-ID hash) and `poseidonHash.ts`.  The linear layers (round
constants + MDS mix) are folded into LCs — they cost ZERO constraints;
only S-boxes materialise wires (3 constraints each: x2, x4, x5), the same
trick circomlib's optimized form exploits.  Cost: 3·(t·R_F + R_P)
constraints per permutation (t=3 -> 192).

Parameters come from gadgets.poseidon_params (Grain LFSR re-derivation of
the official x5_254 constants; C/M spot-pinned against the canonical
published values in tests)."""

from __future__ import annotations

from typing import List, Sequence

from ..field.bn254 import R
from ..snark.r1cs import LC, ConstraintSystem
from .poseidon_params import poseidon_params

P = R  # Poseidon runs over the BN254 scalar field


def _lc_pow5(cs: ConstraintSystem, lc: LC, tag: str) -> int:
    """x^5 of an LC value: wires for x2, x4, x5 (3 constraints), all
    witnessed by ONE object BlockHook (exact field arithmetic)."""
    import numpy as np

    ins = [w for w in lc.terms if w != 0]
    weights = np.asarray([lc.terms[w] for w in ins], dtype=object)[:, None]
    const = lc.terms.get(0, 0)

    x2 = cs.new_wire(f"{tag}.x2")
    cs.enforce(lc, lc, LC.of(x2), f"{tag}/x2")
    x4 = cs.new_wire(f"{tag}.x4")
    cs.enforce(LC.of(x2), LC.of(x2), LC.of(x4), f"{tag}/x4")
    x5 = cs.new_wire(f"{tag}.x5")
    cs.enforce(LC.of(x4), lc, LC.of(x5), f"{tag}/x5")

    def vfn(m, w=weights, c=const):
        x = ((w * m).sum(axis=0) + c) % P
        x2v = x * x % P
        x4v = x2v * x2v % P
        return np.stack([x2v, x4v, x4v * x % P])

    cs.compute_block([x2, x4, x5], vfn, ins, int64=False)
    return x5


def poseidon(cs: ConstraintSystem, inputs: Sequence[int], tag: str = "poseidon") -> int:
    """Poseidon hash of input wires -> output wire (capacity-0 sponge,
    output = state[0] after the permutation)."""
    t = len(inputs) + 1
    consts, mds, r_f, r_p = poseidon_params(t)
    state: List[LC] = [LC()] + [LC.of(w) for w in inputs]
    ci = 0
    total = r_f + r_p
    for rnd in range(total):
        state = [lc + consts[ci + i] for i, lc in enumerate(state)]
        ci += t
        full = rnd < r_f // 2 or rnd >= total - r_f // 2
        if full:
            state = [LC.of(_lc_pow5(cs, lc, f"{tag}.r{rnd}.{i}")) for i, lc in enumerate(state)]
        else:
            state[0] = LC.of(_lc_pow5(cs, state[0], f"{tag}.r{rnd}.0"))
        state = [
            sum((state[j] * mds[i][j] for j in range(t)), LC())
            for i in range(t)
        ]
    out = cs.new_wire(f"{tag}.out")
    cs.enforce_eq(state[0], LC.of(out), f"{tag}/out")
    ins = [w for w in state[0].terms if w != 0]
    weights = [state[0].terms[w] for w in ins]
    const = state[0].terms.get(0, 0)
    cs.compute(out, lambda *vs, ws=tuple(weights), c=const: (sum(v * x for v, x in zip(vs, ws)) + c) % P, ins)
    return out
