"""Optimal ate pairing on BN254 (host side).

The framework's native replacement for the EVM ``ecPairing`` precompile the
reference relies on (contracts/Verifier.sol:146-163 ``pairing(...)`` /
``pairingProd4``).  It lets us verify Groth16 proofs off-chain, exactly as
``snarkjs groth16 verify`` does in the reference pipeline
(dizkus-scripts/5_gen_proof.sh:15-22).

Approach: map the G2 point from the twist E'(Fq2) into E(Fq12) via the
untwist morphism psi(x, y) = (x * w^2, y * w^3), then run a plain affine
Miller loop with generic line functions in Fq12.  This trades speed for
obviousness — it is the *verification* path (a handful of pairings per
proof batch), not the prover hot loop.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from ..curve.host import G1Point, G2Point, g1_is_on_curve, g2_is_on_curve
from ..field.bn254 import ATE_LOOP_COUNT, P, R
from ..field.tower import Fq2, Fq6, Fq12

# w as an element of Fq12 = Fq6[w]
_W = Fq12(Fq6.zero(), Fq6.one())
_W2 = _W * _W
_W3 = _W2 * _W

E12Point = Optional[Tuple[Fq12, Fq12]]


def fq_to_fq12(a: int) -> Fq12:
    return Fq12(Fq6(Fq2(a, 0), Fq2.zero(), Fq2.zero()), Fq6.zero())


def fq2_to_fq12(a: Fq2) -> Fq12:
    return Fq12(Fq6(a, Fq2.zero(), Fq2.zero()), Fq6.zero())


def untwist(q: G2Point) -> E12Point:
    """E'(Fq2) -> E(Fq12): (x, y) -> (x w^2, y w^3)."""
    if q is None:
        return None
    return (fq2_to_fq12(q[0]) * _W2, fq2_to_fq12(q[1]) * _W3)


def _e12_neg(a: E12Point) -> E12Point:
    if a is None:
        return None
    return (a[0], Fq12.zero() - a[1])


def _e12_frobenius(a: E12Point) -> E12Point:
    if a is None:
        return None
    return (a[0].frobenius(), a[1].frobenius())


def _e12_add(a: E12Point, b: E12Point) -> E12Point:
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2:
        if y1 == y2:
            lam = (x1.square() * fq_to_fq12(3)) * (y1 * fq_to_fq12(2)).inv()
        else:
            return None
    else:
        lam = (y2 - y1) * (x2 - x1).inv()
    x3 = lam.square() - x1 - x2
    y3 = lam * (x1 - x3) - y1
    return (x3, y3)


def _line(t: E12Point, q: E12Point, px: Fq12, py: Fq12) -> Fq12:
    """Evaluate the line through t and q at the (embedded) G1 point P."""
    x1, y1 = t
    x2, y2 = q
    if x1 == x2 and y1 == y2:
        lam = (x1.square() * fq_to_fq12(3)) * (y1 * fq_to_fq12(2)).inv()
        return (py - y1) - lam * (px - x1)
    if x1 == x2:
        # vertical line
        return px - x1
    lam = (y2 - y1) * (x2 - x1).inv()
    return (py - y1) - lam * (px - x1)


def miller_loop(p: G1Point, q: G2Point) -> Fq12:
    """Miller loop of the optimal ate pairing (before final exponentiation)."""
    if p is None or q is None:
        return Fq12.one()
    px, py = fq_to_fq12(p[0]), fq_to_fq12(p[1])
    q12 = untwist(q)
    t = q12
    f = Fq12.one()
    for bit in bin(ATE_LOOP_COUNT)[3:]:
        f = f.square() * _line(t, t, px, py)
        t = _e12_add(t, t)
        if bit == "1":
            f = f * _line(t, q12, px, py)
            t = _e12_add(t, q12)
    # Frobenius correction steps of the optimal ate pairing.
    q1 = _e12_frobenius(q12)
    f = f * _line(t, q1, px, py)
    t = _e12_add(t, q1)
    q2 = _e12_neg(_e12_frobenius(_e12_frobenius(q12)))
    f = f * _line(t, q2, px, py)
    return f


def final_exponentiation(f: Fq12) -> Fq12:
    """f^((p^12 - 1) / r), split into easy part and (generic-pow) hard part."""
    # easy: f^((p^6 - 1)(p^2 + 1))
    f1 = f.conjugate() * f.inv()  # f^(p^6 - 1)
    f2 = f1.frobenius(2) * f1  # ^(p^2 + 1)
    # hard: ^((p^4 - p^2 + 1) / r)
    hard = (P**4 - P**2 + 1) // R
    return f2.pow(hard)


def pairing(p: G1Point, q: G2Point) -> Fq12:
    assert g1_is_on_curve(p), "G1 point not on curve"
    assert g2_is_on_curve(q), "G2 point not on twist"
    return final_exponentiation(miller_loop(p, q))


def pairing_product_is_one(
    pairs: Sequence[Tuple[G1Point, G2Point]],
) -> bool:
    """prod e(P_i, Q_i) == 1, sharing one final exponentiation.

    Mirror of Verifier.sol's pairingProd4 (contracts/Verifier.sol:116-145):
    the EVM precompile also checks a product of pairings against 1.
    """
    acc = Fq12.one()
    for p, q in pairs:
        acc = acc * miller_loop(p, q)
    return final_exponentiation(acc) == Fq12.one()
