"""On-chain settlement semantics: the Ramp escrow + Groth16 verifier (L3).

Executable Python model of `contracts/Ramp.sol` (order book state machine,
claim escrow/expiry, proof-gated release, nullifier replay protection) and
`contracts/FakeUSDC.sol`, verified against our pairing-based
`snark.groth16.verify` — the same equation `Verifier.sol:340-380` checks
via the EVM pairing precompile.  The Solidity sources themselves are a
compatibility TARGET (SURVEY.md §7 step 9): proofs emitted by the TPU
prover must satisfy this logic bit for bit, so the model doubles as the
integration-test harness the reference runs under hardhat
(`test/ramp.test.js`).

Semantics mirrored with file:line cites inline.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass
from enum import IntEnum
from typing import Dict, List, Tuple

from ..snark.groth16 import Proof, VerifyingKey, verify

MSG_LEN = 26  # uint[26] signals (Verifier.sol:360)
BYTES_IN_PACKED = 7  # Ramp.sol:57
CLAIM_TTL = 86400  # 1 days (Ramp.sol:144)


class OrderStatus(IntEnum):  # Ramp.sol:14-19
    Unopened = 0
    Open = 1
    Filled = 2
    Canceled = 3


class ClaimStatus(IntEnum):  # Ramp.sol:21-27
    Unsubmitted = 0
    Submitted = 1
    Used = 2
    Clawback = 3


@dataclass
class Order:  # Ramp.sol:29-36
    on_ramper: str
    amount: int
    max_amount_to_pay: int
    status: OrderStatus


@dataclass
class OrderClaim:  # Ramp.sol:38-45
    off_ramper: str
    venmo_id_hash: int
    status: ClaimStatus
    encrypted_off_ramper_venmo_id: bytes
    claim_expiration_time: int
    min_amount_to_pay: int


class FakeUSDC:
    """6-decimals ERC20 with open mint (contracts/FakeUSDC.sol:6-18)."""

    def __init__(self):
        self.balances: Dict[str, int] = {}
        self.allowances: Dict[Tuple[str, str], int] = {}

    def mint(self, to: str, amount: int) -> None:
        self.balances[to] = self.balances.get(to, 0) + amount

    def approve(self, owner: str, spender: str, amount: int) -> None:
        self.allowances[(owner, spender)] = amount

    def transfer(self, sender: str, to: str, amount: int) -> None:
        if self.balances.get(sender, 0) < amount:
            raise AssertionError("ERC20: insufficient balance")
        self.balances[sender] -= amount
        self.balances[to] = self.balances.get(to, 0) + amount

    def transfer_from(self, spender: str, owner: str, to: str, amount: int) -> None:
        if self.allowances.get((owner, spender), 0) < amount:
            raise AssertionError("ERC20: insufficient allowance")
        self.allowances[(owner, spender)] -= amount
        self.transfer(owner, to, amount)


def convert_packed_bytes_to_string(packed: List[int], max_bytes: int) -> str:
    """_convertPackedBytesToBytes (Ramp.sol:299-335): unpack 7-byte LE words,
    keep the single contiguous nonzero run."""
    state = 0
    out = bytearray()
    for word in packed:
        for j in range(BYTES_IN_PACKED):
            b = (word >> (8 * j)) & 0xFF
            if b != 0:
                out.append(b)
                if state % 2 == 0:
                    state += 1
            else:
                if state % 2 == 1:
                    state += 1
    if state != 2:
        raise AssertionError("Invalid final state of packed bytes in email")
    if len(out) > max_bytes:
        raise AssertionError("Venmo id too long")
    return out.decode("latin1")


def string_to_uint(s: str) -> int:
    """_stringToUint256 (Ramp.sol:338-354): digits only, others skipped."""
    result = 0
    for ch in s:
        if "0" <= ch <= "9":
            result = result * 10 + (ord(ch) - 48)
    return result


class Ramp:
    """The escrow order book (`contracts/Ramp.sol:10-354`)."""

    def __init__(self, venmo_keys: List[int], usdc: FakeUSDC, max_amount: int, vk: VerifyingKey, address: str = "ramp"):
        assert len(venmo_keys) == 17
        self.venmo_mailserver_keys = list(venmo_keys)  # Ramp.sol:63
        self.usdc = usdc
        self.max_amount = max_amount
        self.vk = vk
        self.address = address
        self.order_nonce = 1  # Ramp.sol:94 (starts at 1)
        self.orders: Dict[int, Order] = {}
        self.order_claims: Dict[int, Dict[int, OrderClaim]] = {}
        self.order_claim_nonce: Dict[int, int] = {}
        self.claimed_venmo_ids: Dict[int, set] = {}
        self.nullified: set = set()  # Ramp.sol:75
        self._now = int(_time.time())

    # -- test helper (hardhat time.increase analog, test/ramp.test.js:260)
    def increase_time(self, secs: int) -> None:
        self._now += secs

    # ---------------------------------------------------------- Ramp.sol:100
    def post_order(self, sender: str, amount: int, max_amount_to_pay: int) -> int:
        assert 0 < amount <= self.max_amount, "amount over max"
        order_id = self.order_nonce
        self.orders[order_id] = Order(sender, amount, max_amount_to_pay, OrderStatus.Open)
        self.order_claims[order_id] = {}
        self.order_claim_nonce[order_id] = 0
        self.claimed_venmo_ids[order_id] = set()
        self.order_nonce += 1
        return order_id

    # ---------------------------------------------------------- Ramp.sol:122
    def claim_order(self, sender: str, venmo_id_hash: int, order_id: int, encrypted_venmo_id: bytes, min_amount_to_pay: int) -> int:
        order = self.orders.get(order_id)
        assert order and order.status == OrderStatus.Open, "order not open"
        assert venmo_id_hash not in self.claimed_venmo_ids[order_id], "venmo id already claimed"
        claim_id = self.order_claim_nonce[order_id]
        self.order_claims[order_id][claim_id] = OrderClaim(
            off_ramper=sender,
            venmo_id_hash=venmo_id_hash,
            status=ClaimStatus.Submitted,
            encrypted_off_ramper_venmo_id=encrypted_venmo_id,
            claim_expiration_time=self._now + CLAIM_TTL,
            min_amount_to_pay=min_amount_to_pay,
        )
        self.claimed_venmo_ids[order_id].add(venmo_id_hash)
        self.order_claim_nonce[order_id] = claim_id + 1
        # escrow USDC (Ramp.sol:153)
        self.usdc.transfer_from(self.address, sender, self.address, self.orders[order_id].amount)
        return claim_id

    # ---------------------------------------------------------- Ramp.sol:156
    def on_ramp(self, sender: str, proof: Proof, signals: List[int]) -> None:
        venmo_id, usd_amount, order_id, claim_id, nullifier = self._verify_and_parse(proof, signals)
        order = self.orders.get(order_id)
        claim = self.order_claims.get(order_id, {}).get(claim_id)
        assert order and order.status == OrderStatus.Open, "order not open"
        assert claim and claim.status == ClaimStatus.Submitted, "claim not submitted"
        assert claim.venmo_id_hash == venmo_id, "wrong venmo id"
        assert usd_amount >= order.amount, "payment below order amount"  # Ramp.sol:176
        self.nullified.add(nullifier)
        order.status = OrderStatus.Filled
        claim.status = ClaimStatus.Used
        self.usdc.transfer(self.address, order.on_ramper, order.amount)  # Ramp.sol:186-192

    # ---------------------------------------------------------- Ramp.sol:195
    def cancel_order(self, sender: str, order_id: int) -> None:
        order = self.orders.get(order_id)
        assert order and order.status == OrderStatus.Open and order.on_ramper == sender
        order.status = OrderStatus.Canceled

    # ---------------------------------------------------------- Ramp.sol:202
    def clawback(self, sender: str, order_id: int, claim_id: int) -> None:
        claim = self.order_claims.get(order_id, {}).get(claim_id)
        order = self.orders[order_id]
        assert claim and claim.off_ramper == sender
        assert claim.status == ClaimStatus.Submitted
        order_done = order.status in (OrderStatus.Filled, OrderStatus.Canceled)
        if not order_done:
            assert self._now > claim.claim_expiration_time, "claim not expired"
        claim.status = ClaimStatus.Clawback
        self.usdc.transfer(self.address, sender, order.amount)

    # ------------------------------------------------------------- views
    def get_claims_for_order(self, order_id: int) -> List[OrderClaim]:  # Ramp.sol:228
        return list(self.order_claims.get(order_id, {}).values())

    def get_all_orders(self) -> List[Tuple[int, Order]]:  # Ramp.sol:239
        return sorted(self.orders.items())

    # ---------------------------------------------------------- Ramp.sol:253
    def _verify_and_parse(self, proof: Proof, signals: List[int]):
        assert len(signals) == MSG_LEN
        assert verify(self.vk, proof, signals), "Invalid Proof"
        venmo_id = signals[0]
        amount_str = convert_packed_bytes_to_string(signals[1:4], BYTES_IN_PACKED * 3)
        usd_amount = string_to_uint(amount_str) * 10**6
        nullifier = tuple(signals[4:7])  # keccak of the 3 words on-chain
        assert nullifier not in self.nullified, "Email has already been used"
        for i in range(7, MSG_LEN - 2):
            assert signals[i] == self.venmo_mailserver_keys[i - 7], "Invalid: RSA modulus not matched"
        return venmo_id, usd_amount, signals[MSG_LEN - 2], signals[MSG_LEN - 1], nullifier
