"""Deployment config + bootstrap (scripts/deploy.js rebuild).

Carries the production constants the reference bakes into its deploy
script (`scripts/deploy.js:23-47`): the Venmo mailserver RSA modulus as
17 x 121-bit limbs (9 nonzero — a 1024-bit key) and the $10 launch cap,
plus a factory that stands up the executable contract model with them.
On-chain deployment itself stays hardhat territory; `formats.solidity`
exports the Verifier these constants pair with.
"""

from __future__ import annotations

from typing import List, Optional

from ..snark.groth16 import VerifyingKey
from .ramp import FakeUSDC, Ramp

# The Venmo mailserver RSA modulus limbs (deploy.js:24-42), 121-bit x 17.
VENMO_RSA_KEY_LIMBS: List[int] = [
    683441457792668103047675496834917209,
    1011953822609495209329257792734700899,
    1263501452160533074361275552572837806,
    2083482795601873989011209904125056704,
    642486996853901942772546774764252018,
    1463330014555221455251438998802111943,
    2411895850618892594706497264082911185,
    520305634984671803945830034917965905,
    47421696716332554,
    0, 0, 0, 0, 0, 0, 0, 0,
]

MAX_AMOUNT_USDC = 10_000_000  # $10, 6 decimals (deploy.js:23)


def venmo_modulus_int() -> int:
    """The limbs reassembled to the 1024-bit modulus."""
    return sum(v << (121 * i) for i, v in enumerate(VENMO_RSA_KEY_LIMBS))


def deploy(vk: VerifyingKey, usdc: Optional[FakeUSDC] = None, max_amount: int = MAX_AMOUNT_USDC) -> Ramp:
    """Stand up the escrow with production constants (model form)."""
    return Ramp(VENMO_RSA_KEY_LIMBS, usdc or FakeUSDC(), max_amount, vk)
