"""Fused Montgomery multiplication as a Pallas TPU kernel.

docs/ROOFLINE.md: the XLA field-mul path materialises its (B, 512) f32
partial-product planes in HBM between the outer product and the one-hot
fold, capping FR.mul at ~14 M muls/s (~1-2% of VPU) — the measured
ceiling of the whole MSM stack.  This kernel runs the complete SOS
Montgomery product (3 limb convolutions + carry ladders + conditional
subtract) inside ONE kernel with every intermediate resident in VMEM.

Layout: limbs live on the SUBLANE axis and the batch on the 128-wide
LANE axis — (16, T) tiles — so every elementwise op fills the vector
unit (the batch-major (B, 16) layout uses 16/128 lanes).  The wrapper
transposes at the boundary; inside, the dataflow is identical
arithmetic to field.jfield (same 16x16-bit limbs, same Kogge-Stone
carry ladder), differentially tested against it.

The TPU tunnel is down this round, so correctness is pinned with
`interpret=True` on CPU (tests/test_pallas_mont.py); the flag
ZKP2P_FIELD_MUL=pallas arms the kernel inside JPrimeField.mul for A/B
on hardware the moment a chip is reachable.

Reference analog: rapidsnark's x86-assembly Montgomery mul
(its fastest-path field layer); this is the TPU-native equivalent.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field.jfield import LIMB_BITS, MASK, NUM_LIMBS, int_to_limbs

TILE = 256  # batch elements per grid step; VMEM high-water ~ (16,16,TILE) u32


def _up(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """Limb-axis (axis 0) shift up by k, zero-filled."""
    return jnp.pad(x, ((k, 0), (0, 0)))[: x.shape[0]]


def _carry_lm(x: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Kogge-Stone carry resolution, limbs on axis 0 (mirror of
    field.jfield._carry_ladder)."""
    L = x.shape[0]
    if L < out_limbs:
        x = jnp.pad(x, ((0, out_limbs - L), (0, 0)))
    else:
        x = x[:out_limbs]
    for _ in range(2):
        x = (x & MASK) + _up(x >> LIMB_BITS, 1)
    g = x >> LIMB_BITS
    r = x & MASK
    p = (r == MASK).astype(jnp.uint32)
    k = 1
    while k < out_limbs:
        g = g | (p & _up(g, k))
        p = p & _up(p, k)
        k *= 2
    return (r + _up(g, 1)) & MASK


def _mul_wide_lm(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(La, T) x (Lb, T or 1) -> (La+Lb, T) canonical limbs; schoolbook
    accumulation is exact in u32 (sums of < 2*16 values < 2^16).

    The accumulator starts from the i=0 partial product instead of a
    `jnp.zeros` array: a zeros literal created inside the kernel body
    while an outer jit trace is live becomes a CAPTURED CONSTANT of the
    kernel jaxpr, which pallas_call rejects ("captures constants ...
    pass them as inputs") — first seen on the round-5 driver box's JAX
    when ntt.domain() built twiddles mid-trace."""
    La = a.shape[0]
    Lb = b.shape[0]
    out_len = La + Lb + 1
    p0 = a[0][None, :] * b  # (Lb, T)
    acc = jnp.pad(p0 & MASK, ((0, out_len - Lb), (0, 0)))
    acc = acc + jnp.pad(p0 >> LIMB_BITS, ((1, out_len - Lb - 1), (0, 0)))
    for i in range(1, La):
        p = a[i][None, :] * b  # (Lb, T)
        acc = acc + jnp.pad(p & MASK, ((i, out_len - Lb - i), (0, 0)))
        acc = acc + jnp.pad(p >> LIMB_BITS, ((i + 1, out_len - Lb - i - 1), (0, 0)))
    return _carry_lm(acc, La + Lb)


def _sub_raw_lm(a: jnp.ndarray, b: jnp.ndarray):
    """(a - b) mod 2^(16*L) + borrow flag, limb-major."""
    L = a.shape[0]
    x = a + (MASK - b)
    # +1 on limb 0 by slicing and re-concatenating: `.at[0].add` lowers
    # to scatter-add, which Mosaic TPU cannot lower (found on real
    # hardware; interpret mode accepted it), and a broadcasted_iota
    # one-hot becomes a captured kernel constant under a live outer
    # trace (same failure mode as the zeros in _mul_wide_lm).
    x = jnp.concatenate([x[0:1] + 1, x[1:]], axis=0)
    y = _carry_lm(x, L + 1)
    borrow = 1 - y[L]
    return y[:L], borrow


def _mont_mul_math(a, b, n_lm, np_lm):
    """The full Montgomery product, limb-major: shared by the Pallas
    kernel body and the interpret-mode tests."""
    t = _mul_wide_lm(a, b)  # (32, T)
    m = _mul_wide_lm(t[:NUM_LIMBS], np_lm)[:NUM_LIMBS]
    u = _mul_wide_lm(m, n_lm)  # (32, T)
    s = _carry_lm(t + u, 2 * NUM_LIMBS + 1)
    hi = s[NUM_LIMBS : 2 * NUM_LIMBS + 1]
    red = _carry_lm(hi, NUM_LIMBS + 1)[:NUM_LIMBS]
    d, borrow = _sub_raw_lm(red, n_lm)
    return jnp.where(borrow[None, :] != 0, red, d)


def _kernel(a_ref, b_ref, n_ref, np_ref, out_ref):
    out_ref[:] = _mont_mul_math(a_ref[:], b_ref[:], n_ref[:], np_ref[:])


def _pow_kernel(nbits: int):
    """Fused square-and-multiply for a COMPILE-TIME exponent: the whole
    254-step ladder runs inside one kernel (fori_loop, all state in
    VMEM).  The XLA-level `JPrimeField.pow_const` scan issues 2 mul
    dispatches per exponent bit — ~508 kernel launches per inversion —
    which makes the per-chunk batch-inversion totals of the affine MSM
    (ops.msm_affine) latency-bound; this kernel is one launch.

    The exponent bits ride as a (nbits, 1) u32 operand (LSB first) —
    kernels cannot capture traced constants (Mosaic note above) and a
    Python-unrolled ladder would inline ~500 mul graphs."""

    def kernel(a_ref, bits_ref, n_ref, np_ref, one_ref, out_ref):
        from jax.experimental import pallas as pl

        n_lm = n_ref[:]
        np_lm = np_ref[:]
        base0 = a_ref[:]
        acc0 = jnp.broadcast_to(one_ref[:], base0.shape)

        def body(i, carry):
            acc, base = carry
            bit = bits_ref[pl.ds(i, 1), :][0, 0]
            nacc = _mont_mul_math(acc, base, n_lm, np_lm)
            acc = jnp.where(bit != 0, nacc, acc)
            base = _mont_mul_math(base, base, n_lm, np_lm)
            return (acc, base)

        acc, _ = jax.lax.fori_loop(0, nbits, body, (acc0, base0))
        out_ref[:] = acc

    return kernel


@partial(jax.jit, static_argnums=(0, 2, 3))
def mont_pow(field, a: jnp.ndarray, e: int, interpret: bool = False) -> jnp.ndarray:
    """a^e (Montgomery in, Montgomery out) via the fused ladder kernel.

    Montgomery mul is a ring isomorphism, so mont(x)^e mont-wise =
    mont(x^e): callers use e = modulus - 2 for batched Fermat inversion
    (0 maps to 0 like JPrimeField.inv — select around it)."""
    assert e >= 1
    nbits = e.bit_length()
    bits = jnp.asarray(
        np.array([(e >> i) & 1 for i in range(nbits)], dtype=np.uint32)[:, None]
    )
    n_lm = jnp.asarray(np.asarray(int_to_limbs(field.modulus))[:, None])
    np_lm = jnp.asarray(np.asarray(int_to_limbs(field.nprime_int))[:, None])
    one_lm = jnp.asarray(np.asarray(int_to_limbs(field.mont_r))[:, None])
    return _run_tiled(
        _pow_kernel(nbits), (a,), (bits, n_lm, np_lm, one_lm), a.shape[:-1], interpret
    )


def _to_limb_major(x: jnp.ndarray, B: int, pad: int) -> jnp.ndarray:
    """(..., 16) batch-major -> (16, B+pad) limb-major tile input."""
    lm = jnp.moveaxis(x.reshape(B, NUM_LIMBS), -1, 0)
    return jnp.pad(lm, ((0, 0), (0, pad))) if pad else lm


def _run_tiled(kernel, batch_ins, const_ins, bshape, interpret: bool):
    """Shared pallas_call wrapper: flatten batch dims to the 128-lane
    axis, pad to TILE, run a 1-D grid, restore (..., 16)."""
    from jax.experimental import pallas as pl

    B = int(np.prod(bshape)) if bshape else 1
    pad = (-B) % TILE
    spec = pl.BlockSpec((NUM_LIMBS, TILE), lambda i: (0, i))
    out = pl.pallas_call(
        kernel,
        grid=((B + pad) // TILE,),
        in_specs=[spec] * len(batch_ins)
        + [pl.BlockSpec(c.shape, lambda i: (0, 0)) for c in const_ins],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((NUM_LIMBS, B + pad), jnp.uint32),
        interpret=interpret,
    )(*(_to_limb_major(x, B, pad) for x in batch_ins), *const_ins)
    return jnp.moveaxis(out[:, :B], 0, -1).reshape(bshape + (NUM_LIMBS,))


@partial(jax.jit, static_argnums=(0, 3))
def mont_mul(field, a: jnp.ndarray, b: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Montgomery product (a*b*R^-1 mod N) via the fused kernel.

    a, b: (..., 16) uint32 Montgomery limbs (broadcastable batch dims).
    field: a JPrimeField (supplies modulus / N' limb constants).
    interpret=True runs the Pallas interpreter (CPU differential tests).
    """
    n_lm = jnp.asarray(np.asarray(int_to_limbs(field.modulus))[:, None])
    np_lm = jnp.asarray(np.asarray(int_to_limbs(field.nprime_int))[:, None])

    bshape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    a = jnp.broadcast_to(a, bshape + (NUM_LIMBS,))
    b = jnp.broadcast_to(b, bshape + (NUM_LIMBS,))
    return _run_tiled(_kernel, (a, b), (n_lm, np_lm), bshape, interpret)
