"""Radix-2 NTT / iNTT over BN254 Fr on TPU lanes.

The reference's H-polynomial FFTs run inside snarkjs/rapidsnark over the
2^23-point domain (6.6M constraints -> next pow2; SURVEY.md §2.7, §7 step 3).
Here each stage is a reshape + one batched Montgomery mul + add/sub —
pure elementwise dataflow on (..., m, 16) limb tensors, `vmap`-able over
proof batches and shardable over the coefficient axis (all-to-all at the
stage boundary where the butterfly stride crosses the shard width).

Twiddle tables are generated ON DEVICE in log m doubling steps
(`_twiddle_powers`), so domain setup for 2^23 costs m Montgomery muls on
TPU instead of m Python bigint muls on host.

Differentially tested against the host oracle `snark.fft_host` (itself
exercised by the Groth16 host tests).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..field.bn254 import R, fr_domain_root, fr_inv
from ..field.jfield import FR


def _bit_reverse_perm(m: int) -> np.ndarray:
    k = m.bit_length() - 1
    idx = np.arange(m)
    rev = np.zeros(m, dtype=np.int64)
    for b in range(k):
        rev |= ((idx >> b) & 1) << (k - 1 - b)
    return rev


def _twiddle_powers(w: int, count: int) -> jnp.ndarray:
    """[w^0 .. w^(count-1)] in Montgomery form, built by log2(count) doublings:
    powers[j + 2^i] = powers[j] * w^(2^i)."""
    cur = FR.one_mont[None, :]
    e = 1
    while cur.shape[0] < count:
        factor = jnp.asarray(FR.to_mont_host(pow(w, e, R)))
        cur = jnp.concatenate([cur, FR.mul(cur, factor)], axis=0)
        e *= 2
    return cur[:count]


@lru_cache(maxsize=None)
def domain(log_m: int):
    """Precomputed tables for the 2^log_m domain (cached per process).

    Built under `ensure_compile_time_eval` so a first call from inside a
    traced function still produces concrete device arrays (safe to cache)."""
    m = 1 << log_m
    w = fr_domain_root(log_m)
    with jax.ensure_compile_time_eval():
        return {
            "m": m,
            "perm": _bit_reverse_perm(m),
            "tw": _twiddle_powers(w, m // 2),
            "tw_inv": _twiddle_powers(fr_inv(w), m // 2),
            "m_inv_mont": jnp.asarray(FR.to_mont_host(fr_inv(m))),
        }


def _ntt_core(x: jnp.ndarray, tw: jnp.ndarray, perm: np.ndarray) -> jnp.ndarray:
    """Iterative DIT butterfly ladder on (..., m, 16) Montgomery limbs.

    ONE `fori_loop` stage body with gather-based butterflies instead of an
    unrolled per-stage reshape ladder: XLA compile time scales with traced
    graph size, and at the production domain (2^23, log m = 23 stages) the
    unrolled form made every prover compile minutes-long.  All stage
    geometry (butterfly stride, twiddle stride) is computed from the
    traced stage index with shifts, so the compiled body is shared by all
    log m iterations."""
    m = x.shape[-2]
    if m == 1:
        return x
    log_m = m.bit_length() - 1
    x = x[..., perm, :]
    half = m // 2
    j = jnp.arange(half, dtype=jnp.int32)
    k = jnp.arange(m, dtype=jnp.int32)

    def stage(s, xs):
        length = jnp.left_shift(jnp.int32(1), s)
        mask = length - 1
        pos = j & mask
        i0 = ((j >> s) << (s + 1)) | pos  # butterfly low index
        i1 = i0 | length
        twj = pos << (log_m - 1 - s)  # stage twiddle stride m/(2*length)
        a = jnp.take(xs, i0, axis=-2)
        b = FR.mul(jnp.take(xs, i1, axis=-2), jnp.take(tw, twj, axis=0))
        cat = jnp.concatenate([FR.add(a, b), FR.sub(a, b)], axis=-2)
        # Inverse permutation: output k holds sum (bit s of k clear) or
        # difference (set) of butterfly ((k>>(s+1))<<s) | (k & mask).
        jk = (((k >> (s + 1)) << s) | (k & mask)) + ((k >> s) & 1) * half
        return jnp.take(cat, jk, axis=-2)

    return jax.lax.fori_loop(0, log_m, stage, x)


def ntt(x: jnp.ndarray, log_m: int) -> jnp.ndarray:
    """Evaluations of the coefficient vector on the 2^log_m roots domain."""
    d = domain(log_m)
    return _ntt_core(x, d["tw"], d["perm"])


def intt(x: jnp.ndarray, log_m: int) -> jnp.ndarray:
    d = domain(log_m)
    y = _ntt_core(x, d["tw_inv"], d["perm"])
    return FR.mul(y, d["m_inv_mont"])


@lru_cache(maxsize=None)
def _coset_powers(g: int, log_m: int) -> jnp.ndarray:
    with jax.ensure_compile_time_eval():
        return _twiddle_powers(g, 1 << log_m)


def coset_shift(coeffs: jnp.ndarray, g: int, log_m: int) -> jnp.ndarray:
    """coeff[i] *= g^i — moves evaluation onto the coset g*H (host scalar g)."""
    return FR.mul(coeffs, _coset_powers(g, log_m))
