"""Fused BN254 G1/G2 point ops as single Pallas TPU kernels.

docs/ROOFLINE.md round-4 addendum: with the Pallas Montgomery mul
(`ops.pallas_mont`) the field layer reaches ~136 M muls/s on a v5e chip
(7.9x the XLA path), but a Jacobian point add is ~16 muls issued as ~8
separate kernels/fusions — every intermediate round-trips HBM and every
launch re-pays the (B, 16) <-> (16, B) boundary transposes.  These
kernels run the COMPLETE curve op (all muls, adds, carries, and the
branchless infinity/equal/negated case selects of `curve.jcurve`) in
ONE pallas_call with all intermediates VMEM-resident: per point-add the
HBM traffic drops from ~19 mul-kernel round-trips to one read of the
operands and one write of the result.

Semantics mirror `curve.jcurve.JCurve` exactly (same dbl-2009-l and
add-2007-bl formulas, same (0, 0) affine / Z == 0 Jacobian infinity
encodings, same select ordering), and the differential tests pin every
case lane-for-lane against it (tests/test_pallas_curve.py).  The point
math is written once over a tiny field-ops object; the G1 instance works
on single (16, T) limb tiles, the G2 instance on (c0, c1) pairs with
Karatsuba Fq2 products (u^2 = -1, mirroring field.jfield.JFq2Ops.mul).

Layout: limb-major (16, T) tiles like `pallas_mont` — limbs on the
sublane axis, batch on the 128-wide lane axis.  Field helpers are the
limb-major mirrors of `field.jfield` (same Kogge-Stone carry ladder).

Mosaic notes (learned on hardware, rounds 4-5): `.at[].add` lowers to
an unsupported scatter — limb-0 adds are built by slice-and-concat
(NOT broadcasted_iota one-hots: an iota materialised while an outer
jit trace is live becomes a captured kernel constant, which
pallas_call rejects); kernels cannot capture traced constants — the
modulus / N' / R limbs are passed as (16, 1) operands and zeros are
derived from tracers (`a ^ a`), never `jnp.zeros`.

Reference analog: rapidsnark's Jacobian point kernels (its G1/G2 hot
loops); this is the TPU-native equivalent.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field.jfield import NUM_LIMBS, int_to_limbs
from .pallas_mont import TILE, _carry_lm, _mont_mul_math, _sub_raw_lm

G2_TILE = 128  # Fq2 kernels hold ~3x the live tiles; halve the batch tile


# ----------------------------------------------------- field layer (VMEM)


def _f_cond_sub(a, n_lm):
    d, borrow = _sub_raw_lm(a, n_lm)
    return jnp.where(borrow[None, :] != 0, a, d)


def _f_add(a, b, n_lm):
    return _f_cond_sub(_carry_lm(a + b, NUM_LIMBS), n_lm)


def _f_sub(a, b, n_lm):
    d, borrow = _sub_raw_lm(a, b)
    dn = _carry_lm(d + n_lm, NUM_LIMBS)
    return jnp.where(borrow[None, :] != 0, dn, d)


def _f_is_zero(a):
    """(16, T) -> (1, T) bool.  Canonical limbs are < 2^16 so the sum
    cannot overflow; a sum avoids relying on Mosaic's reduce_and.  The
    sum runs in i32 — Mosaic has no unsigned reductions (found on real
    hardware; interpret mode accepted the u32 sum)."""
    return jnp.sum(a.astype(jnp.int32), axis=0, keepdims=True) == 0


class _FqOps:
    """Limb-major Fq ops closed over the (16, 1) modulus constants.
    Elements are single (16, T) tiles."""

    def __init__(self, n_lm, np_lm, one_lm):
        self.n_lm, self.np_lm, self.one = n_lm, np_lm, one_lm

    def mul(self, a, b):
        return _mont_mul_math(a, b, self.n_lm, self.np_lm)

    def add(self, a, b):
        return _f_add(a, b, self.n_lm)

    def sub(self, a, b):
        return _f_sub(a, b, self.n_lm)

    def is_zero(self, a):
        return _f_is_zero(a)

    def sel(self, cond, a, b):
        return jnp.where(cond, a, b)

    def zero_like(self, a):
        # a ^ a, not jnp.zeros_like: a zeros literal materialised while
        # an outer jit trace is live becomes a captured kernel constant,
        # which pallas_call rejects (see pallas_mont._mul_wide_lm).
        return a ^ a

    def one_bcast(self, a):
        return jnp.broadcast_to(self.one, a.shape)


class _Fq2Ops:
    """Fq2 = Fq[u]/(u^2 + 1) on (c0, c1) tile pairs; Karatsuba product —
    the exact dataflow of field.jfield.JFq2Ops.mul."""

    def __init__(self, fq: _FqOps):
        self.fq = fq

    def mul(self, a, b):
        f = self.fq
        v0 = f.mul(a[0], b[0])
        v1 = f.mul(a[1], b[1])
        c0 = f.sub(v0, v1)
        c1 = f.sub(f.mul(f.add(a[0], a[1]), f.add(b[0], b[1])), f.add(v0, v1))
        return (c0, c1)

    def add(self, a, b):
        return (self.fq.add(a[0], b[0]), self.fq.add(a[1], b[1]))

    def sub(self, a, b):
        return (self.fq.sub(a[0], b[0]), self.fq.sub(a[1], b[1]))

    def is_zero(self, a):
        return _f_is_zero(a[0]) & _f_is_zero(a[1])

    def sel(self, cond, a, b):
        return (jnp.where(cond, a[0], b[0]), jnp.where(cond, a[1], b[1]))

    def zero_like(self, a):
        return (self.fq.zero_like(a[0]), self.fq.zero_like(a[1]))

    def one_bcast(self, a):
        # Montgomery 1 in Fq2 = (R, 0)
        return (jnp.broadcast_to(self.fq.one, a[0].shape), self.fq.zero_like(a[1]))


# ------------------------------------------------------------ point math


def _psel(f, cond, p, q):
    return tuple(f.sel(cond, x, y) for x, y in zip(p, q))


def _double_math(f, X1, Y1, Z1):
    """dbl-2009-l, mirror of JCurve.double (infinity -> infinity free)."""
    A = f.mul(X1, X1)
    B = f.mul(Y1, Y1)
    C = f.mul(B, B)
    XB = f.add(X1, B)
    XB2 = f.mul(XB, XB)
    YZ = f.mul(Y1, Z1)
    t = f.sub(f.sub(XB2, A), C)
    D = f.add(t, t)
    E = f.add(f.add(A, A), A)
    Fv = f.mul(E, E)
    X3 = f.sub(Fv, f.add(D, D))
    C8 = f.add(C, C)
    C8 = f.add(C8, C8)
    C8 = f.add(C8, C8)
    Y3 = f.sub(f.mul(E, f.sub(D, X3)), C8)
    Z3 = f.add(YZ, YZ)
    return X3, Y3, Z3


def _add_core_math(f, p, q, U1, U2, S1, S2, Z1Z2):
    """Mirror of JCurve._add_core: the shared tail of add / add_mixed,
    including the same-x / same-y / infinity case selects in the same
    order."""
    H = f.sub(U2, U1)
    Rr = f.sub(S2, S1)
    HH = f.mul(H, H)
    R2 = f.mul(Rr, Rr)
    HHH = f.mul(H, HH)
    V = f.mul(U1, HH)
    X3 = f.sub(f.sub(R2, HHH), f.add(V, V))
    Y3 = f.sub(f.mul(Rr, f.sub(V, X3)), f.mul(S1, HHH))
    Z3 = f.mul(Z1Z2, H)
    res = (X3, Y3, Z3)

    same_x = f.is_zero(H)
    same_y = f.is_zero(Rr)
    res = _psel(f, same_x & same_y, _double_math(f, *p), res)
    zero = f.zero_like(res[0])
    res = _psel(f, same_x & ~same_y, (zero, zero, zero), res)
    res = _psel(f, f.is_zero(p[2]), q, res)
    res = _psel(f, f.is_zero(q[2]), p, res)
    return res


def _add_math(f, p, q):
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = f.mul(Z1, Z1)
    Z2Z2 = f.mul(Z2, Z2)
    U1 = f.mul(X1, Z2Z2)
    U2 = f.mul(X2, Z1Z1)
    S1 = f.mul(f.mul(Y1, Z2), Z2Z2)
    S2 = f.mul(f.mul(Y2, Z1), Z1Z1)
    Z1Z2 = f.mul(Z1, Z2)
    return _add_core_math(f, p, q, U1, U2, S1, S2, Z1Z2)


def _add_mixed_math(f, p, a):
    X1, Y1, Z1 = p
    X2, Y2 = a
    Z1Z1 = f.mul(Z1, Z1)
    U2 = f.mul(X2, Z1Z1)
    S2 = f.mul(Y2, f.mul(Z1, Z1Z1))
    # q = from_affine(a): (0, 0) sentinel -> Z = 0, else Z = R (Mont 1)
    a_inf = f.is_zero(X2) & f.is_zero(Y2)
    zq = f.sel(a_inf, f.zero_like(X2), f.one_bcast(X2))
    return _add_core_math(f, p, (X2, Y2, zq), X1, U2, Y1, S2, Z1)


# ------------------------------------------------------- kernel factories

_OPS = {"add": _add_math, "add_mixed": _add_mixed_math, "double": _double_math}


def _g1_kernel(op):
    math_fn = _OPS[op]

    def kernel(*refs):
        ins, outs = refs[:-3], refs[-3:]
        n_lm, np_lm, one_lm = (r[:] for r in ins[-3:])
        f = _FqOps(n_lm, np_lm, one_lm)
        coords = [r[:] for r in ins[:-3]]
        if op == "add":
            r = math_fn(f, tuple(coords[:3]), tuple(coords[3:6]))
        elif op == "add_mixed":
            r = math_fn(f, tuple(coords[:3]), tuple(coords[3:5]))
        else:
            r = math_fn(f, *coords[:3])
        for o, v in zip(outs, r):
            o[:] = v

    return kernel


def _g2_kernel(op):
    math_fn = _OPS[op]

    def kernel(*refs):
        ins, outs = refs[:-6], refs[-6:]
        n_lm, np_lm, one_lm = (r[:] for r in ins[-3:])
        f = _Fq2Ops(_FqOps(n_lm, np_lm, one_lm))
        raw = [r[:] for r in ins[:-3]]
        pairs = [(raw[i], raw[i + 1]) for i in range(0, len(raw), 2)]
        if op == "add":
            r = math_fn(f, tuple(pairs[:3]), tuple(pairs[3:6]))
        elif op == "add_mixed":
            r = math_fn(f, tuple(pairs[:3]), tuple(pairs[3:5]))
        else:
            r = math_fn(f, *pairs[:3])
        for i, (c0, c1) in enumerate(r):
            outs[2 * i][:] = c0
            outs[2 * i + 1][:] = c1

    return kernel


_G1_KERNELS = {op: _g1_kernel(op) for op in _OPS}
_G2_KERNELS = {op: _g2_kernel(op) for op in _OPS}


# -------------------------------------------------------------- wrappers


def _consts(field):
    return (
        jnp.asarray(np.asarray(int_to_limbs(field.modulus))[:, None]),
        jnp.asarray(np.asarray(int_to_limbs(field.nprime_int))[:, None]),
        jnp.asarray(np.asarray(int_to_limbs(field.mont_r))[:, None]),
    )


def _run_g1(op, field, coords, interpret: bool, tile: int = TILE):
    """Flatten batch dims -> (16, B) limb-major, pad to `tile`, run the
    kernel over a 1-D grid, restore (..., 16)."""
    from jax.experimental import pallas as pl

    bshape = jnp.broadcast_shapes(*(c.shape[:-1] for c in coords))
    coords = tuple(jnp.broadcast_to(c, bshape + (NUM_LIMBS,)) for c in coords)
    B = int(np.prod(bshape)) if bshape else 1
    pad = (-B) % tile
    lm = []
    for c in coords:
        x = jnp.moveaxis(c.reshape(B, NUM_LIMBS), -1, 0)
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
        lm.append(x)

    spec = pl.BlockSpec((NUM_LIMBS, tile), lambda i: (0, i))
    cspec = pl.BlockSpec((NUM_LIMBS, 1), lambda i: (0, 0))
    outs = pl.pallas_call(
        _G1_KERNELS[op],
        grid=((B + pad) // tile,),
        in_specs=[spec] * len(lm) + [cspec] * 3,
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((NUM_LIMBS, B + pad), jnp.uint32)] * 3,
        interpret=interpret,
    )(*lm, *_consts(field))
    return tuple(jnp.moveaxis(o[:, :B], 0, -1).reshape(bshape + (NUM_LIMBS,)) for o in outs)


def _run_g2(op, fq2, coords, interpret: bool, tile: int = G2_TILE):
    """G2 coords are (..., 2, 16); split each into (c0, c1) limb-major
    tiles, run the Fq2 kernel, restore."""
    from jax.experimental import pallas as pl

    bshape = jnp.broadcast_shapes(*(c.shape[:-2] for c in coords))
    coords = tuple(jnp.broadcast_to(c, bshape + (2, NUM_LIMBS)) for c in coords)
    B = int(np.prod(bshape)) if bshape else 1
    pad = (-B) % tile
    lm = []
    for c in coords:
        flat = c.reshape(B, 2, NUM_LIMBS)
        for k in (0, 1):
            x = jnp.moveaxis(flat[:, k, :], -1, 0)
            if pad:
                x = jnp.pad(x, ((0, 0), (0, pad)))
            lm.append(x)

    spec = pl.BlockSpec((NUM_LIMBS, tile), lambda i: (0, i))
    cspec = pl.BlockSpec((NUM_LIMBS, 1), lambda i: (0, 0))
    outs = pl.pallas_call(
        _G2_KERNELS[op],
        grid=((B + pad) // tile,),
        in_specs=[spec] * len(lm) + [cspec] * 3,
        out_specs=[spec] * 6,
        out_shape=[jax.ShapeDtypeStruct((NUM_LIMBS, B + pad), jnp.uint32)] * 6,
        interpret=interpret,
    )(*lm, *_consts(fq2.fq))
    pts = []
    for i in range(3):
        c0 = jnp.moveaxis(outs[2 * i][:, :B], 0, -1)
        c1 = jnp.moveaxis(outs[2 * i + 1][:, :B], 0, -1)
        pts.append(jnp.stack([c0, c1], axis=-2).reshape(bshape + (2, NUM_LIMBS)))
    return tuple(pts)


@partial(jax.jit, static_argnums=(0, 3))
def g1_add(field, p, q, interpret: bool = False):
    """Complete Jacobian + Jacobian, one fused kernel.  p, q: (X, Y, Z)
    triples of (..., 16) uint32 Montgomery limbs."""
    return _run_g1("add", field, (*p, *q), interpret)


@partial(jax.jit, static_argnums=(0, 3))
def g1_add_mixed(field, p, a, interpret: bool = False):
    """Complete Jacobian + affine ((0,0) = infinity), one fused kernel."""
    return _run_g1("add_mixed", field, (*p, *a), interpret)


@partial(jax.jit, static_argnums=(0, 2))
def g1_double(field, p, interpret: bool = False):
    return _run_g1("double", field, p, interpret)


@partial(jax.jit, static_argnums=(0, 3))
def g2_add(fq2, p, q, interpret: bool = False):
    """G2 Jacobian + Jacobian over Fq2; coords (..., 2, 16)."""
    return _run_g2("add", fq2, (*p, *q), interpret)


@partial(jax.jit, static_argnums=(0, 3))
def g2_add_mixed(fq2, p, a, interpret: bool = False):
    return _run_g2("add_mixed", fq2, (*p, *a), interpret)


@partial(jax.jit, static_argnums=(0, 2))
def g2_double(fq2, p, interpret: bool = False):
    return _run_g2("double", fq2, p, interpret)
