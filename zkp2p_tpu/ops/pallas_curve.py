"""Fused BN254 G1 point ops as single Pallas TPU kernels.

docs/ROOFLINE.md round-4 addendum: with the Pallas Montgomery mul
(`ops.pallas_mont`) the field layer reaches ~136 M muls/s on a v5e chip
(7.9x the XLA path), but a Jacobian point add is ~16 muls issued as ~8
separate kernels/fusions — every intermediate round-trips HBM and every
launch re-pays the (B, 16) <-> (16, B) boundary transposes.  These
kernels run the COMPLETE curve op (all muls, adds, carries, and the
branchless infinity/equal/negated case selects of `curve.jcurve`) in
ONE pallas_call with all intermediates VMEM-resident: per point-add the
HBM traffic drops from ~19 mul-kernel round-trips to one read of the
operands and one write of the result.

Semantics mirror `curve.jcurve.JCurve` exactly (same dbl-2009-l and
add-2007-bl formulas, same (0, 0) affine / Z == 0 Jacobian infinity
encodings, same select ordering), and the differential tests pin every
case lane-for-lane against it (tests/test_pallas_curve.py).

Layout: limb-major (16, T) tiles like `pallas_mont` — limbs on the
sublane axis, batch on the 128-wide lane axis.  Field helpers are the
limb-major mirrors of `field.jfield` (same Kogge-Stone carry ladder).

Mosaic notes (learned on hardware, round 4): `.at[].add` lowers to an
unsupported scatter — one-hot adds are built from `broadcasted_iota`
comparisons; kernels cannot capture traced constants — the modulus /
N' / R limbs are passed as (16, 1) operands.

Reference analog: rapidsnark's Jacobian point kernels (its G1 hot
loop); this is the TPU-native equivalent.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..field.jfield import NUM_LIMBS, int_to_limbs
from .pallas_mont import TILE, _carry_lm, _mont_mul_math, _sub_raw_lm

# ----------------------------------------------------- field layer (VMEM)

_f_mul = _mont_mul_math


def _f_cond_sub(a, n_lm):
    d, borrow = _sub_raw_lm(a, n_lm)
    return jnp.where(borrow[None, :] != 0, a, d)


def _f_add(a, b, n_lm):
    return _f_cond_sub(_carry_lm(a + b, NUM_LIMBS), n_lm)


def _f_sub(a, b, n_lm):
    d, borrow = _sub_raw_lm(a, b)
    dn = _carry_lm(d + n_lm, NUM_LIMBS)
    return jnp.where(borrow[None, :] != 0, dn, d)


def _f_is_zero(a):
    """(16, T) -> (1, T) bool.  Canonical limbs are < 2^16 so the u32 sum
    cannot overflow; a sum avoids relying on Mosaic's reduce_and."""
    return jnp.sum(a, axis=0, keepdims=True) == 0


def _sel(cond, p, q):
    """cond: (1, T) bool; p, q: triples of (16, T)."""
    return tuple(jnp.where(cond, x, y) for x, y in zip(p, q))


# ------------------------------------------------------------ point math


def _double_math(X1, Y1, Z1, n_lm, np_lm):
    """dbl-2009-l, mirror of JCurve.double (infinity -> infinity free)."""
    A = _f_mul(X1, X1, n_lm, np_lm)
    B = _f_mul(Y1, Y1, n_lm, np_lm)
    C = _f_mul(B, B, n_lm, np_lm)
    XB = _f_add(X1, B, n_lm)
    XB2 = _f_mul(XB, XB, n_lm, np_lm)
    YZ = _f_mul(Y1, Z1, n_lm, np_lm)
    t = _f_sub(_f_sub(XB2, A, n_lm), C, n_lm)
    D = _f_add(t, t, n_lm)
    E = _f_add(_f_add(A, A, n_lm), A, n_lm)
    Fv = _f_mul(E, E, n_lm, np_lm)
    X3 = _f_sub(Fv, _f_add(D, D, n_lm), n_lm)
    C8 = _f_add(C, C, n_lm)
    C8 = _f_add(C8, C8, n_lm)
    C8 = _f_add(C8, C8, n_lm)
    Y3 = _f_sub(_f_mul(E, _f_sub(D, X3, n_lm), n_lm, np_lm), C8, n_lm)
    Z3 = _f_add(YZ, YZ, n_lm)
    return X3, Y3, Z3


def _add_core_math(p, q, U1, U2, S1, S2, Z1Z2, n_lm, np_lm):
    """Mirror of JCurve._add_core: the shared tail of add / add_mixed,
    including the same-x / same-y / infinity case selects in the same
    order."""
    H = _f_sub(U2, U1, n_lm)
    Rr = _f_sub(S2, S1, n_lm)
    HH = _f_mul(H, H, n_lm, np_lm)
    R2 = _f_mul(Rr, Rr, n_lm, np_lm)
    HHH = _f_mul(H, HH, n_lm, np_lm)
    V = _f_mul(U1, HH, n_lm, np_lm)
    X3 = _f_sub(_f_sub(R2, HHH, n_lm), _f_add(V, V, n_lm), n_lm)
    Y3 = _f_sub(
        _f_mul(Rr, _f_sub(V, X3, n_lm), n_lm, np_lm),
        _f_mul(S1, HHH, n_lm, np_lm),
        n_lm,
    )
    Z3 = _f_mul(Z1Z2, H, n_lm, np_lm)
    res = (X3, Y3, Z3)

    same_x = _f_is_zero(H)
    same_y = _f_is_zero(Rr)
    res = _sel(same_x & same_y, _double_math(*p, n_lm, np_lm), res)
    zero = jnp.zeros_like(res[0])
    res = _sel(same_x & ~same_y, (zero, zero, zero), res)
    res = _sel(_f_is_zero(p[2]), q, res)
    res = _sel(_f_is_zero(q[2]), p, res)
    return res


def _add_kernel(x1, y1, z1, x2, y2, z2, n_ref, np_ref, o0, o1, o2):
    n_lm, np_lm = n_ref[:], np_ref[:]
    X1, Y1, Z1 = x1[:], y1[:], z1[:]
    X2, Y2, Z2 = x2[:], y2[:], z2[:]
    Z1Z1 = _f_mul(Z1, Z1, n_lm, np_lm)
    Z2Z2 = _f_mul(Z2, Z2, n_lm, np_lm)
    U1 = _f_mul(X1, Z2Z2, n_lm, np_lm)
    U2 = _f_mul(X2, Z1Z1, n_lm, np_lm)
    S1 = _f_mul(_f_mul(Y1, Z2, n_lm, np_lm), Z2Z2, n_lm, np_lm)
    S2 = _f_mul(_f_mul(Y2, Z1, n_lm, np_lm), Z1Z1, n_lm, np_lm)
    Z1Z2 = _f_mul(Z1, Z2, n_lm, np_lm)
    r = _add_core_math((X1, Y1, Z1), (X2, Y2, Z2), U1, U2, S1, S2, Z1Z2, n_lm, np_lm)
    o0[:], o1[:], o2[:] = r


def _add_mixed_kernel(x1, y1, z1, x2, y2, n_ref, np_ref, one_ref, o0, o1, o2):
    n_lm, np_lm = n_ref[:], np_ref[:]
    X1, Y1, Z1 = x1[:], y1[:], z1[:]
    X2, Y2 = x2[:], y2[:]
    Z1Z1 = _f_mul(Z1, Z1, n_lm, np_lm)
    U2 = _f_mul(X2, Z1Z1, n_lm, np_lm)
    S2 = _f_mul(Y2, _f_mul(Z1, Z1Z1, n_lm, np_lm), n_lm, np_lm)
    # q = from_affine(a): (0, 0) sentinel -> Z = 0, else Z = R (Mont 1)
    a_inf = _f_is_zero(X2) & _f_is_zero(Y2)
    zq = jnp.where(a_inf, jnp.zeros_like(X2), jnp.broadcast_to(one_ref[:], X2.shape))
    r = _add_core_math((X1, Y1, Z1), (X2, Y2, zq), X1, U2, Y1, S2, Z1, n_lm, np_lm)
    o0[:], o1[:], o2[:] = r


def _double_kernel(x1, y1, z1, n_ref, np_ref, o0, o1, o2):
    r = _double_math(x1[:], y1[:], z1[:], n_ref[:], np_ref[:])
    o0[:], o1[:], o2[:] = r


# -------------------------------------------------------------- wrappers


def _run(kernel, field, coords, interpret: bool, tile: int = TILE):
    """Flatten batch dims -> (16, B) limb-major, pad to `tile`, run the
    kernel over a 1-D grid, restore (..., 16)."""
    from jax.experimental import pallas as pl

    bshape = jnp.broadcast_shapes(*(c.shape[:-1] for c in coords))
    coords = tuple(jnp.broadcast_to(c, bshape + (NUM_LIMBS,)) for c in coords)
    B = int(np.prod(bshape)) if bshape else 1
    pad = (-B) % tile
    lm = []
    for c in coords:
        x = jnp.moveaxis(c.reshape(B, NUM_LIMBS), -1, 0)
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
        lm.append(x)
    n_lm = jnp.asarray(np.asarray(int_to_limbs(field.modulus))[:, None])
    np_lm = jnp.asarray(np.asarray(int_to_limbs(field.nprime_int))[:, None])
    one_lm = jnp.asarray(np.asarray(int_to_limbs(field.mont_r))[:, None])
    consts = [n_lm, np_lm, one_lm] if kernel is _add_mixed_kernel else [n_lm, np_lm]

    spec = pl.BlockSpec((NUM_LIMBS, tile), lambda i: (0, i))
    cspec = pl.BlockSpec((NUM_LIMBS, 1), lambda i: (0, 0))
    outs = pl.pallas_call(
        kernel,
        grid=((B + pad) // tile,),
        in_specs=[spec] * len(lm) + [cspec] * len(consts),
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((NUM_LIMBS, B + pad), jnp.uint32)] * 3,
        interpret=interpret,
    )(*lm, *consts)
    return tuple(jnp.moveaxis(o[:, :B], 0, -1).reshape(bshape + (NUM_LIMBS,)) for o in outs)


@partial(jax.jit, static_argnums=(0, 3))
def g1_add(field, p, q, interpret: bool = False):
    """Complete Jacobian + Jacobian, one fused kernel.  p, q: (X, Y, Z)
    triples of (..., 16) uint32 Montgomery limbs."""
    return _run(_add_kernel, field, (*p, *q), interpret)


@partial(jax.jit, static_argnums=(0, 3))
def g1_add_mixed(field, p, a, interpret: bool = False):
    """Complete Jacobian + affine ((0,0) = infinity), one fused kernel."""
    return _run(_add_mixed_kernel, field, (*p, *a), interpret)


@partial(jax.jit, static_argnums=(0, 2))
def g1_double(field, p, interpret: bool = False):
    return _run(_double_kernel, field, p, interpret)
