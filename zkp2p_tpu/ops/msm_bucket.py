"""Sorted-prefix bucket MSM: Pippenger-class windows with zero scatter.

The classic Pippenger bucket method (rapidsnark's MSM hot loop) routes
each point into bucket d (its current window digit) and then combines
buckets with the suffix-sum triangle — ~(256/w + 2^w/n · small) adds per
point for large windows, far below the windowed-table formulation's
digit-plane accumulate.  Its TPU blocker is the bucket FILL: a random
scatter-accumulate Mosaic/XLA cannot express efficiently (SURVEY.md §7
hard part #2).

This module reformulates the fill as sort + prefix-scan + gather, all
TPU-native primitives:

  1. Per digit plane, argsort the points by digit (XLA sort — cheap
     next to curve arithmetic) and gather points into sorted order.
  2. Take INCLUSIVE PREFIX SUMS S_i of the sorted points under curve
     addition with the batch-affine adder (ops.msm_affine): reshape-
     halving Blelloch structure, 2n adds per plane, every add 4 muls +
     ~5 amortised inversion muls.
  3. The bucket triangle telescopes against the prefixes:

         sum_i d_i P_i  =  sum_{k=0}^{K-1} (S_n - S_{c_k}),

     where c_k = #{i : d_(i) <= k} (one vectorised searchsorted per
     plane) and K = 2^(w-1) signed buckets.  Terms with c_k = n vanish
     (S_n - S_n); k below the smallest digit contribute S_n (c_k = 0,
     S_0 = identity).  This needs only K gathers + K affine subtracts +
     a K-leaf affine tree reduce — no scatter anywhere.

Work per point at w=16 (16 planes, K = 32768 on an m = 2^19 domain):
~2 adds/plane for the prefix + ~2 total for the bucket side = ~34
affine adds vs ~40 Jacobian-equivalent adds for the signed w=8 windowed
path — and with NO multiples table the cost is batch-INDEPENDENT, so
single-proof latency (the north-star p50) gains as much as throughput.

The h MSM is the intended user: its coset-quotient scalars are
full-width (width-classing cannot touch it) and it dominates the
post-classing prover profile (docs/NEXT.md).  Differentially pinned
against the host oracle like every device tier."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..curve.jcurve import AffPoint, JacPoint, JCurve
from .msm import horner_fold_planes
from .msm_affine import affine_add_complete


def _gather(F, triple, idx):
    x, y, inf = triple
    return x[idx], y[idx], inf[idx]


def affine_prefix_incl(F, pts):
    """Inclusive prefix sums along axis 0 (power-of-2 length) under
    complete affine addition: out[i] = pts[0] + ... + pts[i].

    Reshape-halving recursion (the curve-add twin of
    msm_affine.excl_prefix_mul): pair adjacent elements (n/2 adds),
    recurse for the odd-position prefixes, one more add layer fixes the
    even positions — 2n adds total, log depth."""
    x, y, inf = pts
    n = x.shape[0]
    assert n & (n - 1) == 0, "affine_prefix_incl needs a power-of-2 length"
    if n == 1:
        return pts
    evens = (x[0::2], y[0::2], inf[0::2])
    odds = (x[1::2], y[1::2], inf[1::2])
    pair = affine_add_complete(F, evens, odds)
    sub = affine_prefix_incl(F, pair)  # S_1, S_3, S_5, ... (odd positions)
    # S_{2k} = S_{2k-1} + x_{2k}; S_{-1} = identity
    zero = jnp.zeros_like(sub[0][:1])
    shifted = (
        jnp.concatenate([zero, sub[0][:-1]]),
        jnp.concatenate([zero, sub[1][:-1]]),
        jnp.concatenate([jnp.ones_like(sub[2][:1]), sub[2][:-1]]),
    )
    even_pref = affine_add_complete(F, shifted, evens)
    out = []
    for e, o in zip(even_pref, sub):
        out.append(jnp.stack((e, o), axis=1).reshape(x.shape if e.ndim == x.ndim else inf.shape))
    return tuple(out)


def affine_tree_reduce(F, pts):
    """Sum a power-of-2 batch of affine triples along axis 0 by pairwise
    halving (log2(n) batched affine adds)."""
    x, y, inf = pts
    n = x.shape[0]
    assert n & (n - 1) == 0
    while n > 1:
        a = (x[0 : n // 2], y[0 : n // 2], inf[0 : n // 2])
        b = (x[n // 2 : n], y[n // 2 : n], inf[n // 2 : n])
        x, y, inf = affine_add_complete(F, a, b)
        n //= 2
    return x[0], y[0], inf[0]


def msm_bucket_affine(
    curve: JCurve,
    bases: AffPoint,
    mags: jnp.ndarray,
    negs: jnp.ndarray,
    window: int = 16,
) -> JacPoint:
    """MSM over signed base-2^window digit planes via sorted prefix
    buckets.  bases: affine (x, y) with (0, 0) infinity holes; mags/negs
    from `ops.msm.signed_digit_planes_from_limbs(..., window)`.  Returns
    one Jacobian point.  G1 only (same reason as msm_windowed_affine)."""
    assert curve.F.zero_limbs.ndim == 1, "bucket MSM is G1-only"
    F = curve.F
    n_planes = mags.shape[0]
    n = bases[0].shape[0]
    npad = (1 << (n - 1).bit_length()) - n
    bx, by = bases
    if npad:
        bx = jnp.pad(bx, [(0, npad), (0, 0)])
        by = jnp.pad(by, [(0, npad), (0, 0)])
        mags = jnp.pad(mags, [(0, 0), (0, npad)])
        negs = jnp.pad(negs, [(0, 0), (0, npad)])
    base_inf = F.is_zero(bx) & F.is_zero(by)
    K = 1 << (window - 1)

    def plane(_, xs):
        mp, np_ = xs  # (n,) digits + neg mask for this plane
        order = jnp.argsort(mp)
        ds = mp[order]
        px = bx[order]
        py = by[order]
        pinf = base_inf[order] | (ds == 0)
        py = F.select(np_[order], F.neg(py), py)
        zero = jnp.zeros_like(px)
        px = F.select(pinf, zero, px)
        py = F.select(pinf, zero, py)

        Sx, Sy, Sinf = affine_prefix_incl(F, (px, py, pinf))
        # S_ext[0] = identity so a gather at c_k = 0 reads S_0 = O
        Sx = jnp.concatenate([jnp.zeros_like(Sx[:1]), Sx])
        Sy = jnp.concatenate([jnp.zeros_like(Sy[:1]), Sy])
        Sinf = jnp.concatenate([jnp.ones_like(Sinf[:1]), Sinf])

        c = jnp.searchsorted(ds, jnp.arange(K, dtype=ds.dtype), side="right")
        g = _gather(F, (Sx, Sy, Sinf), c)
        total = (
            jnp.broadcast_to(Sx[-1], g[0].shape),
            jnp.broadcast_to(Sy[-1], g[1].shape),
            jnp.broadcast_to(Sinf[-1], g[2].shape),
        )
        diff = affine_add_complete(F, total, (g[0], F.neg(g[1]), g[2]))
        gx, gy, ginf = affine_tree_reduce(F, diff)
        return None, (gx, gy, ginf)

    _, (gx, gy, ginf) = jax.lax.scan(plane, None, (mags, negs))
    # gx/gy carry (0,0) on infinity lanes only if constructed so — force
    # the sentinel before from_affine
    zero = jnp.zeros_like(gx)
    planes_jac = curve.from_affine((F.select(ginf, zero, gx), F.select(ginf, zero, gy)))
    return horner_fold_planes(curve, curve.infinity(()), planes_jac, window)
