"""Batch-affine windowed MSM: the accumulate tier in affine coordinates.

The windowed MSM (ops.msm) spends ~80% of its field muls in the
accumulate step — one complete Jacobian+Jacobian add (16 muls) per
(digit-plane, lane) slot per chunk.  rapidsnark's hot loop avoids this
with batch-affine adds: an affine+affine add is 4 muls plus a shared
inversion, and the inversion amortises to ~5 muls/lane when every lane's
denominator is inverted through ONE Montgomery batch inversion.  This
module is the TPU formulation of that trick (SURVEY.md §7 step 3 /
docs/NEXT.md lever 1):

  - The per-chunk multiples table is normalised to AFFINE once per chunk
    (Jacobian scan build -> one batched Z inversion).  Witness-
    independent, so it amortises over a vmapped proof batch.
  - Accumulators live in affine (x, y, is_inf).  Each chunk step adds
    the selected table multiple with the lambda formulas; all
    (n_digits x lanes) denominators are inverted together.
  - Batch inversion = exclusive prefix AND suffix products via
    Blelloch-style reshape-halving (work ~2 muls/element per direction
    — NOT Hillis-Steele, whose n·log n work would cost more than the
    Jacobian adds it replaces), then ONE Fermat inversion of the total,
    fused into a single kernel launch on TPU (pallas_mont.mont_pow).
  - Exceptional lanes ride branchless selects exactly like curve.jcurve:
    accumulator-at-infinity (every lane's first add), addend-at-infinity
    (digit 0 / pruned-key padding), equal-x doubling, and P + (-P).

Work per accumulate slot: 4 lambda muls + ~5 amortised inversion muls
vs 16 for the Jacobian add — ~1.45x fewer field muls on the h MSM at
the bench shape (and the h MSM is ~85% of post-classing prover adds).

Like every device tier this is pinned against the host oracle: the
differential tests compare proofs/points bit-for-bit with the Jacobian
path (tests/test_msm_affine.py), the same discipline as the reference's
pinned proof vector (``test/ramp.test.js:193-196``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.jcurve import AffPoint, JacPoint, JCurve
from .msm import fold_lanes_per_curve, horner_fold_planes


def _one(F, like: jnp.ndarray) -> jnp.ndarray:
    return jnp.broadcast_to(F.one_mont, like.shape)


def excl_prefix_mul(F, x: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """Exclusive prefix products along axis 0 (power-of-2 length),
    seeded: out[i] = seed * x[0] * ... * x[i-1].

    Blelloch-style reshape-halving: each level pairs adjacent elements,
    recurses on the n/2 pair-products, then fills odd positions with one
    more mul — total work 2n muls (log-depth), vs n·log n for a
    Hillis-Steele scan."""
    n = x.shape[0]
    assert n & (n - 1) == 0, "excl_prefix_mul needs a power-of-2 length"
    if n == 1:
        return jnp.broadcast_to(seed, x.shape)
    pair = F.mul(x[0::2], x[1::2])
    pp = excl_prefix_mul(F, pair, seed)
    odd = F.mul(pp, x[0::2])
    return jnp.stack((pp, odd), axis=1).reshape(x.shape)


def batch_inverse(F, x: jnp.ndarray, fused_inv: bool = True) -> jnp.ndarray:
    """Invert every element of x (axis 0 = batch, power-of-2 length) with
    ONE field inversion: inv(x_i) = prefix_excl_i * (total^-1 *
    suffix_excl_i).  The suffix sweep is seeded with total^-1, so the
    combine is a single extra mul (~5 muls/element total).

    Fq2 (F has a .fq base field) takes the norm route instead:
    inv(a + bu) = (a - bu) * (a^2 + b^2)^-1 — the norm is never zero for
    a nonzero element (u^2 = -1 irreducible means -1 is a non-residue),
    so one Fq batch inversion of the norms serves the whole array at
    ~9 Fq muls/element vs ~15 for Fq2 prefix products.

    Zero elements are mapped to 1 inside the products so they cannot
    zero the total; their output slots are GARBAGE — callers must select
    around them (same contract as JPrimeField.inv's 0 -> 0)."""
    fq = getattr(F, "fq", None)
    if fq is not None:
        a, b = x[..., 0, :], x[..., 1, :]
        norm = fq.add(fq.square(a), fq.square(b))
        ninv = batch_inverse(fq, norm, fused_inv)
        return jnp.stack([fq.mul(a, ninv), fq.neg(fq.mul(b, ninv))], axis=-2)
    n = x.shape[0]
    if n & (n - 1):  # pad to power-of-2 with 1s (e.g. 3-plane narrow MSMs)
        pad = (1 << n.bit_length()) - n
        xp = jnp.concatenate([x, jnp.broadcast_to(F.one_mont, (pad,) + x.shape[1:])])
        return batch_inverse(F, xp, fused_inv)[:n]
    one = _one(F, x)
    safe = F.select(F.is_zero(x), one, x)
    pe = excl_prefix_mul(F, safe, F.one_mont)
    total = F.mul(pe[-1], safe[-1])
    tinv = F.inv_fused(total) if fused_inv else F.inv(total)
    sfx = jnp.flip(excl_prefix_mul(F, jnp.flip(safe, 0), tinv), 0)
    return F.mul(pe, sfx)


def jac_to_affine_batch(F, pts: JacPoint, fused_inv: bool = True) -> AffPoint:
    """Jacobian (X, Y, Z) with axis-0 batch (power-of-2) -> affine
    (x, y) = (X/Z^2, Y/Z^3); infinity (Z = 0) -> the (0, 0) sentinel.
    One batched inversion for the whole array."""
    X, Y, Z = pts
    inf = F.is_zero(Z)
    zinv = batch_inverse(F, Z, fused_inv)
    zi2 = F.square(zinv)
    x = F.mul(X, zi2)
    y = F.mul(Y, F.mul(zi2, zinv))
    zero = jnp.zeros_like(x)
    return F.select(inf, zero, x), F.select(inf, zero, y)


def _affine_add_den(F, a, b) -> tuple:
    """Phase 1 of the complete affine add: the denominator every lane
    contributes to the batch inversion, plus the case flags.  a, b are
    (x, y, is_inf) triples; exceptional lanes get denominator 1 so the
    batch product stays invertible."""
    ax, ay, ainf = a
    bx, by, binf = b
    live = ~ainf & ~binf
    x_eq = F.eq(ax, bx)
    y_eq = F.eq(ay, by)
    dbl = x_eq & y_eq & live
    # P + (-P), and doubling a 2-torsion point (y = 0): both -> infinity
    res_inf = (x_eq & ~y_eq & live) | (dbl & F.is_zero(ay))
    den = F.select(dbl, F.add(ay, ay), F.sub(bx, ax))
    den = F.select(res_inf | ~live, _one(F, den), den)
    return den, (dbl, res_inf)


def _affine_add_apply(F, a, b, dinv: jnp.ndarray, flags) -> tuple:
    """Phase 2: complete the add with the batch-inverted denominators.
    4 muls per lane (x1^2, lambda, lambda^2, y3)."""
    ax, ay, ainf = a
    bx, by, binf = b
    dbl, res_inf = flags
    axsq = F.square(ax)
    num = F.select(dbl, F.add(F.add(axsq, axsq), axsq), F.sub(by, ay))
    lam = F.mul(num, dinv)
    x3 = F.sub(F.sub(F.square(lam), ax), bx)
    y3 = F.sub(F.mul(lam, F.sub(ax, x3)), ay)
    zero = jnp.zeros_like(ax)
    rx = F.select(res_inf, zero, x3)
    ry = F.select(res_inf, zero, y3)
    rinf = res_inf
    # addend at infinity -> keep the accumulator; accumulator at
    # infinity -> take the addend (checked second so a double-infinity
    # lane stays at infinity with (0, 0) coords).
    rx = F.select(binf, ax, rx)
    ry = F.select(binf, ay, ry)
    rinf = jnp.where(binf, ainf, rinf)
    rx = F.select(ainf, bx, rx)
    ry = F.select(ainf, by, ry)
    rinf = jnp.where(ainf, binf, rinf)
    return rx, ry, rinf


def affine_add_complete(F, a, b, fused_inv: bool = True):
    """Complete affine add of two (x, y, is_inf) triples with any
    leading batch shape: phase-1 denominators are batch-inverted over
    the whole (power-of-2-padded) flattened batch, then phase 2
    completes.  The building block of the prefix-scan bucket MSM
    (ops.msm_bucket) and of ad-hoc affine folds."""
    elem = F.zero_limbs.shape
    den, flags = _affine_add_den(F, a, b)
    bshape = den.shape[: den.ndim - len(elem)]
    flat = int(np.prod(bshape)) if bshape else 1
    n_pad = (1 << (flat - 1).bit_length()) - flat if flat > 1 else 0
    d = den.reshape((flat,) + elem)
    if n_pad:
        d = jnp.concatenate([d, jnp.broadcast_to(F.one_mont, (n_pad,) + elem)])
    dinv = batch_inverse(F, d, fused_inv)[:flat].reshape(den.shape)
    return _affine_add_apply(F, a, b, dinv, flags)


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1)


def msm_windowed_affine(
    curve: JCurve,
    bases: AffPoint,
    mags: jnp.ndarray,
    negs: jnp.ndarray,
    lanes: int = 64,
    window: int = 4,
) -> JacPoint:
    """`ops.msm.msm_windowed_signed` with the accumulate tier in batch
    affine — same signed digit planes in, bit-identical Jacobian
    accumulator out (up to Jacobian coordinate equivalence; the
    differential tests compare through the host conversion).

    Works for G1 (Fq) and G2 (Fq2 — `batch_inverse` takes the norm
    route there, so a G2 accumulate add is ~4 Fq2 muls + ~9 amortised
    Fq muls vs ~16 Fq2 muls for the Jacobian add)."""
    F = curve.F
    elem = F.zero_limbs.shape
    n_digits = mags.shape[0]
    n = bases[0].shape[0]
    # lanes must keep the flattened (n_digits * lanes) denominator and
    # (n_table * lanes) table batches power-of-2 for the halving sweeps.
    lanes = _pow2_floor(min(lanes, n))
    pad = (-n) % lanes
    if pad:
        bases = tuple(jnp.pad(c, [(0, pad)] + [(0, 0)] * (c.ndim - 1)) for c in bases)
        mags = jnp.pad(mags, [(0, 0), (0, pad)])
        negs = jnp.pad(negs, [(0, 0), (0, pad)])
    steps = (n + pad) // lanes

    pts = tuple(c.reshape((steps, lanes) + c.shape[1:]) for c in bases)
    mag_t = mags.reshape(n_digits, steps, lanes).transpose(1, 0, 2)
    neg_t = negs.reshape(n_digits, steps, lanes).transpose(1, 0, 2)

    n_table = 1 << (window - 1)  # signed digits reach 2^(w-1)

    def accumulate(acc, xs):
        pt, digits, neg = xs
        base_jac = curve.from_affine(pt)

        def table_step(prev, _):
            return curve.add_mixed(prev, pt), prev

        # multiples 1..n_table as Jacobian, then ONE batched
        # normalisation to affine (witness-independent: vmap hoists it).
        _, stacked = jax.lax.scan(table_step, base_jac, None, length=n_table)
        flat = tuple(c.reshape((n_table * lanes,) + c.shape[2:]) for c in stacked)
        tx, ty = jac_to_affine_batch(F, flat)
        tshape = (n_table + 1, lanes) + elem
        tx = jnp.concatenate([jnp.zeros_like(tx[:lanes]), tx]).reshape(tshape)
        ty = jnp.concatenate([jnp.zeros_like(ty[:lanes]), ty]).reshape(tshape)

        lane_ix = jnp.arange(lanes)[None, :]
        sx = tx[digits, lane_ix]
        sy = ty[digits, lane_ix]
        sy = F.select(neg, F.neg(sy), sy)  # -|d|*P = (x, -y); -0 = 0
        # infinity = the digit-0 row AND infinity bases (pruned-key /
        # pad lanes), both of which normalise to the (0, 0) sentinel
        sinf = F.is_zero(sx) & F.is_zero(sy)
        addend = (sx, sy, sinf)

        den, flags = _affine_add_den(F, acc, addend)
        dinv = batch_inverse(F, den.reshape((n_digits * lanes,) + elem)).reshape(den.shape)
        return _affine_add_apply(F, acc, addend, dinv, flags), None

    zero = jnp.zeros((n_digits, lanes) + F.zero_limbs.shape, dtype=jnp.uint32)
    acc0 = (zero, zero, jnp.ones((n_digits, lanes), dtype=bool))
    (ax, ay, ainf), _ = jax.lax.scan(accumulate, acc0, (pts, mag_t, neg_t))

    # inf lanes carry (0, 0) by construction -> from_affine's sentinel
    partials = curve.from_affine((ax, ay))
    per_lane = horner_fold_planes(
        curve, curve.infinity((lanes,)), tuple(c for c in partials), window
    )
    return fold_lanes_per_curve(curve, per_lane, lanes)
