"""Multi-scalar multiplication on TPU lanes.

The reference's MSMs live inside snarkjs `groth16 prove` (WASM) and
rapidsnark (C++ threads + x86 asm): 4 G1 MSMs + 1 G2 MSM over ~6.6M
scalars per proof (SURVEY.md §3.1 hot loop 2).  TPUs have no fast random
scatter, so bucket accumulation is reformulated as branchless dataflow
(SURVEY.md §7 hard part #2):

  1. 256 bit-plane partial sums, all planes in parallel as a 256-lane
     batch axis: plane_sums[p] = sum_i bit[p,i] * P_i.
  2. The base-point axis is consumed chunk by chunk inside ONE `lax.scan`
     (fixed chunk shape -> one compiled body reused for every chunk;
     XLA compile time scales with traced-graph size, so shape reuse is a
     design constraint here, not a nicety).  Each chunk is masked and
     pairwise tree-reduced (log2(chunk) complete adds).
  3. A second 256-step scan folds the plane sums MSB-first:
     acc = 2*acc + plane_sums[p].

Cost: ~256 point-adds per base point, fully vectorised, zero scatter /
sort / data-dependent control flow.  (Windowed Pippenger via sorted
segment scans is the planned fast path in kernels/; this is the portable
XLA formulation that the rest of the stack is tested against.)

Sharding: split the N axis across devices, run the same scan per shard,
then one `add` tree over the per-device partials (an ICI all-reduce with
the group op) — see zkp2p_tpu.parallel.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..curve.jcurve import AffPoint, JacPoint, JCurve
from ..field.jfield import LIMB_BITS

SCALAR_BITS = 256


def bit_planes_from_limbs(limbs: jnp.ndarray) -> jnp.ndarray:
    """Standard-form scalar limbs (..., n, 16) uint32 -> (256, ..., n) planes,
    MSB first (plane 0 = bit 255).

    Device-side twin of `jcurve.scalar_bit_planes` so witness values produced
    on device never round-trip to the host.  Vectorised (one shift + one
    transpose), not a 256-step Python loop — trace size matters."""
    shifts = jnp.arange(LIMB_BITS, dtype=jnp.uint32)
    bits = (limbs[..., None] >> shifts) & 1  # (..., 16, 16) limb x bit
    flat = bits.reshape(*limbs.shape[:-1], SCALAR_BITS)  # LSB first
    flat = jnp.flip(flat, axis=-1)  # MSB first
    return jnp.moveaxis(flat, -1, 0)


def tree_reduce(curve: JCurve, pts: JacPoint, axis_len: int) -> JacPoint:
    """Sum `axis_len` Jacobian points along axis -1-of-batch (the last batch
    axis) by pairwise halving; all other batch axes stay vectorised."""
    n = axis_len
    ax = -1 - curve.F.zero_limbs.ndim  # the reduced batch axis
    while n > 1:
        if n % 2:
            pad_cfg = [(0, 0)] * pts[0].ndim
            pad_cfg[ax] = (0, 1)
            pts = tuple(jnp.pad(c, pad_cfg) for c in pts)  # zero = infinity
            n += 1
        lo = tuple(jax.lax.slice_in_dim(c, 0, n // 2, axis=ax) for c in pts)
        hi = tuple(jax.lax.slice_in_dim(c, n // 2, n, axis=ax) for c in pts)
        pts = curve.add(lo, hi)
        n //= 2
    return tuple(jnp.squeeze(c, axis=ax) for c in pts)


def fold_lanes_per_curve(curve: JCurve, per_lane: JacPoint, lanes: int) -> JacPoint:
    """Final lane fold of a windowed MSM, shared by the Jacobian and
    batch-affine tiers.  G1 takes the pairwise tree — log2(lanes)
    halving adds instead of a `lanes`-step scan (cheaper dispatch on
    1-core hosts, wider batches on TPU).  G2 joins the tree only when
    the pallas point kernels are in use: with the XLA formulas the tree
    inlines log2(lanes) copies of the Fq2 add graph and XLA:CPU compile
    time blows up (r4 rehearsal: the G2 executable alone compiled
    >400 s with the tree fold) — including bench's forced-XLA fallback
    re-exec on a TPU backend, which must stay compilable."""
    if curve.F.zero_limbs.ndim == 1 or curve._pallas():
        return tree_reduce(curve, per_lane, lanes)

    def fold(acc, p):
        return curve.add(acc, p), None

    total, _ = jax.lax.scan(fold, curve.infinity(()), per_lane)
    return total


def horner_fold_planes(curve: JCurve, init: JacPoint, planes_stacked, window: int) -> JacPoint:
    """MSB-first Horner fold over stacked digit-plane partials (leading
    axis = planes): acc = 2^window * acc + plane.  Shared by the
    windowed, batch-affine, and bucket MSMs.

    The window doublings are a nested lax.scan: ONE compiled double
    graph instead of `window` inlined copies — for G2 (Fq2 limb towers)
    the unrolled form alone pushed XLA:CPU past the driver's dryrun
    budget (MULTICHIP_r04 rehearsal: >300 s compiling jit_local)."""

    def fold(acc, ps):
        def dbl(a, _):
            return curve.double(a), None

        acc, _ = jax.lax.scan(dbl, acc, None, length=window)
        return curve.add(acc, ps), None

    out, _ = jax.lax.scan(fold, init, planes_stacked)
    return out


def digit_planes_from_limbs(limbs: jnp.ndarray, window: int = 4) -> jnp.ndarray:
    """Standard-form scalar limbs (..., n, 16) -> (256/window, ..., n)
    base-2^window digit planes, most significant first.  Vectorised like
    `bit_planes_from_limbs`."""
    assert 16 % window == 0
    per_limb = 16 // window
    shifts = jnp.arange(per_limb, dtype=jnp.uint32) * window
    mask = jnp.uint32((1 << window) - 1)
    digits = (limbs[..., None] >> shifts) & mask  # (..., 16, per_limb)
    flat = digits.reshape(*limbs.shape[:-1], 16 * per_limb)  # LS digit first
    flat = jnp.flip(flat, axis=-1)
    return jnp.moveaxis(flat, -1, 0)


def signed_digit_planes_from_limbs(limbs: jnp.ndarray, window: int = 4):
    """Standard-form scalar limbs (..., n, 16) -> signed base-2^window
    digits, most significant first: (mags, negs) with
    mags (256/window, ..., n) uint32 in [0, 2^(window-1)] and negs a bool
    mask for negated digits.

    Recoding d -> d' in [-(2^(w-1) - 1), 2^(w-1)]: LSB-first, carry into
    the next digit whenever d + carry > 2^(w-1).  The multiples table
    then only needs 2^(w-1) entries — HALF the unsigned table — because
    -|d'|*P is (x, -y) for free.  The top digit cannot overflow for
    BN254 Fr scalars (< 2^254, and the final carry is absorbed by the
    unused high bits: the top base-2^w digit of an Fr scalar is at most
    0x30, so digit + carry never exceeds 2^(w-1)).

    Carry resolution is a 5-pass Kogge-Stone over the digit axis
    (generate = d > half, propagate = d == half), vectorised over the
    scalar batch — no sequential scan."""
    assert 16 % window == 0
    n_digits = 256 // window
    half = jnp.uint32(1 << (window - 1))
    full = jnp.uint32(1 << window)

    planes = digit_planes_from_limbs(limbs, window)  # (n_digits, ..., n) MSB first
    d = jnp.flip(planes, axis=0)  # LSB first for the carry recurrence
    # carry c[i+1] arrives at digit i+1 iff d[i] + c[i] > half:
    #   generate g = d > half, propagate p = (d == half)
    g = d > half
    p = d == half
    k = 1
    gg, pp = g, p
    while k < n_digits:
        shifted_g = jnp.concatenate([jnp.zeros_like(gg[:k]), gg[:-k]], axis=0)
        shifted_p = jnp.concatenate([jnp.zeros_like(pp[:k]), pp[:-k]], axis=0)
        gg = gg | (pp & shifted_g)
        pp = pp & shifted_p
        k *= 2
    carry_in = jnp.concatenate([jnp.zeros_like(gg[:1]), gg[:-1]], axis=0)
    e = d + carry_in.astype(jnp.uint32)  # in [0, 2^w]
    neg = e > half
    mag = jnp.where(neg, full - e, e)  # in [0, half]
    mags = jnp.flip(mag, axis=0)
    negs = jnp.flip(neg, axis=0)
    return mags, negs


# ---------------------------------------------------------------------------
# GLV endomorphism decomposition (field.bn254 derives the constants):
# every Fr scalar splits into two ~128-bit half-scalars k = k1 + k2*lam,
# and k*P = k1*P + k2*phi(P) with phi(x, y) = (beta*x, y).  A length-n
# G1 MSM becomes a length-2n MSM over HALF the signed digit planes —
# the per-scalar sequential work (Horner fold, Pippenger windows/suffix)
# halves, which is what the latency-bound small/medium MSMs and the
# bucket triangle pay for.
#
# The decomposer is fully vectorised 16-bit-limb arithmetic (it must run
# INSIDE _h_and_planes / vmap: the h scalars are born on device from the
# NTT ladder, and a host round-trip per witness would serialize the
# batch).  All multiprecision values are mod-2^256 wraparound; the final
# half-scalars are tiny (< 2^GLV_MAX_BITS), so the top bit is the sign.

from ..field.bn254 import (  # noqa: E402 — grouped with their consumers
    GLV_BETA,
    GLV_K1_TERMS,
    GLV_K2_TERMS,
    GLV_MU1,
    GLV_MU2,
    GLV_SHIFT,
    glv_num_planes,
)

_GLV_SHIFT_LIMBS = GLV_SHIFT // LIMB_BITS
_GLV_C_LIMBS = 9  # Barrett quotients are < 2^129 (8 limbs) + 1 margin
NUM_LIMBS_GLV = 16  # half-scalars stay in the 16-limb layout (top half zero)


def _mp_carry_stack(cols):
    """Carry-propagate a list of per-limb column sums (each < 2^31) into
    canonical 16-bit limbs, dropping the final carry (mod 2^(16*len))."""
    out = []
    carry = None
    for c in cols:
        cur = c if carry is None else c + carry
        out.append(cur & jnp.uint32(0xFFFF))
        carry = cur >> 16
    return jnp.stack(out, axis=-1)


def _mp_mul_const(limbs: jnp.ndarray, const: int, out_limbs: int) -> jnp.ndarray:
    """(..., L) 16-bit limbs * python-int constant -> (..., out_limbs)
    limbs of the product mod 2^(16*out_limbs).  Exact carries from limb
    0 up, so high slices (Barrett shifts) are exact floors."""
    zero = jnp.zeros(limbs.shape[:-1], jnp.uint32)
    cols = [zero] * out_limbs
    L1 = limbs.shape[-1]
    j = 0
    while (const >> (16 * j)) or j == 0:
        cj = (const >> (16 * j)) & 0xFFFF
        if cj:
            prod = limbs * jnp.uint32(cj)  # 16x16-bit -> fits u32
            lo, hi = prod & jnp.uint32(0xFFFF), prod >> 16
            for i in range(L1):
                if j + i < out_limbs:
                    cols[j + i] = cols[j + i] + lo[..., i]
                if j + i + 1 < out_limbs:
                    cols[j + i + 1] = cols[j + i + 1] + hi[..., i]
        j += 1
    return _mp_carry_stack(cols)


def _mp_add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    n = a.shape[-1]
    return _mp_carry_stack([a[..., i] + b[..., i] for i in range(n)])


def _mp_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b mod 2^(16n) via two's complement (a + ~b + 1)."""
    n = a.shape[-1]
    cols = [a[..., i] + (b[..., i] ^ jnp.uint32(0xFFFF)) for i in range(n)]
    cols[0] = cols[0] + jnp.uint32(1)
    return _mp_carry_stack(cols)


def _mp_neg(a: jnp.ndarray) -> jnp.ndarray:
    return _mp_sub(jnp.zeros_like(a), a)


def glv_decompose_limbs(limbs: jnp.ndarray):
    """Standard-form Fr scalar limbs (..., 16) u32 -> (mag1, mag2, neg1,
    neg2): half-scalar magnitude limbs (..., 16) and sign masks (...,)
    with k = (-1)^neg1 * mag1 + (-1)^neg2 * mag2 * lambda (mod r).

    Integer-for-integer identical to the host oracle
    ``field.bn254.glv_decompose`` (floor-Barrett quotients, mod-2^256
    accumulation) — the differential tests diff all three kernels."""
    c1 = _mp_mul_const(limbs, GLV_MU1, _GLV_SHIFT_LIMBS + _GLV_C_LIMBS)[..., _GLV_SHIFT_LIMBS:]
    c2 = _mp_mul_const(limbs, GLV_MU2, _GLV_SHIFT_LIMBS + _GLV_C_LIMBS)[..., _GLV_SHIFT_LIMBS:]
    k1 = limbs
    for c, (mag, sub) in zip((c1, c2), GLV_K1_TERMS):
        t = _mp_mul_const(c, mag, NUM_LIMBS_GLV)
        k1 = _mp_sub(k1, t) if sub else _mp_add(k1, t)
    k2 = jnp.zeros_like(limbs)
    for c, (mag, sub) in zip((c1, c2), GLV_K2_TERMS):
        t = _mp_mul_const(c, mag, NUM_LIMBS_GLV)
        k2 = _mp_sub(k2, t) if sub else _mp_add(k2, t)
    neg1 = (k1[..., -1] >> 15).astype(bool)
    neg2 = (k2[..., -1] >> 15).astype(bool)
    mag1 = jnp.where(neg1[..., None], _mp_neg(k1), k1)
    mag2 = jnp.where(neg2[..., None], _mp_neg(k2), k2)
    return mag1, mag2, neg1, neg2


def glv_signed_planes_from_limbs(limbs: jnp.ndarray, window: int = 4):
    """Standard-form Fr limbs (..., n, 16) -> GLV signed digit planes
    (mags, negs) of shape (glv_num_planes(window), ..., 2n): the first n
    columns are k1's digits (for bases P_i), the last n are k2's (for
    the endomorphism-mapped bases phi(P_i) — see `glv_extend_bases`).
    A negative half-scalar flips every digit's sign mask (-(sum d_j 2^jw)
    = sum (-d_j) 2^jw)."""
    mag1, mag2, neg1, neg2 = glv_decompose_limbs(limbs)
    nk = glv_num_planes(window)
    m1, s1 = signed_digit_planes_from_limbs(mag1, window)
    m2, s2 = signed_digit_planes_from_limbs(mag2, window)
    m1, s1 = m1[-nk:], s1[-nk:]
    m2, s2 = m2[-nk:], s2[-nk:]
    mags = jnp.concatenate([m1, m2], axis=-1)
    negs = jnp.concatenate([s1 ^ neg1, s2 ^ neg2], axis=-1)
    return mags, negs


def glv_extend_bases(bases: AffPoint) -> AffPoint:
    """G1 affine base limbs (x, y) with leading axis n -> the GLV-doubled
    (2n) base set [P_0..P_{n-1}, phi(P_0)..phi(P_{n-1})] with phi(x, y) =
    (beta*x, y).  One batched Fq mul; (0, 0) infinity holes map to
    (0, 0).  Key-dependent only, so callers cache it per proving key."""
    from ..field.jfield import FQ

    x, y = bases
    beta = jnp.asarray(FQ.to_mont_host(GLV_BETA))
    phix = FQ.mul(x, jnp.broadcast_to(beta, x.shape))
    return jnp.concatenate([x, phix]), jnp.concatenate([y, y])


def glv_sel(sel: jnp.ndarray, n: int) -> jnp.ndarray:
    """Lift a base/plane column selector over n points to the GLV-doubled
    layout: position j also selects its endomorphism twin j + n."""
    return jnp.concatenate([jnp.asarray(sel), jnp.asarray(sel) + n])


def msm_windowed_signed(
    curve: JCurve,
    bases: AffPoint,
    mags: jnp.ndarray,
    negs: jnp.ndarray,
    lanes: int = 64,
    window: int = 4,
) -> JacPoint:
    """`msm_windowed` on signed digits: the per-chunk multiples table is
    2^(w-1) entries instead of 2^w - 1 (built with half the adds), and a
    negated digit flips the selected point's Y (one conditional field
    subtract — negligible next to a curve add).  The table cost is the
    batch-amortised term of the windowed MSM (it is witness-independent
    under vmap), so halving it is what makes w=8 win at small batches
    too: ~63.8 adds/pt at batch=4 vs 95.5 unsigned (see the bench
    arming note in prover.groth16_tpu)."""
    return _msm_windowed_impl(curve, bases, mags, negs, lanes, window)


def default_lanes(n: int, cap: int = 4096) -> int:
    """Lane width for an n-point MSM: TPU ops are latency-bound until the
    per-step batch is ~10^5+ elements (measured: FR.mul at B=4096 runs at
    <5% of its B=1M throughput), so spend points on WIDE steps — subject
    to keeping enough scan steps (>=16) to amortise the windowed table."""
    return max(64, min(cap, n // 16))


def msm_windowed(curve: JCurve, bases: AffPoint, digit_planes: jnp.ndarray, lanes: int = 64, window: int = 4) -> JacPoint:
    """Windowed MSM: ~(2^window - 2 + 256/window) adds per point instead of
    256 (window=4 -> ~78, a 3.3x work cut vs `msm`).

    Per chunk step the (lanes,) points expand into a 2^window multiples
    table (built with 2^window - 2 adds on narrow lanes); each digit plane
    then SELECTS its multiple (cheap wheres) and does one masked
    accumulate on the (n_planes, lanes) batch.  Same zero-scatter dataflow,
    same one-adder-per-scan-body compile discipline."""
    return _msm_windowed_impl(curve, bases, digit_planes, None, lanes, window)


def _msm_windowed_impl(
    curve: JCurve,
    bases: AffPoint,
    planes_in: jnp.ndarray,
    negs: Optional[jnp.ndarray],
    lanes: int,
    window: int,
) -> JacPoint:
    """Shared body of `msm_windowed` (negs=None: unsigned 2^w - 1 table +
    masked accumulate — kept op-for-op identical so the sharded/dryrun
    executables and their compile cache are untouched) and
    `msm_windowed_signed` (half table + Y negation)."""
    signed = negs is not None
    n_digits = planes_in.shape[0]
    n = bases[0].shape[0]
    lanes = min(lanes, n)
    pad = (-n) % lanes
    if pad:
        bases = tuple(jnp.pad(c, [(0, pad)] + [(0, 0)] * (c.ndim - 1)) for c in bases)
        planes_in = jnp.pad(planes_in, [(0, 0), (0, pad)])
        if signed:
            negs = jnp.pad(negs, [(0, 0), (0, pad)])
    steps = (n + pad) // lanes

    pts = tuple(c.reshape((steps, lanes) + c.shape[1:]) for c in bases)
    planes = planes_in.reshape(n_digits, steps, lanes).transpose(1, 0, 2)

    # table entries 1..n_table (signed digits only reach 2^(w-1))
    n_table = (1 << (window - 1)) if signed else (1 << window) - 1
    F = curve.F

    def accumulate(acc, xs):
        # the neg planes ride the scan only on the signed path — the
        # unsigned jaxpr must stay IDENTICAL to keep the sharded/dryrun
        # compile-cache entries valid
        if signed:
            pt, digits, neg = xs
        else:
            (pt, digits), neg = xs, None  # pt: (lanes, elem) affine
        base_jac = curve.from_affine(pt)

        def table_step(prev, _):
            nxt = curve.add_mixed(prev, pt)
            return nxt, prev

        # multiples 1..n_table: scan collects [1P, 2P, ...] (ys = prev)
        last, stacked = jax.lax.scan(table_step, base_jac, None, length=n_table)
        table = tuple(
            jnp.concatenate([jnp.zeros_like(c[:1]), c], axis=0) for c in stacked
        )  # index 0 = infinity (Z = 0)

        lane_ix = jnp.arange(digits.shape[-1])[None, :]
        sel = list(c[digits, lane_ix] for c in table)  # per-lane multiple -> (n_digits, lanes, elem)
        if signed:
            # negate Y where the digit is negative; F.neg keeps -0 = 0,
            # so infinity lanes (digit 0) stay (0, 0, 0).  The mask
            # broadcasts over the element dims (one for G1 limbs, two
            # for G2 Fq2 pairs).  Digit 0 selects the Z = 0 infinity
            # entry, which curve.add's case selects pass through — no
            # explicit mask needed.
            mask = neg.reshape(neg.shape + (1,) * (sel[1].ndim - neg.ndim))
            sel[1] = jnp.where(mask, F.neg(sel[1]), sel[1])
            return curve.add(acc, tuple(sel)), None
        nxt = curve.add(acc, tuple(sel))
        return curve.select(digits != 0, nxt, acc), None

    if signed:
        neg_t = negs.reshape(n_digits, steps, lanes).transpose(1, 0, 2)
        xs_in = (pts, planes, neg_t)
    else:
        xs_in = (pts, planes)
    partials, _ = jax.lax.scan(accumulate, curve.infinity((n_digits, lanes)), xs_in)

    per_lane = horner_fold_planes(
        curve, curve.infinity((lanes,)), tuple(c for c in partials), window
    )
    return fold_lanes_per_curve(curve, per_lane, lanes)


def msm(curve: JCurve, bases: AffPoint, bit_planes: jnp.ndarray, lanes: int = 64) -> JacPoint:
    """MSM: sum_i s_i * P_i -> one Jacobian point.

    bases: affine limb arrays, leading axis N ((0,0) lanes = infinity, e.g.
    zkey padding or public-wire holes in the c_query).
    bit_planes: (256, N) uint32 from `bit_planes_from_limbs` /
    `scalar_bit_planes`.

    Three nested scans, each with a ONE-adder body (XLA compile time scales
    with traced-graph size, so every body is exactly one curve-add graph):
      1. over N/lanes steps: masked `add_mixed` into (256, lanes) partials
      2. over 256 planes per lane: MSB-first double-and-add fold -> (lanes,)
      3. over lanes: plain add fold -> scalar point
    Work: ~256 mixed-adds per base point; step granularity (256·lanes
    lanes per step) keeps the VPU busy and loop overhead amortised."""
    n = bases[0].shape[0]
    lanes = min(lanes, n)
    pad = (-n) % lanes
    if pad:
        bases = tuple(jnp.pad(c, [(0, pad)] + [(0, 0)] * (c.ndim - 1)) for c in bases)
        bit_planes = jnp.pad(bit_planes, [(0, 0), (0, pad)])
    steps = (n + pad) // lanes

    # point i = step*lanes + lane; planes: (steps, 256, lanes)
    pts = tuple(c.reshape((steps, lanes) + c.shape[1:]) for c in bases)
    planes = bit_planes.reshape(SCALAR_BITS, steps, lanes).transpose(1, 0, 2)

    def accumulate(acc, xs):
        pt, bits = xs  # pt: (lanes, elem) affine, bits: (256, lanes)
        bcast = tuple(jnp.broadcast_to(c[None], (SCALAR_BITS,) + c.shape) for c in pt)
        nxt = curve.add_mixed(acc, bcast)
        return curve.select(bits.astype(bool), nxt, acc), None

    partials, _ = jax.lax.scan(accumulate, curve.infinity((SCALAR_BITS, lanes)), (pts, planes))

    def fold_planes(acc, ps):
        return curve.add(curve.double(acc), ps), None

    per_lane, _ = jax.lax.scan(
        fold_planes, curve.infinity((lanes,)), tuple(c for c in partials)
    )

    def fold_lanes(acc, p):
        return curve.add(acc, p), None

    total, _ = jax.lax.scan(fold_lanes, curve.infinity(()), per_lane)
    return total
