"""Vectorised BN254 field arithmetic on TPU lanes (JAX).

This module is the TPU mirror of rapidsnark's x86-assembly field library and
of the circom bigint gadgets the reference leans on
(``zk-email-verify-circuits/bigint.circom``, ``fp.circom:26-85``).  TPUs have
no native 64x64 multiply, so field elements are **16 limbs x 16 bits in
uint32 lanes**: a 16x16-bit product fits a uint32 exactly, and its lo/hi
16-bit halves are accumulated in separate uint32 planes (each partial < 2^16,
so thousands can be summed before carry propagation).  All ops are shape-
polymorphic over leading batch dims and therefore `vmap`/`shard_map`-friendly;
multiplication is Montgomery (SOS: full schoolbook product, then one
Montgomery reduction), so a field mul is three 16-limb convolutions — pure
elementwise uint32 mul/add/shift that XLA vectorises onto the VPU.

Layout contract (shared with the host oracle ``zkp2p_tpu.field.bn254``):
  value = sum(limb[i] << (16*i)),  limb[i] < 2^16,  canonical (< modulus).
"""

from __future__ import annotations

from functools import lru_cache


import jax
import jax.numpy as jnp
import numpy as np

from .bn254 import MONT_R, P, R

LIMB_BITS = 16
NUM_LIMBS = 16
MASK = (1 << LIMB_BITS) - 1


def int_to_limbs(x: int, n: int = NUM_LIMBS) -> np.ndarray:
    """Host int -> uint32 limb vector (little-endian 16-bit limbs)."""
    return np.array([(x >> (LIMB_BITS * i)) & MASK for i in range(n)], dtype=np.uint32)


def limbs_to_int(a) -> int:
    a = np.asarray(a, dtype=np.uint64)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(a))


def _shift_up(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """x[i] -> x[i-k] along the limb (last) axis, zero-filled below."""
    pad = [(0, 0)] * (x.ndim - 1) + [(k, 0)]
    return jnp.pad(x, pad)[..., : x.shape[-1]]


def _carry_ladder(x: jnp.ndarray, out_limbs: int, up) -> jnp.ndarray:
    """The relax + Kogge-Stone carry core, shared by both conv layouts
    (`up` is the limb-axis shift for whichever axis holds limbs).

    Two local folds bring every limb to <= 2^16, then a generate/propagate
    doubling ladder resolves the remaining 0/1 carries in
    ceil(log2(out_limbs)) vector steps — O(log limbs) graph and runtime
    dependency chain."""
    for _ in range(2):
        x = (x & MASK) + up(x >> LIMB_BITS, 1)
    g = x >> LIMB_BITS  # 0/1 generate
    r = x & MASK
    p = (r == MASK).astype(jnp.uint32)  # propagate
    k = 1
    while k < out_limbs:
        g = g | (p & up(g, k))
        p = p & up(p, k)
        k *= 2
    return (r + up(g, 1)) & MASK


def _carry_canon(x: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Propagate carries: arbitrary uint32 limbs -> canonical 16-bit limbs
    (limbs on the LAST axis).  Callers guarantee limbs beyond `out_limbs`
    are zero (no value is silently truncated)."""
    L = x.shape[-1]
    if L < out_limbs:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, out_limbs - L)])
    else:
        x = x[..., :out_limbs]
    return _carry_ladder(x, out_limbs, _shift_up)


@lru_cache(maxsize=None)
def _conv_onehot(n: int, m: int) -> jnp.ndarray:
    """(2*n*m, n+m+1) 0/1 f32 matrix folding lo/hi partial-product planes
    onto their limb offsets: flat index (p, i, j) -> column i + j + p."""
    L = n + m + 1
    w = np.zeros((2, n, m, L), dtype=np.float32)
    for i in range(n):
        for j in range(m):
            w[0, i, j, i + j] = 1.0
            w[1, i, j, i + j + 1] = 1.0
    with jax.ensure_compile_time_eval():
        return jnp.asarray(w.reshape(2 * n * m, L))


# Convolution layout selector.  "matmul": the f32 one-hot matmul below
# (MXU path).  "limb_major": transpose so the BATCH is the minor axis and
# run 16 shifted VPU multiply-accumulates — XLA:TPU tiles the last two
# dims onto (8 sublanes, 128 lanes), so batch-major (B, 16) tensors use
# only 16/128 lanes on every elementwise op while limb-major (16, B)
# fills them.  Flip at runtime (e.g. ZKP2P_FIELD_CONV=limb_major) to A/B
# on hardware; both are bit-exact and differentially tested.
from ..utils.config import load_config as _load_config
from ..utils.jaxcfg import on_tpu as _on_tpu

CONV_LAYOUT = _load_config().field_conv

# Field-mul implementation selector: "auto" (default — the fused pallas
# kernel on a real TPU backend, the XLA path elsewhere), "xla", or
# "pallas" (force; runs interpret-mode off-TPU — tests only).  Measured
# on a v5e chip (r4): 136.5 M muls/s fused vs 14.3 M XLA (7.9x) — see
# docs/ROOFLINE.md.
FIELD_MUL_IMPL = _load_config().field_mul


def field_mul_impl() -> str:
    """The RESOLVED field-mul implementation ("pallas" or "xla") — the
    one place the "auto" rule lives (mirror of JCurve._pallas; used by
    JPrimeField.mul and by tools that label A/B arms).  Reports its arm
    to the execution audit at every consultation (trace-time: the arm is
    baked into the compiled executable, so the record marks the trace
    that chose it)."""
    from ..utils.audit import record_arm

    impl = "pallas" if (FIELD_MUL_IMPL == "pallas" or (FIELD_MUL_IMPL == "auto" and _on_tpu())) else "xla"
    record_arm("field_mul", impl)
    return impl


def _mul_wide_limb_major(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook conv with limbs on axis 0 and the flattened batch on
    the minor axis: 16 iterations of (Lb, B) u32 multiply + two padded
    adds into a (La+Lb+1, B) accumulator, then a log-depth carry ladder
    along axis 0.  Sums per output limb <= 2*16 values < 2^16 -> u32
    accumulation exact."""
    La, Lb = a.shape[-1], b.shape[-1]
    bshape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    A = jnp.moveaxis(jnp.broadcast_to(a, bshape + (La,)), -1, 0).reshape(La, -1)
    Bv = jnp.moveaxis(jnp.broadcast_to(b, bshape + (Lb,)), -1, 0).reshape(Lb, -1)
    out_len = La + Lb + 1
    acc = jnp.zeros((out_len, A.shape[1]), dtype=jnp.uint32)
    for i in range(La):
        p = A[i][None, :] * Bv  # (Lb, B), exact u32
        acc = acc + jnp.pad(p & MASK, ((i, out_len - Lb - i), (0, 0)))
        acc = acc + jnp.pad(p >> LIMB_BITS, ((i + 1, out_len - Lb - i - 1), (0, 0)))
    out_limbs = La + Lb
    acc = acc[:out_limbs]

    def up(x, k):  # limb-axis shift, limbs on axis 0
        return jnp.pad(x, ((k, 0), (0, 0)))[: x.shape[0]]

    res = _carry_ladder(acc, out_limbs, up)
    return jnp.moveaxis(res.reshape((out_limbs,) + bshape), 0, -1)


def _mul_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Full product of two 16-limb values -> 32 canonical limbs.

    Default path: schoolbook convolution as ONE f32 matmul — every
    partial product a_i*b_j < 2^32 is split into 16-bit halves (each
    exact in f32), and a precomputed 0/1 matrix folds the (2,16,16)
    planes onto their limb offsets.  Each output limb sums <= 32 values
    < 2^16, so the f32 accumulation stays integral (< 2^21 << 2^24) —
    bit-exact, and the contraction runs on the TPU MXU.
    See CONV_LAYOUT for the limb-major VPU alternative.
    """
    if CONV_LAYOUT == "limb_major":
        return _mul_wide_limb_major(a, b)
    n = a.shape[-1]
    m = b.shape[-1]
    prods = a[..., :, None] * b[..., None, :]  # (..., n, m) uint32
    lo = (prods & MASK).astype(jnp.float32)
    hi = (prods >> LIMB_BITS).astype(jnp.float32)
    planes = jnp.concatenate(
        [lo.reshape(*lo.shape[:-2], n * m), hi.reshape(*hi.shape[:-2], n * m)], axis=-1
    )
    # Precision.HIGHEST: TPU DEFAULT f32 matmul truncates operands to
    # bf16 MXU passes (8 mantissa bits — NOT exact for 16-bit limbs);
    # HIGHEST runs the full-f32 pass schedule, keeping every partial and
    # sum integral and bit-exact.
    acc = jax.lax.dot_general(
        planes,
        _conv_onehot(n, m),
        (((planes.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
    )  # (..., n+m+1), integral f32 < 2^21
    return _carry_canon(acc.astype(jnp.uint32), n + m)


class JPrimeField:
    """A prime field instance with device-resident Montgomery constants.

    Two global instances exist: ``FQ`` (base field, curve coordinates) and
    ``FR`` (scalar field, witnesses / NTT).  Elements are uint32 arrays of
    shape (..., 16) in Montgomery form unless a function says otherwise.
    """

    def __init__(self, modulus: int, name: str):
        from .bn254 import _mont_constants

        self.modulus = modulus
        self.name = name
        self.mont_r, self.mont_r2, self.nprime_int = _mont_constants(modulus)
        self.n_limbs = jnp.asarray(int_to_limbs(modulus))
        self.nprime_limbs = jnp.asarray(int_to_limbs(self.nprime_int))
        self.r2_limbs = jnp.asarray(int_to_limbs(self.mont_r2))
        self.one_mont = jnp.asarray(int_to_limbs(self.mont_r))
        self.zero_limbs = jnp.zeros(NUM_LIMBS, dtype=jnp.uint32)

    # ------------------------------------------------------------ host I/O

    def to_mont_host(self, x: int) -> np.ndarray:
        return int_to_limbs((x * MONT_R) % self.modulus)

    def from_mont_host(self, limbs) -> int:
        return (limbs_to_int(limbs) * pow(MONT_R, -1, self.modulus)) % self.modulus

    def to_std_host(self, x: int) -> np.ndarray:
        return int_to_limbs(x % self.modulus)

    def array_to_mont_host(self, xs) -> np.ndarray:
        return np.stack([self.to_mont_host(int(x)) for x in xs])

    def array_to_mont_host_fast(self, xs) -> np.ndarray:
        """Vectorized (n, 16) Montgomery limbs: one bytes join + one
        frombuffer instead of a per-element 16-limb Python loop — the
        difference between seconds and minutes at venmo-scale wire counts."""
        m = self.modulus
        buf = b"".join((int(x) * MONT_R % m).to_bytes(32, "little") for x in xs)
        return np.frombuffer(buf, "<u2").astype(np.uint32).reshape(len(xs), NUM_LIMBS)

    # --------------------------------------------------------- basic arith

    def _cond_sub_n(self, a: jnp.ndarray) -> jnp.ndarray:
        """a (< 2*modulus, canonical limbs) -> a mod modulus."""
        d, borrow = self._sub_raw(a, self.n_limbs)
        return jnp.where(borrow[..., None] != 0, a, d)

    @staticmethod
    def _sub_raw(a: jnp.ndarray, b: jnp.ndarray):
        """(a - b) mod 2^256 with final borrow flag (1 if a < b).

        Two's-complement addition a + ~b + 1 through the log-depth carry
        ladder; the carry out of the top limb is the no-borrow flag."""
        n = a.shape[-1]
        x = a + (MASK - jnp.broadcast_to(b, a.shape))
        one = jnp.zeros(n, dtype=jnp.uint32).at[0].set(1)
        y = _carry_canon(x + one, n + 1)
        borrow = (1 - y[..., n]).astype(jnp.int32)
        return y[..., :n], borrow

    def add(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return self._cond_sub_n(_carry_canon(a + b, NUM_LIMBS))

    def sub(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        d, borrow = self._sub_raw(a, b)
        dn = _carry_canon(d + self.n_limbs, NUM_LIMBS)
        return jnp.where(borrow[..., None] != 0, dn, d)

    def neg(self, a: jnp.ndarray) -> jnp.ndarray:
        d, _ = self._sub_raw(jnp.broadcast_to(self.n_limbs, a.shape), a)
        # -0 must stay 0, not N
        is_zero = self.is_zero(a)
        return jnp.where(is_zero[..., None], a, self._cond_sub_n(d))

    def mul(self, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """Montgomery product: (a*b*R^-1) mod N, R = 2^256 (SOS method).

        ZKP2P_FIELD_MUL routes the implementation: "auto" (default)
        takes the fused VMEM kernel (ops.pallas_mont, docs/ROOFLINE.md)
        on a real TPU backend and the XLA path elsewhere; "pallas"
        forces the kernel (interpret mode off-TPU — tests only)."""
        if field_mul_impl() == "pallas":
            from ..ops.pallas_mont import mont_mul

            return mont_mul(self, a, b, not _on_tpu())
        t = _mul_wide(a, b)  # (..., 32)
        m = _mul_wide(t[..., :NUM_LIMBS], self.nprime_limbs)[..., :NUM_LIMBS]
        u = _mul_wide(m, self.n_limbs)  # (..., 32)
        # t + u is divisible by 2^256; sum then shift right 16 limbs.
        s = _carry_canon(t.astype(jnp.uint32) + u, 2 * NUM_LIMBS + 1)
        return self._cond_sub_n(s[..., NUM_LIMBS : 2 * NUM_LIMBS + 1][..., :NUM_LIMBS])

    def square(self, a: jnp.ndarray) -> jnp.ndarray:
        return self.mul(a, a)

    def to_mont(self, a: jnp.ndarray) -> jnp.ndarray:
        """Standard-form limbs -> Montgomery form (on device)."""
        return self.mul(a, self.r2_limbs)

    def from_mont(self, a: jnp.ndarray) -> jnp.ndarray:
        """Montgomery form -> standard-form limbs (mont-mul by 1)."""
        one = jnp.zeros_like(a).at[..., 0].set(1)
        return self.mul(a, one)

    # ----------------------------------------------------------- predicates

    @staticmethod
    def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        return jnp.all(a == b, axis=-1)

    @staticmethod
    def is_zero(a: jnp.ndarray) -> jnp.ndarray:
        return jnp.all(a == 0, axis=-1)

    @staticmethod
    def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
        """cond ? a : b, with cond shaped (...,) against (..., 16) operands."""
        return jnp.where(cond[..., None], a, b)

    # ------------------------------------------------------------ inversion

    def pow_const(self, a: jnp.ndarray, e: int) -> jnp.ndarray:
        """a^e for a compile-time exponent.

        lax.scan over the exponent's bits (LSB first) keeps the traced graph
        at one square+select per step regardless of exponent size — the
        unrolled ladder was a 60k-op HLO graph for a 254-bit exponent.
        """
        if e == 0:
            return jnp.broadcast_to(self.one_mont, a.shape)
        bits = jnp.asarray([(e >> i) & 1 for i in range(e.bit_length())], dtype=jnp.uint32)

        def step(carry, bit):
            acc, base = carry
            acc = self.select(bit != 0, self.mul(acc, base), acc)
            base = self.square(base)
            return (acc, base), None

        acc0 = jnp.broadcast_to(self.one_mont, a.shape)
        (acc, _), _ = jax.lax.scan(step, (acc0, a), bits)
        return acc

    def inv(self, a: jnp.ndarray) -> jnp.ndarray:
        """Fermat inverse a^(N-2); 0 maps to 0 (callers select around it)."""
        return self.pow_const(a, self.modulus - 2)

    def inv_fused(self, a: jnp.ndarray) -> jnp.ndarray:
        """`inv`, but one kernel launch on TPU: pow_const's scan issues 2
        mul dispatches per exponent bit (~508 launches per call), which
        makes small-batch inversions latency-bound; the fused ladder
        (ops.pallas_mont.mont_pow) runs the whole ladder in VMEM."""
        if field_mul_impl() == "pallas":
            from ..ops.pallas_mont import mont_pow

            return mont_pow(self, a, self.modulus - 2, not _on_tpu())
        return self.inv(a)


FQ = JPrimeField(P, "fq")
FR = JPrimeField(R, "fr")


# --------------------------------------------------------------------- Fq2
#
# Fq2 = Fq[u]/(u^2 + 1): elements are pairs of Fq limb arrays, stacked on a
# new axis -2: shape (..., 2, 16).  Mirrors zkp2p_tpu.field.tower.Fq2 (host).


class JFq2Ops:
    """Fq2 arithmetic over stacked limb pairs (..., 2, 16)."""

    def __init__(self, fq: JPrimeField = FQ):
        self.fq = fq
        self.one_mont = jnp.stack([fq.one_mont, fq.zero_limbs])
        self.zero_limbs = jnp.zeros((2, NUM_LIMBS), dtype=jnp.uint32)

    def add(self, a, b):
        return self.fq.add(a, b)

    def sub(self, a, b):
        return self.fq.sub(a, b)

    def neg(self, a):
        return self.fq.neg(a)

    def mul(self, a, b):
        a0, a1 = a[..., 0, :], a[..., 1, :]
        b0, b1 = b[..., 0, :], b[..., 1, :]
        v0 = self.fq.mul(a0, b0)
        v1 = self.fq.mul(a1, b1)
        c0 = self.fq.sub(v0, v1)  # u^2 = -1
        c1 = self.fq.sub(
            self.fq.mul(self.fq.add(a0, a1), self.fq.add(b0, b1)),
            self.fq.add(v0, v1),
        )
        return jnp.stack([c0, c1], axis=-2)

    def square(self, a):
        return self.mul(a, a)

    def eq(self, a, b):
        return jnp.all(a == b, axis=(-1, -2))

    def is_zero(self, a):
        return jnp.all(a == 0, axis=(-1, -2))

    @staticmethod
    def select(cond, a, b):
        return jnp.where(cond[..., None, None], a, b)


FQ2 = JFq2Ops(FQ)


# ------------------------------------------------------- batched reductions


def reduce_wide(field: JPrimeField, wide: jnp.ndarray) -> jnp.ndarray:
    """Reduce a canonical-limb value of up to 31 limbs to x mod N.

    Montgomery round-trip: one Montgomery reduction computes x*2^-256 mod N
    (exact because x < 2^496 << 2^256 * N), then a mont-mul by the
    precomputed 2^512 mod N restores the 2^256 factor.  Three convolutions,
    no data-dependent control flow.
    """
    L = wide.shape[-1]
    assert L <= 31, "reduce_wide supports < 2^496 inputs"
    x = jnp.zeros(wide.shape[:-1] + (2 * NUM_LIMBS,), dtype=jnp.uint32)
    x = x.at[..., :L].set(wide)
    m = _mul_wide(x[..., :NUM_LIMBS], field.nprime_limbs)[..., :NUM_LIMBS]
    u = _mul_wide(m, field.n_limbs)  # 32 limbs
    s = _carry_canon(x + u, 2 * NUM_LIMBS + 1)
    t = field._cond_sub_n(s[..., NUM_LIMBS : 2 * NUM_LIMBS])
    # r2_limbs == 2^512 mod N, exactly the factor that undoes the 2^-256.
    return field.mul(t, field.r2_limbs)


def lazy_segment_sum_mod(
    field: JPrimeField, values: jnp.ndarray, segment_ids: jnp.ndarray, num_segments: int
) -> jnp.ndarray:
    """Modular segment-sum: sum canonical limb values per segment, then reduce.

    Limbs are < 2^16, so uint32 per-limb accumulation is exact for up to ~2^16
    terms per segment — far above the row fan-in of any of our constraint
    systems.  This is the sparse-matvec primitive behind Az/Bz/Cz.
    """
    acc = jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)
    wide = _carry_canon(acc, NUM_LIMBS + 2)
    return reduce_wide(field, wide)
