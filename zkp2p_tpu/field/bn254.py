"""BN254 (alt_bn128) base/scalar field parameters and host-side arithmetic.

This is the host-side (Python int) mirror of the TPU limb arithmetic in
``zkp2p_tpu.field.jfield``.  It plays the role the reference delegates to
rapidsnark's x86 assembly field library and to circom's ``bigint.circom``
gadgets (reference: ``zk-email-verify-circuits/bigint.circom``,
``zk-email-verify-circuits/fp.circom:26-85``) — here it is the oracle that
every vectorised TPU kernel is tested against, and the engine for host-only
steps (trusted setup, pairing-based verification, zkey parsing).
"""

from __future__ import annotations

# Base field modulus (Fq) and scalar field modulus (Fr) of BN254.
# These are the constants baked into contracts/Verifier.sol in the reference
# (snarkjs-exported Groth16 verifier) — our proofs must live on exactly this
# curve to stay wire-compatible.
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

# Curve: y^2 = x^3 + 3 over Fq;  G2 twist: y^2 = x^3 + 3/(u+9) over Fq2.
CURVE_B = 3

# Generators.
G1_GEN = (1, 2)
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

# BN parameter u: p(u), r(u) are the standard BN polynomials.
BN_U = 4965661367192848881
ATE_LOOP_COUNT = 6 * BN_U + 2  # 29793968203157093288

# Limb layout shared with the TPU side: 16 limbs x 16 bits = 256 bits.
LIMB_BITS = 16
NUM_LIMBS = 16
MONT_BITS = LIMB_BITS * NUM_LIMBS  # 256
MONT_R = 1 << MONT_BITS

# snarkjs / circom "bigint" layout used at the wire level by the reference app
# (app/src/helpers/binaryFormat.ts:70-78 packs RSA moduli as 121-bit x 17
# limbs).  We keep those constants for input-format parity.
CIRCOM_BIGINT_N = 121
CIRCOM_BIGINT_K = 17


def fq_add(a: int, b: int) -> int:
    return (a + b) % P


def fq_sub(a: int, b: int) -> int:
    return (a - b) % P


def fq_mul(a: int, b: int) -> int:
    return (a * b) % P


def fq_inv(a: int) -> int:
    if a % P == 0:
        raise ZeroDivisionError("inverse of zero in Fq")
    return pow(a, P - 2, P)


def fr_add(a: int, b: int) -> int:
    return (a + b) % R


def fr_sub(a: int, b: int) -> int:
    return (a - b) % R


def fr_mul(a: int, b: int) -> int:
    return (a * b) % R


def fr_inv(a: int) -> int:
    if a % R == 0:
        raise ZeroDivisionError("inverse of zero in Fr")
    return pow(a, R - 2, R)


def _mont_constants(modulus: int):
    """Montgomery constants for the 16x16-bit limb layout."""
    r_mod = MONT_R % modulus
    r2 = (r_mod * r_mod) % modulus
    # n' = -modulus^{-1} mod 2^256  (also per-limb: mod 2^16)
    n_inv = pow(modulus, -1, MONT_R)
    n_prime = (-n_inv) % MONT_R
    return r_mod, r2, n_prime


FQ_MONT_R, FQ_MONT_R2, FQ_NPRIME = _mont_constants(P)
FR_MONT_R, FR_MONT_R2, FR_NPRIME = _mont_constants(R)


def to_mont(a: int, modulus: int = P) -> int:
    return (a * MONT_R) % modulus


def from_mont(a: int, modulus: int = P) -> int:
    return (a * pow(MONT_R, -1, modulus)) % modulus


def find_fr_2adic_root() -> int:
    """A primitive 2^28-th root of unity in Fr.

    r - 1 has 2-adicity 28; this bounds our NTT domain at 2^28 points, well
    above the 2^23 domain the 6.6M-constraint reference circuit needs
    (reference README.md:79).  Verified at import-time by order checks rather
    than trusting a hardcoded factorisation.
    """
    two_adicity = 28
    assert (R - 1) % (1 << two_adicity) == 0
    assert (R - 1) % (1 << (two_adicity + 1)) != 0
    odd = (R - 1) >> two_adicity
    for g in range(2, 100):
        w = pow(g, odd, R)
        # order of w divides 2^28; it is exactly 2^28 iff w^(2^27) != 1
        if pow(w, 1 << (two_adicity - 1), R) != 1:
            return w
    raise RuntimeError("no 2^28 root of unity found")


FR_TWO_ADICITY = 28
FR_ROOT_OF_UNITY = find_fr_2adic_root()


def fr_domain_root(log_size: int) -> int:
    """Primitive 2^log_size-th root of unity in Fr."""
    if log_size > FR_TWO_ADICITY:
        raise ValueError(f"domain 2^{log_size} exceeds Fr 2-adicity {FR_TWO_ADICITY}")
    w = FR_ROOT_OF_UNITY
    for _ in range(FR_TWO_ADICITY - log_size):
        w = (w * w) % R
    return w


# ---------------------------------------------------------------------------
# GLV endomorphism (the MSM work-reduction lever every accelerator MSM
# study leads with — SZKP §IV, ZKProphet §3): BN254 has j-invariant 0,
# so phi(x, y) = (beta * x, y) with beta a primitive cube root of unity
# in Fq is an endomorphism acting as scalar multiplication by lambda, a
# cube root of unity in Fr.  Every 254-bit scalar k then splits into two
# ~128-bit half-scalars k = k1 + k2 * lambda (mod r), and
#
#     k * P  =  k1 * P  +  k2 * phi(P),
#
# turning a length-n MSM over 254-bit scalars into a length-2n MSM over
# half-length scalars: half the digit planes / Pippenger windows.
#
# Nothing below is hardcoded from a paper table: the roots of unity, the
# lattice basis, and the Barrett constants are all DERIVED here at
# import (and cross-checked — lambda*G must literally land on
# (beta, 2)), so a transcription error is an import failure, not a
# silently wrong proof.


def _jac_mul_int(pt, k: int):
    """Tiny host scalar mult (Jacobian, python ints) used only for the
    import-time phi/lambda pairing check — curve.host imports this
    module, so the check cannot use it."""
    X1, Y1 = pt
    X, Y, Z = 0, 1, 0  # infinity
    for bit in bin(k)[2:]:
        if Z:  # double
            A, B = X * X % P, Y * Y % P
            C = B * B % P
            D = 2 * ((X + B) * (X + B) - A - C) % P
            E = 3 * A % P
            X2 = (E * E - 2 * D) % P
            Y, Z = (E * (D - X2) - 8 * C) % P, 2 * Y * Z % P
            X = X2
        if bit == "1":
            if not Z:
                X, Y, Z = X1, Y1, 1
            else:  # mixed add (Z2 = 1); the loop never hits the equal/neg cases
                ZZ = Z * Z % P
                U2, S2 = X1 * ZZ % P, Y1 * ZZ * Z % P
                H, Rr = (U2 - X) % P, (S2 - Y) % P
                HH = H * H % P
                HHH, V = H * HH % P, X * HH % P
                X2 = (Rr * Rr - HHH - 2 * V) % P
                Y, Z = (Rr * (V - X2) - Y * HHH) % P, Z * H % P
                X = X2
    if not Z:
        return None
    zi = pow(Z, P - 2, P)
    return (X * zi * zi % P, Y * zi * zi % P * zi % P)


def _cube_root_of_unity(modulus: int) -> int:
    assert (modulus - 1) % 3 == 0
    for g in range(2, 100):
        w = pow(g, (modulus - 1) // 3, modulus)
        if w != 1:
            assert pow(w, 3, modulus) == 1
            return w
    raise RuntimeError("no cube root of unity found")


def _glv_lattice(lam: int):
    """Short basis (a1, b1), (a2, b2) of {(x, y): x + y*lam = 0 mod r}
    via the half-extended Euclid of the GLV paper (Algorithm 3.74 in
    Guide to ECC): successive remainders r_i = s_i*r + t_i*lam give
    lattice vectors (r_i, -t_i); stop around sqrt(r)."""
    sqrt_r = 1 << ((R.bit_length() + 1) // 2)
    rems = [(R, 0), (lam, 1)]  # (r_i, t_i)
    while rems[-1][0] >= sqrt_r:
        (r0, t0), (r1, t1) = rems[-2], rems[-1]
        q = r0 // r1
        rems.append((r0 - q * r1, t0 - q * t1))
    (rl, tl), (rl1, tl1) = rems[-2], rems[-1]
    v1 = (rl1, -tl1)
    # second vector: the shorter of (r_l, -t_l) and (r_{l+2}, -t_{l+2})
    # (one more Euclid step past the sqrt(r) crossing)
    q = rl // rl1
    cand_a = (rl, -tl)
    cand_b = (rl - q * rl1, -(tl - q * tl1))

    def _n2(v):
        return v[0] * v[0] + v[1] * v[1]

    v2 = cand_a if _n2(cand_a) <= _n2(cand_b) else cand_b
    # normalise orientation so det(v1, v2) = +r (the decomposition
    # formulas below assume it)
    det = v1[0] * v2[1] - v2[0] * v1[1]
    assert abs(det) == R, "GLV lattice determinant must be +-r"
    if det < 0:
        v2 = (-v2[0], -v2[1])
    for a, b in (v1, v2):
        assert (a + b * lam) % R == 0
        assert a != 0 and b != 0
    return v1, v2


def _glv_setup():
    lam = _cube_root_of_unity(R)
    # phi(G) = (beta, 2) for G = (1, 2): one scalar mult pins which of
    # the two cube roots in Fq pairs with this lambda.
    q = _jac_mul_int(G1_GEN, lam)
    b = _cube_root_of_unity(P)
    assert q is not None and q[1] == 2 and q[0] in (b, b * b % P), (
        "lambda*G is not (beta, 2): GLV endomorphism derivation broken"
    )
    beta = q[0]
    v1, v2 = _glv_lattice(lam)
    return lam, beta, v1, v2


GLV_LAMBDA, GLV_BETA, GLV_V1, GLV_V2 = _glv_setup()
(_GLV_A1, _GLV_B1), (_GLV_A2, _GLV_B2) = GLV_V1, GLV_V2

# Barrett constants: exact c_i = round(m_i*k/r) with m1 = b2, m2 = -b1;
# the limb kernels (JAX ops.msm, csrc) use the floor form
# c_abs = (k * MU) >> GLV_SHIFT, whose error vs the exact rounding is
# < 2 — harmless: k1 + lambda*k2 = k (mod r) holds for ANY c_i by
# construction, only the |k_i| bound grows (folded into GLV_MAX_BITS).
GLV_SHIFT = 256
_GLV_M1, _GLV_M2 = _GLV_B2, -_GLV_B1
GLV_MU1 = (abs(_GLV_M1) << GLV_SHIFT) // R
GLV_MU2 = (abs(_GLV_M2) << GLV_SHIFT) // R


def _sign(x: int) -> int:
    return 1 if x > 0 else -1


# Term form consumed by the limb kernels: k1 = k -+ |c1||a1| -+ |c2||a2|
# and k2 = -+ |c1||b1| -+ |c2||b2|, where each subtract flag folds the
# sign of c_i (= sign of m_i) and of the basis entry.
GLV_K1_TERMS = (
    (abs(_GLV_A1), _sign(_GLV_M1) * _sign(_GLV_A1) > 0),
    (abs(_GLV_A2), _sign(_GLV_M2) * _sign(_GLV_A2) > 0),
)
GLV_K2_TERMS = (
    (abs(_GLV_B1), _sign(_GLV_M1) * _sign(_GLV_B1) > 0),
    (abs(_GLV_B2), _sign(_GLV_M2) * _sign(_GLV_B2) > 0),
)

# Worst-case half-scalar magnitudes (Barrett floor error < 2 per c_i):
# |k_i| < 2 * (|basis column|_1).  ~2^128.6 for BN254.
GLV_MAX_K1 = 2 * (abs(_GLV_A1) + abs(_GLV_A2))
GLV_MAX_K2 = 2 * (abs(_GLV_B1) + abs(_GLV_B2))
GLV_MAX_BITS = max(GLV_MAX_K1.bit_length(), GLV_MAX_K2.bit_length())


def glv_decompose(k: int):
    """k (mod r) -> (k1, k2) signed ints with k = k1 + k2*lambda (mod r)
    and |k_i| < 2^GLV_MAX_BITS.  This is the HOST ORACLE: it implements
    the exact floor-Barrett limb algorithm of the JAX and C kernels
    (ops.msm.glv_decompose_limbs, csrc glv_split) so the three can be
    diffed integer-for-integer."""
    k %= R
    c1 = (k * GLV_MU1) >> GLV_SHIFT
    c2 = (k * GLV_MU2) >> GLV_SHIFT
    k1 = k
    for c, (mag, sub) in zip((c1, c2), GLV_K1_TERMS):
        k1 = k1 - c * mag if sub else k1 + c * mag
    k2 = 0
    for c, (mag, sub) in zip((c1, c2), GLV_K2_TERMS):
        k2 = k2 - c * mag if sub else k2 + c * mag
    return k1, k2


def glv_num_planes(window: int) -> int:
    """Signed base-2^window digit planes needed for one GLV half-scalar:
    k planes hold |v| < 2^(window*k - 1) after signed recoding (the top
    digit must absorb the final carry), so k = ceil((GLV_MAX_BITS+1)/w)."""
    return -(-(GLV_MAX_BITS + 1) // window)


# Import-time self-check: a decomposition identity failure must be an
# import error, never a wrong proof.  (Covers the edge scalars the
# property tests also pin.)
for _k in (0, 1, 2, R - 1, GLV_LAMBDA, R - GLV_LAMBDA, (1 << 128) - 1, R >> 1):
    _k1, _k2 = glv_decompose(_k)
    assert (_k1 + _k2 * GLV_LAMBDA - _k) % R == 0
    assert abs(_k1) < (1 << GLV_MAX_BITS) and abs(_k2) < (1 << GLV_MAX_BITS)
del _k, _k1, _k2
