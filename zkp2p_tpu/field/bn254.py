"""BN254 (alt_bn128) base/scalar field parameters and host-side arithmetic.

This is the host-side (Python int) mirror of the TPU limb arithmetic in
``zkp2p_tpu.field.jfield``.  It plays the role the reference delegates to
rapidsnark's x86 assembly field library and to circom's ``bigint.circom``
gadgets (reference: ``zk-email-verify-circuits/bigint.circom``,
``zk-email-verify-circuits/fp.circom:26-85``) — here it is the oracle that
every vectorised TPU kernel is tested against, and the engine for host-only
steps (trusted setup, pairing-based verification, zkey parsing).
"""

from __future__ import annotations

# Base field modulus (Fq) and scalar field modulus (Fr) of BN254.
# These are the constants baked into contracts/Verifier.sol in the reference
# (snarkjs-exported Groth16 verifier) — our proofs must live on exactly this
# curve to stay wire-compatible.
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

# Curve: y^2 = x^3 + 3 over Fq;  G2 twist: y^2 = x^3 + 3/(u+9) over Fq2.
CURVE_B = 3

# Generators.
G1_GEN = (1, 2)
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

# BN parameter u: p(u), r(u) are the standard BN polynomials.
BN_U = 4965661367192848881
ATE_LOOP_COUNT = 6 * BN_U + 2  # 29793968203157093288

# Limb layout shared with the TPU side: 16 limbs x 16 bits = 256 bits.
LIMB_BITS = 16
NUM_LIMBS = 16
MONT_BITS = LIMB_BITS * NUM_LIMBS  # 256
MONT_R = 1 << MONT_BITS

# snarkjs / circom "bigint" layout used at the wire level by the reference app
# (app/src/helpers/binaryFormat.ts:70-78 packs RSA moduli as 121-bit x 17
# limbs).  We keep those constants for input-format parity.
CIRCOM_BIGINT_N = 121
CIRCOM_BIGINT_K = 17


def fq_add(a: int, b: int) -> int:
    return (a + b) % P


def fq_sub(a: int, b: int) -> int:
    return (a - b) % P


def fq_mul(a: int, b: int) -> int:
    return (a * b) % P


def fq_inv(a: int) -> int:
    if a % P == 0:
        raise ZeroDivisionError("inverse of zero in Fq")
    return pow(a, P - 2, P)


def fr_add(a: int, b: int) -> int:
    return (a + b) % R


def fr_sub(a: int, b: int) -> int:
    return (a - b) % R


def fr_mul(a: int, b: int) -> int:
    return (a * b) % R


def fr_inv(a: int) -> int:
    if a % R == 0:
        raise ZeroDivisionError("inverse of zero in Fr")
    return pow(a, R - 2, R)


def _mont_constants(modulus: int):
    """Montgomery constants for the 16x16-bit limb layout."""
    r_mod = MONT_R % modulus
    r2 = (r_mod * r_mod) % modulus
    # n' = -modulus^{-1} mod 2^256  (also per-limb: mod 2^16)
    n_inv = pow(modulus, -1, MONT_R)
    n_prime = (-n_inv) % MONT_R
    return r_mod, r2, n_prime


FQ_MONT_R, FQ_MONT_R2, FQ_NPRIME = _mont_constants(P)
FR_MONT_R, FR_MONT_R2, FR_NPRIME = _mont_constants(R)


def to_mont(a: int, modulus: int = P) -> int:
    return (a * MONT_R) % modulus


def from_mont(a: int, modulus: int = P) -> int:
    return (a * pow(MONT_R, -1, modulus)) % modulus


def find_fr_2adic_root() -> int:
    """A primitive 2^28-th root of unity in Fr.

    r - 1 has 2-adicity 28; this bounds our NTT domain at 2^28 points, well
    above the 2^23 domain the 6.6M-constraint reference circuit needs
    (reference README.md:79).  Verified at import-time by order checks rather
    than trusting a hardcoded factorisation.
    """
    two_adicity = 28
    assert (R - 1) % (1 << two_adicity) == 0
    assert (R - 1) % (1 << (two_adicity + 1)) != 0
    odd = (R - 1) >> two_adicity
    for g in range(2, 100):
        w = pow(g, odd, R)
        # order of w divides 2^28; it is exactly 2^28 iff w^(2^27) != 1
        if pow(w, 1 << (two_adicity - 1), R) != 1:
            return w
    raise RuntimeError("no 2^28 root of unity found")


FR_TWO_ADICITY = 28
FR_ROOT_OF_UNITY = find_fr_2adic_root()


def fr_domain_root(log_size: int) -> int:
    """Primitive 2^log_size-th root of unity in Fr."""
    if log_size > FR_TWO_ADICITY:
        raise ValueError(f"domain 2^{log_size} exceeds Fr 2-adicity {FR_TWO_ADICITY}")
    w = FR_ROOT_OF_UNITY
    for _ in range(FR_TWO_ADICITY - log_size):
        w = (w * w) % R
    return w
