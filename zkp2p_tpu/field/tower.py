"""Extension-field tower Fq2 -> Fq6 -> Fq12 for BN254 pairings (host side).

Tower construction (the one contracts/Verifier.sol's precompiles assume):
    Fq2  = Fq[u]  / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - xi),  xi = u + 9
    Fq12 = Fq6[w] / (w^2 - v)

The reference never implements this itself — it calls the EVM pairing
precompiles (contracts/Verifier.sol:15-163 ``Pairing`` library).  We need it
natively to verify our own proofs without a chain, so this module is the
framework's stand-in for ecPairing (precompile 0x08).

Pure Python ints; used for verification, tests and trusted setup only — the
prover hot path never touches Fq12.
"""

from __future__ import annotations

from .bn254 import P


class Fq2:
    """a + b*u with u^2 = -1."""

    __slots__ = ("c0", "c1")
    NON_RESIDUE = (9, 1)  # xi = 9 + u

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    @classmethod
    def zero(cls) -> "Fq2":
        return cls(0, 0)

    @classmethod
    def one(cls) -> "Fq2":
        return cls(1, 0)

    def __eq__(self, other) -> bool:
        return isinstance(other, Fq2) and self.c0 == other.c0 and self.c1 == other.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __add__(self, other: "Fq2") -> "Fq2":
        return Fq2(self.c0 + other.c0, self.c1 + other.c1)

    def __sub__(self, other: "Fq2") -> "Fq2":
        return Fq2(self.c0 - other.c0, self.c1 - other.c1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, other):
        if isinstance(other, int):
            return Fq2(self.c0 * other, self.c1 * other)
        a0, a1, b0, b1 = self.c0, self.c1, other.c0, other.c1
        t0 = a0 * b0
        t1 = a1 * b1
        # (a0+a1)(b0+b1) - t0 - t1 = a0b1 + a1b0
        return Fq2(t0 - t1, (a0 + a1) * (b0 + b1) - t0 - t1)

    __rmul__ = __mul__

    def square(self) -> "Fq2":
        a0, a1 = self.c0, self.c1
        # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
        return Fq2((a0 + a1) * (a0 - a1), 2 * a0 * a1)

    def mul_by_nonresidue(self) -> "Fq2":
        """Multiply by xi = 9 + u."""
        a0, a1 = self.c0, self.c1
        return Fq2(9 * a0 - a1, a0 + 9 * a1)

    def conjugate(self) -> "Fq2":
        return Fq2(self.c0, -self.c1)

    def inv(self) -> "Fq2":
        a0, a1 = self.c0, self.c1
        norm = (a0 * a0 + a1 * a1) % P
        if norm == 0:
            raise ZeroDivisionError("inverse of zero in Fq2")
        ninv = pow(norm, P - 2, P)
        return Fq2(a0 * ninv, -a1 * ninv)

    def frobenius(self) -> "Fq2":
        """x -> x^p, which for Fq2 is conjugation."""
        return self.conjugate()

    def pow(self, e: int) -> "Fq2":
        result = Fq2.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def __repr__(self):
        return f"Fq2({self.c0}, {self.c1})"


XI = Fq2(9, 1)

# Frobenius coefficients, computed (not hardcoded) at import:
#   FROB_C1[i] = xi^((p^i - 1) / 3)   acting on Fq6 v-coefficients
#   FROB_C2[i] = xi^((2 p^i - 2) / 3)
#   FROB_W[i]  = xi^((p^i - 1) / 6)   acting on Fq12 w-coefficient
def _frob_coeffs():
    # Only the p^1 coefficients are needed: frobenius(power) iterates the
    # p^1 map, so higher-power tables would be dead weight at import time.
    c1, c2, cw = [Fq2.one()], [Fq2.one()], [Fq2.one()]
    c1.append(XI.pow((P - 1) // 3))
    c2.append(XI.pow((2 * P - 2) // 3))
    cw.append(XI.pow((P - 1) // 6))
    return c1, c2, cw


FROB_C1, FROB_C2, FROB_W = _frob_coeffs()


class Fq6:
    """c0 + c1 v + c2 v^2 with v^3 = xi."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    @classmethod
    def zero(cls) -> "Fq6":
        return cls(Fq2.zero(), Fq2.zero(), Fq2.zero())

    @classmethod
    def one(cls) -> "Fq6":
        return cls(Fq2.one(), Fq2.zero(), Fq2.zero())

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Fq6)
            and self.c0 == other.c0
            and self.c1 == other.c1
            and self.c2 == other.c2
        )

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __add__(self, other: "Fq6") -> "Fq6":
        return Fq6(self.c0 + other.c0, self.c1 + other.c1, self.c2 + other.c2)

    def __sub__(self, other: "Fq6") -> "Fq6":
        return Fq6(self.c0 - other.c0, self.c1 - other.c1, self.c2 - other.c2)

    def __neg__(self) -> "Fq6":
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, other: "Fq6") -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = other.c0, other.c1, other.c2
        t0, t1, t2 = a0 * b0, a1 * b1, a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_nonresidue() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_nonresidue()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def mul_fq2(self, s: Fq2) -> "Fq6":
        return Fq6(self.c0 * s, self.c1 * s, self.c2 * s)

    def square(self) -> "Fq6":
        return self * self

    def mul_by_v(self) -> "Fq6":
        """Multiply by v:  (c0,c1,c2) -> (xi*c2, c0, c1)."""
        return Fq6(self.c2.mul_by_nonresidue(), self.c0, self.c1)

    def inv(self) -> "Fq6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - (a1 * a2).mul_by_nonresidue()
        t1 = a2.square().mul_by_nonresidue() - a0 * a1
        t2 = a1.square() - a0 * a2
        denom = a0 * t0 + (a2 * t1).mul_by_nonresidue() + (a1 * t2).mul_by_nonresidue()
        dinv = denom.inv()
        return Fq6(t0 * dinv, t1 * dinv, t2 * dinv)

    def frobenius(self, power: int = 1) -> "Fq6":
        c0, c1, c2 = self.c0, self.c1, self.c2
        for _ in range(power):
            c0, c1, c2 = (
                c0.frobenius(),
                c1.frobenius() * FROB_C1[1],
                c2.frobenius() * FROB_C2[1],
            )
        return Fq6(c0, c1, c2)

    def __repr__(self):
        return f"Fq6({self.c0}, {self.c1}, {self.c2})"


class Fq12:
    """c0 + c1 w with w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0, self.c1 = c0, c1

    @classmethod
    def one(cls) -> "Fq12":
        return cls(Fq6.one(), Fq6.zero())

    @classmethod
    def zero(cls) -> "Fq12":
        return cls(Fq6.zero(), Fq6.zero())

    def __eq__(self, other) -> bool:
        return isinstance(other, Fq12) and self.c0 == other.c0 and self.c1 == other.c1

    def __add__(self, other: "Fq12") -> "Fq12":
        return Fq12(self.c0 + other.c0, self.c1 + other.c1)

    def __sub__(self, other: "Fq12") -> "Fq12":
        return Fq12(self.c0 - other.c0, self.c1 - other.c1)

    def __mul__(self, other: "Fq12") -> "Fq12":
        a0, a1, b0, b1 = self.c0, self.c1, other.c0, other.c1
        t0 = a0 * b0
        t1 = a1 * b1
        return Fq12(t0 + t1.mul_by_v(), (a0 + a1) * (b0 + b1) - t0 - t1)

    def square(self) -> "Fq12":
        a0, a1 = self.c0, self.c1
        t = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_by_v()) - t - t.mul_by_v()
        return Fq12(c0, t + t)

    def conjugate(self) -> "Fq12":
        """x -> x^(p^6): negate the w coefficient."""
        return Fq12(self.c0, -self.c1)

    def inv(self) -> "Fq12":
        a0, a1 = self.c0, self.c1
        denom = a0.square() - a1.square().mul_by_v()
        dinv = denom.inv()
        return Fq12(a0 * dinv, -(a1 * dinv))

    def frobenius(self, power: int = 1) -> "Fq12":
        out = self
        for _ in range(power):
            c0 = out.c0.frobenius(1)
            c1 = out.c1.frobenius(1)
            c1 = Fq6(c1.c0 * FROB_W[1], c1.c1 * FROB_W[1], c1.c2 * FROB_W[1])
            out = Fq12(c0, c1)
        return out

    def pow(self, e: int) -> "Fq12":
        if e < 0:
            return self.inv().pow(-e)
        result = Fq12.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def __repr__(self):
        return f"Fq12({self.c0}, {self.c1})"
