"""The client app surface: order book + flow over HTTP (L4 of SURVEY §1).

A single-file re-imagination of the reference React SPA (`app/src/` —
`MainPage.tsx:38`, `NewOrderForm.tsx`, `ClaimOrderForm.tsx`,
`SubmitOrderClaimsForm.tsx`, `SubmitOrderGenerateProofForm.tsx`) for a
headless deployment: a stdlib HTTP server renders the order table and
drives the same four flows against the in-process `Ramp` escrow:

  post order      -> POST /api/orders       (NewOrderForm semantics)
  claim order     -> POST /api/claims       (ClaimOrderForm: ECIES-encrypt
                     the Venmo id to the on-ramper + Poseidon hash)
  review claims   -> GET  /api/claims-decrypted (Matches / Does Not Match)
  prove + onramp  -> POST /api/onramp       (email -> inputs -> TPU prove
                     -> Ramp.onRamp; requires a loaded prover bundle)

The page polls /api/orders every 15 s, the reference's cadence
(`MainPage.tsx:177-185`).  No build step, no node — the product surface
for environments where the browser prover is replaced by the TPU
service.
"""

from __future__ import annotations

import hmac
import json
import os
import threading
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..contracts.ramp import MSG_LEN, FakeUSDC, Ramp
from .flow import OffRamper, OnRamper


@dataclass
class ProverBundle:
    """Everything /api/onramp needs to prove a receipt email."""

    cs: object
    dpk: object
    params: object
    layout: object


class OnrampApp:
    """Application state: chain objects + wallet sessions."""

    def __init__(
        self,
        ramp: Ramp,
        usdc: FakeUSDC,
        prover: Optional[ProverBundle] = None,
        eml_spool: Optional[str] = None,
        zkey_store: Optional[str] = None,
        zkey_cache: Optional[str] = None,
    ):
        self.ramp = ramp
        self.usdc = usdc
        self.prover = prover
        # The chunked-zkey store/cache are SERVER configuration, like the
        # eml spool: a client-supplied path would hand any caller
        # arbitrary directory creation + file writes + existence probing
        # on the host (the threat the r3 spool lockdown closed).
        self.zkey_store = zkey_store
        self.zkey_cache = zkey_cache
        # Server-side .eml files may only be read from this directory:
        # /api/onramp taking an arbitrary path would let any client probe
        # file existence/contents on the host (r3 advisor).
        self.eml_spool = os.path.realpath(eml_spool) if eml_spool else None
        self.onrampers: Dict[str, OnRamper] = {}
        self.offrampers: Dict[str, OffRamper] = {}
        self.lock = threading.Lock()
        self.zkey_fetch: Dict = {"state": "idle"}

    # ---- chunk-download progress (the reference's ProgressBar.tsx over
    # downloadProofFiles' onDownloaded callback, zkp.ts:24-49): the
    # server-side pull of the chunked zkey runs in a background thread
    # and GET /api/zkey-progress polls {done, total, state}.
    def start_zkey_fetch(self) -> None:
        from ..formats.artifact_store import DirBackend, download_chunked

        if self.zkey_store is None:
            raise PermissionError("no --zkey-store configured on this server")
        store_dir, cache_dir = self.zkey_store, self.zkey_cache
        with self.lock:
            if self.zkey_fetch.get("state") == "downloading":
                raise PermissionError("a zkey fetch is already in progress")
            self.zkey_fetch = {"state": "downloading", "done": 0, "total": 0}

        def progress(done: int, total: int) -> None:
            with self.lock:
                self.zkey_fetch.update(done=done, total=total)

        def run() -> None:
            try:
                blob = download_chunked(
                    DirBackend(store_dir), "circuit.zkey", cache_dir=cache_dir, progress=progress
                )
                with self.lock:
                    self.zkey_fetch.update(state="done", bytes=len(blob))
            except Exception as e:  # noqa: BLE001 — polled by the client
                with self.lock:
                    self.zkey_fetch.update(state="error", error=str(e))

        threading.Thread(target=run, daemon=True).start()

    def spool_eml(self, raw: bytes) -> str:
        """The drag-and-drop equivalent (SubmitOrderGenerateProofForm.tsx
        drop zone): accept raw .eml bytes, store them under the spool with
        a server-chosen name, return the name for /api/onramp."""
        if self.eml_spool is None:
            raise PermissionError("no --eml-spool directory configured on this server")
        if len(raw) > 4 * 1024 * 1024:
            raise PermissionError("eml too large (4 MiB cap)")
        import hashlib as _hashlib

        name = f"upload-{_hashlib.sha256(raw).hexdigest()[:16]}.eml"
        path = os.path.join(self.eml_spool, name)
        with open(path, "wb") as f:
            f.write(raw)
        return name

    def read_spooled_eml(self, name: str) -> bytes:
        if self.eml_spool is None:
            raise PermissionError("no --eml-spool directory configured on this server")
        path = os.path.realpath(os.path.join(self.eml_spool, name))
        if os.path.dirname(path) != self.eml_spool:
            raise PermissionError("eml path escapes the spool directory")
        with open(path, "rb") as f:
            return f.read()

    # Wallet sessions: the reference derives the ECIES identity from a
    # wallet signature the wallet owner produces (NewOrderForm.tsx:35-64).
    # Here the signature doubles as the session secret: the FIRST call
    # for an address binds it, later calls must present the same bytes —
    # otherwise any third party could replay the address and decrypt the
    # off-ramper Venmo IDs the ECIES layer exists to hide.
    # DEMO LIMITATION: with the in-process chain there are no real wallet
    # keys, so the server cannot verify that a signature belongs to an
    # address (the reference proves ownership via signMessage + the wagmi
    # wallet, NewOrderForm.tsx:35-64).  First-use binds the secret; a
    # production deployment must verify an actual wallet signature over a
    # login message before binding.
    def onramper(self, address: str, signature: bytes = b"") -> OnRamper:
        if not signature:
            raise PermissionError("wallet secret required (it seeds the ECIES identity)")
        with self.lock:
            existing = self.onrampers.get(address)
            if existing is None:
                existing = OnRamper(address, self.ramp, signature)
                existing._session_sig = signature
                self.onrampers[address] = existing
            elif not hmac.compare_digest(existing._session_sig, signature):
                raise PermissionError(f"wrong wallet signature for {address}")
            return existing

    def pubkey_of(self, address: str) -> bytes:
        """The on-ramper's ECIES public key — public info by design (the
        reference stores it on-chain with the order, Ramp's encryptPublicKey);
        readable without the wallet secret."""
        with self.lock:
            s = self.onrampers.get(address)
            if s is None:
                raise ValueError(f"no on-ramper session for {address}")
            return s.account.public_key_bytes

    def offramper(self, address: str, venmo_id: str) -> OffRamper:
        with self.lock:
            off = self.offrampers.get(address)
            if off is None or off.venmo_id != venmo_id:
                off = OffRamper(address, self.ramp, venmo_id)
                self.offrampers[address] = off
            return off


_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>ZKP2P on-ramp (TPU)</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;max-width:60rem}
 table{border-collapse:collapse;width:100%}
 td,th{border:1px solid #ccc;padding:.35rem .6rem;text-align:left}
 form{margin:.8rem 0;padding:.8rem;border:1px solid #ddd;border-radius:6px}
 input{margin:.15rem .4rem .15rem 0}
 h1{font-size:1.3rem} h2{font-size:1.05rem}
 #msg{color:#06c;white-space:pre-wrap}
</style></head><body>
<h1>ZKP2P fiat on-ramp &mdash; TPU prover edition</h1>
<div id="msg"></div>
<h2>Orders</h2>
<table id="orders"><tr><th>id</th><th>on-ramper</th><th>amount</th><th>max pay</th><th>status</th></tr></table>
<h2>New order (on-ramper)</h2>
<form onsubmit="return post('/api/orders', this)">
 <input name="address" placeholder="wallet" required>
 <input name="signature" placeholder="wallet secret" type="password" required>
 <input name="amount" placeholder="USDC amount" required>
 <input name="max_amount_to_pay" placeholder="max to pay" required>
 <button>Post order</button></form>
<h2>Claim order (off-ramper)</h2>
<form onsubmit="return post('/api/claims', this)">
 <input name="address" placeholder="wallet" required>
 <input name="venmo_id" placeholder="venmo id" required>
 <input name="order_id" placeholder="order id" required>
 <input name="min_amount_to_pay" placeholder="min pay" required>
 <button>Claim</button></form>
<h2>Review claims (on-ramper)</h2>
<form onsubmit="return post('/api/claims-decrypted', this)">
 <input name="address" placeholder="wallet" required>
 <input name="signature" placeholder="wallet secret" type="password" required>
 <input name="order_id" placeholder="order id" required>
 <button>Decrypt</button></form>
<h2>Prove receipt &amp; on-ramp</h2>
<form onsubmit="return post('/api/onramp', this)">
 <input name="address" placeholder="wallet" required>
 <input name="signature" placeholder="wallet secret" type="password" required>
 <input name="order_id" placeholder="order id" required>
 <input name="claim_id" placeholder="claim id" required>
 <input name="eml_path" placeholder=".eml path (server-side)">
 <button>Prove + on-ramp</button></form>
<script>
async function refresh(){
  const r = await fetch('/api/orders'); const rows = await r.json();
  const t = document.getElementById('orders');
  t.innerHTML = '<tr><th>id</th><th>on-ramper</th><th>amount</th><th>max pay</th><th>status</th></tr>' +
    rows.map(o=>`<tr><td>${o.id}</td><td>${o.on_ramper}</td><td>${o.amount}</td><td>${o.max_amount_to_pay}</td><td>${o.status}</td></tr>`).join('');
}
function say(x){document.getElementById('msg').textContent=JSON.stringify(x,null,1)}
async function post(url, f){
  const body = Object.fromEntries(new FormData(f));
  const r = await fetch(url, {method:'POST', headers:{'content-type':'application/json'}, body: JSON.stringify(body)});
  say(await r.json()); refresh(); return false;
}
refresh(); setInterval(refresh, 15000);  // MainPage.tsx 15s polling
</script></body></html>"""


def make_handler(app: OnrampApp):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _json(self, obj, code: int = 200):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("content-type", "application/json")
            self.send_header("content-length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read(self) -> Dict:
            if self.path == "/api/eml":
                n = int(self.headers.get("content-length", 0))
                if n > 4 * 1024 * 1024:  # bound memory BEFORE reading
                    raise PermissionError("eml too large (4 MiB cap)")
                return {"_raw": self.rfile.read(n)}
            n = int(self.headers.get("content-length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def do_GET(self):
            try:
                self._get()
            except PermissionError as e:
                self._json({"error": str(e)}, 403)
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                self._json({"error": f"{type(e).__name__}: {e}"}, 400)

        def _get(self):
            from urllib.parse import parse_qs, urlparse

            u = urlparse(self.path)
            if u.path == "/":
                body = _PAGE.encode()
                self.send_response(200)
                self.send_header("content-type", "text/html")
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif u.path == "/api/orders":
                # paging (the reference MainPage's table paging): plain
                # offset/limit over the id-sorted book, total included so
                # the client can render page controls
                q = parse_qs(u.query)
                offset = max(0, int(q.get("offset", ["0"])[0]))
                limit_raw = q.get("limit", [None])[0]
                limit = max(0, int(limit_raw)) if limit_raw is not None else None
                all_rows = app.ramp.get_all_orders()
                page = all_rows[offset : offset + limit] if limit is not None else all_rows[offset:]
                rows = [
                    {
                        "id": oid,
                        "on_ramper": o.on_ramper,
                        "amount": o.amount,
                        "max_amount_to_pay": o.max_amount_to_pay,
                        "status": o.status.name,
                    }
                    for oid, o in page
                ]
                if "offset" in q or "limit" in q:
                    self._json({"orders": rows, "total": len(all_rows), "offset": offset})
                else:  # legacy shape: bare list
                    self._json(rows)
            elif u.path == "/api/zkey-progress":
                with app.lock:
                    self._json(dict(app.zkey_fetch))
            elif u.path == "/api/meta":
                # chain-glue registry (the reference's contract address +
                # ABI constants, contracts.ts): everything a client needs
                # to bind to this deployment
                self._json(
                    {
                        "ramp_address": app.ramp.address,
                        "usdc_address": "usdc",
                        "max_amount_usdc": app.ramp.max_amount,
                        "venmo_rsa_limbs": [str(v) for v in app.ramp.venmo_mailserver_keys],
                        "msg_len": MSG_LEN,
                        "prover_loaded": app.prover is not None,
                        "onramp_calldata": f"onRamp(uint[2] a, uint[2][2] b, uint[2] c, uint[{MSG_LEN}] signals)",
                    }
                )
            else:
                self._json({"error": "not found"}, 404)

        def do_POST(self):
            try:
                payload = self._read()
                if self.path == "/api/claims-decrypted":
                    # POST so the wallet secret travels in the body, not
                    # in query strings / proxy logs / browser history.
                    views = app.onramper(
                        payload["address"], payload.get("signature", "").encode()
                    ).decrypt_claims(int(payload["order_id"]))
                    self._json(
                        [
                            {
                                "claim_id": v.claim_id,
                                "venmo_id": v.venmo_id,
                                "matches": v.hash_matches,
                                "min_amount_to_pay": v.min_amount_to_pay,
                            }
                            for v in views
                        ]
                    )
                elif self.path == "/api/orders":
                    ramper = app.onramper(payload["address"], payload.get("signature", "").encode())
                    oid = ramper.post_order(
                        int(payload["amount"]), int(payload["max_amount_to_pay"])
                    )
                    self._json({"order_id": oid})
                elif self.path == "/api/claims":
                    off = app.offramper(payload["address"], payload["venmo_id"])
                    # escrow needs USDC: demo-mint like the Goerli FakeUSDC
                    order = app.ramp.orders[int(payload["order_id"])]
                    app.usdc.mint(payload["address"], order.amount)
                    app.usdc.approve(payload["address"], app.ramp.address, order.amount)
                    on_pk = app.pubkey_of(order.on_ramper)
                    cid = off.claim_order(
                        int(payload["order_id"]), on_pk, int(payload["min_amount_to_pay"])
                    )
                    self._json({"claim_id": cid})
                elif self.path == "/api/eml":
                    # drag-and-drop equivalent: raw .eml bytes in the body
                    name = app.spool_eml(payload["_raw"])
                    self._json({"eml_path": name})
                elif self.path == "/api/zkey-fetch":
                    app.start_zkey_fetch()  # paths are server config only
                    self._json({"ok": True})
                elif self.path == "/api/onramp":
                    if app.prover is None:
                        self._json({"error": "prover bundle not loaded on this server"}, 503)
                        return
                    from ..inputs.email import email_from_eml, make_test_key, make_venmo_email

                    if payload.get("eml_path"):
                        email = email_from_eml(app.read_spooled_eml(payload["eml_path"]))
                        modulus = email.modulus
                    else:  # synthetic demo receipt
                        key = make_test_key(1)
                        email = make_venmo_email(
                            key,
                            raw_id=str(payload.get("raw_id", "1234567891234567891")),
                            amount=str(payload.get("amount", "30")),
                        )
                        modulus = key.n
                    ramper = app.onramper(payload["address"], payload.get("signature", "").encode())
                    inputs = ramper.prove_and_onramp(
                        app.prover.cs,
                        app.prover.dpk,
                        email,
                        modulus,
                        int(payload["order_id"]),
                        int(payload["claim_id"]),
                        app.prover.params,
                        app.prover.layout,
                    )
                    self._json({"ok": True, "public_signals": [str(s) for s in inputs.public_signals]})
                else:
                    self._json({"error": "not found"}, 404)
            except PermissionError as e:
                self._json({"error": str(e)}, 403)
            except Exception as e:  # noqa: BLE001 — surfaced to the client
                self._json({"error": f"{type(e).__name__}: {e}"}, 400)

    return Handler


def serve(app: OnrampApp, port: int = 8080) -> ThreadingHTTPServer:
    """Start the UI server (returns it; call .shutdown() to stop)."""
    srv = ThreadingHTTPServer(("127.0.0.1", port), make_handler(app))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
