"""Client-side claim cryptography: deterministic identity + ECIES.

Rebuild of `app/src/helpers/messagEncryption.ts:5-46` (eth-crypto ECIES):
  - generate_account_from_signature: wallet signature -> sha512 -> secp256k1
    keypair (the deterministic "encryption identity" the on-ramper derives
    by signing a login message, NewOrderForm.tsx:35-64)
  - encrypt_message / decrypt_message: ECIES over secp256k1 — ephemeral
    ECDH, SHA-512 KDF, AES-256-CTR + HMAC-SHA256 (encrypt-then-MAC).
    (The reference's eth-crypto uses AES-CBC; CTR needs no inverse cipher
    and is equivalent here — both sides of this flow are in-framework.)

All primitives are pure Python/stdlib: zero-egress environments have no
pip, and none of this is on the proving hot path.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass
from typing import Optional, Tuple

# ---------------------------------------------------------- secp256k1

_P = 2**256 - 2**32 - 977
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_G = (
    0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)

Point = Optional[Tuple[int, int]]


def _inv(a: int, m: int = _P) -> int:
    return pow(a, m - 2, m)


def _add(p: Point, q: Point) -> Point:
    if p is None:
        return q
    if q is None:
        return p
    if p[0] == q[0]:
        if (p[1] + q[1]) % _P == 0:
            return None
        lam = (3 * p[0] * p[0]) * _inv(2 * p[1]) % _P
    else:
        lam = (q[1] - p[1]) * _inv(q[0] - p[0]) % _P
    x = (lam * lam - p[0] - q[0]) % _P
    return (x, (lam * (p[0] - x) - p[1]) % _P)


def _mul(p: Point, k: int) -> Point:
    acc: Point = None
    while k:
        if k & 1:
            acc = _add(acc, p)
        p = _add(p, p)
        k >>= 1
    return acc


def _ser_pub(pt: Point) -> bytes:
    assert pt is not None
    return b"\x04" + pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


def _parse_pub(data: bytes) -> Point:
    """Parse + validate an uncompressed public key.

    Rejects anything not a finite point on secp256k1 itself: coordinates
    must be < p and satisfy y^2 = x^3 + 7.  The add/double formulas never
    use the curve's b, so small-order points on twist curves would pass
    arithmetically — combined with the MAC check acting as an oracle that
    is the classic invalid-curve key-recovery attack on the static
    identity key.  Validation here closes it for both encrypt (recipient
    key) and decrypt (attacker-supplied ephemeral key).
    """
    if len(data) != 65 or data[0] != 4:
        raise ValueError("bad public key encoding")
    x = int.from_bytes(data[1:33], "big")
    y = int.from_bytes(data[33:], "big")
    if x >= _P or y >= _P:
        raise ValueError("public key coordinate out of range")
    if (y * y - (x * x * x + 7)) % _P != 0:
        raise ValueError("point not on secp256k1")
    return (x, y)


# ---------------------------------------------------------------- AES

_SBOX = None


def _aes_sbox():
    global _SBOX
    if _SBOX is not None:
        return _SBOX
    sbox = [0] * 256
    p = q = 1
    sbox[0] = 0x63
    while True:
        # multiply p by 3 in GF(2^8)
        p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
        # divide q by 3
        q ^= (q << 1) & 0xFF
        q ^= (q << 2) & 0xFF
        q ^= (q << 4) & 0xFF
        q ^= 0x09 if q & 0x80 else 0
        x = q ^ ((q << 1) | (q >> 7)) & 0xFF ^ ((q << 2) | (q >> 6)) & 0xFF ^ ((q << 3) | (q >> 5)) & 0xFF ^ ((q << 4) | (q >> 4)) & 0xFF
        sbox[p] = (x ^ 0x63) & 0xFF
        if p == 1:
            break
    _SBOX = sbox
    return sbox


def _xtime(a: int) -> int:
    a <<= 1
    return (a ^ 0x1B) & 0xFF if a & 0x100 else a


def _aes_expand_key(key: bytes):
    sbox = _aes_sbox()
    nk = len(key) // 4
    nr = nk + 6
    w = [list(key[4 * i : 4 * i + 4]) for i in range(nk)]
    rcon = 1
    for i in range(nk, 4 * (nr + 1)):
        t = list(w[i - 1])
        if i % nk == 0:
            t = t[1:] + t[:1]
            t = [sbox[b] for b in t]
            t[0] ^= rcon
            rcon = _xtime(rcon)
        elif nk > 6 and i % nk == 4:
            t = [sbox[b] for b in t]
        w.append([a ^ b for a, b in zip(w[i - nk], t)])
    return w, nr


def _aes_encrypt_block(block: bytes, w, nr) -> bytes:
    sbox = _aes_sbox()
    s = [list(block[i::4]) for i in range(4)]  # state[r][c] = block[r + 4c]

    def add_round_key(rnd):
        for c in range(4):
            for r in range(4):
                s[r][c] ^= w[4 * rnd + c][r]

    add_round_key(0)
    for rnd in range(1, nr + 1):
        for r in range(4):
            for c in range(4):
                s[r][c] = sbox[s[r][c]]
        for r in range(1, 4):
            s[r] = s[r][r:] + s[r][:r]
        if rnd != nr:
            for c in range(4):
                a = [s[r][c] for r in range(4)]
                s[0][c] = _xtime(a[0]) ^ (_xtime(a[1]) ^ a[1]) ^ a[2] ^ a[3]
                s[1][c] = a[0] ^ _xtime(a[1]) ^ (_xtime(a[2]) ^ a[2]) ^ a[3]
                s[2][c] = a[0] ^ a[1] ^ _xtime(a[2]) ^ (_xtime(a[3]) ^ a[3])
                s[3][c] = (_xtime(a[0]) ^ a[0]) ^ a[1] ^ a[2] ^ _xtime(a[3])
        add_round_key(rnd)
    return bytes(s[r][c] for c in range(4) for r in range(4))


def _aes_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    w, nr = _aes_expand_key(key)
    out = bytearray()
    counter = int.from_bytes(iv, "big")
    for off in range(0, len(data), 16):
        ks = _aes_encrypt_block(counter.to_bytes(16, "big"), w, nr)
        chunk = data[off : off + 16]
        out.extend(b ^ k for b, k in zip(chunk, ks))
        counter = (counter + 1) % (1 << 128)
    return bytes(out)


# --------------------------------------------------------------- ECIES


@dataclass
class Account:
    private_key: int
    public_key: Point

    @property
    def public_key_bytes(self) -> bytes:
        return _ser_pub(self.public_key)


def generate_account_from_signature(signature: bytes) -> Account:
    """signature -> sha512 -> private key (messagEncryption.ts:5-23)."""
    seed = hashlib.sha512(signature).digest()
    priv = int.from_bytes(seed[:32], "big") % _N or 1
    return Account(private_key=priv, public_key=_mul(_G, priv))


def _kdf(shared_x: int) -> Tuple[bytes, bytes]:
    h = hashlib.sha512(shared_x.to_bytes(32, "big")).digest()
    return h[:32], h[32:]


def encrypt_message(message: bytes, recipient_pub: bytes, rng: Optional[bytes] = None) -> bytes:
    """ECIES: ephemeral_pub(65) || iv(16) || mac(32) || ciphertext."""
    eph_priv = int.from_bytes(rng or os.urandom(32), "big") % _N or 1
    eph_pub = _mul(_G, eph_priv)
    shared = _mul(_parse_pub(recipient_pub), eph_priv)
    if shared is None:
        raise ValueError("degenerate ECDH shared secret")
    enc_key, mac_key = _kdf(shared[0])
    iv = (rng and hashlib.sha256(rng).digest()[:16]) or os.urandom(16)
    ct = _aes_ctr(enc_key, iv, message)
    mac = hmac.new(mac_key, iv + ct, hashlib.sha256).digest()
    return _ser_pub(eph_pub) + iv + mac + ct


def decrypt_message(blob: bytes, account: Account) -> bytes:
    eph_pub = _parse_pub(blob[:65])
    iv, mac, ct = blob[65:81], blob[81:113], blob[113:]
    shared = _mul(eph_pub, account.private_key)
    if shared is None:
        raise ValueError("degenerate ECDH shared secret")
    enc_key, mac_key = _kdf(shared[0])
    if not hmac.compare_digest(mac, hmac.new(mac_key, iv + ct, hashlib.sha256).digest()):
        raise ValueError("ECIES MAC mismatch")
    return _aes_ctr(enc_key, iv, ct)
