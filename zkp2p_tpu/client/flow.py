"""The client order flow — the functional core of the reference SPA (L4).

What the React components do, minus the DOM (SURVEY.md §3.3):
  OnRamper.post_order      ~ NewOrderForm.tsx:35-105 (derive ECIES identity
                             from a wallet signature, post with pubkey*)
  OffRamper.claim_order    ~ ClaimOrderForm.tsx:56-104 (encrypt the Venmo
                             id to the on-ramper, Poseidon-hash it)
  OnRamper.decrypt_claims  ~ SubmitOrderClaimsForm.tsx:110-207 (decrypt,
                             re-hash, report Matches / Does Not Match)
  OnRamper.prove_and_onramp ~ SubmitOrderGenerateProofForm.tsx:150-229 +
                             SubmitOrderOnRampForm.tsx:36-59 (email ->
                             inputs -> TPU prove -> submit)

*The reference stores the encrypt pubkey alongside the order; our Ramp
model keeps the order book minimal, so the pubkey travels with the
OnRamper object — same trust shape, the chain never checks it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..contracts.ramp import Ramp
from ..inputs.email import SyntheticEmail, VenmoInputs, generate_inputs, venmo_id_hash
from . import crypto


@dataclass
class ClaimView:
    claim_id: int
    venmo_id: str
    hash_matches: bool
    min_amount_to_pay: int


class OnRamper:
    def __init__(self, address: str, ramp: Ramp, wallet_signature: bytes):
        self.address = address
        self.ramp = ramp
        self.account = crypto.generate_account_from_signature(wallet_signature)

    def post_order(self, amount: int, max_amount_to_pay: int) -> int:
        return self.ramp.post_order(self.address, amount, max_amount_to_pay)

    def decrypt_claims(self, order_id: int) -> List[ClaimView]:
        """Decrypt claimed Venmo ids and re-hash to verify
        (SubmitOrderClaimsForm's Matches / Does Not Match column)."""
        out = []
        for cid, claim in self.ramp.order_claims.get(order_id, {}).items():
            try:
                venmo_id = crypto.decrypt_message(claim.encrypted_off_ramper_venmo_id, self.account).decode()
                ok = venmo_id_hash(venmo_id) == claim.venmo_id_hash
            except Exception:
                venmo_id, ok = "", False
            out.append(ClaimView(cid, venmo_id, ok, claim.min_amount_to_pay))
        return out

    def prove_and_onramp(self, cs, dpk, email: SyntheticEmail, modulus: int, order_id: int, claim_id: int, params, layout) -> VenmoInputs:
        """Generate inputs, prove on TPU, submit to the escrow — the whole
        SubmitOrderGenerateProofForm -> SubmitOrderOnRampForm arc."""
        from ..prover.groth16_tpu import prove_tpu

        inputs = generate_inputs(email, modulus, order_id, claim_id, params, layout)
        w = cs.witness(inputs.public_signals, inputs.seed)
        proof = prove_tpu(dpk, w)
        self.ramp.on_ramp(self.address, proof, inputs.public_signals)
        return inputs


class OffRamper:
    def __init__(self, address: str, ramp: Ramp, venmo_id: str):
        self.address = address
        self.ramp = ramp
        self.venmo_id = venmo_id

    def claim_order(self, order_id: int, on_ramper_pubkey: bytes, min_amount_to_pay: int) -> int:
        encrypted = crypto.encrypt_message(self.venmo_id.encode(), on_ramper_pubkey)
        return self.ramp.claim_order(
            self.address,
            venmo_id_hash(self.venmo_id),
            order_id,
            encrypted,
            min_amount_to_pay,
        )
