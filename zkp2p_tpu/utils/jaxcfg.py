"""JAX runtime configuration helpers (shared by CLI / bench / tests).

The limb-arithmetic graphs are wide and XLA compiles them slowly; the
persistent compilation cache turns that into a once-per-checkout cost —
on every entry path, not just pytest (tests/conftest.py does the same).
"""

from __future__ import annotations

import os


def enable_cache(path: str | None = None) -> None:
    import jax

    cache = path or os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"),
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
