"""JAX runtime configuration helpers (shared by CLI / bench / tests).

The limb-arithmetic graphs are wide and XLA compiles them slowly; the
persistent compilation cache turns that into a once-per-checkout cost —
on every entry path, not just pytest (tests/conftest.py does the same).

The cache directory is keyed by a host-CPU fingerprint: XLA:CPU AOT
entries embed the compile machine's feature set and fail to load (with
"could lead to SIGILL" machine-feature-mismatch warnings) when the same
checkout moves to a host with different CPU features — the round-3
failure mode, where a cache written on one driver box poisoned the next
round's bench/dryrun with a storm of failed AOT loads + recompiles.
Keying by fingerprint makes every machine's entries self-contained:
a foreign cache is simply invisible instead of half-loadable.
"""

from __future__ import annotations

import hashlib
import os
import platform


def host_fingerprint() -> str:
    """Stable short hash of this host's CPU feature flags."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha256(feats.encode()).hexdigest()[:10]
    except OSError:
        pass
    return (platform.machine() or "unknown").replace("/", "_")


def cache_dir(base: str | None = None) -> str:
    # ZKP2P_JAX_CACHE_DIR (registered in utils.config KNOBS; raw read
    # here because this runs before jax import on every entry path)
    # overrides the conventional JAX_COMPILATION_CACHE_DIR so the
    # warm-cache command and its consumers (tools/sharded_scale.py, the
    # tpu-shard smoke) can share one pre-warmed root without touching
    # the global JAX env contract.
    base = (
        base
        or os.environ.get("ZKP2P_JAX_CACHE_DIR")
        or os.environ.get(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"),
        )
    )
    return os.path.join(base, host_fingerprint())


def enable_cache(path: str | None = None, min_compile_s: float = 1.0) -> None:
    # ZKP2P_NO_CACHE=1 is a global off-switch (every caller, including
    # in-process CLI drives inside the test suite): long full-suite runs
    # have segfaulted inside the persistent-cache WRITE path
    # (executable.serialize() under put_executable_and_time,
    # docs/logs/slow_suite_r4b crash stacks) — the green-log suite run
    # trades cache reuse for stability.
    if os.environ.get("ZKP2P_NO_CACHE") == "1":
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir(path))
    # min_compile_s: the default 1.0 keeps trivial executables out of the
    # cache; the warm-cache command and the tpu-shard smoke pass 0.0 so
    # the toy-circuit compiles (sub-second on the virtual mesh) round-trip
    # and the >=10x warm-start assertion has entries to hit.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", float(min_compile_s))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def on_tpu() -> bool:
    """True when the default JAX backend drives a real TPU.

    `jax.default_backend()` names the PJRT *plugin*, not the hardware:
    under the single-chip tunnel JAX_PLATFORMS is "axon" and every
    `default_backend() == "tpu"` gate silently routed the on-chip run to
    the XLA fallback paths (r5 bench1: the un-fused field mul's
    (batch, nnz, 16, 16) partial-product tensor OOM'd 15.75 G HBM at
    batch=16).  Match the device's platform attribute instead — the same
    rule tpu_probe_ok() uses, stable across plugin renames."""
    import jax

    v = jax.default_backend() == "tpu"
    if not v:
        try:
            devs = jax.devices()
            v = bool(devs) and getattr(devs[0], "platform", "") == "tpu"
        except Exception:
            v = False
    # every backend gate in the tree funnels through here — the ONE
    # record covers them all (lazy import: tools import this module
    # before jax/numpy are safe to load)
    from .audit import record_arm

    record_arm("on_tpu", "tpu" if v else "host")
    return v


# last structured probe outcome of this process (None = never probed):
# stamped into the run manifest and the BENCH JSON so "TPU TUNNEL DOWN"
# is a queryable record, not free text inside a unit string.
_last_probe: dict | None = None


def tpu_probe(timeout: int | None = None) -> dict:
    """Probe the TPU in a SUBPROCESS with a timeout; structured result.

    The axon plugin force-selects its platform through jax.config
    (overriding JAX_PLATFORMS) and a wedged tunnel makes backend init
    HANG rather than raise — so any entry point that must always
    complete (bench, the driver's entry() compile check) probes here
    first and pins `jax.config.update("jax_platforms", "cpu")` when the
    probe fails.  Timeout from BENCH_TPU_PROBE_TIMEOUT (default 120 s).
    Matches on the device's platform attribute, not the repr (which has
    changed across plugin versions).

    Returns {"ok", "rc", "timed_out", "seconds", "platform",
    "timeout_s"} and remembers it (`last_probe`)."""
    import subprocess
    import sys
    import time

    global _last_probe
    if timeout is None:
        timeout = int(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "120"))
    rec: dict = {
        "ok": False, "rc": None, "timed_out": False,
        "seconds": 0.0, "platform": None, "timeout_s": timeout,
    }
    t0 = time.perf_counter()
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=timeout, text=True,
        )
        rec["rc"] = probe.returncode
        out = probe.stdout.strip()
        rec["platform"] = out.splitlines()[-1] if out else None
        rec["ok"] = probe.returncode == 0 and "tpu" in probe.stdout.lower()
    except subprocess.TimeoutExpired:
        rec["timed_out"] = True
    rec["seconds"] = round(time.perf_counter() - t0, 3)
    _last_probe = rec
    return rec


def last_probe() -> dict | None:
    """The most recent tpu_probe() result this process (None if never)."""
    return _last_probe


def adopt_probe(rec: dict) -> None:
    """Seed last_probe() from a PARENT process's probe result (the bench
    guard probes in the parent and must not be re-run in the child — the
    single-chip tunnel dial blocks while anyone holds the chip)."""
    global _last_probe
    _last_probe = dict(rec)


def tpu_probe_ok(timeout: int | None = None) -> bool:
    """Boolean view of tpu_probe() (the historical API)."""
    return tpu_probe(timeout)["ok"]
