"""Perf-regression sentry: the fingerprint-keyed stage-cost ledger.

The engine has a fleet observability plane (docs/OBSERVABILITY.md) and
SLO accounting, but until now no LONGITUDINAL memory: per-stage costs
lived in loose `BENCH_*.json` tails and one-off interleaved A/Bs, so a
perf regression — the same silent-failure class as a gate disarm, just
in seconds instead of bytes — was only caught by a human rereading
bench output.  This module gives the repo that memory:

  - an append-only JSONL ledger beside `.bench_cache`
    (`perf_ledger_<fingerprint>.jsonl`, the `hostprof` 16-hex host key)
    recording per-(circuit, stage, arm-digest) p50/p95 span costs from
    bench runs, tune sweeps, warm-cache round trips and sampled live
    service sweeps;
  - per-stage BUDGETS derived from it (trailing-window median ×
    ZKP2P_PERF_TOLERANCE) that `service.py` checks every terminal
    request's spans against (`zkp2p_stage_budget_overruns_total`);
  - a committed baseline band (`PERF_BASELINE.json`) the `make
    perf-gate` target replays the ledger head against, exiting nonzero
    on drift — a machine-checked before/after for CI and the next
    hardware window instead of prose.

Trust model mirrors `hostprof`: every line is stamped with this host's
fingerprint key AND a content digest over its own body.  At read time,
foreign-fingerprint lines (a ledger copied from another box) and
digest-mismatched lines (a body edited after signing) are REFUSED and
counted, never silently blended into budgets — a budget derived from
someone else's hardware would page on every healthy request, and a
doctored history would hide the regression the sentry exists to catch.

Gating: ZKP2P_PERF_LEDGER (`perf_ledger` knob, default on) is
record_arm'd and preflight-armed like every other knob, so a
ledger-on/ledger-off A/B pair is digest-distinguishable on exactly
this gate.  Off means the WHOLE subsystem is off: no appends, no
budget loads, no overrun counting — the fail-closed oracle arm.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

SCHEMA_VERSION = 1
LEDGER_PREFIX = "perf_ledger_"
BASELINE_NAME = "PERF_BASELINE.json"

# a backfilled BENCH tail predates the execution-audit stamp in the
# parsed record; the constant groups history entries under one arm
BACKFILL_DIGEST = "backfill"

_lock = threading.Lock()
# (path, mtime_ns, window, tolerance) -> budgets dict (the service
# checks every terminal request; re-deriving budgets per request would
# re-read and re-sort the whole ledger on the prove hot path)
_budget_memo: Optional[Tuple[Tuple, Dict]] = None


def default_ledger_path() -> Optional[str]:
    """`<precomp cache dir>/perf_ledger_<fingerprint>.jsonl` — beside
    the `.bench_cache` tables and the host profile; None when
    persistence is disabled (ZKP2P_MSM_PRECOMP_CACHE=0)."""
    from ..prover.precomp import _cache_dir

    from .hostprof import fingerprint_key

    d = _cache_dir()
    if d is None:
        return None
    return os.path.join(d, LEDGER_PREFIX + fingerprint_key() + ".jsonl")


def default_baseline_path() -> str:
    """`<repo>/PERF_BASELINE.json` — the committed band `make
    perf-gate` replays the ledger head against."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(here, BASELINE_NAME)


def _entry_digest(body: Dict) -> str:
    """16-hex content digest over the entry body (entry_digest field
    excluded) — the hostprof embedded-key trick applied per line: a
    body edited after signing fails this check and is refused."""
    blob = json.dumps(
        {k: v for k, v in body.items() if k != "entry_digest"},
        sort_keys=True, separators=(",", ":"),
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def stage_stats(ms_values: List[float]) -> Optional[Dict]:
    """Nearest-rank p50/p95 over a span-cost sample (the trace_report
    percentile convention, so ledger entries and report tables agree)."""
    vals = sorted(float(v) for v in ms_values if v is not None)
    if not vals:
        return None

    def pct(p: float) -> float:
        i = max(0, min(len(vals) - 1, int(round(p / 100.0 * len(vals) + 0.5)) - 1))
        return vals[i]

    return {
        "p50_ms": round(pct(50), 3),
        "p95_ms": round(pct(95), 3),
        "n": len(vals),
    }


def make_entry(
    source: str,
    circuit: str,
    stages: Dict[str, Dict],
    run_id: Optional[str] = None,
    execution_digest: Optional[str] = None,
    extra: Optional[Dict] = None,
) -> Dict:
    """One signed ledger line: source ∈ {bench, tune, warm_cache,
    service, bench_backfill}, stages = {name: {p50_ms, p95_ms, n}}."""
    from .hostprof import fingerprint_key

    if execution_digest is None:
        from .audit import execution_digest as _xd

        execution_digest = _xd()
    body: Dict = {
        "schema": SCHEMA_VERSION,
        "ts": round(time.time(), 3),
        "source": source,
        "circuit": circuit,
        "fingerprint_key": fingerprint_key(),
        "execution_digest": execution_digest,
        "stages": {
            name: {
                "p50_ms": round(float(st["p50_ms"]), 3),
                "p95_ms": round(float(st.get("p95_ms", st["p50_ms"])), 3),
                "n": int(st.get("n", 1)),
            }
            for name, st in stages.items()
        },
    }
    if run_id:
        body["run_id"] = run_id
    if extra:
        body.update(extra)
    body["entry_digest"] = _entry_digest(body)
    return body


def append_entry(entry: Dict, path: Optional[str] = None) -> Optional[str]:
    """Append one line, atomically: a single O_APPEND write() per line
    (the JsonlSink/dump_trace discipline — concurrent workers' lines
    interleave whole, never torn).  Returns the path, None when
    persistence is off or the write failed (observation must never
    sink the measured work)."""
    path = path or default_ledger_path()
    if not path:
        return None
    line = (json.dumps(entry, sort_keys=True, separators=(",", ":")) + "\n").encode()
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)
    except OSError:
        return None
    _invalidate_memo()
    return path


def record(
    source: str,
    circuit: str,
    stages: Dict[str, Dict],
    run_id: Optional[str] = None,
    path: Optional[str] = None,
    extra: Optional[Dict] = None,
) -> Optional[str]:
    """Gate-checked stamp: resolve + arm the perf_ledger gate, append
    one entry when it is on.  The single producer-side entry point —
    bench, tune, warm-cache and the service all come through here, so
    the gate's off arm silences every producer at once."""
    if perf_arm() != "on":
        return None
    if not stages:
        return None
    return append_entry(
        make_entry(source, circuit, stages, run_id=run_id, extra=extra), path=path
    )


def perf_arm() -> str:
    """Resolve + arm the perf-ledger gate (the preflight hook):
    "on" | "off".  A ledger-on run must never share an execution
    digest with a ledger-off one."""
    from .audit import record_arm
    from .config import load_config

    return record_arm("perf_ledger", "on" if load_config().perf_ledger else "off")


def load_entries(path: Optional[str] = None) -> Tuple[List[Dict], Dict[str, int]]:
    """Every VALID entry in file (append) order, plus refusal counts.
    Refused like tampered host profiles — never blended into budgets:
      unparseable  — not one JSON object per line
      schema       — schema version drift
      foreign      — fingerprint key is not this host's
      tampered     — entry_digest does not match the body
    """
    from .hostprof import fingerprint_key

    refused = {"unparseable": 0, "schema": 0, "foreign": 0, "tampered": 0}
    path = path or default_ledger_path()
    if not path:
        return [], refused
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return [], refused
    me = fingerprint_key()
    out: List[Dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            e = json.loads(line)
        except ValueError:
            refused["unparseable"] += 1
            continue
        if not isinstance(e, dict) or not isinstance(e.get("stages"), dict):
            refused["unparseable"] += 1
            continue
        if e.get("schema") != SCHEMA_VERSION:
            refused["schema"] += 1
            continue
        if e.get("entry_digest") != _entry_digest(e):
            refused["tampered"] += 1  # body edited after signing
            continue
        if e.get("fingerprint_key") != me:
            refused["foreign"] += 1  # another box's costs: never budget from them
            continue
        out.append(e)
    return out, refused


def _invalidate_memo() -> None:
    global _budget_memo
    with _lock:
        _budget_memo = None


def reset() -> None:
    """Test hook: drop the budget memo (a test that rewrites the ledger
    under one process must not read the previous file's budgets)."""
    _invalidate_memo()


def derive_budgets(
    entries: List[Dict],
    window: Optional[int] = None,
    tolerance: Optional[float] = None,
) -> Dict[str, Dict[str, Dict]]:
    """{circuit: {stage: {budget_ms, median_ms, n, tolerance}}} from
    valid entries in ledger order.

    Per (circuit, stage): take the trailing `window` entries, keep only
    those sharing the HEAD entry's execution digest (mixing arms would
    blend two different cost distributions into one budget — the
    skipped count is recorded as arm_skipped), then
    budget = median(p50_ms) × tolerance.
    """
    from .config import load_config

    cfg = load_config()
    window = cfg.perf_window if window is None else max(1, int(window))
    tolerance = cfg.perf_tolerance if tolerance is None else float(tolerance)
    series: Dict[Tuple[str, str], List[Tuple[float, str, str]]] = {}
    for e in entries:
        circuit = str(e.get("circuit", "?"))
        digest = str(e.get("execution_digest", "?"))
        entry_d = str(e.get("entry_digest", "?"))
        for stage, st in e["stages"].items():
            try:
                p50 = float(st["p50_ms"])
            except (KeyError, TypeError, ValueError):
                continue
            series.setdefault((circuit, stage), []).append((p50, digest, entry_d))
    out: Dict[str, Dict[str, Dict]] = {}
    for (circuit, stage), rows in series.items():
        tail = rows[-window:]
        head_digest = tail[-1][1]
        vals = sorted(v for v, d, _ed in tail if d == head_digest)
        if not vals:
            continue
        # UPPER median (even-count windows take the higher middle): the
        # budget's job is to catch drift, not to page on the slower of
        # two equally-valid historical rounds — a lower-median two-entry
        # window would flag the round that produced it
        med = vals[len(vals) // 2]
        out.setdefault(circuit, {})[stage] = {
            "budget_ms": round(med * tolerance, 3),
            "median_ms": round(med, 3),
            "n": len(vals),
            "arm_skipped": len(tail) - len(vals),
            "tolerance": tolerance,
            # entry_digest of the HEAD ledger entry this budget window
            # is anchored to — a flame capture triggered by this budget
            # records it, so `zkp2p-tpu perf` can walk a DRIFT verdict
            # to the capture that explains it
            "head_digest": tail[-1][2],
        }
    return out


class BudgetBook:
    """The service-side view: per-stage budgets for ONE circuit, loaded
    once (memoized by ledger path+mtime) and consulted per terminal
    request with dict lookups only — the <1% overhead contract."""

    def __init__(self, budgets: Dict[str, Dict]):
        self._budgets = dict(budgets)

    def __len__(self) -> int:
        return len(self._budgets)

    def budget_ms(self, stage: str) -> Optional[float]:
        b = self._budgets.get(stage)
        return None if b is None else b["budget_ms"]

    def head_digest(self, stage: str) -> Optional[str]:
        """The ledger entry_digest this stage's budget window was
        filtered against (None for a stage with no budget) — what a
        triggered flame capture records as its cross-link."""
        b = self._budgets.get(stage)
        return None if b is None else b.get("head_digest")

    def over(self, stage: str, ms: Optional[float]) -> Optional[bool]:
        """True = over budget, False = within, None = NO budget for
        this stage (a fresh host / new stage must not page — the alert
        rule HOLDs on None)."""
        if ms is None:
            return None
        b = self._budgets.get(stage)
        if b is None:
            return None
        return float(ms) > b["budget_ms"]

    @classmethod
    def load(
        cls,
        circuit: str,
        path: Optional[str] = None,
        window: Optional[int] = None,
        tolerance: Optional[float] = None,
    ) -> "BudgetBook":
        """Budgets for `circuit` from the on-disk ledger; an EMPTY book
        (every check returns None) when the gate is off, persistence is
        off, or the ledger has no entries for this host."""
        global _budget_memo

        if perf_arm() != "on":
            return cls({})
        path = path or default_ledger_path()
        if not path:
            return cls({})
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            return cls({})
        key = (path, mtime, window, tolerance)
        with _lock:
            memo = _budget_memo
        if memo is not None and memo[0] == key:
            budgets = memo[1]
        else:
            entries, _refused = load_entries(path)
            budgets = derive_budgets(entries, window=window, tolerance=tolerance)
            with _lock:
                _budget_memo = (key, budgets)
        return cls(budgets.get(circuit, {}))


def tune_stages(profile: Dict) -> Dict[str, Dict]:
    """Ledger stages out of a `zkp2p-tpu tune` profile: the measured
    BEST wall time per sweep family (threads, window tags, columns).
    Best-of-arms is the regression-tracking quantity — a slower box
    moves the best, whichever arm wins it; per-arm spread is the tune
    sweep's own concern."""
    stages: Dict[str, Dict] = {}
    sweep = (profile.get("tune") or {}).get("sweep") or {}

    def best(rows: Dict, name: str) -> None:
        vals = [v for v in (rows or {}).values() if isinstance(v, (int, float))]
        if vals:
            ms = round(min(vals) * 1e3, 3)
            stages[name] = {"p50_ms": ms, "p95_ms": ms, "n": len(vals)}

    best(sweep.get("threads"), "tune/msm_threads_best")
    for tag, rows in (sweep.get("window") or {}).items():
        best(rows, f"tune/msm_window_{tag}")
    best(sweep.get("columns"), "tune/msm_columns_best")
    return stages


# --------------------------------------------------------------------------
# BENCH-history backfill: trendlines start with the committed history,
# not an empty file.


def _bench_tail_stages(tail: str) -> Dict[str, List[float]]:
    """Per-stage span samples out of a BENCH record's free-text tail
    (JSONL trace lines interleaved with log text).  Steady-rep stage
    paths are normalized (`prove_native_3/native/msm_h` →
    `native/msm_h`, `prove_native_3` → `prove_native`) so reps pool
    into one sample per stage."""
    stages: Dict[str, List[float]] = {}
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        st, ms = rec.get("stage"), rec.get("ms")
        if not isinstance(st, str) or not isinstance(ms, (int, float)):
            continue
        root, _, rest = st.partition("/")
        if root.startswith("prove_native"):
            st = rest if rest else "prove_native"
        stages.setdefault(st, []).append(float(ms))
    return stages


def backfill_bench(
    bench_glob: Optional[str] = None,
    path: Optional[str] = None,
    log=None,
) -> int:
    """Import the committed `BENCH_r*.json` tails as ledger entries
    (source=bench_backfill, one per successful round), idempotently:
    a round already in the ledger (matched by its `backfill_of` stamp)
    is skipped, so `make perf-gate` can run this unconditionally.

    The history predates the fingerprint stamp; entries are signed with
    THIS host's key on the documented assumption that the committed
    history and the gate run share the container image.  Returns the
    number of entries appended."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    bench_glob = bench_glob or os.path.join(here, "BENCH_r*.json")
    path = path or default_ledger_path()
    if not path:
        return 0
    entries, _refused = load_entries(path)
    seen = {e.get("backfill_of") for e in entries if e.get("backfill_of")}
    added = 0
    for bench_path in sorted(glob.glob(bench_glob)):
        name = os.path.basename(bench_path)
        if name in seen:
            continue
        try:
            with open(bench_path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if rec.get("rc") != 0:
            continue  # a failed round measured nothing
        stages = {
            st: stats
            for st, samples in _bench_tail_stages(rec.get("tail", "")).items()
            for stats in [stage_stats(samples)]
            if stats is not None
        }
        parsed = rec.get("parsed") or {}
        p50_s = parsed.get("p50_s")
        if not stages and p50_s is None:
            continue
        if p50_s is not None:
            stages.setdefault(
                "prove_native",
                {"p50_ms": round(float(p50_s) * 1e3, 3), "p95_ms": round(float(p50_s) * 1e3, 3), "n": 1},
            )
        entry = make_entry(
            "bench_backfill",
            "venmo",
            stages,
            run_id=parsed.get("run_id"),
            execution_digest=parsed.get("execution_digest") or BACKFILL_DIGEST,
            extra={"backfill_of": name},
        )
        if append_entry(entry, path=path):
            added += 1
            if log:
                log(f"perf: backfilled {name} ({len(stages)} stage(s))")
    return added


# --------------------------------------------------------------------------
# Baseline band + drift gate (`make perf-gate`).


def write_baseline(
    baseline_path: Optional[str] = None,
    ledger_path: Optional[str] = None,
    window: Optional[int] = None,
    tolerance: Optional[float] = None,
) -> Optional[Dict]:
    """Freeze the current budgets as the committed band (tmp+rename —
    a torn baseline must never judge a gate run).  None when the
    ledger is empty (an empty band would make every future gate
    vacuously green — fail closed instead)."""
    from .config import load_config
    from .hostprof import fingerprint_key

    cfg = load_config()
    entries, _refused = load_entries(ledger_path)
    if not entries:
        return None
    budgets = derive_budgets(entries, window=window, tolerance=tolerance)
    if not budgets:
        return None
    doc = {
        "schema": SCHEMA_VERSION,
        "generated_ts": round(time.time(), 3),
        "fingerprint_key": fingerprint_key(),
        "window": cfg.perf_window if window is None else int(window),
        "tolerance": cfg.perf_tolerance if tolerance is None else float(tolerance),
        "bands": budgets,
    }
    baseline_path = baseline_path or default_baseline_path()
    tmp = f"{baseline_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, baseline_path)
    except OSError:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None
    return doc


def gate_check(
    baseline_path: Optional[str] = None,
    ledger_path: Optional[str] = None,
    log=None,
) -> Tuple[int, List[Dict]]:
    """Replay the ledger HEAD (most recent valid entry per circuit/
    stage) against the committed band.  Returns (rc, verdict rows):

      rc 0 — every head stage with a band is within budget (rows may
             still carry the informational IMPROVED verdict: the head
             p50 lands well under the committed band — a stale-loose
             band that wants `zkp2p-tpu perf --rebaseline`)
      rc 1 — DRIFT: at least one head p50 exceeds its band
      rc 2 — fail closed: no baseline, or no valid ledger entries
             (a gate that cannot compare must not pass)

    Stages present on only one side are reported (`new` / `gone`) but
    do not fail the gate — adding instrumentation must not require a
    same-commit rebaseline.  A fingerprint mismatch between the band
    and this host is WARNED about and still compared: absolute ms on
    foreign hardware is suspect either way, and the warning names the
    remediation (`zkp2p-tpu perf --rebaseline`)."""
    log = log or (lambda m: None)
    baseline_path = baseline_path or default_baseline_path()
    try:
        with open(baseline_path) as f:
            base = json.load(f)
    except (OSError, ValueError):
        log(f"perf-gate: FAIL CLOSED — no readable baseline at {baseline_path}")
        return 2, []
    if not isinstance(base, dict) or base.get("schema") != SCHEMA_VERSION:
        log("perf-gate: FAIL CLOSED — baseline schema drift")
        return 2, []
    entries, refused = load_entries(ledger_path)
    if not entries:
        log(
            "perf-gate: FAIL CLOSED — no valid ledger entries for this host "
            f"(refused: {refused})"
        )
        return 2, []
    from .hostprof import fingerprint_key

    if base.get("fingerprint_key") != fingerprint_key():
        log(
            "perf-gate: WARNING — baseline was frozen on different hardware "
            f"({base.get('fingerprint_key')} vs {fingerprint_key()}); comparing "
            "anyway, rebaseline with `zkp2p-tpu perf --rebaseline`"
        )
    # head = last valid entry's p50 per (circuit, stage)
    head: Dict[Tuple[str, str], Dict] = {}
    for e in entries:
        for stage, st in e["stages"].items():
            head[(str(e.get("circuit", "?")), stage)] = {
                "p50_ms": st["p50_ms"],
                "source": e.get("source"),
                "execution_digest": e.get("execution_digest"),
            }
    bands = base.get("bands") or {}
    verdicts: List[Dict] = []
    rc = 0
    for (circuit, stage), h in sorted(head.items()):
        band = (bands.get(circuit) or {}).get(stage)
        if band is None:
            verdicts.append({
                "circuit": circuit, "stage": stage, "verdict": "new",
                "p50_ms": h["p50_ms"],
            })
            continue
        drift = float(h["p50_ms"]) > float(band["budget_ms"])
        # IMPROVED: the head p50 lands as far UNDER the band's median as
        # the tolerance allows over it (head * tol < median) — the band
        # is stale-loose and no longer guards the real floor.  Informs,
        # never fails: rc stays 0, the remediation is a rebaseline
        # (`zkp2p-tpu perf --rebaseline`) so the speedup becomes the
        # guarded floor instead of headroom a regression can hide in.
        tol = float(base.get("tolerance") or 1.5)
        improved = (not drift) and float(h["p50_ms"]) * tol < float(band["median_ms"])
        verdicts.append({
            "circuit": circuit, "stage": stage,
            "verdict": "DRIFT" if drift else ("IMPROVED" if improved else "ok"),
            "p50_ms": h["p50_ms"],
            "budget_ms": band["budget_ms"],
            "median_ms": band["median_ms"],
            "execution_digest": h["execution_digest"],
        })
        if drift:
            rc = 1
    for circuit, stages in sorted(bands.items()):
        for stage in sorted(stages):
            if (circuit, stage) not in head:
                verdicts.append({"circuit": circuit, "stage": stage, "verdict": "gone"})
    return rc, verdicts
