"""Service-level-objective engine for the proving service.

The prover core is instrumented to the nanosecond (stats block, trace
spans, execution digests) but nothing answered the question a
*deployment* asks: "are we meeting our latency objective right now, and
how fast are we burning the error budget?"  This module is that answer:
a rolling-window latency tracker with an explicit objective
(`ZKP2P_SLO_P95_S`), attainment + burn-rate math, gauges on the
Prometheus endpoint, and the `/status` JSON payload.

Definitions (the standard SRE framing):

  objective   latency bound in seconds over a request's FULL life —
              spool arrival (req-file mtime) to terminal artifact.
              0 = no objective configured (latencies still tracked).
  good        a request that terminal'd `done` within the objective
              (with no objective: any `done`).
  attainment  good / total over the rolling window (1.0 on an empty
              window — no traffic is not an outage).
  burn rate   (1 - attainment) / (1 - target): how many times faster
              than sustainable the error budget is burning.  1.0 =
              exactly at target; 0 = no misses; >1 = paging territory.

Fleet extension (docs/OBSERVABILITY.md §fleet plane): a worker's
rolling window SERIALIZES (`window_state()` — samples carried as
age-relative triples, so two processes with unrelated monotonic clocks
stay comparable) and N windows MERGE at the supervisor
(`merge_window_states()`) by pooling the raw samples and recomputing
attainment/percentiles over the pooled set — merged-sample
percentiles, never averaged percentiles (the mean of two p95s is not
any percentile of the fleet).  The merged snapshot also carries
multi-window burn rates: `burn_fast` over the trailing
`fast_window_s` slice and `burn_slow` over the full window — the
standard multi-window burn-rate alerting pair (fast catches a cliff,
slow suppresses a blip).

Design constraints match utils.metrics: stdlib only, GIL-cheap
`observe()` (deque append + opportunistic prune), bounded memory
(window cap, evictions counted), and observation must never fail the
prove around it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Hard cap on samples held regardless of the time window: a runaway
# arrival burst must not grow the deque unboundedly.  Evictions beyond
# the cap are counted in the snapshot (`capped`), never silent.
MAX_WINDOW_SAMPLES = 65536


class SloTracker:
    """Rolling-window latency/outcome tracker.

    `observe(latency_s, ok)` per terminal request; `snapshot()` computes
    attainment, burn rate, and exact window percentiles.  The clock is
    injectable (tests drive synthetic time)."""

    def __init__(
        self,
        objective_s: float = 0.0,
        target: float = 0.95,
        window_s: float = 300.0,
        clock=time.monotonic,
    ):
        self.objective_s = max(0.0, float(objective_s))
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0,1), got {target}")
        self.target = target
        self.window_s = max(0.0, float(window_s))
        self._clock = clock
        # (t, latency_s, good) triples, oldest first
        self._samples: deque = deque()
        self._lock = threading.Lock()
        self._capped = 0  # samples evicted by MAX_WINDOW_SAMPLES

    def _is_good(self, latency_s: float, ok: bool) -> bool:
        if not ok:
            return False
        return self.objective_s <= 0 or latency_s <= self.objective_s

    def observe(self, latency_s: float, ok: bool = True, now: Optional[float] = None) -> None:
        t = self._clock() if now is None else now
        with self._lock:
            self._samples.append((t, float(latency_s), self._is_good(latency_s, ok)))
            if len(self._samples) > MAX_WINDOW_SAMPLES:
                self._samples.popleft()
                self._capped += 1
            self._prune(t)

    def _prune(self, now: float) -> None:
        # caller holds the lock; window_s == 0 keeps everything (the
        # loadgen uses an unbounded-window tracker per ramp step)
        if self.window_s <= 0:
            return
        edge = now - self.window_s
        while self._samples and self._samples[0][0] < edge:
            self._samples.popleft()

    def snapshot(self, now: Optional[float] = None) -> Dict:
        """Attainment, burn rate, and window percentiles — the payload
        behind the `zkp2p_slo_*` gauges and `/status`."""
        t = self._clock() if now is None else now
        with self._lock:
            self._prune(t)
            samples = list(self._samples)
            capped = self._capped
        lats = sorted(s[1] for s in samples)
        n = len(samples)
        good = sum(1 for s in samples if s[2])
        # empty window = vacuous attainment: no traffic is not an outage
        attainment = (good / n) if n else 1.0
        burn = (1.0 - attainment) / (1.0 - self.target)

        def pct(q: float) -> float:
            if not lats:
                return 0.0
            k = max(0, min(n - 1, int(round(q * (n - 1)))))
            return lats[k]

        return {
            "objective_p95_s": self.objective_s,
            "target": self.target,
            "window_s": self.window_s,
            "n": n,
            "good": good,
            "attainment": round(attainment, 6),
            "burn_rate": round(burn, 4),
            "p50_s": round(pct(0.50), 6),
            "p95_s": round(pct(0.95), 6),
            "max_s": round(lats[-1], 6) if lats else 0.0,
            "capped": capped,
        }

    def window_state(self, max_samples: int = 4096, now: Optional[float] = None) -> Dict:
        """Serializable window for cross-process merging (heartbeats,
        the worker `/snapshot` route): samples travel as
        [age_s, latency_s, good] triples — ages, not timestamps,
        because each worker's monotonic clock has its own epoch and a
        raw `t` would be meaningless at the supervisor.  Newest-last;
        when the window exceeds `max_samples` the OLDEST are dropped
        and counted in `dropped` (n stays the true window size, so the
        merged fleet sample count still equals the sum of worker
        windows even when a transport cap trimmed the payload)."""
        t = self._clock() if now is None else now
        with self._lock:
            self._prune(t)
            samples = list(self._samples)
            capped = self._capped
        dropped = max(0, len(samples) - max_samples)
        kept = samples[dropped:]
        return {
            "objective_s": self.objective_s,
            "target": self.target,
            "window_s": self.window_s,
            "n": len(samples),
            "samples": [
                [round(max(0.0, t - s[0]), 3), round(s[1], 6), 1 if s[2] else 0]
                for s in kept
            ],
            "dropped": dropped,
            "capped": capped,
        }


def merge_window_states(
    states: List[Dict],
    fast_window_s: float = 60.0,
    target: Optional[float] = None,
) -> Dict:
    """Merge N serialized worker windows into ONE fleet SLO snapshot.

    The merge pools the raw (age, latency, good) samples and recomputes
    attainment and percentiles over the pooled set — exactly what a
    single tracker observing every worker's traffic would report
    (tests pin this against a pooled oracle).  Averaging the workers'
    snapshots instead would weight an idle worker's vacuous 1.0
    attainment equally with a drowning worker's 0.5, and the mean of
    per-worker p95s is not any percentile of anything.

    `n` = sum of the true worker window sizes (including samples a
    transport cap dropped); percentiles/attainment are computed over
    the samples that actually arrived (`n_merged`).  Burn rates come in
    the multi-window pair: `burn_slow` over every pooled sample,
    `burn_fast` over the trailing `fast_window_s` by age — fast
    detects a fresh cliff in seconds, slow stops a single blip from
    paging (utils.alerts fires on the AND of the two)."""
    states = [s for s in states if s]
    tgt = target
    if tgt is None:
        tgt = max((s.get("target", 0.95) for s in states), default=0.95)
    if not 0.0 < tgt < 1.0:
        tgt = 0.95
    pooled: List[List[float]] = []  # [age_s, latency_s, good]
    n_true = 0
    capped = 0
    objective = 0.0
    for s in states:
        pooled.extend(s.get("samples") or [])
        n_true += int(s.get("n", len(s.get("samples") or [])))
        capped += int(s.get("capped", 0))
        objective = max(objective, float(s.get("objective_s", 0.0)))

    def _burn(sub: List[List[float]]) -> Dict:
        k = len(sub)
        good = sum(1 for x in sub if x[2])
        att = (good / k) if k else 1.0
        return {"n": k, "good": good, "attainment": round(att, 6),
                "burn": round((1.0 - att) / (1.0 - tgt), 4)}

    full = _burn(pooled)
    fast = _burn([x for x in pooled if x[0] <= fast_window_s])
    lats = sorted(x[1] for x in pooled)

    def pct(q: float) -> float:
        if not lats:
            return 0.0
        k = max(0, min(len(lats) - 1, int(round(q * (len(lats) - 1)))))
        return lats[k]

    return {
        "objective_p95_s": objective,
        "target": tgt,
        "fast_window_s": fast_window_s,
        "workers": len(states),
        "n": n_true,
        "n_merged": full["n"],
        "good": full["good"],
        "attainment": full["attainment"],
        "burn_slow": full["burn"],
        "burn_fast": fast["burn"],
        "n_fast": fast["n"],
        "p50_s": round(pct(0.50), 6),
        "p95_s": round(pct(0.95), 6),
        "max_s": round(lats[-1], 6) if lats else 0.0,
        "capped": capped,
    }


def publish_fleet_slo(snap: Dict, registry=None) -> None:
    """Mirror a merged fleet snapshot into `zkp2p_fleet_slo_*` gauges
    (the supervisor's /metrics view of the merged windows)."""
    from .metrics import REGISTRY

    reg = registry if registry is not None else REGISTRY
    reg.gauge("zkp2p_fleet_slo_attainment").set(snap["attainment"])
    reg.gauge("zkp2p_fleet_slo_burn_fast").set(snap["burn_fast"])
    reg.gauge("zkp2p_fleet_slo_burn_slow").set(snap["burn_slow"])
    reg.gauge("zkp2p_fleet_slo_window_p95_s").set(snap["p95_s"])
    reg.gauge("zkp2p_fleet_slo_window_requests").set(snap["n"])
    reg.gauge("zkp2p_fleet_slo_objective_s").set(snap["objective_p95_s"])


# ---------------------------------------------------------------------------
# Process-wide default tracker, resolved once from the typed config (the
# service and the exposition endpoint share ONE window; a per-consumer
# tracker would let /status and /metrics disagree about attainment).

_default: Optional[SloTracker] = None
_default_lock = threading.Lock()


def default_tracker() -> SloTracker:
    global _default
    with _default_lock:
        if _default is None:
            from .config import load_config

            cfg = load_config()
            _default = SloTracker(
                objective_s=cfg.slo_p95_s, target=cfg.slo_target, window_s=cfg.slo_window_s
            )
        return _default


def _reset() -> None:
    """Drop the default tracker so the next consumer re-reads the config
    (tests)."""
    global _default
    with _default_lock:
        _default = None


def publish_slo(registry=None) -> Dict:
    """Refresh the `zkp2p_slo_*` gauges from the default tracker (called
    per terminal record and per scrape); returns the snapshot."""
    from .metrics import REGISTRY

    reg = registry if registry is not None else REGISTRY
    snap = default_tracker().snapshot()
    reg.gauge("zkp2p_slo_attainment").set(snap["attainment"])
    reg.gauge("zkp2p_slo_burn_rate").set(snap["burn_rate"])
    reg.gauge("zkp2p_slo_window_p95_s").set(snap["p95_s"])
    reg.gauge("zkp2p_slo_window_requests").set(snap["n"])
    reg.gauge("zkp2p_slo_objective_s").set(snap["objective_p95_s"])
    return snap


# ---------------------------------------------------------------------------
# Audit gates: the SLO objective and the time-series sampler are service
# observability arms — two runs with different objectives (or sampler
# on/off) must be digest-distinguishable, exactly like the fault gate.


def slo_arm() -> str:
    """record_arm the SLO configuration: 'off' or 'p95=<s>s@<target>'."""
    from .audit import record_arm
    from .config import load_config

    cfg = load_config()
    arm = "off" if cfg.slo_p95_s <= 0 else f"p95={cfg.slo_p95_s:g}s@{cfg.slo_target:g}"
    return record_arm("service_slo", arm)


def timeseries_arm() -> str:
    """record_arm the sampler interval: 'off' or '<interval>s'."""
    from .audit import record_arm
    from .config import load_config

    cfg = load_config()
    arm = "off" if cfg.ts_sample_s <= 0 else f"{cfg.ts_sample_s:g}s"
    return record_arm("service_timeseries", arm)


# ---------------------------------------------------------------------------
# /status payload.  Fails CLOSED while preflight has not run: a scrape
# that answers "healthy" before the gates were armed would report a
# service whose code paths nobody has proven — the round-2 silent-disarm
# lesson applied to the health surface.

_t_start = time.time()


def status_payload() -> Dict:
    """The `/status` JSON: ok flag (preflight-gated), SLO snapshot,
    request-state counters, rescue-ladder counters, and identity.  The
    HTTP layer maps ok=False to a 503."""
    import os

    from .audit import execution_digest, last_preflight
    from .metrics import REGISTRY, run_id

    pf = last_preflight()
    body: Dict = {
        "ok": pf is not None,
        "ts": round(time.time(), 3),
        "run_id": run_id(),
        "pid": os.getpid(),
        "uptime_s": round(time.time() - _t_start, 3),
        "execution_digest": execution_digest(),
    }
    # fleet identity (when a supervisor stamped it): lets a scrape of N
    # auto-ported workers say WHICH worker answered
    try:
        from .config import load_config

        cfg = load_config()
        if cfg.worker_id:
            body["worker"] = cfg.worker_id
        if cfg.fleet_id:
            body["fleet"] = cfg.fleet_id
    except Exception:  # noqa: BLE001 — identity is optional
        pass
    if pf is None:
        body["reason"] = "preflight has not run (gates unarmed; see zkp2p-tpu doctor)"
    else:
        body["preflight"] = pf
    body["slo"] = default_tracker().snapshot()
    # request-state + rescue counters out of the registry snapshot (the
    # registry exposes no by-name getter on purpose — get-or-create
    # would mint zero-valued instruments on every status read)
    states: Dict[str, float] = {}
    counters: Dict[str, float] = {}
    wanted = {
        "zkp2p_service_retries_total": "retries",
        "zkp2p_service_bisections_total": "bisections",
        "zkp2p_service_deadline_total": "deadline",
        "zkp2p_service_shed_total": "shed",
        "zkp2p_service_emit_failures_total": "emit_failures",
        "zkp2p_service_deferred_total": "deferred",
    }
    for rec in REGISTRY.snapshot():
        name = rec["name"]
        if name == "zkp2p_service_requests_total":
            states[rec["labels"].get("state", "?")] = rec["value"]
        elif name in wanted:
            counters[wanted[name]] = counters.get(wanted[name], 0) + rec["value"]
        elif name == "zkp2p_service_degraded_total":
            counters["degraded"] = counters.get("degraded", 0) + rec["value"]
        elif name == "zkp2p_service_takeovers_total":
            key = "takeovers_" + rec["labels"].get("result", "?")
            counters[key] = counters.get(key, 0) + rec["value"]
    body["requests"] = states
    body["counters"] = counters
    return body
