"""Alert engine for the fleet observability plane.

The supervisor aggregates signals nothing per-worker can see — fleet
burn rate over the MERGED SLO windows, spool backlog trend, restart
storms, breaker parks, lingering governor degrades, heartbeat gaps —
and something has to turn those time-series into a bounded set of
actionable facts.  This module is that something: a small rule
evaluator with explicit HYSTERESIS, so a signal flapping across its
threshold raises ONE alert (and later ONE clear), not a stream of
page-worthy transitions every evaluation tick.

State machine per rule (docs/OBSERVABILITY.md §fleet plane):

  ok --cond true--> pending --held for_s--> FIRING --cond false
     <--cond false--          (counter+log)    held clear_s--> ok

  * `for_s`   how long the condition must hold before firing — a
    single noisy evaluation never pages;
  * `clear_s` how long the condition must be CONTINUOUSLY false before
    a firing alert clears — the flap damper; a re-trip inside clear_s
    keeps the ORIGINAL alert firing (same `since`, no new counter inc).
  * a rule whose signal is absent this tick (condition returns None)
    holds its current state — missing data is not evidence either way.

Transitions land in four places at once: the returned transition list
(the caller logs them), `zkp2p_fleet_alerts_total{rule}` (fires only),
the engine's `active()`/`state()` views (fleet status.json + the
`/status` payload), and the caller's log lines.  Evaluation is pure
over (signals, now) — tests drive synthetic time-series with an
injected clock, and the supervisor drives wall-clock.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional


@dataclass
class Rule:
    """One alert rule: `cond(signals)` returns True (condition met),
    False (not met), or None (no data this tick — hold state).
    `detail(signals)` renders the human one-liner stamped on the alert
    at fire time (threshold + observed value)."""

    name: str
    cond: Callable[[Dict], Optional[bool]]
    for_s: float = 0.0
    clear_s: float = 30.0
    detail: Optional[Callable[[Dict], str]] = None


@dataclass
class _RuleState:
    firing: bool = False
    since: float = 0.0           # fire time while firing
    pending_since: Optional[float] = None
    clear_since: Optional[float] = None
    fired_count: int = 0
    last_detail: str = ""


class TrendTracker:
    """Rolling (t, value) history for trend rules (backlog growth):
    `update()` per evaluation, `growing(window_s)` answers "did the
    value rise by >= min_delta across the last window_s, with enough
    history to judge?".  Insufficient history returns None (hold state)
    rather than False — a freshly started supervisor must not CLEAR a
    real backlog alert just because it forgot the past."""

    def __init__(self, keep_s: float = 600.0):
        self.keep_s = keep_s
        self._hist: deque = deque()  # (t, value), oldest first

    def update(self, now: float, value: float) -> None:
        self._hist.append((now, float(value)))
        edge = now - self.keep_s
        while self._hist and self._hist[0][0] < edge:
            self._hist.popleft()

    def growing(self, window_s: float, now: float, min_delta: float = 1.0) -> Optional[bool]:
        if not self._hist:
            return None
        base = None
        for t, v in self._hist:
            if t <= now - window_s:
                base = v
            else:
                break
        if base is None:
            # history does not yet span the window: only a confident
            # False (value at/near zero) is safe to report
            return False if self._hist[-1][1] <= 0 else None
        cur = self._hist[-1][1]
        return cur > 0 and (cur - base) >= min_delta

    def delta(self, window_s: float, now: float) -> Optional[float]:
        """value_now − value_at(now − window_s) for cumulative signals
        (restart counts).  History not yet spanning the window uses the
        oldest sample as the base — an under-estimate, never an
        invented spike.  No history at all returns None."""
        if not self._hist:
            return None
        base = self._hist[0][1]
        for t, v in self._hist:
            if t <= now - window_s:
                base = v
            else:
                break
        return self._hist[-1][1] - base


class AlertEngine:
    def __init__(
        self,
        rules: List[Rule],
        registry=None,
        log: Optional[Callable[[str], None]] = None,
        clock=time.time,
    ):
        self.rules = list(rules)
        self._states: Dict[str, _RuleState] = {r.name: _RuleState() for r in self.rules}
        self._registry = registry
        self._log = log
        self._clock = clock

    def _counter(self, rule: str):
        reg = self._registry
        if reg is None:
            from .metrics import REGISTRY as reg  # noqa: N811 — late default
        return reg.counter("zkp2p_fleet_alerts_total", {"rule": rule})

    def evaluate(self, signals: Dict, now: Optional[float] = None) -> List[Dict]:
        """One evaluation tick; returns the TRANSITIONS (fired/cleared)
        this tick — steady firing/ok states return nothing."""
        t = self._clock() if now is None else now
        transitions: List[Dict] = []
        for rule in self.rules:
            st = self._states[rule.name]
            try:
                cond = rule.cond(signals)
            except Exception:  # noqa: BLE001 — a broken rule must not kill the tick
                cond = None
            if cond is None:
                continue
            if cond:
                st.clear_since = None
                if st.firing:
                    continue
                if st.pending_since is None:
                    st.pending_since = t
                if t - st.pending_since >= rule.for_s:
                    st.firing = True
                    st.since = t
                    st.fired_count += 1
                    st.pending_since = None
                    st.last_detail = rule.detail(signals) if rule.detail else ""
                    self._counter(rule.name).inc()
                    tr = {"rule": rule.name, "event": "fired", "ts": round(t, 3),
                          "detail": st.last_detail}
                    transitions.append(tr)
                    if self._log:
                        self._log(f"ALERT {rule.name}: FIRED ({st.last_detail})")
            else:
                st.pending_since = None
                if not st.firing:
                    continue
                if st.clear_since is None:
                    st.clear_since = t
                if t - st.clear_since >= rule.clear_s:
                    st.firing = False
                    st.clear_since = None
                    tr = {"rule": rule.name, "event": "cleared", "ts": round(t, 3),
                          "after_s": round(t - st.since, 3)}
                    transitions.append(tr)
                    if self._log:
                        self._log(f"ALERT {rule.name}: cleared after {t - st.since:.1f}s")
        return transitions

    def active(self) -> List[Dict]:
        """Currently-firing alerts (the `/status` + status.json view)."""
        return [
            {"rule": name, "since": round(st.since, 3), "detail": st.last_detail}
            for name, st in self._states.items()
            if st.firing
        ]

    def state(self) -> Dict:
        """Full engine state, rule by rule (fired counts survive clears
        — the status.json record of what has EVER paged this run)."""
        return {
            name: {
                "firing": st.firing,
                "since": round(st.since, 3) if st.firing else None,
                "fired_count": st.fired_count,
                "detail": st.last_detail,
            }
            for name, st in self._states.items()
        }


# ---------------------------------------------------------------------------
# The fleet rule set.  Signals schema (built by pipeline.fleet_obs from
# the merged scrape + supervisor state; any key may be absent — rules
# treat missing data as "hold"):
#
#   burn_fast / burn_slow   merged-window burn rates (utils.slo)
#   slo_n                   merged window sample count
#   backlog_growing         bool|None from TrendTracker (spool scan)
#   backlog                 open spool requests now
#   restarts_recent         supervisor restarts inside the trend window
#   parked                  workers parked by the circuit breaker
#   degraded                workers whose heartbeat says degraded=True
#   hb_gap_s                max heartbeat age over live workers (None
#                           when no live worker has beaten yet)
#   budget_overruns         stage-budget overruns summed over workers
#                           reporting perf budgets (None when no worker
#                           has budgets loaded — fresh ledger: HOLD)
#   overruns_recent         overrun delta inside the trend window


def _num(signals: Dict, key: str):
    v = signals.get(key)
    return v if isinstance(v, (int, float)) else None


def fleet_rules(cfg=None) -> List[Rule]:
    """The built-in fleet rule set, thresholds from the typed config
    (the alert_burn_rate/alert_restarts/alert_for_s/alert_clear_s/
    alert_hb_gap_s knobs).  Returned as plain Rule objects so callers
    can extend/replace the set."""
    if cfg is None:
        from .config import load_config

        cfg = load_config()
    burn_thr = cfg.alert_burn_rate
    restarts_thr = cfg.alert_restarts
    for_s = cfg.alert_for_s
    clear_s = cfg.alert_clear_s
    hb_gap_thr = cfg.alert_hb_gap_s

    def slo_burn(s: Dict) -> Optional[bool]:
        bf, bs = _num(s, "burn_fast"), _num(s, "burn_slow")
        if bf is None or bs is None:
            return None
        if not _num(s, "slo_n"):
            return False  # empty window: no traffic is not an outage
        # the multi-window AND: fast alone is a blip, slow alone is
        # stale history — both over threshold is a real, current burn
        return bf >= burn_thr and bs >= burn_thr

    def backlog_growth(s: Dict) -> Optional[bool]:
        return s.get("backlog_growing")

    def restart_storm(s: Dict) -> Optional[bool]:
        parked, rr = _num(s, "parked"), _num(s, "restarts_recent")
        if parked is None and rr is None:
            return None
        # a breaker park IS the storm's terminal state — fire
        # immediately even when the restarts that led there happened
        # before our trend window
        return bool(parked) or (rr is not None and rr >= restarts_thr)

    def governor_degrade(s: Dict) -> Optional[bool]:
        d = _num(s, "degraded")
        return None if d is None else bool(d)

    def heartbeat_gap(s: Dict) -> Optional[bool]:
        gap = _num(s, "hb_gap_s")
        return None if gap is None else gap >= hb_gap_thr

    def perf_regression(s: Dict) -> Optional[bool]:
        # budget_overruns is None when NO live worker has perf budgets
        # loaded for its (fingerprint, circuit) — a fresh host with an
        # empty ledger must HOLD, never page (docs/OBSERVABILITY.md
        # §perf sentry).  Fires only while overruns are still being
        # ACCRUED (the recent delta), so a historical burst clears.
        ov = _num(s, "budget_overruns")
        if ov is None:
            return None
        rec = _num(s, "overruns_recent")
        return (rec or 0) > 0

    return [
        Rule(
            "slo_burn", slo_burn, for_s=for_s, clear_s=clear_s,
            detail=lambda s: (
                f"burn fast={s.get('burn_fast')} slow={s.get('burn_slow')} "
                f">= {burn_thr:g} over n={s.get('slo_n')}"
            ),
        ),
        Rule(
            "backlog_growth", backlog_growth, for_s=for_s, clear_s=clear_s,
            detail=lambda s: f"backlog {s.get('backlog')} and growing",
        ),
        Rule(
            # park fires NOW (for_s=0): by the time the breaker parks a
            # worker the flap already lasted a full breaker window
            "restart_storm", restart_storm, for_s=0.0, clear_s=clear_s,
            detail=lambda s: (
                f"parked={s.get('parked')} restarts_recent={s.get('restarts_recent')}"
                f" (threshold {restarts_thr})"
            ),
        ),
        Rule(
            "governor_degrade", governor_degrade, for_s=for_s, clear_s=clear_s,
            detail=lambda s: f"{s.get('degraded')} worker(s) soft-degraded",
        ),
        Rule(
            "heartbeat_gap", heartbeat_gap, for_s=0.0, clear_s=clear_s,
            detail=lambda s: f"max heartbeat age {s.get('hb_gap_s')}s >= {hb_gap_thr:g}s",
        ),
        Rule(
            # hysteresis like slo_burn: one slow span is a blip; a
            # stage running over its ledger budget for a full for_s
            # window is a regression
            "perf_regression", perf_regression, for_s=for_s, clear_s=clear_s,
            detail=lambda s: (
                f"stage budget overruns {s.get('budget_overruns')} total, "
                f"+{s.get('overruns_recent')} in window"
            ),
        ),
    ]
