"""Host auto-tune profile: detection, fingerprinting, persistence.

Every measured constant in the repo — precomp geometry c/q/L, Pippenger
windows, `ZKP2P_NATIVE_THREADS`, batch columns — was hand-picked on one
2-core IFMA box (docs/NEXT.md flags the first wider host as a full
re-sweep).  `zkp2p-tpu tune` (pipeline.tune) automates that re-sweep:
it measures this host's micro-arms and persists the winners here as an
atomic, fingerprint-keyed JSON profile beside `.bench_cache`.  This
module is the profile's home: hardware detection (cache sizes + core
topology via the native runtime's sysconf probe, sysfs fallback), the
fingerprint policy, load-time validation, and the typed accessors the
resolvers consume (precomp geometry, native thread default, AmortModel
seed points).

Fingerprint policy: the profile embeds the hardware identity it was
tuned on (CPU model, logical/physical core counts, SMT width, L1d/L2/L3
bytes, IFMA tier) and its 16-hex digest is both the default filename
key and the load-time check.  A profile copied onto foreign hardware —
or a host whose topology changed under a pinned path — is REJECTED and
the caller falls back to the committed hand-picked constants, so a
stale profile can degrade a host back to baseline but never mis-tune
it.  The IFMA field is the *gated* tier (ZKP2P_NATIVE_IFMA applied):
a profile tuned with the 52-limb paths on must not steer a scalar run.

The profile-load gate is `record_arm`'d ("host_profile" -> off | tuned
| fallback) and preflight-armed, so tuned-vs-fallback A/Bs are
execution-digest-distinguishable.  Consumers treat every accessor as
Optional: no profile, a foreign profile, or ZKP2P_PROFILE=0 all resolve
to None and the documented fallback constants apply (byte-identical to
the pre-profile behavior, pinned by tests/test_tune.py).
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import threading
from typing import Dict, Optional, Tuple

SCHEMA_VERSION = 1
PROFILE_PREFIX = "host_profile_"

# hardware-identity fields, in digest order — the fingerprint contract.
# Append-only: dropping or reordering silently orphans every profile.
FP_FIELDS = (
    "cpu_model", "cpu_count", "physical_cores", "smt_per_core",
    "l1d_bytes", "l2_bytes", "l3_bytes", "ifma",
)

# profile geometry only applies at and above this family bit-length —
# the same floor the hand-picked fixed-tier c=16 constant uses
# (precomp._pick_window_fixed); below it the small-key heuristic is
# already shape-aware and a bench-shape sweep has nothing to say.
GEOMETRY_MIN_BL = 15

_lock = threading.Lock()
_fp_memo: Optional[Dict] = None
# (path, mtime_ns) -> validated profile dict or None; one entry
_load_memo: Optional[Tuple[Tuple[str, int], Optional[Dict]]] = None


def _sysfs_read(path: str) -> str:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return ""


def _cpu_model() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    return line.split(":", 1)[1].strip()
    except OSError:
        pass
    return "unknown"


def _sysfs_cache_bytes(level: int, want_type: Tuple[str, ...]) -> int:
    """Largest matching cache at `level` across cpu0's index dirs (the
    fallback when the native lib's sysconf probe is unavailable)."""
    best = 0
    for d in glob.glob("/sys/devices/system/cpu/cpu0/cache/index*"):
        if _sysfs_read(os.path.join(d, "level")) != str(level):
            continue
        if _sysfs_read(os.path.join(d, "type")) not in want_type:
            continue
        size = _sysfs_read(os.path.join(d, "size"))
        try:
            mult = 1
            if size.endswith("K"):
                size, mult = size[:-1], 1024
            elif size.endswith("M"):
                size, mult = size[:-1], 1 << 20
            best = max(best, int(size) * mult)
        except ValueError:
            continue
    return best


def _topology() -> Tuple[int, int, int]:
    """(logical_cpus, physical_cores, smt_per_core) from sysfs thread
    siblings; degrades to (cpu_count, cpu_count, 1) when sysfs is
    absent (containers, exotic kernels) — sizing for logical cores is
    today's behavior, so the fallback never regresses it."""
    logical = max(1, os.cpu_count() or 1)
    cores = set()
    seen = 0
    for d in glob.glob("/sys/devices/system/cpu/cpu[0-9]*"):
        sib = _sysfs_read(os.path.join(d, "topology", "thread_siblings_list"))
        if not sib:
            continue
        seen += 1
        cores.add(sib)
    if seen == 0 or not cores:
        return logical, logical, 1
    physical = len(cores)
    return seen, physical, max(1, seen // physical)


def cache_hierarchy() -> Dict[str, int]:
    """{"l1d": B, "l2": B, "l3": B} — native sysconf probe first (the
    csrc detection the MSM schedules key off), sysfs fallback, 0 =
    unknown at that level."""
    from ..native.lib import cache_sizes

    native = cache_sizes() or {}
    out = {}
    for name, level, want in (
        ("l1d", 1, ("Data", "Unified")),
        ("l2", 2, ("Data", "Unified")),
        ("l3", 3, ("Data", "Unified")),
    ):
        v = int(native.get(name) or 0)
        out[name] = v if v > 0 else _sysfs_cache_bytes(level, want)
    return out


def host_fingerprint() -> Dict:
    """This host's hardware identity (memoized per process)."""
    global _fp_memo
    with _lock:
        if _fp_memo is not None:
            return dict(_fp_memo)
    from ..native.lib import ifma_available

    logical, physical, smt = _topology()
    caches = cache_hierarchy()
    fp = {
        "cpu_model": _cpu_model(),
        "cpu_count": logical,
        "physical_cores": physical,
        "smt_per_core": smt,
        "l1d_bytes": caches["l1d"],
        "l2_bytes": caches["l2"],
        "l3_bytes": caches["l3"],
        "ifma": 1 if ifma_available() else 0,
    }
    with _lock:
        _fp_memo = dict(fp)
    return fp


def fingerprint_key(fp: Optional[Dict] = None) -> str:
    """16-hex digest of the identity fields — the profile filename key
    and the load-time foreign-hardware check."""
    fp = host_fingerprint() if fp is None else fp
    blob = json.dumps([(k, fp.get(k)) for k in FP_FIELDS], separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def default_profile_path() -> Optional[str]:
    """`<precomp cache dir>/host_profile_<fingerprint>.json` — beside
    the `.bench_cache` tables; None when persistence is disabled
    (ZKP2P_MSM_PRECOMP_CACHE=0)."""
    from ..prover.precomp import _cache_dir

    d = _cache_dir()
    if d is None:
        return None
    return os.path.join(d, PROFILE_PREFIX + fingerprint_key() + ".json")


def save_profile(profile: Dict, path: Optional[str] = None) -> Optional[str]:
    """Persist atomically (tmp + rename, the `_persist_table` pattern:
    a fleet worker racing a tune must never load a torn profile).
    Stamps schema + this host's fingerprint; returns the path written,
    None when no path resolves (persistence off)."""
    path = path or default_profile_path()
    if not path:
        return None
    prof = dict(profile)
    prof["schema"] = SCHEMA_VERSION
    prof["fingerprint"] = host_fingerprint()
    prof["fingerprint_key"] = fingerprint_key()
    tmp = f"{path}.tmp.{os.getpid()}"
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    try:
        with open(tmp, "w") as f:
            json.dump(prof, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None
    reset(fingerprint=False)
    return path


def _validated(path: str) -> Optional[Dict]:
    """Load + validate one profile file; None on ANY mismatch (missing,
    unparseable, schema drift, foreign or tampered fingerprint)."""
    try:
        with open(path) as f:
            prof = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(prof, dict) or prof.get("schema") != SCHEMA_VERSION:
        return None
    fp = prof.get("fingerprint")
    if not isinstance(fp, dict):
        return None
    embedded_key = fingerprint_key(fp)
    if prof.get("fingerprint_key") != embedded_key:
        return None  # body edited after signing — distrust all of it
    if embedded_key != fingerprint_key():
        return None  # foreign hardware: rebuild, never mis-tune
    return prof


def load_profile() -> Optional[Dict]:
    """The validated host profile, or None (gate off, no file, foreign
    file).  Records the "host_profile" execution-audit gate on every
    resolution — off | tuned | fallback — so an A/B's two digests
    differ exactly on this arm.  Memoized per (path, mtime)."""
    global _load_memo
    from .audit import record_arm
    from .config import load_config

    cfg = load_config()
    if not cfg.profile:
        record_arm("host_profile", "off")
        return None
    path = cfg.profile_path or default_profile_path()
    prof: Optional[Dict] = None
    if path:
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            mtime = -1
        key = (path, mtime)
        with _lock:
            memo = _load_memo
        if memo is not None and memo[0] == key:
            prof = memo[1]
        else:
            prof = _validated(path) if mtime >= 0 else None
            with _lock:
                _load_memo = (key, prof)
    record_arm("host_profile", "tuned" if prof is not None else "fallback")
    return prof


def profile_arm() -> str:
    """Resolve + arm the profile gate (the preflight hook)."""
    from .audit import gate_arms

    load_profile()
    return gate_arms().get("host_profile", "fallback")


def geometry_for(family: str, n: int) -> Optional[Dict]:
    """Tuned fixed-tier geometry for a G1 family of n points: a dict
    with "c" (and optionally "q"), or None -> the hand-picked fallback.
    Only applies at bench-sweep scale (bit_length >= min_bl): the tune
    pass measured full-width shapes, and the small-key heuristic is
    already shape-aware."""
    prof = load_profile()
    if prof is None:
        return None
    fixed = prof.get("msm_fixed")
    if not isinstance(fixed, dict):
        return None
    if n.bit_length() < int(fixed.get("min_bl", GEOMETRY_MIN_BL)):
        return None
    geom = fixed.get("families", {}).get(family) or fixed.get("default")
    if not isinstance(geom, dict) or "c" not in geom:
        return None
    try:
        c = int(geom["c"])
    except (TypeError, ValueError):
        return None
    if not 4 <= c <= 20:  # a corrupt c would allocate 2^(c-1) buckets
        return None
    out = {"c": c}
    if "q" in geom:
        try:
            out["q"] = max(1, int(geom["q"]))
        except (TypeError, ValueError):
            pass
    return out


def tuned_threads() -> Optional[int]:
    """The profile's measured-best native thread count (topology-aware:
    physical cores, not SMT siblings), or None -> size from
    os.cpu_count() as today."""
    prof = load_profile()
    if prof is None:
        return None
    try:
        v = int(prof.get("threads", {}).get("native_default"))
    except (TypeError, ValueError):
        return None
    return v if v >= 1 else None


def tuned_window(tag: str, bl: int, threads: int) -> Optional[int]:
    """The measured-best VARIABLE-BASE Pippenger window for `tag`
    ("plain" | "glv") or None -> the committed curve (_pick_window*).

    Applies only at the EXACT measured context: the sweep ran one shape
    at one thread count, and the window optimum is not monotone in
    either (the glv curve steps DOWN a window at 2^19 when the deferred
    bucket block falls out of LLC) — so `bl` must equal the recorded
    scalar-count bit length and `threads` the recorded worker count, or
    the committed curve stays authoritative.  c is bounds-checked like
    geometry_for (a corrupt c would allocate 2^(c-1) buckets)."""
    prof = load_profile()
    if prof is None:
        return None
    win = prof.get("msm_window")
    if not isinstance(win, dict):
        return None
    row = win.get("families", {}).get(tag)
    if not isinstance(row, dict):
        return None
    try:
        c = int(row["c"])
        if int(row["bl"]) != int(bl) or int(win.get("threads")) != int(threads):
            return None
    except (KeyError, TypeError, ValueError):
        return None
    return c if 4 <= c <= 20 else None


def amort_points(tier: str = "native") -> Optional[Dict[int, float]]:
    """Measured batch-cost points {S: seconds} to seed the scheduler's
    AmortModel (pipeline.sched), or None.  Validated here (strictly
    increasing in both axes, positive) so a corrupt profile degrades to
    the built-in curve instead of raising in the service loop.

    Per worker tier: "native" reads the classic sched.amort_points;
    any other tier reads sched.tiers.<tier>.amort_points (the sharded
    pod-mesh curve a tune pass on mesh hardware records) — absent, the
    caller's built-in per-tier default applies."""
    prof = load_profile()
    if prof is None:
        return None
    sched = prof.get("sched", {})
    if tier == "native":
        raw = sched.get("amort_points")
    else:
        tiers = sched.get("tiers")
        raw = tiers.get(tier, {}).get("amort_points") if isinstance(tiers, dict) else None
    if not isinstance(raw, dict) or not raw:
        return None
    try:
        pts = {int(k): float(v) for k, v in raw.items()}
    except (TypeError, ValueError):
        return None
    ss = sorted(pts)
    if ss[0] < 1 or pts[ss[0]] <= 0.0:
        return None
    for a, b in zip(ss, ss[1:]):
        if pts[b] <= pts[a]:
            return None
    return pts


def profile_manifest() -> Dict:
    """Run-manifest block: which arm resolved, from where — so every
    bench/trace artifact can say whether a tuned profile steered it."""
    from .audit import gate_arms
    from .config import load_config

    prof = load_profile()  # records the gate; read the arm back from it
    out: Dict = {
        "arm": gate_arms().get("host_profile", "fallback"),
        "path": load_config().profile_path or default_profile_path(),
        "host_fingerprint": fingerprint_key(),
    }
    if prof is not None:
        out["created_ts"] = prof.get("created_ts")
        out["fingerprint_key"] = prof.get("fingerprint_key")
    return out


def reset(fingerprint: bool = True) -> None:
    """Drop memoized state (tests; save_profile drops the load memo so
    a just-written profile is visible without an mtime race)."""
    global _fp_memo, _load_memo
    with _lock:
        if fingerprint:
            _fp_memo = None
        _load_memo = None
