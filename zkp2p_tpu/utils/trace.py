"""Structured stage tracing for the proving service.

The reference's observability is `start=$(date +%s)` brackets in shell
scripts, `console.time("zk-dl"/"zk-gen")` and a UI stopwatch
(SURVEY.md §5 tracing).  This is the structured version: nested stage
timers with one JSON-lines sink, plus optional JAX profiler capture for
xprof when JAX_TRACE_DIR is set.

    with trace("prove", batch=16):
        with trace("h_poly"):
            ...
    dump_trace()  ->  [{"stage": "prove", "ms": ..., "batch": 16, ...}]
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

_records: List[Dict[str, Any]] = []
# Stage nesting is PER THREAD (the service overlaps a witness thread with
# the proving thread; a shared stack would interleave their frames and
# pop across threads).  Appends to _records are atomic under the GIL.
_tls = threading.local()


@contextlib.contextmanager
def trace(stage: str, **attrs):
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(stage)
    path = "/".join(stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _records.append({"stage": path, "ms": round((time.perf_counter() - t0) * 1e3, 3), **attrs})
        stack.pop()


def current_stack() -> List[str]:
    """Snapshot of this thread's stage-nesting stack — hand it to worker
    threads (with adopt_stack) so their records keep the submitting
    stage's path prefix instead of starting a fresh root."""
    return list(getattr(_tls, "stack", None) or [])


def adopt_stack(stack: List[str]) -> None:
    """Seed THIS thread's nesting stack (see current_stack)."""
    _tls.stack = list(stack)


def records() -> List[Dict[str, Any]]:
    return list(_records)


def reset() -> None:
    _records.clear()


def dump_trace(path: Optional[str] = None) -> None:
    out = "\n".join(json.dumps(r) for r in _records)
    if path:
        with open(path, "a") as f:
            f.write(out + "\n")
    else:
        print(out, file=sys.stderr)


@contextlib.contextmanager
def jax_profile(name: str = "zkp2p"):
    """xprof capture when JAX_TRACE_DIR is set; no-op otherwise."""
    trace_dir = os.environ.get("JAX_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(os.path.join(trace_dir, name))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
