"""Structured stage tracing for the proving service.

The reference's observability is `start=$(date +%s)` brackets in shell
scripts, `console.time("zk-dl"/"zk-gen")` and a UI stopwatch
(SURVEY.md §5 tracing).  This is the structured version: nested stage
timers with one JSON-lines sink, plus optional JAX profiler capture for
xprof when JAX_TRACE_DIR is set.

    with trace("prove", batch=16):
        with trace("h_poly"):
            ...
    dump_trace()  ->  [{"stage": "prove", "ms": ..., "batch": 16, ...}]

Every closed span also feeds the process metrics registry
(utils.metrics REGISTRY, `zkp2p_stage_ms{stage=...}` histograms), so a
Prometheus scrape sees stage latencies without any dump.

Records are held in a bounded ring (ZKP2P_TRACE_MAX, default 64k): a
service loop tracing forever stays at a fixed memory footprint and the
overflow is COUNTED (`zkp2p_trace_dropped_total` + the dump manifest),
never silent.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time
from typing import Any, Deque, Dict, List, Optional


def _ring_capacity() -> int:
    from .config import load_config

    return load_config().trace_max


_records: Deque[Dict[str, Any]] = collections.deque(maxlen=_ring_capacity())
_dropped = 0  # lifetime count of ring-overflow evictions (GIL-guarded)
# Stage nesting is PER THREAD (the service overlaps a witness thread with
# the proving thread; a shared stack would interleave their frames and
# pop across threads).  Appends to _records are atomic under the GIL.
_tls = threading.local()

# stage-path -> histogram, cached so the registry lock is not taken per
# span close (get-or-create only on first sight of a stage).  Keyed by
# the registry GENERATION too: REGISTRY.reset() orphans instruments, and
# feeding an orphan would silently blank exposition for cached stages.
_stage_hists: Dict[str, Any] = {}
_stage_hists_gen = -1


def _observe_stage(path: str, ms: float) -> None:
    global _stage_hists_gen
    from .metrics import REGISTRY

    if REGISTRY.generation != _stage_hists_gen:
        _stage_hists.clear()
        _stage_hists_gen = REGISTRY.generation
    h = _stage_hists.get(path)
    if h is None:
        h = _stage_hists[path] = REGISTRY.histogram("zkp2p_stage_ms", {"stage": path})
    h.observe(ms)


_append_lock = threading.Lock()


def _append(rec: Dict[str, Any]) -> None:
    # Locked: two threads both seeing len == maxlen-1 would each append
    # (one eviction) yet neither count the drop — and the drop counter's
    # whole contract is "overflow counted, never silent".
    global _dropped
    with _append_lock:
        dropped = _records.maxlen is not None and len(_records) == _records.maxlen
        if dropped:
            _dropped += 1
        _records.append(rec)
    if dropped:
        from .metrics import REGISTRY

        REGISTRY.counter("zkp2p_trace_dropped_total").inc()


@contextlib.contextmanager
def trace(stage: str, **attrs):
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(stage)
    path = "/".join(stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        ctx = getattr(_tls, "ctx", None)
        rec = {"stage": path, "ms": ms}
        if ctx:
            rec.update(ctx)
        rec.update(attrs)
        _append(rec)
        _observe_stage(path, ms)
        stack.pop()


def current_stack() -> List[str]:
    """Snapshot of this thread's stage-nesting stack — hand it to worker
    threads (with adopt_stack) so their records keep the submitting
    stage's path prefix instead of starting a fresh root."""
    return list(getattr(_tls, "stack", None) or [])


def adopt_stack(stack: List[str]) -> None:
    """Seed THIS thread's nesting stack (see current_stack)."""
    _tls.stack = list(stack)


def set_context(**attrs) -> None:
    """Merge ambient attributes into every record THIS thread closes
    (request_id through witness -> prove -> emit; a None value removes
    the key).  Context rides the same per-thread rail as the stack —
    current_context()/adopt_context() hand it across worker pools."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        ctx = _tls.ctx = {}
    for k, v in attrs.items():
        if v is None:
            ctx.pop(k, None)
        else:
            ctx[k] = v


def clear_context() -> None:
    _tls.ctx = {}


def current_context() -> Dict[str, Any]:
    return dict(getattr(_tls, "ctx", None) or {})


def adopt_context(ctx: Dict[str, Any]) -> None:
    _tls.ctx = dict(ctx)


def _resize_ring(capacity: int) -> None:
    """Swap the ring for a new bound, keeping the newest records (tests;
    long-lived services retuning ZKP2P_TRACE_MAX without a restart)."""
    global _records
    _records = collections.deque(_records, maxlen=max(1, capacity))


def records() -> List[Dict[str, Any]]:
    return list(_records)


def dropped() -> int:
    return _dropped


def reset() -> None:
    global _dropped
    _records.clear()
    _dropped = 0


def drain() -> List[Dict[str, Any]]:
    """Atomically take every buffered record (the service's per-sweep
    flush into its JSONL sink) — records appended concurrently after the
    snapshot stay buffered for the next drain."""
    out: List[Dict[str, Any]] = []
    while True:
        try:
            out.append(_records.popleft())
        except IndexError:
            return out


def dump_trace(path: Optional[str] = None) -> None:
    """Emit buffered records.  To a file: ONE atomic O_APPEND write —
    safe for many service workers sharing a sink — with a manifest line
    (run_id, pid, host facts, knob states, drop count) stamped first and
    run_id/pid on every record line, so interleaved multi-process dumps
    stay separable and self-describing.  Without a path: stderr.

    Deliberately NOT routed through metrics.JsonlSink: that sink stamps
    a manifest only on a fresh/rotated file, but a trace sink is shared
    ACROSS processes and knob arms (the A/B workflow appends two bench
    runs to one file), so every dump must carry its own manifest or
    trace_report --runs loses the later runs' knob attribution.  The
    trade-off: a process looping dump_trace on one path grows it
    unboundedly — dump once per process, or point heavy loops at a
    JsonlSink."""
    from .metrics import run_id, run_manifest

    recs = records()
    if path:
        rid, pid = run_id(), os.getpid()
        manifest = {"type": "manifest", **run_manifest(), "trace_dropped": _dropped}
        lines = [json.dumps(manifest)]
        lines += [json.dumps({**r, "run_id": rid, "pid": pid}) for r in recs]
        payload = ("\n".join(lines) + "\n").encode()
        fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        try:
            os.write(fd, payload)
        finally:
            os.close(fd)
    else:
        print("\n".join(json.dumps(r) for r in recs), file=sys.stderr)


@contextlib.contextmanager
def jax_profile(name: str = "zkp2p"):
    """xprof capture when JAX_TRACE_DIR is set; no-op otherwise."""
    trace_dir = os.environ.get("JAX_TRACE_DIR")
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(os.path.join(trace_dir, name))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
