"""One typed configuration for the prover stack (SURVEY.md §5).

Every tuning knob the prover/bench/service read lives HERE, as a frozen
dataclass with per-field provenance — not as ad-hoc `os.environ` reads
scattered across modules (VERDICT r4 weak #7: nine+ ZKP2P_*/BENCH_*
vars steering the tiers, plus a side-file the bench trusted blindly).

Resolution order per knob:

  1. built-in default (the committed, tested configuration),
  2. `.bench_cache/armed_flags.json` — hardware-A/B-validated winners a
     tunnel-window session recorded (only the two MSM-tier knobs may be
     armed this way; anything else in the file is ignored and logged),
  3. explicit environment variable — always wins (operator intent).

`provenance` records which layer produced each value, so a bench record
or bug report can say "msm_h=bucket (armed)" instead of guessing.

The environment remains the TRANSPORT (child processes, the C runtime's
getenv, jit-time module constants) — `apply_env()` writes the resolved
config back so every consumer, Python or C++, sees one consistent view.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

# knob -> (env var, parser, default) — THE registry; the test asserts
# every ZKP2P_* read in the tree maps through it.  Parsers REPRODUCE the
# semantics of the reader each knob steers (they predate this module and
# other consumers — notably the C runtime — still read the env):
_BOOL = lambda s: s == "1"  # noqa: E731 — readers compare == "1"


def _not_zero(s: str) -> bool:
    # the C runtime's rule for ZKP2P_NATIVE_IFMA: off ONLY when the
    # value starts with '0' (csrc ifma_enabled) — "true"/"yes" stay on
    return not s.startswith("0")


def _starts_one(s: str) -> bool:
    # the C runtime's opt-IN rule for default-off native arms: on ONLY
    # when the value starts with '1' (csrc ntt_radix8_enabled)
    return s.startswith("1")


def _opt_int(s: str) -> Optional[int]:
    if not s:
        return None  # empty string = unset (shell-style), not 1 thread
    try:
        return max(1, int(s))
    except ValueError:
        # malformed degrades to sequential — matching the C++ runtime's
        # atoi() on the same variable, so Python- and C-side threading
        # agree
        return 1


def _opt_port(s: str) -> Optional[int]:
    # metrics exposition port: unset/empty/malformed mean OFF (a typo
    # must fail closed — no listener), "auto" or "0" mean EPHEMERAL (the
    # OS picks a free port, recorded in the run manifest and the fleet
    # heartbeat so scrapes stay discoverable — N workers on one host
    # cannot share one fixed port), anything else is the fixed port
    if s.strip().lower() == "auto":
        return 0
    try:
        v = int(s)
    except ValueError:
        return None
    if v == 0:
        return 0
    return v if 0 < v < 65536 else None


def _pos_int(default: int):
    # bounded positive int with a safe fallback (ring-buffer sizes):
    # malformed keeps the committed default rather than crashing import
    def parse(s: str) -> int:
        try:
            return max(1, int(s))
        except ValueError:
            return default

    return parse


def _nonneg_int(default: int):
    # 0 is meaningful here ("unlimited" / "no retries"); malformed keeps
    # the committed default rather than crashing a running service
    def parse(s: str) -> int:
        try:
            return max(0, int(s))
        except ValueError:
            return default

    return parse


def _nonneg_float(default: float):
    # seconds knobs (deadlines, backoff): 0 = disabled; malformed keeps
    # the committed default
    def parse(s: str) -> float:
        try:
            return max(0.0, float(s))
        except ValueError:
            return default

    return parse


def _min_one_float(default: float):
    # perf budget multiplier: must be >= 1.0 — a budget BELOW the
    # trailing median would page on every healthy request; malformed or
    # out-of-range keeps the committed default
    def parse(s: str) -> float:
        try:
            v = float(s)
        except ValueError:
            return default
        return v if v >= 1.0 else default

    return parse


def _pos_float(default: float):
    # sampling rates (Hz): must be strictly positive — a 0 Hz sampler
    # would park its thread forever; malformed or non-positive keeps
    # the committed default
    def parse(s: str) -> float:
        try:
            v = float(s)
        except ValueError:
            return default
        return v if v > 0.0 else default

    return parse


def _fraction(default: float):
    # SLO target fraction: must land strictly inside (0, 1) — a target
    # of 0 or 1 makes the burn-rate denominator meaningless; malformed
    # or out-of-range keeps the committed default
    def parse(s: str) -> float:
        try:
            v = float(s)
        except ValueError:
            return default
        return v if 0.0 < v < 1.0 else default

    return parse


KNOBS: Dict[str, Tuple[str, object, object]] = {
    # device (XLA/Pallas) prover MSM tiers — see prover.groth16_tpu
    "msm_window": ("ZKP2P_MSM_WINDOW", int, 4),
    "msm_signed": ("ZKP2P_MSM_SIGNED", _BOOL, True),
    "msm_unified": ("ZKP2P_MSM_UNIFIED", str, "auto"),
    "msm_affine": ("ZKP2P_MSM_AFFINE", str, "0"),
    "msm_h": ("ZKP2P_MSM_H", str, "windowed"),
    # GLV endomorphism scalar decomposition for the G1 MSMs (JAX and
    # native provers): every Fr scalar splits into two ~128-bit halves,
    # halving digit planes / Pippenger windows at the cost of doubling
    # the base axis.  Off by default (the existing path is the pinned
    # fallback); armable so a hardware A/B session can switch it on.
    "msm_glv": ("ZKP2P_MSM_GLV", _BOOL, False),
    # Stage task-graph in prove_native: the a/b1/b2/c MSMs run on worker
    # threads overlapping the H ladder + msm_h ("1"), or strictly
    # sequentially ("0").  Only engages when the resolved thread count
    # is > 1 (a ZKP2P_NATIVE_THREADS=1 pin means one busy core, which
    # Python-side concurrency must not break).  Overlap wins when cores
    # outnumber the per-region pool width or per-MSM serial glue
    # dominates; where the C tier already saturates every core per
    # stage it is neutral — hence a knob, so the arm is attributable
    # and host-tunable.
    "msm_overlap": ("ZKP2P_MSM_OVERLAP", _BOOL, True),
    # Batch-affine Pippenger bucket accumulation in the NATIVE (C++) MSM
    # tiers: buckets live as affine points, every chunk of bucket adds
    # shares ONE Montgomery batch inversion (~7 muls/add vs ~12 for the
    # mixed-Jacobian add).  Default ON (the measured-fastest arm and the
    # long-standing behavior); off routes every window through the plain
    # Jacobian fill — the honest A/B arm.  The C runtime re-reads the env
    # per MSM (csrc batch_affine_enabled), so flips apply immediately.
    "msm_batch_affine": ("ZKP2P_MSM_BATCH_AFFINE", _not_zero, True),
    # Cross-proof multi-column MSM in prove_native_batch: the a/b1/c/h
    # G1 MSM families each ride ONE native Pippenger call per batch (one
    # sweep over the fixed key bases, S scalar columns, batch-affine
    # inversion rounds shared across columns).  Default ON; "0" falls
    # back to sequential per-proof proves — the byte-parity oracle arm.
    # Fresh-read per batch (the gate resolves through load_config at the
    # prove_native_batch call site), so one process can A/B both arms.
    "msm_multi": ("ZKP2P_MSM_MULTI", _not_zero, True),
    # Fixed-base precomputed-window MSM tier (prover.precomp): the
    # frozen proving-key G1 families resolve to offline level tables at
    # first prove (persisted under .bench_cache/, keyed by key hash +
    # geometry), and the per-prove hot loop becomes pure table gather +
    # batch-affine bucket adds — no GLV split, no base conversion.
    # Default ON (the measured-faster arm at the bench shape); "0"
    # falls back to the variable-base drivers — the byte-parity oracle
    # arm.  Fresh-read per prove, so one process can A/B both arms.
    "msm_precomp": ("ZKP2P_MSM_PRECOMP", _not_zero, True),
    # table depth: max level copies per family (levels = ceil(W/q);
    # deeper tables = fewer hot-loop windows, more RAM — each level is
    # n x 144 B resident / n x 64 B on disk per family).  Build COST is
    # depth-invariant (~(W-q)*c doublings per point either way), so the
    # dial trades only memory against hot-loop windows.
    "precomp_depth": ("ZKP2P_MSM_PRECOMP_DEPTH", _pos_int(8), 8),
    # RAM budget guard for the resident tables (mont256 + 52-limb forms,
    # summed over families, in MiB).  A family that exceeds the budget
    # degrades to a shallower table; one that cannot fit even one level
    # falls through to the variable-base path and is recorded as
    # "skipped: budget" in the run manifest.
    "precomp_max_mb": ("ZKP2P_MSM_PRECOMP_MAX_MB", _pos_int(6144), 6144),
    # persistence root for built tables ("" = <repo>/.bench_cache,
    # "0" = never persist) and the minimum family size that persists at
    # all — tiny test keys rebuild in microseconds and must not litter
    # the shared cache dir.
    "precomp_cache": ("ZKP2P_MSM_PRECOMP_CACHE", str, ""),
    "precomp_persist_min": ("ZKP2P_MSM_PRECOMP_PERSIST_MIN", _pos_int(65536), 65536),
    # which G1 families ride tables.  h included by default: the
    # full-width ladder scalars still measure ~1.25x over the GLV
    # variable-base arm at the bench shape (docs/TUNING.md sweep).
    "precomp_families": ("ZKP2P_MSM_PRECOMP_FAMILIES", str, "a,b1,c,h"),
    # Segmented-plan matvec in the native prover (prover.matvec_plan +
    # csrc fr_matvec_seg): the A/B QAP matvecs run over a per-key
    # presorted plan — 8-wide IFMA coeff·wire products across segment
    # boundaries, segments partitioned over the C worker pool with no
    # scatter conflicts by construction; plans persist beside the
    # precomp tables keyed by matrix hash.  Default ON; "0" falls back
    # to the scatter `fr_matvec` oracle — the byte-parity A/B arm.
    # Fresh-read per prove, so one process can A/B both arms.
    "matvec_seg": ("ZKP2P_MATVEC_SEG", _not_zero, True),
    # Pool-parallel NTT stage splitting + fused coset ladder + Fr
    # vector batch passes in the C runtime: each NTT stage's butterfly
    # blocks fan out across the persistent WorkPool (ONE transform uses
    # every core, vs the old 3-wide whole-transform ladder split), the
    # H ladder keeps data in 52-limb SoA form across iNTT -> coset-mul
    # -> forward NTT (the coset+1/m pass vectorized, two full memory
    # passes dropped), and the fr_mul_batch / to-mont / from-mont
    # passes run 8-wide.  Default ON; "0" restores the full scalar
    # 3-wide unfused path — the byte-parity A/B arm.  The C runtime
    # re-reads the env per call (csrc ntt_pool_enabled), so flips apply
    # immediately.
    "ntt_pool": ("ZKP2P_NTT_POOL", _not_zero, True),
    # MSM apply interleave in the C batch-affine pipeline: the chunk
    # apply splits its block range in two and drives both halves'
    # prefix/inverse/apply mont52 chains through ONE fused register
    # schedule (mont52_mul8x2 — the second chain fills the first's
    # madd52 latency bubbles), plus software prefetch down the known
    # (bucket, point) index streams in the schedule/fill/bail loops.
    # Default ON; "0" restores the single-chain no-prefetch schedule —
    # the byte-parity A/B arm.  Fresh-read per call (csrc
    # msm_interleave_enabled), so flips apply immediately.
    "msm_interleave": ("ZKP2P_MSM_INTERLEAVE", _not_zero, True),
    # Radix-8 NTT stage fusion: three butterfly stages per load/store
    # pass in fr_ntt_soa_stages (vs the radix-4 stage pairs).  Default
    # OFF — measured 0.95x at 2^19 on the 1-core IFMA box (register
    # spills; the muls are throughput-bound, so the saved memory pass
    # does not pay there) — the knob stays for wider hosts; "1" arms it.
    # Fresh-read per transform (csrc ntt_radix8_enabled).
    "ntt_radix8": ("ZKP2P_NTT_RADIX8", _starts_one, False),
    # Witness-at-builder hand-off: snark.r1cs witness builders attach
    # the prover's standard-form (n, 4) u64 serialization at build time
    # and the witness_convert stage hands it off instead of
    # re-serializing Python ints every prove.  Default ON; "0"
    # re-serializes — the byte-parity oracle arm.  Fresh-read per prove
    # at the _witness_std_u64 call site.
    "witness_u64": ("ZKP2P_WITNESS_U64", _not_zero, True),
    # proof-batch sub-chunking: "auto" (4 per chunk on a real TPU — the
    # 16 GB HBM budget; whole batch elsewhere), "0" (never chunk), or an
    # explicit chunk size.  r5 bench1 on-chip: the batched h-evals stage
    # materialises a (batch, nnz, 16, 16) partial-product tensor on the
    # XLA field path — 18 GB at batch=16 against 15.75 G HBM.
    "batch_chunk": ("ZKP2P_BATCH_CHUNK", str, "auto"),
    # device field/curve kernel selection — see field.jfield, curve.jcurve
    "field_conv": ("ZKP2P_FIELD_CONV", str, "matmul"),
    "field_mul": ("ZKP2P_FIELD_MUL", str, "auto"),
    "curve_kernel": ("ZKP2P_CURVE_KERNEL", str, "auto"),
    # native (C++) runtime
    "native_ifma": ("ZKP2P_NATIVE_IFMA", _not_zero, True),
    "native_threads": ("ZKP2P_NATIVE_THREADS", _opt_int, None),
    # compilation-cache opt-out (read by tests/conftest.py at process
    # start as well — the env var is authoritative there by necessity)
    "no_cache": ("ZKP2P_NO_CACHE", _BOOL, False),
    # debug: native MSM phase counters (csrc zkp2p_msm_prof_dump)
    "msm_prof": ("ZKP2P_MSM_PROF", _BOOL, False),
    # observability (utils.metrics / utils.trace): Prometheus exposition
    # port (unset/0 = off), JSONL metrics-sink path ("" = the consumer's
    # default: stderr for bench dumps, <spool>.metrics.jsonl for the
    # service), and the trace ring-buffer bound (records kept in memory
    # between dumps; overflow increments zkp2p_trace_dropped_total).
    "metrics_port": ("ZKP2P_METRICS_PORT", _opt_port, None),
    # bind address for the exposition endpoint: localhost by default —
    # /metrics discloses host facts and knob config, so reaching it from
    # another machine (a real Prometheus) is an explicit opt-in
    # (ZKP2P_METRICS_ADDR=0.0.0.0)
    "metrics_addr": ("ZKP2P_METRICS_ADDR", str, "127.0.0.1"),
    "metrics_sink": ("ZKP2P_METRICS_SINK", str, ""),
    "trace_max": ("ZKP2P_TRACE_MAX", _pos_int(65536), 65536),
    # fault injection (utils.faults): named injection sites through the
    # witness/prove/verify/emit/claim/sink paths, e.g.
    # "seed=7,prove:raise:p=0.2,emit:enospc:once,witness:hang=3".
    # Empty = off (the no-op fast path).  The spec grammar and
    # determinism contract live in utils/faults.py + docs/ROBUSTNESS.md;
    # the knob stays a raw string here (faults.parse_faults is THE
    # parser) so a malformed spec fails loudly at arm time, not silently
    # at config time.
    "faults": ("ZKP2P_FAULTS", str, ""),
    # service fault-tolerance knobs (pipeline.service; constructor args
    # override per instance — these are the fleet-wide defaults):
    # default per-request deadline in seconds (payload deadline_s wins;
    # 0 = no deadline), spool backlog cap (pending requests beyond it
    # are shed as error-shed; 0 = unlimited), bounded transient-failure
    # retries per batch prove, and the exponential-backoff base.
    "deadline_s": ("ZKP2P_DEADLINE_S", _nonneg_float(0.0), 0.0),
    "spool_cap": ("ZKP2P_SPOOL_CAP", _nonneg_int(0), 0),
    "prove_retries": ("ZKP2P_PROVE_RETRIES", _nonneg_int(2), 2),
    "retry_backoff_s": ("ZKP2P_RETRY_BACKOFF_S", _nonneg_float(0.25), 0.25),
    # service-level SLO (utils.slo; docs/OBSERVABILITY.md §SLO): the
    # p95 latency objective in seconds over the request's FULL life
    # (spool arrival -> terminal; 0 = no objective, the tracker still
    # records window latencies), the attainment target fraction behind
    # the burn-rate math, and the rolling-window length the tracker
    # aggregates over.
    "slo_p95_s": ("ZKP2P_SLO_P95_S", _nonneg_float(0.0), 0.0),
    "slo_target": ("ZKP2P_SLO_TARGET", _fraction(0.95), 0.95),
    "slo_window_s": ("ZKP2P_SLO_WINDOW_S", _nonneg_float(300.0), 300.0),
    # time-series sampler interval (pipeline.service.TimeseriesSampler):
    # every interval the service loop appends a `zkp2p_timeseries` line
    # (arrival rate, claimable backlog, in-flight fill, rescue counters,
    # native stats deltas, HBM gauges) to the JSONL sink.  0 = off.
    "ts_sample_s": ("ZKP2P_TS_SAMPLE_S", _nonneg_float(10.0), 10.0),
    # fleet identity + plumbing (pipeline.fleet): the supervisor stamps
    # these into each worker's environment — worker_id/fleet_id land on
    # every service record and time-series line so trace_report can
    # attribute rows to workers across a fleet run, and fleet_dir is
    # where the worker writes heartbeats / reads governor control files.
    # Empty = not a fleet member (solo service).
    "worker_id": ("ZKP2P_WORKER_ID", str, ""),
    "fleet_id": ("ZKP2P_FLEET_ID", str, ""),
    "fleet_dir": ("ZKP2P_FLEET_DIR", str, ""),
    # fleet policy knobs (pipeline.fleet; CLI flags override): worker
    # count, the bounded wait between SIGTERM (drain) and SIGKILL
    # escalation, per-worker RSS budgets for the resource governor
    # (0 = off; soft = ctl-file degradation, hard = drain + restart),
    # the crash-loop circuit breaker (K failures inside W seconds parks
    # the worker; the fleet degrades to N-1 instead of flapping), and
    # the exponential restart-backoff base.
    "fleet_workers": ("ZKP2P_FLEET_WORKERS", _pos_int(2), 2),
    "drain_timeout_s": ("ZKP2P_DRAIN_TIMEOUT_S", _nonneg_float(30.0), 30.0),
    "rss_soft_mb": ("ZKP2P_RSS_SOFT_MB", _nonneg_int(0), 0),
    "rss_hard_mb": ("ZKP2P_RSS_HARD_MB", _nonneg_int(0), 0),
    "breaker_k": ("ZKP2P_BREAKER_K", _pos_int(5), 5),
    "breaker_window_s": ("ZKP2P_BREAKER_WINDOW_S", _nonneg_float(60.0), 60.0),
    "restart_backoff_s": ("ZKP2P_RESTART_BACKOFF_S", _nonneg_float(0.5), 0.5),
    # fleet observability plane (pipeline.fleet_obs; docs/OBSERVABILITY
    # §fleet plane): the supervisor's STABLE aggregated endpoint
    # (/metrics /status /healthz; unset = plane off, "auto"/"0" =
    # ephemeral with the bound port in status.json — port semantics
    # identical to metrics_port), the worker-scrape/merge cadence, and
    # the fast sub-window for the multi-window burn-rate pair.
    "fleet_metrics_port": ("ZKP2P_FLEET_METRICS_PORT", _opt_port, None),
    "fleet_scrape_s": ("ZKP2P_FLEET_SCRAPE_S", _nonneg_float(2.0), 2.0),
    "slo_fast_window_s": ("ZKP2P_SLO_FAST_WINDOW_S", _nonneg_float(60.0), 60.0),
    # adaptive scheduler (pipeline.sched; docs/SCHEDULING.md): the
    # controller gate ("off" = the static batch_size/newest-first-shed
    # oracle arm, byte-for-byte today's behavior; "adaptive" = SLO-
    # driven batch sizing + expected-deadline-miss shedding + priority
    # lanes; anything else fails CLOSED to off), the headroom fraction
    # of the deadline/objective budget batches are planned to, the
    # amortization-curve calibration ("S:sec,S:sec,..."; "" = the
    # built-in conservative venmo curve; malformed raises LOUDLY at
    # controller creation), and the default priority lane for requests
    # whose payload carries none ("interactive" | anything-else=bulk).
    "sched": ("ZKP2P_SCHED", str, "off"),
    "sched_target_fill": ("ZKP2P_SCHED_TARGET_FILL", _fraction(0.8), 0.8),
    "sched_amort": ("ZKP2P_SCHED_AMORT", str, ""),
    "sched_priority_default": ("ZKP2P_SCHED_PRIORITY_DEFAULT", str, "bulk"),
    # fleet autoscaling (pipeline.sched.AutoscalePolicy, driven by the
    # FleetSupervisor off the fleet plane's merged signals): live-worker
    # bounds (workers_max 0 = autoscale off; min clamps to >= 1 when
    # on) and the hysteresis windows — how long the scale-up condition
    # (backlog growth / slo burn) and the scale-down condition (idle)
    # must hold CONTINUOUSLY before a step.
    "workers_min": ("ZKP2P_WORKERS_MIN", _nonneg_int(0), 0),
    "workers_max": ("ZKP2P_WORKERS_MAX", _nonneg_int(0), 0),
    "scale_up_s": ("ZKP2P_SCALE_UP_S", _nonneg_float(10.0), 10.0),
    "scale_down_s": ("ZKP2P_SCALE_DOWN_S", _nonneg_float(30.0), 30.0),
    # alert-engine thresholds (utils.alerts; the rule table lives in
    # docs/OBSERVABILITY.md): burn-rate multiple that pages when BOTH
    # the fast and slow merged windows exceed it, supervisor restarts
    # inside the breaker window that count as a storm, how long a
    # condition must hold to fire (for_s) and how long it must be
    # clean to clear (clear_s — the hysteresis damper), and the
    # heartbeat age that counts as a gap.
    "alert_burn_rate": ("ZKP2P_ALERT_BURN_RATE", _nonneg_float(2.0), 2.0),
    "alert_restarts": ("ZKP2P_ALERT_RESTARTS", _pos_int(3), 3),
    "alert_for_s": ("ZKP2P_ALERT_FOR_S", _nonneg_float(5.0), 5.0),
    "alert_clear_s": ("ZKP2P_ALERT_CLEAR_S", _nonneg_float(30.0), 30.0),
    "alert_hb_gap_s": ("ZKP2P_ALERT_HB_GAP_S", _nonneg_float(15.0), 15.0),
    # host auto-tune profile (utils.hostprof + pipeline.tune;
    # docs/TUNING.md §Host profiles): the profile-load gate ("0" =
    # ignore any profile on disk — the hand-picked-constants oracle arm
    # for tuned-vs-fallback A/Bs), an explicit profile path override
    # ("" = <precomp cache dir>/host_profile_<fingerprint>.json beside
    # .bench_cache; a copied profile whose embedded fingerprint doesn't
    # match this host is REJECTED, never loaded), the `zkp2p-tpu tune`
    # sweep's wall-clock budget in seconds, and a comma filter over the
    # sweep arms ("" = all of threads,ladder,window,geometry,columns).
    "profile": ("ZKP2P_PROFILE", _not_zero, True),
    "profile_path": ("ZKP2P_PROFILE_PATH", str, ""),
    "tune_budget_s": ("ZKP2P_TUNE_BUDGET_S", _nonneg_float(120.0), 120.0),
    "tune_arms": ("ZKP2P_TUNE_ARMS", str, ""),
    # sharded TPU arm (prover.groth16_tpu._prove_batch_sharded;
    # docs/TPU.md): the batch-axis pjit gate ("on" = route prove_tpu_batch
    # chunks through the pod-mesh program — batch data-parallel over the
    # mesh's outer axis, MSM bucket partial sums allreduced over the inner
    # ICI axis; anything else fails CLOSED to the single-device vmap),
    # the mesh shape ("BxS" = B batch-parallel groups of S base-axis
    # shards; a bare int N = "1xN"; "" = auto 1x<all devices>), the
    # persistent XLA compile-cache root the warm-cache command pre-warms
    # ("" = JAX_COMPILATION_CACHE_DIR or <repo>/.jax_cache — read by
    # utils.jaxcfg.cache_dir), and the fleet worker tier this process
    # advertises in heartbeats ("sharded" = the wide-batch mesh tier the
    # scheduler routes the bulk lane to; anything else = "native").
    "tpu_shard": ("ZKP2P_TPU_SHARD", str, "off"),
    "tpu_mesh": ("ZKP2P_TPU_MESH", str, ""),
    "jax_cache_dir": ("ZKP2P_JAX_CACHE_DIR", str, ""),
    "worker_tier": ("ZKP2P_WORKER_TIER", str, ""),
    # perf-regression sentry (utils.perfledger; docs/OBSERVABILITY.md
    # §perf sentry): the stage-cost ledger gate ("0" = the whole
    # subsystem off — no appends, no budgets, no overrun counting; the
    # fail-closed oracle arm of a ledger A/B), the budget multiplier
    # over the trailing-window median (>= 1.0), and the trailing-window
    # length in ledger entries the median is taken over.
    "perf_ledger": ("ZKP2P_PERF_LEDGER", _not_zero, True),
    "perf_tolerance": ("ZKP2P_PERF_TOLERANCE", _min_one_float(1.5), 1.5),
    "perf_window": ("ZKP2P_PERF_WINDOW", _pos_int(8), 8),
    # flame sampler (utils.flameprof; docs/OBSERVABILITY.md §flame
    # profiler): the sampling-profiler gate ("1" = the background
    # sampler may run and sentry overruns trigger captures; default OFF
    # — the zero-overhead oracle arm), the sampling rate in Hz (prime
    # by default so the sampler never phase-locks with periodic stage
    # work), how many service sweeps a triggered capture spans, and the
    # per-process cooldown between triggered captures (0 = no limit).
    "flame": ("ZKP2P_FLAME", _not_zero, False),
    "flame_hz": ("ZKP2P_FLAME_HZ", _pos_float(47.0), 47.0),
    "flame_capture_n": ("ZKP2P_FLAME_CAPTURE_N", _pos_int(2), 2),
    "flame_cooldown_s": ("ZKP2P_FLAME_COOLDOWN_S", _nonneg_float(60.0), 60.0),
}

# The ONLY knobs a hardware-session side-file may arm (bench.py's
# whitelist, promoted here so there is a single list).
ARMABLE = (
    "msm_affine", "msm_h", "msm_glv", "msm_batch_affine", "msm_overlap",
    "msm_multi", "msm_precomp", "matvec_seg", "ntt_pool", "sched",
    "profile", "tpu_shard", "worker_tier", "perf_ledger", "flame",
    "msm_interleave", "ntt_radix8", "witness_u64",
)
_ARMABLE_ENV = {KNOBS[k][0] for k in ARMABLE}


@dataclass(frozen=True)
class ProverConfig:
    msm_window: int = 4
    msm_signed: bool = True
    msm_unified: str = "auto"
    msm_affine: str = "0"
    msm_h: str = "windowed"
    msm_glv: bool = False
    msm_overlap: bool = True
    msm_batch_affine: bool = True
    msm_multi: bool = True
    msm_precomp: bool = True
    matvec_seg: bool = True
    ntt_pool: bool = True
    msm_interleave: bool = True
    ntt_radix8: bool = False
    witness_u64: bool = True
    precomp_depth: int = 8
    precomp_max_mb: int = 6144
    precomp_cache: str = ""
    precomp_persist_min: int = 65536
    precomp_families: str = "a,b1,c,h"
    batch_chunk: str = "auto"
    field_conv: str = "matmul"
    field_mul: str = "auto"
    curve_kernel: str = "auto"
    native_ifma: bool = True
    native_threads: Optional[int] = None
    no_cache: bool = False
    msm_prof: bool = False
    metrics_port: Optional[int] = None
    metrics_addr: str = "127.0.0.1"
    metrics_sink: str = ""
    trace_max: int = 65536
    faults: str = ""
    deadline_s: float = 0.0
    spool_cap: int = 0
    prove_retries: int = 2
    retry_backoff_s: float = 0.25
    slo_p95_s: float = 0.0
    slo_target: float = 0.95
    slo_window_s: float = 300.0
    ts_sample_s: float = 10.0
    worker_id: str = ""
    fleet_id: str = ""
    fleet_dir: str = ""
    fleet_workers: int = 2
    drain_timeout_s: float = 30.0
    rss_soft_mb: int = 0
    rss_hard_mb: int = 0
    breaker_k: int = 5
    breaker_window_s: float = 60.0
    restart_backoff_s: float = 0.5
    fleet_metrics_port: Optional[int] = None
    fleet_scrape_s: float = 2.0
    slo_fast_window_s: float = 60.0
    sched: str = "off"
    sched_target_fill: float = 0.8
    sched_amort: str = ""
    sched_priority_default: str = "bulk"
    workers_min: int = 0
    workers_max: int = 0
    scale_up_s: float = 10.0
    scale_down_s: float = 30.0
    alert_burn_rate: float = 2.0
    alert_restarts: int = 3
    alert_for_s: float = 5.0
    alert_clear_s: float = 30.0
    alert_hb_gap_s: float = 15.0
    profile: bool = True
    profile_path: str = ""
    tune_budget_s: float = 120.0
    tune_arms: str = ""
    tpu_shard: str = "off"
    tpu_mesh: str = ""
    jax_cache_dir: str = ""
    worker_tier: str = ""
    perf_ledger: bool = True
    perf_tolerance: float = 1.5
    perf_window: int = 8
    flame: bool = False
    flame_hz: float = 47.0
    flame_capture_n: int = 2
    flame_cooldown_s: float = 60.0
    # knob -> "default" | "armed" | "env"
    provenance: Dict[str, str] = field(default_factory=dict, compare=False)

    def describe(self) -> str:
        return " ".join(
            f"{k}={getattr(self, k)}({self.provenance.get(k, 'default')})" for k in KNOBS
        )

    def apply_env(self, environ=None) -> None:
        """Write the resolved values back into the environment so child
        processes, import-time module constants, and the C runtime's
        getenv() all see the same configuration."""
        env = os.environ if environ is None else environ
        for attr, (var, _parse, _default) in KNOBS.items():
            v = getattr(self, attr)
            if v is None:
                env.pop(var, None)
            elif isinstance(v, bool):
                env[var] = "1" if v else "0"
            else:
                env[var] = str(v)


# the registry and the dataclass must never drift: a retuned default in
# one place only is an import-time error, not a silent divergence
for _attr, (_var, _parse, _default) in KNOBS.items():
    assert ProverConfig.__dataclass_fields__[_attr].default == _default, (
        f"default drift for {_attr}: KNOBS says {_default!r}, "
        f"ProverConfig says {ProverConfig.__dataclass_fields__[_attr].default!r}"
    )


def load_config(
    environ=None,
    armed_flags_path: Optional[str] = None,
    log=None,
) -> ProverConfig:
    """Resolve the full configuration (default -> armed -> env)."""
    env = os.environ if environ is None else environ
    values: Dict[str, object] = {k: default for k, (_v, _p, default) in KNOBS.items()}
    prov: Dict[str, str] = {k: "default" for k in KNOBS}

    if armed_flags_path and os.path.exists(armed_flags_path):
        try:
            with open(armed_flags_path) as f:
                flags = json.load(f)
        except Exception as e:  # noqa: BLE001 — arming is best-effort
            flags = {}
            if log:
                log(f"armed flags unreadable: {e}")
        for var, raw in flags.items():
            if var not in _ARMABLE_ENV:
                if log:
                    log(f"armed flags: ignoring non-armable key {var!r}")
                continue
            for attr, (v, parse, _d) in KNOBS.items():
                if v == var:
                    values[attr] = parse(str({True: "1", False: "0"}.get(raw, raw)))
                    prov[attr] = "armed"

    for attr, (var, parse, _default) in KNOBS.items():
        raw = env.get(var)
        if raw is not None:
            values[attr] = parse(raw)
            prov[attr] = "env"

    return ProverConfig(provenance=prov, **values)
