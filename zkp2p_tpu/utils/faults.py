"""Seeded, deterministic fault injection for the proving service.

The service's failure semantics (docs/ROBUSTNESS.md) are claims, not
facts, until something can MAKE the failures happen on demand: a prover
that throws on one batch in five, a disk that returns ENOSPC exactly
once, a witness builder that stalls long enough for a SIGKILL to land
mid-prove.  This module is that something — named injection sites
threaded through the service/prover paths, armed by one env knob:

    ZKP2P_FAULTS="prove:raise:p=0.2,emit:enospc:once,witness:hang=3"

Grammar (comma-separated entries):

    entry   = "seed=" INT                 global RNG seed (default 0)
            | site ":" action (":" mod)*
    site    = witness | prove | verify | emit | claim | sink
              (open set — any [a-z_]+ token; the sites above are the
              ones wired into the tree, see docs/ROBUSTNESS.md)
    action  = "raise"                     raise FaultInjected
            | "enospc"                    raise OSError(ENOSPC)
            | "hang=" SECONDS             sleep, then continue
    mod     = "p=" FLOAT                  fire probability (default 1)
            | "once"                      fire at most once  (= n=1)
            | "n=" INT                    fire at most n times
            | "after=" INT                skip the first n eligible hits

Design constraints:

  * **Deterministic**: every fault owns a `random.Random` seeded from
    (global seed, site, entry index) — two processes with the same spec
    and the same call sequence inject identically; reruns reproduce.
  * **No-op when unset**: `fault_point(site)` with no ZKP2P_FAULTS is
    one env read + one compare (~1.5 µs measured); sites sit at request-stage
    granularity (per claim/witness/prove/emit), never inside MSM loops,
    so the armed-off overhead on the prove hot path is far inside the
    1 % budget (measured: docs/ROBUSTNESS.md §overhead).
  * **Audited**: the plan resolves through `record_arm("faults", ...)`
    ("off" or an 8-hex spec digest), so execution digests distinguish
    fault runs from clean ones and two clean A/B arms stay equal.

`FaultInjected` deliberately subclasses RuntimeError: consumers that
classify transient failures (service retry logic) name it explicitly;
everything else treats it as an ordinary crash — which is the point.
"""

from __future__ import annotations

import errno
import hashlib
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

ENV_VAR = "ZKP2P_FAULTS"

# the sites actually wired into the tree (reference, not enforcement —
# a typo'd site parses fine and simply never fires, the same way an
# unused knob arm is legal; keep this list in sync with ROBUSTNESS.md)
KNOWN_SITES = ("witness", "prove", "verify", "emit", "claim", "sink", "native_prove")

_ACTIONS = ("raise", "enospc", "hang")


class FaultInjected(RuntimeError):
    """An injected (transient-classified) failure — see ZKP2P_FAULTS."""


@dataclass
class Fault:
    site: str
    action: str                  # raise | enospc | hang
    arg: float = 0.0             # hang seconds
    p: float = 1.0               # fire probability per eligible hit
    limit: Optional[int] = None  # max fires (None = unlimited; once = 1)
    after: int = 0               # eligible hits to skip before firing
    seed_key: str = ""           # rng derivation key (spec-stable)
    seen: int = 0
    fired: int = 0
    rng: random.Random = field(default_factory=random.Random, repr=False)


class FaultPlan:
    """A parsed ZKP2P_FAULTS spec: per-site fault lists + spec digest."""

    def __init__(self, spec: str, faults: List[Fault], seed: int):
        self.spec = spec
        self.seed = seed
        self.digest = hashlib.sha256(spec.encode()).hexdigest()[:8]
        self.by_site: Dict[str, List[Fault]] = {}
        for f in faults:
            # per-fault deterministic stream: independent of every other
            # fault's draw sequence, reproducible across processes
            f.rng = random.Random(f"{seed}:{f.seed_key}")
            self.by_site.setdefault(f.site, []).append(f)
        # one lock for all counters: fire() runs from the service's
        # producer AND consumer threads; fairness does not matter but
        # the once/n accounting must not double-fire on a race
        self._lock = threading.Lock()

    def fire(self, site: str) -> None:
        flist = self.by_site.get(site)
        if not flist:
            return
        for f in flist:
            with self._lock:
                f.seen += 1
                if f.limit is not None and f.fired >= f.limit:
                    continue
                if f.seen <= f.after:
                    continue
                if f.p < 1.0 and f.rng.random() >= f.p:
                    continue
                f.fired += 1
            if f.action == "hang":
                time.sleep(f.arg)
                continue  # a hang delays the stage, it does not fail it
            if f.action == "enospc":
                raise OSError(errno.ENOSPC, f"injected ENOSPC at {site} [faults:{self.digest}]")
            raise FaultInjected(f"injected fault at {site} [faults:{self.digest}]")

    def counts(self) -> Dict[str, Dict[str, int]]:
        """site -> {seen, fired} totals (tests / chaos reporting)."""
        out: Dict[str, Dict[str, int]] = {}
        for site, flist in self.by_site.items():
            out[site] = {
                "seen": sum(f.seen for f in flist),
                "fired": sum(f.fired for f in flist),
            }
        return out


def parse_faults(spec: str) -> FaultPlan:
    """Parse a ZKP2P_FAULTS spec; raises ValueError with the offending
    entry on malformed input (the config knob stays a raw string — this
    is the one parser, shared by the service and the tests)."""
    faults: List[Fault] = []
    seed = 0
    for idx, raw in enumerate(x.strip() for x in spec.split(",")):
        if not raw:
            continue
        if raw.startswith("seed="):
            try:
                seed = int(raw[len("seed="):])
            except ValueError:
                raise ValueError(f"ZKP2P_FAULTS: bad seed {raw!r}") from None
            continue
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(f"ZKP2P_FAULTS: entry {raw!r} needs site:action")
        site, action_s, mods = parts[0], parts[1], parts[2:]
        if not site or not site.replace("_", "").isalpha():
            raise ValueError(f"ZKP2P_FAULTS: bad site in {raw!r}")
        arg = 0.0
        if action_s.startswith("hang="):
            action = "hang"
            try:
                arg = float(action_s[len("hang="):])
            except ValueError:
                raise ValueError(f"ZKP2P_FAULTS: bad hang seconds in {raw!r}") from None
            if arg < 0:
                raise ValueError(f"ZKP2P_FAULTS: negative hang in {raw!r}")
        elif action_s in ("raise", "enospc"):
            action = action_s
        else:
            raise ValueError(
                f"ZKP2P_FAULTS: unknown action {action_s!r} in {raw!r} "
                f"(have: raise, enospc, hang=SECONDS)"
            )
        f = Fault(site=site, action=action, arg=arg, seed_key=f"{site}:{idx}:{action}")
        for mod in mods:
            if mod == "once":
                f.limit = 1
            elif mod.startswith("p="):
                try:
                    f.p = float(mod[2:])
                except ValueError:
                    raise ValueError(f"ZKP2P_FAULTS: bad probability in {raw!r}") from None
                if not 0.0 <= f.p <= 1.0:
                    raise ValueError(f"ZKP2P_FAULTS: p out of [0,1] in {raw!r}")
            elif mod.startswith("n="):
                try:
                    f.limit = int(mod[2:])
                except ValueError:
                    raise ValueError(f"ZKP2P_FAULTS: bad n= in {raw!r}") from None
                if f.limit < 0:
                    # n=-1 (typo for n=1) would build a fault that can
                    # NEVER fire — a silently-unfaulted chaos run
                    raise ValueError(f"ZKP2P_FAULTS: negative n= in {raw!r}")
            elif mod.startswith("after="):
                try:
                    f.after = int(mod[len("after="):])
                except ValueError:
                    raise ValueError(f"ZKP2P_FAULTS: bad after= in {raw!r}") from None
                if f.after < 0:
                    raise ValueError(f"ZKP2P_FAULTS: negative after= in {raw!r}")
            else:
                raise ValueError(
                    f"ZKP2P_FAULTS: unknown modifier {mod!r} in {raw!r} "
                    f"(have: p=FLOAT, once, n=INT, after=INT)"
                )
        faults.append(f)
    return FaultPlan(spec, faults, seed)


# --------------------------------------------------------------------------
# Process state.  The plan is cached keyed by the RAW env value: the env
# is the transport (chaos workers arm via spawn env), flips re-parse,
# and the unset fast path is one dict lookup + one `is not` compare.
# Counters (once/n accounting) live on the cached plan, so they persist
# for the life of the spec — exactly the semantics "once" promises.

_plan: Optional[FaultPlan] = None
_plan_src: Optional[str] = None
# serializes the parse-and-install slow path: the service's witness
# producer and prove consumer threads race the FIRST fault_point, and
# two unsynchronized parses would install two plans — a `once` fault
# could then fire on each, breaking the determinism contract
_state_lock = threading.Lock()


def current_plan() -> Optional[FaultPlan]:
    """The active plan (None when ZKP2P_FAULTS is unset/empty).  Arms
    the `faults` audit gate on every change, so execution digests
    distinguish fault runs from clean ones."""
    global _plan, _plan_src
    src = os.environ.get(ENV_VAR, "")
    if src is _plan_src or src == _plan_src:
        # fast path, lock-free: _plan is installed BEFORE _plan_src,
        # so a matching src always sees its finished plan
        return _plan
    with _state_lock:
        if src == _plan_src:
            return _plan  # another thread won the parse race
        from .audit import record_arm

        if not src:
            plan = None
            record_arm("faults", "off")
        else:
            # a malformed spec is an operator error and must FAIL
            # LOUDLY (a chaos run that silently injected nothing would
            # "prove" fault tolerance it never tested) — at EVERY
            # fault_point until fixed: _plan_src stays unset on
            # failure, so each site re-parses and re-raises rather
            # than quietly running unfaulted
            plan = parse_faults(src)  # ValueError propagates
            record_arm("faults", plan.digest)
        _plan = plan
        _plan_src = src
        return _plan


def fault_point(site: str) -> None:
    """Injection site: no-op unless ZKP2P_FAULTS names `site`.  Raises
    FaultInjected / OSError(ENOSPC) or sleeps per the armed spec."""
    plan = current_plan()
    if plan is not None:
        plan.fire(site)


def faults_arm() -> str:
    """Resolve + audit-record the faults gate without firing anything
    (preflight/doctor hook).  Returns the recorded arm string."""
    plan = current_plan()
    return "off" if plan is None else plan.digest


def reset() -> None:
    """Drop the cached plan so the next fault_point re-parses the env
    and once/n counters start fresh (tests)."""
    global _plan, _plan_src
    with _state_lock:
        _plan = None
        _plan_src = None
