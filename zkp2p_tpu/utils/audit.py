"""Execution-path audit + device flight recorder.

Round 5's two most expensive findings were not slowness but
*invisibility*: every `default_backend() == "tpu"` fast-path gate had
been silently disarmed on-chip since round 2 (the PJRT plugin renamed
itself "axon"), and the batched prover OOM'd 15.75 G of HBM with no
memory telemetry at all.  PR 3's metrics layer records how LONG stages
took but not WHICH ARM executed — this module closes that blind spot:

  1. **Arm recording** (`record_arm`): every backend/impl gate —
     `jaxcfg.on_tpu`, the prover's `_unified`/`_affine`/`_h_bucket`/
     `_glv`, the pallas-vs-XLA field mul and curve kernel, the native
     GLV / batch-affine / IFMA-vs-scalar tiers — reports `(gate, arm)`
     at its call site into `zkp2p_path_taken_total{gate,arm}` counters
     and a process-wide gate→arm map.

  2. **Execution digest** (`execution_digest`): a stable hash of the
     sorted gate→arm map, stamped into the run manifest, every BENCH
     JSON and every service request record — two runs whose digests
     match are PROVEN to have exercised identical code paths before
     their numbers are compared; a silently-disarmed run is one digest
     diff away from being caught.

  3. **Flight recorder**: HBM watermarks via `device.memory_stats()`
     (`sample_device_memory`, gauges + per-request peak — the next OOM
     is predicted, not discovered) and jit compile events (count +
     seconds per trace stage via `jax.monitoring`; this box has
     measured 20-minute XLA:CPU prover compiles).

  4. **Preflight** (`preflight`): arm every gate, collect mis-arm
     warnings ("pallas requested but interpreting on CPU"), and emit a
     machine-readable report — the payload behind `zkp2p-tpu doctor`
     and the bench/service startup hooks.

Design constraints match utils.metrics: stdlib-only at import,
observation must never fail the prove around it, and the hot-path cost
(record_arm) is two dict operations + one counter add — measured on the
native prove path as noise (see docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Dict, List, Optional

from .metrics import REGISTRY

# gate -> latest arm string (GIL-atomic dict writes; cumulative per
# process, so a gate consulted only at jit-trace time keeps its arm in
# the digest across later proves that reuse the compiled executable).
_arms: Dict[str, str] = {}

# (gate, arm) -> Counter, cached so the registry lock is only taken on
# first sight of an arm; generation-keyed like trace._stage_hists so a
# REGISTRY.reset() never feeds an orphaned instrument.
_counters: Dict[Any, Any] = {}
_counters_gen = -1


def _arm_str(arm) -> str:
    if isinstance(arm, bool):
        return "on" if arm else "off"
    return str(arm)


def record_arm(gate: str, arm):
    """Report that `gate` resolved to `arm` at its call site.

    Returns `arm` unchanged so gate resolvers can
    `return record_arm("msm_glv", value)`.  Cost: two dict ops + a
    float add — cheap enough for resolvers consulted per-MSM or at
    jit-trace time (thousands of calls per trace)."""
    global _counters_gen
    s = _arm_str(arm)
    _arms[gate] = s
    if REGISTRY.generation != _counters_gen:
        _counters.clear()
        _counters_gen = REGISTRY.generation
    key = (gate, s)
    c = _counters.get(key)
    if c is None:
        c = _counters[key] = REGISTRY.counter("zkp2p_path_taken_total", {"gate": gate, "arm": s})
    c.inc()
    return arm


def gate_arms() -> Dict[str, str]:
    """Snapshot of the gate→arm map observed so far this process."""
    return dict(_arms)


def execution_digest(arms: Optional[Dict[str, str]] = None) -> str:
    """Stable 16-hex-char digest of the (sorted) gate→arm map.  Two
    processes that resolved every gate to the same arm produce the same
    digest regardless of resolution order; one flipped arm changes it."""
    if arms is None:
        arms = _arms
    blob = json.dumps(sorted(arms.items()), separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def reset() -> None:
    """Clear the gate→arm map (tests)."""
    _arms.clear()


# Summary of the most recent preflight() this process ran (None until
# one has).  The /status health route fails CLOSED on None: a scrape
# must never report "healthy" for a process whose gates nobody armed —
# the round-2 silent disarm applied to the health surface.
_preflight_report: Optional[Dict] = None


def last_preflight() -> Optional[Dict]:
    """{ts, backend, warnings, execution_digest} of the latest preflight
    run in this process, or None when none has run."""
    return _preflight_report


# ---------------------------------------------------------------------------
# Flight recorder, part 1: HBM watermarks.  `device.memory_stats()` is
# a cheap C call on TPU and None on CPU — the device list is probed once
# and a stats-less backend degrades to a no-op list scan per sample.

_mem_devices: Optional[list] = None
_peak_lock = threading.Lock()


def _memory_devices() -> list:
    global _mem_devices
    if _mem_devices is None:
        try:
            import jax

            devs = []
            for d in jax.devices():
                try:
                    if d.memory_stats():
                        devs.append(d)
                except Exception:  # noqa: BLE001 — stats are optional per PJRT backend
                    pass
            _mem_devices = devs
        except Exception:  # noqa: BLE001 — no backend at all
            _mem_devices = []
    return _mem_devices


def sample_device_memory(stage: str = "") -> Optional[Dict]:
    """Sample per-device HBM watermarks into gauges; returns the
    highest-use device's `{device, bytes_in_use, peak_bytes_in_use,
    bytes_limit}` (None when no device exposes memory stats — XLA:CPU).

    Call sites bracket prove/batch/sub-chunk boundaries so the
    `zkp2p_hbm_*` gauges track the allocation staircase a batched prove
    climbs; `stage` additionally keeps a max-semantics per-stage peak
    (`zkp2p_hbm_stage_peak_bytes{stage=...}`)."""
    best = None
    for i, d in enumerate(_memory_devices()):
        try:
            st = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — observation only
            continue
        used = int(st.get("bytes_in_use", 0))
        peak = int(st.get("peak_bytes_in_use", used))
        limit = int(st.get("bytes_limit", 0))
        lab = {"device": str(i)}
        REGISTRY.gauge("zkp2p_hbm_bytes_in_use", lab).set(used)
        REGISTRY.gauge("zkp2p_hbm_peak_bytes", lab).set(peak)
        if limit:
            REGISTRY.gauge("zkp2p_hbm_bytes_limit", lab).set(limit)
        if best is None or used > best["bytes_in_use"]:
            best = {
                "device": i,
                "bytes_in_use": used,
                "peak_bytes_in_use": peak,
                "bytes_limit": limit,
            }
    if best is not None and stage:
        g = REGISTRY.gauge("zkp2p_hbm_stage_peak_bytes", {"stage": stage})
        # locked max-update: a bare read-then-set from two concurrent
        # samplers of one stage label could regress the recorded peak
        with _peak_lock:
            g.set(max(g.value, best["peak_bytes_in_use"]))
    return best


# ---------------------------------------------------------------------------
# Flight recorder, part 2: compile events.  jax.monitoring publishes
# '/jax/core/compile/backend_compile_duration' per XLA compile; the
# listener attributes each to the calling thread's CURRENT trace stage
# (compiles run synchronously inside the first dispatch), so a 20-minute
# cold prover compile shows up as compile seconds under its stage
# instead of silently inflating the stage's own latency histogram.

_compile_installed = False


def install_compile_listener() -> bool:
    """Idempotently register the jit-compile event listener; False when
    the jax.monitoring API is unavailable."""
    global _compile_installed
    if _compile_installed:
        return True
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 — jax absent or too old
        return False

    from .trace import current_stack

    def _on_event(name: str, secs: float, **_kw) -> None:
        if not name.endswith("backend_compile_duration"):
            return
        try:
            stack = current_stack()
            stage = "/".join(stack) if stack else "(none)"
            REGISTRY.counter("zkp2p_compile_events_total", {"stage": stage}).inc()
            REGISTRY.counter("zkp2p_compile_seconds_total", {"stage": stage}).inc(secs)
        except Exception:  # noqa: BLE001 — observation must never fail a compile
            pass

    try:
        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:  # noqa: BLE001
        return False
    _compile_installed = True
    return True


# ---------------------------------------------------------------------------
# Preflight: the doctor payload.  Arms every gate by calling the real
# resolvers (the same functions the provers consult — no parallel
# reimplementation that could drift), collects mis-arm warnings, and
# returns a machine-readable report.


def _mis_arm_warnings(cfg, backend: str, arms: Dict[str, str], native_ok: bool) -> List[str]:
    """Config-vs-resolution contradictions: an operator asked for an arm
    the gates did not (or could not) take.  Expected degradations (auto
    gates off on a host backend) are NOT warnings."""
    w: List[str] = []
    tpu = arms.get("on_tpu") == "tpu"
    if arms.get("field_mul") == "pallas" and not tpu:
        w.append(
            f"field_mul resolved to the pallas kernel on backend={backend}: pallas "
            "runs in INTERPRET mode off-TPU (orders of magnitude slower) — unset "
            "ZKP2P_FIELD_MUL or run on a TPU"
        )
    if cfg.curve_kernel == "pallas" and arms.get("curve_kernel") != "pallas":
        w.append(
            f"curve_kernel=pallas requested but the gate did not arm (backend={backend} "
            "is not a TPU): running the XLA curve path"
        )
    # NOTE the device-prover gates read IMPORT-TIME knob snapshots (jit
    # identities depend on them) while cfg re-reads the env — so these
    # two warnings also catch a knob exported AFTER prover import, which
    # silently has no effect on the device prover (the native prover
    # re-reads the config and may still arm).
    if cfg.msm_h == "bucket" and arms.get("msm_h") != "bucket":
        w.append(
            "msm_h=bucket requested but the device-prover gate did not arm "
            "(msm_signed off, or ZKP2P_MSM_H was set after prover import — module "
            "constants snapshot at import): running the windowed h MSM"
        )
    if cfg.msm_glv and arms.get("msm_glv") == "off":
        w.append(
            "msm_glv requested but the device-prover gate did not arm "
            "(msm_signed off, or ZKP2P_MSM_GLV was set after prover import — module "
            "constants snapshot at import): unsigned digit planes on the device "
            "prover; the native prover re-reads the env and may still arm"
        )
    if not native_ok:
        w.append(
            "native library unavailable (csrc toolchain/build failed?): native prover "
            "gates report 'unavailable'"
        )
    elif (
        cfg.native_ifma
        and cfg.provenance.get("native_ifma") != "default"
        and arms.get("native_tier") == "scalar"
    ):
        # only when EXPLICITLY requested (env): the default-True knob on
        # a non-IFMA host is an expected degradation, not a mis-arm —
        # warning there would fail a --strict doctor gate on every
        # healthy machine nobody configured for IFMA
        w.append(
            "native_ifma explicitly enabled but the 52-bit IFMA tier did not arm "
            "(CPU lacks AVX512-IFMA, or msm_batch_affine=0 gates it off): scalar tier"
        )
    return w


def preflight(probe: bool = False, workload: bool = True, log=None, cfg=None) -> Dict:
    """Arm every gate, sample the backend, and return the preflight
    report (the `zkp2p-tpu doctor` payload; also hooked into bench.py
    and ProvingService.run so a mis-armed run warns before it burns a
    tunnel window).

    probe: run the subprocess TPU probe (jaxcfg.tpu_probe — seconds of
    wall time; off for in-process hooks whose caller already probed).
    workload: run one tiny jitted op so the backend is proven to
    execute and the compile listener ticks (skipped by lightweight
    startup hooks).
    cfg: a pre-resolved ProverConfig — pass it when the caller has
    already run cfg.apply_env() (bench's TPU tier): apply_env writes
    every knob back into the env, so a fresh load here would see every
    provenance as "env" and the explicit-request-only warning gates
    would fire on defaults."""
    from .config import load_config
    from .jaxcfg import last_probe, on_tpu, tpu_probe
    from .metrics import run_id
    from .trace import trace

    install_compile_listener()
    if cfg is None:
        cfg = load_config()
    report: Dict = {"type": "doctor", "ts": round(time.time(), 3), "run_id": run_id()}

    if probe:
        report["tpu_probe"] = tpu_probe()
    else:
        report["tpu_probe"] = last_probe() or {"skipped": True}

    backend = "unavailable"
    try:
        import jax

        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001 — report it, don't die
        report["backend_error"] = str(e)
    report["backend"] = backend

    # Arm every gate through its REAL resolver (each records itself).
    on_tpu()
    from ..curve.jcurve import G1J
    from ..field.jfield import field_mul_impl
    from ..prover.groth16_tpu import _affine, _batch_chunk_size, _glv, _h_bucket, _shard_mesh, _unified

    field_mul_impl()
    G1J._pallas()
    _unified()
    _affine()
    _h_bucket()
    _glv()
    _batch_chunk_size()
    # sharded-batch gate: "off" | "BxS" mesh shape | "fallback" — a
    # pjit-sharded batch prove must never share a digest with the
    # single-device loop (arms "off"/shape here; prove_tpu_batch
    # re-arms "fallback" when a batch can't split across the mesh)
    _shard_mesh()

    from ..native.lib import get_lib
    from ..prover.native_prove import (
        _msm_interleave_arm,
        _ntt_pool_arm,
        _ntt_radix8_arm,
        _use_batch_affine,
        _use_glv,
        _use_matvec_seg,
        _use_msm_multi,
        _use_msm_overlap,
        _use_msm_precomp,
        _use_witness_u64,
    )

    _use_glv()
    _use_batch_affine()
    _use_msm_multi()
    _use_msm_overlap()
    _use_msm_precomp()
    _use_matvec_seg()
    _ntt_pool_arm()
    _msm_interleave_arm()
    _ntt_radix8_arm()
    _use_witness_u64()
    native_ok = False
    try:
        native_ok = get_lib() is not None
    except Exception:  # noqa: BLE001 — a broken toolchain is a finding, not a crash
        pass
    if native_ok:
        from ..prover.native_prove import _native_ifma_tier, _pick_window

        if _native_ifma_tier():
            # arms window_source ("profile" when the host profile holds
            # tuned MSM geometry for this context, else "fallback") via
            # a representative single-thread pick — the same resolver
            # every real MSM consults
            _pick_window(1 << 12, threads=1)
    else:
        record_arm("native_tier", "unavailable")

    # fault-injection gate (utils.faults): "off" or the 8-hex spec
    # digest — a chaos run and a clean run must never share a digest
    from .faults import faults_arm

    faults_arm()

    # service observability gates (utils.slo): the SLO objective and the
    # time-series sampler interval — an A/B with the sampler off must be
    # digest-distinguishable from one with it on, like the fault gate
    from .slo import slo_arm, timeseries_arm

    slo_arm()
    timeseries_arm()

    # fleet gates (pipeline.fleet): membership ("worker" when a
    # supervisor stamped ZKP2P_WORKER_ID, else "off") and the resource
    # governor budgets — a degraded fleet worker must never share a
    # digest with a clean solo service
    from ..pipeline.fleet import fleet_member_arm, governor_arm

    fleet_member_arm()
    governor_arm()

    # scheduler gate (pipeline.sched): the adaptive batching/shedding
    # controller vs the static oracle arm — an adaptive run must never
    # share a digest with a static one
    from ..pipeline.sched import sched_arm, worker_tier_arm

    sched_arm()
    # worker-tier gate: "native" | "sharded" — heterogeneous-fleet
    # routing decisions must be attributable to the tier this worker
    # advertised (a bulk batch served by the wrong tier is an A/B
    # confound, not just a perf blip)
    worker_tier_arm()

    # host-profile gate (utils.hostprof): off | tuned | fallback — a run
    # steered by a tune-produced profile (geometry, thread default,
    # seeded amortization) must never share a digest with a
    # hand-picked-constants run
    from .hostprof import profile_arm

    profile_arm()

    # perf-ledger gate (utils.perfledger): the stage-cost ledger and
    # its live budgets — a ledger-on run must never share a digest with
    # the ledger-off oracle arm of an overhead A/B
    from .perfledger import perf_arm

    perf_arm()

    # flame-sampler gate (utils.flameprof): the in-process sampling
    # profiler + overrun-triggered captures — the arm carries the
    # sampling rate, so runs at different Hz are distinguishable too
    from .flameprof import flame_arm

    flame_arm()

    if workload and backend != "unavailable":
        # one tiny jitted op: proves the backend executes and ticks the
        # compile listener.  Deliberately NOT a gated field mul — a
        # forced-pallas arm on a host backend would drag the doctor
        # through an interpret-mode compile; the warning below covers it.
        import jax
        import jax.numpy as jnp

        t0 = time.perf_counter()
        with trace("doctor/workload"):
            jax.jit(lambda x: x * 2 + 1)(jnp.arange(8)).block_until_ready()
        report["workload_s"] = round(time.perf_counter() - t0, 3)

    from .metrics import serialize_knobs

    arms = gate_arms()
    report["gates"] = arms
    report["knobs"] = serialize_knobs(cfg)
    report["provenance"] = dict(cfg.provenance)
    report["warnings"] = _mis_arm_warnings(cfg, backend, arms, native_ok)
    probe_rec = report["tpu_probe"]
    if probe_rec.get("ok") and arms.get("on_tpu") != "tpu":
        report["warnings"].append(
            "TPU probe succeeded but the in-process backend is "
            f"{backend}: gates armed for the fallback paths"
        )
    report["device_memory"] = sample_device_memory("preflight")
    report["execution_digest"] = execution_digest()
    global _preflight_report
    _preflight_report = {
        "ts": report["ts"],
        "backend": backend,
        "warnings": len(report["warnings"]),
        "execution_digest": report["execution_digest"],
    }
    if log is not None:
        for msg in report["warnings"]:
            log(f"PREFLIGHT WARNING: {msg}")
    return report
