"""Process-wide metrics registry for the proving stack.

The reference's observability is `console.time("zk-gen")` and a UI
stopwatch (SURVEY.md §5); a proving *service* needs attributable
numbers: counters/gauges/histograms that every layer (bench, native
prover, device prover, pipeline service) publishes into, a run manifest
(host facts + knob states + run_id) that makes each dump self-
describing, a rotating JSONL sink for offline aggregation
(tools/trace_report.py), and Prometheus text exposition behind
ZKP2P_METRICS_PORT (default off).

Design constraints:
  - zero hard dependencies (stdlib + the already-present numpy-free
    paths): importable everywhere trace.py is;
  - instruments are cheap under the GIL (plain attribute updates; the
    registry lock is only taken on get-or-create);
  - histograms are FIXED-BUCKET and mergeable, so per-process snapshots
    can be combined across service workers without raw-sample transfer.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

# Log-spaced millisecond buckets covering one MSM chunk (~1 ms) up to a
# cold full-size prove (~minutes).  Upper bounds; +Inf is implicit.
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000, 10000, 30000, 60000, 180000,
)

_LabelKey = Tuple[Tuple[str, str], ...]

# `# HELP` text per metric family for the Prometheus exposition (0.0.4
# requires one HELP/TYPE block per family; an unknown family gets a
# generic pointer at the docs).  Kept HERE — beside the exposition —
# rather than at the scattered call sites, so the scrape never emits a
# family without its block.
METRIC_HELP: Dict[str, str] = {
    "zkp2p_stage_ms": "Stage latency histogram fed by every trace() span",
    "zkp2p_proves_total": "Proofs produced, by prover backend",
    "zkp2p_service_requests_total": "Terminal request transitions, by state (docs/ROBUSTNESS.md state machine)",
    "zkp2p_service_retries_total": "Transient failures retried or deferred instead of terminal-ed",
    "zkp2p_service_bisections_total": "Batch proves split in half to isolate a poisoned request",
    "zkp2p_service_degraded_total": "Proves rescued by the degradation ladder, by rung",
    "zkp2p_service_deadline_total": "Requests terminal-ed error-deadline-exceeded",
    "zkp2p_service_shed_total": "Requests shed by the spool admission cap",
    "zkp2p_service_emit_failures_total": "Proof-emit failures (transient ones defer the request)",
    "zkp2p_service_deferred_total": "Non-terminal sweep outcomes: claim released for a later sweep to retry",
    "zkp2p_service_takeovers_total": "Stale-claim steal attempts, by result (won|lost)",
    "zkp2p_service_batch_fill": "Live requests per batch handed to the prover (fill vs batch_size)",
    "zkp2p_service_backlog": "Open spool requests at the last time-series sample",
    "zkp2p_service_in_flight": "Open spool requests under a fresh claim at the last time-series sample",
    "zkp2p_slo_attainment": "Fraction of rolling-window requests meeting the SLO (1.0 on an empty window)",
    "zkp2p_slo_burn_rate": "(1-attainment)/(1-target): error-budget burn multiple; 1.0 = at target",
    "zkp2p_slo_window_p95_s": "Exact p95 request latency (arrival->terminal) over the rolling window",
    "zkp2p_slo_window_requests": "Requests in the rolling SLO window",
    "zkp2p_slo_objective_s": "Configured p95 latency objective (ZKP2P_SLO_P95_S; 0 = none)",
    "zkp2p_trace_dropped_total": "Trace ring-buffer overflow evictions",
    "zkp2p_path_taken_total": "Gate consultations by resolved arm (execution audit)",
    "zkp2p_compile_events_total": "XLA/jit compiles attributed to the triggering trace stage",
    "zkp2p_compile_seconds_total": "XLA/jit compile seconds attributed to the triggering trace stage",
    "zkp2p_hbm_bytes_in_use": "Live device memory per device",
    "zkp2p_hbm_peak_bytes": "Process-lifetime device memory high-water mark per device",
    "zkp2p_hbm_bytes_limit": "Device memory capacity per device",
    "zkp2p_hbm_stage_peak_bytes": "Max-semantics per-stage device memory peak",
    "zkp2p_precomp_table_bytes": "Resident fixed-base table bytes per G1 family",
    "zkp2p_precomp_total_bytes": "Resident fixed-base table bytes, all families",
    "zkp2p_fleet_workers": "Fleet worker slots by state (up|backoff|parked|done) at the last supervisor tick",
    "zkp2p_fleet_restarts_total": "Worker restarts performed by the fleet supervisor, by worker",
    "zkp2p_fleet_parked_total": "Workers parked by the crash-loop circuit breaker",
    "zkp2p_fleet_drain_escalations_total": "Drains that exceeded ZKP2P_DRAIN_TIMEOUT_S and were escalated to SIGKILL",
    "zkp2p_fleet_governor_soft_total": "Soft RSS-budget breaches (degradation ctl written), by worker",
    "zkp2p_fleet_governor_hard_total": "Hard RSS-budget breaches (worker drained + restarted), by worker",
    "zkp2p_fleet_worker_rss_bytes": "Per-worker resident-set size at the last governor sample",
    "zkp2p_fleet_watchdog_kills_total": "Hung workers (stale heartbeat, live pid) killed by the supervisor watchdog",
    "zkp2p_fleet_degrade_applied_total": "Governor soft-degrade overlays applied inside a worker",
    "zkp2p_fleet_scrapes_total": "Fleet-plane scrape cycles completed by the supervisor",
    "zkp2p_fleet_scrape_failures_total": "Worker snapshot scrapes that failed (counted, never fatal), by worker",
    "zkp2p_fleet_merge_refusals_total": "Metric families refused during fleet merge (histogram bucket-layout mismatch), by family",
    "zkp2p_fleet_alerts_total": "Alert FIRE transitions by rule (hysteresis: one inc per episode, not per flap)",
    "zkp2p_fleet_slo_attainment": "Merged-window fleet SLO attainment (pooled worker samples)",
    "zkp2p_fleet_slo_burn_fast": "Fleet error-budget burn over the trailing fast window (merged samples)",
    "zkp2p_fleet_slo_burn_slow": "Fleet error-budget burn over the full merged window",
    "zkp2p_fleet_slo_window_p95_s": "Exact p95 over the pooled fleet SLO window",
    "zkp2p_fleet_slo_window_requests": "Samples across every worker's SLO window (sum of window sizes)",
    "zkp2p_fleet_slo_objective_s": "Configured p95 objective the fleet windows are judged against",
    "zkp2p_fleet_backlog": "Open spool requests at the last supervisor scrape (supervisor's own scan)",
    "zkp2p_stage_budget_overruns_total": "Terminal-request spans over their ledger-derived stage budget, by stage (utils.perfledger)",
    "zkp2p_perf_budget_stages": "Stage budgets loaded from the perf ledger for this worker's circuit",
    "zkp2p_flame_captures_total": "Flame-sampler captures written, by trigger (overrun|manual) (utils.flameprof)",
    "zkp2p_sched_batch_size": "Adaptive controller's bulk-lane batch target at the last sweep plan",
    "zkp2p_sched_decisions_total": "Scheduler decisions by kind (batch|shed|lane|scale_up|scale_down)",
    "zkp2p_fleet_workers_target": "Autoscaler's live-worker target after the last evaluation",
}


def _label_key(labels: Optional[Dict[str, str]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter.  inc() is a plain float add — atomic enough
    under the GIL for the per-stage/per-request rates this tracks."""

    kind = "counter"
    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: _LabelKey = ()):  # noqa: D401
        self.name = name
        self.labels = labels
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        self.value += v

    def state(self) -> Dict:
        return {"value": self.value}

    def merge_state(self, st: Dict) -> None:
        self.value += st["value"]


class Gauge:
    """Last-write-wins instantaneous value (pool depth, knob arm...)."""

    kind = "gauge"
    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: _LabelKey = ()):  # noqa: D401
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def state(self) -> Dict:
        return {"value": self.value}

    def merge_state(self, st: Dict) -> None:
        # merging gauges across processes keeps the max (peak semantics —
        # the depth/arm gauges this registry uses are all peak-or-flag)
        self.value = max(self.value, st["value"])


class Histogram:
    """Fixed-bucket histogram: counts per upper bound (+Inf last), sum,
    count, max.  Mergeable ONLY across identical bucket layouts — the
    point of fixing the layout process-wide."""

    kind = "histogram"
    __slots__ = ("name", "labels", "buckets", "counts", "sum", "count", "max")

    def __init__(self, name: str, labels: _LabelKey = (), buckets: Optional[Tuple[float, ...]] = None):
        self.name = name
        self.labels = labels
        self.buckets = tuple(buckets) if buckets else DEFAULT_MS_BUCKETS
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self.max = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (upper bound of the bucket
        holding the q-th sample) — for quick in-process reads; exact
        percentiles come from the raw JSONL records via trace_report."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.buckets[i] if i < len(self.buckets) else self.max
        return self.max

    def state(self) -> Dict:
        return {
            "buckets": list(self.buckets),
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
            "max": self.max,
        }

    def merge_state(self, st: Dict) -> None:
        if tuple(st["buckets"]) != self.buckets:
            raise ValueError(f"histogram {self.name}: bucket layout mismatch")
        for i, c in enumerate(st["counts"]):
            self.counts[i] += c
        self.sum += st["sum"]
        self.count += st["count"]
        self.max = max(self.max, st["max"])


class Registry:
    """Get-or-create instrument store.  One process-wide instance
    (REGISTRY) backs trace(), the service, and the provers; fresh
    instances exist for tests and for merging foreign snapshots."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, str, _LabelKey], object] = {}
        # bumped by reset(): callers holding instrument references
        # (trace.py's per-stage cache) re-fetch when it moves, so a
        # reset never silently severs their exposition
        self.generation = 0

    def _get(self, cls, name: str, labels: Optional[Dict[str, str]], **kw):
        key = (cls.kind, name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(name, key[2], **kw)
            return m

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(
        self, name: str, labels: Optional[Dict[str, str]] = None,
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    def snapshot(self) -> List[Dict]:
        """JSON-able state of every instrument (mergeable elsewhere)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return [
            {"kind": m.kind, "name": m.name, "labels": dict(m.labels), **m.state()}
            for m in metrics
        ]

    def merge(self, snapshot: List[Dict]) -> None:
        """Fold a snapshot() from another process/registry into this one."""
        for rec in snapshot:
            cls = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}[rec["kind"]]
            kw = {"buckets": tuple(rec["buckets"])} if rec["kind"] == "histogram" else {}
            self._get(cls, rec["name"], rec["labels"], **kw).merge_state(rec)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self.generation += 1

    # ------------------------------------------------------- exposition

    def to_prometheus(self) -> str:
        """Prometheus text format (0.0.4).  Metric names are used as
        registered (the zkp2p_ prefix convention lives at call sites)."""

        def fmt_labels(labels: _LabelKey, extra: str = "") -> str:
            parts = [f'{k}="{_esc(v)}"' for k, v in labels]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        def _esc(v: str) -> str:
            return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")

        def _num(v: float) -> str:
            # %g truncates to 6 significant digits — a requests counter
            # past 1e6 (or a ns gauge in the billions) would stop
            # visibly incrementing between scrapes; emit integral values
            # exactly and floats at full precision
            if float(v).is_integer():
                return str(int(v))
            return repr(float(v))

        with self._lock:
            metrics = list(self._metrics.values())
        by_name: Dict[Tuple[str, str], List] = {}
        for m in metrics:
            by_name.setdefault((m.name, m.kind), []).append(m)
        out: List[str] = []
        for (name, kind), ms in sorted(by_name.items()):
            # native gauges share one templated help line; everything
            # else resolves through METRIC_HELP (0.0.4 HELP text escapes
            # only backslash and newline — quotes stay literal)
            if name.startswith("zkp2p_native_"):
                help_s = f"Mirror of the native C stats slot {name[len('zkp2p_native_'):]}"
            else:
                help_s = METRIC_HELP.get(name, "zkp2p metric (docs/OBSERVABILITY.md)")
            out.append("# HELP %s %s" % (name, help_s.replace("\\", r"\\").replace("\n", r"\n")))
            out.append(f"# TYPE {name} {kind}")
            for m in ms:
                if kind == "histogram":
                    cum = 0
                    for ub, c in zip(m.buckets, m.counts):
                        cum += c
                        le = 'le="%g"' % ub
                        out.append(f"{name}_bucket{fmt_labels(m.labels, le)} {cum}")
                    cum += m.counts[-1]
                    le_inf = 'le="+Inf"'
                    out.append(f"{name}_bucket{fmt_labels(m.labels, le_inf)} {cum}")
                    out.append(f"{name}_sum{fmt_labels(m.labels)} {_num(m.sum)}")
                    out.append(f"{name}_count{fmt_labels(m.labels)} {m.count}")
                else:
                    out.append(f"{name}{fmt_labels(m.labels)} {_num(m.value)}")
        return "\n".join(out) + "\n"


REGISTRY = Registry()

# ---------------------------------------------------------------------------
# Run manifest: every dump carries WHO produced it (run_id + pid), WHERE
# (host facts — PR 2's unattributable 3.28-3.68 s spread is why), and
# HOW (every knob state + provenance), so a trace file read weeks later
# is self-describing.

_run_id: Optional[str] = None


def run_id() -> str:
    """Stable per-process run identifier (12 hex chars)."""
    global _run_id
    if _run_id is None:
        _run_id = uuid.uuid4().hex[:12]
    return _run_id


def host_facts() -> Dict:
    """Host facts that explain run-to-run spread: the RESOLVED native
    worker count (ZKP2P_NATIVE_THREADS else core count — the same rule
    the C pool and prover apply), CPU identity, and IFMA availability.
    Shared by bench.py's BENCH record and the run manifest."""
    from .config import load_config

    cpu_model = "unknown"
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    cpu_model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    ifma = 0
    try:
        from ..native.lib import get_lib

        lib = get_lib()
        if lib is not None:
            ifma = int(lib.zkp2p_ifma_available())
    except Exception:  # noqa: BLE001 — attribution must not break a prove
        pass
    cfg = load_config()
    return {
        "native_threads": cfg.native_threads or (os.cpu_count() or 1),
        "cpu_model": cpu_model,
        "cpu_count": os.cpu_count() or 1,
        "ifma": ifma,
    }


def serialize_knobs(cfg) -> Dict:
    """Every knob as a JSON-able value — THE one serialization shared by
    the run manifest and the doctor report (a divergent copy would let
    the two disagree about knob values)."""
    from .config import KNOBS

    return {
        attr: (v if isinstance(v, (int, float, bool, str, type(None))) else str(v))
        for attr, v in ((a, getattr(cfg, a)) for a in KNOBS)
    }


def run_manifest() -> Dict:
    """{run_id, pid, ts, host facts, every knob + provenance, observed
    gate arms + execution digest, last TPU probe}."""
    from .audit import execution_digest, gate_arms
    from .config import load_config
    from .jaxcfg import last_probe

    cfg = load_config()
    knobs = serialize_knobs(cfg)
    # host auto-tune profile (utils.hostprof): which arm resolved (off |
    # tuned | fallback), from which path, under which hardware
    # fingerprint — so a tuned-vs-fallback A/B is attributable from the
    # artifact alone, matching the precomp rows' geometry_source.
    # Resolved BEFORE the gate snapshot: profile_manifest() records the
    # host_profile arm, and the gates/digest below must include it.
    host_profile = None
    try:
        from .hostprof import profile_manifest

        host_profile = profile_manifest()
    except Exception:  # noqa: BLE001 — attribution must not break a dump
        pass
    man = {
        "run_id": run_id(),
        "pid": os.getpid(),
        "ts": round(time.time(), 3),
        "host": host_facts(),
        "knobs": knobs,
        "provenance": dict(cfg.provenance),
        # which arms actually executed (audit.record_arm call sites) —
        # the digest is the comparison key: equal digests = provably
        # identical code paths (docs/OBSERVABILITY.md §execution audit)
        "gates": gate_arms(),
        "execution_digest": execution_digest(),
    }
    probe = last_probe()
    if probe is not None:
        man["tpu_probe"] = probe
    # where THIS process's /metrics endpoint actually listens — under
    # ZKP2P_METRICS_PORT=auto the knob value (0) says nothing, so the
    # manifest records the OS-assigned port (scrape discoverability for
    # fleet workers; the fleet heartbeat carries the same number)
    if _bound_port is not None:
        man["metrics_port_bound"] = _bound_port
    # fixed-base precomputed-table memory accounting (prover.precomp):
    # per-family geometry + resident bytes + build-vs-cache provenance,
    # so table RAM is attributable in every trace/bench artifact
    try:
        from ..prover.precomp import precomp_manifest

        pm = precomp_manifest()
        if pm is not None:
            man["precomp"] = pm
    except Exception:  # noqa: BLE001 — attribution must not break a dump
        pass
    # circuit soundness audits performed in this process (snark.analysis
    # — the registry admission gate): digest + finding counts per
    # circuit, so every artifact records WHICH audited circuit it served
    try:
        from ..snark.analysis import audit_manifest

        am = audit_manifest()
        if am:
            man["circuit_audits"] = am
    except Exception:  # noqa: BLE001 — attribution must not break a dump
        pass
    # segmented matvec plans (prover.matvec_plan): per-matrix shape +
    # provenance + the pool width the segment partition used
    try:
        from ..prover.matvec_plan import matvec_plan_manifest

        mm = matvec_plan_manifest()
        if mm is not None:
            man["matvec_plans"] = mm
    except Exception:  # noqa: BLE001 — attribution must not break a dump
        pass
    if host_profile is not None:
        man["host_profile"] = host_profile
    return man


def publish_native_stats(registry: Optional[Registry] = None) -> Optional[Dict]:
    """Read the native runtime's counter block (native.lib
    stats_snapshot) into `zkp2p_native_<field>` gauges; returns the raw
    snapshot (None when the native lib is unavailable).  Gauges, not
    counters: the C block is itself cumulative, so last-write-wins
    mirrors it without double counting."""
    try:
        from ..native.lib import stats_snapshot

        snap = stats_snapshot()
    except Exception:  # noqa: BLE001 — numpy-less envs, stale .so:
        return None    # observation must never fail the prove around it
    if snap is None:
        return None
    reg = registry if registry is not None else REGISTRY
    for field, v in snap.items():
        reg.gauge(f"zkp2p_native_{field}").set(v)
    return snap


# ---------------------------------------------------------------------------
# Rotating JSONL sink: the durable side of the registry.  One record per
# line; each fresh file opens with a manifest line; every write is ONE
# O_APPEND write() so interleaved service workers produce intact lines.
# Rotation is guarded by an flock'd sidecar (<path>.lock) because the
# advertised mode is MULTIPLE worker processes sharing one path — two
# unsynchronized rotators would double-shift backups (losing records) or
# let a writer land on a fresh file between size-check and open without
# its manifest line.


class JsonlSink:
    def __init__(self, path: str, max_bytes: int = 16 << 20, backups: int = 3):
        self.path = path
        self.max_bytes = max_bytes
        self.backups = backups
        self._lock = threading.Lock()
        # Identity (st_dev, st_ino) of the file THIS instance last
        # stamped its manifest into: a restarted service appending to an
        # existing sub-cap sink must still stamp its run's manifest (new
        # run_id, possibly new knob arms), and a rotation performed by a
        # SIBLING process changes the identity under us — both cases
        # re-stamp, or trace_report --runs/--diff loses the stage-span
        # attribution for every run but the file's first.
        self._stamped_id: Optional[Tuple[int, int]] = None

    def _rotate_locked(self) -> None:
        for i in range(self.backups - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        if os.path.exists(self.path):
            os.replace(self.path, f"{self.path}.1")

    def write(self, record: Dict) -> None:
        self.write_many([record])

    def write_many(self, records: List[Dict]) -> None:
        if not records:
            return
        payload = "".join(json.dumps(r, default=str) + "\n" for r in records)
        with self._lock:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            lock_fd = -1
            try:
                import fcntl

                lock_fd = os.open(self.path + ".lock", os.O_CREAT | os.O_WRONLY, 0o644)
                fcntl.flock(lock_fd, fcntl.LOCK_EX)
            except Exception:  # noqa: BLE001 — no flock (exotic fs): in-process lock only
                if lock_fd >= 0:
                    os.close(lock_fd)
                    lock_fd = -1
            try:
                try:
                    st = os.stat(self.path)
                    size, cur_id = st.st_size, (st.st_dev, st.st_ino)
                except OSError:
                    size, cur_id = -1, None  # fresh file
                if size >= 0 and size + len(payload) > self.max_bytes:
                    self._rotate_locked()
                    size, cur_id = -1, None
                if size < 0 or cur_id != self._stamped_id:
                    payload = json.dumps({"type": "manifest", **run_manifest()}) + "\n" + payload
                fd = os.open(self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
                try:
                    os.write(fd, payload.encode())
                    fst = os.fstat(fd)
                    self._stamped_id = (fst.st_dev, fst.st_ino)
                finally:
                    os.close(fd)
            finally:
                if lock_fd >= 0:
                    os.close(lock_fd)  # releases the flock


# ---------------------------------------------------------------------------
# Prometheus exposition: a tiny stdlib HTTP endpoint, default OFF
# (ZKP2P_METRICS_PORT unset).  One server per process, daemon thread —
# observation must never keep a prover alive.

_server = None
_server_lock = threading.Lock()
# the port the endpoint actually bound — equals the configured port for
# a fixed port, and the OS-assigned ephemeral port under
# ZKP2P_METRICS_PORT=auto/0 (recorded in the run manifest and the fleet
# heartbeat so scrapes stay discoverable across N workers on one host)
_bound_port: Optional[int] = None


def bound_metrics_port() -> Optional[int]:
    """The port the /metrics endpoint is actually listening on (None
    when exposition is off / the server never started)."""
    return _bound_port


def maybe_start_metrics_server(port: Optional[int] = None, registry: Optional[Registry] = None):
    """Start (idempotently) the /metrics HTTP endpoint when a port is
    configured; returns the server or None when exposition is off.
    Port 0 ("auto") binds an OS-assigned ephemeral port — read it back
    via `bound_metrics_port()`.  Binds ZKP2P_METRICS_ADDR (default
    localhost — the payload discloses host facts and knob config;
    0.0.0.0 is an explicit opt-in)."""
    global _server, _bound_port
    reg = registry if registry is not None else REGISTRY
    from .config import load_config

    if port is None:
        port = load_config().metrics_port
    if port is None:
        return None
    addr = load_config().metrics_addr or "127.0.0.1"
    with _server_lock:
        if _server is not None:
            return _server
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib API
                path = self.path.split("?", 1)[0].rstrip("/")
                if path in ("", "/metrics"):
                    publish_native_stats(reg)  # scrape-time native refresh
                    try:  # scrape-time SLO gauge refresh (same contract)
                        from .slo import publish_slo

                        publish_slo(reg)
                    except Exception:  # noqa: BLE001 — exposition only
                        pass
                    self._send(200, reg.to_prometheus().encode(), "text/plain; version=0.0.4")
                elif path == "/status":
                    # fails CLOSED (503) while preflight hasn't run: a
                    # load balancer must not route to a worker whose
                    # gates nobody armed (slo.status_payload docs)
                    try:
                        from .slo import status_payload

                        body = status_payload()
                        code = 200 if body.get("ok") else 503
                    except Exception as e:  # noqa: BLE001 — degraded, not dead
                        body, code = {"ok": False, "reason": f"status error: {e}"}, 500
                    self._send(code, (json.dumps(body) + "\n").encode(), "application/json")
                elif path == "/healthz":
                    # liveness only: the process is up and serving HTTP.
                    # Readiness (gates armed, SLO state) is /status's job.
                    self._send(200, b'{"ok": true}\n', "application/json")
                elif path == "/snapshot":
                    # machine scrape for the FLEET PLANE (docs/
                    # OBSERVABILITY.md §fleet plane): the raw registry
                    # snapshot (mergeable — Registry.merge consumes it
                    # verbatim) plus the serialized SLO window, so the
                    # supervisor can sum counters, label gauges,
                    # bucket-merge histograms and pool SLO samples
                    # instead of re-parsing Prometheus text
                    publish_native_stats(reg)
                    try:  # same refresh-where-read contract as /metrics
                        from .slo import publish_slo

                        publish_slo(reg)
                    except Exception:  # noqa: BLE001 — exposition only
                        pass
                    body: Dict = {
                        "ts": round(time.time(), 3),
                        "pid": os.getpid(),
                        "run_id": run_id(),
                        "metrics": reg.snapshot(),
                    }
                    try:
                        from .audit import last_preflight
                        from .config import load_config
                        from .slo import default_tracker

                        body["armed"] = last_preflight() is not None
                        body["slo_window"] = default_tracker().window_state()
                        cfg = load_config()
                        if cfg.worker_id:
                            body["worker"] = cfg.worker_id
                        if cfg.fleet_id:
                            body["fleet"] = cfg.fleet_id
                    except Exception:  # noqa: BLE001 — a partial snapshot
                        pass           # still merges; armed defaults absent
                    self._send(200, (json.dumps(body) + "\n").encode(), "application/json")
                else:
                    self.send_response(404)
                    self.end_headers()

            def log_message(self, *_a):  # scrapes must not spam stderr
                pass

        try:
            srv = ThreadingHTTPServer((addr, int(port)), Handler)
        except OSError as e:
            # EADDRINUSE from a sibling worker sharing the port, a
            # privileged port, ... — observation must never fail a
            # prove: degrade to no endpoint, loudly
            import sys

            print(f"[metrics] endpoint on :{port} unavailable ({e}); exposition off", file=sys.stderr)
            return None
        threading.Thread(target=srv.serve_forever, daemon=True, name="zkp2p-metrics").start()
        _server = srv
        _bound_port = int(srv.server_address[1])
        if not port:
            # auto mode: say which port the OS handed out — the only
            # place a human would otherwise learn it is the manifest
            import sys

            print(f"[metrics] auto port: listening on :{_bound_port}", file=sys.stderr)
        return srv


def stop_metrics_server() -> None:
    """Tear down the exposition endpoint (tests; service shutdown)."""
    global _server, _bound_port
    with _server_lock:
        if _server is not None:
            srv = _server
            _server = None
            _bound_port = None
            srv.shutdown()
            srv.server_close()
