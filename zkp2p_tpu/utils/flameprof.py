"""Continuous profiling: the in-process flame sampler (ISSUE 19).

The perf sentry (utils.perfledger) says *that* a stage regressed;
nothing in the tree said *why* — attribution meant a hand-run of
`tools/msm_native_prof.py` on a quiet box, useless against a transient
regression on a live fleet.  This module closes that loop:

  - `FlameSampler` — a daemon thread samples `sys._current_frames()` of
    every other thread at ZKP2P_FLAME_HZ (default 47 Hz, prime so the
    sampler never phase-locks with periodic stage work) and folds each
    stack into collapsed-stack counts (the flamegraph.pl wire format:
    `root;child;leaf N`).
  - *Synthetic native frames* — long ctypes calls release the GIL and
    park the Python stack at the bridge frame, so a pure-Python sampler
    would show one opaque tower.  Each sample window is bracketed with
    deltas from the C runtime's always-on stats block
    (`native/lib.py stats_snapshot`: msm wall/fill/suffix/apply ns,
    `matvec_ns`, `ntt_stage_ns`, and the new `msm_inflight` gauge); a
    thread observed parked at a bridge file while native counters moved
    gets `native:<stage>` frames stitched UNDER its parked frame.
    Native self-time that accrues with no parked thread observed (pool
    workers doing the heavy part) folds under a synthetic `[native]`
    root at finalization, one count per expected sample
    (`max(1, round(ns * hz / 1e9))`) — nothing measured is dropped.
  - `CaptureController` — the sentry hook: `service._perf_check`
    triggers a capture on a stage budget overrun; the next
    ZKP2P_FLAME_CAPTURE_N service sweeps run under the sampler, then an
    atomic `flame_<circuit>_<stage>_<ts>.json` lands beside
    `.bench_cache`, rate-limited by ZKP2P_FLAME_COOLDOWN_S, counted in
    `zkp2p_flame_captures_total{trigger}`, and pointed to from the
    heartbeat perf block (federated into `zkp2p-tpu top`).  Each
    capture records the perf-ledger head entry_digest it was judged
    against, so `zkp2p-tpu perf` can walk DRIFT verdict -> capture.

Gating: ZKP2P_FLAME (`flame` knob, default OFF) is record_arm'd and
preflight-armed; a sampler-on run never shares an execution digest
with a sampler-off one.  Off means fully off — no thread, no captures,
the zero-overhead oracle arm of the overhead A/B.

Honest overhead: the sampler clocks its own per-tick work
(`sampler.self_ms` in every capture) and the A/B protocol + measured
numbers live in docs/OBSERVABILITY.md §flame profiler.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

CAPTURE_SCHEMA = 1
CAPTURE_KIND = "zkp2p_flame_capture"
CAPTURE_PREFIX = "flame_"

# Path suffixes (normalized to "/") of the Python files that host the
# ctypes bridge calls: a thread whose LEAF frame sits in one of these
# while native counters move is parked under a GIL-released native
# call, and earns synthetic native frames.  Module-level so tests can
# monkeypatch the set.
BRIDGE_SUFFIXES = (
    "native/lib.py",
    "prover/native_prove.py",
    "prover/precomp.py",
    "prover/matvec_plan.py",
)

# stats-block fields the window deltas are taken over; `stage` name ->
# the wall-ns field that measures it
_STAGE_NS_FIELDS = {
    "msm": "msm_wall_ns",
    "matvec": "matvec_ns",
    "ntt": "ntt_stage_ns",
}
# msm sub-frame attribution: child frame name -> fill/suffix/apply ns
_MSM_SUB_FIELDS = {
    "fill": "msm_fill_ns",
    "suffix": "msm_suffix_ns",
    "apply": "msm_apply_ns",
}
_MAX_DEPTH = 64  # frames kept per stack (root-most are dropped beyond it)


def flame_arm() -> str:
    """Resolve + arm the flame-sampler gate (the preflight hook):
    "off" | "<hz>hz".  The arm string carries the sampling rate so two
    runs at different rates are digest-distinguishable too."""
    from .audit import record_arm
    from .config import load_config

    cfg = load_config()
    return record_arm("flame", f"{cfg.flame_hz:g}hz" if cfg.flame else "off")


def _is_bridge_file(filename: str) -> bool:
    return filename.replace(os.sep, "/").endswith(BRIDGE_SUFFIXES)


# code object -> "file.py:func" label memo.  Keyed on the code object
# itself (not id(): ids recycle after GC and would mislabel).  Bounded
# by the number of live code objects; the held refs pin them, which is
# the same lifetime the interpreter's own caches give hot code.
_label_memo: Dict[object, str] = {}


def _fold(frame, depth: int = _MAX_DEPTH) -> List[str]:
    """One thread's stack as root-first `file.py:func` frames.  This is
    the sampler's hot loop — label formatting is memoized per code
    object so a steady-state sample is dict hits plus one list."""
    out: List[str] = []
    while frame is not None and len(out) < depth:
        code = frame.f_code
        label = _label_memo.get(code)
        if label is None:
            label = f"{os.path.basename(code.co_filename)}:{code.co_name}"
            _label_memo[code] = label
        out.append(label)
        frame = frame.f_back
    out.reverse()
    return out


class _NativeStatsReader:
    """Per-tick stats reads on the sampler's hot path.  The general
    `stats_snapshot()` rebuilds ctypes argtypes, a numpy buffer, and a
    32-field dict on every call — ~240 µs/tick measured under a
    bus-saturated prove, most of the sampler's budget.  This reader
    binds the call and buffer ONCE and extracts only the fields the
    window deltas consume; a missing/stale lib degrades to None (pure
    Python sampling), never an exception."""

    _FIELDS = tuple(
        set(_STAGE_NS_FIELDS.values())
        | set(_MSM_SUB_FIELDS.values())
        | {"msm_inflight"}
    )

    def __init__(self):
        self._fn = None
        try:
            import ctypes

            import numpy as np

            from ..native.lib import STATS_FIELDS, get_lib

            lib = get_lib()
            if lib is None or not hasattr(lib, "zkp2p_stats_count"):
                return
            n = int(lib.zkp2p_stats_count())
            self._buf = np.zeros(max(n, len(STATS_FIELDS)), dtype=np.int64)
            self._ptr = self._buf.ctypes.data_as(
                ctypes.POINTER(ctypes.c_longlong)
            )
            lib.zkp2p_stats_snapshot.argtypes = [
                ctypes.POINTER(ctypes.c_longlong)
            ]
            self._idx = {
                f: STATS_FIELDS.index(f)
                for f in self._FIELDS
                if f in STATS_FIELDS
            }
            self._fn = lib.zkp2p_stats_snapshot
        except Exception:  # noqa: BLE001 — observation must degrade
            self._fn = None

    def __call__(self) -> Optional[dict]:
        if self._fn is None:
            return None
        self._fn(self._ptr)
        buf = self._buf
        return {f: int(buf[i]) for f, i in self._idx.items()}


class FlameSampler:
    """Background sampling profiler.  start() spawns the daemon thread;
    stop() joins it and freezes the folded counts; result() returns the
    capture body (stacks + native attribution + sampler self-cost).

    `stats_source` is injectable for tests (a callable returning a
    stats_snapshot-shaped dict or None); `thread_filter` optionally
    restricts sampling to a set of thread idents."""

    def __init__(
        self,
        hz: float,
        stats_source: Optional[Callable[[], Optional[dict]]] = None,
        thread_filter: Optional[set] = None,
    ):
        self.hz = max(0.001, float(hz))
        self._stats = stats_source or _NativeStatsReader()
        self._filter = thread_filter
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._native_ns: Dict[str, int] = {s: 0 for s in _STAGE_NS_FIELDS}
        self._unattributed_ns: Dict[str, int] = {s: 0 for s in _STAGE_NS_FIELDS}
        self._prev_snap: Optional[dict] = None
        self.samples = 0
        self.windows = 0
        self._self_s = 0.0
        self._t_start: Optional[float] = None
        self.duration_s = 0.0

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "FlameSampler":
        if self._thread is not None:
            return self
        self._prev_snap = self._stats()
        self._t_start = time.perf_counter()
        self._thread = threading.Thread(
            target=self._loop, name="zkp2p-flame-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=5.0)
        self._thread = None
        if self._t_start is not None:
            self.duration_s = time.perf_counter() - self._t_start

    # -- sampling ----------------------------------------------------

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        next_t = time.perf_counter()
        while not self._stop_evt.is_set():
            t0 = time.perf_counter()
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 — the profiler must never
                pass  # take down the thread it is observing
            self._self_s += time.perf_counter() - t0
            next_t += interval
            delay = next_t - time.perf_counter()
            if delay > 0:
                self._stop_evt.wait(delay)
            else:
                # fell behind (a long fold under load): re-anchor rather
                # than bursting to catch up — bursting IS overhead
                next_t = time.perf_counter()

    def _window_deltas(self) -> Tuple[Dict[str, int], Dict[str, int], int]:
        """(stage ns deltas, msm sub-field ns deltas, inflight gauge)
        since the previous tick; zeros when the stats block is absent."""
        snap = self._stats()
        if snap is None:
            return {s: 0 for s in _STAGE_NS_FIELDS}, {k: 0 for k in _MSM_SUB_FIELDS}, 0
        prev = self._prev_snap or {}
        self._prev_snap = snap
        stage_d = {
            s: max(0, int(snap.get(f, 0)) - int(prev.get(f, 0)))
            for s, f in _STAGE_NS_FIELDS.items()
        }
        sub_d = {
            k: max(0, int(snap.get(f, 0)) - int(prev.get(f, 0)))
            for k, f in _MSM_SUB_FIELDS.items()
        }
        return stage_d, sub_d, int(snap.get("msm_inflight", 0))

    def _synthetic_frames(self, stage_d, sub_d, inflight) -> List[str]:
        """Frames to stitch under a bridge-parked leaf for this window.
        Dominant stage by ns delta; an in-flight MSM with no ns movement
        yet (the call entered but hasn't hit an exit-site flush) still
        attributes to msm."""
        stage = max(stage_d, key=lambda s: stage_d[s])
        if stage_d[stage] <= 0:
            if inflight <= 0:
                return []
            stage = "msm"
        frames = [f"native:{stage}"]
        if stage == "msm":
            sub = max(sub_d, key=lambda k: sub_d[k])
            if sub_d[sub] > 0:
                frames.append(f"native:msm.{sub}")
        return frames

    def _sample_once(self) -> None:
        stage_d, sub_d, inflight = self._window_deltas()
        self.windows += 1
        native_active = inflight > 0 or any(v > 0 for v in stage_d.values())
        me = threading.get_ident()
        parked = False
        keys: List[str] = []
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            if self._filter is not None and tid not in self._filter:
                continue
            stack = _fold(frame)
            if not stack:
                continue
            if native_active and _is_bridge_file(frame.f_code.co_filename):
                stack.extend(self._synthetic_frames(stage_d, sub_d, inflight))
                parked = True
            keys.append(";".join(stack))
        # ONE lock acquisition per tick, after the frame walk — the
        # sampler's GIL slice is what the profiled process pays
        with self._lock:
            for key in keys:
                self._counts[key] = self._counts.get(key, 0) + 1
            self.samples += len(keys)
            for stage, ns in stage_d.items():
                if ns <= 0:
                    continue
                self._native_ns[stage] += ns
                if not parked:
                    # the heavy part ran on threads we never saw parked
                    # (pool workers) — credit it to the synthetic root
                    # at finalization instead of dropping it
                    self._unattributed_ns[stage] += ns

    # -- results -----------------------------------------------------

    def stacks(self) -> Dict[str, int]:
        """Folded counts, including the `[native];native:<stage>` root
        stacks for self-time never observed under a parked thread: one
        count per sample the window SHOULD have produced at this hz
        (floor 1, so any nonzero native delta is visible)."""
        with self._lock:
            out = dict(self._counts)
            for stage, ns in self._unattributed_ns.items():
                if ns <= 0:
                    continue
                key = f"[native];native:{stage}"
                out[key] = out.get(key, 0) + max(1, round(ns * self.hz / 1e9))
        return out

    def result(self) -> Dict:
        """The capture body (everything but trigger metadata)."""
        with self._lock:
            native_ns = dict(self._native_ns)
            unattributed = dict(self._unattributed_ns)
        return {
            "hz": self.hz,
            "samples": self.samples,
            "windows": self.windows,
            "duration_s": round(self.duration_s, 4),
            "sampler": {"self_ms": round(self._self_s * 1e3, 3)},
            "native_ns": native_ns,
            "native_unattributed_ns": unattributed,
            "stacks": self.stacks(),
        }


def collapsed_text(stacks: Dict[str, int]) -> str:
    """flamegraph.pl wire format: `frame;frame;frame count` per line,
    heaviest first."""
    rows = sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))
    return "\n".join(f"{k} {v}" for k, v in rows)


# -- capture files ---------------------------------------------------


def capture_dir() -> Optional[str]:
    """Captures live beside the precomp tables / perf ledger; None when
    persistence is disabled (ZKP2P_MSM_PRECOMP_CACHE=0)."""
    from ..prover.precomp import _cache_dir

    return _cache_dir()


def _safe_token(s: str) -> str:
    return "".join(c if (c.isalnum() or c in "-.") else "-" for c in str(s)) or "x"


def write_capture(
    sampler: FlameSampler,
    circuit: str,
    stage: str,
    trigger: str,
    entry_digest: Optional[str] = None,
    budget_ms: Optional[float] = None,
    over_ms: Optional[float] = None,
    out_dir: Optional[str] = None,
) -> Optional[str]:
    """Stop `sampler` and persist its capture atomically (tmp+rename —
    a torn capture must never parse).  Returns the path, or None when
    persistence is off / the write fails.  Counts the capture in
    zkp2p_flame_captures_total{trigger} only on a successful rename."""
    from .audit import execution_digest
    from .metrics import REGISTRY

    sampler.stop()
    d = out_dir or capture_dir()
    if d is None:
        return None
    body = sampler.result()
    ts = int(time.time())  # the request clock: comparable across hosts
    body.update({
        "schema": CAPTURE_SCHEMA,
        "kind": CAPTURE_KIND,
        "circuit": str(circuit),
        "stage": str(stage),
        "trigger": str(trigger),
        "ts": ts,
        "entry_digest": entry_digest,
        "budget_ms": budget_ms,
        "over_ms": over_ms,
        "execution_digest": execution_digest(),
    })
    name = (
        f"{CAPTURE_PREFIX}{_safe_token(circuit)}_{_safe_token(stage)}_{ts}.json"
    )
    path = os.path.join(d, name)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(body, f, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None
    REGISTRY.counter("zkp2p_flame_captures_total", {"trigger": trigger}).inc()
    return path


def load_capture(path: str) -> Optional[Dict]:
    """Fail-closed capture reader: one JSON object of the expected kind
    and schema with a str->int stacks map, or None — a truncated or
    foreign file must never render as a flamegraph."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    if doc.get("kind") != CAPTURE_KIND or doc.get("schema") != CAPTURE_SCHEMA:
        return None
    stacks = doc.get("stacks")
    if not isinstance(stacks, dict) or not all(
        isinstance(k, str) and isinstance(v, int) and v >= 0
        for k, v in stacks.items()
    ):
        return None
    return doc


def captures_for(
    circuit: str,
    stage: Optional[str] = None,
    out_dir: Optional[str] = None,
) -> List[Tuple[str, Dict]]:
    """Valid on-disk captures for `circuit` (newest first), optionally
    narrowed to one stage.  Unparseable files are skipped, not raised —
    this feeds report paths."""
    d = out_dir or capture_dir()
    if d is None:
        return []
    pat = os.path.join(d, f"{CAPTURE_PREFIX}{_safe_token(circuit)}_*.json")
    out: List[Tuple[str, Dict]] = []
    for path in glob.glob(pat):
        doc = load_capture(path)
        if doc is None:
            continue
        if doc.get("circuit") != circuit:
            continue
        if stage is not None and doc.get("stage") != stage:
            continue
        out.append((path, doc))
    out.sort(key=lambda pd: (-int(pd[1].get("ts", 0)), pd[0]))
    return out


# -- the sentry hook -------------------------------------------------


class CaptureController:
    """Overrun-triggered captures: `trigger()` (called from
    service._perf_check on a budget overrun) starts the sampler unless
    gated off, mid-capture, or cooling down; `sweep_tick()` (called
    once per completed service sweep) finishes the capture after
    `flame_capture_n` sweeps and writes the file.  One instance per
    process (`controller()`), shared across service threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sampler: Optional[FlameSampler] = None
        self._meta: Optional[Dict] = None
        self._sweeps = 0
        self._need = 0
        self._last_mono: Optional[float] = None
        self._pointer: Optional[Dict] = None

    def trigger(
        self,
        circuit: str,
        stage: str,
        entry_digest: Optional[str] = None,
        budget_ms: Optional[float] = None,
        over_ms: Optional[float] = None,
    ) -> bool:
        """True when a capture actually started."""
        if flame_arm() == "off":
            return False
        from .config import load_config

        cfg = load_config()
        with self._lock:
            if self._sampler is not None:
                return False  # one capture at a time
            now = time.monotonic()
            if (
                self._last_mono is not None
                and cfg.flame_cooldown_s > 0
                and now - self._last_mono < cfg.flame_cooldown_s
            ):
                return False
            self._sampler = FlameSampler(hz=cfg.flame_hz).start()
            self._meta = {
                "circuit": str(circuit),
                "stage": str(stage),
                "entry_digest": entry_digest,
                "budget_ms": budget_ms,
                "over_ms": over_ms,
            }
            self._sweeps = 0
            self._need = max(1, int(cfg.flame_capture_n))
        return True

    def sweep_tick(self) -> Optional[str]:
        """Called at the end of every service sweep; returns the capture
        path when this tick completed one."""
        with self._lock:
            if self._sampler is None:
                return None
            self._sweeps += 1
            if self._sweeps < self._need:
                return None
            sampler, meta = self._sampler, self._meta
            self._sampler, self._meta = None, None
            self._last_mono = time.monotonic()
        path = write_capture(
            sampler,
            circuit=meta["circuit"],
            stage=meta["stage"],
            trigger="overrun",
            entry_digest=meta["entry_digest"],
            budget_ms=meta["budget_ms"],
            over_ms=meta["over_ms"],
        )
        if path is not None:
            with self._lock:
                self._pointer = {
                    "file": os.path.basename(path),
                    "stage": meta["stage"],
                    "ts": int(time.time()),
                    "samples": sampler.samples,
                }
        return path

    def active(self) -> bool:
        with self._lock:
            return self._sampler is not None

    def pointer(self) -> Optional[Dict]:
        """The most recent capture this process produced — what the
        heartbeat perf block federates to `zkp2p-tpu top`."""
        with self._lock:
            return dict(self._pointer) if self._pointer else None

    def reset(self) -> None:
        """Test hook: abandon any in-flight capture and clear state."""
        with self._lock:
            sampler = self._sampler
            self._sampler = None
            self._meta = None
            self._sweeps = 0
            self._last_mono = None
            self._pointer = None
        if sampler is not None:
            sampler.stop()


_controller = CaptureController()


def controller() -> CaptureController:
    return _controller
