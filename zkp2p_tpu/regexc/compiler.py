"""regex -> NFA -> minimized DFA compiler (the L0 tool).

Our rebuild of `regex_to_circom/` (`lexical.js:63+` parse/NFA/DFA,
`gen.py:64-163` codegen): one Python pipeline, no JS subprocess, emitting
DFA *tables* consumed by (a) the R1CS DFA gadget (gadgets/regex.py) and
(b) the vectorised JAX DFA scan (witness tracers) — instead of circom
source text.

Supported syntax (the subset the reference's catalog uses,
`lexical.js:9-40`): literals, escapes (\\r \\n \\t \\xNN and escaped
metachars), char classes [a-z0-9_] (ranges + literals), alternation `|`,
grouping `(...)`, postfix `* + ?`, and concatenation.  `.` is a literal
dot (email regexes), matching the reference's convention.  `\\x80` is the
header-start sentinel the DKIM regexes rely on
(`dkim_header_regex.circom:11-14`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

import numpy as np

ALPHABET = 256
DEAD = -1


# ------------------------------------------------------------------ parsing


class _Parser:
    """Recursive descent: alt -> cat -> postfix -> atom."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self):
        node = self.alt()
        if self.i != len(self.p):
            raise ValueError(f"unexpected '{self.peek()}' at {self.i}")
        return node

    def alt(self):
        branches = [self.cat()]
        while self.peek() == "|":
            self.take()
            branches.append(self.cat())
        return ("alt", branches) if len(branches) > 1 else branches[0]

    def cat(self):
        parts = []
        while self.peek() not in (None, "|", ")"):
            parts.append(self.postfix())
        if not parts:
            return ("eps",)
        return ("cat", parts) if len(parts) > 1 else parts[0]

    def postfix(self):
        node = self.atom()
        while self.peek() in ("*", "+", "?"):
            op = self.take()
            node = ({"*": "star", "+": "plus", "?": "opt"}[op], node)
        return node

    def atom(self):
        ch = self.take()
        if ch == "(":
            node = self.alt()
            if self.take() != ")":
                raise ValueError("unbalanced group")
            return node
        if ch == "[":
            return ("set", self._char_class())
        if ch == "\\":
            return ("set", frozenset([self._escape()]))
        if ch in "*+?)":
            raise ValueError(f"dangling '{ch}'")
        return ("set", frozenset([ord(ch)]))

    def _escape(self) -> int:
        ch = self.take()
        table = {"r": 13, "n": 10, "t": 9, "0": 0, "f": 12, "v": 11}
        if ch in table:
            return table[ch]
        if ch == "x":
            return int(self.take() + self.take(), 16)
        return ord(ch)

    def _char_class(self) -> FrozenSet[int]:
        chars: Set[int] = set()
        negate = False
        if self.peek() == "^":
            self.take()
            negate = True
        while self.peek() != "]":
            if self.peek() is None:
                raise ValueError("unterminated class")
            ch = self.take()
            lo = self._escape() if ch == "\\" else ord(ch)
            if self.peek() == "-" and self.p[self.i + 1 : self.i + 2] != "]":
                self.take()
                hi_ch = self.take()
                hi = self._escape() if hi_ch == "\\" else ord(hi_ch)
                chars.update(range(lo, hi + 1))
            else:
                chars.add(lo)
        self.take()
        if negate:
            chars = set(range(ALPHABET)) - chars
        return frozenset(chars)


# ---------------------------------------------------------------- NFA / DFA


@dataclass
class _NFA:
    # state -> list of (charset or None-for-eps, next_state)
    edges: List[List[Tuple[Optional[FrozenSet[int]], int]]] = field(default_factory=list)

    def new_state(self) -> int:
        self.edges.append([])
        return len(self.edges) - 1


def _build_nfa(node, nfa: _NFA) -> Tuple[int, int]:
    """Thompson construction; returns (start, accept)."""
    kind = node[0]
    if kind == "eps":
        s = nfa.new_state()
        return s, s
    if kind == "set":
        s, t = nfa.new_state(), nfa.new_state()
        nfa.edges[s].append((node[1], t))
        return s, t
    if kind == "cat":
        start, acc = _build_nfa(node[1][0], nfa)
        for part in node[1][1:]:
            s2, a2 = _build_nfa(part, nfa)
            nfa.edges[acc].append((None, s2))
            acc = a2
        return start, acc
    if kind == "alt":
        s, t = nfa.new_state(), nfa.new_state()
        for br in node[1]:
            bs, ba = _build_nfa(br, nfa)
            nfa.edges[s].append((None, bs))
            nfa.edges[ba].append((None, t))
        return s, t
    if kind in ("star", "opt", "plus"):
        inner_s, inner_a = _build_nfa(node[1], nfa)
        s, t = nfa.new_state(), nfa.new_state()
        nfa.edges[s].append((None, inner_s))
        nfa.edges[inner_a].append((None, t))
        if kind in ("star", "opt"):
            nfa.edges[s].append((None, t))
        if kind in ("star", "plus"):
            nfa.edges[inner_a].append((None, inner_s))
        return s, t
    raise AssertionError(kind)


@dataclass
class DFA:
    """Dense DFA: next[state, byte] (DEAD = -1 = reject sink), start = 0."""

    next: np.ndarray  # (n_states, 256) int16
    accept: FrozenSet[int]

    @property
    def n_states(self) -> int:
        return self.next.shape[0]

    def run(self, data: bytes) -> List[int]:
        """States AFTER each byte (host oracle for the scan/gadget)."""
        out = []
        s = 0
        for b in data:
            s = int(self.next[s, b]) if s != DEAD else DEAD
            out.append(s)
        return out

    def matches(self, data: bytes) -> bool:
        states = self.run(data)
        final = states[-1] if states else 0
        return final in self.accept

    def lookup_rows(self) -> List[Tuple[int, int, int]]:
        """Dense (src, dst, byte) transition triples, DEAD edges omitted —
        the lookup-argument artifact of the reference's regex compiler
        (`regex_to_circom/gen.py` OUTPUT_HALO2 path): a lookup proof
        system shows each scan step's (state, char, state') row is in
        this table instead of compiling per-transition constraints."""
        return [
            (int(s), int(self.next[s, c]), int(c))
            for s, c in np.argwhere(self.next != DEAD)
        ]

    def emit_lookup_table(self, path: str) -> None:
        """Write the lookup artifact in the reference's file format
        (`halo2_regex_lookup.txt`, gen.py:41-51): line 1 = the accept
        states, then one `src dst char` row per dense transition."""
        with open(path, "w") as f:
            f.write(" ".join(str(a) for a in sorted(self.accept)) + " \n")
            for src, dst, c in self.lookup_rows():
                f.write(f"{src} {dst} {c}\n")

    def transitions(self) -> List[Tuple[int, int, FrozenSet[int]]]:
        """(src, dst, charset) triples, DEAD edges omitted — the gadget's
        sparse view."""
        out = []
        for s in range(self.n_states):
            by_dst: Dict[int, Set[int]] = {}
            for c in range(ALPHABET):
                d = int(self.next[s, c])
                if d != DEAD:
                    by_dst.setdefault(d, set()).add(c)
            for d, chars in sorted(by_dst.items()):
                out.append((s, d, frozenset(chars)))
        return out


def _eps_closure(nfa: _NFA, states: FrozenSet[int]) -> FrozenSet[int]:
    stack = list(states)
    seen = set(states)
    while stack:
        s = stack.pop()
        for chars, t in nfa.edges[s]:
            if chars is None and t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def compile_regex(pattern: str) -> DFA:
    """regex string -> minimized dense DFA."""
    nfa = _NFA()
    start, accept = _build_nfa(_Parser(pattern).parse(), nfa)

    init = _eps_closure(nfa, frozenset([start]))
    subsets: Dict[FrozenSet[int], int] = {init: 0}
    order = [init]
    rows: List[List[int]] = []
    i = 0
    while i < len(order):
        cur = order[i]
        row = [DEAD] * ALPHABET
        # group reachable-by-char
        move: Dict[int, Set[int]] = {}
        for s in cur:
            for chars, t in nfa.edges[s]:
                if chars is None:
                    continue
                for c in chars:
                    move.setdefault(c, set()).add(t)
        closures: Dict[FrozenSet[int], FrozenSet[int]] = {}
        for c, tgts in move.items():
            key = frozenset(tgts)
            if key not in closures:
                closures[key] = _eps_closure(nfa, key)
            clo = closures[key]
            if clo not in subsets:
                subsets[clo] = len(order)
                order.append(clo)
            row[c] = subsets[clo]
        rows.append(row)
        i += 1

    accepting = frozenset(i for sub, i in subsets.items() if accept in sub)
    dfa = DFA(np.array(rows, dtype=np.int16), accepting)
    return _minimize(dfa)


def _minimize(dfa: DFA) -> DFA:
    """Moore partition refinement (dead sink handled implicitly)."""
    n = dfa.n_states
    # block id per state; start with accept / non-accept (+ implicit dead).
    block = [1 if s in dfa.accept else 0 for s in range(n)]
    while True:
        # signature: (block, tuple of next-blocks per char)
        sigs: Dict[Tuple, int] = {}
        new_block = [0] * n
        for s in range(n):
            sig = (
                block[s],
                tuple(
                    block[dfa.next[s, c]] if dfa.next[s, c] != DEAD else -1
                    for c in range(ALPHABET)
                ),
            )
            if sig not in sigs:
                sigs[sig] = len(sigs)
            new_block[s] = sigs[sig]
        if new_block == block:
            break
        block = new_block

    # Re-number so the start state's block is 0, preserving reachability order.
    remap: Dict[int, int] = {}
    new_next_rows = []
    queue = [block[0]]
    remap[block[0]] = 0
    reps: Dict[int, int] = {}
    for s in range(n):
        reps.setdefault(block[s], s)
    while queue:
        b = queue.pop(0)
        rep = reps[b]
        row = []
        for c in range(ALPHABET):
            d = int(dfa.next[rep, c])
            if d == DEAD:
                row.append(DEAD)
                continue
            db = block[d]
            if db not in remap:
                remap[db] = len(remap)
                queue.append(db)
            row.append(remap[db])
        new_next_rows.append((remap[b], row))
    new_n = len(remap)
    next_arr = np.full((new_n, ALPHABET), DEAD, dtype=np.int16)
    for idx, row in new_next_rows:
        next_arr[idx] = row
    new_accept = frozenset(remap[block[s]] for s in range(n) if s in dfa.accept and block[s] in remap)
    return DFA(next_arr, new_accept)


# ------------------------------------------------------- reference catalog

# The regex catalog the reference ships (regex_to_circom/lexical.js:9-40 and
# the generated circuits' header comments), expressed in our syntax.
# ANY_STAR prefixes a pattern for substring-search automata (the generated
# circuits get the same effect from their catch-all start loop).
ANY_STAR = "[\\0-\\xff]*"
WORD_CHAR = "[0-9A-Za-z_]"
VENMO_OFFRAMPER_ID = r"user_id=3D[0-9A-Za-z_\r\n=]+"
VENMO_AMOUNT = r"\$[0-9A-Za-z_]+\."
VENMO_ACTOR_ID = r"actor_id=3D[0-9]+"
VENMO_MM_ID = r"user_id=3D[0-9A-Za-z_\r\n=]+"
# Legacy custom-message extractor (`circuit/legacy/venmo_message_regex.circom:8`:
# `<p>(0|1|2|3|4|5|6|7|8|9)+`) — the digits following the first HTML <p> tag.
VENMO_MESSAGE = r"<p>[0-9]+"
DKIM_HEADER = r"(\x80|\r\n)(to|from):[^\r\n]+\r\n"
BODY_HASH = r"\r\ndkim-signature:([a-z]+=[^;]+; )+bh=[0-9A-Za-z+/=]+; "
TWITTER_RESET = r"This email was meant for @[0-9A-Za-z_]+"


def search_dfa(pattern: str) -> DFA:
    """Substring-search automaton: accept fires at every position where a
    match of `pattern` ends (the counting semantics the generated circuits
    rely on, e.g. `out === 2` for two to/from headers)."""
    return compile_regex(ANY_STAR + pattern)


def reveal_circuit(pattern: str, n_bytes: int, reveal_len: int, name: str = "regex_reveal"):
    """Mint a payment-extraction circuit from a bare regex — the
    reference's regex_to_circom L0 path (gen.py:64-217), but straight to
    R1CS: scan `pattern` over `n_bytes` private data bytes, reveal the
    regex-masked match bytes, one-hot shift them to a fixed
    `reveal_len` window anchored on a real revealed char (the venmo
    vid/nonzero trick: an all-zero mask cannot forge the window), and
    pack them into 7-byte public words.

    This is how the registry (models.registry) mints new payment
    circuits; the static soundness audit (snark.analysis) is their
    admission gate, so a minted circuit never reaches the prover
    unaudited.  Returns (cs, layout dict)."""
    # lazy imports: gadgets.regex imports this module (cycle-free at call time)
    from ..field.bn254 import R
    from ..gadgets import core
    from ..gadgets.regex import CharClassCache, dfa_scan, reveal_bytes
    from ..models import common
    from ..snark.r1cs import LC, ConstraintSystem

    assert 0 < reveal_len < n_bytes
    n_words = (reveal_len + 6) // 7
    cs = ConstraintSystem(name)
    word_pubs = [cs.new_public(f"reveal[{i}]") for i in range(n_words)]
    data = cs.new_wires(n_bytes, "data")
    idx = cs.new_wire("reveal_idx")
    cs.mark_input(data + [idx])
    bits = core.assert_bytes(cs, data, "data")
    cache = CharClassCache(cs)
    for w, b in zip(data, bits):
        cache.register_bits(w, b)
    dfa = search_dfa(pattern)
    states = dfa_scan(cs, list(data), dfa, cache, "rx")
    reveal = reveal_bytes(cs, data, states, sorted(dfa.accept), "rx.rev")
    onehot = core.one_hot(cs, idx, n_bytes - reveal_len, "rx.idx")
    chars = common.shift_window(cs, reveal, onehot, reveal_len, "rx.shift")
    inv = cs.new_wire("rx.first_inv")
    cs.compute(inv, lambda v: pow(v, R - 2, R) if v else 0, [chars[0]])
    cs.enforce(LC.of(chars[0]), LC.of(inv), LC.const(1), "rx/nonzero")
    words = core.pack_bytes(cs, chars, 7, "rx.pack")
    for w, pub in zip(words, word_pubs):
        cs.enforce_eq(LC.of(w), LC.of(pub), "rx/out")
    return cs, {"data": data, "idx": idx, "publics": word_pubs, "dfa": dfa}
