"""Sharded NTT over a device mesh: four-step (Bailey) factorisation with
an ICI all-to-all transpose at the stage boundary.

The reference runs its H-polynomial FFTs inside rapidsnark on one
machine's threads (SURVEY.md §2.7); at the production domain (2^23 for
the 6.6M-constraint Venmo circuit, README.md:79) a single chip's HBM
cannot hold the six full-domain transform intermediates, so the domain
is factored m = r·c and sharded:

Index bookkeeping (j = c·j1 + j2, k = k1 + r·k2, w_r = w^c, w_c = w^r):

  X[k1 + r·k2] = Σ_{j2} w^(j2·k1) · w_c^(j2·k2) · [Σ_{j1} w_r^(j1·k1) x[c·j1 + j2]]

so the pipeline per shard is
    1. all-to-all transpose (r,c) -> (c,r): rows become the j1 axis
    2. local length-r NTT along j1                  -> A[j2, k1]
    3. cross twiddle w^(j2·k1)                      (elementwise)
    4. all-to-all transpose (c,r) -> (r,c)          -> B[k1, j2]
    5. local length-c NTT along j2                  -> X_mat[k1, k2]
    6. all-to-all transpose (r,c) -> (c,r)          -> X_t[k2, k1]
  row-major flatten of X_t is exactly natural order (k = r·k2 + k1), so
  callers hand in the natural-order sharded vector and get the
  natural-order sharded transform back — three ICI all-to-alls total.
  (The transposed-FFT trick — DIF forward + DIT inverse with fused
  orderings — can drop two of them; kept simple until profiling says so.)

Differentially tested against ops.ntt (single device) in
tests/test_parallel.py.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..field.bn254 import fr_domain_root, fr_inv
from ..field.jfield import FR, NUM_LIMBS
from ..ops.ntt import _ntt_core, domain


@lru_cache(maxsize=None)
def _factor(log_m: int):
    """m = r * c with r = 2^(log_m//2) (rows), c the co-factor."""
    log_r = log_m // 2
    return 1 << log_r, 1 << (log_m - log_r), log_r, log_m - log_r


@lru_cache(maxsize=None)
def _cross_twiddles(log_m: int, inverse: bool) -> jnp.ndarray:
    """(c, r) matrix W[j2, k1] = w^(±j2*k1) in Montgomery form."""
    r, c, _, _ = _factor(log_m)
    m = r * c
    w = fr_domain_root(log_m)
    if inverse:
        w = fr_inv(w)
    d = domain(log_m)
    tw = d["tw"] if not inverse else d["tw_inv"]  # (m/2,) powers of w
    # full power table: extend to m entries (tw holds m/2; w^(m/2) = -1)
    idx = (np.outer(np.arange(c, dtype=np.int64), np.arange(r, dtype=np.int64))) % m
    lo = idx % (m // 2)
    flip = idx >= (m // 2)
    with jax.ensure_compile_time_eval():
        base = jnp.asarray(tw)[lo]  # (c, r, 16)
        return jnp.where(jnp.asarray(flip)[..., None], FR.neg(base), base)


def _local_ntt(x: jnp.ndarray, log_n: int) -> jnp.ndarray:
    """Batched NTT along axis -2 of (..., n, 16)."""
    d = domain(log_n)
    return _ntt_core(x, d["tw"], d["perm"])


def _local_intt_unscaled(x: jnp.ndarray, log_n: int) -> jnp.ndarray:
    d = domain(log_n)
    return _ntt_core(x, d["tw_inv"], d["perm"])


def _transpose_all_to_all(x: jnp.ndarray, axis: str, rows: int, cols: int, n_dev: int) -> jnp.ndarray:
    """Local block (rows/d, cols, 16) of a row-sharded (rows, cols) matrix
    -> local block (cols/d, rows, 16) of the col-sharded transpose."""
    lr = rows // n_dev
    lc = cols // n_dev
    # split columns into d groups -> (lr, d, lc, 16); all_to_all swaps the
    # device axis with the named mesh axis.
    blocks = x.reshape(lr, n_dev, lc, NUM_LIMBS)
    swapped = jax.lax.all_to_all(blocks, axis, split_axis=1, concat_axis=0, tiled=False)
    # swapped: (d, lr, lc, 16) where dim 0 indexes the source device (row
    # block) — transpose local dims to (lc, d, lr) = (lc, rows) layout.
    return swapped.transpose(2, 0, 1, 3).reshape(lc, rows, NUM_LIMBS)


@lru_cache(maxsize=None)
def _ntt_sharded_fn(log_m: int, mesh: Mesh, axis: str, inverse: bool):
    """Cached jitted shard_map executable per (domain, mesh, direction).

    Without this every `ntt_sharded` call built a fresh shard_map closure,
    so the six transforms of one H-evaluation compiled six separate
    executables (~7 min of XLA on a 1-core host, and 6x the work on TPU
    too).  Cached, a prove compiles exactly two NTT executables (forward +
    inverse) shared by the a/b/c ladders and all later proves."""
    r, c, log_r, log_c = _factor(log_m)
    n_dev = mesh.shape[axis]
    assert c % n_dev == 0 and r % n_dev == 0, "mesh must divide both factors"
    d = domain(log_m)

    def local(xs: jnp.ndarray, cross_blk: jnp.ndarray) -> jnp.ndarray:
        # xs: (m/d, 16) natural order = (r, c) row-major x[j1, j2], the j1
        # row axis sharded.  The inner transforms run over j1 (stride c),
        # so transpose first.
        blk = xs.reshape(r // n_dev, c, NUM_LIMBS)
        blk = _transpose_all_to_all(blk, axis, r, c, n_dev)  # (c/d, r): y[j2, j1]
        if inverse:
            blk = _local_intt_unscaled(blk, log_r)  # A[j2, k1]
        else:
            blk = _local_ntt(blk, log_r)
        blk = FR.mul(blk, cross_blk)  # cross_blk = W[j2, k1] slice (c/d, r)
        blk = _transpose_all_to_all(blk, axis, c, r, n_dev)  # (r/d, c): B[k1, j2]
        if inverse:
            blk = _local_intt_unscaled(blk, log_c)  # X_mat[k1, k2]
        else:
            blk = _local_ntt(blk, log_c)
        blk = _transpose_all_to_all(blk, axis, r, c, n_dev)  # (c/d, r): X_t[k2, k1]
        out = blk.reshape(r * c // n_dev, NUM_LIMBS)  # k = r*k2 + k1: natural
        if inverse:
            out = FR.mul(out, d["m_inv_mont"])
        return out

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None, None)),
            out_specs=P(axis, None),
            check_rep=False,
        )
    )


def ntt_sharded(
    x: jnp.ndarray,
    log_m: int,
    mesh: Mesh,
    axis: str = "shard",
    inverse: bool = False,
) -> jnp.ndarray:
    """NTT/iNTT of a natural-order (m, 16) Montgomery vector, sharded on
    its leading axis over `mesh`'s `axis`.  Returns the natural-order
    result with the same sharding.  Exactly equal to ops.ntt / ops.intt.
    """
    return _ntt_sharded_fn(log_m, mesh, axis, inverse)(x, _cross_twiddles(log_m, inverse))
