"""Sequence/context parallelism for per-byte recurrences (the CP axis).

The framework's "long context" is the email byte axis: every hot witness
recurrence — DFA regex scans, SHA-256 block chaining — is a left fold
over bytes (SURVEY.md §5 long-context).  The reference scales this by
moving a hashed prefix OUT of the circuit (`Sha256Partial` +
`generate_input.ts:110-124`); the TPU-native generalisation is a
blockwise scan over a sharded byte axis — the same shape as ring
attention / Ulysses for transformers, specialised to monoid folds:

  1. each device folds ITS byte block into a composed transition
     function (DFA: a state->state map; SHA: a midstate),
  2. one collective exchanges the per-device functions and every device
     composes the prefix of the devices before it (the "handoff" —
     exactly the Sha256Partial midstate trick, generalised), and
  3. each device re-scans its block from its entry state, emitting the
     per-byte states.

DFA transition functions compose by GATHER (f∘g = g[f]), so the whole
pipeline is int32 vector ops — no matmuls, no field arithmetic.
Differentially tested against the host DFA simulation in
tests/test_seqscan.py.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def dfa_tables(dfa) -> np.ndarray:
    """(256, S+1) int32: next-state per (byte, state); the extra state S
    is the absorbing dead state (-1 entries map to it)."""
    S = dfa.n_states
    t = np.full((256, S + 1), S, dtype=np.int32)
    nxt = np.asarray(dfa.next)  # (S, 256)
    t[:, :S] = np.where(nxt.T >= 0, nxt.T, S)
    return t


@lru_cache(maxsize=None)
def _dfa_scan_fn(mesh: Mesh, axis: str, S: int, block: int):
    """Cached jitted shard_map executable per (mesh, dfa size, block)."""
    n_dev = mesh.shape[axis]
    dead = S  # absorbing

    def local(bytes_blk: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
        # bytes_blk: (block,) uint8 — this device's slice; table: (256, S+1)

        # 1. fold the block into one composed transition fn (S+1,)
        def compose(f, b):
            return table[b][f], None

        ident = jnp.arange(S + 1, dtype=jnp.int32)
        f_blk, _ = jax.lax.scan(compose, ident, bytes_blk)

        # 2. handoff: gather every device's function, compose the strict
        # prefix of this device (the midstate-handoff collective)
        fns = jax.lax.all_gather(f_blk, axis)  # (n_dev, S+1)
        idx = jax.lax.axis_index(axis)

        def prefix_step(carry, i):
            f = fns[i]
            nxt = jnp.where(i < idx, f[carry], carry)
            return nxt, None

        entry, _ = jax.lax.scan(prefix_step, jnp.int32(0), jnp.arange(n_dev))

        # 3. re-scan the block from the entry state, emitting states
        def step(s, b):
            ns = table[b][s]
            return ns, ns

        _, states = jax.lax.scan(step, entry, bytes_blk)
        return states  # (block,) state AFTER each byte

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(None, None)),
            out_specs=P(axis),
            check_rep=False,
        )
    )


def dfa_scan_sharded(data: jnp.ndarray, dfa, mesh: Mesh, axis: str = "shard") -> jnp.ndarray:
    """Run `dfa` over a byte vector sharded on `mesh`'s `axis`.

    data: (n,) uint8, n divisible by the mesh size.  Returns (n,) int32 —
    the DFA state after each byte (dead state = dfa.n_states), sharded
    like the input.  Exactly equals the sequential host simulation."""
    n_dev = mesh.shape[axis]
    n = data.shape[0]
    assert n % n_dev == 0, "pad the byte axis to the mesh size first"
    table = jnp.asarray(dfa_tables(dfa))
    fn = _dfa_scan_fn(mesh, axis, dfa.n_states, n // n_dev)
    return fn(jnp.asarray(data), table)


def dfa_scan_host(data, dfa) -> np.ndarray:
    """Sequential oracle (same dead-state convention)."""
    S = dfa.n_states
    t = dfa_tables(dfa)
    s = 0
    out = np.empty(len(data), dtype=np.int32)
    for i, b in enumerate(bytes(data)):
        s = int(t[b][s]) if s != S else S
        out[i] = s
    return out
