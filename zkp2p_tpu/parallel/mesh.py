"""Device-mesh parallelism: sharded MSM + batched proving over ICI.

The reference's only parallelism is artifact chunking + rapidsnark's
shared-memory threads (SURVEY.md §2.7); the TPU build gets real
distributed axes:

  - "batch": data parallelism over independent proofs (vmap + sharding),
    the batched-onramp configuration of BASELINE.json.
  - "shard": model parallelism over the MSM base-point axis — each device
    accumulates bucket/plane partial sums for its slice of the zkey, and
    ONE group-operation all-reduce (all_gather + local Jacobian fold)
    combines them over ICI.  This is the Pippenger partial-sum allreduce
    of SURVEY.md §2.7 expressed with XLA collectives instead of NCCL.

Everything is `shard_map` over a `jax.sharding.Mesh`, so the same program
runs on 1 chip, a v5e-8 slice, or (with a "dcn" outer axis) multi-host —
the driver's `dryrun_multichip` exercises it on virtual CPU devices.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..curve.jcurve import AffPoint, JacPoint, JCurve
from ..ops.msm import msm, msm_windowed


def make_mesh(n_devices: Optional[int] = None, axis: str = "shard") -> Mesh:
    devs = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.array(devs), (axis,))


def make_pod_mesh(n_dcn: int, n_ici: Optional[int] = None, names=("dcn", "shard")) -> Mesh:
    """Multi-slice mesh for pod-scale configs (the v5e-256 shape of
    BASELINE.json): the outer `dcn` axis spans slices (data-center
    network — carry only the proof-batch data parallelism there, one
    all-gather of proof points per batch), the inner axis rides ICI and
    carries the MSM/NTT sharding (msm_sharded / ntt_sharded take
    axis=names[1] unchanged).  On a single host this builds the same
    layout over virtual devices, which is how the driver's dryrun and the
    tests exercise it."""
    devs = jax.devices()
    if n_ici is None:
        n_ici = len(devs) // n_dcn
    if n_ici < 1 or n_dcn * n_ici > len(devs):
        raise ValueError(f"need {n_dcn}x{n_ici or '?'} devices, have {len(devs)}")
    return Mesh(np.array(devs[: n_dcn * n_ici]).reshape(n_dcn, n_ici), names)


def _fold_gathered(curve: JCurve, gathered: JacPoint, n: int) -> JacPoint:
    """Fold the per-device partial points (leading axis n) with a scan —
    the 'reduce' half of the group-op all-reduce."""

    def body(acc, p):
        return curve.add(acc, p), None

    acc, _ = jax.lax.scan(body, curve.infinity(()), gathered)
    return acc


@lru_cache(maxsize=None)
def _msm_sharded_fn(curve: JCurve, n_bases: int, mesh: Mesh, axis: str, lanes: int, window: int):
    """Cached jitted shard_map executable per (curve, mesh, msm config).

    Same reuse story as parallel.ntt._ntt_sharded_fn: one executable per
    curve/config, shared by the a/b1/c MSMs of every prove (jit re-keys on
    operand shapes, so differing base counts still share the callable)."""

    def local(bs, pl):
        if window:
            part = msm_windowed(curve, bs, pl, lanes=lanes, window=window)
        else:
            part = msm(curve, bs, pl, lanes=lanes)
        gathered = jax.lax.all_gather(part, axis)  # (n_dev,) points on ICI
        return _fold_gathered(curve, gathered, mesh.shape[axis])

    in_specs = (
        tuple(P(axis) for _ in range(n_bases)),
        P(None, axis),
    )
    out_specs = tuple(P() for _ in range(3))
    return jax.jit(shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False))


def msm_sharded(
    curve: JCurve,
    bases: AffPoint,
    planes: jnp.ndarray,
    mesh: Mesh,
    axis: str = "shard",
    lanes: int = 64,
    window: int = 0,
) -> JacPoint:
    """MSM with the base-point axis sharded over `mesh`'s `axis`.

    bases components must have N divisible by the mesh size (pad with the
    (0,0) infinity sentinel + zero planes first).  `planes` is bit planes
    (window=0, 256 rows) or 2^window digit planes (the prover's fast path,
    rows = 256/window).  Returns the full sum, replicated on every device."""
    n_dev = mesh.shape[axis]
    n = bases[0].shape[0]
    assert n % n_dev == 0, "pad the base axis to the mesh size first"
    return _msm_sharded_fn(curve, len(bases), mesh, axis, lanes, window)(bases, planes)


@lru_cache(maxsize=None)
def _msm_pod_fn(curve: JCurve, n_bases: int, mesh: Mesh, dcn_axis: str, ici_axis: str, lanes: int, window: int):
    def local(bs, pl):
        # pl: (B_local, n_planes, n_local) — this slice's share of the
        # proof batch over its shard of the base axis
        def one(p):
            if window:
                return msm_windowed(curve, bs, p, lanes=lanes, window=window)
            return msm(curve, bs, p, lanes=lanes)

        part = jax.vmap(one)(pl)
        # ICI allreduce within the slice: combine base-axis partials
        gathered = jax.lax.all_gather(part, ici_axis, axis=1)
        acc = _fold_gathered_batched(curve, gathered, mesh.shape[ici_axis])
        # DCN all-gather across slices: assemble the full proof batch
        # (one point per proof — the only cross-slice traffic, matching
        # the make_pod_mesh contract of data-parallel-only over dcn)
        return tuple(jax.lax.all_gather(c, dcn_axis, axis=0, tiled=True) for c in acc)

    in_specs = (
        tuple(P(ici_axis) for _ in range(n_bases)),
        P(dcn_axis, None, ici_axis),
    )
    out_specs = tuple(P() for _ in range(3))
    return jax.jit(shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False))


def _fold_gathered_batched(curve: JCurve, gathered: JacPoint, n: int) -> JacPoint:
    """Fold per-device partials with a batch axis: gathered components
    are (B_local, n_dev, ...); scan over the device axis."""

    def body(acc, p):
        return curve.add(acc, p), None

    moved = tuple(jnp.moveaxis(c, 1, 0) for c in gathered)
    acc, _ = jax.lax.scan(body, curve.infinity((moved[0].shape[1],)), moved)
    return acc


def msm_pod_batched(
    curve: JCurve,
    bases: AffPoint,
    planes_batch: jnp.ndarray,
    mesh: Mesh,
    dcn_axis: str = "dcn",
    ici_axis: str = "shard",
    lanes: int = 64,
    window: int = 4,
) -> JacPoint:
    """Batched MSM over a pod mesh (`make_pod_mesh`): the proof batch is
    data-parallel over the `dcn` axis (each slice proves its share of
    the batch) while each slice shards the base-point axis over its ICI
    `shard` axis — the v5e-256 configuration of BASELINE.json, with the
    only DCN traffic being one proof point per batch element.

    planes_batch: (B, n_planes, N) digit planes, B divisible by the dcn
    width, N by the ici width.  Returns (B,)-batched Jacobian points,
    replicated everywhere."""
    B = planes_batch.shape[0]
    assert B % mesh.shape[dcn_axis] == 0, "batch must divide the dcn axis"
    assert bases[0].shape[0] % mesh.shape[ici_axis] == 0, "pad the base axis first"
    return _msm_pod_fn(curve, len(bases), mesh, dcn_axis, ici_axis, lanes, window)(bases, planes_batch)


def pad_to_multiple(bases: AffPoint, bit_planes, multiple: int) -> Tuple[AffPoint, jnp.ndarray]:
    """Pad the MSM base axis (and the matching LAST plane axis) up to a
    multiple of the mesh width: (0, 0) infinity bases and zero digit
    columns contribute nothing.  Planes may be (n_planes, N) single-proof
    or (B, n_planes, N) batched (msm_pod_batched), and signed planes
    arrive as a (mags, negs) tuple — the pad is rank-generic on the last
    axis either way."""
    n = bases[0].shape[0]
    pad = (-n) % multiple
    if pad:
        bases = tuple(jnp.pad(c, [(0, pad)] + [(0, 0)] * (c.ndim - 1)) for c in bases)

        def pad_last(p):
            return jnp.pad(p, [(0, 0)] * (p.ndim - 1) + [(0, pad)])

        if isinstance(bit_planes, tuple):
            bit_planes = tuple(pad_last(p) for p in bit_planes)
        else:
            bit_planes = pad_last(bit_planes)
    return bases, bit_planes
