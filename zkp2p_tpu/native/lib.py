"""ctypes bridge to the native BN254 library (csrc/zkp2p_native.cpp).

The C++ runtime layer of the framework (the role rapidsnark's native
field library plays in the reference, SURVEY.md §2.2) — loaded lazily,
built on demand with make, and everything degrades to the pure-Python
path when a toolchain is unavailable, so imports never hard-fail.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "libzkp2p_native.so")

_lib = None
_tried = False


def _int_to_u64x4(x: int) -> np.ndarray:
    return np.array([(x >> (64 * i)) & ((1 << 64) - 1) for i in range(4)], dtype=np.uint64)


def _u64x4_to_int(a) -> int:
    return int(a[0]) | int(a[1]) << 64 | int(a[2]) << 128 | int(a[3]) << 192


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    # Always (re)build from the committed source: a stale or prebuilt .so
    # must never be loaded in preference to the reviewed C++ (the binary is
    # gitignored; `make` is a no-op when the .so is already newer than the
    # source, so this costs one stat on the warm path).
    try:
        subprocess.run(["make", "-C", _CSRC], check=True, capture_output=True)
    except Exception:
        if not os.path.exists(_SO):
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.g1_fixed_base_batch.argtypes = [u64p, u64p, ctypes.c_int, u64p]
    lib.g1_fixed_base_batch_mont.argtypes = [u64p, u64p, ctypes.c_int, u64p]
    lib.g2_fixed_base_batch_mont.argtypes = [u64p, u64p, ctypes.c_int, u64p]
    lib.fp_mul_std.argtypes = [u64p, u64p, u64p]
    # Self-check before trusting it: one field mul against Python ints AND
    # one fixed-base scalar mul against the host curve oracle, so a library
    # with subtly wrong curve ops (used for trusted-setup point generation)
    # is rejected, not just one with a broken multiplier.
    from ..field.bn254 import P

    a, b = 0x1234567890ABCDEF << 120 | 0x42, P - 12345
    av, bv, cv = _int_to_u64x4(a), _int_to_u64x4(b), np.zeros(4, dtype=np.uint64)
    lib.fp_mul_std(
        av.ctypes.data_as(u64p), bv.ctypes.data_as(u64p), cv.ctypes.data_as(u64p)
    )
    if _u64x4_to_int(cv) != a * b % P:
        return None
    _lib = lib
    from ..curve.host import G1_GEN, g1_mul

    k = 0xDEADBEEFCAFEF00D1234567890ABCDEF
    got = g1_fixed_base_batch(G1_GEN, [k])
    if got is None or got[0] != g1_mul(G1_GEN, k):
        _lib = None
        return None
    return _lib


# Slot order of the C runtime's always-on stats block (csrc StatSlot) —
# the ctypes ABI: index i here reads g_stats[i] there.  Append-only on
# both sides; zkp2p_stats_count() guards against drift at runtime.
STATS_FIELDS = (
    "msm_g1_calls",
    "msm_g2_calls",
    "msm_glv_calls",
    "msm_batch_affine_calls",
    "msm_points",
    "msm_wall_ns",
    "msm_fill_ns",
    "msm_apply_ns",
    "msm_suffix_ns",
    "msm_bailfill_ns",
    "msm_window_last",
    "msm_dbl_lanes",
    "msm_cancel_lanes",
    "msm_defer_hits",
    "pool_jobs",
    "pool_tasks",
    "pool_wait_ns",
    "pool_run_ns",
    "pool_depth_peak",
    "pool_workers",
    "msm_multi_calls",
    "msm_multi_cols",
    "msm_multi_cols_last",
    "msm_multi_prep_ns",
    "msm_fixed_calls",
    "msm_fixed_prep_ns",
    "precomp_build_ns",
    "precomp_table_bytes",
    "matvec_ns",
    "matvec_seg_calls",
    "ntt_stage_ns",
    "msm_inflight",
)


def stats_snapshot() -> Optional[dict]:
    """Read the native runtime's lock-free counter block as a dict
    (field -> int); None if the native lib is unavailable.  Purely
    observational — counters keep accumulating."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "zkp2p_stats_count"):
        # a stale pre-stats .so (toolchain gone, rebuild failed) still
        # passes get_lib's self-checks — observation must degrade to
        # None, never AttributeError a finished prove
        return None
    n = int(lib.zkp2p_stats_count())
    buf = np.zeros(max(n, len(STATS_FIELDS)), dtype=np.int64)
    lib.zkp2p_stats_snapshot.argtypes = [ctypes.POINTER(ctypes.c_longlong)]
    lib.zkp2p_stats_snapshot(buf.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)))
    # a lib ahead of this bridge exposes extra slots we cannot name; a
    # lib behind it reads 0 for the missing names (buf is zero-filled
    # past n) — either way every STATS_FIELDS key is present, so
    # consumers never KeyError on version skew
    return {name: int(buf[i]) for i, name in enumerate(STATS_FIELDS)}


def ifma_available() -> bool:
    """True when the loaded native lib's AVX512-IFMA 52-bit tier is
    usable (hardware present AND not disabled via ZKP2P_NATIVE_IFMA —
    `zkp2p_ifma_available` applies the C runtime's own gate, so this
    mirrors exactly the arm the drivers will take).  False when the lib
    is unavailable."""
    lib = get_lib()
    try:
        return bool(lib is not None and lib.zkp2p_ifma_available())
    except Exception:  # noqa: BLE001 — a stale pre-IFMA .so must not crash callers
        return False


def cache_sizes() -> Optional[dict]:
    """Detected data-cache capacities in bytes via the C runtime's
    sysconf probe: {"l1d": int, "l2": int, "l3": int}, 0 = that level is
    unknown to the kernel/libc.  None when the native lib is unavailable
    or predates the probe (stale .so — degrade, never AttributeError;
    the host-profile layer falls back to sysfs, then to documented
    constants)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "zkp2p_cache_size"):
        return None
    lib.zkp2p_cache_size.argtypes = [ctypes.c_int]
    lib.zkp2p_cache_size.restype = ctypes.c_long
    return {
        "l1d": int(lib.zkp2p_cache_size(1)),
        "l2": int(lib.zkp2p_cache_size(2)),
        "l3": int(lib.zkp2p_cache_size(3)),
    }


def native_cpu_count() -> Optional[int]:
    """Online logical CPU count as the C runtime's WorkPool sees it;
    None when the lib is unavailable/stale, 0 when the libc cannot say."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "zkp2p_cpu_count"):
        return None
    lib.zkp2p_cpu_count.restype = ctypes.c_long
    return int(lib.zkp2p_cpu_count())


def stats_reset() -> bool:
    """Zero the native counter block; False if the lib is unavailable
    (or predates the stats block — see stats_snapshot)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "zkp2p_stats_reset"):
        return False
    lib.zkp2p_stats_reset()
    return True


def g1_fixed_base_batch(base: Tuple[int, int], scalars: Sequence[int]) -> Optional[List]:
    """Batch k_i * base over G1; None if the native lib is unavailable.
    Returns affine (x, y) int tuples, None entries for infinity."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(scalars)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    base_arr = np.concatenate([_int_to_u64x4(base[0]), _int_to_u64x4(base[1])])
    sc = np.zeros((n, 4), dtype=np.uint64)
    for i, s in enumerate(scalars):
        sc[i] = _int_to_u64x4(int(s))
    out = np.zeros((n, 8), dtype=np.uint64)
    lib.g1_fixed_base_batch(
        base_arr.ctypes.data_as(u64p),
        sc.ctypes.data_as(u64p),
        n,
        out.ctypes.data_as(u64p),
    )
    res = []
    for i in range(n):
        x = _u64x4_to_int(out[i, :4])
        y = _u64x4_to_int(out[i, 4:])
        res.append(None if x == 0 and y == 0 else (x, y))
    return res


def _pack_affine(points: Sequence) -> np.ndarray:
    """Affine (x, y) int tuples (None = infinity -> all-zero hole) to the
    (n, 8) u64 layout every g1 native entry point consumes — ONE shared
    encoder so the infinity convention cannot drift between callers."""
    n = len(points)
    bases = np.zeros((n, 8), dtype=np.uint64)
    for i, p in enumerate(points):
        if p is None:
            continue
        bases[i, :4] = _int_to_u64x4(p[0])
        bases[i, 4:] = _int_to_u64x4(p[1])
    return bases


def g1_scale_batch(points: Sequence, scalar: int) -> Optional[List]:
    """out[i] = scalar * points[i] over G1 (shared scalar — the ceremony
    delta-rescale); None if the native lib is unavailable.  Points are
    affine (x, y) int tuples with None = infinity, same out."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(points)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.g1_scale_batch.argtypes = [u64p, ctypes.c_long, u64p, u64p]
    bases = _pack_affine(points)
    sc = _int_to_u64x4(int(scalar))
    out = np.zeros((n, 8), dtype=np.uint64)
    lib.g1_scale_batch(bases.ctypes.data_as(u64p), n, sc.ctypes.data_as(u64p), out.ctypes.data_as(u64p))
    res = []
    for i in range(n):
        x = _u64x4_to_int(out[i, :4])
        y = _u64x4_to_int(out[i, 4:])
        res.append(None if x == 0 and y == 0 else (x, y))
    return res


def g1_msm(points: Sequence, scalars: Sequence[int]) -> Optional[object]:
    """Native variable-base MSM, std-form affine tuples in/out ("sentinel
    False" when the lib is unavailable so callers can distinguish the
    infinity result None from no-lib)."""
    lib = get_lib()
    if lib is None or not points:
        return False if lib is None else None
    n = len(points)
    if len(scalars) != n:
        raise ValueError(f"g1_msm: {n} points but {len(scalars)} scalars")
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.fp_to_mont.argtypes = [u64p, u64p, ctypes.c_int]
    lib.g1_msm_pippenger.argtypes = [u64p, u64p, ctypes.c_long, ctypes.c_int, u64p]
    bases = _pack_affine(points)
    bm = np.zeros_like(bases)
    lib.fp_to_mont(bases.ctypes.data_as(u64p), bm.ctypes.data_as(u64p), 2 * n)
    sc = _scalars_to_u64([int(s) for s in scalars])
    out = np.zeros(8, dtype=np.uint64)
    # the ONE window policy (IFMA-aware clamp included) lives in
    # native_prove; late import avoids the module cycle
    from ..prover.native_prove import _pick_window

    c = _pick_window(n)
    lib.g1_msm_pippenger(bm.ctypes.data_as(u64p), sc.ctypes.data_as(u64p), n, c, out.ctypes.data_as(u64p))
    x, y = _u64x4_to_int(out[:4]), _u64x4_to_int(out[4:])
    return None if x == 0 and y == 0 else (x, y)


def g1_msm_multi(points: Sequence, scalar_cols: Sequence[Sequence[int]]) -> Optional[object]:
    """Multi-column native MSM: ONE sweep over the shared base array, S
    scalar columns, S results (csrc g1_msm_pippenger_multi).  Columns
    shorter than the base set are zero-padded (a zero scalar contributes
    nothing, so the result matches the truncated sequential MSM).
    Returns a list of affine (x, y) tuples — None entries for infinity
    columns — or the "sentinel False" when the lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return False
    S = len(scalar_cols)
    if S == 0:
        return []
    if not points:
        return [None] * S  # every column of an empty MSM is infinity
    n = len(points)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.fp_to_mont.argtypes = [u64p, u64p, ctypes.c_int]
    lib.g1_msm_pippenger_multi.argtypes = [
        u64p, u64p, ctypes.c_long, ctypes.c_int, ctypes.c_int, ctypes.c_int, u64p,
    ]
    bases = _pack_affine(points)
    bm = np.zeros_like(bases)
    lib.fp_to_mont(bases.ctypes.data_as(u64p), bm.ctypes.data_as(u64p), 2 * n)
    sc = np.zeros((S, n, 4), dtype=np.uint64)
    for s, col in enumerate(scalar_cols):
        if len(col) > n:
            raise ValueError(f"g1_msm_multi: column {s} has {len(col)} scalars for {n} points")
        if col:
            sc[s, : len(col)] = _scalars_to_u64([int(k) for k in col])
    sc = np.ascontiguousarray(sc)
    out = np.zeros((S, 8), dtype=np.uint64)
    from ..prover.native_prove import _pick_window

    c = _pick_window(n)
    lib.g1_msm_pippenger_multi(
        bm.ctypes.data_as(u64p), sc.ctypes.data_as(u64p), n, S, c, 1,
        out.ctypes.data_as(u64p),
    )
    res = []
    for s in range(S):
        x, y = _u64x4_to_int(out[s, :4]), _u64x4_to_int(out[s, 4:])
        res.append(None if x == 0 and y == 0 else (x, y))
    return res


def _scalars_to_u64(scalars: Sequence[int]) -> np.ndarray:
    """(n, 4) u64 little-endian — via one bytes join, not a Python limb
    loop (to_bytes is C-speed; this path handles millions of scalars)."""
    buf = b"".join(int(s).to_bytes(32, "little") for s in scalars)
    return np.frombuffer(buf, dtype="<u8").reshape(len(scalars), 4)


def _u64_to_limbs16(a: np.ndarray) -> np.ndarray:
    """(..., 4) u64 -> (..., 16) u32 of 16-bit limbs (the jfield layout)."""
    return np.ascontiguousarray(a).view("<u2").astype(np.uint32).reshape(*a.shape[:-1], 16)


def g1_fixed_base_batch_mont_limbs(base: Tuple[int, int], scalars: Sequence[int]):
    """Batch k_i * base over G1, emitted directly as Montgomery (n, 16)
    u32 limb arrays (the DeviceProvingKey base layout) — skips every
    per-point Python conversion.  None if the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(scalars)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    base_arr = np.concatenate([_int_to_u64x4(base[0]), _int_to_u64x4(base[1])])
    sc = np.ascontiguousarray(_scalars_to_u64(scalars))
    out = np.zeros((n, 8), dtype=np.uint64)
    lib.g1_fixed_base_batch_mont(
        base_arr.ctypes.data_as(u64p), sc.ctypes.data_as(u64p), n, out.ctypes.data_as(u64p)
    )
    limbs = _u64_to_limbs16(out.reshape(n, 2, 4))  # (n, 2, 16)
    return limbs[:, 0], limbs[:, 1]


def g2_fixed_base_batch_mont_limbs(base, scalars: Sequence[int]):
    """Batch k_i * base over G2 -> Montgomery (n, 2, 16) u32 limb arrays
    (x, y as Fq2 pairs).  `base` is a host G2Point ((Fq2, Fq2) affine).
    None if the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(scalars)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    x, y = base
    base_arr = np.concatenate(
        [_int_to_u64x4(x.c0), _int_to_u64x4(x.c1), _int_to_u64x4(y.c0), _int_to_u64x4(y.c1)]
    )
    sc = np.ascontiguousarray(_scalars_to_u64(scalars))
    out = np.zeros((n, 16), dtype=np.uint64)
    lib.g2_fixed_base_batch_mont(
        base_arr.ctypes.data_as(u64p), sc.ctypes.data_as(u64p), n, out.ctypes.data_as(u64p)
    )
    limbs = _u64_to_limbs16(out.reshape(n, 4, 4))  # (n, 4, 16): x0 x1 y0 y1
    return limbs[:, 0:2], limbs[:, 2:4]
