from .groth16_tpu import DeviceProvingKey, device_pk, prove_tpu, prove_tpu_batch

__all__ = ["DeviceProvingKey", "device_pk", "prove_tpu", "prove_tpu_batch"]
