"""Fixed-base precomputed-window tables for the frozen proving key.

The proving key's G1 base arrays (a/b1/c/h) are immutable for the life
of a service, yet every prove re-ran the GLV split, the mont256 ->
mont260 base conversion, and a full Pippenger bucket fill over them.
This module trades that per-prove work for offline tables (the standard
fixed-key-server move — rapidsnark-style servers; SZKP / if-ZKP in
PAPERS.md schedule their accelerators around exactly this):

  level j of a family's table holds  L_j[i] = 2^(j*q*c) * P_i

built ONCE per (key, window c, stride q, depth levels) by the native
`g1_precomp_build`, persisted under `.bench_cache/` keyed by the family
key hash + geometry, and converted once per process to the persistent
52-limb form the IFMA fill consumes.  The per-prove MSM is then pure
digit scatter + table gather + batch-affine bucket adds
(csrc g1_msm_pippenger_fixed / _fixed_multi) — no GLV split, no base
conversion, no multiple recomputation in the hot loop.

Geometry: a 254-bit scalar recodes into W = ceil-over-255-bits signed
base-2^c digits; `levels = ceil(W / q)` table copies buy a hot loop of
only q windows.  Depth is the RAM dial — each level costs n * 64 B on
disk and n * 144 B resident on the IFMA tier (mont256 + 52-limb;
n * 64 B on scalar-tier hosts, which keep no 52-limb form) — bounded
by the
`ZKP2P_MSM_PRECOMP_MAX_MB` budget guard: a family that cannot fit even
one level falls through to the existing variable-base path.  All four
G1 families are eligible by default, h included: the measured h arm
(full-width ladder scalars) still beats the GLV variable-base driver
~1.25x at the bench shape, and the witness families (0/1-heavy venmo
wires) measure ~1.6x (docs/TUNING.md has the sweep).

Cache invalidation is BY CONSTRUCTION: the family key hash (sha256 of
the converted base bytes) and the (c, q, levels) geometry are part of
the filename, so a retuned window, a different depth, or a different
key resolves to a different file and triggers a fresh build.  At load,
level 0 (a verbatim copy of the bases) is compared directly and the
higher levels are spot-checked by walking the doubling chain for
sampled points on the host curve — a corrupt, foreign, or bit-rotted
file rebuilds instead of proving garbage.
"""

from __future__ import annotations

import contextlib
import ctypes
import hashlib
import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

_u64p = ctypes.POINTER(ctypes.c_uint64)

# The G1 MSM families of a DeviceProvingKey eligible for tables (b2 is
# G2 — no fixed G2 tier).  Order fixed: the budget guard admits families
# in this order, so under memory pressure the witness-heavy a query and
# the dominant h query are the last to degrade.
G1_FAMILIES = ("h", "a", "b1", "c")


def fixed_nwin(c: int) -> int:
    """Windows the fixed tier recodes into at width c: ceil(254/c)
    bumped until W*c >= 255 (the signed top-window carry bit) — the
    exact mirror of csrc fixed_nwin, asserted by the parity tests."""
    W = (254 + c - 1) // c
    while W * c < 255:
        W += 1
    return W


def _pick_window_fixed(n: int, threads: int = 1) -> int:
    """Window for the PRECOMPUTED tier.  Doublings are free (they live
    in the tables) and only q windows of suffix remain, so the curve
    sits wider than the variable-base pickers; the ceiling is the
    per-window bucket block (2^(c-1) x 80 B) falling out of cache —
    c=18 already measured BELOW the GLV baseline at 2^19.  Interleaved
    min-of-5 sweep on the driver box (threads=2, distinct points):
      full-width scalars: c16/q2 1.17-1.27x vs GLV, c15/q2 1.22x,
                          c14/q2 1.04x, c17+ <= 1.08x
      narrow 90/10 mix:   c16/q2 1.62-1.64x, c15/q2 1.61x
    c=16 wins or ties both shapes at the bench scale; below sweep
    coverage the variable-base heuristic (+2 for the free doublings)
    applies."""
    del threads  # q, not c, is the parallel-axis dial for this tier
    bl = n.bit_length()
    if bl >= 15:
        return 16
    return max(5, min(16, bl - 3))


def _resolve_geometry(
    n: int, depth: int, budget_bytes: int
) -> Optional[Tuple[int, int, int]]:
    """(c, q, levels) for a family of n points under the RAM budget
    from the HAND-PICKED constants only — the documented fallback arm
    (c=16 at sweep scale, q from depth, i.e. c16/q2/L8 at the default
    depth 8) and the pinned oracle the parity tests compare against.
    Never profile-driven; the prove path resolves through
    `_resolve_geometry_prof` instead."""
    g = _resolve_geometry_prof(n, depth, budget_bytes, family="", use_profile=False)
    return None if g is None else g[:3]


def _resolve_geometry_prof(
    n: int, depth: int, budget_bytes: int, family: str, use_profile: bool = True
) -> Optional[Tuple[int, int, int, str]]:
    """(c, q, levels, source) for a family of n points under the RAM
    budget, or None when even a one-level table does not fit.  The
    window c (and optionally the hot-loop stride q) come from the tuned
    host profile when one is loaded for THIS hardware (source
    "profile"); otherwise the hand-picked constants apply (source
    "fallback").  Depth caps levels; q = ceil(W / levels) keeps
    levels * q >= W (the csrc cover bound), and a profile q may only
    widen the hot loop (shallower table), never deepen past the depth
    cap.  Resident cost per row: mont256 64 B, plus the Aff52 80 B only
    where the IFMA tier will actually keep a 52-limb form — charging
    144 B on a scalar-tier host would shallow or skip families at
    2.25x their real footprint."""
    from ..native.lib import ifma_available

    row_bytes = 144 if ifma_available() else 64
    source = "fallback"
    c = _pick_window_fixed(n)
    tuned_q: Optional[int] = None
    if use_profile:
        from ..utils.hostprof import geometry_for

        tuned = geometry_for(family, n)
        if tuned is not None:
            source = "profile"
            c = int(tuned["c"])
            tuned_q = tuned.get("q")
    W = fixed_nwin(c)
    levels = max(1, min(depth, W))
    q = (W + levels - 1) // levels
    if tuned_q is not None:
        q = max(q, int(tuned_q))
    levels = (W + q - 1) // q
    while levels > 1 and (levels * n) * row_bytes > budget_bytes:
        q += 1
        levels = (W + q - 1) // q
    if (levels * n) * row_bytes > budget_bytes:
        return None
    return c, q, levels, source


@dataclass
class FamilyTable:
    """One family's resident tables + geometry (a row of the manifest)."""

    family: str
    table: np.ndarray  # (levels*n, 8) u64, affine Montgomery
    table52: Optional[np.ndarray]  # (levels*n, 10) u64 Aff52, or None
    n: int
    levels: int
    c: int
    q: int
    source: str  # "built" | "cache"
    key_hash: str
    geometry_source: str = "fallback"  # "profile" | "fallback"

    @property
    def nbytes(self) -> int:
        return int(self.table.nbytes) + (
            int(self.table52.nbytes) if self.table52 is not None else 0
        )

    def p52(self):
        """ctypes pointer to the 52-limb table (NULL on scalar tier —
        the C driver then reads mont256 and converts nothing)."""
        return self.table52.ctypes.data_as(_u64p) if self.table52 is not None else None


@dataclass
class PrecomputedKey:
    """All fixed-base tables attached to one DeviceProvingKey."""

    families: Dict[str, FamilyTable]
    skipped: Dict[str, str]  # family -> reason ("budget", ...)

    def table_bytes(self) -> int:
        return sum(f.nbytes for f in self.families.values())

    def manifest(self) -> Dict:
        """JSON-able summary for the run manifest / flight recorder."""
        return {
            "families": {
                name: {
                    "n": f.n,
                    "levels": f.levels,
                    "c": f.c,
                    "q": f.q,
                    "bytes": f.nbytes,
                    "ifma52": f.table52 is not None,
                    "source": f.source,
                    "geometry_source": f.geometry_source,
                    "key_hash": f.key_hash,
                }
                for name, f in self.families.items()
            },
            "skipped": dict(self.skipped),
            "total_bytes": self.table_bytes(),
        }


# One PrecomputedKey per DeviceProvingKey identity, lock-guarded like
# native_prove._bases_memo (the overlap task-graph resolves tables from
# worker threads); entries pin the dpk so an id() cannot be reused while
# its entry is alive.  Small cap bounds test-suite churn.
_pk_cache: Dict[int, Tuple[object, PrecomputedKey]] = {}
_PK_CACHE_CAP = 4
_pk_lock = threading.Lock()
# serializes table RESOLUTION (build or disk load): two service threads
# hitting the same cold key must not each run a multi-minute build —
# the second waits and takes the first's memo entry.  Builds are
# once-per-key rare, so one global lock (not per-key) is enough.
_build_lock = threading.Lock()

# live manifest of the newest resolution — the run-manifest hook
# (utils.metrics.run_manifest) reads this without touching the cache
_last_manifest: Optional[Dict] = None


def precomp_manifest() -> Optional[Dict]:
    """Manifest of the most recently resolved PrecomputedKey (None
    until a precomp-armed prove ran) — stamped into run manifests so
    table memory is attributable in every trace/bench artifact."""
    return _last_manifest


def reset() -> None:
    """Drop memoized tables + manifest (tests)."""
    global _last_manifest
    with _pk_lock:
        _pk_cache.clear()
    _last_manifest = None


def _cache_dir() -> Optional[str]:
    """Table persistence root: ZKP2P_MSM_PRECOMP_CACHE, else the repo's
    .bench_cache; "0" disables persistence (build-only, in-RAM)."""
    from ..utils.config import load_config

    v = load_config().precomp_cache
    if v == "0":
        return None
    if v:
        return v
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, ".bench_cache")


def _family_bases_u64(dpk, family: str) -> np.ndarray:
    from .native_prove import _g1_bases_u64

    return _g1_bases_u64(getattr(dpk, f"{family}_bases"))


def _key_hash(bases_u64: np.ndarray) -> str:
    """sha256 over the FULL converted base bytes (16 hex chars).  Full,
    not sampled: the hash is the cache-invalidation key, and a stale
    table for a one-point-different key would prove garbage caught only
    at verify.  ~0.2 s at the 2^19 bench shape, once per process."""
    h = hashlib.sha256()
    h.update(np.asarray(bases_u64.shape, dtype=np.int64).tobytes())
    h.update(np.ascontiguousarray(bases_u64).tobytes())
    return h.hexdigest()[:16]


def _cache_path(cache_dir: str, family: str, key_hash: str, c: int, q: int, levels: int) -> str:
    return os.path.join(
        cache_dir, f"precomp_g1_{family}_{key_hash}_c{c}q{q}L{levels}.npy"
    )


def _mont_row_to_point(row: np.ndarray):
    """One affine-Montgomery table row (8 u64: x limbs, y limbs) ->
    integer (x, y), or None for the (0, 0) infinity hole."""
    from ..field.bn254 import from_mont

    x_m = int.from_bytes(np.ascontiguousarray(row[:4]).tobytes(), "little")
    y_m = int.from_bytes(np.ascontiguousarray(row[4:]).tobytes(), "little")
    if x_m == 0 and y_m == 0:
        return None
    return (from_mont(x_m), from_mont(y_m))


def _load_table(path: str, bases: np.ndarray, c: int, q: int, levels: int) -> Optional[np.ndarray]:
    """Load + integrity-check a persisted table; None on any mismatch
    (shape drift, foreign file, torn write, flipped bit) — the caller
    rebuilds.  Level 0 is a verbatim copy of the bases and is compared
    in FULL (the fill reads every level-0 row, so a sample is not
    enough), and the HIGHER levels are spot-checked by walking the
    doubling chain L_j = 2^(q*c) * L_{j-1} for a few sampled points on
    the host curve ((levels-1)*q*c Python doublings per sample, tens of
    ms per family) and comparing every level's row — a bit flipped
    anywhere in a sampled column rebuilds instead of proving garbage.
    Pure host math: no native calls, so a warm start keeps the
    `precomp_build_ns == 0` accounting contract."""
    from ..curve.host import g1_double

    n = bases.shape[0]
    try:
        table = np.load(path)
    except Exception:  # noqa: BLE001 — a corrupt cache must rebuild, not raise
        return None
    if table.shape != (levels * n, 8) or table.dtype != np.uint64:
        return None
    # full level-0 compare, not a sample: the fill reads EVERY level-0
    # row, and the bases are already resident — ~10 ms at 2^19 rows
    if not np.array_equal(table[:n], bases):
        return None
    for i in {0, n // 2, n - 1}:
        pt = _mont_row_to_point(bases[i])
        for lv in range(1, levels):
            if pt is not None:
                for _ in range(q * c):
                    pt = g1_double(pt)
            if _mont_row_to_point(table[lv * n + i]) != pt:
                return None
    return np.ascontiguousarray(table)


@contextlib.contextmanager
def _build_flock(path: str):
    """CROSS-PROCESS build serialization (the JsonlSink sidecar
    pattern, utils/metrics.py): an exclusive flock on `<path>.lock`
    around check-build-persist, so N fleet workers cold-starting on one
    key run ONE multi-minute build — the losers block here, then find
    the winner's atomic-renamed artifact on the re-check and load it.
    The in-process `_build_lock` already serializes threads; this
    sidecar is the process-level tier above it.  No flock (exotic fs) =
    no cross-process exclusion, same as before this existed — the
    builds race but each still produces a correct table (atomic
    rename; last writer wins)."""
    lock_fd = -1
    try:
        import fcntl

        # the sidecar may be the FIRST file in a fresh cache dir (the
        # artifact write creates the dir otherwise) — without this, two
        # cold processes both fail the open and race the first build
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        lock_fd = os.open(path + ".lock", os.O_CREAT | os.O_WRONLY, 0o644)
        fcntl.flock(lock_fd, fcntl.LOCK_EX)
    except Exception:  # noqa: BLE001 — degrade to in-process locking only
        if lock_fd >= 0:
            os.close(lock_fd)
            lock_fd = -1
    try:
        yield
    finally:
        if lock_fd >= 0:
            os.close(lock_fd)  # releases the flock


def _persist_table(path: str, table: np.ndarray) -> None:
    """Atomic write (tmp + rename): service workers may race a cold
    start; a half-written file must never be loadable."""
    tmp = f"{path}.tmp.{os.getpid()}.npy"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "wb") as f:
            np.save(f, table)
        os.replace(tmp, path)
    except OSError:
        # persistence is an optimization; the in-RAM table is already
        # correct and the next cold start simply rebuilds
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass


def _build_family(
    lib, dpk, family: str, geom, cache_dir, threads: int, geometry_source: str = "fallback"
) -> FamilyTable:
    from ..utils.trace import trace

    c, q, levels = geom
    bases = _family_bases_u64(dpk, family)
    n = int(bases.shape[0])
    kh = _key_hash(bases)
    persist = cache_dir is not None and n >= _persist_min()
    path = _cache_path(cache_dir, family, kh, c, q, levels) if persist else None

    table = None
    source = "cache"
    if path is not None and os.path.exists(path):
        with trace("native/precomp_load", family=family):
            table = _load_table(path, bases, c, q, levels)
    if table is None and path is not None:
        # cold + persistable: serialize the build ACROSS PROCESSES on
        # the flock sidecar — N fleet workers sharing one key run ONE
        # multi-minute build; losers block on the lock, then load the
        # winner's atomic-renamed artifact on the re-check below
        with _build_flock(path):
            if os.path.exists(path):
                with trace("native/precomp_load", family=family):
                    table = _load_table(path, bases, c, q, levels)
            if table is None:
                source = "built"
                with trace("native/precomp_build", family=family):
                    table = np.zeros((levels * n, 8), dtype=np.uint64)
                    lib.g1_precomp_build(
                        bases.ctypes.data_as(_u64p), n, c, q, levels, threads,
                        table.ctypes.data_as(_u64p),
                    )
                _persist_table(path, table)
    elif table is None:
        # RAM-only family (below persist_min, or persistence off)
        source = "built"
        with trace("native/precomp_build", family=family):
            table = np.zeros((levels * n, 8), dtype=np.uint64)
            lib.g1_precomp_build(
                bases.ctypes.data_as(_u64p), n, c, q, levels, threads,
                table.ctypes.data_as(_u64p),
            )

    # the persistent 52-limb form (per process, never persisted: it is
    # one cheap conversion pass — 0.4 s at 8 x 2^19 rows — and keying
    # the disk cache by IFMA arm would double the files for no build
    # savings).  Scalar tier: the C driver reads mont256 directly.
    table52: Optional[np.ndarray] = np.zeros((levels * n, 10), dtype=np.uint64)
    if not lib.g1_precomp_to52(
        table.ctypes.data_as(_u64p), levels * n, table52.ctypes.data_as(_u64p)
    ):
        table52 = None
    return FamilyTable(
        family=family, table=table, table52=table52, n=n, levels=levels,
        c=c, q=q, source=source, key_hash=kh, geometry_source=geometry_source,
    )


def _persist_min() -> int:
    from ..utils.config import load_config

    return load_config().precomp_persist_min


def precomputed_for(dpk) -> Optional[PrecomputedKey]:
    """The PrecomputedKey for this DeviceProvingKey — memoized per key
    identity; built (or cache-loaded) on first use.  None when the
    native library is unavailable.  Callers gate on ZKP2P_MSM_PRECOMP
    (native_prove._use_msm_precomp) BEFORE calling: resolution is not
    free the first time."""
    from .native_prove import _lib

    lib = _lib()
    if lib is None:
        return None
    key = id(dpk)
    with _pk_lock:
        hit = _pk_cache.get(key)
        if hit is not None and hit[0] is dpk:
            return hit[1]

    with _build_lock:
        # re-check under the build lock: a concurrent caller may have
        # finished the build while this thread waited
        with _pk_lock:
            hit = _pk_cache.get(key)
            if hit is not None and hit[0] is dpk:
                return hit[1]
        return _resolve(lib, dpk, key)


def _resolve(lib, dpk, key: int) -> PrecomputedKey:
    global _last_manifest
    from ..utils.config import load_config
    from ..utils.metrics import REGISTRY
    from .native_prove import _n_threads

    cfg = load_config()
    budget = int(cfg.precomp_max_mb) << 20
    cache_dir = _cache_dir()
    threads = _n_threads()
    families: Dict[str, FamilyTable] = {}
    skipped: Dict[str, str] = {}
    for family in G1_FAMILIES:
        if family not in [f.strip() for f in cfg.precomp_families.split(",") if f.strip()]:
            skipped[family] = "config"
            continue
        bases = _family_bases_u64(dpk, family)
        n = int(bases.shape[0])
        if n == 0:
            skipped[family] = "empty"
            continue
        geom = _resolve_geometry_prof(n, int(cfg.precomp_depth), budget, family)
        if geom is None:
            skipped[family] = "budget"
            continue
        ft = _build_family(
            lib, dpk, family, geom[:3], cache_dir, threads, geometry_source=geom[3]
        )
        families[family] = ft
        budget -= ft.nbytes

    pk = PrecomputedKey(families=families, skipped=skipped)
    with _pk_lock:
        if len(_pk_cache) >= _PK_CACHE_CAP:
            _pk_cache.pop(next(iter(_pk_cache)))
        _pk_cache[key] = (dpk, pk)
        live = [entry[1] for entry in _pk_cache.values()]
    # memory accounting: the gauges cover ALL resident tables (the memo
    # holds up to _PK_CACHE_CAP keys), summed per family across live
    # entries and zeroed where no live key tables that family — a
    # second key resolving must not understate what the first still
    # pins, nor leave an evicted key's bytes on the board
    for name in G1_FAMILIES:
        nbytes = sum(p.families[name].nbytes for p in live if name in p.families)
        REGISTRY.gauge("zkp2p_precomp_table_bytes", {"family": name}).set(nbytes)
    REGISTRY.gauge("zkp2p_precomp_total_bytes").set(sum(p.table_bytes() for p in live))
    _last_manifest = pk.manifest()
    return pk
