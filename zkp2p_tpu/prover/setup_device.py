"""Array-path trusted setup: ConstraintSystem -> DeviceProvingKey directly.

`snark.groth16.setup` materialises every query point as a Python tuple —
fine at gadget-test scale, hopeless at the flagship circuit's 6.4M wires
(the reference pays 782 s on a 48-core EC2 box for the same step,
`zkp-mooc-hackathon-submission.md:98`).  This path keeps everything in
numpy limb arrays end to end:

  tau-evaluation loops   : Python ints over sparse rows (linear, cheap)
  fixed-base G1/G2 muls  : csrc batch kernels, Montgomery-limb output,
                           batch-inverted normalization (native.lib)
  QAP coeff arrays       : vectorized bytes->u16 limb decode

The emitted DeviceProvingKey is bit-identical to
`device_pk(setup(cs, seed))` for the same seed — pinned by
tests/test_setup_device.py — and the matching VerifyingKey is a host
object usable by `snark.groth16.verify` and the Solidity export.

The same key feeds every device arm unchanged: the single-device loop,
`prove_tpu_sharded`, and the pjit batch-axis arm (ZKP2P_TPU_SHARD=on,
docs/TPU.md).  The pruned b/c query lanes emitted here are NOT padded
to any mesh width — the sharded MSMs pad bases and digit planes with
infinity lanes per-mesh at trace time (parallel.mesh.pad_to_multiple),
so one key serves every mesh shape.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from ..curve.host import G1_GENERATOR, G2_GENERATOR, g1_gen_mul, g2_gen_mul
from ..field.bn254 import R, fr_domain_root, fr_inv
from ..native.lib import g1_fixed_base_batch_mont_limbs, g2_fixed_base_batch_mont_limbs
from ..snark.groth16 import VerifyingKey, _batch_inv, _seeded_scalars, coset_gen, domain_size_for, qap_rows
from ..snark.r1cs import ConstraintSystem
from .groth16_tpu import DeviceProvingKey, _rows_to_arrays


def setup_device(cs: ConstraintSystem, seed: str = "zkp2p-tpu-dev") -> Tuple[DeviceProvingKey, VerifyingKey]:
    """Development setup straight to device arrays (same key material as
    `setup(cs, seed)`).  Requires the native library (use `setup` +
    `device_pk` for small circuits without a toolchain)."""
    tau, alpha, beta, gamma, delta = _seeded_scalars(seed, 5)
    rows = qap_rows(cs)
    m = domain_size_for(cs)
    n_wires = cs.num_wires

    w = fr_domain_root(m.bit_length() - 1)
    z_tau = (pow(tau, m, R) - 1) % R
    minv = fr_inv(m)
    wjs: List[int] = []
    wj = 1
    for _ in range(m):
        wjs.append(wj)
        wj = wj * w % R
    denom_inv = _batch_inv([(tau - wj) % R for wj in wjs])
    lag = [z_tau * wj % R * minv % R * di % R for wj, di in zip(wjs, denom_inv)]

    a_tau = [0] * n_wires
    b_tau = [0] * n_wires
    c_tau = [0] * n_wires
    for j, (ra, rb, rc) in enumerate(rows):
        lj = lag[j]
        for wi, coeff in ra.items():
            a_tau[wi] = (a_tau[wi] + coeff * lj) % R
        for wi, coeff in rb.items():
            b_tau[wi] = (b_tau[wi] + coeff * lj) % R
        for wi, coeff in rc.items():
            c_tau[wi] = (c_tau[wi] + coeff * lj) % R

    delta_inv = fr_inv(delta)
    gamma_inv = fr_inv(gamma)
    vals = [(beta * a_tau[i] + alpha * b_tau[i] + c_tau[i]) % R for i in range(n_wires)]
    scaled = [
        v * (gamma_inv if i <= cs.num_public else delta_inv) % R for i, v in enumerate(vals)
    ]

    g = coset_gen(m.bit_length() - 1)
    tau_p = tau * fr_inv(g) % R
    z_tau_p = (pow(tau_p, m, R) - 1) % R
    z_coset = (pow(g, m, R) - 1) % R
    scale = z_tau_p * minv % R * z_tau % R * fr_inv(delta * z_coset % R) % R
    hden_inv = _batch_inv([(tau_p - wj) % R for wj in wjs])
    h_scalars = [scale * wj % R * di % R for wj, di in zip(wjs, hden_inv)]

    # Prune the b/c queries to their non-infinity lanes (device_pk does
    # the same from the point lists): b_tau is zero for every wire absent
    # from B (half the circuit, measured), so both the setup-time
    # fixed-base muls AND the prove-time b1/b2/c MSMs halve.
    from .groth16_tpu import _prune_sel

    b_flags = [v % R != 0 for v in b_tau]
    c_flags = [i > cs.num_public and scaled[i] % R != 0 for i in range(n_wires)]
    b_sel = _prune_sel(b_flags)
    c_sel = _prune_sel(c_flags)
    # Degenerate fallback lanes ([0] when nothing survives pruning) must
    # be INFINITY bases: index 0 is wire one, whose gamma-scaled C point
    # is NOT infinity — mapping the scalar to 0 here keeps the MSM a
    # no-op for any witness.  (b_tau[0] is already 0 whenever the b
    # fallback triggers, but map it too for uniformity.)
    b_scalars = [b_tau[i] if b_flags[i] else 0 for i in b_sel]
    c_scalars = [scaled[i] if c_flags[i] else 0 for i in c_sel]
    a_bases = g1_fixed_base_batch_mont_limbs(G1_GENERATOR, a_tau)
    b1_bases = g1_fixed_base_batch_mont_limbs(G1_GENERATOR, b_scalars)
    b2_bases = g2_fixed_base_batch_mont_limbs(G2_GENERATOR, b_scalars)
    cq_bases = g1_fixed_base_batch_mont_limbs(G1_GENERATOR, c_scalars)
    h_bases = g1_fixed_base_batch_mont_limbs(G1_GENERATOR, h_scalars)
    if a_bases is None or b2_bases is None:
        raise RuntimeError("native library unavailable; use snark.groth16.setup for small circuits")

    # IC points (host form, few) for the verifier.
    from ..curve.host import g1_gen_mul_batch

    ic = g1_gen_mul_batch(scaled[: cs.num_public + 1])

    a_arr = _rows_to_arrays([t[0] for t in rows], m)
    b_arr = _rows_to_arrays([t[1] for t in rows], m)

    # Width-classed MSM split — THE shared rule from groth16_tpu
    # (class_sels), so this dev-setup path and the pk-import path can
    # never drift.  The degenerate [0] fallback lanes are infinity
    # bases, harmless in either class.
    from .groth16_tpu import class_sels, widths_array

    widths = widths_array(cs)
    a_nsel, a_wsel = class_sels(widths, np.arange(n_wires, dtype=np.int32))
    b_nsel, b_wsel = class_sels(widths, np.asarray(b_sel))
    c_nsel, c_wsel = class_sels(widths, np.asarray(c_sel))
    dpk = DeviceProvingKey(
        n_public=cs.num_public,
        n_wires=n_wires,
        log_m=m.bit_length() - 1,
        a_coeff=a_arr[0], a_wire=a_arr[1], a_row=a_arr[2],
        b_coeff=b_arr[0], b_wire=b_arr[1], b_row=b_arr[2],
        a_bases=tuple(jnp.asarray(x) for x in a_bases),
        b1_bases=tuple(jnp.asarray(x) for x in b1_bases),
        b2_bases=tuple(jnp.asarray(x) for x in b2_bases),
        c_bases=tuple(jnp.asarray(x) for x in cq_bases),
        h_bases=tuple(jnp.asarray(x) for x in h_bases),
        b_sel=jnp.asarray(b_sel),
        c_sel=jnp.asarray(c_sel),
        a_nsel=jnp.asarray(a_nsel), a_wsel=jnp.asarray(a_wsel),
        b_nsel=jnp.asarray(b_nsel), b_wsel=jnp.asarray(b_wsel),
        c_nsel=jnp.asarray(c_nsel), c_wsel=jnp.asarray(c_wsel),
        alpha_1=g1_gen_mul(alpha),
        beta_1=g1_gen_mul(beta),
        beta_2=g2_gen_mul(beta),
        delta_1=g1_gen_mul(delta),
        delta_2=g2_gen_mul(delta),
    )
    vk = VerifyingKey(
        n_public=cs.num_public,
        alpha_1=dpk.alpha_1,
        beta_2=dpk.beta_2,
        gamma_2=g2_gen_mul(gamma),
        delta_2=dpk.delta_2,
        ic=ic,
    )
    return dpk, vk
