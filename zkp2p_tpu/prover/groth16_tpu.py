"""The TPU Groth16 prover: witness limbs in, proof points out.

This is the `prover=tpu` backend the build exists for (BASELINE.json
north star) — the drop-in for snarkjs `groth16 prove` /
rapidsnark (`dizkus-scripts/5_gen_proof.sh`, `6_gen_proof_rapidsnark.sh`):
same zkey material + witness in, same proof out, verified by the same
pairing equation (`contracts/Verifier.sol:340-380`).

Dataflow (one jitted program, SURVEY.md §7 step 6):

  witness w (mont limbs, n_wires x 16)
    ├─ Az/Bz/Cz: gather coeffs -> Montgomery mul -> modular segment-sum
    │  over rows (the sparse matvec; zero scatter)
    ├─ H: iNTT -> coset shift -> NTT -> (a·b - c)·Z⁻¹ -> iNTT -> unshift
    └─ 4 G1 MSMs + 1 G2 MSM over bit planes (ops.msm)
  host: the ~10 scalar ops that blind with (r, s) and assemble (A, B, C)

Determinism contract: given the same (witness, r, s) this emits the exact
proof `snark.groth16.prove_host` does — the two provers are diffed
point-by-point in tests, the same way the reference pins a known-good
proof vector in `test/ramp.test.js:193-196`.

Batching: `prove_tpu_batch` vmaps the whole pipeline over independent
witnesses sharing one key — the reference has no analog (browser proves
one email at a time); this is the TPU data-parallel axis.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..curve.host import G1Point, G2Point, g1_add, g1_mul, g1_neg, g2_add, g2_mul
from ..curve.jcurve import (
    AffPoint,
    G1J,
    G2J,
    g1_jac_to_host,
    g1_to_affine_arrays,
    g2_jac_to_host,
    g2_to_affine_arrays,
)
from ..field.bn254 import R
from ..field.jfield import FR, lazy_segment_sum_mod
from ..ops.msm import (
    default_lanes,
    digit_planes_from_limbs,
    glv_extend_bases,
    glv_sel,
    glv_signed_planes_from_limbs,
    msm_windowed,
    msm_windowed_signed,
    signed_digit_planes_from_limbs,
)
from ..ops.ntt import coset_shift, intt, ntt

# All tier knobs resolve through the ONE typed config (utils.config:
# default -> armed_flags -> env, with provenance); the module constants
# below are its import-time snapshot — jit identities depend on them,
# so they are process-lifetime like the config itself.
#
# MSM_WINDOW: 4-bit digits -> ~78 point-adds per base instead of the 256
#   of the bit-plane formulation (VERDICT r1 #3); w=8 halves accumulate
#   work at the price of a 254-add per-chunk table, worth it vmapped.
# MSM_SIGNED: signed digit recoding (default on) — the per-chunk
#   multiples table halves because a negative digit is (x, -y) for free.
# MSM_UNIFIED ("auto" = on for a real TPU backend): pad the a/b1/c/h
#   MSM inputs to one common base count so all four share ONE compiled
#   executable (each cold TPU MSM compile measured ~2 min).
# MSM_AFFINE: batch-affine accumulate tier (ops.msm_affine) — hardware-
#   gated until the on-chip A/B proves it.
# MSM_H: "windowed" or "bucket" (ops.msm_bucket sorted-prefix
#   Pippenger) — hardware-gated like MSM_AFFINE.
from ..utils.jaxcfg import on_tpu as _on_tpu
from ..utils.audit import record_arm as _record_arm
from ..utils.config import load_config as _load_config

_CFG = _load_config()
MSM_WINDOW = _CFG.msm_window
MSM_SIGNED = _CFG.msm_signed
MSM_UNIFIED = _CFG.msm_unified
MSM_AFFINE = _CFG.msm_affine
MSM_H = _CFG.msm_h
MSM_GLV = _CFG.msm_glv
BATCH_CHUNK = _CFG.batch_chunk
H_BUCKET_WINDOW = 16

from ..snark.groth16 import Proof, ProvingKey, coset_gen, domain_size_for, qap_rows
from ..snark.r1cs import ConstraintSystem
def _unified() -> bool:
    return _record_arm("msm_unified", MSM_UNIFIED == "1" or (MSM_UNIFIED == "auto" and _on_tpu()))


def _affine() -> bool:
    return _record_arm("msm_affine", MSM_AFFINE == "1" or (MSM_AFFINE == "auto" and _on_tpu()))


def _h_bucket() -> bool:
    v = MSM_SIGNED and (MSM_H == "bucket" or (MSM_H == "auto" and _on_tpu()))
    _record_arm("msm_h", "bucket" if v else "windowed")
    return v


def _glv() -> bool:
    """GLV endomorphism decomposition for the G1 MSMs (ZKP2P_MSM_GLV).
    Rides the signed-digit machinery, so MSM_SIGNED off disables it —
    the unsigned path stays the byte-stable fallback."""
    return _record_arm("msm_glv", MSM_GLV and MSM_SIGNED)


def _parse_mesh_spec(spec: str, n_devices: int) -> Optional[Tuple[int, int]]:
    """ZKP2P_TPU_MESH -> (batch_width, shard_width).  "BxS" gives B
    data-parallel batch groups of S base-axis shards; a bare int N is
    "1xN"; "" auto-sizes to 1x<all devices>.  Malformed or non-positive
    specs return None (the caller fails CLOSED to the vmap arm — the
    same malformed-knob rule as _batch_chunk_size)."""
    spec = (spec or "").strip().lower()
    if not spec:
        return (1, n_devices)
    try:
        if "x" in spec:
            b_s = spec.split("x", 1)
            b, s = int(b_s[0]), int(b_s[1])
        else:
            b, s = 1, int(spec)
    except ValueError:
        return None
    if b < 1 or s < 1:
        return None
    return (b, s)


# pod meshes memoised by shape: Mesh construction is cheap but the
# shard_map executable caches (parallel.mesh._msm_pod_fn) key on the
# Mesh instance — one instance per shape keeps them warm across proves.
_POD_MESH_CACHE: Dict[Tuple[int, int], object] = {}


def _shard_mesh():
    """The sharded-arm gate + mesh resolver (fresh config read per call,
    like the scheduler's sched_arm): ZKP2P_TPU_SHARD must be literally
    "on" — anything else fails CLOSED to the single-device vmap path —
    and ZKP2P_TPU_MESH shapes the ("batch", "shard") pod mesh.  Records
    the `tpu_shard` gate with the RESOLVED shape ("off" | "2x4"), so a
    sharded prove is digest-distinguishable from the vmap arm and an
    unsatisfiable mesh is an on-record disarm, never a silent one."""
    cfg = _load_config()
    if cfg.tpu_shard != "on":
        _record_arm("tpu_shard", "off")
        return None
    n_dev = len(jax.devices())
    shape = _parse_mesh_spec(cfg.tpu_mesh, n_dev)
    if shape is None or shape[0] * shape[1] > n_dev:
        _record_arm("tpu_shard", "off")
        return None
    b, s = shape
    mesh = _POD_MESH_CACHE.get((b, s))
    if mesh is None:
        from ..parallel.mesh import make_pod_mesh

        mesh = make_pod_mesh(b, s, names=("batch", "shard"))
        _POD_MESH_CACHE[(b, s)] = mesh
    _record_arm("tpu_shard", f"{b}x{s}")
    return mesh


@dataclass
class DeviceProvingKey:
    """Proving key resident as device arrays (the zkey, TPU-shaped)."""

    n_public: int
    n_wires: int
    log_m: int
    # Sparse QAP rows for A and B (including public binding rows):
    # canonical Montgomery coefficients, wire gather indices, row segment
    # ids.  No C matrix — C evaluations on the domain are A∘B pointwise
    # for a satisfying witness (binding rows have B = 0), the same reason
    # the snarkjs .zkey coefficient section stores only A and B.
    a_coeff: jnp.ndarray
    a_wire: jnp.ndarray
    a_row: jnp.ndarray
    b_coeff: jnp.ndarray
    b_wire: jnp.ndarray
    b_row: jnp.ndarray
    # MSM bases (affine Montgomery limbs; (0,0) = infinity hole).  The b
    # and c queries are PRUNED: only ~50% of wires appear in any B row
    # (and ~60% in C, measured on the venmo circuit), and an infinity
    # base contributes nothing for any witness — so b1/b2/c keep just the
    # non-infinity lanes plus the wire-index gather maps b_sel/c_sel.
    # The G2 MSM (3x the per-point cost of G1) halves outright.
    a_bases: AffPoint
    b1_bases: AffPoint
    b2_bases: AffPoint
    c_bases: AffPoint
    h_bases: AffPoint  # coset-Lagrange H basis, m lanes (zkey section 9)
    b_sel: jnp.ndarray  # wire indices backing b1/b2 lanes
    c_sel: jnp.ndarray  # wire indices backing c lanes
    # Width-classed MSM split (snark.r1cs wire_width: constraint-backed
    # value bounds — ~90% of venmo wires are SHA/DFA bits).  Positions
    # into each query's base array whose wire value is provably < 2^11
    # ("narrow": 3 signed w=4 digit planes suffice) vs the rest ("wide").
    # Empty narrow arrays (zkey import, width-free circuits) degrade to
    # the single-class path.
    a_nsel: jnp.ndarray
    a_wsel: jnp.ndarray
    b_nsel: jnp.ndarray
    b_wsel: jnp.ndarray
    c_nsel: jnp.ndarray
    c_wsel: jnp.ndarray
    # Host-side blinding points for final assembly.
    alpha_1: G1Point
    beta_1: G1Point
    beta_2: G2Point
    delta_1: G1Point
    delta_2: G2Point
    # Wires whose narrow classing came from zkey bit-pattern INFERENCE
    # (not ConstraintSystem width tags): packed int64 ids as bytes —
    # hashable, so it rides the pytree aux tuple and survives
    # flatten/unflatten (a rebuilt key keeps its prove-time width
    # guard).  None for cs-built keys.
    inferred_narrow_wires: Optional[bytes] = None


_DPK_ARRAY_FIELDS = (
    "a_coeff", "a_wire", "a_row", "b_coeff", "b_wire", "b_row",
    "a_bases", "b1_bases", "b2_bases", "c_bases", "h_bases",
    "b_sel", "c_sel",
    "a_nsel", "a_wsel", "b_nsel", "b_wsel", "c_nsel", "c_wsel",
)
_DPK_META_FIELDS = ("n_public", "n_wires", "log_m", "alpha_1", "beta_1", "beta_2", "delta_1", "delta_2", "inferred_narrow_wires")


def _dpk_flatten(d: "DeviceProvingKey"):
    return tuple(getattr(d, f) for f in _DPK_ARRAY_FIELDS), tuple(getattr(d, f) for f in _DPK_META_FIELDS)


def _dpk_unflatten(meta, children) -> "DeviceProvingKey":
    return DeviceProvingKey(**dict(zip(_DPK_ARRAY_FIELDS, children)), **dict(zip(_DPK_META_FIELDS, meta)))


jax.tree_util.register_pytree_node(DeviceProvingKey, _dpk_flatten, _dpk_unflatten)


def _rows_to_arrays(rows: Sequence[dict], m: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sparse QAP rows -> (coeff mont limbs, wire ids, row ids).  The
    coefficient conversion is the vectorized bytes->limbs path — at
    venmo-scale nnz counts a per-element limb loop costs minutes."""
    vals: List[int] = []
    wires: List[int] = []
    row_ids: List[int] = []
    for j, terms in enumerate(rows):
        for wire, coeff in terms.items():
            vals.append(coeff % R)
            wires.append(wire)
            row_ids.append(j)
    if not vals:  # degenerate all-zero matrix
        vals, wires, row_ids = [0], [0], [m - 1]
    return (
        jnp.asarray(FR.array_to_mont_host_fast(vals)),
        jnp.asarray(np.array(wires, dtype=np.int32)),
        jnp.asarray(np.array(row_ids, dtype=np.int32)),
    )


# Width classing (see DeviceProvingKey): wires with constraint-backed
# value bounds < 2^NARROW_WIDTH need only NARROW_PLANES signed w=4 digit
# planes (k planes exactly hold v < 2^(4k-1) after signed recoding).
NARROW_WIDTH = 11
NARROW_PLANES = 3


def widths_array(cs: "ConstraintSystem") -> np.ndarray:
    """cs.wire_width dict -> dense per-wire bound array (254 = unbounded)."""
    widths = np.full(cs.num_wires, 254, dtype=np.int32)
    for w, bits in cs.wire_width.items():
        widths[w] = bits
    return widths


def class_sels(widths: Optional[np.ndarray], wire_ids: np.ndarray):
    """(narrow positions, wide positions) into a base array whose row p
    holds the point for wire wire_ids[p] — THE classing rule, shared by
    device_pk_from_rows and setup_device so the dev-setup and pk-import
    paths can never drift."""
    if widths is None:
        n = len(wire_ids)
        return np.zeros(0, dtype=np.int32), np.arange(n, dtype=np.int32)
    narrow = widths[wire_ids] <= NARROW_WIDTH
    return (
        np.flatnonzero(narrow).astype(np.int32),
        np.flatnonzero(~narrow).astype(np.int32),
    )


def device_pk(pk: ProvingKey, cs: ConstraintSystem) -> DeviceProvingKey:
    """Host ProvingKey + R1CS -> device arrays.  One-time load, amortised
    over every proof (the TPU analog of the browser's IndexedDB zkey cache,
    `app/src/helpers/zkp.ts:56-61`)."""
    rows = qap_rows(cs)
    widths = widths_array(cs)
    return device_pk_from_rows(
        pk, [t[0] for t in rows], [t[1] for t in rows], domain_size_for(cs), cs.num_wires,
        widths=widths,
    )


def infer_zkey_widths(zk) -> np.ndarray:
    """Recover the narrow width class from an imported zkey's coeff
    section by detecting circom's bit-constraint rows x·(x-1)=0
    (circomlib Num2Bits emits A={x:1}, B={x:1, one:-1}, C=0; also
    matched with A/B swapped).  The zkey stores no C matrix, so the
    pattern is NOT conclusive — x·(x-1)=y matches identically — which
    is why every prove on an inferred-width key runs the witness-bound
    validator (`_check_inferred_widths`): a witness that breaks an
    inferred bound raises instead of silently dropping digit planes.

    Recovers the ~10x witness-MSM cut for ceremony keys (the production
    import path) that dev-setup keys get from ConstraintSystem width
    tags."""
    from collections import defaultdict

    a_rows: Dict[int, Dict[int, int]] = defaultdict(dict)
    b_rows: Dict[int, Dict[int, int]] = defaultdict(dict)
    for mat, row, wire, v in zk.coeffs:
        (a_rows if mat == 0 else b_rows)[row][wire] = v
    widths = np.full(zk.n_vars, 254, dtype=np.int32)
    widths[0] = 1  # constant-one wire
    for r in set(a_rows) | set(b_rows):
        A, B = a_rows.get(r, {}), b_rows.get(r, {})
        for X, Y in ((A, B), (B, A)):
            if len(X) == 1 and len(Y) == 2 and 0 in Y:
                ((w, xv),) = X.items()
                if w != 0 and xv == 1 and Y.get(w) == 1 and Y[0] == R - 1:
                    widths[w] = 1
    return widths


def _check_inferred_widths(
    dpk: DeviceProvingKey,
    witness: Sequence[int],
    w_std: Optional[np.ndarray] = None,
) -> None:
    """Host-side guard for inferred-width keys: every wire classed
    narrow must actually fit the narrow digit planes.  No-op for keys
    built from a ConstraintSystem, whose `check_witness`/`check_widths`
    already enforce the tagged bounds.

    `w_std`: optional (n_wires, 4) u64 standard-form limb view of the
    witness (prove_native already builds one) — the check vectorizes
    over it instead of looping Python bigints."""
    blob = dpk.inferred_narrow_wires
    if not blob:
        return
    wires = np.frombuffer(blob, dtype=np.int64)
    bound = 1 << (4 * NARROW_PLANES - 1)
    if w_std is None:
        # build a limb view of just the narrow wires (to_bytes is
        # C-speed; a pure-Python bigint comparison loop over ~90% of a
        # venmo key's wires costs seconds per witness at batch=64)
        from ..native.lib import _scalars_to_u64

        w_std = _scalars_to_u64([witness[j] % R for j in wires])
        wires_idx = np.arange(len(wires))
    else:
        wires_idx = wires
    vals = np.asarray(w_std)[wires_idx]
    bad = (vals[:, 1:].any(axis=1)) | (vals[:, 0] >= bound)
    if not bad.any():
        return
    i = int(wires[int(np.flatnonzero(bad)[0])])
    raise ValueError(
        f"wire {i}: witness value exceeds the width bound inferred "
        f"from the zkey's bit-constraint pattern — the circuit uses "
        f"x*(x-1)=y somewhere; re-import with infer_widths=False"
    )


def device_pk_from_zkey(zk, infer_widths: bool = True) -> DeviceProvingKey:
    """snarkjs zkey (formats.zkey.ZkeyData) -> device arrays: the
    ceremony-key import path (`app/src/helpers/zkp.ts:13` chunk flow).
    The zkey coeff section already contains the public binding rows, so
    the QAP rows come from the file, not from a ConstraintSystem.  Width
    metadata is recovered from the bit-constraint pattern by default
    (`infer_zkey_widths`), guarded at prove time."""
    a_rows, b_rows = zk.qap_row_arrays()
    widths = infer_zkey_widths(zk) if infer_widths else None
    dpk = device_pk_from_rows(
        zk.to_proving_key(), a_rows, b_rows, zk.domain_size, zk.n_vars, widths=widths
    )
    if widths is not None:
        dpk.inferred_narrow_wires = (
            np.flatnonzero(widths <= NARROW_WIDTH).astype(np.int64).tobytes()
        )
    return dpk


def _prune_sel(flags: Sequence[bool]) -> np.ndarray:
    sel = [i for i, f in enumerate(flags) if f]
    if not sel:
        sel = [0]  # degenerate: keep one (infinity) lane
    return np.array(sel, dtype=np.int32)


def device_pk_from_rows(
    pk: ProvingKey,
    a_rows: Sequence[dict],
    b_rows: Sequence[dict],
    m: int,
    n_wires: int,
    widths: Optional[np.ndarray] = None,
) -> DeviceProvingKey:
    log_m = m.bit_length() - 1
    a = _rows_to_arrays(a_rows, m)
    b = _rows_to_arrays(b_rows, m)
    h_pts = list(pk.h_query) + [None] * (m - len(pk.h_query))
    b_sel = _prune_sel(
        [p1 is not None or p2 is not None for p1, p2 in zip(pk.b1_query, pk.b2_query)]
    )
    c_sel = _prune_sel([p is not None for p in pk.c_query])

    all_wires = np.arange(n_wires, dtype=np.int32)
    a_nsel, a_wsel = class_sels(widths, all_wires)
    b_nsel, b_wsel = class_sels(widths, np.asarray(b_sel))
    c_nsel, c_wsel = class_sels(widths, np.asarray(c_sel))
    return DeviceProvingKey(
        n_public=pk.n_public,
        n_wires=n_wires,
        log_m=log_m,
        a_coeff=a[0], a_wire=a[1], a_row=a[2],
        b_coeff=b[0], b_wire=b[1], b_row=b[2],
        a_bases=g1_to_affine_arrays(pk.a_query),
        b1_bases=g1_to_affine_arrays([pk.b1_query[i] for i in b_sel]),
        b2_bases=g2_to_affine_arrays([pk.b2_query[i] for i in b_sel]),
        c_bases=g1_to_affine_arrays([pk.c_query[i] for i in c_sel]),
        h_bases=g1_to_affine_arrays(h_pts),
        b_sel=jnp.asarray(b_sel),
        c_sel=jnp.asarray(c_sel),
        a_nsel=jnp.asarray(a_nsel), a_wsel=jnp.asarray(a_wsel),
        b_nsel=jnp.asarray(b_nsel), b_wsel=jnp.asarray(b_wsel),
        c_nsel=jnp.asarray(c_nsel), c_wsel=jnp.asarray(c_wsel),
        alpha_1=pk.alpha_1,
        beta_1=pk.beta_1,
        beta_2=pk.beta_2,
        delta_1=pk.delta_1,
        delta_2=pk.delta_2,
    )


def _is_u64_witness(witness) -> bool:
    """The (n, 4) uint64 standard-form limb layout (the .bench_cache
    witness format, prove_native's view) — the only ndarray form the
    vectorized paths and _check_inferred_widths' w_std view accept."""
    return (
        isinstance(witness, np.ndarray)
        and witness.dtype == np.uint64
        and witness.ndim == 2
        and witness.shape[-1] == 4
    )


_R_U64 = np.frombuffer(R.to_bytes(32, "little"), dtype="<u8").copy()


def _check_u64_reduced(rows: np.ndarray) -> None:
    """Reject (n, 4)-u64 witness rows >= R.  The fast path trusts its
    input to already be reduced (the .bench_cache contract) — an
    unreduced row would silently emit a wrong Montgomery form and an
    unverifiable proof, so the boundary asserts it (8 vectorized
    compares; negligible next to to_mont)."""
    ge = np.zeros(rows.shape[0], dtype=bool)
    eq = np.ones(rows.shape[0], dtype=bool)
    for j in range(3, -1, -1):
        col = rows[:, j]
        ge |= eq & (col > _R_U64[j])
        eq &= col == _R_U64[j]
    ge |= eq  # exactly R is unreduced too
    if ge.any():
        i = int(np.flatnonzero(ge)[0])
        raise ValueError(
            f"witness row {i} is not reduced below the Fr modulus: the "
            f"(n, 4)-u64 fast path requires canonical scalars (< R); "
            f"reduce mod R before witness_to_device"
        )


def _witness_std_limbs(witness) -> np.ndarray:
    """Host witness (int sequence or (n, 4) u64 limb rows) -> (n, 16)
    u32 standard-form 16-bit limbs, fully vectorized (one C-speed bytes
    pack + a numpy view; never a per-wire Python bigint loop)."""
    from ..native.lib import _scalars_to_u64, _u64_to_limbs16

    if not _is_u64_witness(witness):
        witness = _scalars_to_u64([int(w) % R for w in witness])
    else:
        _check_u64_reduced(witness)
    return _u64_to_limbs16(witness)


def witness_to_device(witness) -> jnp.ndarray:
    """Host witness -> Montgomery limb matrix (n_wires, 16): the
    vectorized standard-form limbs plus ONE device to_mont mul."""
    return FR.to_mont(jnp.asarray(_witness_std_limbs(witness)))


def _matvec(coeff, wire, row, w_mont, m):
    vals = FR.mul(coeff, w_mont[wire])
    return lazy_segment_sum_mod(FR, vals, row, m)


def abc_evals(dpk: DeviceProvingKey, w_mont: jnp.ndarray):
    """Az/Bz/Cz evaluations on the domain: the sparse-matvec stage shared
    by the single-chip and sharded H ladders (and vmapped over the batch
    axis by the dryrun's data-parallel step)."""
    m = 1 << dpk.log_m
    a_ev = _matvec(dpk.a_coeff, dpk.a_wire, dpk.a_row, w_mont, m)
    b_ev = _matvec(dpk.b_coeff, dpk.b_wire, dpk.b_row, w_mont, m)
    return a_ev, b_ev, FR.mul(a_ev, b_ev)


def h_evals(dpk: DeviceProvingKey, w_mont: jnp.ndarray) -> jnp.ndarray:
    """Coset evaluations d_j = (A·B - C)(g·w^j) on device, (m, 16) mont
    limbs — the scalars MSM'd against the coset-Lagrange h_bases.

    Same ladder as the host oracle `snark.groth16.coset_quotient_evals`
    (the snarkjs `groth16 prove` dataflow: 3 iNTT + 3 coset NTT, no
    division — Z is constant on the coset and folded into h_bases), every
    step batched on limb lanes."""
    g = coset_gen(dpk.log_m)
    a_ev, b_ev, c_ev = abc_evals(dpk, w_mont)
    a_cos = ntt(coset_shift(intt(a_ev, dpk.log_m), g, dpk.log_m), dpk.log_m)
    b_cos = ntt(coset_shift(intt(b_ev, dpk.log_m), g, dpk.log_m), dpk.log_m)
    c_cos = ntt(coset_shift(intt(c_ev, dpk.log_m), g, dpk.log_m), dpk.log_m)
    return FR.sub(FR.mul(a_cos, b_cos), c_cos)


def _h_and_planes(dpk: DeviceProvingKey, w_mont: jnp.ndarray):
    h = h_evals(dpk, w_mont)
    if MSM_SIGNED:
        w_std = FR.from_mont(w_mont)
        h_window = H_BUCKET_WINDOW if _h_bucket() else MSM_WINDOW
        if _glv():
            # G1 planes in the GLV-doubled column layout (k1 digits for
            # P_i, k2 digits for phi(P_i)): HALF the digit planes over
            # twice the columns.  The G2 MSM has no cheap endomorphism
            # here, so it keeps full-width signed planes — but ONLY for
            # the b_sel wires it can consume (recoding all n_wires just
            # for b2 would materialize ~65 planes x n_wires per proof);
            # its columns are therefore b_sel POSITIONS, not wire ids.
            w_mags, w_negs = glv_signed_planes_from_limbs(w_std, MSM_WINDOW)
            g2_planes = signed_digit_planes_from_limbs(
                jnp.take(w_std, dpk.b_sel, axis=-2), MSM_WINDOW
            )
            h_mags, h_negs = glv_signed_planes_from_limbs(FR.from_mont(h), h_window)
            if int(dpk.a_nsel.shape[0]) > 0:
                n4_mags, n4_negs = signed_digit_planes_from_limbs(w_std, 4)
                narrow = (n4_mags[-NARROW_PLANES:], n4_negs[-NARROW_PLANES:])
            else:
                narrow = ()
            return ((w_mags, w_negs), narrow, g2_planes), (h_mags, h_negs)
        w_mags, w_negs = signed_digit_planes_from_limbs(w_std, MSM_WINDOW)
        h_mags, h_negs = signed_digit_planes_from_limbs(FR.from_mont(h), h_window)
        # Narrow-class planes: witness wires with width bounds <= 2^11
        # only populate the last NARROW_PLANES signed w=4 digits — the
        # upper 61 planes are provably zero and never reach an MSM.
        # Keys with no narrow class (zkey import) skip the w=4 recode
        # entirely — shapes are static under jit, so this prunes at
        # trace time.
        if int(dpk.a_nsel.shape[0]) > 0:
            n4_mags, n4_negs = signed_digit_planes_from_limbs(w_std, 4)
            narrow = (n4_mags[-NARROW_PLANES:], n4_negs[-NARROW_PLANES:])
        else:
            narrow = ()
        return ((w_mags, w_negs), narrow), (h_mags, h_negs)
    return (
        digit_planes_from_limbs(FR.from_mont(w_mont), MSM_WINDOW),
        digit_planes_from_limbs(FR.from_mont(h), MSM_WINDOW),
    )


def _msm_g1(bases, planes):
    # lanes from the static base count: wide steps keep the VPU batch
    # large (TPU ops are latency-bound at small batches — see
    # ops.msm.default_lanes).
    lanes = default_lanes(bases[0].shape[0])
    if MSM_SIGNED:
        return _signed_windowed(G1J, bases, planes, lanes, MSM_WINDOW)
    return msm_windowed(G1J, bases, planes, lanes=lanes, window=MSM_WINDOW)


def _signed_windowed(curve, bases, planes, lanes, window):
    """Signed windowed MSM with the accumulate-tier selector: batch
    affine (ops.msm_affine) when armed, Jacobian otherwise."""
    mags, negs = planes
    if _affine():
        from ..ops.msm_affine import msm_windowed_affine

        return msm_windowed_affine(curve, bases, mags, negs, lanes=lanes, window=window)
    return msm_windowed_signed(curve, bases, mags, negs, lanes=lanes, window=window)


def _msm_g1_narrow(bases, planes):
    # 3-plane signed w=4 MSM for width-bounded wires: ~3.5 adds/pt at
    # batch=16 vs ~40 on the wide path.  Wider lanes keep the per-step
    # batch (NARROW_PLANES x lanes) off the latency floor.
    return _signed_windowed(
        G1J, bases, planes, default_lanes(bases[0].shape[0], cap=16384), 4
    )


def _msm_g2_narrow(bases, planes):
    return _signed_windowed(
        G2J, bases, planes, default_lanes(bases[0].shape[0], cap=4096), 4
    )


def _msm_g2(bases, planes):
    lanes = default_lanes(bases[0].shape[0], cap=2048)
    if MSM_SIGNED:
        return _signed_windowed(G2J, bases, planes, lanes, MSM_WINDOW)
    return msm_windowed(G2J, bases, planes, lanes=lanes, window=MSM_WINDOW)


def _msm_h(bases, planes):
    """The h MSM: full-width coset-quotient scalars, the dominant prover
    cost — routed to the sorted-prefix bucket formulation when armed."""
    if _h_bucket():
        from ..ops.msm_bucket import msm_bucket_affine

        mags, negs = planes
        return msm_bucket_affine(G1J, bases, mags, negs, window=H_BUCKET_WINDOW)
    return _msm_g1(bases, planes)


# Stage-wise jits, NOT one fused program: XLA compile time scales with
# traced-graph size, so the pipeline is a handful of small executables
# with intermediates staying on device between stages.  Since b/c
# pruning the G1 MSMs run at three different lane counts (a: all wires,
# b1: |b_sel|, c: |c_sel|), so jit re-specializes _msm_g1 per shape —
# the ~50% runtime cut on b1/b2/c outweighs the extra first-proof
# compiles (and the persistent cache amortises them across processes).
_jit_h_planes = jax.jit(_h_and_planes)
_jit_msm_g1 = jax.jit(_msm_g1)
_jit_msm_g2 = jax.jit(_msm_g2)
_jit_msm_h = jax.jit(_msm_h)
_jit_msm_g1_narrow = jax.jit(_msm_g1_narrow)
_jit_msm_g2_narrow = jax.jit(_msm_g2_narrow)
_jit_h_planes_batch = jax.jit(jax.vmap(_h_and_planes, in_axes=(None, 0)))
_jit_msm_g1_batch = jax.jit(jax.vmap(_msm_g1, in_axes=(None, 0)))
_jit_msm_g2_batch = jax.jit(jax.vmap(_msm_g2, in_axes=(None, 0)))
_jit_msm_h_batch = jax.jit(jax.vmap(_msm_h, in_axes=(None, 0)))
_jit_msm_g1_narrow_batch = jax.jit(jax.vmap(_msm_g1_narrow, in_axes=(None, 0)))
_jit_msm_g2_narrow_batch = jax.jit(jax.vmap(_msm_g2_narrow, in_axes=(None, 0)))


def _take_planes(planes, sel):
    # signed planes are a (mags, negs) pair; both gather on wires
    if isinstance(planes, tuple):
        return tuple(jnp.take(p, sel, axis=-1) for p in planes)
    return jnp.take(planes, sel, axis=-1)


def _glv_key_bases(dpk: DeviceProvingKey, name: str, bases: AffPoint) -> AffPoint:
    """GLV-doubled base set [P, phi(P)] for one query, memoised on the
    key instance (one batched Fq mul per query per key — witness-
    independent, like _split_cache)."""
    cache = getattr(dpk, "_glv_cache", None)
    if cache is None:
        cache = {}
        setattr(dpk, "_glv_cache", cache)
    got = cache.get(name)
    if got is None:
        got = glv_extend_bases(bases)
        cache[name] = got
    return got


def _take_bases(bases, pos):
    return tuple(jnp.take(c, pos, axis=0) for c in bases)


def _pad_msm(bases, planes, n_to: int):
    """Pad an MSM's inputs to `n_to` bases: the (0, 0) infinity sentinel
    and zero digit planes contribute nothing, and equal shapes let MSMs
    share one compiled executable."""
    n = bases[0].shape[0]
    if n_to and n < n_to:
        bases = tuple(jnp.pad(c, [(0, n_to - n)] + [(0, 0)] * (c.ndim - 1)) for c in bases)
        if isinstance(planes, tuple):
            planes = tuple(jnp.pad(p, [(0, 0)] * (p.ndim - 1) + [(0, n_to - n)]) for p in planes)
        else:
            planes = jnp.pad(planes, [(0, 0)] * (planes.ndim - 1) + [(0, n_to - n)])
    return bases, planes


def _prove_device(dpk: DeviceProvingKey, w_mont: jnp.ndarray, batched: bool = False):
    """The five big MSMs; everything else about the proof is host-cheap.
    The b/c MSMs run only over their pruned non-infinity lanes (plane
    columns gathered through b_sel/c_sel), and with width metadata each
    witness MSM splits into a narrow class (3 signed w=4 planes — the
    ~90% of wires that are constraint-bounded bits/bytes) and a wide
    class (full planes); the two partial sums combine with one Jacobian
    add per query."""
    classed = MSM_SIGNED and int(dpk.a_nsel.shape[0]) > 0
    jh, m1, m2 = (
        (_jit_h_planes_batch, _jit_msm_g1_batch, _jit_msm_g2_batch)
        if batched
        else (_jit_h_planes, _jit_msm_g1, _jit_msm_g2)
    )
    mh = _jit_msm_h_batch if batched else _jit_msm_h
    m1n, m2n = (
        (_jit_msm_g1_narrow_batch, _jit_msm_g2_narrow_batch)
        if batched
        else (_jit_msm_g1_narrow, _jit_msm_g2_narrow)
    )
    w_all, h_planes = jh(dpk, w_mont)
    if _glv():
        # GLV layout: G1 planes carry 2*n_wires columns (k1 digits for
        # the P half, k2 for the phi(P) half); the G2 MSM keeps its own
        # full-width planes.  G1 bases and column selectors lift to the
        # doubled layout; everything downstream is shape-generic.
        w_planes, w_narrow, g2_planes = w_all
        g1_bases = lambda name, b: _glv_key_bases(dpk, name, b)  # noqa: E731
        g1_cols = lambda sel: glv_sel(sel, dpk.n_wires)  # noqa: E731
    else:
        if MSM_SIGNED:
            w_planes, w_narrow = w_all
        else:
            w_planes, w_narrow = w_all, None
        g2_planes = w_planes
        g1_bases = lambda name, b: b  # noqa: E731
        g1_cols = lambda sel: sel  # noqa: E731

    if not classed:
        a_b = g1_bases("a", dpk.a_bases)
        b1_b = g1_bases("b1", dpk.b1_bases)
        c_b = g1_bases("c", dpk.c_bases)
        h_b = g1_bases("h", dpk.h_bases)
        # bucket-h mode: h no longer shares the unified executable, so
        # padding a/b1/c up to the (domain-sized) h base count would be
        # pure waste — unify the three query MSMs among themselves only.
        g1_n = 0 if not _unified() else max(
            a_b[0].shape[0], b1_b[0].shape[0], c_b[0].shape[0],
            *(() if _h_bucket() else (h_b[0].shape[0],)),
        )
        b_planes = _take_planes(w_planes, g1_cols(dpk.b_sel))
        c_planes = _take_planes(w_planes, g1_cols(dpk.c_sel))
        # GLV g2_planes are already gathered to the b_sel columns
        b2_planes = g2_planes if _glv() else b_planes
        # windowed mode keeps the m1 wrapper so the compiled-executable
        # identity (and its persistent-cache entry) is unchanged
        h_acc = (
            mh(h_b, h_planes)
            if _h_bucket()
            else m1(*_pad_msm(h_b, h_planes, g1_n))
        )
        return (
            m1(*_pad_msm(a_b, w_planes, g1_n)),
            m1(*_pad_msm(b1_b, b_planes, g1_n)),
            m2(dpk.b2_bases, b2_planes),
            m1(*_pad_msm(c_b, c_planes, g1_n)),
            h_acc,
        )

    # Unify shapes WITHIN each class (a/b1/c wide together, narrows
    # together) but NOT with the h MSM: the wide query classes are ~6%
    # of wires while h spans the full domain — padding them to h's size
    # would burn ~16x the work the classing just removed.  Three G1
    # executables total (narrow, query-wide, h).
    g1_wide_n = g1_narrow_n = 0
    if _unified():
        g1_wide_n = max(dpk.a_wsel.shape[0], dpk.b_wsel.shape[0], dpk.c_wsel.shape[0])
        g1_narrow_n = max(dpk.a_nsel.shape[0], dpk.b_nsel.shape[0], dpk.c_nsel.shape[0])
        if _glv():
            g1_wide_n *= 2  # wide-class MSMs run over the doubled base axis

    # The split bases/wire arrays depend only on the KEY — memoise them
    # on the dpk instance so the gathers (O(key size) HBM copies) run
    # once per key, not once per proof.
    split = getattr(dpk, "_split_cache", None)
    if split is None:
        split = {}
        setattr(dpk, "_split_cache", split)

    def key_split(name, bases, sel, wires_of):
        got = split.get((name, "b"))
        if got is None:
            got = _take_bases(bases, sel)
            split[(name, "b")] = got
            split[(name, "w")] = jnp.take(wires_of, sel) if wires_of is not None else sel
        return got, split[(name, "w")]

    def query(name, bases, nsel, wsel, wires_of):
        """One witness MSM (a/b1/c): narrow + wide class partial sums.
        wires_of maps base positions to wire ids (None = identity).
        Under GLV only the WIDE class decomposes — narrow wires are
        width-bounded below 2^11, where a 2-term split has nothing to
        halve — so the narrow executable is byte-identical either way."""
        accs = []
        if int(nsel.shape[0]):
            nb, nw = key_split(name + ".n", bases, nsel, wires_of)
            accs.append(m1n(*_pad_msm(nb, _take_planes(w_narrow, nw), g1_narrow_n)))
        if int(wsel.shape[0]):
            wb, ww = key_split(name + ".w", bases, wsel, wires_of)
            wb = g1_bases(name + ".w", wb)
            accs.append(m1(*_pad_msm(wb, _take_planes(w_planes, g1_cols(ww)), g1_wide_n)))
        return accs[0] if len(accs) == 1 else G1J.add(accs[0], accs[1])

    def query_g2(name, bases, nsel, wsel, wires_of):
        accs = []
        if int(nsel.shape[0]):
            nb, nw = key_split(name + ".n", bases, nsel, wires_of)
            accs.append(m2n(nb, _take_planes(w_narrow, nw)))
        if int(wsel.shape[0]):
            wb, ww = key_split(name + ".w", bases, wsel, wires_of)
            # GLV g2_planes carry b_sel POSITIONS (wsel indexes those);
            # the plain path's full-wire planes gather by wire id
            cols = wsel if _glv() else ww
            accs.append(m2(wb, _take_planes(g2_planes, cols)))
        return accs[0] if len(accs) == 1 else G2J.add(accs[0], accs[1])

    return (
        query("a", dpk.a_bases, dpk.a_nsel, dpk.a_wsel, None),
        query("b1", dpk.b1_bases, dpk.b_nsel, dpk.b_wsel, dpk.b_sel),
        query_g2("b2", dpk.b2_bases, dpk.b_nsel, dpk.b_wsel, dpk.b_sel),
        query("c", dpk.c_bases, dpk.c_nsel, dpk.c_wsel, dpk.c_sel),
        (mh if _h_bucket() else m1)(g1_bases("h", dpk.h_bases), h_planes),
    )


def _assemble(dpk: DeviceProvingKey, acc, r: int, s: int) -> Proof:
    a_acc, b1_acc, b2_acc, c_acc, h_acc = acc
    pi_a = g1_add(g1_add(dpk.alpha_1, a_acc), g1_mul(dpk.delta_1, r))
    pi_b = g2_add(g2_add(dpk.beta_2, b2_acc), g2_mul(dpk.delta_2, s))
    pi_b1 = g1_add(g1_add(dpk.beta_1, b1_acc), g1_mul(dpk.delta_1, s))
    pi_c = g1_add(c_acc, h_acc)
    pi_c = g1_add(pi_c, g1_mul(pi_a, s))
    pi_c = g1_add(pi_c, g1_mul(pi_b1, r))
    pi_c = g1_add(pi_c, g1_neg(g1_mul(dpk.delta_1, r * s % R)))
    return Proof(a=pi_a, b=pi_b, c=pi_c)


def prove_tpu(
    dpk: DeviceProvingKey,
    witness: Sequence[int],
    r: Optional[int] = None,
    s: Optional[int] = None,
) -> Proof:
    from ..utils.audit import sample_device_memory
    from ..utils.metrics import REGISTRY
    from ..utils.trace import trace

    if r is None:
        r = 1 + secrets.randbelow(R - 1)
    if s is None:
        s = 1 + secrets.randbelow(R - 1)
    with trace("tpu/prove"):
        sample_device_memory("tpu/prove")  # entry watermark (flight recorder)
        _check_inferred_widths(dpk, witness, w_std=witness if _is_u64_witness(witness) else None)
        acc = _prove_device(dpk, witness_to_device(witness))
        a, b1, c, hq = (g1_jac_to_host(p)[0] for p in (acc[0], acc[1], acc[3], acc[4]))
        b2 = g2_jac_to_host(acc[2])[0]
        proof = _assemble(dpk, (a, b1, b2, c, hq), r, s)
        sample_device_memory("tpu/prove")  # exit watermark: per-prove HBM peak
    REGISTRY.counter("zkp2p_proves_total", {"prover": "tpu"}).inc()
    return proof


def h_evals_sharded(dpk: DeviceProvingKey, w_mont: jnp.ndarray, mesh, axis: str = "shard") -> jnp.ndarray:
    """`h_evals` with the six domain transforms sharded over `mesh`:
    the production multi-chip path (SURVEY.md §2.7 NTT parallelism).

    The sparse matvec stays replicated (it is ~1% of prove FLOPs and its
    segment-sum does not shard cleanly); each (m, 16) vector is then laid
    out shard-major and run through the four-step `ntt_sharded` with its
    three ICI all-to-alls.  Requires both Bailey factors of the domain to
    be divisible by the mesh width: m >= (mesh size)^2."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.ntt import ntt_sharded

    g = coset_gen(dpk.log_m)
    a_ev, b_ev, c_ev = abc_evals(dpk, w_mont)
    shard = NamedSharding(mesh, P(axis, None))

    def ladder(v):
        v = jax.device_put(v, shard)
        v = ntt_sharded(v, dpk.log_m, mesh, axis=axis, inverse=True)
        v = coset_shift(v, g, dpk.log_m)
        return ntt_sharded(v, dpk.log_m, mesh, axis=axis)

    a_cos, b_cos, c_cos = ladder(a_ev), ladder(b_ev), ladder(c_ev)
    return FR.sub(FR.mul(a_cos, b_cos), c_cos)


def prove_tpu_sharded(
    dpk: DeviceProvingKey,
    witness: Sequence[int],
    mesh,
    r: Optional[int] = None,
    s: Optional[int] = None,
    axis: str = "shard",
    lanes: int = 64,
    unified: bool = False,
    progress=None,
) -> Proof:
    """`prove_tpu` with the MSM base axis AND the NTT domain sharded over
    `mesh` — the same dataflow a v5e slice runs, exercised by the driver's
    `dryrun_multichip` on virtual CPU devices.  Emits the exact proof
    `prove_host`/`prove_tpu` produce for the same (witness, r, s).

    unified=True pads every G1 MSM (a/b1/c/h) to one common base count so
    all four share a single compiled executable — the dryrun/cold-start
    configuration, where XLA compile time on the driver host dwarfs the
    masked-lane runtime waste.  Production keeps per-shape sizing.
    progress, when given, is called with a short string after each
    device stage (the dryrun's per-stage timestamps)."""
    from ..parallel.mesh import msm_sharded, pad_to_multiple
    from ..utils.trace import trace

    if r is None:
        r = 1 + secrets.randbelow(R - 1)
    if s is None:
        s = 1 + secrets.randbelow(R - 1)

    def note(arr, msg: str) -> None:
        # Sync + report only when a progress callback asked for stage
        # boundaries (the dryrun); production dispatch stays fully async.
        if progress is not None:
            arr.block_until_ready()
            progress(msg)

    # Stage spans feed the same trace/metrics rails as the single-chip
    # provers, so a MULTICHIP dryrun dumped to a sink is diffable with
    # trace_report like any bench run.  With a progress callback each
    # span brackets block_until_ready (true stage time); without one
    # dispatch is async and spans measure enqueue latency only.
    n_dev = mesh.shape[axis]
    with trace("sharded/witness"):
        w_mont = witness_to_device(witness)
    with trace("sharded/h_evals"):
        h = h_evals_sharded(dpk, w_mont, mesh, axis)
        note(h, "h_evals_sharded")
    with trace("sharded/planes"):
        w_planes = digit_planes_from_limbs(FR.from_mont(w_mont), MSM_WINDOW)
        h_planes = digit_planes_from_limbs(FR.from_mont(h), MSM_WINDOW)
    if unified:
        # One executable for ALL FOUR G1 MSMs needs identical input
        # LAYOUTS, not just shapes: h_planes inherits the NTT's shard-axis
        # sharding while w_planes is replicated, and jit keys compiled
        # programs on input shardings — without this the h MSM recompiles
        # the whole G1 program (~250 s of the dryrun's cold budget).
        # Replicating h_planes is dryrun-sized traffic only; production
        # (unified=False) keeps the sharded layout.
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(mesh, P())
        w_planes = jax.device_put(w_planes, rep)
        h_planes = jax.device_put(h_planes, rep)

    base_chunk = n_dev * lanes
    g1_chunk = base_chunk
    if unified:
        n_max = max(
            dpk.a_bases[0].shape[0], dpk.b1_bases[0].shape[0],
            dpk.c_bases[0].shape[0], dpk.h_bases[0].shape[0],
        )
        g1_chunk = ((n_max + base_chunk - 1) // base_chunk) * base_chunk

    def msm(curve, bases, planes, tag):
        # Per-MSM padding: the b/c queries are pruned to their
        # non-infinity lanes, so each MSM runs at its own (smaller) size
        # rather than a unified shape (runtime beats executable reuse on
        # the production path); unified=True pads the four G1 MSMs to one
        # shared shape.  G2 compiles its own executable either way (other
        # curve type), so it always keeps its minimal padded size — its
        # per-point cost is ~3x G1's.
        chunk = g1_chunk if curve is G1J else base_chunk
        with trace(f"sharded/msm_{tag}"):
            b, p = pad_to_multiple(bases, planes, chunk)
            acc = msm_sharded(curve, b, p, mesh, axis=axis, lanes=lanes, window=MSM_WINDOW)
            note(acc[0], f"msm {tag} ({b[0].shape[0]} bases)")
        return acc

    b_planes = jnp.take(w_planes, dpk.b_sel, axis=-1)
    a_acc = msm(G1J, dpk.a_bases, w_planes, "a")
    b1_acc = msm(G1J, dpk.b1_bases, b_planes, "b1")
    b2_acc = msm(G2J, dpk.b2_bases, b_planes, "b2")
    c_acc = msm(G1J, dpk.c_bases, jnp.take(w_planes, dpk.c_sel, axis=-1), "c")
    h_acc = msm(G1J, dpk.h_bases, h_planes, "h")
    a, b1, c, hq = (g1_jac_to_host(p)[0] for p in (a_acc, b1_acc, c_acc, h_acc))
    b2 = g2_jac_to_host(b2_acc)[0]
    return _assemble(dpk, (a, b1, b2, c, hq), r, s)


# Batched sharded-arm stage jits: h_evals vmapped over the witness batch
# (the pjit data-parallel axis — inputs arrive batch-sharded, XLA
# propagates the layout through the matvec/NTT ladder), and the UNSIGNED
# digit-plane recode per witness ((B, n_planes, n) — the layout
# msm_pod_batched's shard_map consumes).  The sharded MSMs use the
# unsigned formulation like prove_tpu_sharded: group arithmetic is
# exact, so the proof bytes match the signed vmap arm regardless.
_jit_h_evals_batch = jax.jit(jax.vmap(h_evals, in_axes=(None, 0)))
_jit_digit_planes_batch = jax.jit(
    jax.vmap(lambda w_std: digit_planes_from_limbs(w_std, MSM_WINDOW))
)


def _prove_batch_sharded(dpk: DeviceProvingKey, w_mont: jnp.ndarray, mesh):
    """One prove_tpu_batch chunk on a ("batch", "shard") pod mesh: the
    (B, n_wires, 16) witness chunk is placed batch-sharded
    (`NamedSharding(mesh, P("batch"))` — each batch group proves its
    share of the chunk), and every MSM runs base-axis-sharded over the
    inner "shard" axis with per-device bucket partial sums combined by
    ONE group-op allreduce (all_gather + Jacobian fold — ICI on real
    hardware, host rings on the virtual CPU mesh; parallel.mesh.
    msm_pod_batched).  Returns the same five (B,)-batched accumulators
    `_prove_device(batched=True)` emits, so chunks from either arm
    concatenate identically downstream."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import msm_pod_batched, pad_to_multiple

    n_ici = mesh.shape["shard"]
    w_mont = jax.device_put(w_mont, NamedSharding(mesh, P("batch")))
    h = _jit_h_evals_batch(dpk, w_mont)
    w_planes = _jit_digit_planes_batch(FR.from_mont(w_mont))
    h_planes = _jit_digit_planes_batch(FR.from_mont(h))

    def msm(curve, bases, planes):
        # lanes sized to the per-device slice (tiny CI circuits stay at
        # lanes ~ n/S instead of padding 16x to a 64-lane step); the pad
        # rule matches prove_tpu_sharded — bases to a multiple of
        # S * lanes so every device sees whole steps.
        n = bases[0].shape[0]
        lanes = max(1, min(64, -(-n // n_ici)))
        b, p = pad_to_multiple(bases, planes, n_ici * lanes)
        return msm_pod_batched(
            curve, b, p, mesh,
            dcn_axis="batch", ici_axis="shard", lanes=lanes, window=MSM_WINDOW,
        )

    b_planes = jnp.take(w_planes, dpk.b_sel, axis=-1)
    return (
        msm(G1J, dpk.a_bases, w_planes),
        msm(G1J, dpk.b1_bases, b_planes),
        msm(G2J, dpk.b2_bases, b_planes),
        msm(G1J, dpk.c_bases, jnp.take(w_planes, dpk.c_sel, axis=-1)),
        msm(G1J, dpk.h_bases, h_planes),
    )


def _batch_chunk_size() -> int:
    """Sub-batch size for prove_tpu_batch; 0 = whole batch in one vmap.

    "auto" chunks only on a real TPU: the batched pipeline's peak HBM is
    linear in the vmapped batch (~1.3 GB per witness at the 499k venmo
    shape on the XLA field path), so a 16-witness batch plans 20+ GB
    against the v5e's 15.75 G — chunks of 4 keep every chunk's peak
    under ~7 GB while reusing ONE compiled executable across chunks."""
    auto = 4 if _on_tpu() else 0
    if BATCH_CHUNK == "auto":
        v = auto
    else:
        try:
            v = max(0, int(BATCH_CHUNK))
        except ValueError:
            # a malformed knob must not silently select the unchunked
            # (OOM-prone) behavior the knob exists to prevent — keep the
            # auto rule
            v = auto
    _record_arm("batch_chunk", str(v))
    return v


def prove_tpu_batch(dpk: DeviceProvingKey, witnesses: Sequence[Sequence[int]]) -> List[Proof]:
    """vmap the full device pipeline over a batch of witnesses (the
    batch=64 configuration in BASELINE.json).

    Large batches run as shape-stable sub-chunks (see _batch_chunk_size;
    the last chunk pads by repeating its final witness) so device memory
    is bounded by the chunk, not the batch, and every chunk reuses the
    same compiled executable.

    With ZKP2P_TPU_SHARD=on (and a satisfiable ZKP2P_TPU_MESH) each
    chunk runs the pod-mesh program instead (_prove_batch_sharded):
    batch data-parallel over the mesh's "batch" axis, MSM bucket partial
    sums allreduced over "shard".  The arm is decided ONCE per call —
    a chunk size indivisible by the mesh's batch width records the
    `tpu_shard` arm as "fallback" and the whole call takes the vmap
    path, so every chunk of a call shares one executable either way."""
    from ..utils.audit import sample_device_memory
    from ..utils.metrics import REGISTRY
    from ..utils.trace import trace

    with trace("tpu/prove_batch", n=len(witnesses)):
        sample_device_memory("tpu/prove_batch")  # entry watermark
        for wit in witnesses:
            _check_inferred_widths(dpk, wit, w_std=wit if _is_u64_witness(wit) else None)
        n = len(witnesses)
        chunk = _batch_chunk_size()
        if chunk <= 0 or n <= chunk:
            spans = [list(witnesses)]
        else:
            spans = [list(witnesses[i : i + chunk]) for i in range(0, n, chunk)]
            spans[-1] += [spans[-1][-1]] * (chunk - len(spans[-1]))
        mesh = _shard_mesh()
        if mesh is not None and len(spans[0]) % mesh.shape["batch"]:
            _record_arm("tpu_shard", "fallback")
            mesh = None
        parts = []
        for span in spans:
            # one batched to_mont per chunk (not one device dispatch per witness)
            w = FR.to_mont(jnp.asarray(np.stack([_witness_std_limbs(wit) for wit in span])))
            parts.append(
                _prove_batch_sharded(dpk, w, mesh)
                if mesh is not None
                else _prove_device(dpk, w, batched=True)
            )
            # sub-chunk HBM watermark: the batched pipeline's peak is
            # linear in the vmapped chunk (r5: 15.75 G OOM at batch=16
            # with no telemetry) — sample per chunk so the staircase is
            # on record BEFORE the allocator walks off the top
            sample_device_memory("tpu/prove_batch_chunk")
        accs = (
            parts[0]
            if len(parts) == 1
            else jax.tree_util.tree_map(lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        )
        a, b1, c, hq = (g1_jac_to_host(accs[i]) for i in (0, 1, 3, 4))
        b2 = g2_jac_to_host(accs[2])
        proofs = [
            _assemble(dpk, (a[i], b1[i], b2[i], c[i], hq[i]), 1 + secrets.randbelow(R - 1), 1 + secrets.randbelow(R - 1))
            for i in range(len(witnesses))
        ]
        sample_device_memory("tpu/prove_batch")  # exit watermark: batch HBM peak
    REGISTRY.counter("zkp2p_proves_total", {"prover": "tpu"}).inc(len(witnesses))
    return proofs
