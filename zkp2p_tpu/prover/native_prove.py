"""Native-CPU Groth16 prover: the rapidsnark analog of the framework.

Same dataflow as `prover.groth16_tpu.prove_tpu` (sparse matvec -> iNTT/
coset/NTT ladder -> 4 G1 + 1 G2 variable-base MSMs -> host blind and
assemble), executed by the C++ runtime (csrc/zkp2p_native.cpp: Fr
Montgomery field, precomputed-twiddle NTT, Pippenger bucket MSM) instead
of XLA.  The reference ships exactly this split: a browser/wasm prover
plus the native rapidsnark fast path (`dizkus-scripts/
6_gen_proof_rapidsnark.sh`); here the TPU prover is the accelerator path
and this is the portable native one — the first prover in this repo
that can prove the FULL-SIZE flagship circuit on a 1-core host.

Determinism contract: identical proof bytes to `prove_host`/`prove_tpu`
for the same (witness, r, s) — differentially tested in
tests/test_native_prover.py.
"""

from __future__ import annotations

import ctypes
import secrets
import threading
from typing import Optional, Sequence

import numpy as np

from ..field.bn254 import (
    GLV_BETA,
    GLV_K1_TERMS,
    GLV_K2_TERMS,
    GLV_MAX_BITS,
    GLV_MU1,
    GLV_MU2,
    P,
    R,
    fr_domain_root,
    to_mont,
)
from ..field.tower import Fq2
from ..native.lib import _scalars_to_u64, get_lib
from ..snark.groth16 import Proof, coset_gen
from .groth16_tpu import DeviceProvingKey, _assemble, _check_inferred_widths

_u64p = ctypes.POINTER(ctypes.c_uint64)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_configured = False


def _lib():
    """Native library with the prover entry points configured (lazily —
    get_lib() already built and self-tested the .so)."""
    global _configured
    lib = get_lib()
    if lib is None:
        return None
    if not _configured:
        lib.fr_to_mont_batch.argtypes = [_u64p, _u64p, ctypes.c_long]
        lib.fr_from_mont_batch.argtypes = [_u64p, _u64p, ctypes.c_long]
        lib.fr_mul_batch.argtypes = [_u64p, _u64p, _u64p, ctypes.c_long]
        lib.fr_mul_std.argtypes = [_u64p, _u64p, _u64p]
        lib.fr_matvec.argtypes = [_u64p, _u32p, _u32p, ctypes.c_long, _u64p, ctypes.c_long, _u64p]
        lib.fr_ntt.argtypes = [_u64p, ctypes.c_long, _u64p, _u64p]
        lib.fr_h_ladder.argtypes = [_u64p, _u64p, _u64p, ctypes.c_long, _u64p, _u64p, _u64p]
        lib.g1_msm_pippenger.argtypes = [_u64p, _u64p, ctypes.c_long, ctypes.c_int, _u64p]
        lib.g2_msm_pippenger.argtypes = [_u64p, _u64p, ctypes.c_long, ctypes.c_int, _u64p]
        lib.g1_msm_pippenger_mt.argtypes = [_u64p, _u64p, ctypes.c_long, ctypes.c_int, ctypes.c_int, _u64p]
        lib.g2_msm_pippenger_mt.argtypes = [_u64p, _u64p, ctypes.c_long, ctypes.c_int, ctypes.c_int, _u64p]
        lib.g1_glv_phi_bases.argtypes = [_u64p, ctypes.c_long, _u64p, _u64p]
        lib.g1_msm_pippenger_glv_mt.argtypes = [
            _u64p, _u64p, ctypes.c_long, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            _u64p, ctypes.c_int, _u64p,
        ]
        lib.g1_msm_pippenger_multi.argtypes = [
            _u64p, _u64p, ctypes.c_long, ctypes.c_int, ctypes.c_int, ctypes.c_int, _u64p,
        ]
        lib.g1_msm_pippenger_glv_multi.argtypes = [
            _u64p, _u64p, ctypes.c_long, ctypes.c_long, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, _u64p, ctypes.c_int, _u64p,
        ]
        lib.fr_reduce_batch.argtypes = [_u64p, ctypes.c_long]
        # segmented matvec tier (prover.matvec_plan)
        lib.fr_matvec_pack52.argtypes = [_u64p, ctypes.c_long, _u64p]
        lib.fr_matvec_pack52.restype = ctypes.c_int
        lib.fr_matvec_seg.argtypes = [
            _u64p, _u64p, _u32p, ctypes.POINTER(ctypes.c_longlong), _u32p,
            ctypes.c_long, _u64p, ctypes.c_long, ctypes.c_int, _u64p,
        ]
        # fixed-base precomputed-window tier (prover.precomp)
        lib.g1_precomp_build.argtypes = [
            _u64p, ctypes.c_long, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, _u64p,
        ]
        lib.g1_precomp_to52.argtypes = [_u64p, ctypes.c_long, _u64p]
        lib.g1_precomp_to52.restype = ctypes.c_int
        lib.g1_msm_pippenger_fixed.argtypes = [
            _u64p, _u64p, _u64p, ctypes.c_long, ctypes.c_long, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, _u64p,
        ]
        lib.g1_msm_pippenger_fixed_multi.argtypes = [
            _u64p, _u64p, _u64p, ctypes.c_long, ctypes.c_long, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, _u64p,
        ]
        # Self-test the Fr multiplier before trusting proofs to it (the
        # same covenant native/lib.py applies to the Fq side).
        a, b = R - 987654321, 0xFEDCBA9876543210 << 128 | 0x42
        av = _scalars_to_u64([a]).copy()
        bv = _scalars_to_u64([b]).copy()
        cv = np.zeros((1, 4), dtype=np.uint64)
        lib.fr_mul_std(_p(av), _p(bv), _p(cv))
        if int.from_bytes(cv.tobytes(), "little") != a * b % R:
            raise RuntimeError("native fr_mul self-test failed")
        _configured = True
    return lib


def _p(a: np.ndarray):
    return a.ctypes.data_as(_u64p)


def _p32(a: np.ndarray):
    return a.ctypes.data_as(_u32p)


def _limbs16_to_u64(a: np.ndarray) -> np.ndarray:
    """(..., 16) u32 16-bit-limb layout (jfield) -> (..., 4) u64."""
    a = np.asarray(a)
    a16 = np.ascontiguousarray(a.astype(np.uint16))
    return a16.view("<u8").reshape(*a.shape[:-1], 4)


# Base conversions are pure functions of the (immutable) key arrays:
# memoized per AffPoint identity so a service proving many requests
# against one DeviceProvingKey converts each MSM's bases ONCE (at full
# size the five conversions cost seconds per proof otherwise).  Each
# entry pins the source arrays, so an id() key cannot be reused while
# its entry is alive; a small cap bounds test-suite churn.  Guarded by a
# lock: the stage task-graph converts the a/b1/b2/c bases from worker
# threads concurrently, and a racing evict+insert must not corrupt the
# dict (worst case under the lock is a duplicate convert, never a wrong
# entry).
_bases_cache: dict = {}
_BASES_CACHE_CAP = 16
_bases_lock = threading.Lock()


def _bases_memo(bases, convert, tag: str = ""):
    key = (id(bases[0]), id(bases[1]), tag)
    with _bases_lock:
        hit = _bases_cache.get(key)
        if hit is not None and hit[0] is bases[0] and hit[1] is bases[1]:
            return hit[2]
    out = convert(bases)
    with _bases_lock:
        if len(_bases_cache) >= _BASES_CACHE_CAP:
            _bases_cache.pop(next(iter(_bases_cache)))
        _bases_cache[key] = (bases[0], bases[1], out)
    return out


def _g1_bases_u64(bases) -> np.ndarray:
    """AffPoint ((n,16),(n,16)) Montgomery limbs -> (n, 8) u64."""

    def convert(b):
        x, y = (np.asarray(c) for c in b)
        return np.ascontiguousarray(
            np.concatenate([_limbs16_to_u64(x), _limbs16_to_u64(y)], axis=-1)
        )

    return _bases_memo(bases, convert)


_glv_consts_arr: Optional[np.ndarray] = None


def _glv_consts() -> np.ndarray:
    """GLV constants packed for the C runtime (csrc glv_split layout):
    beta (Montgomery), the two Barrett mus, the four lattice-term
    magnitudes, and the subtract-flag word — all DERIVED in field.bn254,
    nothing hardcoded on either side."""
    global _glv_consts_arr
    if _glv_consts_arr is None:
        mask = (1 << 64) - 1

        def u64x4(v: int):
            return [(v >> (64 * i)) & mask for i in range(4)]

        flags = 0
        mags = []
        for j, (mag, sub) in enumerate(GLV_K1_TERMS):
            mags += u64x4(mag)
            flags |= int(sub) << j
        for j, (mag, sub) in enumerate(GLV_K2_TERMS):
            mags += u64x4(mag)
            flags |= int(sub) << (2 + j)
        _glv_consts_arr = np.array(
            u64x4(to_mont(GLV_BETA, P)) + u64x4(GLV_MU1) + u64x4(GLV_MU2) + mags + [flags],
            dtype=np.uint64,
        )
    return _glv_consts_arr


def _g1_bases_glv_u64(bases) -> np.ndarray:
    """AffPoint Montgomery limbs -> the GLV-doubled (2n, 8) u64 base set
    [P, phi(P)] (csrc g1_glv_phi_bases).  Key-dependent only: memoized
    beside the plain conversion so a service pays the n Fq muls once."""

    def convert(b):
        plain = _g1_bases_u64(b)
        n = plain.shape[0]
        phi = np.zeros_like(plain)
        _lib().g1_glv_phi_bases(_p(plain), n, _p(_glv_consts()), _p(phi))
        return np.ascontiguousarray(np.concatenate([plain, phi]))

    return _bases_memo(bases, convert, tag="glv")


def _use_glv() -> bool:
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_msm_glv", load_config().msm_glv)


def _use_batch_affine() -> bool:
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_batch_affine", load_config().msm_batch_affine)


def _use_msm_multi() -> bool:
    """Cross-proof multi-column MSM gate (ZKP2P_MSM_MULTI, default ON):
    prove_native_batch issues each G1 MSM family as ONE multi-column
    Pippenger call across the batch; =0 falls back to sequential
    per-proof proves — the byte-parity oracle arm.  Fresh-read per batch
    and record_arm-audited, so A/B digests distinguish the arms."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_msm_multi", load_config().msm_multi)


def _use_msm_precomp() -> bool:
    """Fixed-base precomputed-window MSM gate (ZKP2P_MSM_PRECOMP,
    default ON): the frozen G1 families prove from offline level tables
    (prover.precomp) instead of re-running the GLV split + base
    conversion + variable-base fill; =0 falls back to the existing
    drivers — the byte-parity oracle arm.  Fresh-read per prove and
    record_arm-audited, so A/B digests distinguish the arms."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_msm_precomp", load_config().msm_precomp)


def _use_matvec_seg() -> bool:
    """Segmented-plan matvec gate (ZKP2P_MATVEC_SEG, default ON): the
    A/B matvecs run through the presorted per-key segment plan
    (prover.matvec_plan + csrc fr_matvec_seg — 8-wide IFMA products,
    pool-parallel conflict-free segments); =0 falls back to the scatter
    oracle `fr_matvec` — the byte-parity arm.  Fresh-read per prove and
    record_arm-audited, so A/B digests distinguish the arms."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_matvec_seg", load_config().matvec_seg)


def _use_msm_overlap() -> bool:
    """Stage task-graph gate (ZKP2P_MSM_OVERLAP, default ON): the
    witness-dependent MSMs run on worker threads overlapping the H
    ladder; =0 runs the strict sequential schedule — the byte-parity
    arm.  Fresh-read per prove and record_arm-audited (the one armable
    knob that historically lacked an arm record: a flip was invisible
    to the execution digest until zkp2p-lint's gate-arm rule caught
    it)."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_msm_overlap", load_config().msm_overlap)


def _ntt_pool_arm() -> bool:
    """NTT stage-pool + fused-ladder gate (ZKP2P_NTT_POOL, default ON).
    The arm itself is resolved IN the C runtime (fresh getenv per
    ladder/NTT call, like ZKP2P_MSM_BATCH_AFFINE); this mirror records
    it into the execution digest so pool-NTT A/Bs are
    digest-distinguishable.  apply_env keeps the env and the typed
    config coherent, so the recorded arm is the arm C takes."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_ntt_pool", load_config().ntt_pool)


def _msm_interleave_arm() -> bool:
    """MSM apply interleave gate (ZKP2P_MSM_INTERLEAVE, default ON).
    Resolved IN the C runtime (fresh getenv per apply/window-sum call):
    =1 runs the batched affine apply as two independent chunk groups
    through one mont52_mul8x2 register schedule plus software prefetch
    down the known bucket/point schedules; =0 is the single-chain
    byte-parity oracle arm.  This mirror records the arm into the
    execution digest (docs/NEXT.md lever 4)."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_msm_interleave", load_config().msm_interleave)


def _ntt_radix8_arm() -> bool:
    """NTT radix-8 pass gate (ZKP2P_NTT_RADIX8, default OFF on narrow
    hosts — measured 0.95x at 2^19 on the 1-core box, see
    docs/TUNING.md).  Resolved IN the C runtime (fresh getenv per
    stage-batch call): =1 fuses three butterfly stages per load/store
    pass in fr_ntt_soa_stages; unset/=0 keeps the radix-4 pairs — the
    byte-parity oracle arm.  Mirror-recorded into the execution digest
    (docs/NEXT.md lever 2)."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_ntt_radix8", load_config().ntt_radix8)


def _use_witness_u64() -> bool:
    """Witness-at-builder gate (ZKP2P_WITNESS_U64, default ON): when the
    witness object carries a build-time standard-form `u64` array
    (snark.r1cs.Witness / WitnessRow), the witness_convert stage hands
    it off instead of re-serializing Python ints every prove; =0 (or a
    plain witness sequence) re-serializes — the byte-parity oracle arm
    (docs/NEXT.md lever 3).  Fresh-read per prove and record_arm-audited
    so A/B digests distinguish the arms."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("native_witness_u64", load_config().witness_u64)


# ONE process-wide executor for the prover's Python-side task graphs
# (stage overlap + oracle-arm matvec jobs).  The per-prove, per-matvec
# `ThreadPoolExecutor(max_workers=2)` constructions this replaces
# spawned and joined 2-6 threads per proof — tens of thread spawns per
# batch, pure overhead on the hot path (tests/test_nonmsm.py counts
# constructions per batch now).  Sized for the widest acyclic task set:
# 4 overlap tasks + 2 oracle matvec leaves; leaves are only ever
# submitted from the MAIN thread, so the graph cannot deadlock on pool
# exhaustion.
_executor = None
_executor_lock = threading.Lock()


def _shared_executor():
    global _executor
    with _executor_lock:
        if _executor is None:
            from concurrent.futures import ThreadPoolExecutor

            _executor = ThreadPoolExecutor(
                max_workers=6, thread_name_prefix="zkp2p-native"
            )
        return _executor


def _witness_std_u64(
    lib, witness: Sequence[int], fast: bool = False, builder_u64: bool = False
) -> np.ndarray:
    """Witness ints -> standard-form (n, 4) u64 MSM scalars, reduced
    mod r IN THE NATIVE LIBRARY (docs/NEXT.md lever 3): raw 256-bit
    serialization here, `fr_reduce_batch` there — the per-element
    Python `w % R` this replaces was ~half the witness_convert stage.
    Values a 256-bit window cannot hold (negative or >= 2^256 — no
    in-tree witness builder emits them) fall back to the exact Python
    reduction.

    builder_u64=True (the ZKP2P_WITNESS_U64 arm): a witness built by
    snark.r1cs already carries its standard-form serialization (`u64`
    attribute, emitted at build time from the same bulk/exact split),
    so the whole stage collapses to an array hand-off.  The arm is
    resolved by the caller per prove, so an in-process A/B exercises
    both paths on the identical witness object; a plain sequence (no
    `u64`) falls through to the serializing arms regardless.

    fast=True (the ZKP2P_MATVEC_SEG arm — witness-side leg of the same
    vectorized-floor tier, so the knob-off arm reproduces the full
    pre-tier path): real witnesses are overwhelmingly sub-64-bit wires
    (99.2% on the venmo shape — bits, bytes, bignum limbs), so chunks
    bulk-assign into the u64 column at numpy C speed (already < r, no
    reduction needed); a chunk holding any >= 2^64 value raises
    OverflowError and takes the exact serialize+reduce path for that
    chunk alone.  Byte-identical to the slow path by construction
    (pinned in tests/test_nonmsm.py)."""
    n = len(witness)
    if builder_u64:
        u = getattr(witness, "u64", None)
        if u is not None and getattr(u, "shape", None) == (n, 4):
            return np.ascontiguousarray(u)
    if fast and n:
        try:
            arr = np.zeros((n, 4), dtype=np.uint64)
            col = arr[:, 0]
            CH = 8192
            for lo in range(0, n, CH):
                hi = min(n, lo + CH)
                chunk = witness[lo:hi]
                try:
                    col[lo:hi] = chunk  # raises on >= 2^64 / negative / non-int
                except (OverflowError, TypeError, ValueError):
                    sub = np.frombuffer(
                        b"".join(int(w).to_bytes(32, "little") for w in chunk),
                        dtype="<u8",
                    ).reshape(hi - lo, 4)
                    view = arr[lo:hi]
                    view[:] = sub
                    lib.fr_reduce_batch(_p(view), hi - lo)
            return arr
        except (OverflowError, ValueError, TypeError):
            pass  # exotic values (negative / >= 2^256) or a non-sliceable
            # sequence: the exact paths below handle them
    try:
        buf = b"".join(int(w).to_bytes(32, "little") for w in witness)
    except (OverflowError, ValueError):
        return np.ascontiguousarray(_scalars_to_u64([w % R for w in witness]))
    arr = np.frombuffer(buf, dtype="<u8").reshape(len(witness), 4).copy()
    lib.fr_reduce_batch(_p(arr), arr.shape[0])
    return np.ascontiguousarray(arr)


def _native_ifma_tier() -> bool:
    """The 52-bit AVX512-IFMA batch-affine tier gate for G1 windows —
    the native mirror of the device prover's impl gates, reported to the
    execution audit per consultation (one per MSM via _pick_window).
    False routes through the scalar Montgomery tier."""
    from ..native.lib import ifma_available
    from ..utils.audit import record_arm

    v = _use_batch_affine() and ifma_available()
    record_arm("native_tier", "ifma" if v else "scalar")
    return v


def _g2_bases_u64(bases) -> np.ndarray:
    """AffPoint ((n,2,16),(n,2,16)) -> (n, 16) u64 (x.c0 x.c1 y.c0 y.c1)."""

    def convert(b):
        x, y = (np.asarray(c) for c in b)
        n = x.shape[0]
        return np.ascontiguousarray(
            np.concatenate(
                [_limbs16_to_u64(x).reshape(n, 8), _limbs16_to_u64(y).reshape(n, 8)], axis=-1
            )
        )

    return _bases_memo(bases, convert)


def _u64x4_to_int_arr(a: np.ndarray) -> list:
    """(k, 4) u64 -> python ints."""
    return [int.from_bytes(a[i].tobytes(), "little") for i in range(a.shape[0])]


def _tuned_window(tag: str, bl: int, threads: int):
    """Host-profile window resolution for the variable-base G1 curves
    (the tune window arm, APPLIED — docs/NEXT.md §1): the measured-best
    c when the profile recorded one at this exact (shape, threads)
    context, else None -> the committed curve below.  A tuned value
    bypasses the multi-thread clamp: the sweep measured it AT that
    thread count, so the clamp's serial-suffix reasoning is already in
    the number.  The source is recorded per consultation (the precomp
    manifest's geometry_source discipline, on the audit rail) so a
    profile-resolved prove never shares a digest with a curve-resolved
    one."""
    from ..utils.audit import record_arm
    from ..utils.hostprof import tuned_window

    c = tuned_window(tag, bl, threads)
    record_arm("window_source", "profile" if c is not None else "fallback")
    return c


def _pick_window(n: int, g2: bool = False, threads: int = 1) -> int:
    """Pippenger window: ~log2(n) - 4 with SIGNED digits — the signed
    recoding halves the bucket count at a given c, so the sweet spot
    sits one window wider than the unsigned sweep (n=2^19: unsigned
    c=13 3.49s, c=15 3.34s, c=16 3.52s) — same bucket count and
    chunk-conflict rate as unsigned c-1, one fewer window of fill adds.
    At full size (2^23) signed c=16 regressed the prove 125.6->138.7 s
    purely from doubled batch-affine conflicts; the raised clamp lets
    the big domains reach c=17 while the bench shape keeps its
    measured-best c=15 (signed sweep at 2^19: c=15 6.3s, c=16 7.6s)."""
    if not g2 and _native_ifma_tier():  # batch-affine off: wide-window curve n/a
        tuned = _tuned_window("plain", n.bit_length(), threads)
        if tuned is not None:
            return tuned
        # IFMA regime (G1 only) with the 8-lane vector suffix (csrc
        # g1_suffix8): the serial per-window reduction that clamped the
        # r5 sweep at c=14 is vectorized across windows, so wider
        # windows win again (fill scales with ceil(254/c)).  Measured
        # on the vector-suffix build, random full-width scalars:
        #   2^15: c15 166 ms vs c14 189;  2^17: c15 404 vs c14 495;
        #   2^19: c16 1456 vs c14 1808 (c17 equal — keep the smaller).
        # The vector suffix only engages SINGLE-threaded (csrc gates the
        # deferred-bucket pass on n_threads <= 1: each worker already
        # runs its own serial suffix concurrently) — so multi-threaded
        # runs keep the r5 serial-suffix optimum of c=14 instead of
        # paying a 4x longer per-window serial tail at c=15/16
        # (ADVICE r5 #1).  The whole IFMA curve also rides the
        # batch-affine tier: with ZKP2P_MSM_BATCH_AFFINE=0 (the
        # Jacobian A/B arm) both the 52-limb fill and the vector suffix
        # are gated off, so the generic curve below applies instead.
        bl = n.bit_length()
        if bl >= 20:
            c = 16
        elif bl >= 16:  # sweep coverage starts at 2^15; below it the old curve
            c = 15
        else:
            c = max(4, bl - 5)
        return min(c, 14) if threads > 1 else c
    return max(4, min(17, n.bit_length() - 5))


def _pick_window_glv(n: int, threads: int = 1) -> int:
    """Pippenger window for the GLV shape: 2n points of ~129-bit
    half-scalars, nwin = ceil((GLV_MAX_BITS+1)/c).  Swept on the IFMA
    build (min-of-reps, random full-width scalars, GLV arm):
      2^15: c16 225 ms vs c15 253 / c17 533
      2^17: c16 796 vs c15 933
      2^19: c15 3173 vs c16 4383 — at 2^19 the c=16 deferred-suffix
            bucket block (nwin x 2^15 x 80 B = 23 MB) falls out of LLC,
            so the curve steps DOWN a window at the domain shape.
    Multi-threaded keeps the same c=14 serial-suffix clamp as the plain
    curve (the vector suffix is gated off there)."""
    bl = (2 * n).bit_length()
    if _native_ifma_tier():
        tuned = _tuned_window("glv", bl, threads)
        if tuned is not None:
            return tuned
        if bl >= 20:
            c = 15
        elif bl >= 14:
            c = 16
        else:
            c = max(4, bl - 5)
        return min(c, 14) if threads > 1 else c
    return max(4, min(17, bl - 5))


def _pick_window_multi(n: int, S: int, threads: int, glv: bool) -> int:
    """Window for the MULTI-COLUMN drivers.  The single-column curves
    apply unchanged: the S-wide bucket block (S x nbuckets x 80 B per
    window) argues for NARROWER windows, the shared inversion rounds
    for wider ones, and the interleaved prove A/B measured the existing
    threads-clamped curves best on the driver box (a wide-window sweep
    with the t=1 curve + vector suffix at threads=2 regressed the
    whole batch ~15% — see the csrc multi-core comment).  Kept as a
    separate hook so a box with a bigger LLC can retune multi alone."""
    del S
    return _pick_window_glv(n, threads=threads) if glv else _pick_window(n, threads=threads)


def _n_threads() -> int:
    """MSM worker threads: the typed config's native_threads
    (ZKP2P_NATIVE_THREADS) always wins; unset, the tuned host profile's
    topology-aware default applies when one is loaded (physical cores,
    not SMT siblings — the measured-best width from `zkp2p-tpu tune`);
    else the logical core count as before — the parallel axis is
    per-window (rapidsnark's split); on the 1-core build host this
    resolves to 1 and the code path stays sequential."""
    import os

    from ..utils.config import load_config
    from ..utils.hostprof import tuned_threads

    v = load_config().native_threads
    if v:
        return v
    t = tuned_threads()  # records the host_profile gate
    return t if t else max(1, os.cpu_count() or 1)


def _run_matvecs(lib, dpk, w_mont: np.ndarray, m: int, threads: int, a_ev, b_ev, plans):
    """The A/B QAP matvecs into a_ev/b_ev.  With a segment plan armed,
    each matrix is ONE `fr_matvec_seg` call — 8-wide IFMA products,
    segments partitioned across the C pool with no scatter conflicts
    (the pool is the parallel axis; no Python threads needed).  The
    oracle arm keeps the scatter `fr_matvec` with the two matrices on
    the shared executor."""
    if plans is not None:
        for matrix, out in (("a", a_ev), ("b", b_ev)):
            p52, pcf, pwi, pss, psr, nseg = plans[matrix].pointers()
            lib.fr_matvec_seg(
                p52, pcf, pwi, pss, psr, nseg, _p(w_mont), m, threads, _p(out)
            )
        return

    def matvec(coeff, wire, row, out):
        cf = _bases_memo(
            (coeff, coeff),
            lambda b: np.ascontiguousarray(_limbs16_to_u64(np.asarray(b[0]))),
        )
        wi = np.ascontiguousarray(np.asarray(wire, dtype=np.uint32))
        ro = np.ascontiguousarray(np.asarray(row, dtype=np.uint32))
        lib.fr_matvec(_p(cf), _p32(wi), _p32(ro), cf.shape[0], _p(w_mont), m, _p(out))

    jobs = [
        (dpk.a_coeff, dpk.a_wire, dpk.a_row, a_ev),
        (dpk.b_coeff, dpk.b_wire, dpk.b_row, b_ev),
    ]
    if threads > 1:
        # futures, not bare Threads: a worker exception must abort the
        # prove, not leave a zeroed evaluation vector behind.  Shared
        # executor — the per-matvec ThreadPoolExecutor construction this
        # replaces spawned threads on every proof.
        ex = _shared_executor()
        for f in [ex.submit(matvec, *j) for j in jobs]:
            f.result()
    else:
        for j in jobs:
            matvec(*j)


def _seg_plans(dpk):
    """The memoized segment plans when ZKP2P_MATVEC_SEG arms (and the
    native lib is up); None otherwise — callers fall back to the
    scatter oracle."""
    if not _use_matvec_seg():
        return None
    from .matvec_plan import plans_for

    return plans_for(dpk)


def prove_native(
    dpk: DeviceProvingKey,
    witness: Sequence[int],
    r: Optional[int] = None,
    s: Optional[int] = None,
) -> Proof:
    """Prove with the native C++ runtime.  Emits the exact proof
    `prove_host` / `prove_tpu` produce for the same (witness, r, s)."""
    from ..utils.faults import fault_point
    from ..utils.trace import trace

    # chaos/fault-injection site for the CLI/bench prove path (the
    # service's batch prove has its own `prove` site one level up) —
    # a single env-read no-op when ZKP2P_FAULTS is unset
    fault_point("native_prove")
    lib = _lib()
    if lib is None:
        raise RuntimeError("native library unavailable (csrc build failed?)")
    if r is None:
        r = 1 + secrets.randbelow(R - 1)
    if s is None:
        s = 1 + secrets.randbelow(R - 1)
    m = 1 << dpk.log_m
    threads = _n_threads()
    plans = _seg_plans(dpk)  # memoized; resolves the matvec_seg gate
    _ntt_pool_arm()  # C-side gate; recorded here for the digest
    _msm_interleave_arm()  # C-side gate; recorded here for the digest
    _ntt_radix8_arm()  # C-side gate; recorded here for the digest
    wit_u64 = _use_witness_u64()

    # Witness: standard-form u64x4 (MSM scalars) + Montgomery (matvec).
    with trace("native/witness_convert"):
        w_std = _witness_std_u64(
            lib, witness, fast=plans is not None, builder_u64=wit_u64
        )
        n_wires = w_std.shape[0]
        # inferred-width guard, vectorized over the limb view
        _check_inferred_widths(dpk, witness, w_std=w_std)
        w_mont = np.zeros_like(w_std)
        lib.fr_to_mont_batch(_p(w_std), _p(w_mont), n_wires)

    # Az/Bz/Cz evaluations on the domain (Cz = Az . Bz pointwise, valid
    # for a satisfying witness — same shortcut as abc_evals).  The A and
    # B matvecs are independent and ctypes releases the GIL, so they run
    # on two Python threads when the host has cores.
    a_ev = np.zeros((m, 4), dtype=np.uint64)
    b_ev = np.zeros((m, 4), dtype=np.uint64)
    c_ev = np.zeros((m, 4), dtype=np.uint64)
    with trace("native/matvec"):
        _run_matvecs(lib, dpk, w_mont, m, threads, a_ev, b_ev, plans)
        lib.fr_mul_batch(_p(a_ev), _p(b_ev), _p(c_ev), m)

    b_sel = np.asarray(dpk.b_sel)
    c_sel = np.asarray(dpk.c_sel)

    glv = _use_glv()
    # Fixed-base precomputed tables for the frozen G1 families: resolved
    # ONCE per key (built or cache-loaded on first prove), then each
    # family's MSM is pure digit scatter + gather/add — the GLV split
    # and base conversion leave the hot loop entirely.  Families the
    # budget guard skipped fall through to the variable-base path below.
    from .precomp import precomputed_for

    ptables = precomputed_for(dpk) if _use_msm_precomp() else None

    def msm_g1(bases, scalars: np.ndarray, tag: str):
        fam = ptables.families.get(tag) if ptables is not None else None
        with trace(f"native/msm_{tag}"):
            out = np.zeros(8, dtype=np.uint64)
            if fam is not None:
                n = min(fam.n, scalars.shape[0])
                sc = np.ascontiguousarray(scalars[:n])
                lib.g1_msm_pippenger_fixed(
                    _p(fam.table), fam.p52(), _p(sc), n, fam.n, fam.levels,
                    fam.c, fam.q, threads, _p(out),
                )
            elif glv:
                b = _g1_bases_glv_u64(bases)
                nb = b.shape[0] // 2  # phi half offset in the cached doubled set
                n = min(nb, scalars.shape[0])
                sc = np.ascontiguousarray(scalars[:n])
                c = _pick_window_glv(n, threads=threads)
                lib.g1_msm_pippenger_glv_mt(
                    _p(b), _p(sc), n, nb, c, threads, _p(_glv_consts()), GLV_MAX_BITS, _p(out)
                )
            else:
                b = _g1_bases_u64(bases)
                n = min(b.shape[0], scalars.shape[0])
                sc = np.ascontiguousarray(scalars[:n])
                lib.g1_msm_pippenger_mt(
                    _p(b), _p(sc), n, _pick_window(n, threads=threads), threads, _p(out)
                )
        x, y = _u64x4_to_int_arr(out.reshape(2, 4))
        return None if x == 0 and y == 0 else (x, y)

    def msm_g2(bases, scalars: np.ndarray, tag: str):
        with trace(f"native/msm_{tag}"):
            b = _g2_bases_u64(bases)
            n = min(b.shape[0], scalars.shape[0])
            sc = np.ascontiguousarray(scalars[:n])
            out = np.zeros(16, dtype=np.uint64)
            lib.g2_msm_pippenger_mt(_p(b), _p(sc), n, _pick_window(n, g2=True), threads, _p(out))
        xc0, xc1, yc0, yc1 = _u64x4_to_int_arr(out.reshape(4, 4))
        if xc0 == xc1 == yc0 == yc1 == 0:
            return None
        return (Fq2(xc0, xc1), Fq2(yc0, yc1))

    def h_ladder_and_d():
        # H ladder: d_j = (A.B - C)(g . w^j), Montgomery -> std scalars.
        d = np.zeros((m, 4), dtype=np.uint64)
        with trace("native/h_ladder"):
            w_root = _scalars_to_u64([fr_domain_root(dpk.log_m)]).copy()
            g_cos = _scalars_to_u64([coset_gen(dpk.log_m)]).copy()
            lib.fr_h_ladder(_p(a_ev), _p(b_ev), _p(c_ev), m, _p(w_root), _p(g_cos), _p(d))
            d_std = np.zeros_like(d)
            lib.fr_from_mont_batch(_p(d), _p(d_std), m)
        return d_std

    # Stage task-graph (ZKP2P_MSM_OVERLAP, default on): the a/b1/b2/c
    # MSMs depend only on the witness scalars, while msm_h sits behind
    # the H ladder — so the four independent MSMs run on worker threads
    # (ctypes releases the GIL; the C pool's per-region width caps bound
    # total MSM-window concurrency) and OVERLAP the ladder and msm_h on
    # this thread instead of queuing behind them.  Gated on threads > 1:
    # a ZKP2P_NATIVE_THREADS=1 pin means "at most one busy core", and
    # Python-level concurrency would quietly break that promise.
    # Results are gathered in the fixed assembly order, so proof bytes
    # are identical to the sequential schedule (pinned by
    # tests/test_msm_native_edge.py parity).
    if _use_msm_overlap() and threads > 1:
        from ..utils.trace import adopt_context, adopt_stack, current_context, current_stack

        # worker-thread trace records keep this thread's stage prefix
        # (e.g. bench.py's prove_native_N span) — without it the four
        # submitted MSMs log under a bare root and per-rep stage
        # attribution in the bench trace is lost.  The ambient context
        # (the service's request_id) rides along the same way.
        stack = current_stack()
        ctx = current_context()

        def seeded(fn, *fargs):
            adopt_stack(stack)
            adopt_context(ctx)
            return fn(*fargs)

        ex = _shared_executor()
        fut_a = ex.submit(seeded, msm_g1, dpk.a_bases, w_std, "a")
        fut_b1 = ex.submit(seeded, msm_g1, dpk.b1_bases, np.ascontiguousarray(w_std[b_sel]), "b1")
        fut_b2 = ex.submit(seeded, msm_g2, dpk.b2_bases, np.ascontiguousarray(w_std[b_sel]), "b2")
        fut_c = ex.submit(seeded, msm_g1, dpk.c_bases, np.ascontiguousarray(w_std[c_sel]), "c")
        d_std = h_ladder_and_d()
        h_acc = msm_g1(dpk.h_bases, d_std, "h")
        a_acc, b1_acc, b2_acc, c_acc = (
            fut_a.result(), fut_b1.result(), fut_b2.result(), fut_c.result()
        )
    else:
        d_std = h_ladder_and_d()
        a_acc = msm_g1(dpk.a_bases, w_std, "a")
        b1_acc = msm_g1(dpk.b1_bases, np.ascontiguousarray(w_std[b_sel]), "b1")
        b2_acc = msm_g2(dpk.b2_bases, np.ascontiguousarray(w_std[b_sel]), "b2")
        c_acc = msm_g1(dpk.c_bases, np.ascontiguousarray(w_std[c_sel]), "c")
        h_acc = msm_g1(dpk.h_bases, d_std, "h")
    proof = _assemble(dpk, (a_acc, b1_acc, b2_acc, c_acc, h_acc), r, s)
    # publish into the process registry: prove count + a refresh of the
    # native runtime's counter block (one ctypes read of ~20 slots —
    # noise next to a prove), so a Prometheus scrape or the service's
    # per-sweep flush always sees current MSM/pool stats
    from ..utils.metrics import REGISTRY, publish_native_stats

    REGISTRY.counter("zkp2p_proves_total", {"prover": "native"}).inc()
    publish_native_stats()
    return proof


def prove_native_batch(
    dpk: DeviceProvingKey,
    witnesses: Sequence[Sequence[int]],
    rs: Optional[Sequence[int]] = None,
    ss: Optional[Sequence[int]] = None,
) -> list:
    """Prove a whole batch with the native runtime, amortizing the fixed
    proving-key bases across proofs: witness-convert / matvec / H-ladder
    run per proof, but each of the four G1 MSM families (a, b1, c, h) is
    issued as ONE multi-column Pippenger call — one base sweep, S scalar
    columns, batch-affine inversion rounds shared across columns (csrc
    g1_msm_pippenger_multi).  The G2 b2 MSM stays per proof (no
    multi-column G2 tier yet).  Gated by ZKP2P_MSM_MULTI (default ON);
    off — or S <= 1 — falls back to sequential `prove_native` calls,
    which remain the byte-parity oracle: every proof here is
    byte-identical to its sequential counterpart for the same
    (witness, r, s), pinned by tests/test_msm_multi.py."""
    from ..utils.faults import fault_point
    from ..utils.trace import trace

    fault_point("native_prove")
    lib = _lib()
    if lib is None:
        raise RuntimeError("native library unavailable (csrc build failed?)")
    S = len(witnesses)
    if S == 0:
        return []
    rs = list(rs) if rs is not None else [1 + secrets.randbelow(R - 1) for _ in range(S)]
    ss = list(ss) if ss is not None else [1 + secrets.randbelow(R - 1) for _ in range(S)]
    if len(rs) != S or len(ss) != S:
        raise ValueError(f"prove_native_batch: {S} witnesses but {len(rs)}/{len(ss)} blinds")
    if not _use_msm_multi() or S == 1:
        return [prove_native(dpk, w, r=r, s=s) for w, r, s in zip(witnesses, rs, ss)]

    m = 1 << dpk.log_m
    threads = _n_threads()
    glv = _use_glv()
    b_sel = np.asarray(dpk.b_sel)
    c_sel = np.asarray(dpk.c_sel)

    # Resolved once per batch (not per proof): the segment plans + both
    # arm recordings — ladder constants are hoisted further down.
    plans = _seg_plans(dpk)
    _ntt_pool_arm()
    _msm_interleave_arm()
    _ntt_radix8_arm()
    wit_u64 = _use_witness_u64()

    # Phase 1: witness conversion for EVERY proof first — it is cheap
    # and unlocks all three witness-column multi MSMs (a/b1/c) plus the
    # per-proof b2 G2 MSMs, which the overlap arm below launches before
    # the expensive per-proof matvec/H-ladder work runs on this thread.
    w_cols, w_monts = [], []
    for witness in witnesses:
        with trace("native/witness_convert"):
            w_std = _witness_std_u64(
                lib, witness, fast=plans is not None, builder_u64=wit_u64
            )
            n_wires = w_std.shape[0]
            _check_inferred_widths(dpk, witness, w_std=w_std)
            w_mont = np.zeros_like(w_std)
            lib.fr_to_mont_batch(_p(w_std), _p(w_mont), n_wires)
        w_cols.append(w_std)
        w_monts.append(w_mont)

    # Hoisted out of the per-proof ladder loop: the domain root and
    # coset generator are key-shape constants, yet were re-derived (a
    # Python bigint pow chain each) S times per batch.
    w_root = _scalars_to_u64([fr_domain_root(dpk.log_m)]).copy()
    g_cos = _scalars_to_u64([coset_gen(dpk.log_m)]).copy()

    # Concurrency cap for the pipelined d-column tasks: each live
    # ladder body holds ~5 m-row buffers (a/b/c/d/d_std) plus the fused
    # ladder's 5-plane SoA scratch — letting all 6 executor workers run
    # ladders would multiply transient memory ~6x over the old serial
    # walk (≈8 GB at 2^23).  Two concurrent bodies keep the b2-overlap
    # win while bounding the peak at ~2x serial; the gate is INSIDE the
    # task so a capped task parks its worker, never deadlocks (the
    # tasks holding the permits always progress and release).
    d_gate = threading.Semaphore(2)

    def ladder_one_col(w_mont):
        # one proof: A/B matvecs, Cz = Az . Bz, H ladder -> d column
        # (evaluation buffers freed on return)
        with d_gate:
            a_ev = np.zeros((m, 4), dtype=np.uint64)
            b_ev = np.zeros((m, 4), dtype=np.uint64)
            c_ev = np.zeros((m, 4), dtype=np.uint64)
            with trace("native/matvec"):
                _run_matvecs(lib, dpk, w_mont, m, threads, a_ev, b_ev, plans)
                lib.fr_mul_batch(_p(a_ev), _p(b_ev), _p(c_ev), m)
            with trace("native/h_ladder"):
                d = np.zeros((m, 4), dtype=np.uint64)
                lib.fr_h_ladder(_p(a_ev), _p(b_ev), _p(c_ev), m, _p(w_root), _p(g_cos), _p(d))
                d_std = np.zeros_like(d)
                lib.fr_from_mont_batch(_p(d), _p(d_std), m)
            return d_std

    def ladder_cols():
        return [ladder_one_col(w_mont) for w_mont in w_monts]

    # Phase 2: the MSMs.  a/b1/c/h each ride ONE multi-column call over
    # the fixed (memoized) bases; b2 stays a per-proof G2 MSM.  With
    # precomp armed, a family's call is the fixed-table multi driver —
    # S digit scatters over ONE persistent table, sharing the same
    # batch-affine inversion rounds the variable-base multi path built.
    from .precomp import precomputed_for

    ptables = precomputed_for(dpk) if _use_msm_precomp() else None

    def msm_g1_multi(bases, cols, tag: str):
        fam = ptables.families.get(tag) if ptables is not None else None
        with trace(f"native/msm_{tag}", cols=len(cols)):
            out = np.zeros((S, 8), dtype=np.uint64)
            if fam is not None:
                n = min(fam.n, cols[0].shape[0])
                sc = np.ascontiguousarray(np.stack([np.asarray(col[:n]) for col in cols]))
                lib.g1_msm_pippenger_fixed_multi(
                    _p(fam.table), fam.p52(), _p(sc), n, fam.n, S, fam.levels,
                    fam.c, fam.q, threads, _p(out),
                )
            elif glv:
                b = _g1_bases_glv_u64(bases)
                nb = b.shape[0] // 2
                n = min(nb, cols[0].shape[0])
                sc = np.ascontiguousarray(np.stack([np.asarray(col[:n]) for col in cols]))
                c = _pick_window_multi(n, S, threads, glv=True)
                lib.g1_msm_pippenger_glv_multi(
                    _p(b), _p(sc), n, nb, S, c, threads,
                    _p(_glv_consts()), GLV_MAX_BITS, _p(out),
                )
            else:
                b = _g1_bases_u64(bases)
                n = min(b.shape[0], cols[0].shape[0])
                sc = np.ascontiguousarray(np.stack([np.asarray(col[:n]) for col in cols]))
                lib.g1_msm_pippenger_multi(
                    _p(b), _p(sc), n, S, _pick_window_multi(n, S, threads, glv=False),
                    threads, _p(out)
                )
        res = []
        for s in range(S):
            x, y = _u64x4_to_int_arr(out[s].reshape(2, 4))
            res.append(None if x == 0 and y == 0 else (x, y))
        return res

    def msm_g2_one(bases, scalars: np.ndarray, tag: str):
        with trace(f"native/msm_{tag}"):
            b = _g2_bases_u64(bases)
            n = min(b.shape[0], scalars.shape[0])
            sc = np.ascontiguousarray(scalars[:n])
            out = np.zeros(16, dtype=np.uint64)
            lib.g2_msm_pippenger_mt(_p(b), _p(sc), n, _pick_window(n, g2=True), threads, _p(out))
        xc0, xc1, yc0, yc1 = _u64x4_to_int_arr(out.reshape(4, 4))
        if xc0 == xc1 == yc0 == yc1 == 0:
            return None
        return (Fq2(xc0, xc1), Fq2(yc0, yc1))

    b_cols = [np.ascontiguousarray(w[b_sel]) for w in w_cols]
    c_cols = [np.ascontiguousarray(w[c_sel]) for w in w_cols]
    if _use_msm_overlap() and threads > 1:
        # Same stage task-graph contract as prove_native, one level up:
        # everything witness-dependent — the three witness-column multi
        # MSMs and the S per-proof G2 MSMs — runs on worker threads
        # (ctypes releases the GIL; the C pool's region width caps bound
        # window concurrency) while the per-proof matvec/H-ladder
        # pipeline produces d columns, then the h multi MSM (which sits
        # behind ALL of them) runs on this thread.  Assembly order stays
        # fixed, so proof bytes match the sequential schedule.
        from ..utils.trace import adopt_context, adopt_stack, current_context, current_stack

        stack = current_stack()
        ctx = current_context()

        def seeded(fn, *fargs):
            adopt_stack(stack)
            adopt_context(ctx)
            return fn(*fargs)

        ex = _shared_executor()
        fut_a = ex.submit(seeded, msm_g1_multi, dpk.a_bases, w_cols, "a")
        fut_b1 = ex.submit(seeded, msm_g1_multi, dpk.b1_bases, b_cols, "b1")
        fut_c = ex.submit(seeded, msm_g1_multi, dpk.c_bases, c_cols, "c")
        if plans is not None:
            # PIPELINED arm: per-proof b2 tasks (not one serialized
            # list — a free worker starts proof k's G2 MSM while k-1's
            # runs) interleaved with ladder d-column tasks, so the h
            # multi MSM starts when the LAST column lands instead of
            # after a serial ladder walk.  Segment-plan arm only: its
            # matvec parallelism lives in the C pool, so a d task never
            # submits executor work (workers submitting-and-blocking
            # could exhaust the shared pool).
            b2_futs = [
                ex.submit(seeded, msm_g2_one, dpk.b2_bases, col, "b2") for col in b_cols
            ]
            d_cols = [f.result() for f in [
                ex.submit(seeded, ladder_one_col, w_mont) for w_mont in w_monts
            ]]
        else:
            # oracle arm: ONE serialized b2 task (the pre-tier
            # schedule) — S individual b2 tasks would FIFO-queue ahead
            # of the main-thread ladder's matvec leaves on the shared
            # executor and stall the d-column pipeline the h MSM waits
            # on.
            b2_futs = [ex.submit(
                seeded, lambda: [msm_g2_one(dpk.b2_bases, col, "b2") for col in b_cols]
            )]
            d_cols = ladder_cols()
        h_accs = msm_g1_multi(dpk.h_bases, d_cols, "h")
        a_accs, b1_accs, c_accs = (fut_a.result(), fut_b1.result(), fut_c.result())
        gathered = [f.result() for f in b2_futs]
        b2_accs = gathered if plans is not None else gathered[0]
    else:
        d_cols = ladder_cols()
        a_accs = msm_g1_multi(dpk.a_bases, w_cols, "a")
        b1_accs = msm_g1_multi(dpk.b1_bases, b_cols, "b1")
        b2_accs = [msm_g2_one(dpk.b2_bases, col, "b2") for col in b_cols]
        c_accs = msm_g1_multi(dpk.c_bases, c_cols, "c")
        h_accs = msm_g1_multi(dpk.h_bases, d_cols, "h")

    proofs = [
        _assemble(dpk, (a_accs[s], b1_accs[s], b2_accs[s], c_accs[s], h_accs[s]), rs[s], ss[s])
        for s in range(S)
    ]
    from ..utils.metrics import REGISTRY, publish_native_stats

    REGISTRY.counter("zkp2p_proves_total", {"prover": "native_batch"}).inc(S)
    publish_native_stats()
    return proofs


# The service's degradation ladder (pipeline.service) only makes sense
# for provers that actually READ the MSM knobs it flips per rung
# (ZKP2P_MSM_PRECOMP/MULTI/BATCH_AFFINE/OVERLAP are fresh-read here,
# per prove) — mark them so the ladder can tell.
prove_native.reads_msm_knobs = True
prove_native_batch.reads_msm_knobs = True
