"""Segmented matvec plans for the frozen QAP matrices.

The A/B matvecs (out[row[i]] += coeff[i]·w[wire[i]]) are a serial
read-modify-write scatter in the oracle kernel — at ~2-4 nnz per
constraint row the Montgomery mul IS the stage, and the scatter blocks
both vectorization and threading.  The matrices are immutable for the
life of a DeviceProvingKey, so (the same trade the fixed-base MSM
tables made in prover.precomp) this module presorts each matrix's nnz
by output row ONCE into a plan:

  * `perm`        — stable argsort of the row array (plan order),
  * `coeff`/`wire`— the gathered (permuted) coefficient / wire arrays,
  * `seg_starts`  — row-segment boundaries (segment s = one output row),
  * `seg_rows`    — the output row each segment sums into,
  * `coeff52`     — per process, the coeffs re-packed to the mont260
                    8-lane SoA blocks the IFMA product loop consumes
                    (csrc fr_matvec_pack52; never persisted — one cheap
                    conversion pass, and keying the disk cache by IFMA
                    arm would double the files).

`csrc fr_matvec_seg` then runs the products 8-wide ACROSS segment
boundaries (they are independent) and partitions the segment space over
the persistent WorkPool with zero scatter conflicts by construction —
each worker owns a disjoint row range.  Byte parity with the scatter
oracle is exact (field addition is associative; products are reduced
canonically), pinned by tests/test_nonmsm.py.

Plans persist beside the fixed-base precomp tables (``.bench_cache/``,
``matvec_seg_<mat>_<key_hash>.npz``) keyed by a sha256 over the SOURCE
matrix bytes, so a different key or matrix resolves to a different file
by construction.  Loads are tamper-rejecting: structural invariants
(monotone segment bounds, strictly increasing rows, in-range wires), an
embedded content digest, and sampled cross-checks of plan entries
against the live source matrix through ``perm`` — a corrupt, foreign,
or bit-rotted plan rebuilds (cheap: one argsort) instead of proving
garbage.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

_u64p = ctypes.POINTER(ctypes.c_uint64)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_i64p = ctypes.POINTER(ctypes.c_longlong)

# The QAP matrices with a matvec in the prove path (Cz is the pointwise
# Az·Bz product, never a matvec).
MATRICES = ("a", "b")


@dataclass
class MatvecPlan:
    """One matrix's presorted segment plan (+ the per-process 52-pack)."""

    matrix: str
    coeff: np.ndarray  # (nnz, 4) u64 Montgomery, plan order
    wire: np.ndarray  # (nnz,) u32, plan order
    perm: np.ndarray  # (nnz,) u32: plan index -> source nnz index
    seg_starts: np.ndarray  # (nseg+1,) i64, monotone, [0 .. nnz]
    seg_rows: np.ndarray  # (nseg,) u32, strictly increasing
    coeff52: Optional[np.ndarray]  # packed mont260 blocks, or None (scalar tier)
    key_hash: str
    source: str  # "built" | "cache"

    @property
    def nseg(self) -> int:
        return int(self.seg_rows.shape[0])

    def pointers(self):
        """The (coeff52, coeff, wire, seg_starts, seg_rows, nseg) ctypes
        argument pack fr_matvec_seg consumes."""
        p52 = self.coeff52.ctypes.data_as(_u64p) if self.coeff52 is not None else None
        return (
            p52,
            self.coeff.ctypes.data_as(_u64p),
            self.wire.ctypes.data_as(_u32p),
            self.seg_starts.ctypes.data_as(_i64p),
            self.seg_rows.ctypes.data_as(_u32p),
            self.nseg,
        )


# One plan dict per DeviceProvingKey identity — the precomp.py memo
# pattern: entries pin the dpk so an id() cannot be reused while its
# entry is alive; lock-guarded (batch d-column workers resolve plans
# concurrently); small cap bounds test-suite churn.
_plan_cache: Dict[int, Tuple[object, Dict[str, MatvecPlan]]] = {}
_PLAN_CACHE_CAP = 4
_plan_lock = threading.Lock()
_build_lock = threading.Lock()

# live manifest of the newest resolution (the precomp._last_manifest
# pattern) — the run-manifest hook reads this without touching the cache
_last_manifest: Optional[Dict] = None


def matvec_plan_manifest() -> Optional[Dict]:
    """Manifest of the most recently resolved plan set (None until a
    seg-armed prove ran): per-matrix shape + build-vs-cache provenance,
    plus the worker-pool width the segment partition fanned out over at
    resolve time (profile-aware via native_prove._n_threads) — so plan
    cache hits and the tuned thread width are attributable in every
    trace/bench artifact."""
    return _last_manifest


def reset() -> None:
    """Drop memoized plans + manifest (tests)."""
    global _last_manifest
    with _plan_lock:
        _plan_cache.clear()
    _last_manifest = None


def _source_arrays(dpk, matrix: str):
    """(coeff_u64 (nnz,4) mont256, wire u32, row u32) for one matrix —
    the same limb conversion + memo the oracle matvec path uses."""
    from .native_prove import _bases_memo, _limbs16_to_u64

    coeff = getattr(dpk, f"{matrix}_coeff")
    cf = _bases_memo(
        (coeff, coeff),
        lambda b: np.ascontiguousarray(_limbs16_to_u64(np.asarray(b[0]))),
    )
    wi = np.ascontiguousarray(np.asarray(getattr(dpk, f"{matrix}_wire"), dtype=np.uint32))
    ro = np.ascontiguousarray(np.asarray(getattr(dpk, f"{matrix}_row"), dtype=np.uint32))
    return cf, wi, ro


def _key_hash(cf: np.ndarray, wi: np.ndarray, ro: np.ndarray, m: int) -> str:
    """sha256 over the FULL source matrix bytes + domain size (16 hex).
    Full, not sampled — the hash is the cache-invalidation key."""
    h = hashlib.sha256()
    h.update(np.asarray([cf.shape[0], m], dtype=np.int64).tobytes())
    h.update(np.ascontiguousarray(cf).tobytes())
    h.update(wi.tobytes())
    h.update(ro.tobytes())
    return h.hexdigest()[:16]


def _content_digest(coeff, wire, perm, seg_starts, seg_rows) -> str:
    """Digest over the PLAN arrays (embedded in the npz; a flipped bit
    anywhere in the file fails the compare and rebuilds)."""
    h = hashlib.sha256()
    for a in (coeff, wire, perm, seg_starts, seg_rows):
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()


def _cache_path(cache_dir: str, matrix: str, key_hash: str) -> str:
    return os.path.join(cache_dir, f"matvec_seg_{matrix}_{key_hash}.npz")


def _build(cf: np.ndarray, wi: np.ndarray, ro: np.ndarray):
    """Presort by output row -> (coeff, wire, perm, seg_starts, seg_rows)."""
    nnz = int(ro.shape[0])
    perm = np.argsort(ro, kind="stable").astype(np.uint32)
    rows_sorted = ro[perm]
    coeff = np.ascontiguousarray(cf[perm])
    wire = np.ascontiguousarray(wi[perm])
    if nnz:
        bounds = np.flatnonzero(np.diff(rows_sorted)) + 1
        seg_starts = np.concatenate(
            [[0], bounds, [nnz]]
        ).astype(np.int64)
        seg_rows = rows_sorted[seg_starts[:-1]].astype(np.uint32)
    else:
        seg_starts = np.zeros(1, dtype=np.int64)
        seg_rows = np.zeros(0, dtype=np.uint32)
    return coeff, wire, perm, np.ascontiguousarray(seg_starts), np.ascontiguousarray(seg_rows)


def _validate(
    data, cf: np.ndarray, wi: np.ndarray, ro: np.ndarray, m: int
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Structural + digest + sampled-source validation of a loaded plan;
    None on ANY mismatch (the caller rebuilds)."""
    try:
        coeff = np.ascontiguousarray(data["coeff"])
        wire = np.ascontiguousarray(data["wire"])
        perm = np.ascontiguousarray(data["perm"])
        seg_starts = np.ascontiguousarray(data["seg_starts"])
        seg_rows = np.ascontiguousarray(data["seg_rows"])
        digest = str(data["digest"])
    except Exception:  # noqa: BLE001 — a torn npz must rebuild, not raise
        return None
    nnz = int(ro.shape[0])
    nseg = int(seg_rows.shape[0])
    if (
        coeff.shape != (nnz, 4)
        or coeff.dtype != np.uint64
        or wire.shape != (nnz,)
        or wire.dtype != np.uint32
        or perm.shape != (nnz,)
        or perm.dtype != np.uint32
        or seg_starts.shape != (nseg + 1,)
        or seg_starts.dtype != np.int64
        or seg_rows.dtype != np.uint32
    ):
        return None
    if digest != _content_digest(coeff, wire, perm, seg_starts, seg_rows):
        return None
    # structural invariants the C driver relies on
    if nseg:
        if seg_starts[0] != 0 or seg_starts[-1] != nnz:
            return None
        if not (np.diff(seg_starts) > 0).all():
            return None
        if not (np.diff(seg_rows.astype(np.int64)) > 0).all():
            return None
        if int(seg_rows.max()) >= m:
            return None
    elif nnz:
        return None
    # wire indices must stay inside the source's index range — an
    # out-of-range tamper would read past the witness buffer in C
    if nnz and int(wire.max()) > int(wi.max()):
        return None
    # sampled cross-check against the LIVE source through perm: a plan
    # for a different (but structurally valid) matrix fails here
    if nnz:
        idx = np.unique(np.linspace(0, nnz - 1, num=min(nnz, 64), dtype=np.int64))
        src = perm[idx].astype(np.int64)
        if int(src.max()) >= nnz:
            return None
        if not np.array_equal(coeff[idx], cf[src]) or not np.array_equal(
            wire[idx], wi[src]
        ):
            return None
        seg_of = np.searchsorted(seg_starts, idx, side="right") - 1
        if not np.array_equal(seg_rows[seg_of], ro[src]):
            return None
    return coeff, wire, perm, seg_starts, seg_rows


def _persist(path: str, coeff, wire, perm, seg_starts, seg_rows) -> None:
    """Atomic write (tmp + rename) — precomp._persist_table's contract."""
    tmp = f"{path}.tmp.{os.getpid()}.npz"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "wb") as f:
            np.savez(
                f,
                coeff=coeff,
                wire=wire,
                perm=perm,
                seg_starts=seg_starts,
                seg_rows=seg_rows,
                digest=_content_digest(coeff, wire, perm, seg_starts, seg_rows),
            )
        os.replace(tmp, path)
    except OSError:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass


def _pack52(lib, coeff: np.ndarray) -> Optional[np.ndarray]:
    nnz = int(coeff.shape[0])
    if nnz == 0:
        return None
    out = np.zeros(((nnz + 7) // 8) * 40, dtype=np.uint64)
    if not lib.fr_matvec_pack52(coeff.ctypes.data_as(_u64p), nnz, out.ctypes.data_as(_u64p)):
        return None
    return out


def _resolve_one(lib, dpk, matrix: str, cache_dir: Optional[str], persist_min: int) -> MatvecPlan:
    from ..utils.trace import trace

    cf, wi, ro = _source_arrays(dpk, matrix)
    m = 1 << dpk.log_m
    nnz = int(ro.shape[0])
    kh = _key_hash(cf, wi, ro, m)
    persist = cache_dir is not None and nnz >= persist_min
    path = _cache_path(cache_dir, matrix, kh) if persist else None

    def _try_load():
        with trace("native/matvec_plan_load", matrix=matrix):
            try:
                with np.load(path) as data:
                    return _validate(data, cf, wi, ro, m)
            except Exception:  # noqa: BLE001 — corrupt cache rebuilds
                return None

    plan_arrays = None
    source = "cache"
    if path is not None and os.path.exists(path):
        plan_arrays = _try_load()
    if plan_arrays is None and path is not None:
        # cross-process build serialization (precomp._build_flock):
        # plan builds are cheap (one argsort) but the sidecar keeps N
        # cold fleet workers from racing the persist, and the loser
        # loads the winner's atomic-renamed file instead of rebuilding
        from .precomp import _build_flock

        with _build_flock(path):
            if os.path.exists(path):
                plan_arrays = _try_load()
            if plan_arrays is None:
                source = "built"
                with trace("native/matvec_plan_build", matrix=matrix):
                    plan_arrays = _build(cf, wi, ro)
                _persist(path, *plan_arrays)
    elif plan_arrays is None:
        source = "built"
        with trace("native/matvec_plan_build", matrix=matrix):
            plan_arrays = _build(cf, wi, ro)
    coeff, wire, perm, seg_starts, seg_rows = plan_arrays
    return MatvecPlan(
        matrix=matrix,
        coeff=coeff,
        wire=wire,
        perm=perm,
        seg_starts=seg_starts,
        seg_rows=seg_rows,
        coeff52=_pack52(lib, coeff),
        key_hash=kh,
        source=source,
    )


def plans_for(dpk) -> Optional[Dict[str, MatvecPlan]]:
    """The segment plans for this DeviceProvingKey ({"a": .., "b": ..}),
    memoized per key identity; built or cache-loaded on first use.  None
    when the native library is unavailable.  Callers gate on
    ZKP2P_MATVEC_SEG (native_prove._use_matvec_seg) BEFORE calling."""
    from .native_prove import _lib

    lib = _lib()
    if lib is None:
        return None
    key = id(dpk)
    with _plan_lock:
        hit = _plan_cache.get(key)
        if hit is not None and hit[0] is dpk:
            return hit[1]
    with _build_lock:
        with _plan_lock:
            hit = _plan_cache.get(key)
            if hit is not None and hit[0] is dpk:
                return hit[1]
        from .precomp import _cache_dir
        from ..utils.config import load_config

        cache_dir = _cache_dir()
        persist_min = load_config().precomp_persist_min
        plans = {
            matrix: _resolve_one(lib, dpk, matrix, cache_dir, persist_min)
            for matrix in MATRICES
        }
        with _plan_lock:
            if len(_plan_cache) >= _PLAN_CACHE_CAP:
                _plan_cache.pop(next(iter(_plan_cache)))
            _plan_cache[key] = (dpk, plans)
        from .native_prove import _n_threads

        global _last_manifest
        _last_manifest = {
            "matrices": {
                m: {
                    "nnz": int(p.coeff.shape[0]),
                    "nseg": p.nseg,
                    "ifma52": p.coeff52 is not None,
                    "source": p.source,
                    "key_hash": p.key_hash,
                }
                for m, p in plans.items()
            },
            # the pool width fr_matvec_seg partitions segments over —
            # profile-aware (tuned physical-core default) via _n_threads
            "threads": _n_threads(),
        }
        return plans
