"""Data-only persistence for device proving keys (.npz).

The interop format stays snarkjs `.zkey` (formats.zkey); this cache is
the fast *internal* form — the DeviceProvingKey's numpy limb arrays
written as-is, so bench/service restarts skip both setup AND the
points->ints->limbs conversions.  Pure array data (numpy .npz), never
pickle (round-1 advisor finding)."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ..curve.host import G1Point, G2Point
from ..field.tower import Fq2
from ..snark.groth16 import VerifyingKey
from .groth16_tpu import _DPK_ARRAY_FIELDS, DeviceProvingKey

# Bump whenever _DPK_ARRAY_FIELDS / the npz layout changes: a cache written
# by an older schema must fail fast here (triggering re-setup upstream),
# not materialize empty arrays that crash deep inside jit (r3 advisor).
SCHEMA_VERSION = 3  # v3: width-classed MSM position arrays (a/b/c x narrow/wide)


class KeyCacheSchemaError(RuntimeError):
    """Cache file does not match the current DeviceProvingKey schema."""


def circuit_digest(cs) -> str:
    """Sampled structural digest of a ConstraintSystem: wire/constraint
    counts plus ~1k evenly-sampled constraint rows.  Catches the silent
    killer the (n_wires, domain) guard cannot: a gadget change that
    REORDERS wires or constraints without changing their counts — a key
    cached for the old order would prove garbage (caught only at
    verify).  Sampling keeps it O(1k) at the 4.9M-constraint flagship."""
    import hashlib

    n = len(cs.constraints)
    h = hashlib.sha256(f"{cs.num_wires}|{cs.num_public}|{n}".encode())
    step = max(1, n // 997)
    for i in range(0, n, step):
        c = cs.constraints[i]
        h.update(repr((i, sorted(c.a.items()), sorted(c.b.items()), sorted(c.c.items()))).encode())
    # The v3 cache stores narrow/wide classification arrays derived from
    # wire_width and the NARROW_WIDTH rule — a width-tag or rule change
    # with unchanged constraints must invalidate the cache, or the prover
    # would silently drop nonzero digit planes (caught only at verify).
    from .groth16_tpu import NARROW_PLANES, NARROW_WIDTH

    h.update(f"|nw{NARROW_WIDTH}|np{NARROW_PLANES}|".encode())
    widths = getattr(cs, "wire_width", {})
    h.update(hashlib.sha256(repr(sorted(widths.items())).encode()).digest())
    return h.hexdigest()[:16]


def _g1_arr(pt: G1Point) -> np.ndarray:
    if pt is None:
        return np.zeros((2, 32), dtype=np.uint8)
    return np.stack([
        np.frombuffer(pt[0].to_bytes(32, "little"), dtype=np.uint8),
        np.frombuffer(pt[1].to_bytes(32, "little"), dtype=np.uint8),
    ])


def _g1_from(arr: np.ndarray) -> G1Point:
    x = int.from_bytes(arr[0].tobytes(), "little")
    y = int.from_bytes(arr[1].tobytes(), "little")
    return None if x == 0 and y == 0 else (x, y)


def _g2_arr(pt: G2Point) -> np.ndarray:
    if pt is None:
        return np.zeros((4, 32), dtype=np.uint8)
    x, y = pt
    return np.stack([
        np.frombuffer(v.to_bytes(32, "little"), dtype=np.uint8)
        for v in (x.c0, x.c1, y.c0, y.c1)
    ])


def _g2_from(arr: np.ndarray) -> G2Point:
    vals = [int.from_bytes(arr[i].tobytes(), "little") for i in range(4)]
    if not any(vals):
        return None
    return (Fq2(vals[0], vals[1]), Fq2(vals[2], vals[3]))


def save_dpk(
    path: str, dpk: DeviceProvingKey, vk: VerifyingKey, digest: str = ""
) -> None:
    """`digest`, when given, pins the cache to circuit_digest(cs) — load
    callers passing a digest reject a key for a reordered circuit."""
    data = {}
    if digest:
        data["circuit_digest"] = np.frombuffer(digest.encode(), dtype=np.uint8)
    for f in _DPK_ARRAY_FIELDS:
        v = getattr(dpk, f)
        if isinstance(v, tuple):
            for i, c in enumerate(v):
                data[f"{f}.{i}"] = np.asarray(c)
        else:
            data[f] = np.asarray(v)
    data["meta"] = np.array([dpk.n_public, dpk.n_wires, dpk.log_m], dtype=np.int64)
    data["schema_version"] = np.array([SCHEMA_VERSION], dtype=np.int64)
    for name in ("alpha_1", "beta_1", "delta_1"):
        data[name] = _g1_arr(getattr(dpk, name))
    for name in ("beta_2", "delta_2"):
        data[name] = _g2_arr(getattr(dpk, name))
    data["vk_gamma_2"] = _g2_arr(vk.gamma_2)
    data["vk_ic"] = np.stack([_g1_arr(p) for p in vk.ic])
    np.savez_compressed(path, **data)


def load_dpk(path: str, digest: str = "") -> Tuple[DeviceProvingKey, VerifyingKey]:
    z = np.load(path)
    found = int(z["schema_version"][0]) if "schema_version" in z else 0
    if found != SCHEMA_VERSION:
        raise KeyCacheSchemaError(
            f"{path}: key cache schema {found} != current {SCHEMA_VERSION}; re-run setup"
        )
    if digest:
        had = bytes(z["circuit_digest"]).decode() if "circuit_digest" in z else "<none>"
        if had != digest:
            raise KeyCacheSchemaError(
                f"{path}: circuit digest {had} != rebuilt circuit {digest} "
                f"(wire/constraint order changed); re-run setup"
            )
    arrays = {}
    for f in _DPK_ARRAY_FIELDS:
        if f in z:
            arrays[f] = jnp.asarray(z[f])
        else:
            parts = []
            i = 0
            while f"{f}.{i}" in z:
                parts.append(jnp.asarray(z[f"{f}.{i}"]))
                i += 1
            if not parts:
                raise KeyCacheSchemaError(f"{path}: missing field {f!r}; re-run setup")
            arrays[f] = tuple(parts)
    n_public, n_wires, log_m = (int(v) for v in z["meta"])
    dpk = DeviceProvingKey(
        n_public=n_public,
        n_wires=n_wires,
        log_m=log_m,
        alpha_1=_g1_from(z["alpha_1"]),
        beta_1=_g1_from(z["beta_1"]),
        beta_2=_g2_from(z["beta_2"]),
        delta_1=_g1_from(z["delta_1"]),
        delta_2=_g2_from(z["delta_2"]),
        **arrays,
    )
    vk = VerifyingKey(
        n_public=n_public,
        alpha_1=dpk.alpha_1,
        beta_2=dpk.beta_2,
        gamma_2=_g2_from(z["vk_gamma_2"]),
        delta_2=dpk.delta_2,
        ic=[_g1_from(z["vk_ic"][i]) for i in range(z["vk_ic"].shape[0])],
    )
    return dpk, vk
