"""`zkp2p-tpu tune`: the budgeted host micro-sweep behind host profiles.

Every committed constant was hand-picked on one 2-core IFMA box; this
module re-derives the host-dependent ones HERE, on THIS hardware, by
running the same micro-arms `tools/msm_hwbench.py` measures (variable-
base Pippenger, the fixed precomputed-table tier, multi-column batch
MSMs, the segmented matvec + pooled H ladder) and persisting the
winners as a fingerprint-keyed profile (utils.hostprof) that the
geometry/thread/scheduler resolvers load at startup.

Sweep arms (ZKP2P_TUNE_ARMS filters; all by default, run in DECISION
order — arms that pin schedules first, evidence arms last, so a budget
truncation costs rows, never winners):

  threads   variable-base MSM wall vs worker count over the detected
            topology candidates {1, physical cores, logical CPUs} — the
            profile's ZKP2P_NATIVE_THREADS default.  Physical-vs-SMT
            aware: when the arm cannot run, the default falls back to
            the PHYSICAL core count, never the SMT-inflated logical one
            (two hyperthreads share one FMA pipe; the MSM inner loop
            gains nothing from the second).
  geometry  the fixed precomputed-table tier, cache-consciously
            (SZKP-style): candidate windows are ranked by bucket-set
            bytes (2^(c-1) x 80 B batch-affine block per in-flight
            window) against the detected LLC before any is measured,
            tables are built per candidate, and the best measured c
            (with its depth-derived q, widened to >= the thread count
            so the window-parallel axis stays covered) becomes the
            per-G1-family schedule.  Run this arm at bench scale
            (--n >= 2^17): bucket occupancy shifts with shape, and a
            schedule tuned on a toy MSM extrapolates upward badly —
            the hysteresis rule additionally keeps the committed
            geometry unless a candidate beats it beyond jitter.
  columns   the multi-column fixed-tier kernel at S in {1, 2, 4} — the
            batch amortization curve.  The profile stores the measured
            RATIOS scaled onto the committed single-prove anchor
            (DEFAULT_AMORT_POINTS[1]), because a micro-arm MSM second
            is not a whole-prove second; the basis is recorded in the
            profile and the controller's observe_batch EWMA folds
            residual absolute error in after the first real batch.
  window    variable-base window sweep around the committed curves
            (plain + GLV) — recorded as evidence only; the hand curves
            stay authoritative for the variable-base tiers.
  ladder    the non-MSM floor (segmented matvec + pooled H ladder) at
            the resolved pool width — evidence for the NTT/matvec pool
            split (the C pool re-reads its width from the env, so the
            per-thread sweep rides the threads arm's MSM numbers).

The sweep is WALL-CLOCK BUDGETED (ZKP2P_TUNE_BUDGET_S / --budget-s):
the deadline is checked before every measured candidate, a truncated
sweep persists whatever it measured (with `tune.truncated` set), and
every un-measured dimension simply keeps the committed fallback — a
tune pass can only ever pin measured winners, never guess.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

# execution order = decision arms first (threads feeds geometry's
# worker count; geometry feeds columns its table), evidence arms last —
# so a budget truncation drops evidence rows, never a tuned schedule
ARMS = ("threads", "geometry", "columns", "window", "ladder")

# fixed-tier candidate windows, widest-first trimmed by the cache model;
# the committed fallback (c=16) is always a candidate so the tune pass
# can only ever match-or-beat it on this host's own measurements
_GEOMETRY_CANDIDATES = (14, 15, 16, 17)
_COLUMN_CANDIDATES = (1, 2, 4)
_BUCKET_ROW_BYTES = 80  # one batch-affine Aff52 bucket row (csrc)
# a candidate must beat the COMMITTED geometry by more than this to
# replace it — a sub-jitter micro-arm win is noise, and switching the
# production schedule on noise is how a tune pass regresses a prove
_GEOMETRY_HYSTERESIS = 0.03


def parse_arms(spec: str) -> List[str]:
    """The ZKP2P_TUNE_ARMS grammar: comma-separated arm names, unknown
    names ignored with a warning by the caller, "" = all."""
    if not spec.strip():
        return list(ARMS)
    want = {p.strip() for p in spec.split(",") if p.strip()}
    return [a for a in ARMS if a in want]


def _bucket_set_bytes(c: int, threads: int) -> int:
    """Resident bucket working set for one in-flight batch-affine
    window per worker — the SZKP-style cache-pressure model the
    geometry candidates are ranked against."""
    return (1 << (c - 1)) * _BUCKET_ROW_BYTES * max(1, threads)


def _tiled_bases(lib, n: int):
    """(n, 8) Montgomery affine bases: 64 distinct k*G tiled to n — the
    msm_hwbench base-set idiom (distinct enough to defeat trivial
    bucket collisions, cheap enough to build inside the budget)."""
    from ..curve.host import G1_GENERATOR, g1_mul
    from ..native.lib import _pack_affine
    from ..prover.native_prove import _p

    rng = np.random.default_rng(7)
    host_pts = [g1_mul(G1_GENERATOR, int(k)) for k in rng.integers(1, 1 << 30, 64)]
    bases = _pack_affine(host_pts)
    bm64 = np.zeros_like(bases)
    lib.fp_to_mont(_p(bases), _p(bm64), 2 * 64)
    return np.ascontiguousarray(np.tile(bm64, ((n + 63) // 64, 1))[:n])


def _scalar_cols(n: int, S: int) -> np.ndarray:
    """(S, n, 4) full-width random Fr scalars (the ladder-shape worst
    case — witness columns are narrower and only faster)."""
    import random

    from ..field.bn254 import R
    from ..native.lib import _scalars_to_u64

    py_rng = random.Random(13)
    cols = [[py_rng.randrange(R) for _ in range(n)] for _ in range(S)]
    return np.ascontiguousarray(np.stack([_scalars_to_u64(col) for col in cols]))


def _min_of(fn, reps: int) -> float:
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def run_tune(
    n: int = 1 << 15,
    reps: int = 3,
    budget_s: Optional[float] = None,
    out_path: Optional[str] = None,
    arms_spec: Optional[str] = None,
    log=print,
) -> Optional[Dict]:
    """Run the budgeted sweep and persist the profile; returns the
    profile dict (None when the native library is unavailable — there
    is nothing host-specific to tune on the pure-Python path)."""
    from ..native.lib import ifma_available
    from ..prover.native_prove import _lib, _p
    from ..utils.config import load_config
    from ..utils.hostprof import (
        GEOMETRY_MIN_BL,
        cache_hierarchy,
        fingerprint_key,
        host_fingerprint,
        save_profile,
    )
    from .sched import DEFAULT_AMORT_POINTS

    lib = _lib()
    if lib is None:
        log("tune: native library unavailable — nothing to tune")
        return None
    cfg = load_config()
    if budget_s is None:
        budget_s = cfg.tune_budget_s
    arms = parse_arms(cfg.tune_arms if arms_spec is None else arms_spec)
    t_start = time.perf_counter()
    deadline = t_start + budget_s if budget_s > 0 else None

    def left() -> float:
        return float("inf") if deadline is None else deadline - time.perf_counter()

    fp = host_fingerprint()
    caches = cache_hierarchy()
    llc = caches["l3"] or caches["l2"] or (1 << 25)
    physical = int(fp["physical_cores"])
    logical = int(fp["cpu_count"])
    log(
        f"tune: host {fingerprint_key()} ({fp['cpu_model']}, "
        f"{physical} core(s) / {logical} cpu(s), "
        f"L2 {caches['l2']:,} B, LLC {llc:,} B, "
        f"ifma {'on' if fp['ifma'] else 'off'}) "
        f"shape n={n} reps={reps} budget {budget_s:.0f}s arms {','.join(arms)}"
    )

    bm = _tiled_bases(lib, n)
    sweep: Dict = {}
    arms_run: List[str] = []
    truncated = False
    window_profile: Optional[Dict] = None

    # ---------------------------------------------------------- threads
    # candidates from the detected topology; the variable-base plain MSM
    # is the probe (explicit thread arg — the C pool width itself is an
    # env read the sweep must not fight)
    thread_cands = sorted({1, physical, logical})
    best_threads = physical  # the topology-aware no-measurement default
    if "threads" in arms and left() > 0:
        arms_run.append("threads")
        sc1 = _scalar_cols(n, 1)[0]
        out = np.zeros(8, dtype=np.uint64)
        times: Dict[int, float] = {}
        from ..prover.native_prove import _pick_window

        for t in thread_cands:
            if left() <= 0:
                truncated = True
                break
            c = _pick_window(n, threads=t)
            times[t] = _min_of(
                lambda: lib.g1_msm_pippenger_mt(_p(bm), _p(sc1), n, c, t, _p(out)),
                reps,
            )
            log(f"tune: threads={t} c={c} min={times[t]*1e3:.0f} ms")
        if times:
            # argmin, ties to FEWER threads (same wall, cooler box)
            best_threads = min(sorted(times), key=lambda t: (times[t], t))
        sweep["threads"] = {str(t): v for t, v in times.items()}

    # --------------------------------------------------------- geometry
    # the fixed tier, cache-consciously: rank candidates by bucket-set
    # bytes against the LLC, then measure the survivors
    geometry: Optional[Dict] = None
    best_table = None  # (table, p52/table52, c, q, levels) for the columns arm
    if "geometry" in arms and left() > 0:
        arms_run.append("geometry")
        from ..prover.precomp import _resolve_geometry, fixed_nwin

        depth = int(cfg.precomp_depth)
        cands = [
            c for c in _GEOMETRY_CANDIDATES
            if _bucket_set_bytes(c, best_threads) <= llc // 2
        ]
        dropped = [c for c in _GEOMETRY_CANDIDATES if c not in cands]
        if dropped:
            log(
                f"tune: geometry candidates {dropped} dropped — bucket set "
                f"exceeds LLC/2 ({llc // 2:,} B) at threads={best_threads}"
            )
        sc1 = _scalar_cols(n, 1)
        out = np.zeros((1, 8), dtype=np.uint64)
        rows: Dict[str, Dict] = {}
        tables: Dict[int, tuple] = {}
        for c in cands:
            if left() <= 0:
                truncated = True
                break
            W = fixed_nwin(c)
            levels = max(1, min(depth, W))
            q = (W + levels - 1) // levels
            # q >= threads keeps the window-parallel axis at least as
            # wide as the worker pool (the csrc fixed driver splits on
            # the q hot-loop windows)
            q = max(q, best_threads)
            levels = (W + q - 1) // q
            t0 = time.perf_counter()
            table = np.zeros((levels * n, 8), dtype=np.uint64)
            lib.g1_precomp_build(_p(bm), n, c, q, levels, best_threads, _p(table))
            build_s = time.perf_counter() - t0
            table52 = np.zeros((levels * n, 10), dtype=np.uint64)
            p52 = _p(table52) if lib.g1_precomp_to52(_p(table), levels * n, _p(table52)) else None
            min_s = _min_of(
                lambda: lib.g1_msm_pippenger_fixed(
                    _p(table), p52, _p(sc1), n, n, levels, c, q, best_threads, _p(out)
                ),
                reps,
            )
            rows[str(c)] = {
                "q": q, "levels": levels, "min_s": min_s, "build_s": build_s,
                "bucket_set_bytes": _bucket_set_bytes(c, best_threads),
            }
            log(f"tune: geometry c={c} q={q} L={levels} min={min_s*1e3:.0f} ms")
            tables[c] = (table, table52 if p52 is not None else None, q, levels)
        sweep["geometry"] = rows
        if rows:
            # hysteresis: the committed geometry stays unless a
            # candidate beats it by more than the jitter floor
            fb = _resolve_geometry(n, depth, 1 << 62)
            chosen = int(min(rows, key=lambda k: rows[k]["min_s"]))
            if fb is not None and str(fb[0]) in rows and chosen != fb[0]:
                win = 1.0 - rows[str(chosen)]["min_s"] / rows[str(fb[0])]["min_s"]
                if win < _GEOMETRY_HYSTERESIS:
                    log(
                        f"tune: geometry keeping committed c={fb[0]} — "
                        f"c={chosen} wins by {win:.1%} "
                        f"(< {_GEOMETRY_HYSTERESIS:.0%} hysteresis)"
                    )
                    chosen = fb[0]
            r = rows[str(chosen)]
            geometry = {"c": chosen, "q": int(r["q"])}
            tb = tables.pop(chosen)
            best_table = (tb[0], tb[1], chosen, tb[2], tb[3])
            tables.clear()  # free the losing candidates' tables

    # ---------------------------------------------------------- columns
    # the multi-column fixed kernel at the chosen geometry — the batch
    # amortization curve (ratios, anchored; see module docstring)
    amort: Optional[Dict[str, float]] = None
    batch_columns: Optional[int] = None
    if "columns" in arms and best_table is not None and left() > 0:
        arms_run.append("columns")
        table, table52, gc, gq, glev = best_table
        p52 = _p(table52) if table52 is not None else None
        col_times: Dict[int, float] = {}
        for S in _COLUMN_CANDIDATES:
            if left() <= 0:
                truncated = True
                break
            scm = _scalar_cols(n, S)
            outm = np.zeros((S, 8), dtype=np.uint64)
            if S == 1:
                col_times[S] = _min_of(
                    lambda: lib.g1_msm_pippenger_fixed(
                        _p(table), p52, _p(scm), n, n, glev, gc, gq,
                        best_threads, _p(outm),
                    ),
                    reps,
                )
            else:
                col_times[S] = _min_of(
                    lambda: lib.g1_msm_pippenger_fixed_multi(
                        _p(table), p52, _p(scm), n, n, S, glev, gc, gq,
                        best_threads, _p(outm),
                    ),
                    reps,
                )
            log(f"tune: columns S={S} min={col_times[S]*1e3:.0f} ms")
        sweep["columns"] = {str(s): v for s, v in col_times.items()}
        if 1 in col_times and len(col_times) >= 2:
            t1 = col_times[1]
            anchor = DEFAULT_AMORT_POINTS[1]
            pts = {s: anchor * t / t1 for s, t in sorted(col_times.items())}
            # strictly increasing in both axes or the curve is unusable
            vals = [pts[s] for s in sorted(pts)]
            if all(b > a for a, b in zip(vals, vals[1:])):
                amort = {str(s): round(v, 4) for s, v in pts.items()}
            # best column efficiency = min per-column seconds
            batch_columns = min(col_times, key=lambda s: col_times[s] / s)

    # ----------------------------------------------------------- window
    # variable-base evidence sweep around the committed curves — both
    # tags, one step each side; recorded, not applied (the hand curves
    # stay authoritative for the variable-base tiers)
    if "window" in arms and left() > 0:
        arms_run.append("window")
        from ..field.bn254 import GLV_MAX_BITS
        from ..prover.native_prove import (
            _glv_consts,
            _pick_window,
            _pick_window_glv,
        )

        sc1 = _scalar_cols(n, 1)[0]
        out = np.zeros(8, dtype=np.uint64)
        win: Dict[str, Dict[str, float]] = {}
        phi = np.zeros_like(bm)
        lib.g1_glv_phi_bases(_p(bm), n, _p(_glv_consts()), _p(phi))
        b2 = np.ascontiguousarray(np.concatenate([bm, phi]))
        for tag, c0 in (
            ("plain", _pick_window(n, threads=best_threads)),
            ("glv", _pick_window_glv(n, threads=best_threads)),
        ):
            rows: Dict[str, float] = {}
            for c in (c0 - 1, c0, c0 + 1):
                if c < 4 or left() <= 0:
                    truncated = truncated or left() <= 0
                    continue
                if tag == "glv":
                    rows[str(c)] = _min_of(
                        lambda: lib.g1_msm_pippenger_glv_mt(
                            _p(b2), _p(sc1), n, n, c, best_threads,
                            _p(_glv_consts()), GLV_MAX_BITS, _p(out),
                        ),
                        reps,
                    )
                else:
                    rows[str(c)] = _min_of(
                        lambda: lib.g1_msm_pippenger_mt(
                            _p(bm), _p(sc1), n, c, best_threads, _p(out)
                        ),
                        reps,
                    )
                log(f"tune: window[{tag}] c={c} min={rows[str(c)]*1e3:.0f} ms")
            win[tag] = rows
        sweep["window"] = win
        # promote winners: the measured-best c per tag, with the same
        # hysteresis discipline as the fixed-tier geometry — a neighbor
        # must beat the committed c0 by >3% to displace it, so rep noise
        # never flaps the curve.  The context (scalar-count bit length,
        # thread count) rides along: hostprof.tuned_window applies the
        # value only at the exact measured shape — window optima are not
        # monotone in either axis.
        fams: Dict[str, Dict[str, int]] = {}
        for tag in ("plain", "glv"):
            rows = win.get(tag, {})
            if not rows:
                continue
            best_c = min(rows, key=lambda k: rows[k])
            c0 = (
                _pick_window(n, threads=best_threads)
                if tag == "plain"
                else _pick_window_glv(n, threads=best_threads)
            )
            if str(c0) in rows and rows[best_c] > rows[str(c0)] * (1.0 - _GEOMETRY_HYSTERESIS):
                best_c = str(c0)
            bl = n.bit_length() if tag == "plain" else (2 * n).bit_length()
            fams[tag] = {"c": int(best_c), "bl": int(bl)}
        if fams:
            window_profile = {"threads": int(best_threads), "families": fams}

    # ----------------------------------------------------------- ladder
    # non-MSM floor at the resolved pool width — evidence rows only
    if "ladder" in arms and left() > 0:
        arms_run.append("ladder")
        try:
            sweep["ladder"] = _ladder_probe(lib, min(n, 1 << 14), reps, left)
        except Exception as e:  # noqa: BLE001 — evidence, not a gate
            log(f"tune: ladder probe failed ({e}); fallback rows kept")
        if left() <= 0:
            truncated = True

    spent = time.perf_counter() - t_start
    profile: Dict = {
        "created_ts": round(time.time(), 3),
        "topology": {
            "logical_cpus": logical,
            "physical_cores": physical,
            "smt_per_core": int(fp["smt_per_core"]),
        },
        "cache": caches,
        "threads": {
            "native_default": int(best_threads),
            "basis": "measured" if sweep.get("threads") else "physical_cores",
        },
        "tune": {
            "budget_s": float(budget_s),
            "spent_s": round(spent, 3),
            "shape_n": int(n),
            "reps": int(reps),
            "arms_run": arms_run,
            "truncated": truncated,
            "ifma": 1 if ifma_available() else 0,
            "sweep": sweep,
        },
    }
    if geometry is not None:
        from ..prover.precomp import G1_FAMILIES

        profile["msm_fixed"] = {
            "min_bl": GEOMETRY_MIN_BL,
            "default": dict(geometry),
            "families": {f: dict(geometry) for f in G1_FAMILIES},
        }
    if amort is not None:
        profile["sched"] = {
            "amort_points": amort,
            "amort_basis": (
                "msm-multi micro-arm ratios x the committed venmo "
                f"single-prove anchor ({DEFAULT_AMORT_POINTS[1]} s); "
                "observe_batch EWMA corrects absolute error online"
            ),
        }
        if batch_columns is not None:
            profile["sched"]["batch_columns"] = int(batch_columns)
    if window_profile is not None:
        profile["msm_window"] = window_profile

    path = save_profile(profile, out_path)
    if path is None:
        log("tune: profile persistence disabled (no cache dir) — not saved")
    else:
        log(
            f"tune: profile saved to {path} "
            f"({spent:.1f}s of {budget_s:.0f}s budget, "
            f"{'TRUNCATED, ' if truncated else ''}arms: {','.join(arms_run)})"
        )
    return profile


def _ladder_probe(lib, m: int, reps: int, left) -> Dict:
    """One segmented-matvec + pooled-H-ladder measurement at domain m
    (the msm_hwbench --ladder arms, budget-aware) — the profile's
    non-MSM evidence rows."""
    import ctypes

    from ..field.bn254 import fr_domain_root
    from ..prover import matvec_plan
    from ..prover.native_prove import _n_threads, _p
    from ..snark.groth16 import coset_gen

    u32p = ctypes.POINTER(ctypes.c_uint32)
    i64p = ctypes.POINTER(ctypes.c_longlong)
    log_m = m.bit_length() - 1
    m = 1 << log_m
    threads = _n_threads()
    g = np.random.default_rng(17)

    def rand_fr(k):
        a = g.integers(0, 1 << 64, size=(k, 4), dtype=np.uint64)
        a[:, 3] &= np.uint64((1 << 60) - 1)
        return np.ascontiguousarray(a)

    def mont(std):
        out = np.zeros_like(std)
        lib.fr_to_mont_batch(_p(std), _p(out), std.shape[0])
        return out

    out: Dict = {"m": m, "threads": threads}
    nnz = 4 * m
    coeff = mont(rand_fr(nnz))
    wire = g.integers(0, m, size=nnz, dtype=np.uint32)
    row = g.integers(0, m, size=nnz, dtype=np.uint32)
    w_mont = mont(rand_fr(m))
    cp, wp, _perm, seg_starts, seg_rows = matvec_plan._build(coeff, wire, row)
    c52 = matvec_plan._pack52(lib, cp)
    mv = np.zeros((m, 4), dtype=np.uint64)
    if left() > 0:
        out["matvec_seg_s"] = _min_of(
            lambda: lib.fr_matvec_seg(
                _p(c52) if c52 is not None else None, _p(cp),
                wp.ctypes.data_as(u32p), seg_starts.ctypes.data_as(i64p),
                seg_rows.ctypes.data_as(u32p), seg_rows.shape[0],
                _p(w_mont), m, threads, _p(mv),
            ),
            reps,
        )
    if left() > 0:
        wroot = np.ascontiguousarray(
            np.frombuffer(int(fr_domain_root(log_m)).to_bytes(32, "little"), dtype="<u8")
        )
        gcos = np.ascontiguousarray(
            np.frombuffer(int(coset_gen(log_m)).to_bytes(32, "little"), dtype="<u8")
        )
        base = mont(rand_fr(3 * m)).reshape(3, m, 4)
        d = np.zeros((m, 4), dtype=np.uint64)

        def run_ladder():
            abc = [np.ascontiguousarray(base[i].copy()) for i in range(3)]
            lib.fr_h_ladder(_p(abc[0]), _p(abc[1]), _p(abc[2]), m, _p(wroot), _p(gcos), _p(d))

        out["h_ladder_s"] = _min_of(run_ladder, reps)
    return out
