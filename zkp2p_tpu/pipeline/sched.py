"""The adaptive admission-and-batching scheduler (ROADMAP item 2).

Every scaling knob in the proving service used to be static: batch
size was a constructor argument, the fleet was `--workers N`, and the
admission cap shed newest-first.  All the signals needed to close the
loop already existed — the PR-8 arrival-rate/backlog sampler, the
PR-12 fleet burn rates, the measured batch amortization curve (batch=4
proves in 13.3 s vs 4x3.17 s sequential on the 499k circuit) — and
this module is the controller that sits on them.  It is the same shape
as continuous-batching schedulers in inference serving (Orca-style
iteration-level scheduling; zkSpeed in PAPERS.md likewise treats batch
geometry as a load-dependent dial): at low load small batches minimize
latency, at high load wide batches maximize throughput, and only a
controller can hold the right point of that curve as traffic moves.

Three deterministic pieces (docs/SCHEDULING.md has the full model):

  AmortModel        the per-circuit batch cost curve batch_s(S):
                    measured points with linear interpolation,
                    calibrated from BENCH/loadgen data via
                    ZKP2P_SCHED_AMORT ("S:sec,S:sec,...") or the
                    built-in conservative venmo default.
  BatchController   per sweep: EWMA arrival rate from spool mtimes,
                    expected-deadline-miss shedding (a greedy walk in
                    service order — shed exactly the requests the
                    model predicts cannot finish, never ones that
                    still can), priority lanes (interactive requests
                    batch first at a small lane width while bulk
                    rides wide), and SLO-driven batch sizing: the
                    largest S whose predicted completion keeps the
                    oldest queued request inside its deadline/
                    objective, clamped to [1, cap] and to the live
                    backlog.
  AutoscalePolicy   fleet grow/shrink between workers-min/max from the
                    fleet plane's merged backlog trend + burn rate,
                    with alerts-style hysteresis (a condition must
                    hold scale_up_s/scale_down_s CONTINUOUSLY before
                    a decision; any flap resets the clock, so a
                    boundary-oscillating signal never flaps the fleet).

Everything here is pure over (clock, inputs): no registry writes, no
env reads outside the typed config, injectable clocks — the service
and the fleet supervisor own the side effects (metrics, records,
spawns), tests drive synthetic time.

The gate: ZKP2P_SCHED=off|adaptive, fresh-read per sweep, ARMABLE,
record_arm'd as `service_sched` (sched_arm below, preflight-armed) —
the PR-2/PR-5 discipline, so adaptive-vs-off A/Bs are
digest-distinguishable.  `off` (the default) reproduces the static
path byte-for-byte.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Built-in conservative amortization default: the measured 499k venmo
# curve (PR-9: single prove 3.17 s, batch=4 13.3 s at threads=2).
# Nearly linear on the 2-core box — which makes the default CONSERVATIVE
# for batching: the controller never assumes amortization a host has not
# measured.  Calibrate per circuit/host via ZKP2P_SCHED_AMORT.
DEFAULT_AMORT_POINTS: Dict[int, float] = {1: 3.17, 4: 13.3}

# Built-in sharded-tier default: a mesh worker (prover=tpu, the
# ZKP2P_TPU_SHARD pod program) pays a heavy per-dispatch floor — witness
# staging, collective setup, and the residual warm-cache executable
# load — but its batch axis is data-parallel across the mesh, so the
# marginal proof is cheap and wide batches amortize hard.  Deliberately
# conservative in the same sense as DEFAULT_AMORT_POINTS (a worse
# single-proof cost than any real mesh would measure): it steers the
# bulk lane toward wide batches without ever promising latency the
# interactive lane should get from a native worker instead.  Measured
# per-host curves land via `zkp2p-tpu tune` (hostprof amort_points,
# tier="sharded").
DEFAULT_SHARDED_AMORT_POINTS: Dict[int, float] = {1: 8.0, 4: 12.0, 16: 28.0}

# Interactive latency-lane width: interactive batches never exceed this
# many columns, however wide the bulk target is — the lane exists so an
# interactive request's service time is bounded by a small batch even
# when bulk traffic has driven the controller to the cap.
INTERACTIVE_LANE_CAP = 2


class AmortModel:
    """Piecewise-linear batch cost model: batch_s(S) = predicted wall
    seconds to prove a batch of S, interpolated between measured points.
    Below the smallest measured S the cost scales proportionally; above
    the largest it extends along the last segment's slope (one point =
    proportional everywhere).  Points must be positive and strictly
    increasing in both S and seconds — a non-monotone curve would let
    the controller "prove" a wider batch finishes sooner."""

    def __init__(self, points: Dict[int, float]):
        items = sorted((int(s), float(t)) for s, t in points.items())
        if not items:
            raise ValueError("AmortModel needs at least one (S, seconds) point")
        last_s, last_t = 0, 0.0
        for s, t in items:
            if s <= last_s or t <= last_t:
                raise ValueError(
                    f"amortization points must be strictly increasing: ({s}:{t}) after ({last_s}:{last_t})"
                )
            last_s, last_t = s, t
        self.points: List[Tuple[int, float]] = items

    @classmethod
    def from_spec(cls, spec: str) -> "AmortModel":
        """Parse a "S:seconds,S:seconds" calibration spec (the
        ZKP2P_SCHED_AMORT knob); empty = the built-in default.  A
        malformed spec raises LOUDLY — a silently-defaulted calibration
        would make every sizing decision wrong without a trace (the
        utils.faults malformed-spec rule applied here)."""
        if not spec.strip():
            return cls(DEFAULT_AMORT_POINTS)
        pts: Dict[int, float] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                s_raw, t_raw = part.split(":")
                s, t = int(s_raw), float(t_raw)
            except ValueError as e:
                raise ValueError(
                    f"bad ZKP2P_SCHED_AMORT entry {part!r} (want 'S:seconds,...'): {e}"
                ) from None
            if s in pts:
                raise ValueError(f"duplicate ZKP2P_SCHED_AMORT batch size {s}")
            pts[s] = t
        return cls(pts)

    def batch_s(self, s: int) -> float:
        """Predicted wall seconds for a batch of `s` live requests."""
        if s <= 0:
            return 0.0
        pts = self.points
        if s <= pts[0][0]:
            return pts[0][1] * s / pts[0][0]
        for (s0, t0), (s1, t1) in zip(pts, pts[1:]):
            if s <= s1:
                return t0 + (t1 - t0) * (s - s0) / (s1 - s0)
        if len(pts) >= 2:
            (s0, t0), (s1, t1) = pts[-2], pts[-1]
            slope = (t1 - t0) / (s1 - s0)
        else:
            slope = pts[0][1] / pts[0][0]
        return pts[-1][1] + slope * (s - pts[-1][0])

    def per_proof_s(self, s: int) -> float:
        return self.batch_s(s) / s if s > 0 else float("inf")

    def best_throughput_size(self, cap: int) -> int:
        """The S in [1, cap] minimizing per-proof seconds (ties break to
        the SMALLER batch — same throughput, better latency)."""
        cap = max(1, cap)
        best_s, best_t = 1, self.per_proof_s(1)
        for s in range(2, cap + 1):
            t = self.per_proof_s(s)
            if t < best_t - 1e-12:
                best_s, best_t = s, t
        return best_s


@dataclass(frozen=True)
class SchedRequest:
    """One queued request as the controller sees it: identity, spool
    arrival time, absolute deadline (None = no hard deadline), lane."""

    rid: str
    t_submit: float
    deadline: Optional[float] = None
    interactive: bool = False


@dataclass
class SweepPlan:
    """One sweep's decisions: the batch partition in service order
    (interactive lane first), the shed verdicts, and the telemetry the
    service records (gauge values, decision-line fields)."""

    batches: List[List[SchedRequest]] = field(default_factory=list)
    shed: List[Tuple[SchedRequest, str]] = field(default_factory=list)
    batch_target: int = 0           # the bulk-lane S (0 = no bulk work)
    interactive_target: int = 0     # the interactive-lane S (0 = none)
    batch_reason: str = "idle"      # slo | throughput | backlog | warmup | idle
    rate_hz: float = 0.0
    oldest_wait_s: float = 0.0
    lanes: Dict[str, int] = field(default_factory=dict)
    # heterogeneous-tier routing (docs/TPU.md §tier routing): the tier
    # this plan was made under, per-lane counts LEFT IN THE SPOOL for a
    # better-suited live peer tier (not batched, not shed — the peer
    # claims them), and the tier-loss flag: True exactly once when a
    # previously-live sharded peer vanished while bulk work was queued,
    # so the service can count the degrade-to-native event.
    tier: str = "native"
    deferred: Dict[str, int] = field(default_factory=dict)
    tier_fallback: bool = False


class BatchController:
    """The per-worker admission-and-batching controller.  Stateful only
    in the arrival-rate EWMA; plan() is otherwise pure over (now, queue)
    so tests drive synthetic arrival streams with injected clocks."""

    def __init__(
        self,
        amort: AmortModel,
        objective_s: float = 0.0,
        target_fill: float = 0.8,
        ewma_tau_s: float = 10.0,
        tier: str = "native",
    ):
        self.amort = amort
        # the worker tier this controller plans for (normalize_tier
        # grammar): lane routing against live peer tiers happens in
        # plan(); the amort curve for the tier is the factory's job
        # (build_controller).
        self.tier = normalize_tier(tier)
        # tier-loss edge detector: set when a plan() has SEEN a live
        # sharded peer, cleared when the loss event fires — so the
        # degrade-to-native fallback is counted once per loss, not once
        # per sweep.
        self._seen_sharded_peer = False
        self.objective_s = max(0.0, float(objective_s))
        # headroom fraction of the deadline/objective budget batches are
        # planned to — 0.8 leaves 20% for queue wait drift, witness
        # time, and model error between sizing and completion
        self.target_fill = min(max(float(target_fill), 0.05), 1.0)
        self.ewma_tau_s = max(0.1, float(ewma_tau_s))
        self.rate_hz = 0.0
        self._last_now: Optional[float] = None
        # online calibration: EWMA of observed-vs-modelled batch cost.
        # The static curve (or its built-in venmo default) can be
        # arbitrarily wrong for THIS circuit/host — on a stub-speed
        # circuit an uncorrected 3.17 s/proof default would predict
        # every tight-deadline request hopeless and shed the whole
        # queue.  Until the first real batch is observed, predictive
        # shedding applies only to requests whose deadline has ALREADY
        # passed (model-free truth); after that, predictions ride
        # model_scale toward measured reality.
        self.model_scale = 1.0
        self.calibrated = False

    def observe_batch(self, fill: int, seconds: float) -> float:
        """Fold one completed batch's actual wall cost into the
        calibration scale (EWMA of actual/modelled, clamped so one
        outlier — a cold compile, a stolen core — cannot blow up every
        prediction).  Returns the current scale."""
        if fill <= 0 or seconds <= 0:
            return self.model_scale
        modelled = self.amort.batch_s(fill)
        if modelled <= 0:
            return self.model_scale
        ratio = min(max(seconds / modelled, 0.02), 50.0)
        if not self.calibrated:
            self.model_scale = ratio
            self.calibrated = True
        else:
            self.model_scale += 0.3 * (ratio - self.model_scale)
        return self.model_scale

    def seed_calibration(self) -> None:
        """Mark the controller calibrated WITHOUT an observed batch —
        for amortization curves that are themselves measurements of
        this host (the tune-produced profile points), so a fresh worker
        sizes and sheds from its first decision instead of spending its
        warm-up window at the cap with deadline-only shedding.  The
        scale stays 1.0: the seed points ARE the model; the first real
        observe_batch still folds measured-vs-seeded error in through
        the normal EWMA path (calibrated stays True, so one outlier
        cannot overwrite the seed wholesale the way the cold-start
        first-observation assignment would)."""
        self.model_scale = 1.0
        self.calibrated = True

    def _batch_s(self, s: int) -> float:
        """The model with the online calibration applied."""
        return self.model_scale * self.amort.batch_s(s)

    # ------------------------------------------------------------ arrivals

    def observe_arrivals(self, now: float, t_submits: List[float]) -> float:
        """Update the EWMA arrival rate from the queue's spool mtimes:
        arrivals since the last observation are the t_submits inside
        (last_now, now].  First observation seeds the rate from the
        trailing tau window (a controller born into a storm must not
        start from zero).  Returns the current rate in Hz."""
        if self._last_now is None:
            n = sum(1 for t in t_submits if now - t <= self.ewma_tau_s)
            self.rate_hz = n / self.ewma_tau_s
            self._last_now = now
            return self.rate_hz
        dt = now - self._last_now
        if dt <= 0:
            return self.rate_hz
        arrivals = sum(1 for t in t_submits if self._last_now < t <= now)
        inst = arrivals / dt
        alpha = 1.0 - math.exp(-dt / self.ewma_tau_s)
        self.rate_hz += alpha * (inst - self.rate_hz)
        self._last_now = now
        return self.rate_hz

    # ------------------------------------------------------------- sizing

    def _budget_s(self, req: SchedRequest, now: float) -> Optional[float]:
        """Remaining latency budget for `req` at `now`: time to its hard
        deadline, else to the SLO objective (anchored at its arrival).
        None = no bound at all (no deadline, no objective)."""
        if req.deadline is not None:
            return req.deadline - now
        if self.objective_s > 0:
            return (req.t_submit + self.objective_s) - now
        return None

    def _size_for(
        self, now: float, reqs: List[SchedRequest], cap: int, parallelism: int = 1,
    ) -> Tuple[int, str]:
        """SLO-driven sizing over `reqs` (MUST be in service order):
        pick the S in [1, min(cap, backlog)] that maximizes the number
        of queued requests predicted to finish inside their deadline/
        objective when the queue is served in S-wide batches — request
        at position p completes at now + (p//S + 1) * batch_s(S), and
        "inside" leaves target_fill headroom for queue drift and model
        error.  Ties break to the LARGER S (same served count, queue
        cleared sooner).  With one queued request this reduces to "the
        largest S whose predicted completion keeps it inside its
        budget"; with a deep queue it holds throughput at the cap
        instead of collapsing to tiny batches chasing the oldest
        stragglers (the classic head-of-line inversion).  No bound on
        any request = pure throughput (the cap); a queue where even the
        best S serves nobody in time falls back to the best-throughput
        size — the shed pass owns hopeless requests, sizing must not
        thrash on them."""
        n = len(reqs)
        if n == 0:
            return 0, "idle"
        hi = max(1, min(cap, n))
        # warm-up: until a real batch has confirmed the model, size like
        # the static arm (everything available up to the cap) — an
        # unconfirmed curve steering sizing can serialize a fast queue
        # into its deadlines (a 3.17 s/proof default on a stub circuit
        # picks S=1 and starves throughput exactly when it matters)
        if not self.calibrated:
            return hi, "warmup"
        par = max(1, int(parallelism))
        budgets = [self._budget_s(r, now) for r in reqs]
        if all(b is None for b in budgets):
            return hi, "backlog"
        best_s, best_count = 1, -1
        for s in range(1, hi + 1):
            bs = self._batch_s(s)
            count = 0
            for p, b in enumerate(budgets):
                if b is None or (p // (s * par) + 1) * bs <= self.target_fill * b:
                    count += 1
            if count >= best_count:
                best_s, best_count = s, count
        if best_count <= 0:
            return min(hi, self.amort.best_throughput_size(hi)), "throughput"
        return best_s, "slo"

    # -------------------------------------------------------------- plan

    def plan(
        self,
        now: float,
        reqs: List[SchedRequest],
        cap: int,
        spool_cap: int = 0,
        allow_shed: bool = True,
        parallelism: int = 1,
        peer_tiers: Optional[List[str]] = None,
    ) -> SweepPlan:
        """One sweep's full decision: lane-sort, tier-route, shed,
        partition.

        `peer_tiers` (None = no tier information, serve everything) is
        the tiers of the OTHER live workers on this spool.  Routing is
        deferral, not claiming: a native worker with a live sharded peer
        leaves the bulk lane in the spool (wide batches belong on the
        mesh tier — per-batch cost there amortizes hard); a sharded
        worker with a live native peer leaves the interactive lane (an
        interactive request must never wait behind a sharded-tier
        dispatch/compile).  Deferred requests are NEVER shed here — they
        are the peer's to serve, and its own shed walk owns their
        deadlines.  A worker with no live peer of the other tier serves
        both lanes (no starvation when the fleet degrades to one tier);
        a native worker that LOSES its sharded peer with bulk queued
        flags tier_fallback exactly once per loss so the service counts
        the degrade event.

        1. service order: interactive first, then by (t_submit, rid) —
           oldest-first within a lane, deterministic throughout.
        2. expected-deadline-miss shed (allow_shed): walk the order;
           a request at kept-position p is predicted done at now +
           best_serve_s(p+1), the OPTIMISTIC best batch partition the
           model admits (min over S of ceil(n/S) * batch_s(S)) — so a
           request servable under ANY batch geometry is never shed, and
           a shed one dropped out of virtual capacity first (the walk
           never sheds a request the removal of earlier hopeless ones
           would have saved).  Requests without a hard deadline are
           never predictively shed — a late proof beats no proof.
        3. admission cap: still over `spool_cap` after step 2, shed by
           ascending slack (deadline-or-objective minus predicted
           completion): the most-hopeless go first, a request that can
           still finish is shed only when the cap leaves no choice.
        4. partition: interactive lane first in batches of
           min(size, INTERACTIVE_LANE_CAP); bulk in batches of the
           SLO-sized S.

        `parallelism` = live workers sharing this spool (>= 1): on a
        fleet, N workers sweep ONE queue, so a request at position p is
        really at position ~p/N — predictions (shed walk, cap slack,
        sizing counts) divide positions by it.  Optimistic perfect
        speedup on purpose: a worker must never shed a request its
        PEERS could still serve (the fleet-wide over-shed bug class).
        """
        plan = SweepPlan()
        plan.tier = self.tier
        plan.rate_hz = round(self.observe_arrivals(now, [r.t_submit for r in reqs]), 6)

        # Tier routing before anything else: deferred lanes drop out of
        # the shed walk, the sizing, and the partition — they stay in
        # the spool for the peer.  The sharded-peer edge detector runs
        # even on an empty queue so a loss during idle does not fire a
        # stale fallback on the next busy sweep.
        sharded_peer = peer_tiers is not None and "sharded" in peer_tiers
        native_peer = peer_tiers is not None and "native" in peer_tiers
        has_bulk = any(not r.interactive for r in reqs)
        if self.tier == "native":
            if sharded_peer:
                self._seen_sharded_peer = True
            elif self._seen_sharded_peer:
                self._seen_sharded_peer = False
                if has_bulk:
                    plan.tier_fallback = True
        if self.tier == "native" and sharded_peer:
            deferred = [r for r in reqs if not r.interactive]
            reqs = [r for r in reqs if r.interactive]
            if deferred:
                plan.deferred["bulk"] = len(deferred)
        elif self.tier == "sharded" and native_peer:
            deferred = [r for r in reqs if r.interactive]
            reqs = [r for r in reqs if not r.interactive]
            if deferred:
                plan.deferred["interactive"] = len(deferred)

        if not reqs:
            return plan
        order = sorted(reqs, key=lambda r: (not r.interactive, r.t_submit, r.rid))
        plan.oldest_wait_s = round(max(0.0, now - min(r.t_submit for r in reqs)), 6)

        par = max(1, int(parallelism))
        kept: List[SchedRequest] = []
        if allow_shed:
            hi = max(1, min(cap, len(order)))
            pred_cache: Dict[int, float] = {}

            def best_serve_s(count: int) -> float:
                # optimistic seconds to serve `count` requests: the
                # best batch partition within the cap (min over S of
                # ceil(count/S) * batch_s(S)).  Optimistic on purpose:
                # shed only what cannot finish under ANY geometry; a
                # kept-but-late request still hits the claim/assembly
                # deadline gates.
                t = pred_cache.get(count)
                if t is None:
                    t = min(
                        -(-count // s) * self._batch_s(s) for s in range(1, hi + 1)
                    )
                    pred_cache[count] = t
                return t

            for r in order:
                if r.deadline is not None:
                    pred = now + best_serve_s(-(-(len(kept) + 1) // par))
                    # warm-up guard: until a real batch has calibrated
                    # the model, trust only the model-free truth (the
                    # deadline already passed) — a wrong static curve
                    # must not shed a whole queue of servable requests
                    miss = (pred > r.deadline) if self.calibrated else (now >= r.deadline)
                    if miss:
                        plan.shed.append((r, f"predicted completion +{pred - now:.2f}s past deadline"))
                        continue
                kept.append(r)
            if spool_cap and len(kept) > spool_cap:
                # slack = budget at predicted completion; no budget at
                # all sorts LAST-position-first (mirrors the static
                # arm's newest-first cap semantics for unbounded work)
                def slack(item: Tuple[int, SchedRequest]) -> Tuple[float, float, str]:
                    p, r = item
                    pred = now + best_serve_s(-(-(p + 1) // par))
                    b = self._budget_s(r, now)
                    s = (b - (pred - now)) if b is not None else float("inf")
                    return (s, -p, r.rid)

                ranked = sorted(enumerate(kept), key=slack)
                to_shed = {id(r) for _p, r in ranked[: len(kept) - spool_cap]}
                survivors = []
                for r in kept:
                    if id(r) in to_shed:
                        plan.shed.append((r, f"backlog over admission cap {spool_cap}"))
                    else:
                        survivors.append(r)
                kept = survivors
        else:
            kept = order

        interactive = [r for r in kept if r.interactive]
        bulk = [r for r in kept if not r.interactive]
        plan.lanes = {"interactive": len(interactive), "bulk": len(bulk)}
        if interactive:
            s_int, _ = self._size_for(now, interactive, cap, parallelism=par)
            s_int = max(1, min(s_int, INTERACTIVE_LANE_CAP))
            plan.interactive_target = s_int
            for i in range(0, len(interactive), s_int):
                plan.batches.append(interactive[i : i + s_int])
        if bulk:
            s_bulk, reason = self._size_for(now, bulk, cap, parallelism=par)
            plan.batch_target = s_bulk
            plan.batch_reason = reason
            for i in range(0, len(bulk), s_bulk):
                plan.batches.append(bulk[i : i + s_bulk])
        elif interactive:
            plan.batch_target = plan.interactive_target
            plan.batch_reason = "interactive"
        return plan


# ---------------------------------------------------------------------------
# Fleet autoscaling.


class AutoscalePolicy:
    """Grow/shrink decisions between [workers_min, workers_max] with
    explicit hysteresis (the utils.alerts fire/clear discipline applied
    to scaling): the scale-up condition (merged backlog trend growing,
    or both burn rates over the alert threshold) must hold CONTINUOUSLY
    for scale_up_s before a +1; the scale-down condition (empty backlog,
    no growth) must hold for scale_down_s before a -1.  Any tick where
    the condition is false resets its clock; a tick with no data (None
    signals) HOLDS both clocks — missing data is not evidence either
    way.  Every decision resets BOTH clocks (the cooldown: a second
    step needs a full fresh window), so a boundary-oscillating signal
    produces exactly zero decisions, never a flap."""

    def __init__(
        self,
        workers_min: int,
        workers_max: int,
        scale_up_s: float = 10.0,
        scale_down_s: float = 30.0,
        burn_threshold: float = 2.0,
    ):
        self.workers_min = max(1, int(workers_min))
        self.workers_max = max(self.workers_min, int(workers_max))
        self.scale_up_s = max(0.0, float(scale_up_s))
        self.scale_down_s = max(0.0, float(scale_down_s))
        self.burn_threshold = float(burn_threshold)
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._last_reason = ""

    def _up_cond(self, signals: Dict) -> Optional[bool]:
        growing = signals.get("backlog_growing")
        bf, bs = signals.get("burn_fast"), signals.get("burn_slow")
        burn = None
        if isinstance(bf, (int, float)) and isinstance(bs, (int, float)):
            n = signals.get("slo_n")
            burn = bool(n) and bf >= self.burn_threshold and bs >= self.burn_threshold
        if growing is None and burn is None:
            return None
        if growing is True:
            self._last_reason = "backlog_growth"
            return True
        if burn:
            self._last_reason = "slo_burn"
            return True
        return False

    def _down_cond(self, signals: Dict) -> Optional[bool]:
        backlog = signals.get("backlog")
        if not isinstance(backlog, (int, float)):
            return None
        return backlog <= 0 and signals.get("backlog_growing") is not True

    def update(self, now: float, live: int, signals: Dict) -> Optional[Dict]:
        """One evaluation tick; returns {"direction": "up"|"down",
        "reason": ...} when a sustained condition crosses its window and
        the bound allows the step, else None."""
        up = self._up_cond(signals)
        down = self._down_cond(signals)
        if up is True:
            self._down_since = None
            if self._up_since is None:
                self._up_since = now
            if now - self._up_since >= self.scale_up_s and live < self.workers_max:
                self._up_since = self._down_since = None
                return {"direction": "up", "reason": self._last_reason}
        elif up is False:
            self._up_since = None
        if down is True and up is not True:
            if self._down_since is None:
                self._down_since = now
            if now - self._down_since >= self.scale_down_s and live > self.workers_min:
                self._up_since = self._down_since = None
                return {"direction": "down", "reason": "idle"}
        elif down is False:
            self._down_since = None
        return None


# ---------------------------------------------------------------------------
# The audit gate (PR-2/PR-5 discipline): the scheduler mode is a code
# path — an adaptive run and a static run must never share an execution
# digest.  Fresh-read per call (load_config re-reads the env), so one
# process can A/B both arms; anything but the literal "adaptive" fails
# CLOSED to the static oracle arm.


def normalize_sched(value: str) -> str:
    """The gate grammar in ONE place: anything but the literal
    "adaptive" fails CLOSED to the static "off" oracle arm (consumers:
    sched_mode below, the loadgen capacity report)."""
    return "adaptive" if value == "adaptive" else "off"


def sched_mode() -> str:
    """Resolve + record the scheduler arm: "adaptive" or "off"."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("service_sched", normalize_sched(load_config().sched))


def sched_arm() -> str:
    """Preflight alias (the *_arm naming every other gate resolver
    uses); identical to sched_mode()."""
    return sched_mode()


def normalize_tier(value: str) -> str:
    """The worker-tier grammar in ONE place: anything but the literal
    "sharded" fails CLOSED to "native" (the single-device arm keeps
    serving everything; a typo'd tier must not strand the bulk lane
    waiting for a mesh worker that does not exist)."""
    return "sharded" if value == "sharded" else "native"


def worker_tier_arm() -> str:
    """Resolve + record the worker tier (ZKP2P_WORKER_TIER, fresh-read
    like sched_mode): "sharded" or "native".  The tier is a routing code
    path — a mixed-tier fleet and a homogeneous one must never share an
    execution digest — so it rides the same record_arm rail as every
    other gate and preflight arms it explicitly."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("worker_tier", normalize_tier(load_config().worker_tier))


def build_controller(cfg) -> BatchController:
    """THE BatchController factory (service + tests share it): the
    amortization curve resolves explicit spec -> tuned host profile ->
    built-in venmo default, in operator-intent order.

      1. ZKP2P_SCHED_AMORT set: the operator's calibration wins and the
         controller starts UNCALIBRATED as before (the spec may describe
         a different circuit than the traffic).
      2. spec empty + a tuned profile loaded with measured batch-cost
         points: the profile seeds the model AND the calibration
         (seed_calibration) — a fresh host's scheduler exits warm-up
         with zero observed batches, because the points were measured
         on THIS hardware by `zkp2p-tpu tune`.
      3. neither: the built-in conservative curve, warm-up as before.

    The curve is PER TIER (worker_tier_arm, recorded here so every
    controller build stamps the tier into the digest): a sharded-tier
    worker resolves the profile's sharded batch-cost points (hostprof
    amort_points(tier="sharded")) and falls back to the built-in
    DEFAULT_SHARDED_AMORT_POINTS — heavy dispatch floor, hard
    wide-batch amortization — while the native tier keeps the venmo
    default.  An explicit ZKP2P_SCHED_AMORT still wins for either tier.

    Resolving through hostprof records the "host_profile" gate, so a
    seeded and an unseeded run never share an execution digest."""
    from ..utils.hostprof import amort_points

    tier = worker_tier_arm()
    seeded = False
    if cfg.sched_amort.strip():
        amort = AmortModel.from_spec(cfg.sched_amort)
    else:
        pts = amort_points(tier=tier)
        if pts is not None:
            amort = AmortModel(pts)
            seeded = True
        else:
            amort = AmortModel(
                DEFAULT_SHARDED_AMORT_POINTS if tier == "sharded" else DEFAULT_AMORT_POINTS
            )
    ctl = BatchController(
        amort,
        objective_s=cfg.slo_p95_s,
        target_fill=cfg.sched_target_fill,
        tier=tier,
    )
    if seeded:
        ctl.seed_calibration()
    return ctl
