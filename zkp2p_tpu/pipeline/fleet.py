"""The supervised proving fleet: N worker processes on one spool.

ROADMAP item 2's missing half: PR 7 made a *single* worker fault-
tolerant (rescue ladder, claims, takeover) and PR 8 made it observable
(SLO, waterfalls, loadgen) — but one `zkp2p-tpu service` process was
still the whole deployment.  A SIGTERM stranded claims until the
stale-claim timeout, a crash-looping worker restarted forever by hand,
and two workers cold-starting on one host each ran the multi-minute
precomp build.  This module is the serving layer SZKP/ZKProphet-style
accelerator provers assume: a supervisor that keeps the device fed
through worker crashes, restarts, and drains.

Topology (docs/ROBUSTNESS.md §fleet has the state machine):

  supervisor (this module, `zkp2p-tpu fleet`)
    ├─ spawns N workers (`zkp2p-tpu service` with ZKP2P_WORKER_ID /
    │  ZKP2P_FLEET_ID / ZKP2P_FLEET_DIR stamped into the env; any argv
    │  via `worker_cmd` — the chaos harness runs toy workers)
    ├─ liveness: per-worker heartbeat files (written each sweep by
    │  `worker_tick`) + process exit codes; a live pid with a stale
    │  heartbeat is HUNG and gets SIGKILL + restart
    ├─ restart policy: exponential backoff per consecutive failure,
    │  crash-loop circuit breaker — K failures inside W seconds PARKS
    │  the worker (counter + log line; the fleet degrades to N−1
    │  instead of flapping)
    ├─ graceful drain: SIGTERM fans out, each worker stops claiming,
    │  finishes in-flight batches, flushes sinks, exits 0; stragglers
    │  past ZKP2P_DRAIN_TIMEOUT_S are escalated to SIGKILL (counted —
    │  a clean fleet restart loses zero requests)
    └─ resource governor: per-worker RSS sampled from /proc; over the
       SOFT budget the worker is told (ctl file) to drop the precomp
       arm + shrink batch columns; over the HARD budget it is drained
       and restarted — OOM becomes a counted, recoverable event.

Worker↔supervisor plumbing is files in `fleet_dir` (default
`<spool>/.fleet/`), same crash-only philosophy as the spool itself:

  <wid>.hb    heartbeat, atomically replaced once per sweep:
              {pid, ts, worker, fleet, state, port, rss_mb, degraded}
              — `port` is the worker's BOUND metrics port (auto-port
              mode), so scrapes stay discoverable across a fleet
  <wid>.ctl   supervisor → worker control: {"degrade": 1} applies the
              soft-governor overlay at the worker's next sweep
  status.json supervisor state, atomically replaced per tick — the
              fleet's one-stop answer to "what is running where"

The supervisor holds no request state at all: killing it mid-run loses
nothing (workers keep sweeping; claims arbitrate), and a restarted
supervisor simply spawns fresh workers onto the same spool — the chaos
harness (`tools/chaos.py --fleet`) SIGKILLs it mid-prove to prove that.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# ---------------------------------------------------------------------------
# Worker-side: drain signals, heartbeat, governor compliance.  These run
# inside the service process (hooked from ProvingService.run) — keep the
# imports lazy so a solo service without a fleet pays nothing.


def install_drain_handlers(svc) -> bool:
    """SIGTERM/SIGINT → svc.request_drain(): stop claiming, finish
    in-flight batches, flush, exit run() with status "drained".  A
    SECOND signal while already draining restores the default action
    and re-delivers itself — a worker wedged mid-drain (the hang class
    the fleet watchdog SIGKILLs, but a solo service has no supervisor)
    must stay killable by a repeated Ctrl-C / SIGTERM, not only by
    kill -9.  Main thread only (CPython restriction) — returns False
    elsewhere instead of raising, so library users can call it
    unconditionally."""

    def _handler(signum, _frame):
        if svc.draining:
            print(f"[service] signal {signum} again while draining: exiting NOW", flush=True)
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        print(f"[service] signal {signum}: draining (finish in-flight, claim nothing)", flush=True)
        svc.request_drain()

    try:
        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
        return True
    except ValueError:  # not the main thread
        return False


def slowed_prover(inner, per_request_s: float, batch_overhead_s: float = 0.0):
    """Wrap a batch prover with artificial service time — THE one
    service-time model the toy capacity arms share (loadgen in-process
    AND the chaos/fleet workers), so their QPS numbers stay comparable
    by construction: `batch_overhead_s + per_request_s * fill` per
    prover call.  The per-BATCH overhead term models the real
    amortization curve's fixed cost (base sweep setup, dispatch) so
    scheduler A/Bs have a curve to sit on; 0 (the default) keeps the
    PR-8 purely-linear model.  Keeps the `reads_msm_knobs` marker: the
    degradation ladder gates on it."""
    if per_request_s <= 0 and batch_overhead_s <= 0:
        return inner

    def slowed(dpk, wits):
        time.sleep(batch_overhead_s + per_request_s * max(1, len(wits)))
        return inner(dpk, wits)

    slowed.reads_msm_knobs = getattr(inner, "reads_msm_knobs", False)
    return slowed


def _rss_mb(pid: int) -> Optional[float]:
    """Resident-set size of `pid` in MiB from /proc (None off-Linux or
    when the process is gone — the caller treats None as 'no sample',
    never as zero)."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):
        pass
    return None


def apply_soft_degrade(svc) -> None:
    """The worker-side SOFT governor action (idempotent): gate the
    fixed-base precomp arm off via its PR-7 overlay (the knob is
    fresh-read per prove), drop the memoized tables — they are the
    gigabytes — and halve the batch columns.  Proof bytes are
    knob-invariant, so degraded proofs still byte-match the fast path;
    the arm lands in the execution digest via the fleet_governor gate,
    so a degraded run is provably not comparable to a clean one."""
    if getattr(svc, "_fleet_degraded", False):
        return
    from ..prover import precomp
    from ..utils.audit import record_arm
    from ..utils.metrics import REGISTRY

    os.environ["ZKP2P_MSM_PRECOMP"] = "0"
    try:
        precomp.reset()  # free resident tables (refcounts keep any in-flight prove safe)
    except Exception:  # noqa: BLE001 — degrade must never crash the worker
        pass
    svc.batch_size = max(1, svc.batch_size // 2)
    svc._fleet_degraded = True
    REGISTRY.counter("zkp2p_fleet_degrade_applied_total").inc()
    record_arm("fleet_governor", "soft-applied")
    print(
        f"[service] governor: soft degrade applied (precomp off, batch={svc.batch_size})",
        flush=True,
    )


def _atomic_write_json(path: str, obj: Dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except OSError:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass


def _write_heartbeat(svc, fleet_dir: str, state: Optional[str] = None) -> None:
    from ..utils.metrics import bound_metrics_port

    wid = getattr(svc, "_worker_id", "") or f"pid{os.getpid()}"
    os.makedirs(fleet_dir, exist_ok=True)
    hb = {
        "pid": os.getpid(),
        "ts": round(time.time(), 3),
        "worker": wid,
        "fleet": getattr(svc, "_fleet_id", ""),
        "state": state or ("draining" if svc.draining else "up"),
        "port": bound_metrics_port(),
        "rss_mb": _rss_mb(os.getpid()),
        "degraded": bool(getattr(svc, "_fleet_degraded", False)),
    }
    # worker tier advertisement (ZKP2P_WORKER_TIER): peers read this
    # from the heartbeat to route lanes — a sharded-tier peer takes the
    # bulk lane, native peers keep interactive (pipeline.sched).  Fresh
    # read + record_arm so the gate digest tracks what was advertised.
    try:
        from .sched import worker_tier_arm

        hb["tier"] = worker_tier_arm()
    except Exception:  # noqa: BLE001 — the heartbeat must always land
        hb["tier"] = "native"
    # the worker's last scheduler decision (pipeline.sched block:
    # mode, batch target, lane depths) — surfaces in fleet /status
    # and `zkp2p-tpu top` without another scrape route
    sched_hb = getattr(svc, "_sched_hb", None)
    if sched_hb:
        hb["sched"] = dict(sched_hb)
    # the worker's perf-sentry counters (utils.perfledger budgets vs
    # terminal-request spans): overrun/check totals + budgets loaded —
    # the fleet plane sums these into the perf_regression alert signal
    # and `zkp2p-tpu top` renders the per-worker overrun column
    perf_hb = getattr(svc, "_perf_hb", None)
    if perf_hb:
        hb["perf"] = dict(perf_hb)
    # serialized SLO window (capped — the heartbeat is written every
    # ~5 s): the fleet plane's FALLBACK merge source when the worker's
    # /snapshot scrape fails (port not yet bound, worker mid-restart),
    # so a scrape gap degrades fleet attainment to slightly-stale
    # instead of punching a worker-sized hole in it
    try:
        from ..utils.slo import default_tracker

        hb["slo_window"] = default_tracker().window_state(max_samples=512)
    except Exception:  # noqa: BLE001 — the heartbeat must always land
        pass
    _atomic_write_json(os.path.join(fleet_dir, wid + ".hb"), hb)


def start_heartbeat_thread(svc, fleet_dir: str, interval_s: float = 5.0) -> threading.Event:
    """Background liveness heartbeat for a fleet worker, BETWEEN sweep
    ticks: a single sweep can legitimately run for minutes (the cold
    precomp build — and flock losers block for the winner's whole
    build), which a sweep-cadence heartbeat alone would render
    indistinguishable from a hang, so the default 60 s watchdog would
    SIGKILL a healthy cold-starting worker mid-build forever.  Long
    native calls release the GIL, so this thread keeps beating through
    them; a worker wedged holding the GIL (or deadlocked in Python)
    stops beating — exactly the distinction the watchdog needs.
    Returns the stop Event."""
    stop = threading.Event()

    def beat():
        while not stop.wait(interval_s):
            try:
                _write_heartbeat(svc, fleet_dir)
            except Exception:  # noqa: BLE001 — liveness must never crash the worker
                pass

    threading.Thread(target=beat, daemon=True, name="zkp2p-fleet-hb").start()
    return stop


def worker_tick(svc, fleet_dir: str, state: Optional[str] = None) -> None:
    """One per-sweep fleet tick inside a worker: write the heartbeat
    (liveness + bound metrics port + RSS) and apply any governor ctl.
    Failures degrade silently — fleet plumbing must never stop a sweep
    (the supervisor's watchdog covers a worker whose disk is so broken
    heartbeats stop landing).  Governor ctl is applied HERE only, never
    from the heartbeat thread — mutating batch_size mid-sweep would
    race the producer."""
    _write_heartbeat(svc, fleet_dir, state=state)
    wid = getattr(svc, "_worker_id", "") or f"pid{os.getpid()}"
    ctl_path = os.path.join(fleet_dir, wid + ".ctl")
    if not getattr(svc, "_fleet_degraded", False) and os.path.exists(ctl_path):
        try:
            with open(ctl_path) as f:
                ctl = json.load(f)
        except (OSError, ValueError):
            ctl = {}
        if ctl.get("degrade"):
            apply_soft_degrade(svc)


# ---------------------------------------------------------------------------
# Audit gates: fleet membership and the governor budgets are code-path
# arms (a degraded fleet run must never share a digest with a clean
# solo run) — registered like slo_arm/timeseries_arm.


def fleet_member_arm() -> str:
    """record_arm the fleet-membership gate: "worker" when a supervisor
    stamped ZKP2P_WORKER_ID into this process's env, else "off"."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    return record_arm("service_fleet", "worker" if load_config().worker_id else "off")


def governor_arm() -> str:
    """record_arm the resource-governor budgets: "off" or
    "soft=<mb>mb,hard=<mb>mb"."""
    from ..utils.audit import record_arm
    from ..utils.config import load_config

    cfg = load_config()
    arm = (
        "off"
        if not (cfg.rss_soft_mb or cfg.rss_hard_mb)
        else f"soft={cfg.rss_soft_mb}mb,hard={cfg.rss_hard_mb}mb"
    )
    return record_arm("fleet_governor", arm)


# ---------------------------------------------------------------------------
# Supervisor.


@dataclass
class WorkerSlot:
    """One worker's supervisor-side state.

    States: starting → up → (done | backoff → up | parked |
    draining → done).  `done` = exited rc 0 (a deliberate exit: drained,
    or the spool went terminal) — never restarted.  `parked` = the
    crash-loop breaker tripped — never restarted; the fleet runs N−1.
    """

    wid: str
    proc: Optional[subprocess.Popen] = None
    state: str = "starting"
    started_at: float = 0.0
    restarts: int = 0
    last_rc: Optional[int] = None
    failures: List[float] = field(default_factory=list)  # failure timestamps (breaker window)
    consec_failures: int = 0
    backoff_until: float = 0.0
    soft_signalled: bool = False
    governor_deadline: float = 0.0  # hard-governor drain escalation deadline (0 = none)
    governor_restart: bool = False  # next exit is a governor restart, not a crash
    # autoscale scale-down: the worker was SIGTERM'd to leave the fleet
    # (graceful drain — zero lost requests); its exit is final whatever
    # the rc, and a drain overrunning scale_deadline escalates like the
    # fleet drain does
    retiring: bool = False
    scale_deadline: float = 0.0


class FleetSupervisor:
    """Spawn and keep healthy N workers on one spool.  `worker_cmd`
    maps a worker id to its argv; the supervisor adds ZKP2P_WORKER_ID /
    ZKP2P_FLEET_ID / ZKP2P_FLEET_DIR (+ `worker_env`) to each child's
    environment.  Policy args default from the typed config
    (ZKP2P_DRAIN_TIMEOUT_S, ZKP2P_BREAKER_K/WINDOW_S,
    ZKP2P_RESTART_BACKOFF_S, ZKP2P_RSS_SOFT_MB/HARD_MB)."""

    def __init__(
        self,
        spool: str,
        worker_cmd: Callable[[str], List[str]],
        workers: Optional[int] = None,
        fleet_dir: Optional[str] = None,
        worker_env: Optional[Dict[str, str]] = None,
        drain_timeout_s: Optional[float] = None,
        breaker_k: Optional[int] = None,
        breaker_window_s: Optional[float] = None,
        restart_backoff_s: Optional[float] = None,
        rss_soft_mb: Optional[int] = None,
        rss_hard_mb: Optional[int] = None,
        liveness_s: float = 60.0,
        fleet_metrics_port: Optional[int] = None,
        workers_min: Optional[int] = None,
        workers_max: Optional[int] = None,
        scale_up_s: Optional[float] = None,
        scale_down_s: Optional[float] = None,
        log: Callable[[str], None] = lambda m: print(f"[fleet] {m}", flush=True),
    ):
        from ..utils.audit import record_arm
        from ..utils.config import load_config

        cfg = load_config()
        self.spool = spool
        self.worker_cmd = worker_cmd
        self.n = workers if workers is not None else cfg.fleet_workers
        self.fleet_dir = fleet_dir or os.path.join(spool, ".fleet")
        self.worker_env = dict(worker_env or {})
        self.drain_timeout_s = (
            drain_timeout_s if drain_timeout_s is not None else cfg.drain_timeout_s
        )
        self.breaker_k = breaker_k if breaker_k is not None else cfg.breaker_k
        self.breaker_window_s = (
            breaker_window_s if breaker_window_s is not None else cfg.breaker_window_s
        )
        self.restart_backoff_s = (
            restart_backoff_s if restart_backoff_s is not None else cfg.restart_backoff_s
        )
        self.rss_soft_mb = rss_soft_mb if rss_soft_mb is not None else cfg.rss_soft_mb
        self.rss_hard_mb = rss_hard_mb if rss_hard_mb is not None else cfg.rss_hard_mb
        self.liveness_s = liveness_s
        self.log = log
        self.fleet_id = cfg.fleet_id or uuid.uuid4().hex[:8]
        self.slots: Dict[str, WorkerSlot] = {f"w{i}": WorkerSlot(wid=f"w{i}") for i in range(self.n)}
        self.escalations = 0
        self.watchdog_kills = 0
        self._stop = threading.Event()
        self._draining = False
        os.makedirs(self.fleet_dir, exist_ok=True)
        # fleet observability plane (pipeline.fleet_obs): scrape +
        # merge + alert + serve, when ZKP2P_FLEET_METRICS_PORT (or the
        # ctor arg) configures a port.  None = plane off — the PR-10
        # per-worker ephemeral-port behavior, unchanged.
        self.fleet_metrics_port = (
            fleet_metrics_port if fleet_metrics_port is not None else cfg.fleet_metrics_port
        )
        self.plane = None
        # fleet autoscaling (pipeline.sched.AutoscalePolicy; ROADMAP
        # item 2): live workers move inside [workers_min, workers_max]
        # on the plane's merged backlog trend + burn rate, with
        # hysteresis windows scale_up_s/scale_down_s.  workers_max == 0
        # (the default) = off, exactly the PR-10 static fleet.
        self.workers_min = workers_min if workers_min is not None else cfg.workers_min
        self.workers_max = workers_max if workers_max is not None else cfg.workers_max
        self.scale_up_s = scale_up_s if scale_up_s is not None else cfg.scale_up_s
        self.scale_down_s = scale_down_s if scale_down_s is not None else cfg.scale_down_s
        self.autoscale = self.workers_max > 0
        self._autoscaler = None
        self._scale_events: List[Dict] = []
        self._next_widx = self.n
        if self.autoscale:
            from .sched import AutoscalePolicy

            self.workers_min = max(1, self.workers_min)
            self.workers_max = max(self.workers_min, self.workers_max)
            # start inside the band: --workers seeds, the bounds clamp
            if self.n < self.workers_min or self.n > self.workers_max:
                was = self.n
                self.n = min(max(self.n, self.workers_min), self.workers_max)
                log(f"autoscale: initial workers {was} clamped to {self.n} "
                    f"(band [{self.workers_min}, {self.workers_max}])")
                self.slots = {f"w{i}": WorkerSlot(wid=f"w{i}") for i in range(self.n)}
                self._next_widx = self.n
            self._autoscaler = AutoscalePolicy(
                self.workers_min, self.workers_max,
                scale_up_s=self.scale_up_s, scale_down_s=self.scale_down_s,
                burn_threshold=cfg.alert_burn_rate,
            )
            # the policy consumes the plane's merged signals — without
            # an endpoint the plane never runs, so autoscale implies an
            # (ephemeral, if unconfigured) plane port
            if self.fleet_metrics_port is None:
                self.fleet_metrics_port = 0
                log("autoscale needs the fleet plane: enabling an ephemeral fleet metrics port")
        record_arm("service_fleet", f"supervisor:{self.n}")
        governor_arm()
        # host profile: arm the gate once at supervisor startup and say
        # which way it went — workers inherit the same .bench_cache, so
        # one line here covers the whole fleet's tuning provenance
        from ..utils.hostprof import profile_arm

        log(f"host profile: {profile_arm()}")

    # ------------------------------------------------------------ spawn

    def _spawn(self, slot: WorkerSlot) -> None:
        env = dict(os.environ)
        env.update(self.worker_env)
        env["ZKP2P_WORKER_ID"] = slot.wid
        env["ZKP2P_FLEET_ID"] = self.fleet_id
        env["ZKP2P_FLEET_DIR"] = self.fleet_dir
        # the fleet plane needs scrape targets: when it is on, workers
        # get auto-bound exposition even if the operator configured none
        # (the plane without per-worker /snapshot endpoints would be an
        # aggregator of nothing).  Parse-checked, not setdefault: an
        # explicitly EMPTY ZKP2P_METRICS_PORT also means exposition off,
        # and leaving it would strand /status at 503 for the whole run.
        if self.fleet_metrics_port is not None:
            from ..utils.config import _opt_port

            if _opt_port(env.get("ZKP2P_METRICS_PORT") or "") is None:
                env["ZKP2P_METRICS_PORT"] = "auto"
        # N workers cannot share one fixed metrics port: force auto-bind
        # for the children whenever exposition is on at all (the bound
        # port comes back via the heartbeat + run manifest)
        if env.get("ZKP2P_METRICS_PORT") not in (None, "", "auto", "0"):
            self.log(
                f"{slot.wid}: rewriting ZKP2P_METRICS_PORT="
                f"{env['ZKP2P_METRICS_PORT']!r} to 'auto' (fixed ports collide across workers)"
            )
            env["ZKP2P_METRICS_PORT"] = "auto"
        # a fresh spawn must not inherit the previous incarnation's ctl
        # OR heartbeat: a stale .hb would satisfy readiness gates (the
        # loadgen --fleet warm-up wait) and backdate the watchdog clock
        # before the new process ever runs
        for suffix in (".ctl", ".hb"):
            try:
                os.unlink(os.path.join(self.fleet_dir, slot.wid + suffix))
            except OSError:
                pass
        slot.proc = subprocess.Popen(self.worker_cmd(slot.wid), env=env)
        slot.state = "up"
        slot.started_at = time.time()
        slot.soft_signalled = False
        slot.governor_deadline = 0.0
        self.log(f"{slot.wid}: up (pid {slot.proc.pid})")

    def start(self) -> None:
        if self.fleet_metrics_port is not None and self.plane is None:
            from .fleet_obs import FleetPlane

            self.plane = FleetPlane(self, port=self.fleet_metrics_port, log=self.log)
            self.plane.start()
        for slot in self.slots.values():
            self._spawn(slot)

    # ------------------------------------------------------------- tick

    def _hb(self, slot: WorkerSlot) -> Optional[Dict]:
        try:
            with open(os.path.join(self.fleet_dir, slot.wid + ".hb")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _hb_age_s(self, slot: WorkerSlot) -> Optional[float]:
        try:
            return time.time() - os.path.getmtime(os.path.join(self.fleet_dir, slot.wid + ".hb"))
        except OSError:
            return None

    def _on_failure(self, slot: WorkerSlot, now: float, why: str) -> None:
        """Crashed/hung worker: count toward the circuit breaker, park
        or schedule a backoff restart."""
        from ..utils.metrics import REGISTRY

        slot.failures.append(now)
        slot.failures = [t for t in slot.failures if now - t <= self.breaker_window_s]
        # a crash after a healthy run longer than the breaker window is
        # a FRESH failure, not the next rung of a crash loop — without
        # this, rare unrelated crashes days apart compound the backoff
        # to its 30 s cap forever
        if slot.started_at and now - slot.started_at > self.breaker_window_s:
            slot.consec_failures = 0
        slot.consec_failures += 1
        if len(slot.failures) >= self.breaker_k:
            slot.state = "parked"
            REGISTRY.counter("zkp2p_fleet_parked_total").inc()
            self.log(
                f"{slot.wid}: PARKED by circuit breaker ({len(slot.failures)} failures "
                f"inside {self.breaker_window_s:g}s; {why}) — fleet degrades to "
                f"{sum(1 for s in self.slots.values() if s.state in ('up', 'backoff', 'starting'))} workers"
            )
            return
        delay = min(self.restart_backoff_s * (2 ** (slot.consec_failures - 1)), 30.0)
        slot.backoff_until = now + delay
        slot.state = "backoff"
        self.log(f"{slot.wid}: {why}; restart in {delay:.2f}s (failure {len(slot.failures)}/{self.breaker_k})")

    def _governor(self, slot: WorkerSlot, now: float) -> None:
        from ..utils.metrics import REGISTRY

        if not (self.rss_soft_mb or self.rss_hard_mb) or slot.proc is None:
            return
        rss = _rss_mb(slot.proc.pid)
        if rss is None:
            return
        REGISTRY.gauge("zkp2p_fleet_worker_rss_bytes", {"worker": slot.wid}).set(rss * 1048576)
        if self.rss_hard_mb and rss > self.rss_hard_mb and not slot.governor_deadline:
            # HARD: drain + restart.  The drain (not SIGKILL) lets the
            # worker terminal its in-flight batch first; the deadline
            # below escalates if even draining cannot finish.
            REGISTRY.counter("zkp2p_fleet_governor_hard_total", {"worker": slot.wid}).inc()
            self.log(
                f"{slot.wid}: RSS {rss:.0f} MiB over hard budget {self.rss_hard_mb} MiB — "
                "draining for restart"
            )
            try:
                slot.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            slot.governor_deadline = now + (self.drain_timeout_s or 10.0)
            slot.governor_restart = True
        elif (
            self.rss_soft_mb
            and rss > self.rss_soft_mb
            and not slot.soft_signalled
            and not slot.governor_deadline
        ):
            # SOFT: tell the worker to shed memory (drop precomp arm,
            # shrink batch columns) via its ctl file
            REGISTRY.counter("zkp2p_fleet_governor_soft_total", {"worker": slot.wid}).inc()
            self.log(
                f"{slot.wid}: RSS {rss:.0f} MiB over soft budget {self.rss_soft_mb} MiB — "
                "writing degrade ctl"
            )
            _atomic_write_json(
                os.path.join(self.fleet_dir, slot.wid + ".ctl"), {"degrade": 1, "ts": now}
            )
            slot.soft_signalled = True

    def tick(self) -> None:
        """One supervisor pass: reap exits, restart/park, watchdog hung
        workers, run the governor, publish gauges + status.json."""
        from ..utils.metrics import REGISTRY

        now = time.time()
        for slot in self.slots.values():
            if slot.state in ("parked", "done"):
                continue
            if slot.state == "backoff":
                if now >= slot.backoff_until and not self._draining:
                    slot.restarts += 1
                    REGISTRY.counter("zkp2p_fleet_restarts_total", {"worker": slot.wid}).inc()
                    self._spawn(slot)
                continue
            if slot.proc is None:
                continue
            rc = slot.proc.poll()
            if rc is not None:
                slot.last_rc = rc
                if self._draining:
                    # during a fleet drain any exit is final
                    slot.state = "done"
                elif slot.retiring:
                    # autoscale scale-down: the exit we asked for — the
                    # worker drained its claims and left; final whatever
                    # the rc (a SIGKILL-escalated straggler's claims go
                    # stale and peers take them over — zero lost)
                    slot.state = "done"
                    slot.retiring = False
                    slot.scale_deadline = 0.0
                    self.log(f"{slot.wid}: scaled down (rc={rc})")
                elif slot.governor_restart:
                    # governor-requested recycle (hard RSS): immediate,
                    # no breaker penalty — OOM pressure is recoverable,
                    # not a crash loop.  Checked BEFORE the rc==0
                    # branch: a well-behaved worker drains CLEANLY on
                    # the governor's SIGTERM, and treating that rc 0 as
                    # "chose to leave" would silently shrink the fleet
                    # to N−1 on every hard-budget event.
                    slot.governor_restart = False
                    slot.governor_deadline = 0.0
                    slot.restarts += 1
                    REGISTRY.counter("zkp2p_fleet_restarts_total", {"worker": slot.wid}).inc()
                    self._spawn(slot)
                elif rc == 0:
                    # deliberate exit: drained, or the spool went
                    # terminal.  Never restarted — a worker that chose
                    # to leave is not a crash.
                    slot.state = "done"
                    slot.consec_failures = 0
                    self.log(f"{slot.wid}: exited cleanly")
                else:
                    self._on_failure(slot, now, f"exited rc={rc}")
                continue
            # alive: scale-down escalation, hard-governor escalation,
            # watchdog, governor
            if slot.retiring:
                if slot.scale_deadline and now > slot.scale_deadline:
                    self.log(f"{slot.wid}: scale-down drain timed out — SIGKILL")
                    self.escalations += 1
                    REGISTRY.counter("zkp2p_fleet_drain_escalations_total").inc()
                    try:
                        slot.proc.kill()
                    except OSError:
                        pass
                    slot.scale_deadline = 0.0
                continue  # a retiring worker is leaving: no watchdog/governor
            if slot.governor_deadline and now > slot.governor_deadline:
                self.log(f"{slot.wid}: governor drain timed out — SIGKILL")
                self.escalations += 1
                REGISTRY.counter("zkp2p_fleet_drain_escalations_total").inc()
                try:
                    slot.proc.kill()
                except OSError:
                    pass
                slot.governor_deadline = 0.0
                continue
            # Liveness begins at the FIRST heartbeat (the k8s
            # startup-vs-liveness probe distinction): a real service
            # worker spends minutes in pre-run() setup (circuit build,
            # zkey load, device_pk) before any heartbeat can land, and
            # killing on spawn-relative age would SIGKILL every healthy
            # cold start forever.  After the first beat, a live pid
            # whose heartbeat goes stale is HUNG (wedged holding the
            # GIL, deadlock — long native calls release the GIL, so the
            # background beat survives them).  SIGKILL — a SIGTERM
            # would need the very Python loop that stopped running.
            hb_age = self._hb_age_s(slot)
            grace = max(self.liveness_s, 2.0)
            if hb_age is not None and hb_age > grace and slot.started_at < now - hb_age:
                self.watchdog_kills += 1
                REGISTRY.counter("zkp2p_fleet_watchdog_kills_total").inc()
                self.log(f"{slot.wid}: heartbeat stale ({hb_age:.1f}s) with a live pid — watchdog SIGKILL")
                try:
                    slot.proc.kill()
                except OSError:
                    pass
                continue
            self._governor(slot, now)
        self._autoscale_tick(now)
        # fleet-level gauges + the status file
        counts: Dict[str, int] = {}
        for slot in self.slots.values():
            counts[slot.state] = counts.get(slot.state, 0) + 1
        for state in ("up", "backoff", "parked", "done", "starting", "retiring"):
            REGISTRY.gauge("zkp2p_fleet_workers", {"state": state}).set(counts.get(state, 0))
        self._write_status(now)

    # -------------------------------------------------------- autoscale

    def _live_workers(self) -> List[WorkerSlot]:
        """Slots currently serving (or about to): up/starting/backoff
        and not leaving — the count the autoscale band governs.
        Snapshot (list) because scale-up mutates `slots` while the
        plane's scrape thread and /status handlers also iterate it."""
        return [
            s for s in list(self.slots.values())
            if s.state in ("up", "starting", "backoff") and not s.retiring
        ]

    def _autoscale_tick(self, now: float) -> None:
        """One autoscale evaluation: feed the plane's merged signals
        (backlog trend, burn rates — nothing a single worker can see)
        through the hysteresis policy; apply at most one step.  Scale
        up = spawn a FRESH slot (ids never recycle — wN stays unique in
        records across the run); scale down = graceful drain of the
        newest live worker (SIGTERM → finishes in-flight claims, exits
        0; zero lost, zero duplicated — the PR-10 drain contract)."""
        if self._autoscaler is None or self._draining or self.plane is None:
            return
        from ..utils.metrics import REGISTRY

        signals = self.plane.last_signals()
        if signals is None:
            return
        live = self._live_workers()
        REGISTRY.gauge("zkp2p_fleet_workers_target").set(len(live))
        decision = self._autoscaler.update(now, len(live), signals)
        if decision is None:
            return
        if decision["direction"] == "up":
            wid = f"w{self._next_widx}"
            self._next_widx += 1
            slot = self.slots[wid] = WorkerSlot(wid=wid)
            self._spawn(slot)
            n_after = len(live) + 1
        else:
            # newest-first shrink: the highest-index live "up" worker —
            # the longest-lived keep their warm caches.  The floor
            # bounds RUNNING workers: slots in backoff/starting count
            # as live for the policy, but draining the only "up" worker
            # while its peers wait out a backoff would leave the spool
            # unserved below workers_min
            candidates = [s for s in live if s.state == "up" and s.proc is not None
                          and not s.governor_deadline]
            if not candidates or len(candidates) - 1 < self.workers_min:
                return
            victim = max(candidates, key=lambda s: int(s.wid[1:]) if s.wid[1:].isdigit() else 0)
            try:
                victim.proc.send_signal(signal.SIGTERM)
            except OSError:
                return
            victim.state = "retiring"
            victim.retiring = True
            victim.scale_deadline = now + (self.drain_timeout_s or 10.0)
            wid = victim.wid
            n_after = len(live) - 1
        REGISTRY.counter(
            "zkp2p_sched_decisions_total", {"kind": f"scale_{decision['direction']}"}
        ).inc()
        REGISTRY.gauge("zkp2p_fleet_workers_target").set(n_after)
        event = {
            "ts": round(now, 3), "direction": decision["direction"],
            "reason": decision["reason"], "worker": wid, "workers": n_after,
        }
        self._scale_events.append(event)
        self.log(
            f"autoscale: {decision['direction']} ({decision['reason']}) — "
            f"{wid}, fleet now targets {n_after} worker(s) "
            f"in [{self.workers_min}, {self.workers_max}]"
        )

    def status(self) -> Dict:
        workers = {}
        # list(): status() runs on plane HTTP-handler and scrape
        # threads while the autoscaler inserts slots from the tick
        for slot in list(self.slots.values()):
            hb = self._hb(slot) or {}
            workers[slot.wid] = {
                "pid": slot.proc.pid if slot.proc is not None else None,
                "state": slot.state,
                "restarts": slot.restarts,
                "last_rc": slot.last_rc,
                # the worker's BOUND metrics port (auto mode) — the
                # scrape-discovery contract: `/status` and `/metrics`
                # are reachable at 127.0.0.1:<port> per worker
                "port": hb.get("port"),
                "rss_mb": hb.get("rss_mb"),
                "hb_age_s": round(self._hb_age_s(slot), 3) if self._hb_age_s(slot) is not None else None,
                "hb_state": hb.get("state"),
                "degraded": hb.get("degraded", False),
            }
            # the worker's last scheduler decision (batch target, lane
            # depths) — rides the heartbeat, rendered by `zkp2p-tpu top`
            if hb.get("sched"):
                workers[slot.wid]["sched"] = hb["sched"]
            # the worker's perf-sentry counters (stage-budget overruns)
            # — rides the heartbeat, rendered by `zkp2p-tpu top`
            if hb.get("perf"):
                workers[slot.wid]["perf"] = hb["perf"]
        sched_block: Dict = {"autoscale": self.autoscale}
        if self.autoscale:
            sched_block.update({
                "workers_min": self.workers_min,
                "workers_max": self.workers_max,
                "workers_live": len(self._live_workers()),
                "scale_events": len(self._scale_events),
                "last_scale": self._scale_events[-1] if self._scale_events else None,
                # the full event history (newest 50 — a flapping-free
                # policy makes more an impossibility, but bound the
                # status payload anyway): the auditable record of every
                # grow/shrink this run took, in status.json and the
                # loadgen capacity JSON
                "events": list(self._scale_events[-50:]),
            })
        return {
            "type": "fleet_status",
            "fleet_id": self.fleet_id,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "spool": self.spool,
            "workers": workers,
            "sched": sched_block,
            "drain_timeout_s": self.drain_timeout_s,
            "escalations": self.escalations,
            "watchdog_kills": self.watchdog_kills,
            "draining": self._draining,
        }

    def _write_status(self, _now: float) -> None:
        # with the plane on, status.json is the FULL service-health view
        # (merged SLO, active alerts, scrape health, the plane's bound
        # port for endpoint discovery) — the same payload /status serves
        if self.plane is not None:
            try:
                status = self.plane.status_payload()
            except Exception:  # noqa: BLE001 — status must always land
                status = self.status()
        else:
            status = self.status()
        _atomic_write_json(os.path.join(self.fleet_dir, "status.json"), status)

    # ------------------------------------------------------------ drain

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Propagate SIGTERM to every live worker and wait (bounded) for
        clean exits; stragglers are escalated to SIGKILL.  Returns True
        when every worker drained cleanly (no escalation) — the fleet
        exit-code contract: 0 = clean drain, 3 = escalation needed."""
        from ..utils.metrics import REGISTRY

        timeout = timeout_s if timeout_s is not None else self.drain_timeout_s
        self._draining = True
        live = [s for s in self.slots.values() if s.proc is not None and s.proc.poll() is None]
        for slot in live:
            # a retiring worker already got its SIGTERM — a second one
            # while it drains means "exit NOW" (install_drain_handlers'
            # stay-killable contract) and would strand its claims
            if not slot.retiring:
                slot.state = "draining"
                try:
                    slot.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        self.log(f"draining {len(live)} worker(s), timeout {timeout:g}s")
        deadline = time.time() + max(timeout, 0.0)
        clean = True
        for slot in live:
            remaining = deadline - time.time()
            try:
                slot.proc.wait(timeout=max(remaining, 0.05))
                slot.last_rc = slot.proc.returncode
                slot.state = "done"
            except subprocess.TimeoutExpired:
                clean = False
                self.escalations += 1
                REGISTRY.counter("zkp2p_fleet_drain_escalations_total").inc()
                self.log(f"{slot.wid}: drain timed out — SIGKILL")
                try:
                    slot.proc.kill()
                    slot.proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    pass
                slot.last_rc = slot.proc.returncode
                slot.state = "done"
        self._write_status(time.time())
        return clean

    def stop(self) -> None:
        """Ask run() to drain and exit (signal handlers / tests)."""
        self._stop.set()

    # -------------------------------------------------------------- run

    def run(
        self,
        poll_s: float = 0.25,
        max_seconds: Optional[float] = None,
        install_signals: bool = True,
    ) -> int:
        """Supervise until every worker is done/parked, a signal (or
        stop()) asks for a drain, or max_seconds expires (the fleet is
        then drained).  Exit codes: 0 = clean (drain clean or all
        workers exited cleanly), 3 = drain escalated to SIGKILL,
        4 = every worker parked (the fleet is dead — page someone)."""
        if install_signals:
            def _handler(signum, _frame):
                self.log(f"signal {signum}: draining the fleet")
                self._stop.set()

            try:
                signal.signal(signal.SIGTERM, _handler)
                signal.signal(signal.SIGINT, _handler)
            except ValueError:
                pass  # not the main thread (tests drive stop() directly)
        self.start()
        deadline = (time.time() + max_seconds) if max_seconds else None
        clean = True
        while not self._stop.is_set():
            self.tick()
            states = {s.state for s in self.slots.values()}
            if states <= {"done", "parked"}:
                break
            if deadline is not None and time.time() > deadline:
                self.log("max-seconds expired: draining")
                break
            self._stop.wait(poll_s)
        clean = self.drain()
        self.tick()
        if self.plane is not None:
            # final view into status.json (alert history survives the
            # exit — a storm that fired mid-run is still on record),
            # then stop the scrape thread and release the port
            try:
                self.plane.scrape_once()
            except Exception:  # noqa: BLE001
                pass
            self._write_status(time.time())
            self.plane.stop()
        parked = sum(1 for s in self.slots.values() if s.state == "parked")
        if parked:
            self.log(f"{parked} worker(s) parked by the circuit breaker")
        if parked == len(self.slots):
            return 4
        # exit 3 only when the FINAL drain escalated (requests may have
        # been stranded mid-prove).  Mid-run hard-governor escalations
        # that were recovered by a restart stay counted (the gauge/
        # counter + status.json) but do not fail an otherwise clean
        # shutdown — "counted, recoverable" is the governor's contract.
        if not clean:
            return 3
        return 0
