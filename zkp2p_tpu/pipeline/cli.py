"""The proving pipeline CLI — `prover=tpu` beside snarkjs/rapidsnark.

Command-for-command parity with the reference's L2 scripts
(`dizkus-scripts/1..6_*.sh`, `circuit/scripts/*`, SURVEY.md §2.3):

  setup    ~ 1_compile.sh + 3_gen_both_zkeys.sh + 4_gen_vkey.sh +
             generate_contract.sh: build the circuit, run the dev setup,
             write circuit_final.zkey (snarkjs format, optionally b..k
             chunked) + verification_key.json + verifier.sol
  prove    ~ 2_gen_wtns.sh + 5/6_gen_proof: email/eml (or input.json) in,
             proof.json + public.json out, TPU prover
  verify   ~ verify_proof_groth16.sh: pairing check against the vkey
  batch    ~ the batching service of BASELINE.json: a directory of inputs
             proved as ONE vmapped batch

Config is flags + env (CIRCUIT_NAME/BUILD_DIR convention of
`dizkus-scripts/circuit.env.example`), centralised here instead of
scattered shell env files (SURVEY.md §5 config).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time


def _log(*a):
    print("[zkp2p-tpu]", *a, file=sys.stderr, flush=True)


def _build_circuit(name: str, header: int, body: int):
    if name == "venmo":
        from ..models.venmo import VenmoParams, build_venmo_circuit

        params = VenmoParams(max_header_bytes=header, max_body_bytes=body)
        cs, lay = build_venmo_circuit(params)
        return cs, (params, lay)
    if name == "email_verify":
        from ..models.email_verify import EmailVerifyParams, build_email_verify

        params = EmailVerifyParams(max_header_bytes=header, max_body_bytes=body)
        cs, lay = build_email_verify(params)
        return cs, (params, lay)
    if name == "sha256":
        from ..gadgets import core, sha256
        from ..snark.r1cs import ConstraintSystem

        cs = ConstraintSystem("sha256")
        msg = cs.new_wires(header, "msg")
        cs.mark_input(msg)
        bits = core.assert_bytes(cs, msg)
        sha256.sha256_blocks(cs, bits, None)
        return cs, (None, msg)
    if name == "toy":
        # smoke-test circuit: public out = (x*y)^2 over two byte inputs
        from ..field.bn254 import R
        from ..snark.r1cs import LC, ConstraintSystem

        cs = ConstraintSystem("toy")
        out = cs.new_public("out")
        x = cs.new_wire("x")
        y = cs.new_wire("y")
        z = cs.new_wire("z")
        cs.mark_input([x, y])
        cs.enforce(LC.of(x), LC.of(y), LC.of(z), "mul")
        cs.enforce(LC.of(z), LC.of(z), LC.of(out), "sq")
        cs.compute(z, lambda a, b: a * b % R, [x, y])
        return cs, (None, [x, y, out])
    raise SystemExit(f"unknown circuit {name!r} (have: venmo, email_verify, sha256, toy)")


def cmd_setup(args):
    from ..formats.proof_json import dump, vkey_to_json
    from ..formats.solidity import export_verifier
    from ..formats.zkey import split_zkey, write_zkey
    from ..snark.groth16 import qap_rows, setup

    os.makedirs(args.build_dir, exist_ok=True)
    t0 = time.perf_counter()
    _log(f"building circuit {args.circuit} ...")
    cs, meta = _build_circuit(args.circuit, args.max_header, args.max_body)
    _log(f"constraints={cs.num_constraints} wires={cs.num_wires} ({time.perf_counter()-t0:.0f}s)")
    if not args.skip_audit:
        # the registry admission gate (docs/STATIC_ANALYSIS.md §circuit
        # audit): no key material is cut for a circuit with unwaived
        # soundness findings.  Registered names carry their declared
        # on-chain public layout into the public-layout rule.
        from ..models.registry import SPECS
        from ..snark.analysis import audit_circuit, require_clean

        spec = SPECS.get(args.circuit)
        rep = require_clean(audit_circuit(
            cs,
            name=f"{args.circuit}_{args.max_header}_{args.max_body}",
            declared_n_public=spec.n_public if spec else None,
        ))
        _log(
            f"soundness audit clean: 0 unwaived / {rep['waived']} waived "
            f"findings in {rep['audit_s']}s ({rep['source']}, digest {rep['digest']})"
        )
    _log("running development setup (production: import a ceremony zkey instead)")
    pk, vk = setup(cs, seed=args.seed)
    zkey_path = os.path.join(args.build_dir, "circuit_final.zkey")
    write_zkey(zkey_path, pk, vk, qap_rows(cs))
    if args.chunks:
        split_zkey(zkey_path, args.chunks)
        _log(f"wrote {args.chunks} zkey chunks (b..) beside {zkey_path}")
    if args.publish:
        # The S3 layer (upload_chunked_keys_to_s3.sh semantics): gzip
        # chunks + manifest + integrity hash into the artifact store.
        from ..formats.artifact_store import DirBackend, upload_chunked

        with open(zkey_path, "rb") as f:
            blob = f.read()
        man = upload_chunked(DirBackend(args.publish), "circuit.zkey", blob)
        _log(f"published {len(man.chunks)} gzip chunks -> {args.publish} (sha256 {man.sha256[:16]}…)")
    dump(vkey_to_json(vk), os.path.join(args.build_dir, "verification_key.json"))
    with open(os.path.join(args.build_dir, "verifier.sol"), "w") as f:
        f.write(export_verifier(vk))
    _log(f"setup done in {time.perf_counter()-t0:.0f}s -> {args.build_dir}/")


def _infer_widths(args) -> bool:
    """zkey width inference is on unless --no-infer-widths was passed
    (one knob, consumed by every subcommand that imports a zkey)."""
    return not getattr(args, "no_infer_widths", False)


def _load_zkey(args):
    """The key material always travels as a snarkjs-format .zkey (never
    pickle): --zkey overrides (monolithic path or glob of b..k chunks),
    --zkey-store pulls through the chunked artifact store (the browser's
    S3-download + IndexedDB-cache path, `zkp.ts:24-68`), default is the
    build dir's circuit_final.zkey."""
    from ..formats.zkey import read_zkey

    if getattr(args, "zkey_store", None):
        from ..formats.artifact_store import DirBackend, download_chunked

        blob = download_chunked(
            DirBackend(args.zkey_store),
            "circuit.zkey",
            cache_dir=os.path.join(args.build_dir, "zkey_cache"),
        )
        return read_zkey(blob)
    if getattr(args, "zkey", None):
        paths = sorted(glob.glob(args.zkey)) if any(c in args.zkey for c in "*?[") else args.zkey
        if isinstance(paths, list) and not paths:
            raise SystemExit(f"no zkey matches {args.zkey}")
        return read_zkey(paths)
    return read_zkey(os.path.join(args.build_dir, "circuit_final.zkey"))


def _check_zkey_matches(zk, cs):
    """Fail fast on a key/circuit mismatch instead of deep in jitted code."""
    from ..snark.groth16 import domain_size_for

    if zk.n_vars != cs.num_wires or zk.domain_size != domain_size_for(cs):
        raise SystemExit(
            f"zkey does not match circuit: zkey has {zk.n_vars} wires / domain "
            f"{zk.domain_size}, circuit has {cs.num_wires} / {domain_size_for(cs)} "
            "(check --circuit/--max-header/--max-body against the setup)"
        )


def _witness_for(args, cs, meta, source=None):
    """Build (witness, public_signals) for one input.  `source` is an
    input file path (.eml or .json) — None falls back to --eml/--message
    flags or the synthetic demo email."""
    params, lay = meta
    if args.circuit == "venmo":
        from ..inputs.email import email_from_eml, generate_inputs, make_test_key, make_venmo_email

        src = source or getattr(args, "eml", None)
        if src:
            with open(src, "rb") as f:
                email = email_from_eml(f.read())  # unknown keys raise in _verified_eml
            modulus = email.modulus
        else:
            key = make_test_key(1)
            email = make_venmo_email(key)
            modulus = key.n
        inputs = generate_inputs(email, modulus, args.order_id, args.claim_id, params, lay)
        return cs.witness(inputs.public_signals, inputs.seed), inputs.public_signals
    elif args.circuit == "email_verify":
        from ..inputs.email import (
            email_verify_from_eml,
            generate_email_verify_inputs,
            make_test_key,
            make_twitter_email,
        )

        src = source or getattr(args, "eml", None)
        if src:
            with open(src, "rb") as f:
                email, modulus = email_verify_from_eml(f.read())  # unknown keys raise
        else:
            key = make_test_key(1)
            email, modulus = make_twitter_email(key), key.n
        inputs = generate_email_verify_inputs(email, modulus, params, lay)
        return cs.witness(inputs.public_signals, inputs.seed), inputs.public_signals
    elif args.circuit == "toy":
        from ..field.bn254 import R

        msg = args.message
        if source:
            with open(source) as f:
                msg = json.load(f)["message"]
        data = (msg or "35").encode().ljust(2, b"\x00")[:2]
        x_v, y_v = data[0], data[1]
        out_v = pow(x_v * y_v, 2, R)
        x, y, _ = lay
        return cs.witness([out_v], {x: x_v, y: y_v}), [out_v]
    else:
        from ..inputs.sha_host import sha256_pad

        msg = args.message
        if source:
            with open(source) as f:
                msg = json.load(f)["message"]
        data = (msg or "zkp2p").encode()
        padded, _ = sha256_pad(data, len(lay))
        return cs.witness([], {w: b for w, b in zip(lay, padded)}), []


def _prover_fn(args):
    """--prover tpu (default, XLA device path) | native (C++ Pippenger
    runtime, prover.native_prove) — the snarkjs-vs-rapidsnark split of
    the reference's scripts (5_gen_proof.sh / 6_gen_proof_rapidsnark.sh),
    selected by flag over the same zkey + witness."""
    if getattr(args, "prover", "tpu") == "native":
        from ..prover.native_prove import prove_native

        return prove_native
    from ..prover.groth16_tpu import prove_tpu

    return prove_tpu


def cmd_prove(args):
    from ..formats.proof_json import dump, proof_to_json, public_to_json
    from ..prover.groth16_tpu import device_pk_from_zkey

    prove_fn = _prover_fn(args)
    if getattr(args, "wtns", None):
        # Drop-in rapidsnark/snarkjs parity (`6_gen_proof_rapidsnark.sh:24-31`):
        # externally generated witness.wtns + zkey in, proof out — no
        # circuit rebuild needed, everything comes from the files.
        from ..formats.circom_bin import read_wtns

        zk = _load_zkey(args)
        w = read_wtns(args.wtns)
        if len(w) != zk.n_vars:
            raise SystemExit(f"witness has {len(w)} wires, zkey expects {zk.n_vars}")
        dpk = device_pk_from_zkey(zk, infer_widths=_infer_widths(args))
        pub = w[1 : zk.n_public + 1]
        t0 = time.perf_counter()
        proof = prove_fn(dpk, w)
        _log(f"proved in {time.perf_counter()-t0:.1f}s (incl. first-call compile)")
        dump(proof_to_json(proof), args.proof)
        dump(public_to_json(pub), args.public)
        _log(f"wrote {args.proof} {args.public}")
        return

    cs, meta = _build_circuit(args.circuit, args.max_header, args.max_body)
    zk = _load_zkey(args)
    _check_zkey_matches(zk, cs)
    dpk = device_pk_from_zkey(zk, infer_widths=_infer_widths(args))
    w, pub = _witness_for(args, cs, meta)
    t0 = time.perf_counter()
    proof = prove_fn(dpk, w)
    _log(f"proved in {time.perf_counter()-t0:.1f}s (incl. first-call compile)")
    dump(proof_to_json(proof), args.proof)
    dump(public_to_json(pub or w[1 : cs.num_public + 1]), args.public)
    _log(f"wrote {args.proof} {args.public}")


def cmd_verify(args):
    from ..formats.proof_json import load, proof_from_json, vkey_from_json
    from ..snark.groth16 import verify

    vk = vkey_from_json(load(os.path.join(args.build_dir, "verification_key.json")))
    proof = proof_from_json(load(args.proof))
    pub = [int(x) for x in load(args.public)]
    ok = verify(vk, proof, pub)
    print("OK" if ok else "INVALID")
    sys.exit(0 if ok else 1)


def cmd_ceremony(args):
    """Phase-2 MPC ops over zkeys (`dizkus-scripts/3_gen_both_zkeys.sh`)."""
    from ..snark import ceremony

    if args.op == "contribute":
        ceremony.contribute(args.zkey_in, args.zkey_out, args.entropy.encode(), name=args.name)
        print(f"contributed -> {args.zkey_out}")
    elif args.op == "beacon":
        if not args.beacon_hash:
            print("beacon requires --beacon-hash", file=sys.stderr)
            sys.exit(2)
        ceremony.beacon(args.zkey_in, args.zkey_out, bytes.fromhex(args.beacon_hash),
                        iter_exp=args.iter_exp, name=args.name or "final beacon")
        print(f"beacon applied -> {args.zkey_out}")
    else:
        ok, log = ceremony.verify_chain(args.zkey_in, args.zkey_out)
        for line in log:
            print(line)
        print("ZKEY OK" if ok else "ZKEY INVALID")
        sys.exit(0 if ok else 1)


def cmd_batch(args):
    """Prove every input in a directory as one vmapped batch —
    circuit-generic: .eml files for the email circuits, .json
    ({"message": ...}) for sha256/toy, all through the same per-circuit
    witness builder as `prove`."""
    from ..formats.proof_json import dump, proof_to_json, public_to_json
    from ..prover.groth16_tpu import device_pk_from_zkey, prove_tpu_batch

    if getattr(args, "prover", "tpu") == "native":
        # multi-column CPU batch tier: ONE base sweep per G1 MSM family
        # across the whole batch (ZKP2P_MSM_MULTI=0 falls back to
        # sequential per-proof proves inside)
        from ..prover.native_prove import prove_native_batch as prove_tpu_batch  # noqa: F811

    cs, meta = _build_circuit(args.circuit, args.max_header, args.max_body)
    zk = _load_zkey(args)
    _check_zkey_matches(zk, cs)
    dpk = device_pk_from_zkey(zk, infer_widths=_infer_widths(args))
    # Per-circuit input type: email circuits consume .eml, the rest .json
    # ({"message": ...}) — one glob per circuit so a stray file of the
    # other type can't crash the batch or collide on output basenames.
    ext = "*.eml" if args.circuit in ("venmo", "email_verify") else "*.json"
    files = sorted(glob.glob(os.path.join(args.indir, ext)))
    if not files:
        raise SystemExit(f"no {ext} inputs in {args.indir}")
    wits, pubs = [], []
    for fp in files:
        w, pub = _witness_for(args, cs, meta, source=fp)
        wits.append(w)
        pubs.append(pub)
    t0 = time.perf_counter()
    proofs = prove_tpu_batch(dpk, wits)
    dt = time.perf_counter() - t0
    _log(f"batch of {len(wits)} proved in {dt:.1f}s -> {len(wits)/dt:.2f} proofs/s")
    os.makedirs(args.outdir, exist_ok=True)
    for fp, proof, pub in zip(files, proofs, pubs):
        base = os.path.basename(fp).rsplit(".", 1)[0]
        dump(proof_to_json(proof), os.path.join(args.outdir, base + ".proof.json"))
        dump(public_to_json(pub), os.path.join(args.outdir, base + ".public.json"))
    _log(f"wrote {len(proofs)} proofs to {args.outdir}")


def cmd_service(args):
    """Run the batched proving service daemon over a spool directory
    (queue -> witness||prove -> verify sample -> emit;
    pipeline.service.ProvingService)."""
    from ..pipeline.service import ProvingService
    from ..prover.groth16_tpu import device_pk_from_zkey

    if args.circuit not in ("venmo", "email_verify"):
        raise SystemExit("service supports the email circuits (venmo, email_verify)")
    from ..formats.proof_json import load, vkey_from_json

    cs, meta = _build_circuit(args.circuit, args.max_header, args.max_body)
    zk = _load_zkey(args)
    _check_zkey_matches(zk, cs)
    dpk = device_pk_from_zkey(zk, infer_widths=_infer_widths(args))
    vk = vkey_from_json(load(os.path.join(args.build_dir, "verification_key.json")))
    params, lay = meta
    prover_fn = None
    if getattr(args, "prover", "tpu") == "native":
        # the service fast path: whole claimed batches feed the native
        # multi-column prover (one base sweep, S scalar columns per G1
        # MSM family) instead of a per-request prove loop
        from ..prover.native_prove import prove_native_batch as prover_fn  # noqa: F811

    # SLO observability (docs/OBSERVABILITY.md §SLO): the flags ride the
    # env knobs (the tracker and sampler read the typed config), written
    # BEFORE run() so preflight arms the gates with the operator's values
    if getattr(args, "slo_p95_s", None) is not None:
        os.environ["ZKP2P_SLO_P95_S"] = str(args.slo_p95_s)
    if getattr(args, "ts_sample_s", None) is not None:
        os.environ["ZKP2P_TS_SAMPLE_S"] = str(args.ts_sample_s)
    # adaptive scheduler arm (pipeline.sched; fresh-read per sweep, so
    # the env write is the whole wiring)
    if getattr(args, "sched_flag", None) is not None:
        os.environ["ZKP2P_SCHED"] = args.sched_flag
    # fault-tolerance policy (docs/ROBUSTNESS.md): flags override the
    # ZKP2P_DEADLINE_S / ZKP2P_SPOOL_CAP config defaults; None defers
    svc_kw = dict(
        batch_size=args.batch, prover_fn=prover_fn, prefetch=args.prefetch,
        stale_claim_s=args.stale_claim_s, deadline_s=args.deadline_s,
        spool_cap=args.spool_cap,
    )
    if args.circuit == "venmo":
        svc = ProvingService.for_venmo(cs, lay, params, dpk, vk, **svc_kw)
    else:

        def witness_fn(payload):
            from ..inputs.email import email_verify_from_eml, generate_email_verify_inputs

            with open(payload["eml_path"], "rb") as f:
                email, modulus = email_verify_from_eml(f.read())
            inputs = generate_email_verify_inputs(email, modulus, params, lay)
            return cs.witness(inputs.public_signals, inputs.seed)

        svc = ProvingService(
            cs, dpk, vk, witness_fn, lambda w: list(w[1 : cs.num_public + 1]), **svc_kw
        )
    os.makedirs(args.spool, exist_ok=True)
    # graceful drain (docs/ROBUSTNESS.md §fleet): SIGTERM/SIGINT stop
    # claiming, finish in-flight batches, flush sinks, exit 0 — so a
    # fleet restart (or a plain ^C) loses zero requests
    from ..pipeline.fleet import install_drain_handlers

    install_drain_handlers(svc)
    _log(f"service sweeping {args.spool} (batch={args.batch})")
    why = svc.run(
        args.spool, poll_s=args.poll, max_sweeps=args.max_sweeps,
        max_seconds=args.max_seconds,
        exit_when_spool_terminal=args.exit_when_terminal,
    )
    # exit-code contract (the supervisor and init systems key off it):
    # 0 = clean (drained / spool terminal / sweeps done), 2 = timeout
    sys.exit(0 if why in ("drained", "terminal", "sweeps") else 2)


def cmd_fleet(args):
    """Supervise N `service` workers on one spool (pipeline.fleet):
    restart with exponential backoff + crash-loop circuit breaker,
    graceful drain on SIGTERM/SIGINT with bounded SIGKILL escalation,
    per-worker RSS governor, heartbeat watchdog, and a status.json +
    per-worker auto metrics ports for scrape discovery.  Exit codes:
    0 clean, 3 drain escalated, 4 every worker parked."""
    import json as _json

    from ..pipeline.fleet import FleetSupervisor

    os.makedirs(args.spool, exist_ok=True)
    if args.worker_cmd:
        # advanced/chaos arm: the operator supplies the worker argv
        # (JSON list; '{wid}'/'{spool}' substitute per worker)
        template = _json.loads(args.worker_cmd)
        if not isinstance(template, list) or not template:
            raise SystemExit("--worker-cmd must be a non-empty JSON argv list")

        def worker_cmd(wid):
            return [str(t).replace("{wid}", wid).replace("{spool}", args.spool) for t in template]
    else:
        # default: this same CLI's `service` subcommand, one process per
        # worker, sharing the spool (the claim files arbitrate) and the
        # build dir's key material (the flock'd precomp/plan sidecars
        # serialize the cold builds to ONE across the fleet)
        base = [
            sys.executable, "-m", "zkp2p_tpu",
            "--build-dir", args.build_dir,
            "--circuit", args.circuit,
            "--max-header", str(args.max_header),
            "--max-body", str(args.max_body),
            "service",
            "--spool", args.spool,
            "--batch", str(args.batch),
            "--poll", str(args.poll),
            "--prover", args.prover,
            "--prefetch", str(args.prefetch),
            "--stale-claim-s", str(args.stale_claim_s),
        ]
        if args.zkey:
            base += ["--zkey", args.zkey]
        if args.no_infer_widths:
            base += ["--no-infer-widths"]
        for flag, v in (
            ("--deadline-s", args.deadline_s), ("--spool-cap", args.spool_cap),
            ("--slo-p95-s", args.slo_p95_s), ("--ts-sample-s", args.ts_sample_s),
            ("--sched", args.sched_flag),
        ):
            if v is not None:
                base += [flag, str(v)]

        def worker_cmd(_wid):
            return list(base)

    # fleet observability plane port (stable aggregated /metrics +
    # /status): flag wins, else ZKP2P_FLEET_METRICS_PORT; same
    # "auto"/"0" = ephemeral semantics as the worker metrics port
    fleet_metrics_port = None
    if args.fleet_metrics_port is not None:
        from ..utils.config import _opt_port

        fleet_metrics_port = _opt_port(str(args.fleet_metrics_port))
        if fleet_metrics_port is None:
            raise SystemExit(
                f"--fleet-metrics-port {args.fleet_metrics_port!r}: want a port, 'auto', or 0"
            )

    # a --sched flag on the supervisor reaches workers through the env
    # (the child env inherits; the knob is fresh-read per sweep)
    if args.sched_flag is not None:
        os.environ["ZKP2P_SCHED"] = args.sched_flag
    sup = FleetSupervisor(
        args.spool, worker_cmd,
        workers=args.workers,
        fleet_dir=args.fleet_dir,
        drain_timeout_s=args.drain_timeout_s,
        breaker_k=args.breaker_k,
        breaker_window_s=args.breaker_window_s,
        restart_backoff_s=args.restart_backoff_s,
        rss_soft_mb=args.rss_soft_mb,
        rss_hard_mb=args.rss_hard_mb,
        liveness_s=args.liveness_s,
        fleet_metrics_port=fleet_metrics_port,
        workers_min=args.workers_min,
        workers_max=args.workers_max,
        scale_up_s=args.scale_up_s,
        scale_down_s=args.scale_down_s,
        log=lambda m: _log(f"fleet: {m}"),
    )
    # the supervisor's own exposition (fleet gauges/counters) — workers
    # get auto ports regardless (FleetSupervisor rewrites the env)
    from ..utils.metrics import maybe_start_metrics_server

    maybe_start_metrics_server()
    _log(
        f"fleet {sup.fleet_id}: {sup.n} worker(s) on {args.spool} "
        f"(fleet dir {sup.fleet_dir}, drain timeout {sup.drain_timeout_s:g}s)"
    )
    sys.exit(sup.run(max_seconds=args.max_seconds))


def cmd_top(args):
    """Live fleet terminal view: poll the fleet plane's /status and
    render worker table + merged SLO + active alerts (pipeline.fleet_obs
    renders; this loop only fetches).  The endpoint is found from
    --url, --port, or a --fleet-dir's status.json (`metrics_port` —
    the supervisor records its bound port there, so `zkp2p-tpu top
    --fleet-dir <spool>/.fleet` needs no port bookkeeping)."""
    import time as _time

    from ..pipeline.fleet_obs import discover_fleet_port, http_status_json, render_top

    def resolve_url() -> str:
        if args.url:
            return args.url.rstrip("/") + ("" if args.url.rstrip("/").endswith("/status") else "/status")
        port = args.port
        if port is None and args.fleet_dir:
            port = discover_fleet_port(args.fleet_dir)
            if port is None:
                raise SystemExit(
                    f"{args.fleet_dir}/status.json has no metrics_port — is the "
                    "fleet running with --fleet-metrics-port (or ZKP2P_FLEET_METRICS_PORT)?"
                )
        if port is None:
            raise SystemExit("top needs --url, --port, or --fleet-dir")
        return f"http://127.0.0.1:{port}/status"

    url = resolve_url()
    try:
        while True:
            # a 503 body still renders (the reason line is the point);
            # transport failure degrades to an unreachable frame, not a die
            body = http_status_json(url, timeout=5) or {"ok": False, "reason": f"unreachable: {url}"}
            frame = render_top(body)
            if args.once:
                print(frame)
                return
            # clear + home, then the frame (plain ANSI; no curses dep)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            _time.sleep(args.interval)
    except KeyboardInterrupt:
        # Ctrl-C is the live view's ONLY interactive exit — leave the
        # last frame on screen, not a stack trace over it
        print()


def cmd_serve(args):
    """Serve the client order-book UI (client/web.py) with the in-process
    escrow; --with-prover loads the build dir's zkey so /api/onramp can
    prove receipts on the TPU."""
    import time as _time

    from ..client.web import OnrampApp, ProverBundle, serve
    from ..contracts.deploy import VENMO_RSA_KEY_LIMBS
    from ..contracts.ramp import FakeUSDC, Ramp
    from ..formats.proof_json import load, vkey_from_json

    vk = vkey_from_json(load(os.path.join(args.build_dir, "verification_key.json")))
    usdc = FakeUSDC()
    # --demo deploys the escrow with the synthetic test key's modulus limbs:
    # the UI's synthetic /api/onramp path proves against make_test_key(1), so
    # a Ramp holding the production Venmo limbs would reject every demo proof
    # with 'RSA modulus not matched' (r3 advisor).  Without --demo the served
    # form only offers the server-side .eml path.
    if args.demo:
        from ..gadgets.bigint import int_to_limbs_host
        from ..inputs.email import make_test_key

        key_limbs = int_to_limbs_host(make_test_key(1).n, 121, 17)
    else:
        key_limbs = VENMO_RSA_KEY_LIMBS
    ramp = Ramp(key_limbs, usdc, max_amount=args.max_amount, vk=vk)
    prover = None
    if args.with_prover:
        from ..prover.groth16_tpu import device_pk_from_zkey

        if args.circuit != "venmo":
            raise SystemExit("/api/onramp proves venmo receipts; pass --circuit venmo")
        cs, meta = _build_circuit(args.circuit, args.max_header, args.max_body)
        zk = _load_zkey(args)
        _check_zkey_matches(zk, cs)
        prover = ProverBundle(cs=cs, dpk=device_pk_from_zkey(zk, infer_widths=_infer_widths(args)), params=meta[0], layout=meta[1])
        _log("prover bundle loaded")
    app = OnrampApp(
        ramp, usdc, prover, eml_spool=args.eml_spool,
        zkey_store=getattr(args, "zkey_store", None),
        zkey_cache=os.path.join(args.build_dir, "zkey_cache"),
    )
    srv = serve(app, port=args.port)
    _log(f"serving on http://127.0.0.1:{srv.server_address[1]} (ctrl-c to stop)")
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()


def cmd_lint(args):
    """Run the zkp2p-lint suite (tools/lint) over this checkout.  The
    linter lives beside the tools it polices rather than inside the
    package, so it can parse a tree whose imports are broken — exactly
    the tree that needs linting most."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.lint import main as lint_main

    argv = []
    if args.rules:
        argv += ["--rules", args.rules]
    if args.json:
        argv.append("--json")
    if args.circuits is not None:
        argv += ["--circuits", args.circuits] if args.circuits != "all" else ["--circuits"]
        if args.flagship:
            argv.append("--flagship")
        if args.no_cache:
            argv.append("--no-cache")
    raise SystemExit(lint_main(argv))


def cmd_doctor(args):
    """Execution-path preflight: probe the backend, arm EVERY gate
    through its real resolver, and report which arm each one took —
    the tool that would have caught the round-2 silent disarm (plugin
    renamed, every `default_backend()=="tpu"` gate quietly off) in one
    run instead of a burned 50-minute tunnel window.

    Machine output (`--json`) is one JSON object on stdout: backend,
    structured tpu_probe, gate→arm map, knobs+provenance, warnings,
    device memory (when the backend exposes it) and the execution
    digest — the comparison key two runs must share before their
    numbers are comparable."""
    import json as _json

    from ..utils.audit import preflight

    # log=None: the text mode below prints rep["warnings"] itself —
    # letting preflight log them too would show every mis-arm twice
    rep = preflight(probe=not args.no_probe, workload=not args.no_workload)
    if args.json:
        print(_json.dumps(rep))
    else:
        probe = rep["tpu_probe"]
        if probe.get("skipped"):
            probe_s = "skipped"
        elif probe.get("ok"):
            probe_s = f"ok ({probe['seconds']}s, platform={probe.get('platform')})"
        elif probe.get("timed_out"):
            probe_s = f"TIMED OUT after {probe.get('timeout_s')}s (tunnel wedged?)"
        else:
            probe_s = f"down (rc={probe.get('rc')}, {probe.get('seconds')}s)"
        _log(f"backend: {rep['backend']}   tpu probe: {probe_s}")
        prov = rep["provenance"]
        gate_knob = {  # gate -> the knob that steers it, for the listing
            "field_mul": "field_mul", "curve_kernel": "curve_kernel",
            "msm_unified": "msm_unified", "msm_affine": "msm_affine",
            "msm_h": "msm_h", "msm_glv": "msm_glv", "batch_chunk": "batch_chunk",
            "native_msm_glv": "msm_glv", "native_batch_affine": "msm_batch_affine",
            "native_tier": "native_ifma",
        }
        _log("gates:")
        for gate, arm in sorted(rep["gates"].items()):
            src = f"  [{gate_knob[gate]}:{prov.get(gate_knob[gate])}]" if gate in gate_knob else ""
            _log(f"  {gate:<22} = {arm}{src}")
        if rep.get("workload_s") is not None:
            _log(f"workload: tiny jit ran in {rep['workload_s']}s")
        mem = rep.get("device_memory")
        if mem:
            _log(
                f"device memory: {mem['bytes_in_use']/2**30:.2f} GiB in use, "
                f"peak {mem['peak_bytes_in_use']/2**30:.2f} GiB"
                + (f" of {mem['bytes_limit']/2**30:.2f} GiB" if mem.get("bytes_limit") else "")
            )
        _log(f"execution digest: {rep['execution_digest']}")
        for w in rep["warnings"]:
            _log(f"WARNING: {w}")
        if not rep["warnings"]:
            _log("no mis-armed gates detected")
    if args.strict and rep["warnings"]:
        sys.exit(1)


def cmd_tune(args):
    """Budgeted host micro-sweep → fingerprint-keyed profile beside
    .bench_cache (pipeline.tune).  Every resolver that today falls back
    to a hand-picked constant (fixed-tier MSM geometry, native thread
    default, the scheduler's amortization curve) loads the profile at
    startup; `--out` writes elsewhere (set ZKP2P_PROFILE_PATH to load
    it), `--arms` filters the sweep, `--budget-s` caps wall clock."""
    from .tune import run_tune

    prof = run_tune(
        n=args.n,
        reps=args.reps,
        budget_s=args.budget_s,
        out_path=args.out or None,
        arms_spec=args.arms,
        log=_log,
    )
    if prof is None:
        _log("tune: nothing tuned (native library unavailable)")
        sys.exit(1)
    # perf-ledger stamp: the sweep's measured bests become one
    # structured entry (source=tune) so host slowdowns show up as a
    # trend across tunes, not just a changed profile on disk
    try:
        from ..utils.perfledger import record as perf_record, tune_stages

        where = perf_record("tune", "microbench", tune_stages(prof))
        if where:
            _log(f"tune: sweep bests stamped into the perf ledger ({where})")
    except Exception:  # noqa: BLE001 — observation must never fail the tune
        pass


def cmd_warm_cache(args):
    """Pre-populate the persistent XLA compile cache with the batch
    prover's executables, so the first REAL batch of a service/fleet
    session dispatches warm instead of paying the multi-minute
    shard_map compile inline (docs/TPU.md §warm-start).

    The executables XLA caches are keyed by SHAPES (circuit wires +
    domain, batch width, mesh geometry, window), not key material — so
    a dev in-memory setup over the same circuit warms exactly the
    entries a production zkey will hit, and no zkey file is needed.
    Run it with the same --circuit/--batch and ZKP2P_TPU_SHARD/
    ZKP2P_TPU_MESH (or --shard) the service will use."""
    # knob wiring BEFORE any compile — flags ride the env knobs like
    # cmd_service's --sched (the prover's shard gate fresh-reads)
    if args.shard:
        os.environ["ZKP2P_TPU_SHARD"] = "on"
        if args.shard != "on":
            os.environ["ZKP2P_TPU_MESH"] = args.shard
    if args.cache_dir:
        os.environ["ZKP2P_JAX_CACHE_DIR"] = args.cache_dir
    # re-assert the cache with a ZERO compile-time floor: main() enabled
    # it with the 1.0 s default, which would skip sub-second executables
    # (the toy-circuit smoke depends on those round-tripping)
    from ..utils.audit import install_compile_listener
    from ..utils.jaxcfg import cache_dir as _resolved_cache_dir, enable_cache

    enable_cache(path=args.cache_dir or None, min_compile_s=0.0)
    install_compile_listener()
    from ..utils.metrics import REGISTRY

    def _compile_totals():
        ev = secs = 0.0
        for m in REGISTRY.snapshot():
            if m["name"] == "zkp2p_compile_events_total":
                ev += m.get("value", 0.0)
            elif m["name"] == "zkp2p_compile_seconds_total":
                secs += m.get("value", 0.0)
        return ev, secs

    cdir = _resolved_cache_dir(args.cache_dir or None)

    def _cache_entries():
        files = total = 0
        for root, _dirs, fns in os.walk(cdir):
            for fn in fns:
                files += 1
                try:
                    total += os.path.getsize(os.path.join(root, fn))
                except OSError:
                    pass
        return files, total

    f0, b0 = _cache_entries()
    ev0, s0 = _compile_totals()

    cs, meta = _build_circuit(args.circuit, args.max_header, args.max_body)
    from ..prover import device_pk
    from ..prover.groth16_tpu import prove_tpu_batch
    from ..snark.groth16 import setup

    pk, _vk = setup(cs)
    dpk = device_pk(pk, cs)
    w, _pub = _witness_for(args, cs, meta)
    wits = [w] * max(1, args.batch)
    _log(f"warm-cache: compiling batch={len(wits)} of {args.circuit!r} into {cdir}")
    t0 = time.perf_counter()
    prove_tpu_batch(dpk, wits)
    dt = time.perf_counter() - t0
    f1, b1 = _cache_entries()
    ev1, s1 = _compile_totals()
    from ..utils.audit import gate_arms

    _log(
        f"warm-cache: {dt:.1f}s wall, {ev1 - ev0:.0f} compiles "
        f"({s1 - s0:.1f}s compile time), cache {'+' if f1 >= f0 else ''}{f1 - f0} "
        f"entries ({(b1 - b0) / 2**20:.1f} MiB) -> {f1} total"
    )
    _log(f"warm-cache: tpu_shard arm = {gate_arms().get('tpu_shard', 'off')}")
    # warm runs still fire backend_compile EVENTS (the cache hit and its
    # deserialization happen inside the span) — zero NEW entries is the
    # round-trip proof
    if f1 - f0 == 0:
        _log("warm-cache: zero new cache entries — every executable loaded warm")
    # perf-ledger stamp: the round trip's wall + backend_compile rail
    # (source=warm_cache) — a cold-start regression (cache miss storm,
    # slower deserialize) becomes a ledger trend, not a vibe
    try:
        from ..utils.perfledger import record as perf_record

        wall_ms = round(dt * 1e3, 3)
        compile_ms = round((s1 - s0) * 1e3, 3)
        where = perf_record(
            "warm_cache", args.circuit,
            {
                "warm_cache/wall": {"p50_ms": wall_ms, "p95_ms": wall_ms, "n": 1},
                "warm_cache/backend_compile": {
                    "p50_ms": compile_ms, "p95_ms": compile_ms,
                    "n": max(1, int(ev1 - ev0)),
                },
            },
        )
        if where:
            _log(f"warm-cache: round trip stamped into the perf ledger ({where})")
    except Exception:  # noqa: BLE001 — observation must never fail the warm
        pass


def cmd_flame(args):
    """On-demand flame profile (utils.flameprof; docs/OBSERVABILITY.md
    §flame profiler): run a real prove loop under the sampling profiler
    for --duration seconds, print the collapsed-stack profile
    (flamegraph.pl wire format — pipe into flamegraph.pl directly) and
    write a trigger="manual" capture file beside .bench_cache, which
    `tools/trace_report.py --flame <capture> --chrome-trace out.json`
    merges into a Perfetto track."""
    from ..utils import flameprof
    from ..utils.config import load_config

    # flags are TRANSPORT: arm the gate for this invocation so the
    # sampler may run and the recorded arm (and digest) reflect it
    os.environ["ZKP2P_FLAME"] = "1"
    if args.hz is not None:
        os.environ["ZKP2P_FLAME_HZ"] = str(args.hz)
    _log(f"flame: arm {flameprof.flame_arm()}")
    cfg = load_config()

    from ..prover.groth16_tpu import device_pk_from_zkey

    prove_fn = _prover_fn(args)
    cs, meta = _build_circuit(args.circuit, args.max_header, args.max_body)
    try:
        zk = _load_zkey(args)
        _check_zkey_matches(zk, cs)
        dpk = device_pk_from_zkey(zk, infer_widths=_infer_widths(args))
    except (OSError, SystemExit):
        # no zkey on disk: a dev setup keeps the command self-contained
        # (the profile's shape is what matters, not the key's origin)
        _log("flame: no zkey found — running the dev setup in-process")
        from ..prover.groth16_tpu import device_pk
        from ..snark.groth16 import setup

        pk, _vk = setup(cs, seed="flame-profile")
        dpk = device_pk(pk, cs)
    w, _pub = _witness_for(args, cs, meta)

    # one warmup prove OUTSIDE the sampler: first-call compiles and
    # table builds are real costs, but not the steady state a profile
    # is meant to attribute
    prove_fn(dpk, w)

    sampler = flameprof.FlameSampler(hz=cfg.flame_hz).start()
    t0 = time.perf_counter()
    proves = 0
    while True:
        prove_fn(dpk, w)
        proves += 1
        if time.perf_counter() - t0 >= args.duration:
            break
    path = flameprof.write_capture(
        sampler, circuit=args.circuit, stage="on-demand", trigger="manual",
    )
    body = sampler.result()
    _log(
        f"flame: {proves} prove(s) in {body['duration_s']:.1f}s — "
        f"{body['samples']} samples over {body['windows']} windows "
        f"@ {cfg.flame_hz:g} Hz, sampler self-cost "
        f"{body['sampler']['self_ms']:.1f} ms"
    )
    if path:
        _log(f"flame: capture -> {path}")
    else:
        _log("flame: capture NOT persisted (cache dir disabled)")
    print(flameprof.collapsed_text(body["stacks"]))


def cmd_perf(args):
    """Perf-regression sentry (utils.perfledger; docs/OBSERVABILITY.md
    §perf sentry): render per-(circuit, stage) trendlines + regression
    verdicts from the host's stage-cost ledger; `--backfill` imports
    the committed BENCH_r*.json history, `--rebaseline` freezes current
    budgets as PERF_BASELINE.json, `--gate` replays the ledger head
    against the committed band and exits nonzero on drift (the `make
    perf-gate` engine — rc 1 drift, rc 2 fail-closed)."""
    from ..utils import flameprof
    from ..utils import perfledger as pl
    from ..utils.config import load_config

    did_action = False
    if args.backfill:
        did_action = True
        n = pl.backfill_bench(log=_log)
        _log(f"perf: backfill appended {n} entr{'y' if n == 1 else 'ies'}")
    if args.rebaseline:
        did_action = True
        doc = pl.write_baseline(
            baseline_path=args.baseline or None, ledger_path=args.ledger or None,
            window=args.window, tolerance=args.tolerance,
        )
        if doc is None:
            _log("perf: rebaseline FAILED — no valid ledger entries to freeze "
                 "(run a bench / tune / service sweep, or --backfill, first)")
            sys.exit(2)
        bands = sum(len(v) for v in doc["bands"].values())
        _log(f"perf: baseline frozen — {bands} band(s), "
             f"window={doc['window']} tolerance={doc['tolerance']:g}")
    if args.gate:
        rc, verdicts = pl.gate_check(
            baseline_path=args.baseline or None, ledger_path=args.ledger or None,
            log=_log,
        )
        for v in verdicts:
            if v["verdict"] in ("new", "gone"):
                print(f"{v['verdict']:<8} {v['circuit']}/{v['stage']}")
                continue
            print(
                f"{v['verdict']:<8} {v['circuit']}/{v['stage']}: "
                f"head p50 {v['p50_ms']:.1f} ms vs budget {v['budget_ms']:.1f} ms "
                f"(band median {v['median_ms']:.1f} ms)"
            )
            # DRIFT -> the flame capture that shows WHY (utils.flameprof)
            if v["verdict"] == "DRIFT":
                for cpath, cdoc in flameprof.captures_for(
                    v["circuit"], v["stage"]
                )[:1]:
                    print(f"       capture: {cpath} "
                          f"(trigger {cdoc.get('trigger')}, "
                          f"entry {cdoc.get('entry_digest')})")
        drifts = sum(1 for v in verdicts if v["verdict"] == "DRIFT")
        improved = sum(1 for v in verdicts if v["verdict"] == "IMPROVED")
        print(f"perf-gate: {'DRIFT' if rc == 1 else 'FAIL CLOSED' if rc else 'ok'} "
              f"({drifts} drifting stage(s) of {len(verdicts)})")
        # a head landing well UNDER its band means the band is stale-
        # loose: say so and name the fix — the improvement becomes the
        # guarded floor only after a rebaseline
        if improved:
            print(f"perf-gate: {improved} IMPROVED stage(s) — band is "
                  "stale-loose; freeze the new floor with "
                  "`zkp2p-tpu perf --rebaseline`")
        sys.exit(rc)
    if did_action:
        return
    # default: trendlines + verdicts against the current budgets
    entries, refused = pl.load_entries(args.ledger or None)
    if not entries:
        _log(f"perf: no valid ledger entries for this host (refused: {refused})")
        sys.exit(1)
    cfg = load_config()
    budgets = pl.derive_budgets(entries, window=args.window, tolerance=args.tolerance)
    series = {}
    for e in entries:
        circuit = str(e.get("circuit", "?"))
        if args.circuit and circuit != args.circuit:
            continue
        for stage, st in e["stages"].items():
            if args.stage and args.stage not in stage:
                continue
            series.setdefault((circuit, stage), []).append(float(st["p50_ms"]))
    if args.json:
        print(json.dumps({
            "budgets": budgets,
            "series": {f"{c}/{s}": v for (c, s), v in sorted(series.items())},
            "refused": refused,
        }, indent=1, sort_keys=True))
        return
    marks = "_.-=#"  # low..high within each stage's own range
    for (circuit, stage), vals in sorted(series.items()):
        lo, hi = min(vals), max(vals)
        span = (hi - lo) or 1.0
        line = "".join(marks[int((v - lo) / span * (len(marks) - 1))] for v in vals[-48:])
        b = (budgets.get(circuit) or {}).get(stage)
        if b is None:
            verdict = "no-budget"
        else:
            verdict = "REGRESSED" if vals[-1] > b["budget_ms"] else "ok"
        print(
            f"{circuit}/{stage:<28} [{line}] last {vals[-1]:.1f} ms "
            + (f"budget {b['budget_ms']:.1f} ms " if b else "")
            + f"(n={len(vals)}) {verdict}"
        )
        # a REGRESSED stage with an overrun-triggered capture on disk
        # gets the pointer printed under its trendline — the sentry's
        # "that" row linked to the sampler's "why" file
        if verdict == "REGRESSED":
            for cpath, cdoc in flameprof.captures_for(circuit, stage)[:1]:
                print(f"    capture: {cpath} (trigger {cdoc.get('trigger')}, "
                      f"entry {cdoc.get('entry_digest')})")
    if any(refused.values()):
        _log(f"perf: refused entries: {refused} "
             f"(window={cfg.perf_window} tolerance={cfg.perf_tolerance:g})")


def main(argv=None):
    ap = argparse.ArgumentParser("zkp2p-tpu", description=__doc__)
    ap.add_argument("--build-dir", default=os.environ.get("BUILD_DIR", "build"))
    ap.add_argument("--circuit", default=os.environ.get("CIRCUIT_NAME", "sha256"))
    ap.add_argument("--max-header", type=int, default=256)
    ap.add_argument("--max-body", type=int, default=192)
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("setup", help="build circuit + dev zkey + vkey + verifier.sol")
    s.add_argument("--skip-audit", action="store_true",
                   help="bypass the circuit soundness audit (admission gate)")
    s.add_argument("--seed", default="zkp2p-tpu-dev")
    s.add_argument("--chunks", type=int, default=0, help="also split the zkey into N chunks (b..)")
    s.add_argument("--publish", help="artifact-store dir: upload gzip zkey chunks + manifest")
    s.set_defaults(fn=cmd_setup)

    s = sub.add_parser("prove", help="prove one input on TPU")
    s.add_argument("--eml", help="email file (venmo / email_verify circuits)")
    s.add_argument("--demo", action="store_true", help="use the synthetic signed email")
    s.add_argument("--message", help="message (sha256 circuit)")
    s.add_argument("--zkey", help="zkey path or chunk glob (default: BUILD_DIR/circuit_final.zkey)")
    s.add_argument("--no-infer-widths", action="store_true", help="disable the zkey bit-constraint width inference (use when the circuit contains x*(x-1)=y rows)")
    s.add_argument("--zkey-store", help="artifact-store dir to pull the chunked zkey from")
    s.add_argument("--wtns", help="externally generated witness.wtns (drop-in prover parity)")
    s.add_argument("--prover", choices=["tpu", "native"], default="tpu",
                   help="tpu: XLA device path; native: C++ Pippenger runtime")
    s.add_argument("--order-id", type=int, default=1)
    s.add_argument("--claim-id", type=int, default=0)
    s.add_argument("--proof", default="proof.json")
    s.add_argument("--public", default="public.json")
    s.set_defaults(fn=cmd_prove)

    s = sub.add_parser("verify", help="verify proof.json against the vkey")
    s.add_argument("--proof", default="proof.json")
    s.add_argument("--public", default="public.json")
    s.set_defaults(fn=cmd_verify)

    s = sub.add_parser("service", help="run the batched proving service over a spool dir")
    s.add_argument("--spool", required=True)
    s.add_argument("--batch", type=int, default=4)
    s.add_argument("--poll", type=float, default=1.0)
    s.add_argument("--max-sweeps", type=int, default=None)
    s.add_argument("--zkey", help="zkey path or chunk glob")
    s.add_argument("--no-infer-widths", action="store_true", help="disable the zkey bit-constraint width inference")
    s.add_argument("--prover", choices=["tpu", "native"], default="tpu",
                   help="tpu: vmapped XLA batch; native: C++ runtime, sequential")
    s.add_argument("--prefetch", type=int, default=1, help="ready-batch queue depth")
    s.add_argument("--stale-claim-s", type=float, default=300.0,
                   help="claim age after which a dead worker's request is taken over")
    s.add_argument("--deadline-s", type=float, default=None,
                   help="default per-request deadline in s (payload deadline_s overrides; "
                        "default: ZKP2P_DEADLINE_S; 0 = none)")
    s.add_argument("--spool-cap", type=int, default=None,
                   help="max pending requests admitted per sweep — the excess is shed as "
                        "error-shed (default: ZKP2P_SPOOL_CAP; 0 = unlimited)")
    s.add_argument("--slo-p95-s", type=float, default=None,
                   help="p95 latency objective in s for the SLO tracker + /status "
                        "(default: ZKP2P_SLO_P95_S; 0 = none)")
    s.add_argument("--ts-sample-s", type=float, default=None,
                   help="time-series sampler interval in s "
                        "(default: ZKP2P_TS_SAMPLE_S; 0 = off)")
    s.add_argument("--sched", dest="sched_flag", choices=["off", "adaptive"], default=None,
                   help="batching/admission scheduler: off = static batch_size + "
                        "newest-first shed; adaptive = SLO-driven sizing, deadline-"
                        "aware shed, priority lanes (default: ZKP2P_SCHED)")
    s.add_argument("--max-seconds", type=float, default=None,
                   help="exit (rc 2) after this many seconds (tests/fleet smokes)")
    s.add_argument("--exit-when-terminal", action="store_true",
                   help="exit 0 once every spool request has a terminal artifact")
    s.set_defaults(fn=cmd_service)

    s = sub.add_parser(
        "fleet",
        help="supervise N service workers on one spool (restart/backoff/"
             "circuit-breaker, graceful drain, RSS governor)",
    )
    s.add_argument("--spool", required=True)
    s.add_argument("--workers", type=int, default=None,
                   help="worker count (default: ZKP2P_FLEET_WORKERS)")
    s.add_argument("--batch", type=int, default=4)
    s.add_argument("--poll", type=float, default=1.0)
    s.add_argument("--zkey", help="zkey path or chunk glob")
    s.add_argument("--no-infer-widths", action="store_true",
                   help="disable the zkey bit-constraint width inference")
    s.add_argument("--prover", choices=["tpu", "native"], default="native",
                   help="worker prover arm (native = multi-column C batch path)")
    s.add_argument("--prefetch", type=int, default=1)
    s.add_argument("--stale-claim-s", type=float, default=300.0)
    s.add_argument("--deadline-s", type=float, default=None)
    s.add_argument("--spool-cap", type=int, default=None)
    s.add_argument("--slo-p95-s", type=float, default=None)
    s.add_argument("--ts-sample-s", type=float, default=None)
    s.add_argument("--fleet-dir", default=None,
                   help="heartbeat/ctl/status dir (default: <spool>/.fleet)")
    s.add_argument("--drain-timeout-s", type=float, default=None,
                   help="bounded wait between SIGTERM and SIGKILL escalation "
                        "(default: ZKP2P_DRAIN_TIMEOUT_S)")
    s.add_argument("--liveness-s", type=float, default=60.0,
                   help="heartbeat age past which a live worker counts as hung")
    s.add_argument("--rss-soft-mb", type=int, default=None,
                   help="per-worker RSS soft budget: degrade ctl (default: ZKP2P_RSS_SOFT_MB; 0 = off)")
    s.add_argument("--rss-hard-mb", type=int, default=None,
                   help="per-worker RSS hard budget: drain + restart (default: ZKP2P_RSS_HARD_MB; 0 = off)")
    s.add_argument("--breaker-k", type=int, default=None,
                   help="failures inside the window that park a worker (default: ZKP2P_BREAKER_K)")
    s.add_argument("--breaker-window-s", type=float, default=None,
                   help="circuit-breaker window (default: ZKP2P_BREAKER_WINDOW_S)")
    s.add_argument("--restart-backoff-s", type=float, default=None,
                   help="exponential restart-backoff base (default: ZKP2P_RESTART_BACKOFF_S)")
    s.add_argument("--max-seconds", type=float, default=None,
                   help="drain + exit after this long (tests/chaos)")
    s.add_argument("--worker-cmd", default=None,
                   help="JSON argv for each worker (advanced/chaos; '{wid}' and "
                        "'{spool}' substitute) — default spawns 'zkp2p-tpu service' workers")
    s.add_argument("--fleet-metrics-port", default=None,
                   help="fleet observability plane port: aggregated /metrics + /status "
                        "+ /healthz ('auto'/0 = ephemeral, recorded in status.json; "
                        "default: ZKP2P_FLEET_METRICS_PORT; unset = plane off)")
    s.add_argument("--sched", dest="sched_flag", choices=["off", "adaptive"], default=None,
                   help="worker batching/admission scheduler arm (default: ZKP2P_SCHED)")
    s.add_argument("--workers-min", type=int, default=None,
                   help="autoscale floor (default: ZKP2P_WORKERS_MIN)")
    s.add_argument("--workers-max", type=int, default=None,
                   help="autoscale ceiling; 0 = autoscale off "
                        "(default: ZKP2P_WORKERS_MAX)")
    s.add_argument("--scale-up-s", type=float, default=None,
                   help="how long backlog growth / slo burn must hold before +1 worker "
                        "(default: ZKP2P_SCALE_UP_S)")
    s.add_argument("--scale-down-s", type=float, default=None,
                   help="how long an idle backlog must hold before -1 worker "
                        "(default: ZKP2P_SCALE_DOWN_S)")
    s.set_defaults(fn=cmd_fleet)

    s = sub.add_parser("top", help="live fleet view: poll the fleet /status and render it")
    s.add_argument("--url", help="full fleet status URL (overrides --port/--fleet-dir)")
    s.add_argument("--port", type=int, default=None, help="fleet plane port on 127.0.0.1")
    s.add_argument("--fleet-dir", help="read the port from <fleet-dir>/status.json")
    s.add_argument("--interval", type=float, default=2.0, help="poll interval in s")
    s.add_argument("--once", action="store_true",
                   help="print one frame and exit (scripts/tests)")
    s.set_defaults(fn=cmd_top)

    s = sub.add_parser("serve", help="serve the client order-book UI")
    s.add_argument("--port", type=int, default=8080)
    s.add_argument("--max-amount", type=int, default=10_000_000)
    s.add_argument("--with-prover", action="store_true", help="load the zkey so /api/onramp proves")
    s.add_argument("--zkey", help="zkey path or chunk glob")
    s.add_argument("--no-infer-widths", action="store_true", help="disable the zkey bit-constraint width inference")
    s.add_argument("--demo", action="store_true", help="deploy the escrow with the synthetic test-key limbs")
    s.add_argument("--eml-spool", help="directory server-side .eml paths are restricted to")
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser("ceremony", help="phase-2 zkey MPC: contribute / beacon / verify")
    s.add_argument("op", choices=["contribute", "beacon", "verify"])
    s.add_argument("zkey_in", help="input zkey (for verify: the trusted initial zkey)")
    s.add_argument("zkey_out", help="output zkey (for verify: the final zkey to check)")
    s.add_argument("--entropy", default="", help="contributor entropy string (contribute)")
    s.add_argument("--name", default="", help="contributor name recorded in the transcript")
    s.add_argument("--beacon-hash", default="", help="public beacon value, hex (beacon)")
    s.add_argument("--iter-exp", type=int, default=10, help="beacon hash iterations = 2^n (beacon)")
    s.set_defaults(fn=cmd_ceremony)

    s = sub.add_parser(
        "tune",
        help="budgeted host micro-sweep -> fingerprint-keyed profile (geometry/threads/amortization)",
    )
    s.add_argument("--n", type=int, default=1 << 15,
                   help="MSM shape per micro-arm (default 32768; bigger = more faithful, slower)")
    s.add_argument("--reps", type=int, default=3, help="min-of-reps per measurement")
    s.add_argument("--budget-s", type=float, default=None,
                   help="wall-clock budget (default: ZKP2P_TUNE_BUDGET_S)")
    s.add_argument("--out", default=None,
                   help="profile path (default: .bench_cache/host_profile_<fp>.json)")
    s.add_argument("--arms", default=None,
                   help="comma list of arms (threads,window,geometry,columns,ladder); default: ZKP2P_TUNE_ARMS or all")
    s.set_defaults(fn=cmd_tune)

    s = sub.add_parser(
        "warm-cache",
        help="pre-compile the batch prover into the persistent XLA cache "
             "(sharded arm included when ZKP2P_TPU_SHARD/--shard asks)",
    )
    s.add_argument("--batch", type=int, default=8,
                   help="batch width to compile for (must match the service's; "
                        "sharded: a multiple of the mesh batch dim)")
    s.add_argument("--shard", nargs="?", const="on", default=None, metavar="BxS",
                   help="arm the sharded batch prover (sets ZKP2P_TPU_SHARD=on; "
                        "an explicit BxS value also sets ZKP2P_TPU_MESH)")
    s.add_argument("--cache-dir", default=None,
                   help="cache root (default: ZKP2P_JAX_CACHE_DIR or <repo>/.jax_cache)")
    s.add_argument("--message", help=argparse.SUPPRESS)
    s.add_argument("--eml", help=argparse.SUPPRESS)
    s.set_defaults(fn=cmd_warm_cache)

    s = sub.add_parser("doctor", help="execution-path preflight: arm every gate, report arms + digest")
    s.add_argument("--json", action="store_true", help="machine-readable report on stdout")
    s.add_argument("--no-probe", action="store_true", help="skip the subprocess TPU probe")
    s.add_argument("--no-workload", action="store_true", help="skip the tiny jitted workload")
    s.add_argument("--strict", action="store_true", help="exit 1 when any gate is mis-armed")
    s.set_defaults(fn=cmd_doctor)

    s = sub.add_parser(
        "lint",
        help="static invariant checks: knob/gate discipline, stats-ABI drift, "
        "metric naming, durability, clocks, pyflakes tier — docs/STATIC_ANALYSIS.md",
    )
    s.add_argument("--rules", default="", help="comma-separated rule filter")
    s.add_argument("--json", action="store_true", help="machine-readable findings")
    s.add_argument(
        "--circuits", nargs="?", const="all", default=None, metavar="IDS",
        help="run the R1CS soundness audit on registered circuits "
        "(the registry admission gate) instead of the source rules",
    )
    s.add_argument("--flagship", action="store_true",
                   help="with --circuits: include the 4.9M-wire flagship")
    s.add_argument("--no-cache", action="store_true",
                   help="with --circuits: ignore cached audit reports")
    # no_jax: lint is the pre-commit path — it must answer in seconds
    # without importing jax or touching the compilation cache (the
    # circuit tier builds real circuits but still needs only numpy)
    s.set_defaults(fn=cmd_lint, no_jax=True)

    s = sub.add_parser(
        "flame",
        help="on-demand flame profile: a real prove loop under the sampler -> "
             "collapsed stacks on stdout + a capture file beside .bench_cache",
    )
    s.add_argument("--duration", type=float, default=30.0,
                   help="prove-loop wall clock in s (at least one prove always runs)")
    s.add_argument("--hz", type=float, default=None,
                   help="sampling rate override (default: ZKP2P_FLAME_HZ)")
    s.add_argument("--zkey", help="zkey path or chunk glob (default: BUILD_DIR/"
                                  "circuit_final.zkey; missing = in-process dev setup)")
    s.add_argument("--no-infer-widths", action="store_true",
                   help="disable the zkey bit-constraint width inference")
    s.add_argument("--prover", choices=["tpu", "native"], default="native",
                   help="prover arm under the sampler (native = the C runtime "
                        "the synthetic frames attribute)")
    s.add_argument("--message", help=argparse.SUPPRESS)
    s.add_argument("--eml", help=argparse.SUPPRESS)
    s.add_argument("--order-id", type=int, default=1)
    s.add_argument("--claim-id", type=int, default=0)
    s.set_defaults(fn=cmd_flame)

    s = sub.add_parser(
        "perf",
        help="perf-regression sentry: ledger trendlines, stage budgets, baseline drift gate",
    )
    s.add_argument("--ledger", default="", help="ledger path override (default: host-keyed beside .bench_cache)")
    s.add_argument("--baseline", default="", help="baseline path (default: PERF_BASELINE.json at the repo root)")
    s.add_argument("--circuit", default="", help="filter trendlines to one circuit label")
    s.add_argument("--stage", default="", help="substring filter over stage names")
    s.add_argument("--window", type=int, default=None, help="trailing-window override (ZKP2P_PERF_WINDOW)")
    s.add_argument("--tolerance", type=float, default=None, help="budget multiplier override (ZKP2P_PERF_TOLERANCE)")
    s.add_argument("--json", action="store_true", help="machine-readable budgets + series")
    s.add_argument("--backfill", action="store_true", help="import committed BENCH_r*.json history (idempotent)")
    s.add_argument("--rebaseline", action="store_true", help="freeze current budgets as the committed baseline band")
    s.add_argument("--gate", action="store_true",
                   help="replay the ledger head against the baseline band; rc 1 = drift, rc 2 = fail closed")
    # no_jax: the sentry reads JSON on disk — it must answer in seconds
    # (and run in CI) without paying a backend import
    s.set_defaults(fn=cmd_perf, no_jax=True)

    s = sub.add_parser("batch", help="prove a directory of inputs as one batch")
    s.add_argument("--indir", required=True)
    s.add_argument("--outdir", required=True)
    s.add_argument("--prover", choices=["tpu", "native"], default="tpu",
                   help="tpu: vmapped XLA batch; native: C++ runtime, sequential")
    s.add_argument("--zkey", help="zkey path or chunk glob")
    s.add_argument("--no-infer-widths", action="store_true", help="disable the zkey bit-constraint width inference")
    s.add_argument("--message", help=argparse.SUPPRESS)
    s.add_argument("--order-id", type=int, default=1)
    s.add_argument("--claim-id", type=int, default=0)
    s.set_defaults(fn=cmd_batch)

    args = ap.parse_args(argv)
    if getattr(args, "no_jax", False):
        args.fn(args)
        return
    from ..utils.jaxcfg import enable_cache

    enable_cache()
    args.fn(args)


if __name__ == "__main__":
    main()
