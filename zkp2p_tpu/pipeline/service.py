"""The batched proving service: queue -> pad to batch -> prove -> verify
sample -> emit (the BASELINE.json north-star service shape).

Failure semantics mirror the reference UI's explicit state machine
(`SubmitOrderGenerateProofForm.tsx:45-56,171-220`): each request ends in
  done | error-bad-input | error-failed-to-prove
with the error recorded next to the request — no silent drops; plus the
verify-after-prove self-check the pipeline scripts do
(`5_gen_proof.sh:15-22` runs `snarkjs groth16 verify` right after prove).

Requests are JSON files in a spool directory (the S3/queue stand-in);
results and errors are written alongside.  Single-process, deliberately
simple: the scheduling story (latency vs batch fill, SURVEY.md §7 hard
part #6) is a bench-driven knob, not a framework constraint.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..formats.proof_json import dump
from ..utils.audit import execution_digest, preflight, sample_device_memory
from ..utils.metrics import REGISTRY, JsonlSink, maybe_start_metrics_server, publish_native_stats, run_id, run_manifest
from ..utils.trace import drain as drain_trace, set_context, trace


@dataclass
class Request:
    path: str
    payload: Dict
    witness: Optional[list] = None
    error: Optional[str] = None
    # observability: request_id (the spool base name — unique per
    # request, stable across worker takeovers) + claim timestamp, so the
    # terminal record carries true claim->terminal latency
    rid: str = ""
    t_claim: float = 0.0


class ProvingService:
    def __init__(
        self,
        cs,
        dpk,
        vk,
        witness_fn: Callable[[Dict], list],
        public_fn: Callable[[list], list],
        batch_size: int = 4,
        max_wait_s: float = 2.0,
        inputs_fn: Optional[Callable[[Dict], tuple]] = None,
        prover_fn: Optional[Callable] = None,
        prefetch: int = 1,
        stale_claim_s: float = 300.0,
    ):
        """witness_fn: request payload -> witness vector (raises on bad
        input); public_fn: witness -> public signals.

        inputs_fn (optional): payload -> (public_inputs, seed); when
        given, the producer runs the whole batch through the vectorized
        `witness_batch` tier (r1cs BlockHooks) and falls back to
        per-request scalar witnessing if the batch evaluation fails.
        prover_fn (optional): (dpk, [witness]) -> [Proof]; defaults to
        the vmapped device `prove_tpu_batch` — on chip-less hosts pass
        `prover.native_prove.prove_native_batch` (the multi-column fast
        path: whole claimed batches ride ONE base sweep per G1 MSM
        family; ZKP2P_MSM_MULTI=0 degrades it to sequential proves).
        prefetch: ready-batch queue depth (witness ∥ prove overlap
        window; 1 = classic double buffering).
        stale_claim_s: concurrent workers sweeping one spool partition
        requests via O_EXCL <name>.claim files; a claim older than this
        is treated as a crashed worker's and taken over."""
        self.cs = cs
        self.dpk = dpk
        self.vk = vk
        self.witness_fn = witness_fn
        self.public_fn = public_fn
        self.batch_size = batch_size
        self.max_wait_s = max_wait_s
        self.inputs_fn = inputs_fn
        self.prover_fn = prover_fn
        self.prefetch = max(1, prefetch)
        self.stale_claim_s = stale_claim_s
        # per-spool rotating JSONL sinks (lazy; see _sink).  Locked:
        # the witness producer thread and the proving thread both emit
        # records, and two racing JsonlSink instances for one path
        # would rotate against each other.
        self._sinks: Dict[str, JsonlSink] = {}
        self._sinks_lock = threading.Lock()
        # knob manifest + sink override for request records, resolved
        # once per process (env-derived; cannot change under a running
        # service — and _emit_record must not re-parse the config per
        # record).  None = not yet resolved.
        self._knobs: Optional[Dict] = None
        self._sink_override: Optional[str] = None

    # -------------------------------------------------------- observability
    #
    # Every request's terminal transition is RECORDED, not just counted:
    # one JSONL line per request (request_id, state, claim->terminal ms,
    # run_id/pid, the full knob manifest) in a rotating sink next to the
    # spool, aggregatable offline by tools/trace_report.py.  The env-level
    # ZKP2P_METRICS_SINK override redirects all spools to one path.

    def _sink(self, spool: str) -> JsonlSink:
        # keyed by the RESOLVED path, not the spool: a ZKP2P_METRICS_SINK
        # override funnels every spool into one file, which must mean one
        # JsonlSink instance (two would race each other's rotation)
        with self._sinks_lock:
            if self._sink_override is None:
                from ..utils.config import load_config

                self._sink_override = load_config().metrics_sink  # "" = per-spool
            path = self._sink_override or (spool.rstrip("/") + ".metrics.jsonl")
            s = self._sinks.get(path)
            if s is None:
                s = self._sinks[path] = JsonlSink(path)
            return s

    def _emit_record(
        self,
        spool: str,
        req: Request,
        state: str,
        knobs: Dict,
        batch_index: Optional[int] = None,
        batch_n: Optional[int] = None,
    ) -> None:
        try:
            rec = {
                "type": "request",
                "ts": round(time.time(), 3),
                "run_id": run_id(),
                "pid": os.getpid(),
                "request_id": req.rid,
                "state": state,
                "ms": round((time.time() - req.t_claim) * 1e3, 3) if req.t_claim else None,
                "knobs": knobs,
                # which code paths this process has exercised (the audit
                # gate→arm map hash): two requests are comparable only
                # when their digests match — see docs/OBSERVABILITY.md
                "execution_digest": execution_digest(),
            }
            # batched-prove attribution: which slot of which batch this
            # request rode, so trace_report can split a batch's prove
            # latency across its requests (a batch=4 multi-column prove
            # is ONE service/prove span covering four terminal records)
            if batch_index is not None:
                rec["batch_index"] = batch_index
            if batch_n is not None:
                rec["batch_n"] = batch_n
            if req.error:
                rec["error"] = req.error[:500]
            # flight recorder: HBM watermark at terminal time.  NOTE
            # peak_bytes_in_use is the PROCESS-lifetime high-water mark
            # (PJRT exposes no per-interval peak/reset), so the first
            # record whose peak jumps names the request class that
            # raised the ceiling; in_use is the live point sample.
            # Absent on stats-less backends (XLA:CPU).
            mem = sample_device_memory("service/request")
            if mem is not None:
                rec["hbm_peak_bytes"] = mem["peak_bytes_in_use"]
                rec["hbm_bytes_in_use"] = mem["bytes_in_use"]
            self._sink(spool).write(rec)
        except Exception:  # noqa: BLE001 — observation must never fail a prove
            pass
        REGISTRY.counter("zkp2p_service_requests_total", {"state": state}).inc()

    # ------------------------------------------------------------- claims
    #
    # Crash/restart and multi-worker semantics (the service-level mirror
    # of the reference's claim-with-expiry escrow pattern,
    # `Ramp.sol:144` + `clawback`): a worker that dies mid-prove leaves
    # a .claim file but no terminal output; any later sweep — same
    # worker restarted or a peer — takes the request over once the claim
    # is stale.  Terminal outputs (.proof/.error) always win over
    # claims, so a request is never reprocessed after completion.

    def _try_claim(self, base_path: str) -> bool:
        # Terminal outputs are re-checked at CLAIM time, not just at scan
        # time: a peer may have completed this request (proof emitted,
        # claim released) between our scan and our dequeue — re-claiming
        # it would duplicate the prove and double-count `done`.  A
        # microscopic emit-between-check-and-claim window remains
        # (at-least-once, never wrong: terminal writes are atomic and any
        # duplicate proof still verifies).
        if os.path.exists(base_path + ".proof.json") or os.path.exists(base_path + ".error.json"):
            return False
        claim = base_path + ".claim"
        try:
            fd = os.open(claim, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            try:
                age = time.time() - os.path.getmtime(claim)
            except OSError:
                return False  # vanished: owner just completed it
            if age < self.stale_claim_s:
                return False
            # stale claim: take over (best-effort refresh; losing a race
            # here only risks duplicate work, never a wrong result)
            try:
                os.utime(claim, None)
            except OSError:
                return False
            return True
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps({"pid": os.getpid(), "ts": time.time()}))
        return True

    @staticmethod
    def _release_claim(base_path: str) -> None:
        try:
            os.unlink(base_path + ".claim")
        except OSError:
            pass

    # ------------------------------------------------------------ one pass

    def process_dir(self, spool: str) -> Dict[str, int]:
        """One spool sweep; returns counters. Files: <name>.req.json in,
        <name>.proof.json / <name>.error.json out."""
        from ..formats.proof_json import proof_to_json, public_to_json
        from ..prover.groth16_tpu import prove_tpu_batch
        from ..snark.groth16 import verify

        stats = {"done": 0, "error-bad-input": 0, "error-failed-to-prove": 0}
        # knob manifest stamped on every request record (the acceptance
        # contract: a record is attributable without joining against a
        # separate manifest line) — resolved once per process, not per
        # sweep: an idle 1 s poll loop must not re-read /proc/cpuinfo
        # and re-parse the config every tick
        if self._knobs is None:
            self._knobs = run_manifest()["knobs"]
        knobs = self._knobs
        pending: List[Request] = []
        for fn in sorted(os.listdir(spool)):
            if not fn.endswith(".req.json"):
                continue
            base = fn[: -len(".req.json")]
            if os.path.exists(os.path.join(spool, base + ".proof.json")) or os.path.exists(
                os.path.join(spool, base + ".error.json")
            ):
                self._release_claim(os.path.join(spool, base))
                continue
            with open(os.path.join(spool, fn)) as f:
                pending.append(Request(path=os.path.join(spool, base), payload=json.load(f), rid=base))

        # Pipeline overlap (SURVEY.md §2.7 "witness ∥ prove"): witness
        # generation is host CPU, proving is device compute — a producer
        # thread builds upcoming batches while the device proves the
        # current one.  The queue holds at most `prefetch` ready batches
        # (so up to prefetch+1 batches of witnesses may be live; size the
        # knob with host memory in mind).  Mirrors the reference's
        # two-stage shell pipeline (2_gen_wtns.sh -> 5_gen_proof.sh),
        # overlapped instead of sequential.
        ready_q: "queue.Queue[Optional[List[Request]]]" = queue.Queue(maxsize=self.prefetch)
        producer_error: List[BaseException] = []

        def scalar_witness(req: Request) -> bool:
            set_context(request_id=req.rid)
            try:
                with trace("service/witness"):
                    req.witness = self.witness_fn(req.payload)
                    self.cs.check_witness(req.witness)
                return True
            except Exception as e:  # noqa: BLE001 — recorded, not silenced
                req.error = f"error-bad-input: {e}"
                self._emit_error(req, "error-bad-input", e)
                self._emit_record(spool, req, "error-bad-input", knobs)
                stats["error-bad-input"] += 1
                return False
            finally:
                set_context(request_id=None)

        def batched_witness(cand: List[Request]) -> List[Request]:
            """Vectorized tier: per-request input derivation (errors stay
            per request), ONE witness_batch evaluation, sample Az∘Bz=Cz
            check (the prove step verifies a sample proof anyway); any
            batch-level failure falls back to the scalar path."""
            batch: List[Request] = []
            inputs = []
            for req in cand:
                try:
                    set_context(request_id=req.rid)
                    with trace("service/inputs"):
                        inputs.append(self.inputs_fn(req.payload))
                    batch.append(req)
                except Exception as e:  # noqa: BLE001
                    req.error = f"error-bad-input: {e}"
                    self._emit_error(req, "error-bad-input", e)
                    self._emit_record(spool, req, "error-bad-input", knobs)
                    stats["error-bad-input"] += 1
                finally:
                    set_context(request_id=None)
            if not batch:
                return []
            try:
                with trace("service/witness_batch", n=len(batch)):
                    ws = self.cs.witness_batch(inputs)
                # EVERY witness gets the Az∘Bz=Cz self-check, exactly like
                # the scalar tier — only checking a sample would let an
                # unsatisfying witness at index > 0 ship an invalid proof
                # as done (the consumer pairing-verifies one sample too).
                for req, w in zip(batch, ws):
                    self.cs.check_witness(w)
                    req.witness = w
                return batch
            except Exception:  # noqa: BLE001 — batch tier is an optimization
                return [r for r in batch if scalar_witness(r)]

        def produce():
            try:
                for i in range(0, len(pending), self.batch_size):
                    # Claim at DEQUEUE, not at scan: a long sweep must
                    # not hold scan-time claims that go stale while
                    # earlier batches prove (peer takeover would then
                    # duplicate in-progress work).
                    cand = [r for r in pending[i : i + self.batch_size] if self._try_claim(r.path)]
                    for r in cand:
                        r.t_claim = time.time()
                    if self.inputs_fn is not None:
                        batch = batched_witness(cand)
                    else:
                        batch = [r for r in cand if scalar_witness(r)]
                    if batch:
                        ready_q.put(batch)
            except BaseException as e:  # noqa: BLE001 — re-raised by the consumer
                producer_error.append(e)
            finally:
                # The sentinel MUST go out even if this thread dies (e.g.
                # _emit_error hitting a full disk) — otherwise the
                # consumer blocks on ready_q.get() forever.
                ready_q.put(None)

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        while True:
            batch = ready_q.get()
            if batch is None:
                break
            completed: set = set()  # rids terminal as done in THIS batch
            try:
                # heartbeat: refresh the batch's claims periodically WHILE
                # the prove runs, so claim age stays bounded by the refresh
                # interval — not by one batch's prove time (a batch of
                # full-size proves can exceed stale_claim_s and a peer
                # would take over in-flight work)
                stop_hb = threading.Event()

                def _heartbeat(reqs=batch):
                    while True:
                        for req in reqs:
                            try:
                                os.utime(req.path + ".claim", None)
                            except OSError:
                                pass
                        if stop_hb.wait(max(self.stale_claim_s / 3.0, 0.05)):
                            return

                hb = threading.Thread(target=_heartbeat, daemon=True)
                hb.start()
                try:
                    with trace("service/prove", n=len(batch), request_ids=[r.rid for r in batch]):
                        prove = self.prover_fn or prove_tpu_batch
                        proofs = prove(self.dpk, [r.witness for r in batch])
                finally:
                    stop_hb.set()
                    hb.join()
                # verify a sample from every batch before emitting
                sample_pub = self.public_fn(batch[0].witness)
                if not verify(self.vk, proofs[0], sample_pub):
                    raise RuntimeError("sample proof failed verification")
                for bi, (req, proof) in enumerate(zip(batch, proofs)):
                    set_context(request_id=req.rid)
                    try:
                        with trace("service/emit"):
                            dump(proof_to_json(proof), req.path + ".proof.json")
                            dump(public_to_json(self.public_fn(req.witness)), req.path + ".public.json")
                    finally:
                        set_context(request_id=None)
                    self._release_claim(req.path)
                    self._emit_record(spool, req, "done", knobs, batch_index=bi, batch_n=len(batch))
                    completed.add(req.rid)
                    stats["done"] += 1
            except Exception as e:  # noqa: BLE001
                # Only requests NOT already terminal: a dump() failing
                # mid-batch must not stamp an error artifact/record (and
                # a second counter bump) onto requests whose proofs were
                # already emitted as done — one terminal state per
                # request is what the per-request attribution rides on.
                for bi, req in enumerate(batch):
                    if req.rid in completed:
                        continue
                    req.error = f"error-failed-to-prove: {e}"
                    self._emit_error(req, "error-failed-to-prove", e)
                    self._emit_record(
                        spool, req, "error-failed-to-prove", knobs,
                        batch_index=bi, batch_n=len(batch),
                    )
                    stats["error-failed-to-prove"] += 1
        producer.join()
        if producer_error:
            # Requests after the failure point got no witness, no proof
            # and no .error.json — surfacing stats as if the sweep were
            # complete would silently drop them.
            raise producer_error[0]
        return stats

    @classmethod
    def _emit_error(cls, req: Request, state: str, exc: Exception) -> None:
        # atomic (temp+rename) like every other terminal artifact: a crash
        # or racing peer mid-write must never leave a torn .error.json that
        # the sweep's existence check treats as final
        dump(
            {"state": state, "error": str(exc), "trace": traceback.format_exc(limit=3), "ts": time.time()},
            req.path + ".error.json",
        )
        cls._release_claim(req.path)

    # ------------------------------------------------------------- daemon

    @classmethod
    def for_venmo(cls, cs, lay, params, dpk, vk, keys=None, **kw) -> "ProvingService":
        """Service wired for the flagship circuit: request payloads are
        either {"eml_path": ...} (real DKIM email, keys resolved from the
        known-keys registry) or the synthetic-demo shape {"raw_id",
        "amount", "order_id", "claim_id"} (hermetic tests)."""
        from ..inputs.email import email_from_eml, generate_inputs, make_test_key, make_venmo_email

        demo_key = make_test_key(1)

        def inputs_fn(payload: Dict) -> tuple:
            order_id = int(payload.get("order_id", 1))
            claim_id = int(payload.get("claim_id", 0))
            if "eml_path" in payload:
                with open(payload["eml_path"], "rb") as f:
                    email = email_from_eml(f.read(), keys)  # unknown keys raise
                modulus = email.modulus
            else:
                email = make_venmo_email(
                    demo_key, raw_id=str(payload["raw_id"]), amount=str(payload["amount"])
                )
                modulus = demo_key.n
            inputs = generate_inputs(email, modulus, order_id, claim_id, params, lay)
            return inputs.public_signals, inputs.seed

        def witness_fn(payload: Dict) -> list:
            pubs, seed = inputs_fn(payload)
            return cs.witness(pubs, seed)

        def public_fn(witness: list) -> list:
            return list(witness[1 : cs.num_public + 1])

        kw.setdefault("inputs_fn", inputs_fn)
        return cls(cs, dpk, vk, witness_fn, public_fn, **kw)

    def run(self, spool: str, poll_s: float = 1.0, max_sweeps: Optional[int] = None) -> None:
        # Prometheus exposition (ZKP2P_METRICS_PORT, default off) — the
        # scrape sees stage histograms, request-state counters, and a
        # scrape-time native counter refresh.
        maybe_start_metrics_server()
        # Preflight (execution audit): arm every gate, warn LOUDLY when
        # an expected arm failed to arm (pallas requested on a CPU
        # backend, bucket-h without signed digits...) — the round-5
        # silent-disarm class of failure must announce itself before the
        # first request is claimed, not after a burned tunnel window.
        try:
            import sys

            rep = preflight(
                probe=False, workload=False,
                log=lambda m: print(f"[service] {m}", file=sys.stderr, flush=True),
            )
            print(
                f"[service] preflight: backend={rep['backend']} "
                f"execution_digest={rep['execution_digest']}",
                flush=True,
            )
        except Exception:  # noqa: BLE001 — observation must never stop the service
            pass
        sweeps = 0
        while max_sweeps is None or sweeps < max_sweeps:
            stats = self.process_dir(spool)
            if any(stats.values()):
                print(f"[service] {stats}", flush=True)
                # Per-sweep observability flush: buffered stage spans go
                # to the rotating sink (stamped with run_id/pid so
                # concurrent workers stay separable) and the native C
                # counter block is re-published for the next scrape.
                # The trace ring is DRAINED, which with the bounded
                # buffer closes the unbounded-growth leak the run() loop
                # had.
                rid, pid = run_id(), os.getpid()
                spans = [
                    {"type": "stage", "run_id": rid, "pid": pid, **r} for r in drain_trace()
                ]
                try:
                    self._sink(spool).write_many(spans)
                except Exception:  # noqa: BLE001 — observation only
                    pass
                publish_native_stats()
            sweeps += 1
            time.sleep(poll_s)
