"""The batched proving service: queue -> pad to batch -> prove -> verify
sample -> emit (the BASELINE.json north-star service shape).

Failure semantics mirror the reference UI's explicit state machine
(`SubmitOrderGenerateProofForm.tsx:45-56,171-220`): each request ends in
  done | error-bad-input | error-failed-to-prove
with the error recorded next to the request — no silent drops; plus the
verify-after-prove self-check the pipeline scripts do
(`5_gen_proof.sh:15-22` runs `snarkjs groth16 verify` right after prove).

Requests are JSON files in a spool directory (the S3/queue stand-in);
results and errors are written alongside.  Single-process, deliberately
simple: the scheduling story (latency vs batch fill, SURVEY.md §7 hard
part #6) is a bench-driven knob, not a framework constraint.
"""

from __future__ import annotations

import json
import os
import time
import traceback
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..utils.trace import trace


@dataclass
class Request:
    path: str
    payload: Dict
    witness: Optional[list] = None
    error: Optional[str] = None


class ProvingService:
    def __init__(
        self,
        cs,
        dpk,
        vk,
        witness_fn: Callable[[Dict], list],
        public_fn: Callable[[list], list],
        batch_size: int = 4,
        max_wait_s: float = 2.0,
    ):
        """witness_fn: request payload -> witness vector (raises on bad
        input); public_fn: witness -> public signals."""
        self.cs = cs
        self.dpk = dpk
        self.vk = vk
        self.witness_fn = witness_fn
        self.public_fn = public_fn
        self.batch_size = batch_size
        self.max_wait_s = max_wait_s

    # ------------------------------------------------------------ one pass

    def process_dir(self, spool: str) -> Dict[str, int]:
        """One spool sweep; returns counters. Files: <name>.req.json in,
        <name>.proof.json / <name>.error.json out."""
        from ..formats.proof_json import dump, proof_to_json, public_to_json
        from ..prover.groth16_tpu import prove_tpu_batch
        from ..snark.groth16 import verify

        stats = {"done": 0, "error-bad-input": 0, "error-failed-to-prove": 0}
        pending: List[Request] = []
        for fn in sorted(os.listdir(spool)):
            if not fn.endswith(".req.json"):
                continue
            base = fn[: -len(".req.json")]
            if os.path.exists(os.path.join(spool, base + ".proof.json")) or os.path.exists(
                os.path.join(spool, base + ".error.json")
            ):
                continue
            with open(os.path.join(spool, fn)) as f:
                pending.append(Request(path=os.path.join(spool, base), payload=json.load(f)))

        # input validation stage
        ready: List[Request] = []
        for req in pending:
            try:
                with trace("service/witness"):
                    req.witness = self.witness_fn(req.payload)
                    self.cs.check_witness(req.witness)
                ready.append(req)
            except Exception as e:  # noqa: BLE001 — recorded, not silenced
                req.error = f"error-bad-input: {e}"
                self._emit_error(req, "error-bad-input", e)
                stats["error-bad-input"] += 1

        for i in range(0, len(ready), self.batch_size):
            batch = ready[i : i + self.batch_size]
            try:
                with trace("service/prove", n=len(batch)):
                    proofs = prove_tpu_batch(self.dpk, [r.witness for r in batch])
                # verify a sample from every batch before emitting
                sample_pub = self.public_fn(batch[0].witness)
                if not verify(self.vk, proofs[0], sample_pub):
                    raise RuntimeError("sample proof failed verification")
                for req, proof in zip(batch, proofs):
                    dump(proof_to_json(proof), req.path + ".proof.json")
                    dump(public_to_json(self.public_fn(req.witness)), req.path + ".public.json")
                    stats["done"] += 1
            except Exception as e:  # noqa: BLE001
                for req in batch:
                    self._emit_error(req, "error-failed-to-prove", e)
                    stats["error-failed-to-prove"] += 1
        return stats

    @staticmethod
    def _emit_error(req: Request, state: str, exc: Exception) -> None:
        with open(req.path + ".error.json", "w") as f:
            json.dump(
                {"state": state, "error": str(exc), "trace": traceback.format_exc(limit=3), "ts": time.time()},
                f,
                indent=1,
            )

    # ------------------------------------------------------------- daemon

    def run(self, spool: str, poll_s: float = 1.0, max_sweeps: Optional[int] = None) -> None:
        sweeps = 0
        while max_sweeps is None or sweeps < max_sweeps:
            stats = self.process_dir(spool)
            if any(stats.values()):
                print(f"[service] {stats}", flush=True)
            sweeps += 1
            time.sleep(poll_s)
